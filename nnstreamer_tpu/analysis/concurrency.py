"""nns-tsan static side: lock-discipline lint for the threaded runtime.

Pure-AST pass (module 4 of the analyzer; zero jax imports, zero target
imports — files are *read*, never executed) behind ``lint --threads``.
Four checks, each a stable kebab-case diagnostic:

``unguarded-write`` (error)
    A class declares its lock discipline as data::

        class TensorSink:
            _GUARDED_BY = {"_outstanding": "_win_lock", ...}

    and every write / read-modify-write / mutating method call on a
    guarded attribute must happen inside ``with self.<lock>:`` — either
    lexically, or in a helper whose every in-class call site holds the
    lock (one level deep: the ``_write_locked`` convention).
    ``__init__`` is exempt (no aliasing before publication), and so are
    helpers called *only* from ``__init__``.  Conditions constructed
    over a lock (``self._not_empty = Condition(self._lock)``) alias it.

``lock-order-inversion`` (error)
    A package-wide acquisition-order graph built from nested ``with``
    blocks (plus one level of helper / known-singleton calls made while
    holding a lock: ``metrics.count(...)`` under ``self._win_lock`` is
    an edge to ``Metrics._lock``).  A cycle names both acquisition
    paths.  Locks are keyed ``Class.attr`` / ``module.attr`` — the same
    class-level identity the dynamic twin
    (:mod:`nnstreamer_tpu.utils.locks`) uses, so the two sides report
    the same finding.

``unjoined-thread`` (error) / ``daemon-thread`` (warning)
    Every non-daemon ``threading.Thread(...)`` constructed in the
    package must have a ``join()`` reachable from the owning object's
    ``stop()``/``close()``-family methods (one call level deep; local
    threads must join in the same function).  Every ``daemon=True`` is
    a warning that must be explicitly baselined — daemons opt out of
    join-on-exit, which is a decision, not a default.

``cond-wait-no-predicate`` (warning)
    ``cond.wait()`` on a known Condition outside a ``while`` predicate
    loop: bare waits miss spurious wakeups and notify-before-wait
    races.  ``wait_for`` carries its own loop and is exempt.

The motivating escaped bugs are the PR 7/12/13 review-fix trail: the
fetch-window gauge written outside ``_win_lock``, the check-then-create
pool race with ``stop()``, journal ack-vs-GC ordering — all of which
this pass turns into compile-time findings.
"""

from __future__ import annotations

import ast
import os
from typing import Dict, List, Optional, Set, Tuple

from .diagnostics import ERROR, WARNING, Diagnostic, Report

CODES = {
    "unguarded-write": ERROR,
    "lock-order-inversion": ERROR,
    "unjoined-thread": ERROR,
    "daemon-thread": WARNING,
    "cond-wait-no-predicate": WARNING,
}

#: container methods that MUTATE their receiver (a call on a guarded
#: attribute through one of these is a write)
MUTATORS = frozenset({
    "append", "appendleft", "add", "extend", "insert", "pop", "popleft",
    "popitem", "remove", "discard", "clear", "update", "setdefault",
    "sort", "reverse", "rotate", "difference_update",
    "intersection_update", "symmetric_difference_update",
})

#: method names from which a thread join must be reachable
_STOPLIKE = ("stop", "close", "shutdown", "join", "finish", "teardown",
             "__exit__", "__del__", "wait")

_LOCK_CTORS = {"Lock": "lock", "RLock": "rlock", "Condition": "cond",
               "make_lock": "lock", "make_rlock": "rlock",
               "make_condition": "cond"}


def _pos(line_starts: List[int], node: ast.AST) -> int:
    """Global char offset of ``node`` (the Report caret contract)."""
    return line_starts[node.lineno - 1] + node.col_offset


def _line_starts(source: str) -> List[int]:
    starts, n = [0], 0
    for ln in source.splitlines(keepends=True):
        n += len(ln)
        starts.append(n)
    return starts


def _is_self_attr(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Attribute) and \
            isinstance(node.value, ast.Name) and node.value.id == "self":
        return node.attr
    return None


def _const_kwarg(call: ast.Call, key: str):
    for kw in call.keywords:
        if kw.arg == key and isinstance(kw.value, ast.Constant):
            return kw.value.value
    return None


class _ModuleFacts:
    """Everything one file contributes to the package-wide passes."""

    def __init__(self, path: str, relpath: str, source: str,
                 tree: ast.Module):
        self.path = path
        self.relpath = relpath
        self.source = source
        self.tree = tree
        self.line_starts = _line_starts(source)
        self.threading_aliases: Set[str] = set()  # `threading`, `_threading`
        self.threaded = False
        self.classes: Dict[str, "_ClassFacts"] = {}
        self.module_locks: Dict[str, str] = {}  # name -> kind
        #: module-level ``NAME = ClassName()`` singletons
        self.singletons: Dict[str, str] = {}
        self._scan_imports()
        self._scan_toplevel()

    def _scan_imports(self) -> None:
        for node in ast.walk(self.tree):
            if isinstance(node, ast.Import):
                for a in node.names:
                    if a.name == "threading":
                        self.threading_aliases.add(a.asname or "threading")
            elif isinstance(node, ast.ImportFrom):
                if node.module and node.module.endswith("threading"):
                    self.threaded = True
        if self.threading_aliases:
            self.threaded = True

    def lock_ctor_kind(self, call: ast.Call) -> Optional[str]:
        """'lock'|'rlock'|'cond' when ``call`` constructs a (possibly
        tracked) lock primitive, else None."""
        f = call.func
        if isinstance(f, ast.Attribute):
            name = f.attr
            if isinstance(f.value, ast.Name) and (
                    f.value.id in self.threading_aliases
                    or f.value.id == "locks"):
                return _LOCK_CTORS.get(name)
            return None
        if isinstance(f, ast.Name):
            return _LOCK_CTORS.get(f.id)
        return None

    def thread_ctor(self, call: ast.Call) -> bool:
        f = call.func
        if isinstance(f, ast.Attribute):
            return (f.attr == "Thread" and isinstance(f.value, ast.Name)
                    and f.value.id in self.threading_aliases)
        return isinstance(f, ast.Name) and f.id == "Thread" \
            and self.threaded

    def _scan_toplevel(self) -> None:
        for node in self.tree.body:
            if isinstance(node, ast.ClassDef):
                self.classes[node.name] = _ClassFacts(self, node)
            elif isinstance(node, ast.Assign) and len(node.targets) == 1 \
                    and isinstance(node.targets[0], ast.Name):
                name = node.targets[0].id
                if isinstance(node.value, ast.Call):
                    kind = self.lock_ctor_kind(node.value)
                    if kind:
                        self.module_locks[name] = kind
                    elif isinstance(node.value.func, ast.Name):
                        self.singletons[name] = node.value.func.id


class _ClassFacts:
    """Per-class lock/guard/thread facts."""

    def __init__(self, mod: _ModuleFacts, node: ast.ClassDef):
        self.mod = mod
        self.node = node
        self.name = node.name
        self.guarded: Dict[str, str] = {}
        self.lock_attrs: Dict[str, str] = {}  # attr -> kind
        self.aliases: Dict[str, str] = {}  # cond attr -> backing lock attr
        self.methods: Dict[str, ast.FunctionDef] = {}
        for stmt in node.body:
            if isinstance(stmt, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self.methods[stmt.name] = stmt
            elif isinstance(stmt, ast.Assign) and len(stmt.targets) == 1 \
                    and isinstance(stmt.targets[0], ast.Name) \
                    and stmt.targets[0].id == "_GUARDED_BY" \
                    and isinstance(stmt.value, ast.Dict):
                try:
                    self.guarded = {
                        str(k): str(v)
                        for k, v in ast.literal_eval(stmt.value).items()}
                except (ValueError, TypeError):
                    pass
        for meth in self.methods.values():
            for sub in ast.walk(meth):
                if not (isinstance(sub, ast.Assign)
                        and len(sub.targets) == 1
                        and isinstance(sub.value, ast.Call)):
                    continue
                attr = _is_self_attr(sub.targets[0])
                if attr is None:
                    continue
                kind = mod.lock_ctor_kind(sub.value)
                if kind is None:
                    continue
                self.lock_attrs[attr] = kind
                if kind == "cond" and sub.value.args:
                    backing = _is_self_attr(sub.value.args[0])
                    if backing:
                        self.aliases[attr] = backing
        # guard names are locks even when their construction was not
        # recognized (injected locks, test doubles)
        for lk in self.guarded.values():
            self.lock_attrs.setdefault(lk, "lock")

    def canon(self, attr: str) -> str:
        seen = set()
        while attr in self.aliases and attr not in seen:
            seen.add(attr)
            attr = self.aliases[attr]
        return attr

    def lock_id(self, attr: str) -> str:
        return f"{self.name}.{self.canon(attr)}"


class _FuncWalk(ast.NodeVisitor):
    """One function/method traversal with a lexical held-lock stack.

    Collects, in source order: guarded-attr writes (with held set),
    with-acquisition edges, calls made while holding locks, thread
    constructions, joins, and bare condition waits."""

    def __init__(self, mod: _ModuleFacts, cls: Optional[_ClassFacts],
                 func: ast.FunctionDef):
        self.mod = mod
        self.cls = cls
        self.func = func
        self.held: List[str] = []  # canonical lock ids, outermost first
        self.writes: List[Tuple[str, ast.AST, Tuple[str, ...]]] = []
        self.order_edges: List[Tuple[str, str, ast.AST]] = []
        self.calls: List[Tuple[str, str, Tuple[str, ...], ast.AST]] = []
        self.acquired: Set[str] = set()
        self.threads: List[dict] = []
        self.joins: Set[str] = set()  # self attrs joined here
        self.local_joins: Set[str] = set()
        self.bare_waits: List[Tuple[str, ast.AST]] = []
        self._while_depth = 0
        self._thread_locals: Dict[str, dict] = {}
        self._local_from_selfattr: Dict[str, str] = {}
        for stmt in func.body:
            self.visit(stmt)
        for rec in self._thread_locals.values():
            if rec["var"] not in self.local_joins:
                self.threads.append(rec)

    # -- lock expression resolution ---------------------------------------
    def _lock_of(self, expr: ast.AST) -> Optional[str]:
        attr = _is_self_attr(expr)
        if attr is not None and self.cls is not None \
                and attr in self.cls.lock_attrs:
            return self.cls.lock_id(attr)
        if isinstance(expr, ast.Name) and \
                expr.id in self.mod.module_locks:
            return f"{self.mod.relpath}:{expr.id}"
        return None

    # -- traversal ---------------------------------------------------------
    def visit_With(self, node: ast.With) -> None:
        got = []
        for item in node.items:
            self.generic_visit(item.context_expr)
            lock = self._lock_of(item.context_expr)
            if lock is not None:
                for h in self.held:
                    if h != lock:
                        self.order_edges.append((h, lock,
                                                 item.context_expr))
                self.acquired.add(lock)
                self.held.append(lock)
                got.append(lock)
        for stmt in node.body:
            self.visit(stmt)
        for _ in got:
            self.held.pop()

    visit_AsyncWith = visit_With

    def visit_While(self, node: ast.While) -> None:
        self._while_depth += 1
        self.generic_visit(node)
        self._while_depth -= 1

    def visit_FunctionDef(self, node: ast.FunctionDef) -> None:
        # a nested def may run on another thread: its body is walked
        # with an EMPTY held stack (conservative), its writes count
        inner = _FuncWalk(self.mod, self.cls, node)
        self.writes.extend(inner.writes)
        self.order_edges.extend(inner.order_edges)
        self.acquired.update(inner.acquired)
        self.bare_waits.extend(inner.bare_waits)

    visit_AsyncFunctionDef = visit_FunctionDef

    def visit_Lambda(self, node: ast.Lambda) -> None:
        pass  # no statements inside

    def _note_write(self, attr: str, node: ast.AST) -> None:
        if self.cls is not None and attr in self.cls.guarded:
            self.writes.append((attr, node, tuple(self.held)))

    def _target_attr(self, tgt: ast.AST) -> Optional[str]:
        """self.X in plain / subscript / tuple-element target position."""
        attr = _is_self_attr(tgt)
        if attr is not None:
            return attr
        if isinstance(tgt, (ast.Subscript, ast.Starred)):
            return self._target_attr(tgt.value)
        return None

    def visit_Assign(self, node: ast.Assign) -> None:
        for tgt in node.targets:
            elts = tgt.elts if isinstance(tgt, ast.Tuple) else [tgt]
            for el in elts:
                attr = self._target_attr(el)
                if attr is not None:
                    self._note_write(attr, el)
        # dataflow for join detection: t = self._thread (incl. tuple
        # form `t, self._thread = self._thread, None`)
        tgt0 = node.targets[0]
        pairs = []
        if isinstance(tgt0, ast.Name):
            pairs = [(tgt0, node.value)]
        elif isinstance(tgt0, ast.Tuple) and \
                isinstance(node.value, ast.Tuple) and \
                len(tgt0.elts) == len(node.value.elts):
            pairs = list(zip(tgt0.elts, node.value.elts))
        for t, v in pairs:
            if isinstance(t, ast.Name):
                src = _is_self_attr(v)
                if src is not None:
                    self._local_from_selfattr[t.id] = src
        self._scan_thread_ctor(node)
        self.generic_visit(node)

    def visit_AugAssign(self, node: ast.AugAssign) -> None:
        attr = self._target_attr(node.target)
        if attr is not None:
            self._note_write(attr, node.target)
        self.generic_visit(node)

    def visit_AnnAssign(self, node: ast.AnnAssign) -> None:
        if node.value is not None:
            attr = self._target_attr(node.target)
            if attr is not None:
                self._note_write(attr, node.target)
        self.generic_visit(node)

    def visit_Delete(self, node: ast.Delete) -> None:
        for tgt in node.targets:
            attr = self._target_attr(tgt)
            if attr is not None:
                self._note_write(attr, tgt)
        self.generic_visit(node)

    def _scan_thread_ctor(self, assign: ast.Assign) -> None:
        if not isinstance(assign.value, ast.Call) or \
                not self.mod.thread_ctor(assign.value):
            return
        call = assign.value
        rec = {
            "node": call,
            "daemon": bool(_const_kwarg(call, "daemon")),
            "tname": _const_kwarg(call, "name"),
            "attr": None, "var": None,
            "method": self.func.name,
        }
        tgt = assign.targets[0]
        attr = _is_self_attr(tgt)
        if attr is not None:
            rec["attr"] = attr
            self.threads.append(rec)
        elif isinstance(tgt, ast.Name):
            rec["var"] = tgt.id
            self._thread_locals[tgt.id] = rec

    def visit_Expr(self, node: ast.Expr) -> None:
        # bare `threading.Thread(...).start()`
        v = node.value
        if isinstance(v, ast.Call) and isinstance(v.func, ast.Attribute) \
                and v.func.attr == "start" \
                and isinstance(v.func.value, ast.Call) \
                and self.mod.thread_ctor(v.func.value):
            call = v.func.value
            self.threads.append({
                "node": call,
                "daemon": bool(_const_kwarg(call, "daemon")),
                "tname": _const_kwarg(call, "name"),
                "attr": None, "var": None, "method": self.func.name,
            })
        self.generic_visit(node)

    def visit_Call(self, node: ast.Call) -> None:
        f = node.func
        if isinstance(f, ast.Attribute):
            recv_attr = _is_self_attr(f.value)
            # mutator call on a guarded attr: self._dq.append(...)
            if recv_attr is not None and f.attr in MUTATORS:
                self._note_write(recv_attr, f.value)
            # join bookkeeping
            if f.attr == "join":
                if recv_attr is not None:
                    self.joins.add(recv_attr)
                elif isinstance(f.value, ast.Name):
                    n = f.value.id
                    self.local_joins.add(n)
                    if n in self._local_from_selfattr:
                        self.joins.add(self._local_from_selfattr[n])
            # bare condition wait
            if f.attr == "wait" and recv_attr is not None \
                    and self.cls is not None \
                    and self.cls.lock_attrs.get(recv_attr) == "cond" \
                    and self._while_depth == 0:
                self.bare_waits.append((recv_attr, f.value))
            # singleton calls under a lock (order-graph input)
            if self.held and recv_attr is None \
                    and isinstance(f.value, ast.Name) \
                    and f.value.id != "self":
                self.calls.append(("name." + f.value.id, f.attr,
                                   tuple(self.held), node))
            # self.helper() / self._attr.method() — the call-site map
            # the guard pass and order graph reason over
            if recv_attr is not None or (isinstance(f.value, ast.Name)
                                         and f.value.id == "self"):
                self.calls.append(("self", f.attr, tuple(self.held),
                                   node))
            # daemon set post-construction: self.X.daemon = True handled
            # in visit_Assign via _target_attr? (Attribute of Attribute
            # — rare; the kwarg form dominates this codebase)
        self.generic_visit(node)


def _guard_pass(mod: _ModuleFacts, rep: Report) -> None:
    """unguarded-write over every class with a ``_GUARDED_BY``."""
    for cls in mod.classes.values():
        if not cls.guarded:
            continue
        walks = {name: _FuncWalk(mod, cls, fn)
                 for name, fn in cls.methods.items()}
        # call sites: method -> list of (caller, held lock ids)
        call_sites: Dict[str, List[Tuple[str, Tuple[str, ...]]]] = {}
        for caller, w in walks.items():
            for kind, meth, held, _ in w.calls:
                if kind == "self":
                    call_sites.setdefault(meth, []).append((caller, held))
        # fixpoint: method -> locks provably held on EVERY non-__init__
        # entry (each caller holds the lock lexically at the call or is
        # itself proven) — extends the call-site rule through
        # ``_locked``-style helper chains of any depth
        proven: Dict[str, Set[str]] = {m: set() for m in walks}
        all_locks = {cls.lock_id(g) for g in cls.guarded.values()}
        changed = True
        while changed:
            changed = False
            for mname in walks:
                sites = [s for s in call_sites.get(mname, ())
                         if s[0] != "__init__"]
                if not sites:
                    continue
                for lock_id in all_locks - proven[mname]:
                    if all(lock_id in held
                           or lock_id in proven.get(caller, ())
                           for caller, held in sites):
                        proven[mname].add(lock_id)
                        changed = True
        reported: Set[Tuple[str, str]] = set()
        for mname, w in walks.items():
            if mname == "__init__":
                continue
            for attr, node, held in w.writes:
                lock_id = cls.lock_id(cls.guarded[attr])
                if lock_id in held or lock_id in proven[mname]:
                    continue
                sites = [s for s in call_sites.get(mname, ())
                         if s[0] != "__init__"]
                init_only = (not sites
                             and bool(call_sites.get(mname)))
                if init_only:
                    continue
                key = (mname, attr)
                if key in reported:
                    continue
                reported.add(key)
                bad = next((c for c, h in sites
                            if lock_id not in h
                            and lock_id not in proven.get(c, ())),
                           None)
                why = (f"called without it from {cls.name}.{bad}()"
                       if bad else "and no guarded call path proves it")
                rep.add(
                    "unguarded-write", CODES["unguarded-write"],
                    f"self.{attr} is _GUARDED_BY "
                    f"{cls.guarded[attr]!r} but {cls.name}.{mname}() "
                    f"writes it outside `with self."
                    f"{cls.guarded[attr]}:` ({why})",
                    path=f"{mod.relpath}:{cls.name}.{mname}.{attr}",
                    pos=_pos(mod.line_starts, node),
                )


def _thread_pass(mod: _ModuleFacts, rep: Report) -> None:
    """unjoined-thread / daemon-thread over classes AND module funcs."""
    def flag(rec, owner: str, joined: bool) -> None:
        label = f" ({rec['tname']!r})" if rec.get("tname") else ""
        loc = f"{mod.relpath}:{owner}"
        if rec["daemon"]:
            rep.add(
                "daemon-thread", CODES["daemon-thread"],
                f"daemon thread{label} started in {owner}(): daemons "
                f"skip join-on-exit — baseline this only with a "
                f"documented shutdown story",
                path=f"{loc}{'.' + rec['tname'] if rec.get('tname') else ''}",
                pos=_pos(mod.line_starts, rec["node"]))
        if not joined and not rec["daemon"]:
            rep.add(
                "unjoined-thread", CODES["unjoined-thread"],
                f"thread{label} started in {owner}() has no join() "
                f"reachable from a stop()/close()-family method",
                path=f"{loc}.unjoined",
                pos=_pos(mod.line_starts, rec["node"]))

    for cls in mod.classes.values():
        walks = {name: _FuncWalk(mod, cls, fn)
                 for name, fn in cls.methods.items()}
        threads = [t for w in walks.values() for t in w.threads]
        if not threads:
            continue
        # join closure over stop-like methods, one call level deep
        joined: Set[str] = set()
        for mname, w in walks.items():
            if not (mname.startswith("stop") or mname.startswith("close")
                    or mname in _STOPLIKE):
                continue
            joined |= w.joins
            for kind, meth, _, _ in w.calls:
                if kind == "self" and meth in walks:
                    joined |= walks[meth].joins
        for rec in threads:
            ok = (rec["attr"] in joined if rec["attr"] is not None
                  else rec["var"] is None and False
                  or rec.get("var") in
                  walks.get(rec["method"],
                            _FuncWalk(mod, cls,
                                      cls.methods[rec["method"]])
                            ).local_joins)
            # locals joined in the same method were filtered already;
            # a surviving local/bare thread is unjoined by construction
            if rec["attr"] is None:
                ok = False
            flag(rec, f"{cls.name}.{rec['method']}", ok)

    for node in mod.tree.body:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            w = _FuncWalk(mod, None, node)
            for rec in w.threads:
                joined = (rec["attr"] is None and rec["var"] is None
                          and False)
                flag(rec, node.name, joined)


def _cond_pass(mod: _ModuleFacts, rep: Report) -> None:
    for cls in mod.classes.values():
        for mname, fn in cls.methods.items():
            if mname in ("wait", "wait_for"):
                continue  # a delegating wait wrapper IS the primitive;
                # its callers own the predicate loop
            w = _FuncWalk(mod, cls, fn)
            for attr, node in w.bare_waits:
                rep.add(
                    "cond-wait-no-predicate",
                    CODES["cond-wait-no-predicate"],
                    f"{cls.name}.{mname}() calls self.{attr}.wait() "
                    f"outside a `while <predicate>` loop — bare waits "
                    f"miss spurious wakeups and notify-before-wait "
                    f"races (use a predicate loop or wait_for)",
                    path=f"{mod.relpath}:{cls.name}.{mname}.{attr}",
                    pos=_pos(mod.line_starts, node))


class _OrderGraph:
    """Package-wide static acquisition-order graph."""

    def __init__(self) -> None:
        self.edges: Dict[Tuple[str, str], str] = {}
        #: class name -> method -> locks acquired (any depth, own file)
        self.acquires: Dict[str, Dict[str, Set[str]]] = {}
        #: singleton variable name -> class name (package-wide)
        self.singletons: Dict[str, str] = {}
        self.pending_calls: List[Tuple[str, str, str,
                                       Tuple[str, ...], str]] = []

    def add_module(self, mod: _ModuleFacts) -> None:
        for var, clsname in mod.singletons.items():
            self.singletons.setdefault(var, clsname)
        for cls in mod.classes.values():
            acq = self.acquires.setdefault(cls.name, {})
            for mname, fn in cls.methods.items():
                w = _FuncWalk(mod, cls, fn)
                acq[mname] = set(w.acquired)
                for a, b, node in w.order_edges:
                    site = f"{mod.relpath}:{cls.name}.{mname}:" \
                           f"{node.lineno}"
                    self.edges.setdefault((a, b), site)
                for kind, meth, held, node in w.calls:
                    if not held:
                        continue
                    site = f"{mod.relpath}:{cls.name}.{mname}:" \
                           f"{node.lineno}"
                    self.pending_calls.append(
                        (kind, meth, cls.name, held, site))
        for node in mod.tree.body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                w = _FuncWalk(mod, None, node)
                for a, b, n in w.order_edges:
                    site = f"{mod.relpath}:{node.name}:{n.lineno}"
                    self.edges.setdefault((a, b), site)

    def resolve_calls(self) -> None:
        """One level of call propagation: a helper / known-singleton
        method invoked while holding S contributes S → (its acquires)."""
        for kind, meth, clsname, held, site in self.pending_calls:
            if kind == "self":
                targets = self.acquires.get(clsname, {}).get(meth, ())
            elif kind.startswith("name."):
                var = kind[5:]
                tcls = self.singletons.get(var)
                targets = self.acquires.get(tcls, {}).get(meth, ()) \
                    if tcls else ()
            else:
                targets = ()
            for lock in targets:
                for h in held:
                    if h != lock:
                        self.edges.setdefault((h, lock),
                                              site + " (via call)")

    def cycles(self) -> List[List[str]]:
        """One representative cycle per strongly-connected component."""
        adj: Dict[str, List[str]] = {}
        for a, b in self.edges:
            adj.setdefault(a, []).append(b)
            adj.setdefault(b, [])
        index: Dict[str, int] = {}
        low: Dict[str, int] = {}
        on: Set[str] = set()
        stack: List[str] = []
        sccs: List[List[str]] = []
        counter = [0]

        def strong(v: str) -> None:  # iterative Tarjan
            work = [(v, 0)]
            while work:
                node, pi = work[-1]
                if pi == 0:
                    index[node] = low[node] = counter[0]
                    counter[0] += 1
                    stack.append(node)
                    on.add(node)
                recurse = False
                for i in range(pi, len(adj[node])):
                    u = adj[node][i]
                    if u not in index:
                        work[-1] = (node, i + 1)
                        work.append((u, 0))
                        recurse = True
                        break
                    if u in on:
                        low[node] = min(low[node], index[u])
                if recurse:
                    continue
                if low[node] == index[node]:
                    comp = []
                    while True:
                        u = stack.pop()
                        on.discard(u)
                        comp.append(u)
                        if u == node:
                            break
                    if len(comp) > 1:
                        sccs.append(comp)
                work.pop()
                if work:
                    parent = work[-1][0]
                    low[parent] = min(low[parent], low[node])

        for v in list(adj):
            if v not in index:
                strong(v)

        out = []
        for comp in sccs:
            cset = set(comp)
            # walk one actual cycle inside the component
            start = comp[0]
            path, seen = [start], {start}
            cur = start
            while True:
                nxt = next((u for u in adj[cur]
                            if u in cset and u not in seen), None)
                if nxt is None:
                    nxt = next(u for u in adj[cur] if u in cset)
                    path.append(nxt)
                    break
                path.append(nxt)
                seen.add(nxt)
                cur = nxt
            # trim to the repeated node
            first = path.index(path[-1])
            out.append(path[first:])
        return out

    def diagnose(self, rep: Report) -> None:
        self.resolve_calls()
        for cyc in self.cycles():
            hops = []
            for a, b in zip(cyc, cyc[1:]):
                hops.append(f"{a} -> {b} at "
                            f"{self.edges.get((a, b), '?')}")
            nodes = sorted(set(cyc))
            rep.add(
                "lock-order-inversion", CODES["lock-order-inversion"],
                "lock-order inversion: " + "; but ".join(hops),
                path="order:" + "->".join(nodes))


def package_root() -> str:
    return os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _iter_py(root: str) -> List[str]:
    out = []
    for dirpath, dirnames, filenames in os.walk(root):
        dirnames[:] = [d for d in dirnames if d != "__pycache__"]
        for f in sorted(filenames):
            if f.endswith(".py"):
                out.append(os.path.join(dirpath, f))
    return out


def lint_paths(paths: List[str], *, root: Optional[str] = None
               ) -> Tuple[List[Report], dict]:
    """Run all four passes over ``paths``; the lock-order graph spans
    the whole set.  Returns per-file Reports (source attached for caret
    rendering) plus a trailing package-level Report carrying the
    cross-file order-cycle findings, and a stats dict."""
    mods: List[_ModuleFacts] = []
    reports: List[Report] = []
    base = root or os.path.commonpath([os.path.dirname(p)
                                       for p in paths]) if paths else ""
    for path in paths:
        with open(path) as f:
            source = f.read()
        rel = os.path.relpath(path, base) if base else \
            os.path.basename(path)
        try:
            tree = ast.parse(source, filename=path)
        except SyntaxError as e:  # pragma: no cover - repo parses
            rep = Report(source)
            rep.add("unguarded-write", ERROR, f"unparsable: {e}",
                    path=rel)
            reports.append(rep)
            continue
        mods.append(_ModuleFacts(path, rel, source, tree))

    graph = _OrderGraph()
    stats = {"files": len(paths), "threaded": 0, "guarded_classes": 0,
             "locks": 0, "edges": 0}
    for mod in mods:
        rep = Report(mod.source)
        if mod.threaded:
            stats["threaded"] += 1
        stats["guarded_classes"] += sum(
            1 for c in mod.classes.values() if c.guarded)
        stats["locks"] += sum(len(c.lock_attrs)
                              for c in mod.classes.values()) \
            + len(mod.module_locks)
        _guard_pass(mod, rep)
        _thread_pass(mod, rep)
        _cond_pass(mod, rep)
        graph.add_module(mod)
        reports.append(rep)
    pkg_rep = Report()
    graph.diagnose(pkg_rep)
    stats["edges"] = len(graph.edges)
    reports.append(pkg_rep)
    return reports, stats


def lint_package(root: Optional[str] = None) -> Tuple[List[Report], dict]:
    root = root or package_root()
    return lint_paths(_iter_py(root), root=root)


def baseline_key(d: Diagnostic) -> str:
    """Stable baseline key: no line numbers (they drift), the path
    component already pins file + class.method + attr / cycle nodes."""
    return f"threads:{d.code}:{d.path}"
