"""Topology / concurrency checks over a parsed PipelineGraph.

Everything here is pure graph structure + element-class metadata (kind,
is_source/is_sink, sync_policy, request-pad numbering) — no element is
instantiated, no JAX is touched.  Checks:

* dangling ``name.pad`` refs (``graph.unresolved_refs`` from
  ``parse(..., validate=False)``)
* unknown element kinds (with a did-you-mean suggestion)
* cycles outside the ``tensor_repo`` loop mechanism
* sources with inputs / non-sources without inputs (the missing-'!' bug)
* sinks with outputs, and non-sink leaves that silently drop buffers
* double-linked src pads (branching without a tee)
* mux/merge arity: single-input collators, numbered-pad gaps that stall
  slowest-sync collation forever
* the tee-diamond deadlock hazard: branches of one tee rejoining a
  slowest-sync collator (mux/merge/compositor/crop) without a ``queue``
  on every branch.  In this runtime every stage already owns a bounded
  queue, so the GStreamer-style hard deadlock becomes unbounded pending
  growth + latency skew at the collator — the check sizes the hazard
  against the configured per-stage queue capacity and branch depth skew.
"""

from __future__ import annotations

import difflib
from typing import Dict, List, Optional, Set

from ..core.registry import KIND_ELEMENT, lookup, names
from ..elements.base import SinkElement, SourceElement
from ..pipeline.graph import PipelineGraph
from .diagnostics import Diagnostic, ERROR, WARNING, node_label

#: kinds whose class collates one buffer per sink pad (sync_policy "all")
#: — the reconvergence points the deadlock check cares about
_COLLATORS = {"tensor_mux", "tensor_merge", "compositor", "tensor_crop"}

#: the explicit stage-boundary element (GStreamer ``queue``)
_QUEUE_KINDS = {"queue"}


def _cls(kind: str):
    if kind == "capsfilter":
        return None
    return lookup(KIND_ELEMENT, kind)


def check_topology(graph: PipelineGraph, *,
                   queue_capacity: Optional[int] = None) -> List[Diagnostic]:
    diags: List[Diagnostic] = []
    add = lambda *a, **k: diags.append(Diagnostic(*a, **k))  # noqa: E731

    # dangling named-pad refs (validate=False parse carries them through)
    for name, pad, pos in getattr(graph, "unresolved_refs", []):
        add("dangling-pad-ref", ERROR,
            f"reference to unknown element {name!r} (pad {pad!r})",
            path=f"{name}.{pad}", pos=pos)

    # unknown kinds
    known: Dict[int, object] = {}
    all_names = None
    for node in graph.nodes.values():
        cls = _cls(node.kind)
        if cls is None and node.kind != "capsfilter":
            if all_names is None:
                all_names = names(KIND_ELEMENT)
            near = difflib.get_close_matches(node.kind, all_names, n=1)
            hint = f" — did you mean {near[0]!r}?" if near else ""
            add("unknown-element", ERROR,
                f"no element kind {node.kind!r}{hint}",
                path=node_label(node), pos=node.pos)
        else:
            known[node.id] = cls

    # cycles (reference: loops must go through tensor_repo slots, which
    # break the edge — reposrc has no in-edge)
    cycle = graph.find_cycle()
    cycle_nodes: Set[int] = set(cycle or ())
    if cycle:
        path = " -> ".join(node_label(graph.nodes[i]) for i in cycle)
        add("cycle", ERROR,
            f"pipeline graph has a cycle: {path} — loops must go through "
            "tensor_reposink/tensor_reposrc slots",
            path=node_label(graph.nodes[cycle[0]]),
            pos=graph.nodes[cycle[0]].pos)

    # double-linked src pads (graph.validate would reject; lint reports all)
    seen_src: Set = set()
    for e in graph.edges:
        k = (e.src, e.src_pad)
        if k in seen_src:
            add("pad-linked-twice", ERROR,
                f"source pad {e.src_pad!r} linked twice — insert a tee to "
                "branch", path=node_label(graph.nodes[e.src]),
                pos=graph.nodes[e.src].pos)
        seen_src.add(k)

    # nodes whose in/out link was dropped because a name ref never
    # resolved: the dangling-pad-ref diagnostic IS their finding — no
    # derived missing-'!'/unreachable/leaf noise on either side
    phantom_fed: Set[int] = set(getattr(graph, "phantom_fed", ()))
    phantom_out: Set[int] = set(getattr(graph, "phantom_out", ()))

    # per-node structural checks
    for node in graph.nodes.values():
        cls = known.get(node.id)
        ins = graph.in_edges(node.id)
        outs = graph.out_edges(node.id)
        is_source = cls is not None and issubclass(cls, SourceElement)
        is_sink = cls is not None and issubclass(cls, SinkElement)
        if is_source and ins:
            add("source-has-input", ERROR,
                f"source element {node.kind!r} cannot have input links",
                path=node_label(node), pos=node.pos)
        if not is_source and cls is not None and not ins \
                and node.id not in cycle_nodes \
                and node.id not in phantom_fed:
            add("no-input", ERROR,
                f"element {node.kind!r} has no input link — missing '!' "
                "before it?", path=node_label(node), pos=node.pos)
        if is_sink and outs:
            add("sink-has-output", ERROR,
                f"sink element {node.kind!r} cannot have output links",
                path=node_label(node), pos=node.pos)
        if not is_sink and cls is not None and not outs \
                and node.id not in phantom_out:
            add("leaf-not-sink", WARNING,
                f"element {node.kind!r} has no downstream link — its output "
                "buffers are silently dropped", path=node_label(node),
                pos=node.pos)

        # collator arity + numbered-pad gaps: slowest-sync waits for a
        # buffer on EVERY connected sink pad, so a gap in sink_N numbering
        # is usually a mislinked branch
        if node.kind in _COLLATORS and _collates(node):
            idxs = sorted(
                int(e.dst_pad.rsplit("_", 1)[1]) for e in ins
                if "_" in e.dst_pad and e.dst_pad.rsplit("_", 1)[1].isdigit()
            )
            if len(ins) < 2:
                add("collator-single-input", WARNING,
                    f"{node.kind} collates one buffer per sink pad but has "
                    f"{len(ins)} input(s)", path=node_label(node),
                    pos=node.pos)
            if idxs and idxs != list(range(idxs[0], idxs[0] + len(idxs))):
                add("pad-gap", ERROR,
                    f"{node.kind} sink pads are numbered {idxs} — gaps stall "
                    "slowest-sync collation", path=node_label(node),
                    pos=node.pos)

    # unreachable branches: BFS from every true root (phantom-fed nodes
    # count as roots — their feed exists, it just failed to resolve)
    roots = [
        n.id for n in graph.nodes.values()
        if not graph.in_edges(n.id)
        and (n.id in phantom_fed or known.get(n.id) is None
             or issubclass(known[n.id], SourceElement))
    ]
    reached: Set[int] = set()
    work = list(roots)
    while work:
        i = work.pop()
        if i in reached:
            continue
        reached.add(i)
        work.extend(e.dst for e in graph.out_edges(i))
    for node in graph.nodes.values():
        if node.id in reached or node.id in cycle_nodes:
            continue
        ins = graph.in_edges(node.id)
        # nodes whose only problem is a missing input were reported above
        if not ins:
            continue
        add("unreachable", WARNING,
            f"element {node.kind!r} can never receive a buffer (no source "
            "feeds this branch)", path=node_label(node), pos=node.pos)

    diags.extend(_check_tee_diamonds(graph, known, queue_capacity))
    return diags


def _collates(node) -> bool:
    """Does this collator instance actually run slowest-sync?  sync-mode
    basepad/refresh switch the element to 'any' collation at runtime."""
    mode = str(node.props.get("sync_mode", "slowest")).lower()
    return mode not in ("basepad", "refresh")


def _reachable(graph: PipelineGraph, start: int, *,
               skip_kinds: Set[str] = frozenset()) -> Dict[int, int]:
    """BFS depths from ``start`` (inclusive), not expanding through nodes
    whose kind is in ``skip_kinds`` (used to ask "is there a queue-less
    path?" by deleting queues)."""
    depth = {start: 0}
    work = [start]
    while work:
        i = work.pop(0)
        if graph.nodes[i].kind in skip_kinds:
            continue
        for e in graph.out_edges(i):
            if e.dst not in depth:
                depth[e.dst] = depth[i] + 1
                work.append(e.dst)
    return depth


def _check_tee_diamonds(graph: PipelineGraph, known: Dict[int, object],
                        queue_capacity: Optional[int]) -> List[Diagnostic]:
    """Branches of one multi-out element rejoining a slowest-sync collator
    must each pass through a bounded ``queue``.

    Reference semantics: a queue-less tee diamond hard-deadlocks GStreamer
    (the tee's chain call blocks in the muxer while the muxer waits for the
    other branch).  This runtime gives every stage its own bounded queue, so
    the failure mode is softer but real: the collator's pending lists grow
    by one buffer per *depth-skew* step between the branches, and with the
    per-stage queue capacity C the upstream tee stalls once the short
    branch runs C buffers ahead — pipeline throughput then degrades to the
    long branch with zero overlap.  The check therefore reports severity by
    sizing depth skew against C (planner stage/queue model: one stage and
    one bounded queue per element outside fused spans).
    """
    if queue_capacity is None:
        from ..core.config import get_config

        queue_capacity = get_config().queue_capacity
    diags: List[Diagnostic] = []
    for node in graph.nodes.values():
        outs = graph.out_edges(node.id)
        if len(outs) < 2:
            continue
        branch_heads = sorted({e.dst for e in outs})
        if len(branch_heads) < 2:
            continue
        depths = {h: _reachable(graph, h) for h in branch_heads}
        noq = {h: _reachable(graph, h, skip_kinds=_QUEUE_KINDS)
               for h in branch_heads}
        joins = {}
        for join in graph.nodes.values():
            if join.kind not in _COLLATORS or not _collates(join):
                continue
            through = [h for h in branch_heads if join.id in depths[h]]
            if len(through) < 2:
                continue
            joins[join.id] = through
        for join_id, through in joins.items():
            join = graph.nodes[join_id]
            bare = [h for h in through if join_id in noq[h]]
            if not bare:
                continue  # every rejoining branch is decoupled by a queue
            skew = (max(depths[h][join_id] for h in through)
                    - min(depths[h][join_id] for h in through))
            sev = ERROR if len(bare) == len(through) else WARNING
            branches = ", ".join(
                f"via {node_label(graph.nodes[h])}" for h in bare)
            diags.append(Diagnostic(
                "tee-deadlock", sev,
                f"branches of {node_label(node)} rejoin slowest-sync "
                f"{join.kind} without a queue on every branch ({branches}); "
                f"branch depth skew {skew} vs stage queue capacity "
                f"{queue_capacity} — insert 'queue' after each branch",
                path=f"{node_label(node)} → {node_label(join)}",
                pos=node.pos))
    return diags
