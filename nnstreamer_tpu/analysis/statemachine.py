"""nns-proto model checker: bounded explicit-state exploration of the
distributed serving protocols.

The runtime protocols (elements/query.py exactly-once delivery,
filters/llm.py drain→adopt handover, utils/armor.py quarantine,
utils/elastic.py spill hysteresis) are exercised dynamically by the
chaos soak (docs/ROBUSTNESS.md); this module gives each one a
compile-time twin: a small declarative state machine whose FULL state
graph is explored under the same fault vocabulary the soak injects —
message drop / duplication / reordering and crash-before-ack — checking
safety invariants on every reachable state and liveness (every reachable
state can still reach an accepting state) by backward reachability over
the explored graph.  Violations come back with the complete transition
trace from the initial state, so a counterexample reads like a soak log.

DSL
---
A :class:`Model` is a dict-shaped initial state, a list of :class:`Rule`
transitions (``guard(state) -> bool``, ``effect(state) -> state | [state]``;
the effect receives a private mutable copy), named safety ``invariants``,
an ``accepting`` predicate (the "done / healthy" states liveness must
keep reachable), and the protocol ``alphabet`` the model covers (checked
against the AST-extracted code alphabet by analysis/protocol.py).  State
keys whose value is a tuple and that are listed in ``channels`` are
lossy/reordering message channels: the explorer auto-generates
drop/dup/reorder fault rules for them, budgeted by the ``_drop`` /
``_dup`` / ``_reorder`` counters in the initial state.  Crash faults are
ordinary model rules (what survives a crash — the journal, the free
list — is protocol knowledge, not harness knowledge).

This module is jax-free at import and must stay that way: it runs inside
the ``lint --proto`` CI gate on machines with no accelerator stack.

See docs/ANALYSIS.md "Protocol pass" for the model inventory and a
counterexample reading guide.
"""

from __future__ import annotations

import collections
import dataclasses
import time
from typing import Callable, Dict, FrozenSet, List, Optional, Sequence, Tuple

from ..core import meta_keys

__all__ = [
    "Rule", "Model", "Violation", "CheckResult", "check",
    "exactly_once_model", "handover_model", "quarantine_model",
    "hysteresis_model", "weave_clock_model", "SHIPPED_MODELS",
    "shipped_alphabet",
]


# ---------------------------------------------------------------------------
# state freezing (dict states -> hashable canonical form)
# ---------------------------------------------------------------------------

def _freeze(v):
    if isinstance(v, dict):
        return ("d", tuple(sorted(((k, _freeze(x)) for k, x in v.items()),
                                  key=repr)))
    if isinstance(v, (set, frozenset)):
        return ("s", tuple(sorted((_freeze(x) for x in v), key=repr)))
    if isinstance(v, (list, tuple)):
        return ("t", tuple(_freeze(x) for x in v))
    return v


def _thaw(v):
    if isinstance(v, tuple) and len(v) == 2 and v[0] in ("d", "s", "t"):
        tag, items = v
        if tag == "d":
            return {k: _thaw(x) for k, x in items}
        if tag == "s":
            return frozenset(_thaw(x) for x in items)
        return tuple(_thaw(x) for x in items)
    return v


@dataclasses.dataclass(frozen=True)
class Rule:
    """One named transition: fires when ``guard`` holds, producing the
    state(s) returned by ``effect`` (which may mutate its private copy
    in place and return it, or return a list for nondeterminism)."""
    name: str
    guard: Callable[[dict], bool]
    effect: Callable[[dict], object]
    fault: bool = False  # injected fault, not protocol behavior


@dataclasses.dataclass
class Model:
    name: str
    init: dict
    rules: List[Rule]
    invariants: Dict[str, Callable[[dict], bool]]
    accepting: Callable[[dict], bool]
    #: protocol meta keys / message kinds this model covers — compared
    #: against the AST-extracted code alphabet by the drift gate
    alphabet: FrozenSet[str]
    #: state keys holding message channels (tuples) subject to faults
    channels: Sequence[str] = ()
    #: per-channel length cap (dup is disabled at the cap)
    channel_cap: int = 3


@dataclasses.dataclass
class Violation:
    kind: str              # "safety" | "deadlock" | "wedge"
    prop: str              # invariant name / accepting-property name
    trace: List[Tuple[str, dict]]  # (rule fired, resulting state) from init
    state: dict

    def render(self) -> str:
        lines = [f"{self.kind} violation: {self.prop}",
                 f"  trace ({len(self.trace)} steps from init):"]
        for step, (rule, state) in enumerate(self.trace):
            lines.append(f"    {step:3d}. {rule:<28s} -> {_fmt_state(state)}")
        lines.append(f"  violating state: {_fmt_state(self.state)}")
        return "\n".join(lines)


def _fmt_state(s: dict) -> str:
    parts = []
    for k in sorted(s, key=repr):
        v = s[k]
        if isinstance(v, frozenset):
            v = "{" + ",".join(sorted(map(str, v))) + "}"
        parts.append(f"{k}={v}")
    return " ".join(parts)


@dataclasses.dataclass
class CheckResult:
    model: str
    ok: bool
    states: int
    transitions: int
    elapsed_s: float
    violation: Optional[Violation] = None
    bounded_out: bool = False  # hit max_states before exhausting the graph

    def render(self) -> str:
        verdict = "OK" if self.ok else "FAIL"
        head = (f"[{verdict}] {self.model}: {self.states} states, "
                f"{self.transitions} transitions, {self.elapsed_s*1e3:.1f} ms"
                + (" (STATE BOUND HIT)" if self.bounded_out else ""))
        if self.violation is None:
            return head
        return head + "\n" + self.violation.render()


# ---------------------------------------------------------------------------
# auto-generated channel fault rules
# ---------------------------------------------------------------------------

def _channel_fault_rules(channels: Sequence[str], cap: int) -> List[Rule]:
    rules: List[Rule] = []
    for ch in channels:
        def mk(ch=ch):
            def drop(s):
                out = []
                for i in range(len(s[ch])):
                    t = dict(s)
                    t[ch] = t[ch][:i] + t[ch][i + 1:]
                    t["_drop"] -= 1
                    out.append(t)
                return out

            def dup(s):
                out = []
                for i in range(len(s[ch])):
                    t = dict(s)
                    t[ch] = t[ch][:i + 1] + t[ch][i:]
                    t["_dup"] -= 1
                    out.append(t)
                return out

            def reorder(s):
                out = []
                for i in range(len(s[ch]) - 1):
                    t = dict(s)
                    c = list(t[ch])
                    c[i], c[i + 1] = c[i + 1], c[i]
                    t[ch] = tuple(c)
                    t["_reorder"] -= 1
                    out.append(t)
                return out

            return [
                Rule(f"fault.drop[{ch}]",
                     lambda s: s.get("_drop", 0) > 0 and len(s[ch]) > 0,
                     drop, fault=True),
                Rule(f"fault.dup[{ch}]",
                     lambda s: s.get("_dup", 0) > 0
                     and 0 < len(s[ch]) < cap,
                     dup, fault=True),
                Rule(f"fault.reorder[{ch}]",
                     lambda s: s.get("_reorder", 0) > 0 and len(s[ch]) > 1,
                     reorder, fault=True),
            ]
        rules.extend(mk())
    return rules


# ---------------------------------------------------------------------------
# the explorer
# ---------------------------------------------------------------------------

def check(model: Model, max_states: int = 200_000) -> CheckResult:
    """Exhaustively explore ``model``'s state graph (BFS), checking every
    invariant on every reachable state, deadlock-freedom (a quiescent
    state must be accepting), and liveness (every reachable state can
    still reach an accepting state — computed by backward reachability
    once the graph is exhausted).  Returns the first violation with its
    full transition trace."""
    t0 = time.monotonic()
    rules = list(model.rules) + _channel_fault_rules(
        model.channels, model.channel_cap)
    init_f = _freeze(model.init)
    # pred: state -> (predecessor, rule name) for trace reconstruction
    pred: Dict[object, Optional[Tuple[object, str]]] = {init_f: None}
    rev: Dict[object, List[object]] = collections.defaultdict(list)
    frontier = collections.deque([init_f])
    accepting: List[object] = []
    n_trans = 0
    bounded_out = False

    def trace_to(sf) -> List[Tuple[str, dict]]:
        steps = []
        cur = sf
        while pred[cur] is not None:
            prev, rule = pred[cur]
            steps.append((rule, _thaw(cur)))
            cur = prev
        steps.reverse()
        return steps

    while frontier:
        sf = frontier.popleft()
        s = _thaw(sf)
        for prop, inv in model.invariants.items():
            if not inv(s):
                return CheckResult(
                    model.name, False, len(pred), n_trans,
                    time.monotonic() - t0,
                    Violation("safety", prop, trace_to(sf), s))
        if model.accepting(s):
            accepting.append(sf)
        quiescent = True
        for rule in rules:
            if not rule.guard(s):
                continue
            succs = rule.effect(_thaw(sf))
            if succs is None:
                succs = []
            elif isinstance(succs, dict):
                succs = [succs]
            for t in succs:
                quiescent = False
                n_trans += 1
                tf = _freeze(t)
                rev[tf].append(sf)
                if tf not in pred:
                    if len(pred) >= max_states:
                        bounded_out = True
                        continue
                    pred[tf] = (sf, rule.name)
                    frontier.append(tf)
        if quiescent and not model.accepting(s):
            return CheckResult(
                model.name, False, len(pred), n_trans,
                time.monotonic() - t0,
                Violation("deadlock", "quiescent-non-accepting",
                          trace_to(sf), s))

    # liveness: states that can NOT reach any accepting state are wedges
    co = set(accepting)
    work = collections.deque(accepting)
    while work:
        tf = work.popleft()
        for sf in rev[tf]:
            if sf not in co:
                co.add(sf)
                work.append(sf)
    for sf in pred:
        if sf not in co:
            return CheckResult(
                model.name, False, len(pred), n_trans,
                time.monotonic() - t0,
                Violation("wedge", "accepting-unreachable",
                          trace_to(sf), _thaw(sf)))
    return CheckResult(model.name, not bounded_out, len(pred), n_trans,
                       time.monotonic() - t0, None, bounded_out)


# ---------------------------------------------------------------------------
# shipped model 1: client reconnect/resend x journal dedupe/replay
# ---------------------------------------------------------------------------

def exactly_once_model(n_requests: int = 2, *, journal: bool = True,
                       client_dedupe: bool = True,
                       resend: bool = True) -> Model:
    """Exactly-once delivery (docs/ROBUSTNESS.md "Durable request
    journal" + elements/query.py client resend): every request is
    answered exactly once at the client app despite drop/dup/reorder on
    both wire directions and a server crash before the journal ack.

    ``client_dedupe=False`` (client counts duplicate answers) and
    ``resend=False`` (fire-and-forget client: each request is sent once)
    are the known-bad mutations used by the tests: the first answers a
    request twice (safety), the second wedges on any dropped frame
    (liveness — no path back to all-answered).  ``journal=False``
    disables append-before-admission/replay; the model still verifies
    because client resend alone re-covers a crashed queue — the journal
    is what answers a request whose CLIENT is gone (replay acks), which
    is outside this model's client-visible property.
    """
    rids = tuple(range(n_requests))
    init = {
        "pending": frozenset(rids),      # client: not yet answered
        "answers": {r: 0 for r in rids},  # app-visible answer count
        # fire-and-forget clients preload the wire; resending clients
        # (re)issue pending requests from the resend rule instead
        "c2s": () if resend else tuple(("req", r) for r in rids),
        "s2c": (),                       # wire channels
        "srv_q": (),                     # admitted in-memory work (lost on crash)
        "journal": frozenset(),          # durable: appended seqnos (rids)
        "acked": frozenset(),            # durable: answered seqnos
        "_drop": 1, "_dup": 1, "_reorder": 1, "_crash": 1,
    }

    def do_resend(s):
        # timeout/reconnect resend of every still-pending request id
        out = []
        for r in sorted(s["pending"]):
            if s["c2s"].count(("req", r)) == 0 and len(s["c2s"]) < 3:
                t = dict(s)
                t["c2s"] = t["c2s"] + (("req", r),)
                out.append(t)
        return out

    def srv_recv(s):
        (kind, r), rest = s["c2s"][0], s["c2s"][1:]
        t = dict(s)
        t["c2s"] = rest
        if journal:
            t["journal"] = t["journal"] | {r}
        if r in t["acked"]:
            # journal dedupe: already answered — re-answer from the
            # durable record instead of re-admitting the work
            if len(t["s2c"]) < 3:
                t["s2c"] = t["s2c"] + (("resp", r),)
        elif t["srv_q"].count(r) == 0:
            t["srv_q"] = t["srv_q"] + (r,)
        return t

    def srv_answer(s):
        r, rest = s["srv_q"][0], s["srv_q"][1:]
        t = dict(s)
        t["srv_q"] = rest
        t["s2c"] = t["s2c"] + (("resp", r),)
        t["acked"] = t["acked"] | {r}
        return t

    def crash(s):
        # crash-before-ack: in-memory queue and both wire channels are
        # lost; the journal and its acks survive
        t = dict(s)
        t["srv_q"] = ()
        t["c2s"] = ()
        t["s2c"] = ()
        t["_crash"] -= 1
        return t

    def replay(s):
        # recovery: journalled-but-unacked requests re-enter admission
        out = []
        for r in sorted(s["journal"] - s["acked"]):
            if s["srv_q"].count(r) == 0:
                t = dict(s)
                t["srv_q"] = t["srv_q"] + (r,)
                out.append(t)
        return out

    def cli_recv(s):
        (kind, r), rest = s["s2c"][0], s["s2c"][1:]
        t = dict(s)
        t["s2c"] = rest
        if client_dedupe and r not in t["pending"]:
            return t  # duplicate answer: dropped at the client cursor
        t["pending"] = t["pending"] - {r}
        t["answers"] = dict(t["answers"])
        t["answers"][r] += 1
        return t

    return Model(
        name="exactly-once",
        init=init,
        rules=[
            Rule("client.resend",
                 lambda s: resend and bool(s["pending"]), do_resend),
            Rule("server.recv", lambda s: len(s["c2s"]) > 0
                 and len(s["srv_q"]) < 3, srv_recv),
            Rule("server.answer", lambda s: len(s["srv_q"]) > 0
                 and len(s["s2c"]) < 3, srv_answer),
            Rule("server.crash", lambda s: s["_crash"] > 0, crash,
                 fault=True),
            Rule("journal.replay",
                 lambda s: bool(s["journal"] - s["acked"]), replay),
            Rule("client.recv", lambda s: len(s["s2c"]) > 0, cli_recv),
        ],
        invariants={
            "answered-at-most-once":
                lambda s: all(n <= 1 for n in s["answers"].values()),
        },
        accepting=lambda s: not s["pending"]
        and all(n == 1 for n in s["answers"].values()),
        alphabet=frozenset({
            meta_keys.META_QUERY_MSG, meta_keys.META_QUERY_CONN,
            meta_keys.META_JOURNAL_SEQ, meta_keys.META_JOURNAL_REPLAY,
            meta_keys.META_QUERY_BATCH, meta_keys.META_SHED,
            meta_keys.META_WIRE_REJECT, meta_keys.META_ERROR,
            meta_keys.ABORT_REASON_WIRE, meta_keys.ABORT_REASON_INTERNAL,
            meta_keys.CTRL_HELLO, meta_keys.CTRL_ACK, meta_keys.CTRL_NACK,
            # journal record magics + the wire frame magic: the journal
            # rules below model exactly their append/replay lifecycle
            "record:REQ", "record:ACK", "record:FRAME",
        }),
        channels=("c2s", "s2c"),
    )


# ---------------------------------------------------------------------------
# shipped model 2: drain -> adopt handover
# ---------------------------------------------------------------------------

def handover_model(n_streams: int = 2, *, adopt_guard: bool = True,
                   release_on_drain: bool = True) -> Model:
    """Elastic handover (filters/llm.py drain_stream/adopt_stream,
    docs/SERVING.md §4d): every live stream drained from the source
    serve loop is adopted exactly once at the target, KV blocks return
    to the free list on every path — including a crash that loses the
    snapshot mid-transfer (the orchestrator retains it and retries).

    ``adopt_guard=False`` lets a duplicated snapshot adopt twice
    (safety); ``release_on_drain=False`` leaks the source block when the
    transfer crashes (wedge: blocks never all return).
    """
    sids = tuple(range(n_streams))
    total = n_streams  # one KV block per stream, per side
    init = {
        "src_live": frozenset(sids),
        "src_used": n_streams,          # blocks held by source slots
        "orch": frozenset(),            # snapshots the orchestrator holds
        "xfer": (),                     # adopt calls in flight
        "dst_live": frozenset(),
        "dst_used": 0,
        "done": frozenset(),
        "_drop": 1, "_dup": 1, "_reorder": 1,
    }

    def drain(s):
        out = []
        for sid in sorted(s["src_live"]):
            t = dict(s)
            t["src_live"] = t["src_live"] - {sid}
            if release_on_drain:
                # snapshot MATERIALIZES host copies; pool blocks free now
                t["src_used"] -= 1
            t["orch"] = t["orch"] | {sid}
            out.append(t)
        return out

    def submit(s):
        out = []
        for sid in sorted(s["orch"]):
            if s["xfer"].count(("snap", sid)) == 0 and len(s["xfer"]) < 3:
                t = dict(s)
                t["xfer"] = t["xfer"] + (("snap", sid),)
                out.append(t)
        return out

    def adopt(s):
        (kind, sid), rest = s["xfer"][0], s["xfer"][1:]
        t = dict(s)
        t["xfer"] = rest
        if adopt_guard and (sid in t["dst_live"] or sid in t["done"]):
            return t  # duplicate snapshot: already adopted — rejected
        t["dst_live"] = t["dst_live"] | {sid}
        t["dst_used"] += 1
        t["orch"] = t["orch"] - {sid}
        return t

    def finish(s):
        out = []
        for sid in sorted(s["dst_live"]):
            t = dict(s)
            t["dst_live"] = t["dst_live"] - {sid}
            t["dst_used"] -= 1
            t["done"] = t["done"] | {sid}
            out.append(t)
        return out

    return Model(
        name="drain-adopt",
        init=init,
        rules=[
            Rule("src.drain", lambda s: bool(s["src_live"]), drain),
            Rule("orch.submit", lambda s: bool(s["orch"]), submit),
            Rule("dst.adopt", lambda s: len(s["xfer"]) > 0, adopt),
            Rule("dst.finish", lambda s: bool(s["dst_live"]), finish),
        ],
        invariants={
            "no-duplicate-stream":
                lambda s: not (s["src_live"] & s["dst_live"])
                and not (s["dst_live"] & s["done"])
                and s["dst_used"] == len(s["dst_live"]),
            "block-accounting":
                lambda s: 0 <= s["src_used"] <= total
                and 0 <= s["dst_used"] <= total,
        },
        accepting=lambda s: s["done"] == frozenset(sids)
        and s["src_used"] == 0 and s["dst_used"] == 0,
        alphabet=frozenset({
            meta_keys.META_STREAM_ID, meta_keys.META_STREAM_INDEX,
            meta_keys.META_STREAM_LAST,
            # live-stream snapshot version tag carried by drain->adopt
            "snapshot:v2",
        }),
        channels=("xfer",),
    )


# ---------------------------------------------------------------------------
# shipped model 3: DLQ / circuit-breaker quarantine
# ---------------------------------------------------------------------------

def quarantine_model(n_requests: int = 2, *, dlq_guard: bool = True,
                     max_retries: int = 1) -> Model:
    """Poison armor (utils/armor.py, docs/ROBUSTNESS.md "Poison armor"):
    a request that keeps failing is quarantined to the DLQ and its
    client receives the typed ``abort_reason=poison`` terminator; a
    quarantined id NEVER re-enters the live path, even when the fault
    injector re-delivers a stale duplicate of it.

    ``dlq_guard=False`` is the known-bad mutation: a duplicated message
    of an already-quarantined id is re-admitted (safety violation).
    """
    rids = tuple(range(n_requests))
    init = {
        "live": tuple(("req", r) for r in rids),
        "attempts": {r: 0 for r in rids},
        "dlq": frozenset(),
        "answered": frozenset(),   # poison terminator delivered
        "relive": frozenset(),     # quarantined id seen live again (bug)
        "_drop": 0, "_dup": 1, "_reorder": 1,
    }

    def process(s):
        (kind, r), rest = s["live"][0], s["live"][1:]
        t = dict(s)
        t["live"] = rest
        if r in t["dlq"]:
            if dlq_guard:
                return t  # stale duplicate of a quarantined id: dropped
            t["relive"] = t["relive"] | {r}
            return t
        t["attempts"] = dict(t["attempts"])
        t["attempts"][r] += 1
        if t["attempts"][r] > max_retries:
            # quarantine: DLQ record + typed poison terminator
            t["dlq"] = t["dlq"] | {r}
            t["answered"] = t["answered"] | {r}
        elif t["live"].count(("req", r)) == 0 and len(t["live"]) < 3:
            t["live"] = t["live"] + (("req", r),)  # retry
        return t

    return Model(
        name="dlq-quarantine",
        init=init,
        rules=[
            Rule("armor.process", lambda s: len(s["live"]) > 0, process),
        ],
        invariants={
            "quarantined-never-relive": lambda s: not s["relive"],
            "bounded-retries":
                lambda s: all(n <= max_retries + 1
                              for n in s["attempts"].values()),
        },
        accepting=lambda s: not s["live"]
        and s["answered"] == frozenset(rids),
        alphabet=frozenset({
            meta_keys.META_POISON, meta_keys.META_DLQ,
            meta_keys.META_ABORT_REASON, meta_keys.ABORT_REASON_POISON,
            meta_keys.META_STREAM_ABORTED, meta_keys.META_TRACE_ID,
            meta_keys.META_INGRESS_NS,
            # DLQ record magic: the quarantine rule models its lifecycle
            "record:DLQ",
        }),
        channels=("live",),
    )


# ---------------------------------------------------------------------------
# shipped model 4: autoscaler spill hysteresis
# ---------------------------------------------------------------------------

def hysteresis_model(cooldown: int = 2, *, honor_cooldown: bool = True,
                     horizon: int = 6) -> Model:
    """Autoscaler admission spill (utils/elastic.ScaleRule engage/relax
    edges): once a tenant class is flipped to shed, it may not relax
    before the cooldown elapses — and vice versa — no matter how the
    burn-rate signal flaps, so admission never oscillates faster than
    the cooldown window.

    ``honor_cooldown=False`` removes the guard: a flapping burn signal
    produces a shed->relax flip inside the window (safety violation).
    """
    init = {
        "burn_high": False,   # environment: SLO burn above the edge?
        "mode": "ok",         # admission override: ok | shed
        "since_flip": cooldown,  # ticks since the last mode change
        "tick": 0,            # bounded time horizon
        "early_flip": False,  # a flip fired inside the cooldown window
    }

    def env_flap(s):
        t = dict(s)
        t["burn_high"] = not t["burn_high"]
        return t

    def tick(s):
        t = dict(s)
        t["tick"] += 1
        t["since_flip"] = min(t["since_flip"] + 1, cooldown)
        return t

    def flip(s, to):
        t = dict(s)
        if t["since_flip"] < cooldown:
            t["early_flip"] = True
        t["mode"] = to
        t["since_flip"] = 0
        return t

    def guard_flip(s, want_burn, frm):
        if s["mode"] != frm or s["burn_high"] is not want_burn:
            return False
        return s["since_flip"] >= cooldown if honor_cooldown else True

    return Model(
        name="spill-hysteresis",
        init=init,
        rules=[
            Rule("env.flap", lambda s: s["tick"] < horizon, env_flap),
            Rule("clock.tick", lambda s: s["tick"] < horizon, tick),
            Rule("scale.engage-shed",
                 lambda s: guard_flip(s, True, "ok"),
                 lambda s: flip(s, "shed")),
            Rule("scale.relax",
                 lambda s: guard_flip(s, False, "shed"),
                 lambda s: flip(s, "ok")),
        ],
        invariants={
            "no-flip-inside-cooldown": lambda s: not s["early_flip"],
        },
        accepting=lambda s: True,
        alphabet=frozenset({
            meta_keys.META_TENANT, meta_keys.META_SHED,
        }),
        channels=(),
    )


def weave_clock_model(retries: int = 2, *, dedup_guard: bool = True
                      ) -> Model:
    """nns-weave clock probe/ack exchange (elements/query.py client rx
    loop + _ServerCore._reader; docs/OBSERVABILITY.md "Distributed
    tracing"): the client sends ``clock`` probes stamped t0, the server
    answers each with a ``clock_ack`` echoing t0, and the client applies
    ONE offset sample per outstanding probe — a duplicated or replayed
    ack (the channels are lossy AND duplicating) must never double-apply,
    or the refresh-timestamp bookkeeping would claim more samples than
    probes were ever sent.  The distributed parent context
    (``_tparent``) rides the same connection's data frames; its
    scrub-then-adopt step has no protocol state beyond what
    exactly-once already covers, so this model carries it in the
    alphabet only.

    ``dedup_guard=False`` removes the outstanding-probe check: a
    duplicated ack double-applies (safety counterexample).
    """
    init = {
        "retries": retries,      # probes the client may still send
        "probes": 0,             # probes sent so far (t0 = probe index)
        "outstanding": frozenset(),  # t0s sent but not yet applied
        "synced": 0,             # distinct probes that produced a sample
        "applied": 0,            # offset applications (must <= probes)
        "c2s": (),               # clock probes in flight
        "s2c": (),               # clock_acks in flight
        "_drop": 1, "_dup": 1, "_reorder": 1,
    }

    def send_probe(s):
        t = dict(s)
        t0 = t["probes"]
        t["retries"] -= 1
        t["probes"] += 1
        t["outstanding"] = t["outstanding"] | {t0}
        t["c2s"] = t["c2s"] + (t0,)
        return t

    def server_echo(s):
        t = dict(s)
        t0, t["c2s"] = t["c2s"][0], t["c2s"][1:]
        t["s2c"] = t["s2c"] + (("ack", t0),)
        return t

    def client_apply(s):
        t = dict(s)
        (_, t0), t["s2c"] = t["s2c"][0], t["s2c"][1:]
        if t0 in t["outstanding"] or not dedup_guard:
            if t0 in t["outstanding"]:
                t["outstanding"] = t["outstanding"] - {t0}
                t["synced"] += 1
            t["applied"] += 1
        return t

    return Model(
        name="weave-clock",
        init=init,
        rules=[
            Rule("clock.send-probe", lambda s: s["retries"] > 0,
                 send_probe),
            Rule("server.echo", lambda s: bool(s["c2s"]), server_echo),
            Rule("client.apply", lambda s: bool(s["s2c"]), client_apply),
        ],
        invariants={
            "applies-bounded-by-probes":
                lambda s: s["applied"] <= s["probes"],
        },
        accepting=lambda s: s["synced"] >= 1 or (
            s["retries"] == 0 and not s["c2s"] and not s["s2c"]),
        alphabet=frozenset({
            meta_keys.CTRL_CLOCK, meta_keys.CTRL_CLOCK_ACK,
            meta_keys.META_TRACE_PARENT,
        }),
        channels=("c2s", "s2c"),
    )


#: name -> zero-arg factory for every model shipped (and CI-checked)
SHIPPED_MODELS: Dict[str, Callable[[], Model]] = {
    "exactly-once": exactly_once_model,
    "drain-adopt": handover_model,
    "dlq-quarantine": quarantine_model,
    "spill-hysteresis": hysteresis_model,
    "weave-clock": weave_clock_model,
}


def shipped_alphabet() -> FrozenSet[str]:
    """Union of every shipped model's declared alphabet — what the
    models collectively claim to cover; the drift gate in
    analysis/protocol.py compares this against the code's alphabet."""
    out: FrozenSet[str] = frozenset()
    for factory in SHIPPED_MODELS.values():
        out = out | factory().alphabet
    return out
