"""nns-lint: compile-time pipeline verification.

The runtime surfaces pipeline misconfigurations one at a time, mid-stream.
This package finds them *before a pipeline ever starts*, with three passes
over the parsed :class:`~nnstreamer_tpu.pipeline.graph.PipelineGraph` —
no JAX execution, no device, no model files:

1. :mod:`~nnstreamer_tpu.analysis.capsflow` — whole-graph caps/spec
   propagation through every edge, reporting EVERY incompatibility in one
   run with element-path diagnostics;
2. :mod:`~nnstreamer_tpu.analysis.topology` — dangling refs, cycles,
   unreachable branches, collator arity, tee-diamond deadlock hazards;
3. :mod:`~nnstreamer_tpu.analysis.purity` — AST lint of device_fns and
   registered pure filter functions for host side effects that break
   tracing or silently block fusion/batching.

Entry points::

    report = analyze("appsrc ! tensor_converter ! tensor_sink")
    report.ok            # no errors
    print(report.render())

    nt.Pipeline(desc, validate=True)   # raises PipelineLintError on errors
    python -m nnstreamer_tpu.tools.lint "<pipeline>"   # CLI
"""

from __future__ import annotations

from typing import Optional, Union

from ..pipeline.graph import PipelineGraph
from ..pipeline.parser import ParseError, parse
from .diagnostics import (  # noqa: F401
    Diagnostic,
    ERROR,
    PipelineLintError,
    Report,
    WARNING,
)


def analyze(
    pipeline: Union[str, PipelineGraph],
    *,
    caps: bool = True,
    topology: bool = True,
    purity: bool = True,
    proto: bool = False,
    queue_capacity: Optional[int] = None,
    deep: bool = False,
    batch_max: Optional[int] = None,
    batch_buckets: Optional[list] = None,
    adaptive_buckets: Optional[bool] = None,
    data_parallel: Optional[int] = None,
    model_parallel: Optional[int] = None,
    dispatch_depth: Optional[int] = None,
    hbm_budget_bytes: Optional[int] = None,
    max_compiled_variants: Optional[int] = None,
    reconfig: Optional[dict] = None,
) -> Report:
    """Run the static passes; always returns a :class:`Report` (a syntax
    error becomes a single ``parse-error`` diagnostic rather than an
    exception, so tools can render every pipeline the same way).

    ``deep=True`` additionally runs the abstract-execution pass
    (:mod:`~nnstreamer_tpu.analysis.tracecheck`): every device stage is
    traced symbolically with ``jax.eval_shape`` against the negotiated
    spec (shape/dtype contract violations, tracing failures) and a static
    HBM/recompile budget report is attached as ``report.resources``.  The
    deep pass imports jax — unlike the syntactic passes — but performs
    zero device dispatch.  The remaining keyword knobs parameterize its
    resource model and default to the global Config."""
    source = pipeline if isinstance(pipeline, str) else None
    report = Report(source)
    if isinstance(pipeline, str):
        try:
            graph = parse(pipeline, validate=False)
        except ParseError as e:
            report.add("parse-error", ERROR, str(e), pos=e.pos)
            return report
    else:
        graph = pipeline

    def run(name, fn):
        # the analyzer's contract is report-everything-never-crash: a bug
        # in one pass must not take down the CLI or the CI gate, and must
        # not hide the OTHER passes' findings
        try:
            report.extend(fn())
        except Exception as e:  # noqa: BLE001
            report.add("analyzer-error", ERROR,
                       f"{name} pass crashed: {e!r} — report this bug")

    if topology:
        from .topology import check_topology

        run("topology",
            lambda: check_topology(graph, queue_capacity=queue_capacity))
    caps_state = {}
    if caps:
        from .capsflow import propagate

        def _run_caps():
            diags, out_caps = propagate(graph)
            caps_state["out_caps"] = out_caps  # reused by the deep pass
            return diags

        run("capsflow", _run_caps)
    if purity:
        from .purity import lint_graph

        run("purity", lambda: lint_graph(graph))
    if proto:
        # nns-proto (docs/ANALYSIS.md "Protocol pass"): a package-level
        # property, not a per-pipeline one — the serving protocol
        # alphabet, handler totality, unanswered-path proof, and the
        # model-vs-code drift gate over the protocol modules.
        from . import protocol as _protocol

        def _run_proto():
            reports, _stats = _protocol.lint_package()
            return [d for rep in reports for d in rep]

        run("protocol", _run_proto)
    if deep:
        from .tracecheck import deep_check

        try:
            ddiags, resources = deep_check(
                graph, batch_max=batch_max, batch_buckets=batch_buckets,
                adaptive_buckets=adaptive_buckets,
                data_parallel=data_parallel, model_parallel=model_parallel,
                dispatch_depth=dispatch_depth,
                hbm_budget_bytes=hbm_budget_bytes,
                max_compiled_variants=max_compiled_variants,
                reconfig=reconfig,
                out_caps=caps_state.get("out_caps"))
            report.extend(ddiags)
            report.resources = resources
        except Exception as e:  # noqa: BLE001 - report, never crash
            report.add("analyzer-error", ERROR,
                       f"deep pass crashed: {e!r} — report this bug")
    return report
