"""nns-lint: compile-time pipeline verification.

The runtime surfaces pipeline misconfigurations one at a time, mid-stream.
This package finds them *before a pipeline ever starts*, with three passes
over the parsed :class:`~nnstreamer_tpu.pipeline.graph.PipelineGraph` —
no JAX execution, no device, no model files:

1. :mod:`~nnstreamer_tpu.analysis.capsflow` — whole-graph caps/spec
   propagation through every edge, reporting EVERY incompatibility in one
   run with element-path diagnostics;
2. :mod:`~nnstreamer_tpu.analysis.topology` — dangling refs, cycles,
   unreachable branches, collator arity, tee-diamond deadlock hazards;
3. :mod:`~nnstreamer_tpu.analysis.purity` — AST lint of device_fns and
   registered pure filter functions for host side effects that break
   tracing or silently block fusion/batching.

Entry points::

    report = analyze("appsrc ! tensor_converter ! tensor_sink")
    report.ok            # no errors
    print(report.render())

    nt.Pipeline(desc, validate=True)   # raises PipelineLintError on errors
    python -m nnstreamer_tpu.tools.lint "<pipeline>"   # CLI
"""

from __future__ import annotations

from typing import Optional, Union

from ..pipeline.graph import PipelineGraph
from ..pipeline.parser import ParseError, parse
from .diagnostics import (  # noqa: F401
    Diagnostic,
    ERROR,
    PipelineLintError,
    Report,
    WARNING,
)


def analyze(
    pipeline: Union[str, PipelineGraph],
    *,
    caps: bool = True,
    topology: bool = True,
    purity: bool = True,
    queue_capacity: Optional[int] = None,
) -> Report:
    """Run the static passes; always returns a :class:`Report` (a syntax
    error becomes a single ``parse-error`` diagnostic rather than an
    exception, so tools can render every pipeline the same way)."""
    source = pipeline if isinstance(pipeline, str) else None
    report = Report(source)
    if isinstance(pipeline, str):
        try:
            graph = parse(pipeline, validate=False)
        except ParseError as e:
            report.add("parse-error", ERROR, str(e), pos=e.pos)
            return report
    else:
        graph = pipeline

    def run(name, fn):
        # the analyzer's contract is report-everything-never-crash: a bug
        # in one pass must not take down the CLI or the CI gate, and must
        # not hide the OTHER passes' findings
        try:
            report.extend(fn())
        except Exception as e:  # noqa: BLE001
            report.add("analyzer-error", ERROR,
                       f"{name} pass crashed: {e!r} — report this bug")

    if topology:
        from .topology import check_topology

        run("topology",
            lambda: check_topology(graph, queue_capacity=queue_capacity))
    if caps:
        from .capsflow import propagate

        run("capsflow", lambda: propagate(graph)[0])
    if purity:
        from .purity import lint_graph

        run("purity", lambda: lint_graph(graph))
    return report
