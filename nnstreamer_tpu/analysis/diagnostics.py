"""Diagnostic model for the static pipeline analyzer (nns-lint).

A :class:`Diagnostic` is one finding: a stable code, a severity, the
element-path it anchors to (``appsrc[0]:src → tensor_transform[2]:sink``)
and — when the graph came from a pipeline string — the character offset of
the offending element so tools can print a source caret.  A :class:`Report`
collects every finding from every pass, because the whole point of the
analyzer is to surface ALL problems in one run instead of the runtime's
fail-on-first-push behavior.
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional

ERROR = "error"
WARNING = "warning"


@dataclasses.dataclass(frozen=True)
class Diagnostic:
    """One analyzer finding."""

    code: str  # stable kebab-case class, e.g. "caps-mismatch"
    severity: str  # ERROR | WARNING
    message: str  # field-level reason ("dtype uint8 ⊄ float32")
    path: str = ""  # element path ("appsrc[0]:src → tensor_filter[2]:sink")
    pos: Optional[int] = None  # char offset in the pipeline string

    def __str__(self) -> str:
        loc = f"{self.path}: " if self.path else ""
        at = f" (at char {self.pos})" if self.pos is not None else ""
        return f"{self.severity}[{self.code}] {loc}{self.message}{at}"


def node_label(node) -> str:
    """Stable element-path label: user name when given, else kind[id]."""
    return node.name if node.name else f"{node.kind}[{node.id}]"


def edge_path(graph, edge) -> str:
    src = node_label(graph.nodes[edge.src])
    dst = node_label(graph.nodes[edge.dst])
    return f"{src}:{edge.src_pad} → {dst}:{edge.dst_pad}"


class Report:
    """All findings of one analyzer run over one pipeline."""

    def __init__(self, source: Optional[str] = None):
        self.source = source  # original pipeline string (caret rendering)
        self.diagnostics: List[Diagnostic] = []
        #: static HBM/recompile estimate from the deep pass
        #: (:class:`~nnstreamer_tpu.analysis.tracecheck.ResourceReport`);
        #: None unless analyze(deep=True) ran
        self.resources = None

    def add(self, code: str, severity: str, message: str, *, path: str = "",
            pos: Optional[int] = None) -> None:
        self.diagnostics.append(Diagnostic(code, severity, message, path, pos))

    def extend(self, diags) -> None:
        self.diagnostics.extend(diags)

    @property
    def errors(self) -> List[Diagnostic]:
        return [d for d in self.diagnostics if d.severity == ERROR]

    @property
    def warnings(self) -> List[Diagnostic]:
        return [d for d in self.diagnostics if d.severity == WARNING]

    @property
    def ok(self) -> bool:
        """No errors (warnings allowed)."""
        return not self.errors

    @property
    def clean(self) -> bool:
        """Nothing at all to report."""
        return not self.diagnostics

    def codes(self) -> List[str]:
        return sorted({d.code for d in self.diagnostics})

    def render(self, *, carets: bool = True) -> str:
        """Human-readable report; diagnostics ordered by source position,
        each followed by a caret line into the pipeline string when its
        position is known."""
        if not self.diagnostics:
            return "OK: no diagnostics"
        order = sorted(
            self.diagnostics,
            key=lambda d: (d.pos if d.pos is not None else 1 << 30, d.code),
        )
        lines: List[str] = []
        for d in order:
            lines.append(str(d))
            if carets and self.source and d.pos is not None \
                    and d.pos < len(self.source):
                # pos is a GLOBAL char offset; pipeline strings may span
                # lines, so resolve it to (line, column) before drawing
                before = self.source[:d.pos]
                col = d.pos - (before.rfind("\n") + 1)
                src_line = self.source.splitlines()[before.count("\n")]
                lines.append(f"    {src_line}")
                lines.append(f"    {' ' * col}^")
        n_e, n_w = len(self.errors), len(self.warnings)
        lines.append(f"{n_e} error(s), {n_w} warning(s)")
        return "\n".join(lines)

    def raise_if_errors(self) -> None:
        """One exception carrying EVERY error (the validate=True hook)."""
        if self.errors:
            raise PipelineLintError(self)

    def __str__(self) -> str:
        return self.render()

    def __iter__(self):
        return iter(self.diagnostics)

    def __len__(self) -> int:
        return len(self.diagnostics)


class PipelineLintError(ValueError):
    """Raised by Report.raise_if_errors(); carries the full report."""

    def __init__(self, report: Report):
        super().__init__(
            "pipeline failed static analysis:\n" + report.render(carets=False)
        )
        self.report = report
