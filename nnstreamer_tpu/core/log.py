"""Structured logging (reference analog: nnstreamer_log.c nns_logi/logw/loge).

Also hosts the lightweight metrics registry promised by SURVEY.md §5.5:
frames in/out, queue depths, bytes moved, per-stage latency percentiles are
recorded in-process and dumped on demand — the reference had only GST debug
categories plus tensor_filter's latency property.

Three sample families (all rendered by ``utils/profiler.metrics_text`` in
Prometheus text format, docs/OBSERVABILITY.md):

* **counters** (:meth:`Metrics.count`) — monotonically increasing totals;
* **gauges** (:meth:`Metrics.gauge`) — set-not-add instantaneous values
  (queue depths, staleness watermarks — fed by the runtime's sampler);
* **distributions** (:meth:`Metrics.observe` /
  :meth:`Metrics.observe_latency`) — a BOUNDED per-series reservoir
  (decimating at ``_lat_cap`` samples, so a hot stage can never grow
  process memory without limit) from which quantiles derive, and — for
  ``observe_latency`` series — a cumulative fixed-bucket **histogram**
  (``LATENCY_BUCKETS``), the real ``_bucket``/``_sum``/``_count``
  exposition Prometheus can aggregate across scrapes.

Thread-safety discipline: every mutation and every raw-state copy happens
under one lock, but derived work (sorting reservoirs for quantiles) runs
on the COPY outside the lock — concurrent runner writes never stall
behind a scrape's O(n log n).
"""

from __future__ import annotations

import bisect
import collections
import logging
import math
import os
import threading
import time
from typing import Dict, List, Optional, Tuple

_FMT = "%(asctime)s %(levelname).1s %(name)s: %(message)s"
_configured = False


def logger(name: str) -> logging.Logger:
    global _configured
    if not _configured:
        level = os.environ.get("NNS_TPU_LOG", "WARNING").upper()
        logging.basicConfig(level=getattr(logging, level, logging.WARNING), format=_FMT)
        _configured = True
    return logging.getLogger(name)


#: histogram bucket upper bounds (seconds) for every observe_latency
#: series: 100 µs .. 10 s log-ish spaced (explicit ``le`` labels in the
#: Prometheus exposition; the final implicit bucket is +Inf)
LATENCY_BUCKETS: Tuple[float, ...] = (
    0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025,
    0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0)

#: an admission (h2d) or materialization (d2h) wait above this is a real
#: transport/backlog stall, not a lock hop — ONE threshold for both
#: halves of the fetch-engine stall split (``<src>.h2d_stalls`` in
#: elements/source.py, ``<sink>.d2h_stalls`` in elements/sink.py) so the
#: two directions stay comparable.  docs/FETCH.md "Stall accounting".
STALL_FLOOR_S = 1e-3


class Metrics:
    """Process-wide counters + gauges + latency reservoirs/histograms,
    thread-safe (see module docstring for the lock discipline)."""

    def __init__(self):
        self._lock = threading.Lock()
        self._counters: Dict[str, float] = collections.defaultdict(float)
        self._gauges: Dict[str, float] = {}
        self._lat: Dict[str, List[float]] = collections.defaultdict(list)
        #: per-series reservoir bound: at cap, every other sample is
        #: dropped (decimation keeps a uniform-ish spread of the stream's
        #: lifetime instead of only its head or tail)
        self._lat_cap = 4096
        # name -> [bucket_counts(len(LATENCY_BUCKETS)+1 incl +Inf),
        #          sum, count]
        self._hist: Dict[str, list] = {}

    def count(self, name: str, value: float = 1.0) -> None:
        with self._lock:
            self._counters[name] += value

    def gauge(self, name: str, value: float) -> None:
        """Set an instantaneous value (queue depth, staleness watermark)."""
        with self._lock:
            self._gauges[name] = float(value)

    def observe(self, name: str, value: float) -> None:
        """Record one sample of a distribution (batch occupancy, sizes,
        ...); snapshot() derives p50/p99/mean/n per series.  The reservoir
        is BOUNDED at ``_lat_cap`` (decimation), so a hot series costs
        O(cap) memory for the process lifetime, not O(samples)."""
        with self._lock:
            self._observe_locked(name, value)

    def _observe_locked(self, name: str, value: float) -> None:
        r = self._lat[name]
        if len(r) >= self._lat_cap:
            # reservoir decimation: keep every other sample
            del r[::2]
        r.append(value)

    def observe_latency(self, name: str, seconds: float) -> None:
        """observe() + cumulative fixed-bucket histogram update — the
        series Prometheus can aggregate (``<name>_bucket{le=...}``)."""
        i = bisect.bisect_left(LATENCY_BUCKETS, seconds)
        with self._lock:
            self._observe_locked(name, seconds)
            h = self._hist.get(name)
            if h is None:
                h = self._hist[name] = [
                    [0] * (len(LATENCY_BUCKETS) + 1), 0.0, 0]
            h[0][i] += 1
            h[1] += seconds
            h[2] += 1

    def percentile(self, name: str, q: float) -> Optional[float]:
        with self._lock:
            r = list(self._lat.get(name, ()))
        if not r:
            return None
        r.sort()  # on the copy — never under the lock
        idx = min(len(r) - 1, max(0, math.ceil(q / 100.0 * len(r)) - 1))
        return r[idx]

    def snapshot(self) -> Dict[str, float]:
        with self._lock:
            out = dict(self._counters)
            out.update(self._gauges)
            lat = {name: list(r) for name, r in self._lat.items() if r}
        for name, s in lat.items():  # derived stats on copies, lock-free
            s.sort()
            out[f"{name}.p50"] = s[len(s) // 2]
            out[f"{name}.p99"] = s[min(len(s) - 1, int(len(s) * 0.99))]
            out[f"{name}.mean"] = sum(s) / len(s)
            out[f"{name}.n"] = float(len(s))
        return out

    def gauges(self) -> Dict[str, float]:
        with self._lock:
            return dict(self._gauges)

    def histograms(self) -> Dict[str, Tuple[List[int], float, int]]:
        """Copy of every latency histogram: name -> (per-bucket counts
        incl. the final +Inf bucket, sum_seconds, count)."""
        with self._lock:
            return {name: (list(h[0]), h[1], h[2])
                    for name, h in self._hist.items()}

    def reset(self) -> None:
        with self._lock:
            self._counters.clear()
            self._gauges.clear()
            self._lat.clear()
            self._hist.clear()


metrics = Metrics()


class Timer:
    """Context manager feeding a Metrics latency series."""

    def __init__(self, name: str, m: Metrics = metrics):
        self.name = name
        self.m = m

    def __enter__(self):
        self.t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        self.m.observe_latency(self.name, time.perf_counter() - self.t0)
        return False
