"""Structured logging (reference analog: nnstreamer_log.c nns_logi/logw/loge).

Also hosts the lightweight metrics counter set promised by SURVEY.md §5.5:
frames in/out, queue depths, bytes moved, per-stage latency percentiles are
recorded in-process and dumped on demand — the reference had only GST debug
categories plus tensor_filter's latency property.
"""

from __future__ import annotations

import collections
import logging
import math
import os
import threading
import time
from typing import Dict, List, Optional

_FMT = "%(asctime)s %(levelname).1s %(name)s: %(message)s"
_configured = False


def logger(name: str) -> logging.Logger:
    global _configured
    if not _configured:
        level = os.environ.get("NNS_TPU_LOG", "WARNING").upper()
        logging.basicConfig(level=getattr(logging, level, logging.WARNING), format=_FMT)
        _configured = True
    return logging.getLogger(name)


class Metrics:
    """Process-wide counters + latency reservoirs, thread-safe."""

    def __init__(self):
        self._lock = threading.Lock()
        self._counters: Dict[str, float] = collections.defaultdict(float)
        self._lat: Dict[str, List[float]] = collections.defaultdict(list)
        self._lat_cap = 4096

    def count(self, name: str, value: float = 1.0) -> None:
        with self._lock:
            self._counters[name] += value

    def observe(self, name: str, value: float) -> None:
        """Record one sample of a distribution (latency seconds, batch
        occupancy, ...); snapshot() derives p50/p99/mean/n per series."""
        with self._lock:
            r = self._lat[name]
            if len(r) >= self._lat_cap:
                # reservoir decimation: keep every other sample
                del r[::2]
            r.append(value)

    def observe_latency(self, name: str, seconds: float) -> None:
        self.observe(name, seconds)

    def percentile(self, name: str, q: float) -> Optional[float]:
        with self._lock:
            r = sorted(self._lat.get(name, ()))
        if not r:
            return None
        idx = min(len(r) - 1, max(0, math.ceil(q / 100.0 * len(r)) - 1))
        return r[idx]

    def snapshot(self) -> Dict[str, float]:
        with self._lock:
            out = dict(self._counters)
            for name, r in self._lat.items():
                if r:
                    s = sorted(r)
                    out[f"{name}.p50"] = s[len(s) // 2]
                    out[f"{name}.p99"] = s[min(len(s) - 1, int(len(s) * 0.99))]
                    out[f"{name}.mean"] = sum(s) / len(s)
                    out[f"{name}.n"] = float(len(s))
        return out

    def reset(self) -> None:
        with self._lock:
            self._counters.clear()
            self._lat.clear()


metrics = Metrics()


class Timer:
    """Context manager feeding a Metrics latency series."""

    def __init__(self, name: str, m: Metrics = metrics):
        self.name = name
        self.m = m

    def __enter__(self):
        self.t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        self.m.observe_latency(self.name, time.perf_counter() - self.t0)
        return False
