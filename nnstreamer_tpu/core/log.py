"""Structured logging (reference analog: nnstreamer_log.c nns_logi/logw/loge).

Also hosts the lightweight metrics registry promised by SURVEY.md §5.5:
frames in/out, queue depths, bytes moved, per-stage latency percentiles are
recorded in-process and dumped on demand — the reference had only GST debug
categories plus tensor_filter's latency property.

Three sample families (all rendered by ``utils/profiler.metrics_text`` in
Prometheus text format, docs/OBSERVABILITY.md):

* **counters** (:meth:`Metrics.count`) — monotonically increasing totals;
* **gauges** (:meth:`Metrics.gauge`) — set-not-add instantaneous values
  (queue depths, staleness watermarks — fed by the runtime's sampler);
* **distributions** (:meth:`Metrics.observe` /
  :meth:`Metrics.observe_latency`) — a BOUNDED per-series reservoir
  (decimating at ``_lat_cap`` samples, so a hot stage can never grow
  process memory without limit) from which quantiles derive, and — for
  ``observe_latency`` series — a cumulative fixed-bucket **histogram**
  (``LATENCY_BUCKETS``), the real ``_bucket``/``_sum``/``_count``
  exposition Prometheus can aggregate across scrapes.

Every family optionally splits **per tenant** (docs/SERVING.md "Front
door"): ``count/gauge/observe_latency`` accept ``tenant=``.  For
counters and latency observations a non-None tenant updates BOTH the
base series (the aggregate everyone already scrapes) and a labeled twin
rendered as ``{tenant="..."}`` samples under the same exposition
family.  Gauges are the exception: a tenant gauge writes ONLY the
labeled twin — gauges are set-not-add, so writing one tenant's value
through to the base sample would clobber the aggregate (the base gauge
is set separately, e.g. by the runtime sampler).  ``tenant=None`` is
byte-for-byte the pre-tenant hot path — no extra lookups, no labeled
state.

Thread-safety discipline: every mutation and every raw-state copy happens
under one lock, but derived work (sorting reservoirs for quantiles) runs
on the COPY outside the lock — concurrent runner writes never stall
behind a scrape's O(n log n).
"""

from __future__ import annotations

import bisect
import collections
import logging
import math
import os
import threading
import time
from typing import Dict, List, Optional, Tuple

_FMT = "%(asctime)s %(levelname).1s %(name)s: %(message)s"
_configured = False


def logger(name: str) -> logging.Logger:
    global _configured
    if not _configured:
        level = os.environ.get("NNS_TPU_LOG", "WARNING").upper()
        logging.basicConfig(level=getattr(logging, level, logging.WARNING), format=_FMT)
        _configured = True
    return logging.getLogger(name)


#: histogram bucket upper bounds (seconds) for every observe_latency
#: series: 100 µs .. 10 s log-ish spaced (explicit ``le`` labels in the
#: Prometheus exposition; the final implicit bucket is +Inf)
LATENCY_BUCKETS: Tuple[float, ...] = (
    0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025,
    0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0)

#: histogram bucket upper bounds for occupancy/size distributions fed by
#: :meth:`Metrics.observe_bucketed` — the static bucket ladder's shape
#: (powers of two), so the ``<stage>.batch_occupancy`` exposition and the
#: adaptive ladder (pipeline/batching.AdaptiveLadder) describe the same
#: per-dispatch occupancy stream in the same units (final implicit
#: bucket: +Inf)
OCCUPANCY_BUCKETS: Tuple[float, ...] = (
    1.0, 2.0, 4.0, 8.0, 16.0, 32.0, 64.0, 128.0, 256.0, 512.0)

#: an admission (h2d) or materialization (d2h) wait above this is a real
#: transport/backlog stall, not a lock hop — ONE threshold for both
#: halves of the fetch-engine stall split (``<src>.h2d_stalls`` in
#: elements/source.py, ``<sink>.d2h_stalls`` in elements/sink.py) so the
#: two directions stay comparable.  docs/FETCH.md "Stall accounting".
STALL_FLOOR_S = 1e-3


class Metrics:
    """Process-wide counters + gauges + latency reservoirs/histograms,
    thread-safe (see module docstring for the lock discipline)."""

    #: nns-tsan lock discipline (lint --threads verifies statically,
    #: NNS_TPU_TSAN=1 verifies live — docs/ANALYSIS.md "Threads pass")
    _GUARDED_BY = {
        "_counters": "_lock", "_gauges": "_lock", "_lat": "_lock",
        "_hist": "_lock", "_vhist": "_lock", "_lcounters": "_lock",
        "_lgauges": "_lock", "_llat": "_lock", "_lhist": "_lock",
    }

    def __init__(self):
        # function-level import: utils.locks is stdlib-only, but core.log
        # is imported package-wide at init and the lazy import keeps the
        # core -> utils edge out of module load order
        from ..utils.locks import make_lock

        self._lock = make_lock("Metrics._lock")
        self._counters: Dict[str, float] = collections.defaultdict(float)
        self._gauges: Dict[str, float] = {}
        self._lat: Dict[str, List[float]] = collections.defaultdict(list)
        #: per-series reservoir bound: at cap, every other sample is
        #: dropped (decimation keeps a uniform-ish spread of the stream's
        #: lifetime instead of only its head or tail)
        self._lat_cap = 4096
        # name -> [bucket_counts(len(LATENCY_BUCKETS)+1 incl +Inf),
        #          sum, count]
        self._hist: Dict[str, list] = {}
        # value histograms with their own bounds (occupancy families):
        # name -> [bounds, bucket_counts(len(bounds)+1 incl +Inf), sum, n]
        self._vhist: Dict[str, list] = {}
        # labeled twins, keyed (name, tenant) — populated only when a
        # caller passes tenant= (docs/SERVING.md "Front door")
        self._lcounters: Dict[Tuple[str, str], float] = \
            collections.defaultdict(float)
        self._lgauges: Dict[Tuple[str, str], float] = {}
        self._llat: Dict[Tuple[str, str], List[float]] = \
            collections.defaultdict(list)
        self._lhist: Dict[Tuple[str, str], list] = {}

    def count(self, name: str, value: float = 1.0,
              tenant: Optional[str] = None) -> None:
        with self._lock:
            self._counters[name] += value
            if tenant is not None:
                self._lcounters[(name, tenant)] += value

    def gauge(self, name: str, value: float,
              tenant: Optional[str] = None) -> None:
        """Set an instantaneous value (queue depth, staleness watermark)."""
        with self._lock:
            if tenant is not None:
                self._lgauges[(name, tenant)] = float(value)
            else:
                self._gauges[name] = float(value)

    def observe(self, name: str, value: float) -> None:
        """Record one sample of a distribution (batch occupancy, sizes,
        ...); snapshot() derives p50/p99/mean/n per series.  The reservoir
        is BOUNDED at ``_lat_cap`` (decimation), so a hot series costs
        O(cap) memory for the process lifetime, not O(samples)."""
        with self._lock:
            self._observe_locked(self._lat, name, value)

    def _observe_locked(self, store, key, value: float) -> None:
        r = store[key]
        if len(r) >= self._lat_cap:
            # reservoir decimation: keep every other sample
            del r[::2]
        r.append(value)

    def _hist_locked(self, store, key, i: int, seconds: float) -> None:
        h = store.get(key)
        if h is None:
            h = store[key] = [[0] * (len(LATENCY_BUCKETS) + 1), 0.0, 0]
        h[0][i] += 1
        h[1] += seconds
        h[2] += 1

    def observe_bucketed(self, name: str, value: float,
                         bounds: Tuple[float, ...] = OCCUPANCY_BUCKETS
                         ) -> None:
        """observe() + a cumulative fixed-bucket histogram with
        ``bounds`` as the explicit ``le`` labels — the occupancy twin of
        :meth:`observe_latency`, so batch-occupancy distributions render
        as real aggregatable ``_bucket``/``_sum``/``_count`` series
        (docs/BATCHING.md "Metrics") instead of point-in-time quantile
        gauges only."""
        with self._lock:
            self._observe_locked(self._lat, name, value)
            h = self._vhist.get(name)
            if h is None:
                h = self._vhist[name] = [tuple(bounds),
                                         [0] * (len(bounds) + 1), 0.0, 0]
            # first-writer-wins bounds: a series' exposition must keep one
            # bucket layout for its lifetime (Prometheus contract)
            h[1][bisect.bisect_left(h[0], value)] += 1
            h[2] += value
            h[3] += 1

    def value_histograms(self) -> Dict[str, Tuple[Tuple[float, ...],
                                                  List[int], float, int]]:
        """Copy of every bucketed value histogram: name -> (bounds,
        per-bucket counts incl. the final +Inf bucket, sum, count)."""
        with self._lock:
            return {name: (h[0], list(h[1]), h[2], h[3])
                    for name, h in self._vhist.items()}

    def observe_latency(self, name: str, seconds: float,
                        tenant: Optional[str] = None) -> None:
        """observe() + cumulative fixed-bucket histogram update — the
        series Prometheus can aggregate (``<name>_bucket{le=...}``).
        ``tenant`` additionally feeds the labeled twin series."""
        i = bisect.bisect_left(LATENCY_BUCKETS, seconds)
        with self._lock:
            self._observe_locked(self._lat, name, seconds)
            self._hist_locked(self._hist, name, i, seconds)
            if tenant is not None:
                key = (name, tenant)
                self._observe_locked(self._llat, key, seconds)
                self._hist_locked(self._lhist, key, i, seconds)

    def observe_latency_labeled(self, name: str, seconds: float,
                                tenant: str) -> None:
        """Update ONLY the labeled twin (no base-series sample) — for
        call sites that already fed the base series once per dispatch
        and split the amortized per-row time across member tenants."""
        i = bisect.bisect_left(LATENCY_BUCKETS, seconds)
        with self._lock:
            key = (name, tenant)
            self._observe_locked(self._llat, key, seconds)
            self._hist_locked(self._lhist, key, i, seconds)

    def percentile(self, name: str, q: float,
                   tenant: Optional[str] = None) -> Optional[float]:
        with self._lock:
            if tenant is not None:
                r = list(self._llat.get((name, tenant), ()))
            else:
                r = list(self._lat.get(name, ()))
        if not r:
            return None
        r.sort()  # on the copy — never under the lock
        idx = min(len(r) - 1, max(0, math.ceil(q / 100.0 * len(r)) - 1))
        return r[idx]

    def fraction_over(self, name: str, threshold_s: float,
                      tenant: Optional[str] = None
                      ) -> Tuple[float, int]:
        """(fraction of recorded samples strictly above ``threshold_s``,
        total samples) for one ``observe_latency`` series, computed from
        the cumulative histogram — the SLO engine's bad-event source
        (utils/slo.py).  Resolution is one bucket: samples in the bucket
        the threshold falls into count as UNDER (optimistic by at most
        one bucket width)."""
        with self._lock:
            h = (self._lhist.get((name, tenant)) if tenant is not None
                 else self._hist.get(name))
            if h is None or not h[2]:
                return 0.0, 0
            counts, _total, n = list(h[0]), h[1], h[2]
        j = bisect.bisect_left(LATENCY_BUCKETS, threshold_s)
        over = sum(counts[j + 1:])
        return over / n, n

    def snapshot(self) -> Dict[str, float]:
        with self._lock:
            out = dict(self._counters)
            out.update(self._gauges)
            lat = {name: list(r) for name, r in self._lat.items() if r}
        for name, s in lat.items():  # derived stats on copies, lock-free
            s.sort()
            out[f"{name}.p50"] = s[len(s) // 2]
            out[f"{name}.p99"] = s[min(len(s) - 1, int(len(s) * 0.99))]
            out[f"{name}.mean"] = sum(s) / len(s)
            out[f"{name}.n"] = float(len(s))
        return out

    def gauges(self) -> Dict[str, float]:
        with self._lock:
            return dict(self._gauges)

    def histograms(self) -> Dict[str, Tuple[List[int], float, int]]:
        """Copy of every latency histogram: name -> (per-bucket counts
        incl. the final +Inf bucket, sum_seconds, count)."""
        with self._lock:
            return {name: (list(h[0]), h[1], h[2])
                    for name, h in self._hist.items()}

    # -- labeled (per-tenant) accessors -----------------------------------
    def labeled_histograms(self) -> Dict[Tuple[str, str],
                                         Tuple[List[int], float, int]]:
        """Copy of every tenant-labeled latency histogram:
        (name, tenant) -> (bucket counts incl. +Inf, sum_seconds, n)."""
        with self._lock:
            return {key: (list(h[0]), h[1], h[2])
                    for key, h in self._lhist.items()}

    def reservoir(self, name: str,
                  tenant: Optional[str] = None) -> List[float]:
        """Copy of one distribution's bounded reservoir (the quantile
        source) — base series, or the labeled twin when ``tenant``."""
        with self._lock:
            if tenant is not None:
                return list(self._llat.get((name, tenant), ()))
            return list(self._lat.get(name, ()))

    def labeled_counters(self) -> Dict[Tuple[str, str], float]:
        with self._lock:
            return dict(self._lcounters)

    def labeled_gauges(self) -> Dict[Tuple[str, str], float]:
        with self._lock:
            return dict(self._lgauges)

    def tenants(self, name: str) -> List[str]:
        """Sorted tenant label values seen on any labeled family whose
        series name equals ``name`` (histograms + counters + gauges)."""
        with self._lock:
            seen = {t for (n, t) in self._lhist if n == name}
            seen.update(t for (n, t) in self._lcounters if n == name)
            seen.update(t for (n, t) in self._lgauges if n == name)
        return sorted(seen)

    def reset(self) -> None:
        with self._lock:
            self._counters.clear()
            self._gauges.clear()
            self._lat.clear()
            self._hist.clear()
            self._vhist.clear()
            self._lcounters.clear()
            self._lgauges.clear()
            self._llat.clear()
            self._lhist.clear()


metrics = Metrics()


class Timer:
    """Context manager feeding a Metrics latency series."""

    def __init__(self, name: str, m: Metrics = metrics):
        self.name = name
        self.m = m

    def __enter__(self):
        self.t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        self.m.observe_latency(self.name, time.perf_counter() - self.t0)
        return False
