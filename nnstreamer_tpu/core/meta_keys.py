"""Shared registry of protocol-bearing buffer-meta keys.

Every meta key that rides the query wire, routes a message, or carries a
protocol decision (shed / abort / replay) is declared HERE and imported
by the modules that stamp or read it (elements/query.py, elements/sink.py,
utils/tracing.py, utils/elastic.py, utils/armor.py, filters/llm.py).
The nns-proto lint (analysis/protocol.py, docs/ANALYSIS.md "Protocol
pass") treats this module as the alphabet source of truth: a protocol
meta literal used elsewhere that is not registered here is reported as
``meta-key-drift``, and the checked protocol models
(analysis/statemachine.py) must declare the same alphabet or the
model-vs-code drift gate fails.

Import rule: this module is pure constants — no imports — so anything
(core/, utils/, elements/, the jax-free analysis package) may depend on
it without cycles.
"""

# --- message routing (elements/query.py) --------------------------------
#: wire message id: stamped by the client, echoed by every response
META_QUERY_MSG = "_query_msg"
#: server-side connection id the answer routes back to (never on the wire)
META_QUERY_CONN = "_query_conn"
#: journal seqno of an accepted request (docs/ROBUSTNESS.md): stamped by
#: the serversrc reader, consumed (ack + strip) by the serversink
META_JOURNAL_SEQ = "_journal_seq"
#: marks a buffer re-admitted by journal replay after a crash
META_JOURNAL_REPLAY = "_journal_replay"
#: serversrc batching: list of per-request meta dicts on one stacked buffer
META_QUERY_BATCH = "_query_batch"

# --- identity / tracing (utils/tracing.py, docs/SERVING.md) -------------
#: tenant identity riding the wire meta (admission + accounting)
META_TENANT = "_tenant"
#: per-buffer trace id (stamped at source ingress when tracing is active)
META_TRACE_ID = "_tid"
#: distributed parent trace context (docs/OBSERVABILITY.md "Distributed
#: tracing"): the CLIENT's epoch-prefixed trace id riding the query wire
#: both directions — the serversrc adopts it as the server-side trace id
#: (after scrubbing any client-supplied ``_tid``), and the serversink
#: echoes it on every response/token so the client can link ``recv``
#: spans back to the originating request
META_TRACE_PARENT = "_tparent"
#: ingress timestamp (ns) for end-to-end latency spans
META_INGRESS_NS = "_ts0"
#: enqueue timestamp (ns) for queue-wait spans
META_ENQUEUE_NS = "_tq"

# --- poison armor (utils/armor.py) --------------------------------------
#: marks a quarantined/poison terminator buffer (runners skip stages)
META_POISON = "_poison"
#: dead-letter-queue record annotation (why/when the entry quarantined)
META_DLQ = "_dlq"
#: host-side completion callback handle — stripped (popped) before a
#: buffer is quarantined or turned into a terminator; stamped by the
#: runtime, outside the protocol modules
META_HOST_POST = "_host_post"

# --- streaming telemetry (filters/llm.py) -------------------------------
#: monotonic emit timestamp stamped on every streamed token; consumed by
#: client-side TPOT dashboards, outside the protocol modules
META_EMIT_T = "emit_t"

# --- streaming responses (utils/elastic.py, filters/llm.py) -------------
#: continuous-batching stream identity (submit -> every emitted token)
META_STREAM_ID = "stream_id"
#: 0-based index of a streamed response chunk within its request
META_STREAM_INDEX = "stream_index"
#: final chunk of a streamed response (True on exactly one buffer)
META_STREAM_LAST = "stream_last"
#: typed terminator: the stream ended abnormally (pair with abort reason)
META_STREAM_ABORTED = "stream_aborted"
#: why a stream/request was aborted — value must be in :data:`ABORT_REASONS`
META_ABORT_REASON = "abort_reason"

# --- server verdict flags (elements/query.py responses) -----------------
#: admission verdict: request shed under backlog/tenant pressure
META_SHED = "shed"
#: a frame failed wire validation; client sees this instead of a timeout
META_WIRE_REJECT = "wire_reject"
#: human-readable error detail riding a reject/abort response
META_ERROR = "error"

#: closed vocabulary for :data:`META_ABORT_REASON` values.  Extending it
#: means teaching the client taxonomy (elements/query.py
#: ``_handle_response``) AND the protocol models about the new reason.
ABORT_REASON_WIRE = "wire"
ABORT_REASON_POISON = "poison"
ABORT_REASON_INTERNAL = "internal"
ABORT_REASONS = frozenset({
    ABORT_REASON_WIRE, ABORT_REASON_POISON, ABORT_REASON_INTERNAL,
})

#: JSON control-channel message types (utils/net.py handshake; the
#: clock pair is the nns-weave NTP-style echo — docs/OBSERVABILITY.md
#: "Distributed tracing": a client-initiated probe carrying t0, answered
#: with (t0, t1, t2) + the server's trace epoch)
CTRL_HELLO = "hello"
CTRL_ACK = "ack"
CTRL_NACK = "nack"
CTRL_CLOCK = "clock"
CTRL_CLOCK_ACK = "clock_ack"
CONTROL_TYPES = frozenset({
    CTRL_HELLO, CTRL_ACK, CTRL_NACK, CTRL_CLOCK, CTRL_CLOCK_ACK,
})

#: the full meta-key alphabet — the lint's ground truth
PROTOCOL_META_KEYS = frozenset({
    META_QUERY_MSG, META_QUERY_CONN, META_JOURNAL_SEQ, META_JOURNAL_REPLAY,
    META_QUERY_BATCH, META_TENANT, META_TRACE_ID, META_TRACE_PARENT,
    META_INGRESS_NS,
    META_ENQUEUE_NS, META_POISON, META_DLQ, META_STREAM_ID,
    META_STREAM_INDEX, META_STREAM_LAST, META_STREAM_ABORTED,
    META_ABORT_REASON, META_SHED, META_WIRE_REJECT, META_ERROR,
    META_HOST_POST, META_EMIT_T,
})

#: keys whose producer OR consumer lives outside the protocol modules
#: (runtime stamping, tracing spans, DLQ drain tooling, client-side
#: dashboards).  Registered so they cannot drift, but exempt from the
#: handler-totality check (sent-without-reader / read-without-sender is
#: expected across the lint boundary) and from the model drift alphabet.
EXTERNAL_META_KEYS = frozenset({
    META_TRACE_ID, META_INGRESS_NS, META_ENQUEUE_NS,
    META_HOST_POST, META_EMIT_T, META_DLQ,
})
