"""Sub-plugin and element registries.

Reference analog: ``gst/nnstreamer/nnstreamer_subplugin.c`` (name->vtable hash
per sub-plugin class, lazy dlopen from configured paths) plus GStreamer's
element factory registry (upstream-reconstructed; SURVEY.md §2.1).

TPU-first translation: sub-plugins are Python classes registered under a
(kind, name) key via decorators; "lazy dlopen" becomes lazy import of the
built-in plugin modules on first lookup, plus user modules listed in
config/env (``NNS_TPU_PLUGINS=pkg.mod:pkg2.mod2``).  Entry-point discovery
keeps the reference's "drop a .so in a directory" extensibility without
dynamic linking.
"""

from __future__ import annotations

import importlib
import threading
from typing import Callable, Dict, Iterable, List, Optional, Tuple, Type

from .config import get_config
from .log import logger

log = logger(__name__)

# Sub-plugin kinds (reference: NNS_SUBPLUGIN_FILTER / _DECODER / _CONVERTER / _TRAINER).
KIND_ELEMENT = "element"
KIND_FILTER = "filter"
KIND_DECODER = "decoder"
KIND_CONVERTER = "converter"
KIND_TRAINER = "trainer"

_registry: Dict[Tuple[str, str], type] = {}
_aliases: Dict[Tuple[str, str], str] = {}
_lock = threading.RLock()
_builtins_loaded = False

#: Modules imported lazily on first lookup; each registers its plugins at
#: import time (the analog of .so constructors calling nnstreamer_filter_probe).
_BUILTIN_MODULES = [
    "nnstreamer_tpu.elements.source",
    "nnstreamer_tpu.elements.video",
    "nnstreamer_tpu.elements.converter",
    "nnstreamer_tpu.elements.transform",
    "nnstreamer_tpu.elements.filter",
    "nnstreamer_tpu.elements.decoder",
    "nnstreamer_tpu.elements.routing",
    "nnstreamer_tpu.elements.aggregator",
    "nnstreamer_tpu.elements.sink",
    "nnstreamer_tpu.elements.repo",
    "nnstreamer_tpu.elements.sparse",
    "nnstreamer_tpu.elements.rate",
    "nnstreamer_tpu.elements.crop",
    "nnstreamer_tpu.elements.cond",
    "nnstreamer_tpu.elements.debug",
    "nnstreamer_tpu.elements.query",
    "nnstreamer_tpu.elements.edge",
    "nnstreamer_tpu.elements.datarepo",
    "nnstreamer_tpu.elements.trainer",
    "nnstreamer_tpu.elements.shm",
    "nnstreamer_tpu.elements.mqtt",
    "nnstreamer_tpu.elements.grpc_io",
    "nnstreamer_tpu.filters.custom_easy",
    "nnstreamer_tpu.filters.custom_so",
    "nnstreamer_tpu.filters.jax_fw",
    "nnstreamer_tpu.filters.python3",
    "nnstreamer_tpu.filters.llm",
    "nnstreamer_tpu.filters.torch_fw",
    "nnstreamer_tpu.filters.gated",
    "nnstreamer_tpu.decoders.image_labeling",
    "nnstreamer_tpu.decoders.bounding_boxes",
    "nnstreamer_tpu.decoders.pose",
    "nnstreamer_tpu.decoders.image_segment",
    "nnstreamer_tpu.decoders.direct_video",
    "nnstreamer_tpu.decoders.serialize",
    "nnstreamer_tpu.decoders.ctc",
    "nnstreamer_tpu.converters.serialize",
    "nnstreamer_tpu.trainer.subplugin",
]


def register(kind: str, name: str, cls=None, *, aliases: Iterable[str] = ()):
    """Register ``cls`` under (kind, name).  Usable as a decorator:

    >>> @register(KIND_FILTER, "custom-easy")
    ... class CustomEasy: ...
    """

    def do(c):
        with _lock:
            key = (kind, name)
            if key in _registry and _registry[key] is not c:
                log.debug("re-registering %s/%s", kind, name)
            _registry[key] = c
            for a in aliases:
                _aliases[(kind, a)] = name
        return c

    return do(cls) if cls is not None else do


def register_element(name: str, cls=None, **kw):
    return register(KIND_ELEMENT, name, cls, **kw)


def register_filter(name: str, cls=None, **kw):
    return register(KIND_FILTER, name, cls, **kw)


def register_decoder(name: str, cls=None, **kw):
    return register(KIND_DECODER, name, cls, **kw)


def register_converter(name: str, cls=None, **kw):
    return register(KIND_CONVERTER, name, cls, **kw)


def register_trainer(name: str, cls=None, **kw):
    return register(KIND_TRAINER, name, cls, **kw)


def _ensure_builtins():
    global _builtins_loaded
    if _builtins_loaded:
        return
    with _lock:
        if _builtins_loaded:
            return
        _builtins_loaded = True  # set first: modules may look things up
        for mod in _BUILTIN_MODULES + get_config().plugin_modules:
            try:
                importlib.import_module(mod)
            except ImportError as e:
                # Module file simply absent (not yet built / optional): fine.
                # Module EXISTS but failed to import: that's a real bug whose
                # elements would silently vanish — surface it loudly.
                if e.name == mod:
                    log.debug("plugin module %s absent: %s", mod, e)
                else:
                    raise


def lookup(kind: str, name: str) -> Optional[type]:
    _ensure_builtins()
    with _lock:
        key = (kind, name)
        if key in _aliases:
            key = (kind, _aliases[key])
        return _registry.get(key)


def get(kind: str, name: str) -> type:
    cls = lookup(kind, name)
    if cls is None:
        raise KeyError(
            f"no {kind} sub-plugin named {name!r}; known: {sorted(names(kind))}"
        )
    return cls


def names(kind: str) -> List[str]:
    _ensure_builtins()
    with _lock:
        return sorted(n for k, n in _registry if k == kind)


def aliases_of(kind: str, name: str) -> List[str]:
    """Registered aliases resolving to ``(kind, name)`` (introspection)."""
    _ensure_builtins()
    with _lock:
        return sorted(
            alias for (k, alias), target in _aliases.items()
            if k == kind and target == name
        )


def unregister(kind: str, name: str) -> bool:
    with _lock:
        return _registry.pop((kind, name), None) is not None
