"""Backend/platform selection helpers.

One quirk of environments with a site hook that pre-imports jax (the dev
TPU tunnel does): ``JAX_PLATFORMS`` read from the environment lands too
late for a pre-imported jax, so a user's ``JAX_PLATFORMS=cpu`` would be
ignored and the process could touch — and hang on — an unreachable
device tunnel.  :func:`honor_jax_platforms` makes the env var behave as
documented; importing THIS module does not import jax, so entry scripts
can call it before any backend init.
"""

from __future__ import annotations

import os
import sys


def honor_jax_platforms() -> None:
    """Re-assert ``JAX_PLATFORMS`` through the live config when jax was
    pre-imported (site hook); no-op — and no jax import — otherwise, since
    a fresh import honors the env var natively.

    For SCRIPT entry points (bench.py, tools/smoke_tpu.py) that own their
    process — the library itself never mutates global jax config on
    import, so a user's deliberate programmatic pin survives
    ``import nnstreamer_tpu``.
    """
    plat = os.environ.get("JAX_PLATFORMS")
    if not (plat and "jax" in sys.modules):
        return
    import jax

    jax.config.update("jax_platforms", plat)
    _warn_if_backends_live(plat, stacklevel=3)  # attribute to the entry script


def enable_compilation_cache(path: str | None = None) -> None:
    """Point jax at a persistent on-disk compilation cache.

    For SCRIPT entry points (bench.py, smoke) — same ownership rule as
    :func:`honor_jax_platforms`.  Measured on the tunneled TPU backend: a
    cross-process recompile of a cached program drops from tens of
    seconds to sub-second, which is most of the wall time of short driver
    runs.  TPU-backend runs only: CPU AOT cache hits warn about
    machine-feature mismatches ("could lead to SIGILL"), so CPU-pinned
    runs — and the driver graft entry, whose dry run is CPU by design —
    must stay uncached.  Default cache dir lives inside the repo (the
    environment forbids writes outside it); override with
    ``NNSTPU_XLA_CACHE`` (empty string disables).
    """
    env = os.environ.get("NNSTPU_XLA_CACHE")
    if env == "":
        return
    if "cpu" in os.environ.get("JAX_PLATFORMS", "").lower():
        return  # CPU AOT cache = SIGILL hazard; see docstring
    # The env string alone is not enough: on a host with no TPU and no
    # JAX_PLATFORMS, jax silently resolves to CPU — ask the backend.
    # default_backend() initializes the backend, which scripts calling
    # this at startup are about to do anyway.
    import jax

    try:
        if jax.default_backend() == "cpu":
            return
    except Exception:  # noqa: BLE001 - no backend at all: nothing to cache
        return
    if path is None:
        path = env or os.path.join(
            os.path.dirname(os.path.dirname(os.path.dirname(
                os.path.abspath(__file__)))), ".xla_cache")
    os.makedirs(path, exist_ok=True)
    jax.config.update("jax_compilation_cache_dir", path)
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)


def _warn_if_backends_live(plat: str, stacklevel: int = 2) -> None:
    try:  # best-effort: warn when the update can no longer take effect
        from jax._src import xla_bridge

        if not getattr(xla_bridge, "_backends", None):
            return
        import jax

        # A live backend that already IS the requested platform (test
        # suites pin cpu, then import an entry script that re-asserts the
        # same pin) lost nothing — warning there is pure noise.
        want = plat.split(",")[0].strip().lower()
        if want and jax.default_backend() == want:
            return
        import warnings

        warnings.warn(
            "JAX backend already initialized before JAX_PLATFORMS "
            "could be honored; the requested platform may be ignored",
            RuntimeWarning, stacklevel=stacklevel + 1)
    except Exception:  # noqa: BLE001 - private API probe only
        pass
