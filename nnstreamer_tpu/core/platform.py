"""Backend/platform selection helpers.

One quirk of environments with a site hook that pre-imports jax (the dev
TPU tunnel does): ``JAX_PLATFORMS`` read from the environment lands too
late for a pre-imported jax, so a user's ``JAX_PLATFORMS=cpu`` would be
ignored and the process could touch — and hang on — an unreachable
device tunnel.  :func:`honor_jax_platforms` makes the env var behave as
documented; importing THIS module does not import jax, so entry scripts
can call it before any backend init.
"""

from __future__ import annotations

import os
import sys


def honor_jax_platforms() -> None:
    """Re-assert ``JAX_PLATFORMS`` through the live config when jax was
    pre-imported (site hook); no-op — and no jax import — otherwise, since
    a fresh import honors the env var natively.

    For SCRIPT entry points (bench.py, tools/smoke_tpu.py) that own their
    process — the library itself never mutates global jax config on
    import, so a user's deliberate programmatic pin survives
    ``import nnstreamer_tpu``.
    """
    plat = os.environ.get("JAX_PLATFORMS")
    if not (plat and "jax" in sys.modules):
        return
    import jax

    jax.config.update("jax_platforms", plat)
    try:  # best-effort: warn when the update can no longer take effect
        from jax._src import xla_bridge

        if getattr(xla_bridge, "_backends", None):
            import warnings

            warnings.warn(
                "JAX backend already initialized before JAX_PLATFORMS "
                "could be honored; the requested platform may be ignored",
                RuntimeWarning, stacklevel=2)
    except Exception:  # noqa: BLE001 - private API probe only
        pass
