"""Backend/platform selection helpers.

One quirk of environments with a site hook that pre-imports jax (the dev
TPU tunnel does): ``JAX_PLATFORMS`` read from the environment lands too
late for a pre-imported jax, so a user's ``JAX_PLATFORMS=cpu`` would be
ignored and the process could touch — and hang on — an unreachable
device tunnel.  :func:`honor_jax_platforms` makes the env var behave as
documented; importing THIS module does not import jax, so entry scripts
can call it before any backend init.
"""

from __future__ import annotations

import os
import sys


def honor_jax_platforms() -> None:
    """Re-assert ``JAX_PLATFORMS`` through the live config when jax was
    pre-imported (site hook); no-op — and no jax import — otherwise, since
    a fresh import honors the env var natively."""
    plat = os.environ.get("JAX_PLATFORMS")
    if plat and "jax" in sys.modules:
        import jax

        jax.config.update("jax_platforms", plat)
