"""nnstreamer_tpu.core"""
