"""Capabilities and negotiation between pipeline elements.

Reference analog: GstCaps with the nnstreamer media types
(``other/tensors``, ``other/tensor``) plus raw media caps
(``video/x-raw``, ``audio/x-raw``, ``text/x-raw``,
``application/octet-stream``) — caps<->config conversion lives in
``gst/nnstreamer/tensor_common.c`` upstream (reconstructed; SURVEY.md §2.1).

Simplified model: a :class:`Caps` is a media type + field dict where each
field value is either a concrete value, a tuple of allowed options, or
``ANY``.  Negotiation intersects the src pad's caps with the sink pad's
template; elements then "fixate" remaining options.  This is deliberately a
small, deterministic subset of GStreamer's machinery — enough to express the
reference's pipelines, simple enough to reason about in a compiler pass.
"""

from __future__ import annotations

import dataclasses
from enum import Enum
from typing import Any, Dict, Optional, Tuple, Union

from .types import TensorsSpec, parse_fraction


class MediaType(str, Enum):
    VIDEO = "video/x-raw"
    AUDIO = "audio/x-raw"
    TEXT = "text/x-raw"
    OCTET = "application/octet-stream"
    TENSORS = "other/tensors"
    FLEX_TENSORS = "other/tensors-flexible"  # flexible format on the wire
    ANY = "ANY"


class _Any:
    def __repr__(self):
        return "ANY"


ANY = _Any()


_VIDEO_FORMATS_BPP = {
    "RGB": 3,
    "BGR": 3,
    "RGBA": 4,
    "BGRA": 4,
    "ARGB": 4,
    "ABGR": 4,
    "RGBx": 4,
    "BGRx": 4,
    "GRAY8": 1,
    "GRAY16_LE": 2,
}

_AUDIO_FORMATS = {"S8": "int8", "U8": "uint8", "S16LE": "int16", "U16LE": "uint16",
                  "S32LE": "int32", "U32LE": "uint32", "F32LE": "float32",
                  "F64LE": "float64"}


def video_bpp(fmt: str) -> int:
    try:
        return _VIDEO_FORMATS_BPP[fmt]
    except KeyError:
        raise ValueError(f"unsupported video format {fmt!r}") from None


def audio_dtype(fmt: str) -> str:
    try:
        return _AUDIO_FORMATS[fmt]
    except KeyError:
        raise ValueError(f"unsupported audio format {fmt!r}") from None


@dataclasses.dataclass(frozen=True)
class Caps:
    """Media type + constraint fields.  Field values: concrete | tuple | ANY."""

    media: MediaType
    fields: Tuple[Tuple[str, Any], ...] = ()

    @classmethod
    def new(cls, media: Union[MediaType, str], **fields) -> "Caps":
        if isinstance(media, str) and media not in MediaType._value2member_map_:
            raise ValueError(f"unknown media type {media!r}")
        return cls(MediaType(media), tuple(sorted(fields.items())))

    @classmethod
    def any(cls) -> "Caps":
        return cls(MediaType.ANY)

    @classmethod
    def tensors(cls, spec: Optional[TensorsSpec] = None) -> "Caps":
        if spec is None:
            return cls.new(MediaType.TENSORS)
        return cls.new(MediaType.TENSORS, spec=spec)

    # -- views -------------------------------------------------------------
    @property
    def dict(self) -> Dict[str, Any]:
        return dict(self.fields)

    def get(self, key: str, default=None):
        return self.dict.get(key, default)

    @property
    def spec(self) -> Optional[TensorsSpec]:
        s = self.get("spec")
        return s if isinstance(s, TensorsSpec) else None

    def is_any(self) -> bool:
        return self.media == MediaType.ANY

    def is_fixed(self) -> bool:
        return not self.is_any() and all(
            not isinstance(v, (tuple, _Any)) for _, v in self.fields
        )

    # -- negotiation -------------------------------------------------------
    def intersect(self, other: "Caps") -> Optional["Caps"]:
        """Narrow two caps to their common subset; None when incompatible."""
        if self.is_any():
            return other
        if other.is_any():
            return self
        if self.media != other.media:
            # flexible tensors accept static tensors (upstream: flex pads).
            medias = {self.media, other.media}
            if medias == {MediaType.TENSORS, MediaType.FLEX_TENSORS}:
                pass
            else:
                return None
        out: Dict[str, Any] = {}
        a, b = self.dict, other.dict
        for key in set(a) | set(b):
            va, vb = a.get(key, ANY), b.get(key, ANY)
            v = _intersect_value(va, vb)
            if v is _NO:
                return None
            if not isinstance(v, _Any):
                out[key] = v
        return Caps.new(self.media, **out)

    def fixate(self) -> "Caps":
        """Pick the first option for every still-open field."""
        out = {}
        for k, v in self.fields:
            if isinstance(v, _Any):
                continue
            out[k] = v[0] if isinstance(v, tuple) else v
        return Caps.new(self.media, **out)

    def __str__(self) -> str:  # pragma: no cover
        fs = ",".join(f"{k}={v}" for k, v in self.fields)
        return f"{self.media.value}" + (f",{fs}" if fs else "")


def intersect_template(caps: Caps, templates) -> Optional[Caps]:
    """Intersect ``caps`` against a pad template: one :class:`Caps` or a
    tuple of alternatives (GstCaps is a *list* of structures; element pad
    templates mirror that here as a tuple).  Returns the first non-empty
    intersection, or None when every alternative is incompatible.

    This is the negotiation primitive exposed for OFFLINE use: the static
    analyzer (``nnstreamer_tpu.analysis``) runs it over every edge of a
    parsed graph without instantiating elements or touching a device.
    """
    if isinstance(templates, Caps):
        templates = (templates,)
    for t in templates:
        got = caps.intersect(t)
        if got is not None:
            return got
    return None


def _explain_spec_mismatch(a: TensorsSpec, b: TensorsSpec) -> str:
    from .types import dims_to_string, dtype_name

    if a.format != b.format:
        return f"tensor format {a.format.value} ⊄ {b.format.value}"
    if len(a) != len(b):
        return f"num_tensors {len(a)} ⊄ {len(b)}"
    for i, (sa, sb) in enumerate(zip(a.specs, b.specs)):
        at = f"[{i}]" if len(a) > 1 else ""
        if sa.dtype != sb.dtype:
            return f"dtype{at} {dtype_name(sa.dtype)} ⊄ {dtype_name(sb.dtype)}"
        if not sa.is_compatible(sb):
            return (f"dims{at} {dims_to_string(sa.dims)} ⊄ "
                    f"{dims_to_string(sb.dims)}")
    return "incompatible tensor specs"


def explain_mismatch(a: Caps, b: Caps) -> str:
    """Field-level reason two caps do not intersect (diagnostic text).

    Finds the first offending field the same way :meth:`Caps.intersect`
    walks them, so the explanation always names the field that actually
    failed — ``dtype uint8 ⊄ float32``, ``media video/x-raw ⊄
    other/tensors`` — instead of dumping both caps at the reader.
    """
    if a.media != b.media:
        medias = {a.media, b.media}
        if medias != {MediaType.TENSORS, MediaType.FLEX_TENSORS}:
            return f"media {a.media.value} ⊄ {b.media.value}"
    fa, fb = a.dict, b.dict
    for key in sorted(set(fa) | set(fb)):
        va, vb = fa.get(key, ANY), fb.get(key, ANY)
        if isinstance(va, TensorsSpec) and isinstance(vb, TensorsSpec):
            if not va.is_compatible(vb):
                return _explain_spec_mismatch(va, vb)
            continue
        if _intersect_value(va, vb) is _NO:
            return f"{key} {va} ⊄ {vb}"
    return "incompatible caps"


class _No:
    pass


_NO = _No()


def _intersect_value(a, b):
    if isinstance(a, _Any):
        return b
    if isinstance(b, _Any):
        return a
    ta = a if isinstance(a, tuple) else (a,)
    tb = b if isinstance(b, tuple) else (b,)
    if isinstance(a, TensorsSpec) or isinstance(b, TensorsSpec):
        if isinstance(a, TensorsSpec) and isinstance(b, TensorsSpec):
            return a if a.is_compatible(b) else _NO
        return a if isinstance(a, TensorsSpec) else b
    common = [x for x in ta if x in tb]
    if not common:
        return _NO
    if len(common) == 1:
        return common[0]
    return tuple(common)


def _split_caps_fields(text: str) -> list:
    """Split a caps string on ',' while keeping '{...}' option lists intact."""
    parts = []
    depth = 0
    cur = []
    for ch in text:
        if ch == "{":
            depth += 1
        elif ch == "}":
            depth -= 1
        if ch == "," and depth == 0:
            parts.append("".join(cur).strip())
            cur = []
        else:
            cur.append(ch)
    parts.append("".join(cur).strip())
    return parts


def parse_caps_string(text: str) -> Caps:
    """Parse a gst-launch caps filter like ``video/x-raw,format=RGB,width=224``
    including option lists ``format={RGB,BGR}``."""
    parts = _split_caps_fields(text)
    media = parts[0]
    fields: Dict[str, Any] = {}
    for p in parts[1:]:
        if not p:
            continue
        if "=" not in p:
            raise ValueError(f"bad caps field {p!r} in {text!r}")
        k, v = p.split("=", 1)
        k = k.strip()
        v = v.strip()
        # (int)640 style type prefixes from gst-launch syntax
        if v.startswith("(") and ")" in v:
            v = v[v.index(")") + 1 :]
        if "/" in v and k in ("framerate", "rate") and v.replace("/", "").isdigit():
            num, den = v.split("/")
            fields[k] = (int(num), int(den)) if k == "framerate" else int(num)
            continue
        if v.startswith("{") and v.endswith("}"):  # option list {RGB,BGR}
            opts = [o.strip() for o in v[1:-1].split(",") if o.strip()]
            fields[k] = tuple(_coerce(o) for o in opts)
            continue
        # Tensor-spec fields stay raw strings: '.' separates tensors there
        # (dimensions=4.10 is two 1-D tensors), so numeric coercion would
        # corrupt them (float 4.10 -> "4.1").
        fields[k] = v if k in ("dimensions", "types", "names") else _coerce(v)
    if media in (
        MediaType.TENSORS.value,
        MediaType.FLEX_TENSORS.value,
        "other/tensor",
    ) and "dimensions" in fields:
        # Reference caps syntax: tensors separated by '.' inside one field
        # (``dimensions=3:224:224:1.10:1:1:1,types=uint8.float32``).
        dims = str(fields.pop("dimensions")).replace(".", ",")
        types = str(fields.pop("types", "uint8")).replace(".", ",")
        names = str(fields.pop("names", "")).replace(".", ",")
        fields.pop("num_tensors", None)
        fmt = fields.pop("format", "static")
        rate = parse_fraction(fields.pop("framerate", (0, 1)))
        if media == MediaType.FLEX_TENSORS.value:
            fmt = "flexible"
        if media == "other/tensor":
            media = MediaType.TENSORS.value
        fields["spec"] = TensorsSpec.from_string(
            dims, types, names, format=fmt, rate=rate
        )
    return Caps.new(media, **fields)


def _coerce(v: str):
    try:
        return int(v)
    except ValueError:
        pass
    try:
        return float(v)
    except ValueError:
        pass
    if v.lower() in ("true", "false"):
        return v.lower() == "true"
    return v
