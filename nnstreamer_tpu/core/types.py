"""Tensor type system: the ABI every element shares.

TPU-native re-design of the reference's core tensor plumbing
(``gst/nnstreamer/tensor_common.c`` + ``include/tensor_typedef.h``, upstream
nnstreamer — reconstructed per SURVEY.md; reference mount was empty):

* ``GstTensorInfo``  -> :class:`TensorSpec`   (name, dtype, dims)
* ``GstTensorsInfo`` -> :class:`TensorsSpec`  (up to ``TENSOR_COUNT_LIMIT`` specs)
* ``GstTensorsConfig``-> :class:`TensorsSpec` + ``rate`` (framerate fraction)
* ``GstTensorMemory`` -> :class:`~nnstreamer_tpu.core.buffer.TensorChunk`

Differences from the reference, on purpose (TPU-first):

* dtypes are numpy dtypes and include ``bfloat16`` — the native MXU compute
  type — which the reference does not have.
* dims keep nnstreamer's **innermost-first** ("3:224:224:1" = C:W:H:N) string
  syntax for pipeline-string compatibility, but :attr:`TensorSpec.shape` gives
  the numpy/JAX (outermost-first) shape, because XLA wants static row-major
  shapes.
* "flexible" tensors (per-buffer shapes) exist but are bucketed/padded before
  they reach a compiled stage (see pipeline/fusion.py) — XLA recompiles per
  shape, the reference just memcpy'd.
"""

from __future__ import annotations

import dataclasses
import math
import re
from enum import Enum
from typing import Iterable, Optional, Sequence, Tuple, Union

import numpy as np

try:  # ml_dtypes ships with jax; bfloat16 as a numpy extension dtype.
    import ml_dtypes

    bfloat16 = np.dtype(ml_dtypes.bfloat16)
except Exception:  # pragma: no cover - ml_dtypes is bundled with jax
    bfloat16 = np.dtype(np.float32)

#: Maximum rank of a single tensor (reference: NNS_TENSOR_RANK_LIMIT == 16).
TENSOR_RANK_LIMIT = 16
#: Maximum number of tensors in one stream buffer (reference: 16 + "extra").
TENSOR_COUNT_LIMIT = 256


class TensorFormat(str, Enum):
    """Stream-level tensor format (reference: _tensor_format)."""

    STATIC = "static"  # shapes fixed at negotiation time
    FLEXIBLE = "flexible"  # every buffer carries its own spec header
    SPARSE = "sparse"  # COO index+value wire format


# name -> numpy dtype. Reference: tensor_element_typename[] in tensor_common.c.
_DTYPE_NAMES = {
    "int8": np.dtype(np.int8),
    "uint8": np.dtype(np.uint8),
    "int16": np.dtype(np.int16),
    "uint16": np.dtype(np.uint16),
    "int32": np.dtype(np.int32),
    "uint32": np.dtype(np.uint32),
    "int64": np.dtype(np.int64),
    "uint64": np.dtype(np.uint64),
    "float16": np.dtype(np.float16),
    "float32": np.dtype(np.float32),
    "float64": np.dtype(np.float64),
    # TPU-native extension: MXU compute type.
    "bfloat16": bfloat16,
}
_DTYPE_TO_NAME = {v: k for k, v in reversed(_DTYPE_NAMES.items())}


def dtype_from_name(name: str) -> np.dtype:
    """Map a pipeline-string type name to a numpy dtype.

    Accepts nnstreamer names (``uint8`` ... ``float64``) plus ``bfloat16``.
    """
    key = name.strip().lower()
    if key in _DTYPE_NAMES:
        return _DTYPE_NAMES[key]
    # Fall back to anything numpy understands ("f4", "float", ...).
    try:
        dt = np.dtype(key)
    except TypeError as e:
        raise ValueError(f"unknown tensor dtype name: {name!r}") from e
    return dt


def dtype_name(dtype: Union[np.dtype, type, str]) -> str:
    dt = np.dtype(dtype)
    if dt in _DTYPE_TO_NAME:
        return _DTYPE_TO_NAME[dt]
    return dt.name


def parse_dims(text: str) -> Tuple[int, ...]:
    """Parse an nnstreamer dimension string, e.g. ``"3:224:224:1"``.

    Innermost dimension first (reference: gst_tensor_parse_dimension).
    ``0`` or empty trailing components are dropped.  Rank is capped at
    :data:`TENSOR_RANK_LIMIT`.
    """
    parts = [p for p in text.strip().split(":") if p != ""]
    if not parts:
        raise ValueError(f"empty dimension string: {text!r}")
    if len(parts) > TENSOR_RANK_LIMIT:
        raise ValueError(
            f"rank {len(parts)} exceeds TENSOR_RANK_LIMIT={TENSOR_RANK_LIMIT}: {text!r}"
        )
    dims = []
    for p in parts:
        v = int(p)
        if v < 0:
            raise ValueError(f"negative dimension in {text!r}")
        dims.append(v)
    # Drop trailing zeros (unspecified dims in the reference encoding).
    while dims and dims[-1] == 0:
        dims.pop()
    if not dims or any(d == 0 for d in dims):
        raise ValueError(f"invalid (zero) dimension inside {text!r}")
    return tuple(dims)


def dims_to_string(dims: Sequence[int]) -> str:
    return ":".join(str(int(d)) for d in dims)


def dims_equal(a: Sequence[int], b: Sequence[int]) -> bool:
    """Compare dims ignoring trailing 1s (reference: gst_tensor_dimension_is_equal)."""
    la, lb = list(a), list(b)
    while la and la[-1] == 1:
        la.pop()
    while lb and lb[-1] == 1:
        lb.pop()
    return la == lb


@dataclasses.dataclass(frozen=True)
class TensorSpec:
    """Static description of one tensor in a stream (reference: GstTensorInfo).

    ``dims`` is innermost-first (nnstreamer order); :attr:`shape` is the
    outermost-first numpy/JAX shape.
    """

    dims: Tuple[int, ...]
    dtype: np.dtype = np.dtype(np.uint8)
    name: str = ""

    def __post_init__(self):
        object.__setattr__(self, "dims", tuple(int(d) for d in self.dims))
        object.__setattr__(self, "dtype", np.dtype(self.dtype))
        if len(self.dims) > TENSOR_RANK_LIMIT:
            raise ValueError(f"rank>{TENSOR_RANK_LIMIT}: {self.dims}")
        # Zero-size dims are legal for concrete arrays (e.g. an empty token
        # piece in a FLEXIBLE stream); the *string* parse path still rejects
        # 0 because the reference encoding uses it for "unspecified".
        if any(d < 0 for d in self.dims):
            raise ValueError(f"negative dim: {self.dims}")

    # -- constructors ------------------------------------------------------
    @classmethod
    def from_string(cls, dims: str, dtype: str = "uint8", name: str = "") -> "TensorSpec":
        return cls(parse_dims(dims), dtype_from_name(dtype), name)

    @classmethod
    def from_shape(
        cls, shape: Sequence[int], dtype=np.uint8, name: str = ""
    ) -> "TensorSpec":
        """Build from a numpy-order (outermost-first) shape."""
        return cls(tuple(reversed([int(s) for s in shape])), np.dtype(dtype), name)

    @classmethod
    def of(cls, array) -> "TensorSpec":
        """Spec describing a concrete numpy/JAX array."""
        return cls.from_shape(array.shape, np.dtype(array.dtype))

    # -- views -------------------------------------------------------------
    @property
    def shape(self) -> Tuple[int, ...]:
        """Numpy/JAX (outermost-first) shape."""
        return tuple(reversed(self.dims))

    @property
    def rank(self) -> int:
        return len(self.dims)

    @property
    def count(self) -> int:
        return int(math.prod(self.dims)) if self.dims else 0

    @property
    def nbytes(self) -> int:
        return self.count * self.dtype.itemsize

    def with_name(self, name: str) -> "TensorSpec":
        return dataclasses.replace(self, name=name)

    def is_compatible(self, other: "TensorSpec") -> bool:
        return self.dtype == other.dtype and dims_equal(self.dims, other.dims)

    def to_string(self) -> str:
        return f"{dims_to_string(self.dims)},{dtype_name(self.dtype)}"

    def __str__(self) -> str:  # pragma: no cover - repr sugar
        n = f" name={self.name!r}" if self.name else ""
        return f"TensorSpec({dims_to_string(self.dims)} {dtype_name(self.dtype)}{n})"


@dataclasses.dataclass(frozen=True)
class TensorsSpec:
    """Description of all tensors in one stream buffer (GstTensorsInfo/Config).

    ``rate`` is the stream framerate as a (numerator, denominator) fraction;
    (0, 1) means "not applicable / not negotiated".
    """

    specs: Tuple[TensorSpec, ...] = ()
    format: TensorFormat = TensorFormat.STATIC
    rate: Tuple[int, int] = (0, 1)

    def __post_init__(self):
        object.__setattr__(self, "specs", tuple(self.specs))
        object.__setattr__(self, "format", TensorFormat(self.format))
        if len(self.specs) > TENSOR_COUNT_LIMIT:
            raise ValueError(f"too many tensors: {len(self.specs)}")

    # -- constructors ------------------------------------------------------
    @classmethod
    def from_string(
        cls,
        dimensions: str,
        types: str = "",
        names: str = "",
        format: Union[str, TensorFormat] = TensorFormat.STATIC,
        rate: Tuple[int, int] = (0, 1),
    ) -> "TensorsSpec":
        """Parse comma-separated per-tensor ``dimensions``/``types``/``names``.

        Mirrors the reference's ``dimensions=3:224:224,10 types=uint8,float32``
        property syntax on converter/filter elements.
        """
        dim_parts = [d for d in dimensions.split(",") if d.strip()]
        type_parts = [t for t in types.split(",") if t.strip()] if types else []
        name_parts = names.split(",") if names else []
        specs = []
        for i, d in enumerate(dim_parts):
            t = type_parts[i] if i < len(type_parts) else "uint8"
            n = name_parts[i].strip() if i < len(name_parts) else ""
            specs.append(TensorSpec.from_string(d, t, n))
        return cls(tuple(specs), TensorFormat(format), rate)

    @classmethod
    def of(cls, arrays: Iterable, format=TensorFormat.STATIC, rate=(0, 1)) -> "TensorsSpec":
        return cls(tuple(TensorSpec.of(a) for a in arrays), format, rate)

    @classmethod
    def single(cls, spec: TensorSpec, rate=(0, 1)) -> "TensorsSpec":
        return cls((spec,), TensorFormat.STATIC, rate)

    # -- views -------------------------------------------------------------
    def __len__(self) -> int:
        return len(self.specs)

    def __getitem__(self, i: int) -> TensorSpec:
        return self.specs[i]

    def __iter__(self):
        return iter(self.specs)

    @property
    def is_flexible(self) -> bool:
        return self.format == TensorFormat.FLEXIBLE

    @property
    def is_sparse(self) -> bool:
        return self.format == TensorFormat.SPARSE

    @property
    def nbytes(self) -> int:
        return sum(s.nbytes for s in self.specs)

    def is_compatible(self, other: "TensorsSpec") -> bool:
        if self.format != other.format:
            return False
        if self.format != TensorFormat.STATIC:
            return True  # flexible/sparse: per-buffer specs decide
        if len(self.specs) != len(other.specs):
            return False
        return all(a.is_compatible(b) for a, b in zip(self.specs, other.specs))

    def replace(self, **kw) -> "TensorsSpec":
        return dataclasses.replace(self, **kw)

    def to_string(self) -> str:
        dims = ",".join(dims_to_string(s.dims) for s in self.specs)
        types = ",".join(dtype_name(s.dtype) for s in self.specs)
        return f"num={len(self.specs)} dims={dims} types={types} fmt={self.format.value}"

    def __str__(self) -> str:  # pragma: no cover
        return f"TensorsSpec({self.to_string()})"


_FRACTION_RE = re.compile(r"^\s*(\d+)\s*/\s*(\d+)\s*$")


def parse_fraction(text: Union[str, Tuple[int, int]]) -> Tuple[int, int]:
    """Parse a framerate fraction like ``"30/1"`` (GstCaps fraction field)."""
    if isinstance(text, tuple):
        return int(text[0]), int(text[1])
    m = _FRACTION_RE.match(str(text))
    if not m:
        try:
            return int(text), 1
        except ValueError:
            raise ValueError(f"bad fraction: {text!r}") from None
    return int(m.group(1)), int(m.group(2))
