"""Stream buffers: the unit of data flowing between pipeline stages.

Reference analog: ``GstBuffer`` carrying N ``GstMemory`` chunks, one per
tensor, plus pts/duration metadata (``gst/nnstreamer/tensor_common.c``,
upstream-reconstructed — see SURVEY.md).

TPU-first difference: a chunk's payload may be **either** a host numpy array
**or** a ``jax.Array`` already resident in HBM.  Fused device stages pass
device arrays straight through (the zero-copy requirement of the north star —
the reference's CUDA ``cudaMallocManaged`` path in tensor_filter_tensorrt.cc
becomes "stay in HBM between compiled stages").  Host boundaries
(ingest/overlay out) are the only places `device_put`/`device_get` happen.
"""

from __future__ import annotations

import dataclasses
import itertools
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from .types import TensorFormat, TensorSpec, TensorsSpec

_seq = itertools.count()


def _is_device_array(x) -> bool:
    # jax.Array without importing jax at module import time (keeps core light).
    return type(x).__module__.startswith("jax") or hasattr(x, "addressable_shards")


@dataclasses.dataclass
class Buffer:
    """One pipeline buffer: a tuple of tensors + timing + metadata.

    ``tensors`` entries are numpy arrays or jax Arrays.  ``spec`` describes
    them; for FLEXIBLE streams it is derived per-buffer.  ``pts`` is the
    presentation timestamp in nanoseconds (reference: GST_BUFFER_PTS);
    ``meta`` carries cross-element metadata (e.g. the query client id, the
    crop-region info — reference: GstMeta).
    """

    tensors: List[Any]
    spec: Optional[TensorsSpec] = None
    pts: Optional[int] = None
    duration: Optional[int] = None
    seqno: int = dataclasses.field(default_factory=lambda: next(_seq))
    meta: Dict[str, Any] = dataclasses.field(default_factory=dict)

    def __post_init__(self):
        if self.spec is None:
            self.spec = TensorsSpec.of(self.tensors)

    # -- constructors ------------------------------------------------------
    @classmethod
    def of(cls, *arrays, pts: Optional[int] = None, **meta) -> "Buffer":
        return cls(list(arrays), pts=pts, meta=dict(meta))

    # -- views -------------------------------------------------------------
    def __len__(self) -> int:
        return len(self.tensors)

    def __getitem__(self, i: int):
        return self.tensors[i]

    @property
    def nbytes(self) -> int:
        return sum(int(t.nbytes) for t in self.tensors)

    @property
    def on_device(self) -> bool:
        return all(_is_device_array(t) for t in self.tensors)

    # -- transforms --------------------------------------------------------
    def with_tensors(self, tensors: Sequence[Any], spec: Optional[TensorsSpec] = None) -> "Buffer":
        """New buffer with same timing/meta but different payload."""
        return Buffer(
            list(tensors),
            spec=spec,
            pts=self.pts,
            duration=self.duration,
            seqno=self.seqno,
            meta=dict(self.meta),
        )

    def resolve(self) -> "Buffer":
        """Apply a deferred device->media mapping (set by fused stages whose
        tail decoder runs on device and finishes the decode on host)."""
        post = self.meta.get("_host_post")
        if post is None:
            return self
        host = [np.asarray(t) for t in self.tensors]
        base = self.with_tensors(host)
        base.meta.pop("_host_post", None)
        return post(host, base)

    def to_host(self) -> "Buffer":
        if "_host_post" in self.meta:
            return self.resolve()
        arrs = [np.asarray(t) for t in self.tensors]
        return self.with_tensors(arrs)

    def to_device(self, device=None, sharding=None) -> "Buffer":
        import jax

        if sharding is not None:
            arrs = [jax.device_put(t, sharding) for t in self.tensors]
        elif device is not None:
            arrs = [jax.device_put(t, device) for t in self.tensors]
        else:
            arrs = [jax.device_put(t) for t in self.tensors]
        return self.with_tensors(arrs)

    def block_until_ready(self) -> "Buffer":
        for t in self.tensors:
            if hasattr(t, "block_until_ready"):
                t.block_until_ready()
        return self


# -- micro-batch stack/split ----------------------------------------------
#
# The adaptive micro-batching layer (pipeline/batching.py) stacks the
# tensors of several same-spec buffers on a NEW leading axis, runs one
# bucketed XLA dispatch, and splits the result back into per-buffer rows.
# The helpers below are the stack/split primitives: plain jnp ops, so they
# work standalone AND trace cleanly inside the batcher's jitted program
# (payloads stay jax Arrays in HBM end to end — the split rows are lazy
# slices of the batched output, never host copies).


def batch_signature(buf: "Buffer") -> Tuple:
    """Stacking compatibility key: two buffers may join one micro-batch iff
    their signatures match (same tensor count, shapes, dtypes)."""
    return tuple(
        (tuple(t.shape), str(getattr(t, "dtype", type(t)))) for t in buf.tensors
    )


def pad_rows(rows: Sequence[Any], pad_to: int) -> List[Any]:
    """THE bucket-padding policy: repeat the last row until ``pad_to`` —
    valid data, so padded programs need no masking, and the repeats are
    references, not copies (pad rows' outputs are dropped by split_rows).
    Single implementation shared by stack_tensors and BatchRunner."""
    rows = list(rows)
    if pad_to > len(rows):
        rows += [rows[-1]] * (pad_to - len(rows))
    return rows


def stack_tensors(rows: Sequence[Sequence[Any]], pad_to: Optional[int] = None):
    """Stack per-buffer tensor rows on a new leading axis.

    ``rows`` is a list of per-buffer tensor tuples (all same signature);
    returns a tuple of arrays shaped ``[B, ...]``; ``pad_to`` applies
    :func:`pad_rows` first."""
    import jax.numpy as jnp

    rows = pad_rows(rows, pad_to) if pad_to is not None else list(rows)
    k = len(rows[0])
    return tuple(jnp.stack([r[t] for r in rows]) for t in range(k))


def split_rows(arrays: Sequence[Any], n: int) -> List[Tuple]:
    """Inverse of stack_tensors: ``[B, ...]`` arrays -> n per-buffer tensor
    tuples (rows past n — bucket padding — are dropped)."""
    return [tuple(a[i] for a in arrays) for i in range(n)]


@dataclasses.dataclass
class Event:
    """In-band stream event (reference: GstEvent — EOS, segment, caps)."""

    kind: str  # "eos" | "caps" | "segment" | "flush" | "error"
    payload: Any = None

    @classmethod
    def eos(cls) -> "Event":
        return cls("eos")

    @classmethod
    def caps(cls, spec: TensorsSpec) -> "Event":
        return cls("caps", spec)

    @classmethod
    def error(cls, exc: BaseException) -> "Event":
        return cls("error", exc)


def now_ns() -> int:
    return time.monotonic_ns()
