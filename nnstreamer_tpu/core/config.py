"""Global configuration registry.

Reference analog: ``gst/nnstreamer/nnstreamer_conf.c`` + ``nnstreamer.ini``
(sub-plugin search paths, per-framework priority for ``framework=auto``,
env-var overrides NNSTREAMER_CONF/FILTERS/DECODERS/CONVERTERS) —
upstream-reconstructed, SURVEY.md §5.6.

TPU build: one dataclass, populated from (in priority order) explicit set() >
environment > ini file (``NNS_TPU_CONF`` path, default ``~/.nnstreamer_tpu.ini``)
> defaults.  Sub-plugin discovery is module-import based (see registry.py), so
"paths" become module lists.
"""

from __future__ import annotations

import configparser
import dataclasses
import os
import threading
from typing import Dict, List, Optional, Tuple

_ENV_CONF = "NNS_TPU_CONF"
_ENV_PLUGINS = "NNS_TPU_PLUGINS"
_ENV_FW_PRIORITY = "NNS_TPU_FILTER_PRIORITY"
_ENV_BUCKETING = "NNS_TPU_SHAPE_BUCKETING"
_ENV_ADAPTIVE = "NNS_TPU_ADAPTIVE_BUCKETS"
_ENV_LADDERS = "NNS_TPU_BUCKET_LADDERS"
_ENV_BATCH_MAX = "NNS_TPU_BATCH_MAX"
_ENV_DATA_PARALLEL = "NNS_TPU_DATA_PARALLEL"
_ENV_MODEL_PARALLEL = "NNS_TPU_MODEL_PARALLEL"
_ENV_DISPATCH_DEPTH = "NNS_TPU_DISPATCH_DEPTH"
_ENV_HBM_BUDGET = "NNS_TPU_HBM_BUDGET"
_ENV_MAX_VARIANTS = "NNS_TPU_MAX_COMPILED_VARIANTS"
_ENV_TRACE = "NNS_TPU_TRACE"
_ENV_TRACE_RING = "NNS_TPU_TRACE_RING"
_ENV_FETCH_DEPTH = "NNS_TPU_FETCH_DEPTH"
_ENV_DONATE_INGRESS = "NNS_TPU_DONATE_INGRESS"
_ENV_REDUCE_OUTPUTS = "NNS_TPU_REDUCE_OUTPUTS"
_ENV_LINK_D2H_MBPS = "NNS_TPU_LINK_D2H_MBPS"
_ENV_LINK_RTT_MS = "NNS_TPU_LINK_RTT_MS"
_ENV_STAGE_RESTARTS = "NNS_TPU_MAX_STAGE_RESTARTS"
_ENV_XRAY = "NNS_TPU_XRAY"
_ENV_XRAY_HBM_TOL = "NNS_TPU_XRAY_HBM_TOLERANCE"
_ENV_PEAK_TFLOPS = "NNS_TPU_PEAK_TFLOPS"


@dataclasses.dataclass
class Config:
    #: extra plugin modules to import at registry init (comma/colon separated env)
    plugin_modules: List[str] = dataclasses.field(default_factory=list)
    #: framework priority for tensor_filter framework=auto
    filter_priority: List[str] = dataclasses.field(
        default_factory=lambda: ["jax", "custom-easy", "python3"]
    )
    #: default queue capacity between pipeline stages (buffers)
    queue_capacity: int = 4
    #: adaptive micro-batching: max already-queued buffers a device stage
    #: drains into ONE bucketed XLA dispatch (1 = off, the seed semantics)
    batch_max: int = 1
    #: allowed stacked batch sizes (bounds XLA recompiles); empty = powers
    #: of two up to batch_max
    batch_buckets: List[int] = dataclasses.field(default_factory=list)
    #: optional wait (ms) for more buffers once one is in hand; 0 = never
    #: trade latency for occupancy (drain only what is already queued)
    batch_linger_ms: float = 0.0
    #: adaptive bucket ladder (docs/BATCHING.md "Adaptive ladder"): each
    #: batchable stage refines its ladder online from observed drain
    #: occupancies — persistent skew mints an exact bucket instead of
    #: padding to the next power of two — bounded per stage by
    #: ``pipeline/plan.adaptive_variant_budget`` against
    #: ``max_compiled_variants`` so the deep-lint recompile census stays
    #: closed.  False = the static ladder, bit-identical behavior.
    adaptive_buckets: bool = False
    #: warm-start ladders per stage name (the export of a previous run's
    #: ``Pipeline.ladder_snapshot()``): ``{"f": [1, 2, 4, 6, 8]}``.  Ini
    #: ``[ladders]`` section (``f = 1,2,4,6,8``) or env
    #: ``NNS_TPU_BUCKET_LADDERS=f:1|2|4|6|8;g:...``.  Minted sizes
    #: compile at warmup, so steady-state deployments skip the online
    #: learning phase entirely.
    bucket_ladders: Dict[str, List[int]] = dataclasses.field(
        default_factory=dict)
    #: data-parallel replicas a bucketed micro-batch is sharded over (the
    #: ``data`` mesh axis): 0 = all local devices once batch_max > 1,
    #: 1 = single-device dispatch (the pre-mesh behavior), N = exactly N
    #: local devices.  Only shard-eligible stages (see pipeline/plan.py)
    #: ever see the mesh.
    data_parallel: int = 0
    #: tensor-parallel ways over the pipeline mesh's ``model`` axis
    #: (pipeline/plan.mesh_plan): 1 = off (the dp-only legacy path,
    #: bit-identical), N = exactly N ways (shardable stages place params
    #: per their ``param_pspecs``; the llm filter runs TP on the SAME
    #: mesh), 0 = auto — absorb every local device the ``data`` axis
    #: doesn't claim.  Unlike data_parallel this is NOT gated on
    #: batch_max: TP-only pipelines shard weights without micro-batching.
    model_parallel: int = 1
    #: in-flight dispatch window for batching device stages: how many
    #: micro-batches a runner may have dispatched-but-not-yet-emitted, so
    #: the next drain overlaps the previous dispatch (1 = the lockstep
    #: drain->dispatch->emit loop)
    dispatch_depth: int = 2
    #: pad flexible shapes up to the next bucket to bound XLA recompiles
    shape_bucketing: bool = True
    #: async fetch window at sinks (the output-side twin of
    #: ``dispatch_depth``): how many buffers a tensor_sink may have in
    #: background D2H / host-post resolution at once, so the fetch of
    #: buffer N overlaps the dispatch of buffer N+1 instead of being paid
    #: inside pop().  1 = the serial resolver — see docs/FETCH.md.
    fetch_depth: int = 2
    #: donate host-fed ingress buffers to the fused program (appsrc et al:
    #: the stage device_puts the pushed frame and XLA reuses that HBM for
    #: outputs — steady-state H2D stops allocating).  Only applies where
    #: the planner can prove sole ownership; see docs/FETCH.md.
    donate_ingress: bool = True
    #: HBM-residency planner: let the planner auto-select a model's
    #: REDUCED output (e.g. deeplab's native-stride class map, 256x less
    #: D2H) when every downstream consumer's negotiated caps admit it —
    #: "fetch the smaller thing" becomes the default instead of a
    #: hand-tuned custom= option.  See docs/FETCH.md "Residency rules".
    reduce_outputs: bool = True
    #: calibrated D2H link bandwidth in MB/s (the bench ``link_calibration``
    #: row) — lets nns-lint --deep price each sink edge's planned fetch
    #: bytes in milliseconds and flag ``fetch-bound`` pipelines statically.
    #: 0 = uncalibrated: fetch bytes are still reported, never priced.
    link_d2h_mbps: float = 0.0
    #: calibrated small-fetch roundtrip (ms), recorded next to the
    #: bandwidth term in the deep pass's fetch report.  Deliberately NOT
    #: part of the ``fetch-bound`` decision: the RTT amortizes behind the
    #: async fetch window (the point of ``fetch_depth``), link occupancy
    #: cannot — see docs/FETCH.md "Static fetch pricing".
    link_fetch_rtt_ms: float = 0.0
    #: static-analysis budget (nns-lint --deep): estimated per-device HBM
    #: high-water mark in bytes a pipeline may plan for before the deep
    #: pass warns (0 = no budget).  The estimate multiplies per-stage
    #: param + abstract activation bytes over the bucket ladder,
    #: data_parallel replication, and the dispatch_depth in-flight window
    #: — see docs/ANALYSIS.md "Deep pass".
    hbm_budget_bytes: int = 0
    #: static-analysis budget (nns-lint --deep): max distinct compiled XLA
    #: signatures (buckets x spec variants across device stages) before
    #: the deep pass warns of a recompile storm (0 = no budget)
    max_compiled_variants: int = 0
    #: elastic stage restarts (docs/SERVING.md "Elastic serving"): how
    #: many times a PURE/STATELESS stage's runner thread may be
    #: restarted in place after an exception before the pipeline fails
    #: for real (with the flight-recorder ring dumped).  0 = off (the
    #: pre-elastic fail-fast behavior); restarts are counted in
    #: ``<stage>.restarts``.
    max_stage_restarts: int = 0
    #: flight-recorder trace mode (utils/tracing.py, docs/OBSERVABILITY.md):
    #: ``off`` = no recorder installed (hot paths pay one pointer check),
    #: ``ring`` = always-on bounded ring of span events (post-mortem mode;
    #: watchdog fires / pipeline errors dump the recent window),
    #: ``full`` = unbounded capture for short profiling runs
    trace_mode: str = "off"
    #: span capacity of the ``ring`` trace mode
    trace_ring_capacity: int = 65536
    #: nns-xray predicted-vs-actual reconciliation (utils/xray.py,
    #: docs/OBSERVABILITY.md "Predicted vs actual"): register every jit
    #: entry point's compiles with the live program census, attribute
    #: per-stage device time / MFU, and reconcile the HBM ledger against
    #: the deep-lint estimate.  False = structurally off — every hook is
    #: one pointer check, no meta, no cost_analysis calls.
    xray: bool = False
    #: HBM-ledger drift tolerance: a category whose measured bytes drift
    #: past this factor from the deep-lint estimate (either direction,
    #: above the 1 MiB noise floor) warns once
    xray_hbm_tolerance: float = 2.0
    #: peak dense-matmul TFLOPs per chip for the MFU gauges (0 = derive
    #: from the device kind; utils/xray.peak_flops)
    peak_tflops: float = 0.0
    #: emit per-stage latency measurements
    enable_latency: bool = True
    #: free-form per-framework options ([filter-jax] section of the ini)
    framework_options: Dict[str, Dict[str, str]] = dataclasses.field(default_factory=dict)

    @classmethod
    def load(cls) -> "Config":
        cfg = cls()
        path = os.environ.get(_ENV_CONF, os.path.expanduser("~/.nnstreamer_tpu.ini"))
        if path and os.path.exists(path):
            ini = configparser.ConfigParser()
            ini.read(path)
            if ini.has_option("common", "plugin_modules"):
                cfg.plugin_modules = _split(ini.get("common", "plugin_modules"))
            if ini.has_option("filter", "priority"):
                cfg.filter_priority = _split(ini.get("filter", "priority"))
            if ini.has_option("common", "queue_capacity"):
                cfg.queue_capacity = ini.getint("common", "queue_capacity")
            if ini.has_option("common", "batch_max"):
                cfg.batch_max = ini.getint("common", "batch_max")
            if ini.has_option("common", "batch_buckets"):
                cfg.batch_buckets = [
                    int(v) for v in _split(ini.get("common", "batch_buckets"))
                ]
            if ini.has_option("common", "batch_linger_ms"):
                cfg.batch_linger_ms = ini.getfloat("common",
                                                   "batch_linger_ms")
            if ini.has_option("common", "adaptive_buckets"):
                cfg.adaptive_buckets = ini.getboolean("common",
                                                      "adaptive_buckets")
            if ini.has_section("ladders"):
                # case-preserving re-read: configparser lowercases option
                # keys by default, but stage names are case-sensitive
                # (ladder_snapshot() exports them verbatim) — a lowercased
                # key would silently miss the warm-start lookup
                cased = configparser.ConfigParser()
                cased.optionxform = str
                cased.read(path)
                cfg.bucket_ladders = {
                    stage: [int(v) for v in _split(sizes)]
                    for stage, sizes in cased.items("ladders")
                }
            if ini.has_option("common", "data_parallel"):
                cfg.data_parallel = ini.getint("common", "data_parallel")
            if ini.has_option("common", "model_parallel"):
                cfg.model_parallel = ini.getint("common", "model_parallel")
            if ini.has_option("common", "dispatch_depth"):
                cfg.dispatch_depth = ini.getint("common", "dispatch_depth")
            if ini.has_option("common", "shape_bucketing"):
                cfg.shape_bucketing = ini.getboolean("common",
                                                     "shape_bucketing")
            if ini.has_option("common", "hbm_budget_bytes"):
                cfg.hbm_budget_bytes = ini.getint("common",
                                                  "hbm_budget_bytes")
            if ini.has_option("common", "max_compiled_variants"):
                cfg.max_compiled_variants = ini.getint(
                    "common", "max_compiled_variants")
            if ini.has_option("common", "fetch_depth"):
                cfg.fetch_depth = ini.getint("common", "fetch_depth")
            if ini.has_option("common", "donate_ingress"):
                cfg.donate_ingress = ini.getboolean("common",
                                                    "donate_ingress")
            if ini.has_option("common", "reduce_outputs"):
                cfg.reduce_outputs = ini.getboolean("common",
                                                    "reduce_outputs")
            if ini.has_option("common", "link_d2h_mbps"):
                cfg.link_d2h_mbps = ini.getfloat("common", "link_d2h_mbps")
            if ini.has_option("common", "link_fetch_rtt_ms"):
                cfg.link_fetch_rtt_ms = ini.getfloat(
                    "common", "link_fetch_rtt_ms")
            if ini.has_option("common", "max_stage_restarts"):
                cfg.max_stage_restarts = ini.getint(
                    "common", "max_stage_restarts")
            if ini.has_option("common", "trace_mode"):
                cfg.trace_mode = ini.get("common",
                                         "trace_mode").strip().lower()
            if ini.has_option("common", "trace_ring_capacity"):
                cfg.trace_ring_capacity = ini.getint(
                    "common", "trace_ring_capacity")
            if ini.has_option("common", "xray"):
                cfg.xray = ini.getboolean("common", "xray")
            if ini.has_option("common", "xray_hbm_tolerance"):
                cfg.xray_hbm_tolerance = ini.getfloat(
                    "common", "xray_hbm_tolerance")
            if ini.has_option("common", "peak_tflops"):
                cfg.peak_tflops = ini.getfloat("common", "peak_tflops")
            for sec in ini.sections():
                if sec.startswith("filter-"):
                    cfg.framework_options[sec[len("filter-"):]] = dict(ini.items(sec))
        if os.environ.get(_ENV_PLUGINS):
            cfg.plugin_modules = _split(os.environ[_ENV_PLUGINS])
        if os.environ.get(_ENV_FW_PRIORITY):
            cfg.filter_priority = _split(os.environ[_ENV_FW_PRIORITY])
        if os.environ.get(_ENV_BATCH_MAX):
            cfg.batch_max = int(os.environ[_ENV_BATCH_MAX])
        if os.environ.get(_ENV_DATA_PARALLEL):
            cfg.data_parallel = int(os.environ[_ENV_DATA_PARALLEL])
        if os.environ.get(_ENV_MODEL_PARALLEL):
            cfg.model_parallel = int(os.environ[_ENV_MODEL_PARALLEL])
        if os.environ.get(_ENV_DISPATCH_DEPTH):
            cfg.dispatch_depth = int(os.environ[_ENV_DISPATCH_DEPTH])
        if os.environ.get(_ENV_HBM_BUDGET):
            cfg.hbm_budget_bytes = int(os.environ[_ENV_HBM_BUDGET])
        if os.environ.get(_ENV_MAX_VARIANTS):
            cfg.max_compiled_variants = int(os.environ[_ENV_MAX_VARIANTS])
        if os.environ.get(_ENV_FETCH_DEPTH):
            cfg.fetch_depth = int(os.environ[_ENV_FETCH_DEPTH])
        if os.environ.get(_ENV_DONATE_INGRESS):
            cfg.donate_ingress = os.environ[_ENV_DONATE_INGRESS].lower() in (
                "1", "true", "yes", "on")
        if os.environ.get(_ENV_REDUCE_OUTPUTS):
            cfg.reduce_outputs = os.environ[_ENV_REDUCE_OUTPUTS].lower() in (
                "1", "true", "yes", "on")
        if os.environ.get(_ENV_LINK_D2H_MBPS):
            cfg.link_d2h_mbps = float(os.environ[_ENV_LINK_D2H_MBPS])
        if os.environ.get(_ENV_LINK_RTT_MS):
            cfg.link_fetch_rtt_ms = float(os.environ[_ENV_LINK_RTT_MS])
        if os.environ.get(_ENV_STAGE_RESTARTS):
            cfg.max_stage_restarts = int(os.environ[_ENV_STAGE_RESTARTS])
        if os.environ.get(_ENV_XRAY):
            cfg.xray = os.environ[_ENV_XRAY].lower() in (
                "1", "true", "yes", "on")
        if os.environ.get(_ENV_XRAY_HBM_TOL):
            cfg.xray_hbm_tolerance = float(os.environ[_ENV_XRAY_HBM_TOL])
        if os.environ.get(_ENV_PEAK_TFLOPS):
            cfg.peak_tflops = float(os.environ[_ENV_PEAK_TFLOPS])
        if os.environ.get(_ENV_TRACE):
            cfg.trace_mode = os.environ[_ENV_TRACE].strip().lower()
        if os.environ.get(_ENV_TRACE_RING):
            cfg.trace_ring_capacity = int(os.environ[_ENV_TRACE_RING])
        if os.environ.get(_ENV_BUCKETING):
            cfg.shape_bucketing = os.environ[_ENV_BUCKETING].lower() in (
                "1", "true", "yes", "on")
        if os.environ.get(_ENV_ADAPTIVE):
            cfg.adaptive_buckets = os.environ[_ENV_ADAPTIVE].lower() in (
                "1", "true", "yes", "on")
        if os.environ.get(_ENV_LADDERS):
            cfg.bucket_ladders = parse_ladders(os.environ[_ENV_LADDERS])
        return cfg


def parse_ladders(s: str) -> Dict[str, List[int]]:
    """``"f:1|2|4|6;g:1|2|8"`` -> ``{"f": [1,2,4,6], "g": [1,2,8]}`` (the
    env encoding of a ladder snapshot; ':' splits stage from sizes, '|'
    splits sizes — both survive shells unquoted)."""
    out: Dict[str, List[int]] = {}
    for part in s.split(";"):
        part = part.strip()
        if not part:
            continue
        stage, _, sizes = part.partition(":")
        out[stage.strip()] = [int(v) for v in sizes.split("|") if v.strip()]
    return out


def _split(s: str) -> List[str]:
    out = []
    for part in s.replace(":", ",").split(","):
        part = part.strip()
        if part:
            out.append(part)
    return out


_config: Optional[Config] = None
_lock = threading.Lock()


def get_config() -> Config:
    global _config
    if _config is None:
        with _lock:
            if _config is None:
                _config = Config.load()
    return _config


def set_config(cfg: Config) -> None:
    global _config
    with _lock:
        _config = cfg


def reset_config() -> None:
    global _config
    with _lock:
        _config = None
