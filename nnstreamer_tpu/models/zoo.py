"""Model zoo: named JAX models loadable by ``tensor_filter framework=jax``.

Reference analog: the reference loads vendor model *files* (.tflite/.pb/
.onnx) through per-SDK sub-plugins (SURVEY §2.4).  Here a "model" is a pure
JAX program: ``ModelBundle(apply_fn, params, in_spec, out_spec)``.  The zoo
maps pipeline-string names (``model=mobilenet_v1``) to builder functions;
foreign checkpoints enter by converting weights into these bundles (utils/
import_torch.py), and arbitrary user models enter via ``module.path:attr``
import strings or by passing a bundle object programmatically.

Builders take an options dict (the filter's ``custom=`` string, parsed) so
pipelines can pick variants: ``custom=width:0.5,classes:10``.
"""

from __future__ import annotations

import dataclasses
import importlib
import threading
from typing import Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..core.types import TensorsSpec


@dataclasses.dataclass
class ModelBundle:
    """A runnable model: pure apply + pytree of params + IO specs."""

    apply_fn: Callable  # (params, *inputs) -> output | tuple(outputs)
    params: object
    in_spec: Optional[TensorsSpec]
    out_spec: Optional[TensorsSpec]
    #: optional per-model sharding hints: pytree matching params of
    #: jax.sharding.PartitionSpec, used by the parallel runner
    param_pspecs: object = None
    name: str = "model"
    #: optional text tokenizer carried by the checkpoint itself (GGUF
    #: tokenizer.ggml.* vocab -> models/tokenizer.py); the llm framework
    #: uses it in place of its byte-level fallback
    tokenizer: object = None
    #: optional REDUCED output variant for the HBM-residency planner
    #: (pipeline/residency.py, docs/FETCH.md): a thunk returning a bundle
    #: that shares THIS bundle's params (read at call time, so device
    #: placement/replication survives) but emits a smaller output — e.g.
    #: deeplab's native-stride score map vs the full-res bilinear blow-up.
    #: The planner selects it only when every downstream consumer admits
    #: arbitrary tensor geometry.  None = no reduced form exists, or the
    #: caller pinned the output explicitly.
    reduced_variant: Optional[Callable[[], "ModelBundle"]] = None
    #: human description of the reduced variant (logged when selected)
    reduced_desc: str = ""


_builders: Dict[str, Callable[[Dict[str, str]], ModelBundle]] = {}
_lock = threading.Lock()


def register_model(name: str, builder=None):
    """``@register_model("mobilenet_v1")`` on a builder(opts)->ModelBundle."""

    def do(b):
        with _lock:
            _builders[name] = b
        return b

    return do(builder) if builder is not None else do


def model_names() -> List[str]:
    _ensure_builtin()
    with _lock:
        return sorted(_builders)


_builtin_loaded = False


def _ensure_builtin():
    global _builtin_loaded
    if _builtin_loaded:
        return
    _builtin_loaded = True
    for mod in (
        "nnstreamer_tpu.models.testmodels",
        "nnstreamer_tpu.models.mobilenet",
        "nnstreamer_tpu.models.ssd",
        "nnstreamer_tpu.models.yolo",
        "nnstreamer_tpu.models.posenet",
        "nnstreamer_tpu.models.segment",
        "nnstreamer_tpu.models.audio",
        "nnstreamer_tpu.models.llama",
    ):
        try:
            importlib.import_module(mod)
        except ImportError:
            pass


def build(name: str, opts: Optional[Dict[str, str]] = None) -> ModelBundle:
    """Resolve a model name to a bundle.

    Accepts zoo names, ``pkg.mod:attr`` import strings (attr may be a bundle
    or a builder), or a ModelBundle instance.
    """
    if isinstance(name, ModelBundle):
        return name
    _ensure_builtin()
    opts = dict(opts or {})
    key = str(name)
    with _lock:
        b = _builders.get(key)
    if b is not None:
        return b(opts)
    # Model FILES (the reference's default tensor_filter path: model=<file>).
    import os

    is_ckpt_dir = os.path.isdir(key) and (
        os.path.exists(os.path.join(key, "model.safetensors.index.json"))
        or os.path.exists(os.path.join(key, "model.safetensors")))
    if key.endswith((".tflite", ".onnx", ".safetensors", ".npz", ".gguf",
                     ".safetensors.index.json")) or is_ckpt_dir:
        if not os.path.exists(key):
            raise KeyError(f"model file not found: {key}")
        if key.endswith(".tflite"):
            from . import tflite

            return tflite.load_bundle(key, opts)
        if key.endswith(".onnx"):
            from . import onnx

            return onnx.load_bundle(key, opts)
        from . import llama

        return llama.build_from_checkpoint(key, opts)
    if ":" in key:
        mod_name, attr = key.split(":", 1)
        mod = importlib.import_module(mod_name)
        obj = getattr(mod, attr)
        if isinstance(obj, ModelBundle):
            return obj
        if callable(obj):
            out = obj(opts)
            if isinstance(out, ModelBundle):
                return out
    raise KeyError(f"unknown model {name!r}; zoo has {model_names()}")
