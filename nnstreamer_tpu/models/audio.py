"""Audio models — benchmark config #4 (speech-command / wav2vec2 stream).

Reference analog: the reference's audio pipelines feed ``audioconvert ->
tensor_converter -> tensor_filter`` with a speech-commands tflite model
(BASELINE config #4).  Two models here:

* ``speech_commands`` — conv keyword spotter.  The feature frontend (framed
  DFT magnitude -> log-mel-ish filterbank) is INSIDE the model as two
  matmuls (frames @ DFT basis, power @ mel weights): spectrograms become
  MXU work and fuse with the conv stack in one XLA program, instead of the
  reference's host-side feature pipeline.
* ``wav2vec2`` — strided conv feature encoder + bidirectional transformer
  encoder blocks (pre-LN, GELU) on top, CTC-style vocab head; the
  long-sequence path that exercises attention on audio streams.

Inputs: float32 waveform (B, samples) in [-1, 1] @16kHz.
"""

from __future__ import annotations

import functools
from typing import Dict, Tuple

import numpy as np

from ..core.types import TensorSpec, TensorsSpec
from .zoo import ModelBundle, register_model

SAMPLE_RATE = 16000
_SPEECH_LABELS = ("silence", "unknown", "yes", "no", "up", "down", "left",
                  "right", "on", "off", "stop", "go")


def _dft_basis(frame: int, bins: int) -> Tuple[np.ndarray, np.ndarray]:
    """Real-DFT cos/sin bases (frame, bins) with a Hann window folded in."""
    n = np.arange(frame, dtype=np.float32)
    k = np.arange(bins, dtype=np.float32)
    ang = 2.0 * np.pi * np.outer(n, k) / frame
    win = (0.5 - 0.5 * np.cos(2.0 * np.pi * n / frame))[:, None]
    return (np.cos(ang) * win).astype(np.float32), \
        (np.sin(ang) * win).astype(np.float32)


def _canon_wave(x, min_samples: int):
    """Canonicalize waveform input to (B, S).  A trailing dim of 1 is a
    mono channel axis (converter layout), not a batch of 1-sample clips."""
    if x.ndim >= 2 and x.shape[-1] == 1:
        x = x[..., 0]
    if x.ndim == 1:
        x = x[None, :]
    if x.ndim != 2:
        raise ValueError(f"waveform must be (S,), (S,1), (B,S); got {x.shape}")
    if x.shape[1] < min_samples:
        raise ValueError(
            f"waveform too short: {x.shape[1]} < {min_samples} samples")
    return x


def _mel_weights(bins: int, mels: int, sr: int, frame: int) -> np.ndarray:
    """Triangular mel filterbank (bins, mels)."""
    def hz_to_mel(f):
        return 2595.0 * np.log10(1.0 + f / 700.0)

    def mel_to_hz(m):
        return 700.0 * (10.0 ** (m / 2595.0) - 1.0)

    f_max = sr / 2.0
    pts = mel_to_hz(np.linspace(hz_to_mel(20.0), hz_to_mel(f_max), mels + 2))
    bin_hz = np.linspace(0.0, f_max, bins)
    w = np.zeros((bins, mels), np.float32)
    for m in range(mels):
        lo, ctr, hi = pts[m], pts[m + 1], pts[m + 2]
        up = (bin_hz - lo) / max(ctr - lo, 1e-6)
        down = (hi - bin_hz) / max(hi - ctr, 1e-6)
        w[:, m] = np.maximum(0.0, np.minimum(up, down))
    return w


# -- speech_commands ------------------------------------------------------

def init_params_kws(classes: int = len(_SPEECH_LABELS), mels: int = 64,
                    seed: int = 0) -> Dict:
    import jax

    keys = iter(jax.random.split(jax.random.PRNGKey(seed), 16))

    def conv(kh, kw, cin, cout):
        w = jax.random.normal(next(keys), (kh, kw, cin, cout), np.float32)
        return w * np.sqrt(2.0 / (kh * kw * cin))

    def dense(cin, cout):
        w = jax.random.normal(next(keys), (cin, cout), np.float32)
        return w * np.sqrt(2.0 / cin)

    return {
        "c1": {"w": conv(3, 3, 1, 64), "b": np.zeros((64,), np.float32)},
        "c2": {"w": conv(3, 3, 64, 64), "b": np.zeros((64,), np.float32)},
        "c3": {"w": conv(3, 3, 64, 128), "b": np.zeros((128,), np.float32)},
        "fc": {"w": dense(128, classes), "b": np.zeros((classes,), np.float32)},
    }


def apply_kws(params, x, *, frame: int, hop: int, bins: int, mels: int,
              compute_dtype="bfloat16"):
    """waveform -> logits (B, classes).  Accepts (S,), (S, 1) mono audio
    (the converter's frames×channels layout), (B, S), or (B, S, 1)."""
    import jax.numpy as jnp
    from jax import lax

    cdt = jnp.dtype(compute_dtype)
    x = _canon_wave(x, frame)
    B, S = x.shape
    n_frames = 1 + (S - frame) // hop
    cos_b, sin_b = _dft_basis(frame, bins)
    mel_w = _mel_weights(bins, mels, SAMPLE_RATE, frame)

    # Frame via gather of static indices, then two matmuls on the MXU.
    idx = (np.arange(n_frames)[:, None] * hop + np.arange(frame)[None, :])
    frames = x[:, idx]  # (B, T, frame)
    frames = frames.astype(cdt)
    re = frames @ jnp.asarray(cos_b, cdt)
    im = frames @ jnp.asarray(sin_b, cdt)
    power = re * re + im * im  # (B, T, bins)
    mel = power @ jnp.asarray(mel_w, cdt)
    feats = jnp.log(mel.astype(jnp.float32) + 1e-6).astype(cdt)
    h = feats[..., None]  # (B, T, mels, 1)

    def conv2d(h, w, stride):
        return lax.conv_general_dilated(
            h, w.astype(cdt), (stride, stride), "SAME",
            dimension_numbers=("NHWC", "HWIO", "NHWC"))

    h = jnp.maximum(conv2d(h, params["c1"]["w"], 2)
                    + params["c1"]["b"].astype(cdt), 0.0)
    h = jnp.maximum(conv2d(h, params["c2"]["w"], 2)
                    + params["c2"]["b"].astype(cdt), 0.0)
    h = jnp.maximum(conv2d(h, params["c3"]["w"], 2)
                    + params["c3"]["b"].astype(cdt), 0.0)
    h = jnp.mean(h, axis=(1, 2))  # (B, 128)
    logits = h @ params["fc"]["w"].astype(cdt) + params["fc"]["b"].astype(cdt)
    return logits.astype(jnp.float32)


@register_model("speech_commands")
def _speech_commands(opts: Dict[str, str]) -> ModelBundle:
    classes = int(opts.get("classes", len(_SPEECH_LABELS)))
    seed = int(opts.get("seed", 0))
    samples = int(opts.get("samples", SAMPLE_RATE))  # 1s window
    batch = int(opts.get("batch", 1))
    mels = int(opts.get("mels", 64))
    dtype = opts.get("dtype", "bfloat16")

    params = init_params_kws(classes=classes, mels=mels, seed=seed)
    apply_fn = functools.partial(
        apply_kws, frame=640, hop=320, bins=256, mels=mels,
        compute_dtype=dtype)
    return ModelBundle(
        apply_fn=apply_fn,
        params=params,
        in_spec=TensorsSpec.from_string(f"{samples}:{batch}", "float32"),
        out_spec=TensorsSpec.from_string(f"{classes}:{batch}", "float32"),
        param_pspecs=None,
        name="speech_commands",
    )


# -- wav2vec2-style encoder ------------------------------------------------

# Strided conv feature encoder: (kernel, stride, channels).
_W2V_CONVS: Tuple[Tuple[int, int, int], ...] = (
    (10, 5, 256), (3, 2, 256), (3, 2, 256), (3, 2, 256), (2, 2, 256),
)


def init_params_w2v(dim: int = 256, n_layers: int = 4, n_heads: int = 4,
                    ffn: int = 512, vocab: int = 32, seed: int = 0) -> Dict:
    import jax

    keys = iter(jax.random.split(jax.random.PRNGKey(seed), 64))

    def dense(cin, cout):
        w = jax.random.normal(next(keys), (cin, cout), np.float32)
        return w * np.sqrt(2.0 / cin)

    convs = []
    cin = 1
    for (k, _s, ch) in _W2V_CONVS:
        w = jax.random.normal(next(keys), (k, cin, ch), np.float32)
        convs.append({"w": w * np.sqrt(2.0 / (k * cin)),
                      "b": np.zeros((ch,), np.float32)})
        cin = ch
    L = n_layers
    ks = iter(jax.random.split(next(keys), 8))

    def stack(shape, fan_in):
        return (jax.random.normal(next(ks), (L,) + shape, np.float32)
                * np.sqrt(2.0 / fan_in))

    layers = {
        "wq": stack((dim, dim), dim), "wk": stack((dim, dim), dim),
        "wv": stack((dim, dim), dim), "wo": stack((dim, dim), dim),
        "w1": stack((dim, ffn), dim), "w2": stack((ffn, dim), ffn),
        "ln1": np.ones((L, dim), np.float32),
        "ln1b": np.zeros((L, dim), np.float32),
        "ln2": np.ones((L, dim), np.float32),
        "ln2b": np.zeros((L, dim), np.float32),
    }
    return {
        "convs": convs,
        "proj": {"w": dense(cin, dim), "b": np.zeros((dim,), np.float32)},
        "layers": layers,
        "head": {"w": dense(dim, vocab), "b": np.zeros((vocab,), np.float32)},
    }


def apply_w2v(params, x, *, n_heads: int, compute_dtype="bfloat16"):
    """waveform -> frame logits (B, T, vocab) (CTC-style); input layouts
    as :func:`_canon_wave`."""
    import jax
    import jax.numpy as jnp
    from jax import lax

    cdt = jnp.dtype(compute_dtype)
    x = _canon_wave(x, _W2V_CONVS[0][0])
    h = x.astype(cdt)[:, :, None]  # (B, S, 1) NWC

    for cp, (k, s, _ch) in zip(params["convs"], _W2V_CONVS):
        h = lax.conv_general_dilated(
            h, cp["w"].astype(cdt), (s,), "VALID",
            dimension_numbers=("NWC", "WIO", "NWC"))
        h = jax.nn.gelu(h + cp["b"].astype(cdt))
    h = h @ params["proj"]["w"].astype(cdt) + params["proj"]["b"].astype(cdt)

    B, T, D = h.shape
    hd = D // n_heads

    def layer_norm(v, g, b):
        v32 = v.astype(jnp.float32)
        mu = jnp.mean(v32, axis=-1, keepdims=True)
        var = jnp.var(v32, axis=-1, keepdims=True)
        out = (v32 - mu) / jnp.sqrt(var + 1e-5)
        return (out.astype(cdt) * g.astype(cdt) + b.astype(cdt))

    def body(h, lp):
        v = layer_norm(h, lp["ln1"], lp["ln1b"])
        q = (v @ lp["wq"].astype(cdt)).reshape(B, T, n_heads, hd)
        k = (v @ lp["wk"].astype(cdt)).reshape(B, T, n_heads, hd)
        vv = (v @ lp["wv"].astype(cdt)).reshape(B, T, n_heads, hd)
        s = jnp.einsum("bqhd,bkhd->bhqk", q, k,
                       preferred_element_type=jnp.float32)
        p = jax.nn.softmax(s * (1.0 / np.sqrt(hd)), axis=-1)
        attn = jnp.einsum("bhqk,bkhd->bqhd", p.astype(cdt), vv)
        h = h + attn.reshape(B, T, D) @ lp["wo"].astype(cdt)
        v = layer_norm(h, lp["ln2"], lp["ln2b"])
        h = h + jax.nn.gelu(v @ lp["w1"].astype(cdt)) @ lp["w2"].astype(cdt)
        return h, None

    h, _ = lax.scan(body, h, params["layers"])
    logits = h @ params["head"]["w"].astype(cdt) + params["head"]["b"].astype(cdt)
    return logits.astype(jnp.float32)


@register_model("wav2vec2")
def _wav2vec2(opts: Dict[str, str]) -> ModelBundle:
    dim = int(opts.get("dim", 256))
    n_layers = int(opts.get("n_layers", 4))
    n_heads = int(opts.get("n_heads", 4))
    vocab = int(opts.get("vocab", 32))
    seed = int(opts.get("seed", 0))
    batch = int(opts.get("batch", 1))
    samples = int(opts.get("samples", SAMPLE_RATE))
    dtype = opts.get("dtype", "bfloat16")

    params = init_params_w2v(dim=dim, n_layers=n_layers, n_heads=n_heads,
                             vocab=vocab, seed=seed)
    apply_fn = functools.partial(apply_w2v, n_heads=n_heads,
                                 compute_dtype=dtype)
    # Static [B, T, vocab] out spec via shape-only tracing (T falls out of
    # the conv encoder strides; no compile, no FLOPs).  A static spec keeps
    # the whole chain fusable, so a downstream ctc decoder's device argmax
    # joins the same XLA program and only [B, T] ids cross D2H.
    import jax
    import jax.numpy as jnp

    out = jax.eval_shape(apply_fn, params,
                         jax.ShapeDtypeStruct((batch, samples), jnp.float32))
    return ModelBundle(
        apply_fn=apply_fn,
        params=params,
        in_spec=TensorsSpec.from_string(f"{samples}:{batch}", "float32"),
        out_spec=TensorsSpec((TensorSpec.from_shape(out.shape, out.dtype),)),
        param_pspecs=None,
        name="wav2vec2",
    )
