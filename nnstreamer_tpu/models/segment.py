"""DeepLab-style semantic segmentation — pairs with the image_segment
decoder (SURVEY §2.5 ``tensordec-imagesegment.c``; the reference's stock
segmentation example runs deeplabv3_257_mv_gpu.tflite through it).

TPU-first shape: MobileNet separable backbone at output-stride 16 (shared
blocks from models/backbone.py), an ASPP-lite context head (1x1 + global
pooling branch — the deeplab recipe minus the dilated pyramid, which XLA
fuses poorly at tiny feature maps), and a bilinear upsample back to input
resolution INSIDE the jitted program, so the fused pipeline hands the
decoder a full-resolution [B, H, W, classes] score map and the decoder's
device argmax shrinks D2H to one byte-ish id per pixel.
"""

from __future__ import annotations

import functools
from typing import Dict, Tuple

import numpy as np

from ..core.types import TensorsSpec
from .backbone import (he_conv, make_ops, rounded, sep_block_params,
                       sep_block_pspecs, stem_params, stem_pspecs)
from .zoo import ModelBundle, register_model

_BACKBONE: Tuple[Tuple[int, int], ...] = (
    (1, 64), (2, 128), (1, 128), (2, 256), (1, 256), (2, 512),
    (1, 512), (1, 512),
)
CLASSES = 21  # PASCAL-VOC, the reference example's label set


def init_params(width: float = 1.0, classes: int = CLASSES,
                seed: int = 0) -> Dict:
    import jax

    keys = iter(jax.random.split(jax.random.PRNGKey(seed), 64))
    params: Dict = {"stem": stem_params(keys, 3, rounded(32, width))}
    cin = rounded(32, width)
    for i, (_s, ch) in enumerate(_BACKBONE):
        cout = rounded(ch, width)
        params[f"block{i}"] = sep_block_params(keys, cin, cout)
        cin = cout
    mid = rounded(256, width)
    params["aspp_conv"] = {"w": he_conv(next(keys), 1, 1, cin, mid),
                           "bias": np.zeros((mid,), np.float32)}
    params["aspp_pool"] = {"w": he_conv(next(keys), 1, 1, cin, mid),
                           "bias": np.zeros((mid,), np.float32)}
    params["head"] = {"w": he_conv(next(keys), 1, 1, 2 * mid, classes),
                      "bias": np.zeros((classes,), np.float32)}
    return params


def param_pspecs() -> Dict:
    from jax.sharding import PartitionSpec as P

    specs: Dict = {"stem": stem_pspecs()}
    for i in range(len(_BACKBONE)):
        specs[f"block{i}"] = sep_block_pspecs()
    for head in ("aspp_conv", "aspp_pool", "head"):
        specs[head] = {"w": P(), "bias": P()}
    return specs


def apply(params, x, *, compute_dtype="bfloat16", upsample: bool = True):
    """[B, H, W, 3] -> [B, H, W, classes] float32 score map (or the
    native-stride [B, H/16, W/16, classes] map with ``upsample=False`` —
    the class DECISION at the model's true resolution; the full-res map
    is only a bilinear blow-up of it, so consumers that ship maps over a
    link can upsample after transport instead of before)."""
    import jax
    import jax.numpy as jnp

    cdt = jnp.dtype(compute_dtype)
    B, H, W = x.shape[0], x.shape[1], x.shape[2]
    x = x.astype(cdt)
    conv2d, sbr, sep = make_ops(cdt)

    p = params["stem"]
    x = sbr(conv2d(x, p["w"], 2), p["scale"], p["bias"])
    for i, (stride, _ch) in enumerate(_BACKBONE):
        x = sep(x, params[f"block{i}"], stride)

    # ASPP-lite: local 1x1 branch + image-level pooling branch
    a = params["aspp_conv"]
    local = jax.nn.relu(conv2d(x, a["w"], 1) + a["bias"].astype(cdt))
    g = params["aspp_pool"]
    pooled = jnp.mean(x, axis=(1, 2), keepdims=True)
    pooled = jax.nn.relu(conv2d(pooled, g["w"], 1) + g["bias"].astype(cdt))
    pooled = jnp.broadcast_to(pooled, local.shape)
    feat = jnp.concatenate([local, pooled], axis=-1)

    h = params["head"]
    logits = conv2d(feat, h["w"], 1) + h["bias"].astype(cdt)
    if not upsample:
        return logits.astype(jnp.float32)
    # full-resolution upsample inside the program (XLA lowers
    # jax.image.resize to gathers that fuse with the head conv)
    logits = jax.image.resize(
        logits.astype(jnp.float32), (B, H, W, logits.shape[-1]), "bilinear")
    return logits


@register_model("deeplab_mobilenet")
def _deeplab(opts: Dict[str, str]) -> ModelBundle:
    width = float(opts.get("width", 1.0))
    classes = int(opts.get("classes", CLASSES))
    seed = int(opts.get("seed", 0))
    size = int(opts.get("size", 257))  # the reference example's 257x257
    batch = int(opts.get("batch", 1))
    dtype = opts.get("dtype", "bfloat16")

    # custom=upsample:0 -> emit the native output-stride-16 score map
    # (the class decision; full res is a bilinear blow-up of it): the
    # D2H payload shrinks 256x for link-bound serving
    up = str(opts.get("upsample", "1")).lower() not in ("0", "false", "no")
    params = init_params(width=width, classes=classes, seed=seed)
    apply_fn = functools.partial(apply, compute_dtype=dtype, upsample=up)
    out_size = size
    native_size = size
    for _ in range(4):  # stride 16 = four SAME stride-2 stages
        native_size = -(-native_size // 2)
    if not up:
        out_size = native_size
    bundle = ModelBundle(
        apply_fn=apply_fn,
        params=params,
        in_spec=TensorsSpec.from_string(f"3:{size}:{size}:{batch}", "float32"),
        out_spec=TensorsSpec.from_string(
            f"{classes}:{out_size}:{out_size}:{batch}", "float32"),
        param_pspecs=param_pspecs(),
        name="deeplab_mobilenet",
    )
    if up and "upsample" not in opts:
        # Offer the HBM-residency planner the native-stride variant — but
        # ONLY when the caller didn't pin upsample explicitly (an explicit
        # upsample:1 means full resolution was asked for).  The thunk
        # reads ``bundle.params`` at call time, so device placement /
        # mesh replication applied after build carries over, and the
        # 16x16-downsampled score map shares every weight.
        def _reduced(b=bundle, n=native_size):
            import dataclasses as _dc

            return _dc.replace(
                b,
                apply_fn=functools.partial(
                    apply, compute_dtype=dtype, upsample=False),
                out_spec=TensorsSpec.from_string(
                    f"{classes}:{n}:{n}:{batch}", "float32"),
                reduced_variant=None, reduced_desc="")

        ratio = (size * size) // max(1, native_size * native_size)
        bundle.reduced_variant = _reduced
        bundle.reduced_desc = (
            f"native-stride score map [{batch},{native_size},{native_size},"
            f"{classes}] ({ratio}x less D2H than full resolution)")
    return bundle
