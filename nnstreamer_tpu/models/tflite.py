"""``.tflite`` model-file ingestion: flatbuffer -> JAX ``ModelBundle``.

Reference analog: the reference's default ``tensor_filter`` path loads a
model FILE through the tensorflow-lite sub-plugin
(``ext/nnstreamer/tensor_filter/tensor_filter_tensorflow_lite.cc``,
SURVEY §2.3/§2.4 [UNVERIFIED]) and invokes the TFLite interpreter on it.
This environment ships no TFLite runtime, and a TPU-native framework
shouldn't want one: a .tflite graph is a static dataflow of dense ops —
exactly what XLA compiles well.  So ingestion is a pure-Python flatbuffer
parser (the format is public; no TF dependency) that reads the graph ONCE
at open time and emits a jittable JAX closure over the file's REAL
weights.  ``tensor_filter framework=jax model=/path/m.tflite`` then fuses
into the surrounding pipeline's XLA program like any zoo model.

Supported operator set (the MobileNet/SSD-era CNN vocabulary the
reference's examples actually use): CONV_2D, DEPTHWISE_CONV_2D,
FULLY_CONNECTED, AVERAGE/MAX_POOL_2D, RESHAPE, SOFTMAX, ADD, SUB, MUL,
DIV, CONCATENATION, PAD, MEAN, SQUEEZE, TRANSPOSE, RESIZE_BILINEAR,
SPACE_TO_DEPTH, RELU, RELU6, LOGISTIC, TANH.  Float and HYBRID quantized
models load (integer weights dequantize at parse time, per-tensor or
per-axis, and run float on the MXU).  FULLY-quantized graphs (integer
activations — the reference's canonical ``mobilenet_v1_..._quant.tflite``
class) run by INTEGER EXECUTION (r5, VERDICT r4 Missing #1): activations
flow as the file's integer dtypes end to end, CONV_2D /
DEPTHWISE_CONV_2D / FULLY_CONNECTED execute as native int8 x int8 ->
int32 XLA ops on the MXU (int8 is the v5e's 2x-peak datatype) with
exact zero-point correction algebra, and every op requantizes to its
output tensor's (scale, zero_point) exactly where the graph says so;
light ops (softmax/logistic/add/...) run dequant -> f32 -> requant,
which XLA fuses.  ``custom=int_exec:0`` restores the r4
dequantized-execution fallback (integer boundary, float interior).
Requantization multiplies the int32 accumulator by an f32 multiplier
instead of TFLite's fixed-point doubling-high-mul, so results can
differ from TFLite's kernels by +-1 LSB on round-to-even boundaries —
function-exact, not bit-exact (tests pin both the numerics and that
the interior really is int8 by inspecting the jaxpr).
"""

from __future__ import annotations

import struct
from typing import Dict, List, Optional, Sequence

import numpy as np

from ..core.types import TensorsSpec, TensorSpec
from .zoo import ModelBundle


# ---------------------------------------------------------------------------
# Minimal flatbuffer reader (tables / vtables / vectors / strings — the
# subset the tflite schema uses).
# ---------------------------------------------------------------------------

class _FB:
    def __init__(self, data: bytes):
        self.d = data

    def u8(self, o):
        return self.d[o]

    def u16(self, o):
        return struct.unpack_from("<H", self.d, o)[0]

    def u32(self, o):
        return struct.unpack_from("<I", self.d, o)[0]

    def i8(self, o):
        return struct.unpack_from("<b", self.d, o)[0]

    def i32(self, o):
        return struct.unpack_from("<i", self.d, o)[0]

    def i64(self, o):
        return struct.unpack_from("<q", self.d, o)[0]

    def f32(self, o):
        return struct.unpack_from("<f", self.d, o)[0]

    def indirect(self, o):
        """Follow a uoffset at ``o`` to its target position."""
        return o + self.u32(o)

    def root(self):
        return self.indirect(0)

    def field(self, tab: int, fid: int) -> Optional[int]:
        """Absolute position of table field ``fid``'s data, or None."""
        vt = tab - self.i32(tab)  # soffset points BACK from table to vtable
        vsz = self.u16(vt)
        slot = 4 + 2 * fid
        if slot + 2 > vsz:
            return None
        off = self.u16(vt + slot)
        return tab + off if off else None

    # typed field reads with schema defaults
    def f_u8(self, tab, fid, default=0):
        p = self.field(tab, fid)
        return self.u8(p) if p is not None else default

    def f_i8(self, tab, fid, default=0):
        p = self.field(tab, fid)
        return self.i8(p) if p is not None else default

    def f_i32(self, tab, fid, default=0):
        p = self.field(tab, fid)
        return self.i32(p) if p is not None else default

    def f_u32(self, tab, fid, default=0):
        p = self.field(tab, fid)
        return self.u32(p) if p is not None else default

    def f_f32(self, tab, fid, default=0.0):
        p = self.field(tab, fid)
        return self.f32(p) if p is not None else default

    def f_bool(self, tab, fid, default=False):
        p = self.field(tab, fid)
        return bool(self.u8(p)) if p is not None else default

    def f_tab(self, tab, fid) -> Optional[int]:
        p = self.field(tab, fid)
        return self.indirect(p) if p is not None else None

    def f_str(self, tab, fid, default=""):
        p = self.field(tab, fid)
        if p is None:
            return default
        s = self.indirect(p)
        n = self.u32(s)
        return self.d[s + 4:s + 4 + n].decode("utf-8", "replace")

    def _vec(self, tab, fid):
        p = self.field(tab, fid)
        if p is None:
            return None, 0
        v = self.indirect(p)
        return v + 4, self.u32(v)

    def f_vec_i32(self, tab, fid) -> Optional[List[int]]:
        base, n = self._vec(tab, fid)
        if base is None:
            return None
        return list(struct.unpack_from(f"<{n}i", self.d, base))

    def f_vec_f32(self, tab, fid) -> Optional[List[float]]:
        base, n = self._vec(tab, fid)
        if base is None:
            return None
        return list(struct.unpack_from(f"<{n}f", self.d, base))

    def f_vec_i64(self, tab, fid) -> Optional[List[int]]:
        base, n = self._vec(tab, fid)
        if base is None:
            return None
        return list(struct.unpack_from(f"<{n}q", self.d, base))

    def f_vec_bytes(self, tab, fid) -> Optional[bytes]:
        base, n = self._vec(tab, fid)
        if base is None:
            return None
        return self.d[base:base + n]

    def f_vec_tabs(self, tab, fid) -> List[int]:
        base, n = self._vec(tab, fid)
        if base is None:
            return []
        return [self.indirect(base + 4 * i) for i in range(n)]


# ---------------------------------------------------------------------------
# tflite schema constants (public schema.fbs)
# ---------------------------------------------------------------------------

_TENSOR_DTYPES = {
    0: np.float32, 1: np.float16, 2: np.int32, 3: np.uint8, 4: np.int64,
    6: np.bool_, 7: np.int16, 9: np.int8, 10: np.float64,
}

_OP_NAMES = {
    0: "ADD", 1: "AVERAGE_POOL_2D", 2: "CONCATENATION", 3: "CONV_2D",
    4: "DEPTHWISE_CONV_2D", 9: "FULLY_CONNECTED", 14: "LOGISTIC",
    17: "MAX_POOL_2D", 18: "MUL", 19: "RELU", 21: "RELU6", 22: "RESHAPE",
    23: "RESIZE_BILINEAR", 25: "SOFTMAX", 26: "SPACE_TO_DEPTH", 28: "TANH",
    34: "PAD", 39: "TRANSPOSE", 40: "MEAN", 41: "SUB", 42: "DIV",
    43: "SQUEEZE",
}

_PADDING = {0: "SAME", 1: "VALID"}
_ACT = {0: None, 1: "relu", 3: "relu6", 4: "tanh"}


class TFLiteError(ValueError):
    pass


def _act_fn(code: int, what: str):
    import jax.numpy as jnp

    if code not in _ACT:
        raise TFLiteError(f"{what}: unsupported fused activation {code}")
    name = _ACT[code]
    if name is None:
        return lambda x: x
    if name == "relu":
        return lambda x: jnp.maximum(x, 0)
    if name == "relu6":
        return lambda x: jnp.clip(x, 0, 6)
    return jnp.tanh


# ---------------------------------------------------------------------------
# Graph IR
# ---------------------------------------------------------------------------

class _Op:
    __slots__ = ("kind", "inputs", "outputs", "attrs")

    def __init__(self, kind, inputs, outputs, attrs):
        self.kind = kind
        self.inputs = inputs
        self.outputs = outputs
        self.attrs = attrs


class TFLiteGraph:
    """Parsed model: tensors, constant weights, op list, graph IO."""

    def __init__(self, data: bytes, name: str = "tflite"):
        if len(data) < 8:
            raise TFLiteError("file too short to be a flatbuffer")
        if data[4:8] != b"TFL3":
            raise TFLiteError(
                f"not a tflite flatbuffer (identifier {data[4:8]!r}, "
                "expected b'TFL3')")
        self.name = name
        fb = _FB(data)
        model = fb.root()
        opcodes = []
        for oc in fb.f_vec_tabs(model, 1):
            # effective builtin code: max of the deprecated int8 field (0)
            # and the extended int32 field (3) — the schema's own rule for
            # codes above 127
            opcodes.append(max(fb.f_i8(oc, 0), fb.f_i32(oc, 3)))
        buffers = [fb.f_vec_bytes(b, 0) for b in fb.f_vec_tabs(model, 4)]
        subgraphs = fb.f_vec_tabs(model, 2)
        if not subgraphs:
            raise TFLiteError("model has no subgraph")
        sg = subgraphs[0]

        self.shapes: List[List[int]] = []
        self.dtypes: List[np.dtype] = []
        self.tensor_names: List[str] = []
        self.constants: Dict[int, np.ndarray] = {}
        #: ORIGINAL integer constants (weights/biases) of quantized
        #: tensors, kept alongside the dequantized ``constants`` so the
        #: integer-execution path can feed the MXU int8 directly
        self.raw_constants: Dict[int, np.ndarray] = {}
        #: full quantization record for EVERY quantized tensor
        #: (constants and activations): idx -> (scales f32 [k],
        #: zero_points i32 [k], axis)
        self.quant: Dict[int, tuple] = {}
        #: activation quantization: tensor idx -> (scale, zero_point,
        #: dtype) for integer activation tensors (graph IO contract +
        #: interior tensors of fully-quantized graphs)
        self.io_quant: Dict[int, tuple] = {}
        for idx, t in enumerate(fb.f_vec_tabs(sg, 0)):
            shape = fb.f_vec_i32(t, 0) or []
            tcode = fb.f_i8(t, 1, 0)
            if tcode not in _TENSOR_DTYPES:
                raise TFLiteError(
                    f"tensor {idx} ({fb.f_str(t, 3)}): unsupported tensor "
                    f"type code {tcode}")
            dt = np.dtype(_TENSOR_DTYPES[tcode])
            tname = fb.f_str(t, 3)
            self.shapes.append(shape)
            self.dtypes.append(dt)
            self.tensor_names.append(tname)
            q = fb.f_tab(t, 4)
            scale = fb.f_vec_f32(q, 2) if q is not None else None
            bufidx = fb.f_u32(t, 2, 0)
            raw = buffers[bufidx] if bufidx < len(buffers) else None
            if scale and np.issubdtype(dt, np.integer):
                zp = fb.f_vec_i64(q, 3) or [0] * len(scale)
                axis = fb.f_i32(q, 6, 0)
                self.quant[idx] = (np.asarray(scale, np.float32),
                                   np.asarray(zp, np.int32), axis)
            if scale and not raw and np.issubdtype(dt, np.integer):
                # Quantized ACTIVATION (fully-quantized graph); only
                # per-tensor scales make sense here.
                zp = fb.f_vec_i64(q, 3) or [0]
                if len(scale) != 1:
                    raise TFLiteError(
                        f"tensor {idx} ({tname!r}): per-axis activation "
                        "quantization is not meaningful; file corrupt?")
                self.io_quant[idx] = (float(scale[0]), int(zp[0]), dt)
            if raw:
                arr = np.frombuffer(raw, dtype=dt)
                arr = arr.reshape(shape) if shape else arr
                # Only INTEGER weights dequantize; some converters leave a
                # stale scale on already-float tensors (schema-legal), and
                # re-scaling those would silently corrupt them.
                if scale and np.issubdtype(dt, np.integer):
                    self.raw_constants[idx] = arr
                    arr = self._dequantize(fb, q, arr, scale, tname)
                    self.dtypes[idx] = np.dtype(np.float32)
                self.constants[idx] = arr

        self.inputs = fb.f_vec_i32(sg, 1) or []
        self.outputs = fb.f_vec_i32(sg, 2) or []
        self.ops: List[_Op] = []
        for op in fb.f_vec_tabs(sg, 3):
            oci = fb.f_u32(op, 0, 0)
            code = opcodes[oci]
            kind = _OP_NAMES.get(code)
            if kind is None:
                raise TFLiteError(
                    f"unsupported builtin operator code {code} "
                    f"(supported: {sorted(_OP_NAMES.values())})")
            ins = fb.f_vec_i32(op, 1) or []
            outs = fb.f_vec_i32(op, 2) or []
            bo = fb.f_tab(op, 4)
            self.ops.append(_Op(kind, ins, outs, self._attrs(fb, kind, bo)))

    @staticmethod
    def _dequantize(fb: _FB, q: int, arr: np.ndarray, scale, tname: str):
        """int8/uint8 weights -> float32 via (q - zero_point) * scale,
        per-tensor or per-axis (quantized_dimension)."""
        zp = fb.f_vec_i64(q, 3) or [0] * len(scale)
        axis = fb.f_i32(q, 6, 0)
        s = np.asarray(scale, np.float32)
        z = np.asarray(zp, np.float32)
        if s.size == 1:
            return (arr.astype(np.float32) - z[0]) * s[0]
        if arr.ndim == 0 or arr.shape[axis] != s.size:
            raise TFLiteError(
                f"tensor {tname!r}: per-axis scale count {s.size} does not "
                f"match dim {axis} of shape {arr.shape}")
        bshape = [1] * arr.ndim
        bshape[axis] = s.size
        return ((arr.astype(np.float32) - z.reshape(bshape))
                * s.reshape(bshape))

    @staticmethod
    def _attrs(fb: _FB, kind: str, bo: Optional[int]) -> Dict:
        """Decode the builtin-options table for ``kind`` (field ids from the
        public schema.fbs; all fields default like the schema does)."""
        a: Dict = {}
        if kind in ("CONV_2D",):
            a["padding"] = _PADDING[fb.f_i8(bo, 0, 0)] if bo else "SAME"
            a["strides"] = (fb.f_i32(bo, 2, 1), fb.f_i32(bo, 1, 1)) if bo else (1, 1)
            a["act"] = fb.f_i8(bo, 3, 0) if bo else 0
            a["dilation"] = (fb.f_i32(bo, 5, 1), fb.f_i32(bo, 4, 1)) if bo else (1, 1)
        elif kind == "DEPTHWISE_CONV_2D":
            a["padding"] = _PADDING[fb.f_i8(bo, 0, 0)] if bo else "SAME"
            a["strides"] = (fb.f_i32(bo, 2, 1), fb.f_i32(bo, 1, 1)) if bo else (1, 1)
            a["act"] = fb.f_i8(bo, 4, 0) if bo else 0
            a["dilation"] = (fb.f_i32(bo, 6, 1), fb.f_i32(bo, 5, 1)) if bo else (1, 1)
        elif kind in ("AVERAGE_POOL_2D", "MAX_POOL_2D"):
            a["padding"] = _PADDING[fb.f_i8(bo, 0, 0)] if bo else "SAME"
            a["strides"] = (fb.f_i32(bo, 2, 1), fb.f_i32(bo, 1, 1)) if bo else (1, 1)
            a["filter"] = (fb.f_i32(bo, 4, 1), fb.f_i32(bo, 3, 1)) if bo else (1, 1)
            a["act"] = fb.f_i8(bo, 5, 0) if bo else 0
        elif kind == "FULLY_CONNECTED":
            a["act"] = fb.f_i8(bo, 0, 0) if bo else 0
            a["keep_num_dims"] = fb.f_bool(bo, 2, False) if bo else False
        elif kind == "SOFTMAX":
            a["beta"] = fb.f_f32(bo, 0, 1.0) if bo else 1.0
        elif kind == "RESHAPE":
            a["new_shape"] = fb.f_vec_i32(bo, 0) if bo else None
        elif kind in ("ADD", "SUB", "MUL", "DIV"):
            a["act"] = fb.f_i8(bo, 0, 0) if bo else 0
        elif kind == "RESIZE_BILINEAR":
            a["align_corners"] = fb.f_bool(bo, 2, False) if bo else False
            a["half_pixel"] = fb.f_bool(bo, 3, False) if bo else False
        elif kind == "SPACE_TO_DEPTH":
            a["block"] = fb.f_i32(bo, 0, 1) if bo else 1
        elif kind == "CONCATENATION":
            a["axis"] = fb.f_i32(bo, 0, 0) if bo else 0
            a["act"] = fb.f_i8(bo, 1, 0) if bo else 0
        elif kind == "MEAN":
            a["keep_dims"] = fb.f_bool(bo, 0, False) if bo else False
        elif kind == "SQUEEZE":
            a["squeeze_dims"] = fb.f_vec_i32(bo, 0) if bo else None
        return a


# ---------------------------------------------------------------------------
# JAX execution
# ---------------------------------------------------------------------------

#: per-op input positions that are STATIC metadata (shapes/axes/paddings),
#: not data: they must resolve to concrete graph constants at trace time —
#: reading them through the traced params pytree would crash under jit.
_STATIC_OPERANDS = {"RESHAPE": (1,), "PAD": (1,), "MEAN": (1,),
                    "TRANSPOSE": (1,), "RESIZE_BILINEAR": (1,)}


def _resize_bilinear(x, oh: int, ow: int, align_corners: bool,
                     half_pixel: bool):
    """tflite ResizeBilinear semantics (all three coordinate mappings)."""
    import jax.numpy as jnp

    h, w = x.shape[1], x.shape[2]

    def coords(o, n):
        i = jnp.arange(o, dtype=jnp.float32)
        if align_corners and o > 1:
            return i * (n - 1) / (o - 1)
        if half_pixel:
            return jnp.maximum((i + 0.5) * n / o - 0.5, 0.0)
        return i * n / o

    yf = coords(oh, h)
    xf = coords(ow, w)
    y0 = jnp.clip(jnp.floor(yf).astype(jnp.int32), 0, h - 1)
    x0 = jnp.clip(jnp.floor(xf).astype(jnp.int32), 0, w - 1)
    y1 = jnp.minimum(y0 + 1, h - 1)
    x1 = jnp.minimum(x0 + 1, w - 1)
    wy = (yf - y0)[None, :, None, None]
    wx = (xf - x0)[None, None, :, None]
    f = x.astype(jnp.float32)
    top = f[:, y0][:, :, x0] * (1 - wx) + f[:, y0][:, :, x1] * wx
    bot = f[:, y1][:, :, x0] * (1 - wx) + f[:, y1][:, :, x1] * wx
    return (top * (1 - wy) + bot * wy).astype(x.dtype)


def _run_op(op: _Op, get, const, attrs_name: str):
    """Execute one op; ``get(idx)`` resolves a tensor index to a (possibly
    traced) array, ``const(idx)`` to a concrete numpy constant."""
    import jax.numpy as jnp
    from jax import lax

    k, a = op.kind, op.attrs
    if k == "CONV_2D":
        x, w = get(op.inputs[0]), get(op.inputs[1])
        # tflite kernel layout OHWI -> XLA HWIO
        y = lax.conv_general_dilated(
            x, jnp.transpose(w, (1, 2, 3, 0)),
            window_strides=a["strides"], padding=a["padding"],
            rhs_dilation=a["dilation"],
            dimension_numbers=("NHWC", "HWIO", "NHWC"))
        if len(op.inputs) > 2 and op.inputs[2] >= 0:
            y = y + get(op.inputs[2])
        return _act_fn(a["act"], attrs_name)(y)
    if k == "DEPTHWISE_CONV_2D":
        x, w = get(op.inputs[0]), get(op.inputs[1])
        cin = x.shape[-1]
        # tflite layout [1, kh, kw, cin*mult] -> HWIO with I=1, groups=cin
        y = lax.conv_general_dilated(
            x, jnp.transpose(w, (1, 2, 0, 3)),
            window_strides=a["strides"], padding=a["padding"],
            rhs_dilation=a["dilation"], feature_group_count=cin,
            dimension_numbers=("NHWC", "HWIO", "NHWC"))
        if len(op.inputs) > 2 and op.inputs[2] >= 0:
            y = y + get(op.inputs[2])
        return _act_fn(a["act"], attrs_name)(y)
    if k == "FULLY_CONNECTED":
        x, w = get(op.inputs[0]), get(op.inputs[1])
        if not a["keep_num_dims"] and x.ndim != 2:
            x = x.reshape(-1, w.shape[1])
        y = x @ w.T
        if len(op.inputs) > 2 and op.inputs[2] >= 0:
            y = y + get(op.inputs[2])
        return _act_fn(a["act"], attrs_name)(y)
    if k in ("AVERAGE_POOL_2D", "MAX_POOL_2D"):
        x = get(op.inputs[0])
        fh, fw = a["filter"]
        sh, sw = a["strides"]
        dims, strides = (1, fh, fw, 1), (1, sh, sw, 1)
        if k == "MAX_POOL_2D":
            y = lax.reduce_window(x, -jnp.inf, lax.max, dims, strides,
                                  a["padding"])
        else:
            s = lax.reduce_window(x, 0.0, lax.add, dims, strides,
                                  a["padding"])
            # SAME average pooling divides by the ACTUAL window size at the
            # edges (tflite semantics): count via the same reduce on ones
            ones = jnp.ones_like(x)
            cnt = lax.reduce_window(ones, 0.0, lax.add, dims, strides,
                                    a["padding"])
            y = s / cnt
        return _act_fn(a["act"], attrs_name)(y)
    if k == "RESHAPE":
        x = get(op.inputs[0])
        shape = a["new_shape"]
        if shape is None and len(op.inputs) > 1:
            shape = [int(v) for v in const(op.inputs[1])]
        if shape is None:
            raise TFLiteError(f"{attrs_name}: RESHAPE without a target shape")
        return x.reshape(shape)
    if k == "SOFTMAX":
        import jax

        return jax.nn.softmax(get(op.inputs[0]) * a["beta"], axis=-1)
    if k in ("ADD", "SUB", "MUL", "DIV"):
        import operator

        fn = {"ADD": operator.add, "SUB": operator.sub,
              "MUL": operator.mul, "DIV": operator.truediv}[k]
        z = fn(get(op.inputs[0]), get(op.inputs[1]))
        return _act_fn(a["act"], attrs_name)(z)
    if k == "TRANSPOSE":
        perm = [int(v) for v in const(op.inputs[1]).ravel()]
        return jnp.transpose(get(op.inputs[0]), perm)
    if k == "SPACE_TO_DEPTH":
        x = get(op.inputs[0])
        b = a["block"]
        B, H, W, C = x.shape
        x = x.reshape(B, H // b, b, W // b, b, C)
        return x.transpose(0, 1, 3, 2, 4, 5).reshape(
            B, H // b, W // b, C * b * b)
    if k == "RESIZE_BILINEAR":
        x = get(op.inputs[0])
        oh, ow = (int(v) for v in const(op.inputs[1]).ravel())
        return _resize_bilinear(x, oh, ow, a["align_corners"],
                                a["half_pixel"])
    if k == "CONCATENATION":
        parts = [get(i) for i in op.inputs]
        z = jnp.concatenate(parts, axis=a["axis"])
        return _act_fn(a["act"], attrs_name)(z)
    if k == "PAD":
        x = get(op.inputs[0])
        pads = const(op.inputs[1]).reshape(-1, 2)
        return jnp.pad(x, [(int(lo), int(hi)) for lo, hi in pads])
    if k == "MEAN":
        x = get(op.inputs[0])
        axes = [int(v) for v in const(op.inputs[1]).ravel()]
        return jnp.mean(x, axis=tuple(axes), keepdims=a["keep_dims"])
    if k == "SQUEEZE":
        x = get(op.inputs[0])
        dims = a["squeeze_dims"]
        axis = tuple(dims) if dims else None
        return jnp.squeeze(x, axis=axis)
    if k == "RELU":
        return jnp.maximum(get(op.inputs[0]), 0)
    if k == "RELU6":
        return jnp.clip(get(op.inputs[0]), 0, 6)
    if k == "LOGISTIC":
        import jax

        return jax.nn.sigmoid(get(op.inputs[0]))
    if k == "TANH":
        return jnp.tanh(get(op.inputs[0]))
    raise TFLiteError(f"unsupported op {k}")  # pragma: no cover


# ---------------------------------------------------------------------------
# Integer execution (fully-quantized graphs)
# ---------------------------------------------------------------------------
#
# The heavy ops (CONV_2D / DEPTHWISE_CONV_2D / FULLY_CONNECTED) run as
# NATIVE int8 x int8 -> int32 XLA dots/convs — int8 is the v5e MXU's
# 2x-peak datatype, so the quantized model class finally runs MORE
# TPU-native than its float twin instead of less (VERDICT r4 Missing #1).
# Zero-point algebra (uint8 legacy files have nonzero zps on BOTH sides):
# operands are shifted into int8 (x-128 / w-128, zps adjusted), inputs
# are explicitly padded with their zero point so every window is full,
# and
#     y = conv(x8, w8) - x_zp*sum(w8) - w_zp*sum_win(x8) + K*x_zp*w_zp
# with sum(w8) per-out-channel precomputed host-side and sum_win(x8) a
# 1-channel ones-kernel conv (only materialized when w_zp != 0).  The
# int32 accumulator requantizes per-op through an f32 multiplier
# (per-axis where the file says so) with the fused activation expressed
# as clamping in the quantized domain — elementwise work XLA fuses into
# the conv epilogue.  Light ops (softmax/logistic/add/...) run
# dequant -> f32 -> requant, which also fuses; the MXU-bound ops are the
# integer story.


def _deq_t(x, q):
    """Traced dequantize, per-tensor: (q - zp) * scale -> f32."""
    import jax.numpy as jnp

    s, z, _ = q
    return (jnp.asarray(x).astype(jnp.float32) - float(z[0])) * float(s[0])


def _req_t(x, q, dt):
    """Traced requantize, per-tensor: f32 -> clamped integer dtype."""
    import jax.numpy as jnp

    s, z, _ = q
    info = np.iinfo(dt)
    y = jnp.round(jnp.asarray(x).astype(jnp.float32) / float(s[0])) \
        + float(z[0])
    return jnp.clip(y, info.min, info.max).astype(dt)


def _act_qrange(act: int, dt, scale: float, zp: int, what: str):
    """Fused-activation clamp range in the QUANTIZED domain."""
    info = np.iinfo(dt)
    lo, hi = info.min, info.max
    name = _ACT.get(act)
    if act not in _ACT or name == "tanh":
        raise TFLiteError(f"{what}: unsupported fused activation {act} "
                          "for integer execution")
    if name in ("relu", "relu6"):
        lo = max(lo, zp)
    if name == "relu6":
        hi = min(hi, int(round(6.0 / scale)) + zp)
    return lo, hi


def _same_pads(in_hw, k_hw, strides, dilation):
    """Explicit TFLite/XLA SAME padding (so integer convs can pad with
    the zero point and run VALID — every window full, algebra exact)."""
    pads = []
    for n, k, s, d in zip(in_hw, k_hw, strides, dilation):
        eff = (k - 1) * d + 1
        total = max(0, (-(-n // s) - 1) * s + eff - n)
        pads.append((total // 2, total - total // 2))
    return pads


def _to_i8(x, zp: int):
    """Shift a uint8 activation/weight into int8 (zp adjusted by -128);
    int8 passes through."""
    import jax.numpy as jnp

    if np.dtype(x.dtype) == np.uint8:
        return (jnp.asarray(x).astype(jnp.int32) - 128).astype(jnp.int8), \
            zp - 128
    return x, zp


def _requant_acc(acc, bias, mult, out_q, act, what):
    """int32 accumulator (+int32 bias) -> quantized output tensor."""
    import jax.numpy as jnp

    if bias is not None:
        acc = acc + bias.astype(jnp.int32)
    s, z, _ = out_q[0]
    dt = out_q[1]
    y = jnp.round(acc.astype(jnp.float32) * mult) + float(z[0])
    lo, hi = _act_qrange(act, dt, float(s[0]), int(z[0]), what)
    return jnp.clip(y, lo, hi).astype(dt)


def _run_op_int(op: _Op, geti, const, g: "TFLiteGraph", p, name: str):
    """Integer-execution twin of :func:`_run_op`.  ``geti`` resolves a
    tensor index to its env value (integer activations keep their file
    dtype); falls back to dequant->float->requant per op for kinds with
    no integer benefit."""
    import jax.numpy as jnp
    from jax import lax

    k, a = op.kind, op.attrs
    qof = g.quant.get

    def out_q(pos=0):
        i = op.outputs[pos]
        q = qof(i)
        if q is None:
            raise TFLiteError(
                f"{name}: output tensor {i} ({g.tensor_names[i]!r}) of "
                f"{k} has no quantization — not a fully-quantized graph")
        return q, g.dtypes[i]

    if k in ("CONV_2D", "DEPTHWISE_CONV_2D") and qof(op.inputs[0]):
        xi, wi = op.inputs[0], op.inputs[1]
        x = geti(xi)
        xs, xz, _ = qof(xi)
        w_raw = g.raw_constants[wi]
        ws, wz, _ = qof(wi)
        x8, xz8 = _to_i8(x, int(xz[0]))
        w8 = w_raw.astype(np.int32) - (128 if w_raw.dtype == np.uint8
                                       else 0)
        wz8 = wz.astype(np.int32) - (128 if w_raw.dtype == np.uint8
                                     else 0)
        dw = k == "DEPTHWISE_CONV_2D"
        # tflite layouts: conv OHWI, depthwise [1, kh, kw, cin*mult]
        hwio = (w8.transpose(1, 2, 0, 3) if dw
                else w8.transpose(1, 2, 3, 0))
        kh, kw = hwio.shape[:2]
        cin = x.shape[-1]
        if a["padding"] == "SAME":
            pads = _same_pads(x.shape[1:3], (kh, kw), a["strides"],
                              a["dilation"])
        else:
            pads = [(0, 0), (0, 0)]
        x8p = jnp.pad(x8, [(0, 0), pads[0], pads[1], (0, 0)],
                      constant_values=np.int8(xz8))
        acc = lax.conv_general_dilated(
            x8p, jnp.asarray(hwio.astype(np.int8)),
            window_strides=a["strides"], padding="VALID",
            rhs_dilation=a["dilation"],
            feature_group_count=cin if dw else 1,
            dimension_numbers=("NHWC", "HWIO", "NHWC"),
            preferred_element_type=jnp.int32)
        # host-side per-out-channel correction constants
        sum_w = hwio.sum(axis=(0, 1, 2)).astype(np.int64)  # [O]
        K = kh * kw * (1 if dw else cin)
        # per-out-channel weight zero point vector [O]
        wz_vec = (np.broadcast_to(wz8, (acc.shape[-1],))
                  if wz8.size > 1 else np.full((acc.shape[-1],),
                                               int(wz8.ravel()[0])))
        corr = (-xz8 * sum_w + K * xz8 * wz_vec).astype(np.int32)
        acc = acc + jnp.asarray(corr)[None, None, None, :]
        if np.any(wz_vec != 0):
            if dw:
                sum_x = lax.reduce_window(
                    x8p.astype(jnp.int32), 0, lax.add,
                    (1, kh, kw, 1), (1,) + tuple(a["strides"]) + (1,),
                    "VALID",
                    window_dilation=(1,) + tuple(a["dilation"]) + (1,))
                rep = acc.shape[-1] // cin  # [B,H',W',C] -> out channels
                sum_x = jnp.repeat(sum_x, rep, axis=-1)
            else:
                ones = np.ones((kh, kw, cin, 1), np.int8)
                sum_x = lax.conv_general_dilated(
                    x8p, jnp.asarray(ones), window_strides=a["strides"],
                    padding="VALID", rhs_dilation=a["dilation"],
                    dimension_numbers=("NHWC", "HWIO", "NHWC"),
                    preferred_element_type=jnp.int32)
            acc = acc - jnp.asarray(wz_vec, jnp.int32) * sum_x
        bias = None
        if len(op.inputs) > 2 and op.inputs[2] >= 0:
            bias = jnp.asarray(g.raw_constants[op.inputs[2]])[
                None, None, None, :]
        oq = out_q()
        mult = (float(xs[0]) * ws.astype(np.float32)
                / float(oq[0][0][0]))  # [O] or scalar
        mult = np.broadcast_to(mult, (acc.shape[-1],)).astype(np.float32)
        return _requant_acc(acc, bias, jnp.asarray(mult), oq, a["act"],
                            name)

    if k == "FULLY_CONNECTED" and qof(op.inputs[0]):
        xi, wi = op.inputs[0], op.inputs[1]
        x = geti(xi)
        xs, xz, _ = qof(xi)
        w_raw = g.raw_constants[wi]  # [O, K]
        ws, wz, _ = qof(wi)
        x8, xz8 = _to_i8(x, int(xz[0]))
        if x8.ndim != 2:
            x8 = x8.reshape(-1, w_raw.shape[1])
        w8 = w_raw.astype(np.int32) - (128 if w_raw.dtype == np.uint8
                                       else 0)
        wz8 = wz.astype(np.int32) - (128 if w_raw.dtype == np.uint8
                                     else 0)
        acc = lax.dot_general(
            x8, jnp.asarray(w8.astype(np.int8)),
            (((1,), (1,)), ((), ())), preferred_element_type=jnp.int32)
        sum_w = w8.sum(axis=1).astype(np.int64)  # [O]
        Kdim = w_raw.shape[1]
        wz_vec = (np.broadcast_to(wz8, (acc.shape[-1],))
                  if wz8.size > 1 else np.full((acc.shape[-1],),
                                               int(wz8.ravel()[0])))
        corr = (-xz8 * sum_w + Kdim * xz8 * wz_vec).astype(np.int32)
        acc = acc + jnp.asarray(corr)[None, :]
        if np.any(wz_vec != 0):
            sum_x = jnp.sum(x8.astype(jnp.int32), axis=1, keepdims=True)
            acc = acc - jnp.asarray(wz_vec, jnp.int32)[None, :] * sum_x
        bias = None
        if len(op.inputs) > 2 and op.inputs[2] >= 0:
            bias = jnp.asarray(g.raw_constants[op.inputs[2]])[None, :]
        oq = out_q()
        mult = (float(xs[0]) * ws.astype(np.float32) / float(oq[0][0][0]))
        mult = np.broadcast_to(mult, (acc.shape[-1],)).astype(np.float32)
        return _requant_acc(acc, bias, jnp.asarray(mult), oq, a["act"],
                            name)

    if k == "MAX_POOL_2D" and qof(op.inputs[0]):
        # max commutes with the (monotone) quantization map; same
        # in/out quant per the tflite spec — run on raw integers
        x = geti(op.inputs[0])
        fh, fw = a["filter"]
        sh, sw = a["strides"]
        info = np.iinfo(g.dtypes[op.inputs[0]])
        return lax.reduce_window(x, np.asarray(info.min, x.dtype),
                                 lax.max, (1, fh, fw, 1),
                                 (1, sh, sw, 1), a["padding"])

    if k in ("RESHAPE", "SQUEEZE", "TRANSPOSE", "SPACE_TO_DEPTH"):
        return _run_op(op, geti, const, name)  # pure layout: int passes

    if k == "PAD" and qof(op.inputs[0]):
        x = geti(op.inputs[0])
        _, z, _ = qof(op.inputs[0])
        padv = np.asarray(int(z[0]), x.dtype)
        pads = const(op.inputs[1]).reshape(-1, 2)
        import jax.numpy as jnp

        return jnp.pad(x, [(int(lo), int(hi)) for lo, hi in pads],
                       constant_values=padv)

    if k == "CONCATENATION" and all(qof(i) for i in op.inputs):
        import jax.numpy as jnp

        oq, odt = out_q()
        parts = []
        for i in op.inputs:
            q = qof(i)
            same = (float(q[0][0]) == float(oq[0][0])
                    and int(q[1][0]) == int(oq[1][0]))
            parts.append(geti(i) if same
                         else _req_t(_deq_t(geti(i), q), oq, odt))
        return jnp.concatenate(parts, axis=a["axis"])

    # generic fallback: dequant integer inputs, run the float op,
    # requant to the op output's quantization (fuses; no MXU involved)
    def getf(i):
        v = geti(i)
        q = qof(i)
        if q is not None and np.issubdtype(np.dtype(v.dtype), np.integer) \
                and i not in g.raw_constants:
            return _deq_t(v, q)
        if i in g.constants and i in g.raw_constants:
            return np.asarray(g.constants[i])  # pre-dequantized weights
        return v

    res = _run_op(op, getf, const, name)
    oi = op.outputs[0]
    q = qof(oi)
    if q is not None:
        return _req_t(res, q, g.dtypes[oi])
    return res


def load_bundle(path: str, opts: Optional[Dict[str, str]] = None) -> ModelBundle:
    """Parse a .tflite file into a jittable :class:`ModelBundle`.

    The file's weight tensors become the bundle's params pytree (so they
    ride HBM and donation/sharding machinery like any zoo model); the graph
    walk happens at trace time, producing one fused XLA program.

    ``custom=param_dtype:bfloat16`` casts the float weights (e.g. to feed
    the MXU at 2 bytes/param); other option keys are rejected so a typo'd
    pipeline string fails loudly instead of being silently ignored.
    """
    opts = dict(opts or {})
    param_dtype = opts.pop("param_dtype", None)
    int_exec_opt = str(opts.pop("int_exec", "1")).lower() not in (
        "0", "false", "no")
    if opts:
        raise TFLiteError(
            f"{path}: unsupported options {sorted(opts)} "
            "(tflite ingestion supports: param_dtype, int_exec)")
    with open(path, "rb") as f:
        data = f.read()
    g = TFLiteGraph(data, name=path)
    # Fully-quantized graph (every graph input AND output is an integer
    # activation): run the INTEGER execution path — native int8 MXU
    # dots/convs with per-op requantization (_run_op_int) — unless the
    # caller forces the dequantized fallback with custom=int_exec:0.
    int_exec = (int_exec_opt and g.inputs and g.outputs
                and all(i in g.io_quant for i in g.inputs)
                and all(i in g.io_quant for i in g.outputs))
    if int_exec:
        return _load_bundle_int(path, g)
    # Static-metadata operands (reshape shapes, pad widths, mean axes) stay
    # OUT of params: they must be concrete at trace time, and shipping them
    # to device would be pointless anyway.  A constant ALSO consumed as
    # data by some other op keeps its params slot.
    static_ids = set()
    data_ids = set()
    for op in g.ops:
        static_pos = _STATIC_OPERANDS.get(op.kind, ())
        for pos, idx in enumerate(op.inputs):
            (static_ids if pos in static_pos else data_ids).add(idx)
    params = {f"t{i}": np.asarray(v) for i, v in g.constants.items()
              if i not in (static_ids - data_ids)}
    if param_dtype:
        from ..core.types import dtype_from_name

        dt = dtype_from_name(str(param_dtype))
        params = {k: v.astype(dt) if np.issubdtype(v.dtype, np.floating)
                  else v for k, v in params.items()}

    def apply_fn(p, *inputs):
        import jax.numpy as jnp

        if len(inputs) != len(g.inputs):
            raise TFLiteError(
                f"{path}: expected {len(g.inputs)} input(s), got "
                f"{len(inputs)}")
        env: Dict[int, object] = {}
        for idx, arr in zip(g.inputs, inputs):
            if idx in g.io_quant:
                # fully-quantized graph boundary: integer in, float inside
                scale, zp, _ = g.io_quant[idx]
                arr = (jnp.asarray(arr).astype(jnp.float32) - zp) * scale
            env[idx] = arr

        def get(i):
            if i in env:
                return env[i]
            key = f"t{i}"
            if key in p:
                return p[key]
            raise TFLiteError(
                f"{path}: tensor {i} ({g.tensor_names[i]!r}) used before "
                "produced — graph is not topologically ordered?")

        def const(i):
            if i not in g.constants:
                raise TFLiteError(
                    f"{path}: tensor {i} ({g.tensor_names[i]!r}) must be a "
                    "graph constant (shapes/axes/paddings are static under "
                    "XLA; dynamic values are unsupported)")
            return np.asarray(g.constants[i])

        for op in g.ops:
            outs = op.outputs
            res = _run_op(op, get, const, path)
            env[outs[0]] = res

        def requant(i):
            x = env[i]
            if i not in g.io_quant:
                return x
            scale, zp, dt = g.io_quant[i]
            info = np.iinfo(dt)
            q = jnp.round(jnp.asarray(x).astype(jnp.float32) / scale) + zp
            return jnp.clip(q, info.min, info.max).astype(dt)

        results = tuple(requant(i) for i in g.outputs)
        return results if len(results) > 1 else results[0]

    return ModelBundle(apply_fn=apply_fn, params=params,
                       in_spec=_graph_spec(g, g.inputs),
                       out_spec=_graph_spec(g, g.outputs), name=path)


def _graph_spec(g: TFLiteGraph, ids) -> TensorsSpec:
    return TensorsSpec(tuple(
        TensorSpec.from_shape(g.shapes[i], g.dtypes[i], g.tensor_names[i])
        for i in ids))


def _load_bundle_int(path: str, g: TFLiteGraph) -> ModelBundle:
    """Integer-execution bundle for a fully-quantized graph.

    Weights stay in their file dtype and are baked into the program as
    constants (a quantized CNN is a few MB of int8 — XLA embeds and
    dedupes them; the params pytree is empty).  Activations flow as the
    file's integer dtypes end to end; CONV/DW/FC hit the MXU as int8
    (see the integer-execution section above)."""

    def apply_fn(p, *inputs):
        import jax.numpy as jnp

        if len(inputs) != len(g.inputs):
            raise TFLiteError(
                f"{path}: expected {len(g.inputs)} input(s), got "
                f"{len(inputs)}")
        env: Dict[int, object] = {}
        for idx, arr in zip(g.inputs, inputs):
            env[idx] = jnp.asarray(arr)

        def geti(i):
            if i in env:
                return env[i]
            if i in g.raw_constants:
                return jnp.asarray(g.raw_constants[i])
            if i in g.constants:
                return jnp.asarray(g.constants[i])
            raise TFLiteError(
                f"{path}: tensor {i} ({g.tensor_names[i]!r}) used before "
                "produced — graph is not topologically ordered?")

        def const(i):
            if i not in g.constants:
                raise TFLiteError(
                    f"{path}: tensor {i} ({g.tensor_names[i]!r}) must be "
                    "a graph constant (shapes/axes/paddings are static "
                    "under XLA; dynamic values are unsupported)")
            return np.asarray(g.constants[i])

        for op in g.ops:
            env[op.outputs[0]] = _run_op_int(op, geti, const, g, p, path)

        results = []
        for i in g.outputs:
            x = env[i]
            want = g.dtypes[i]
            if np.dtype(x.dtype) != want:
                q = g.quant.get(i)
                x = (_req_t(x, q, want) if q is not None
                     else x.astype(want))
            results.append(x)
        return tuple(results) if len(results) > 1 else results[0]

    return ModelBundle(apply_fn=apply_fn, params={},
                       in_spec=_graph_spec(g, g.inputs),
                       out_spec=_graph_spec(g, g.outputs), name=path)
