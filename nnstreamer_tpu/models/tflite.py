"""``.tflite`` model-file ingestion: flatbuffer -> JAX ``ModelBundle``.

Reference analog: the reference's default ``tensor_filter`` path loads a
model FILE through the tensorflow-lite sub-plugin
(``ext/nnstreamer/tensor_filter/tensor_filter_tensorflow_lite.cc``,
SURVEY §2.3/§2.4 [UNVERIFIED]) and invokes the TFLite interpreter on it.
This environment ships no TFLite runtime, and a TPU-native framework
shouldn't want one: a .tflite graph is a static dataflow of dense ops —
exactly what XLA compiles well.  So ingestion is a pure-Python flatbuffer
parser (the format is public; no TF dependency) that reads the graph ONCE
at open time and emits a jittable JAX closure over the file's REAL
weights.  ``tensor_filter framework=jax model=/path/m.tflite`` then fuses
into the surrounding pipeline's XLA program like any zoo model.

Supported operator set (the MobileNet/SSD-era CNN vocabulary the
reference's examples actually use): CONV_2D, DEPTHWISE_CONV_2D,
FULLY_CONNECTED, AVERAGE/MAX_POOL_2D, RESHAPE, SOFTMAX, ADD, SUB, MUL,
DIV, CONCATENATION, PAD, MEAN, SQUEEZE, TRANSPOSE, RESIZE_BILINEAR,
SPACE_TO_DEPTH, RELU, RELU6, LOGISTIC, TANH.  Float and HYBRID quantized
models load (integer weights dequantize at parse time, per-tensor or
per-axis, and run float on the MXU).  FULLY-quantized graphs (integer
activations — the reference's canonical ``mobilenet_v1_..._quant.tflite``
class) load too, by DEQUANTIZED EXECUTION: graph inputs keep the file's
integer dtype and dequantize on entry ((q - zero_point) * scale), the
interior runs float32/bf16 on the MXU, and integer graph outputs
requantize on exit (round(x/scale) + zero_point, saturating cast).  This
reproduces the model's FUNCTION to within quantization error rather than
bit-matching TFLite's integer kernels — per-op integer requantization is
deliberately not emulated (documented dequant, VERDICT r3 ask #4): on
TPU the float path IS the fast path, and the integer wire contract at
the pipeline boundary is what the reference's callers see.
"""

from __future__ import annotations

import struct
from typing import Dict, List, Optional, Sequence

import numpy as np

from ..core.types import TensorsSpec, TensorSpec
from .zoo import ModelBundle


# ---------------------------------------------------------------------------
# Minimal flatbuffer reader (tables / vtables / vectors / strings — the
# subset the tflite schema uses).
# ---------------------------------------------------------------------------

class _FB:
    def __init__(self, data: bytes):
        self.d = data

    def u8(self, o):
        return self.d[o]

    def u16(self, o):
        return struct.unpack_from("<H", self.d, o)[0]

    def u32(self, o):
        return struct.unpack_from("<I", self.d, o)[0]

    def i8(self, o):
        return struct.unpack_from("<b", self.d, o)[0]

    def i32(self, o):
        return struct.unpack_from("<i", self.d, o)[0]

    def i64(self, o):
        return struct.unpack_from("<q", self.d, o)[0]

    def f32(self, o):
        return struct.unpack_from("<f", self.d, o)[0]

    def indirect(self, o):
        """Follow a uoffset at ``o`` to its target position."""
        return o + self.u32(o)

    def root(self):
        return self.indirect(0)

    def field(self, tab: int, fid: int) -> Optional[int]:
        """Absolute position of table field ``fid``'s data, or None."""
        vt = tab - self.i32(tab)  # soffset points BACK from table to vtable
        vsz = self.u16(vt)
        slot = 4 + 2 * fid
        if slot + 2 > vsz:
            return None
        off = self.u16(vt + slot)
        return tab + off if off else None

    # typed field reads with schema defaults
    def f_u8(self, tab, fid, default=0):
        p = self.field(tab, fid)
        return self.u8(p) if p is not None else default

    def f_i8(self, tab, fid, default=0):
        p = self.field(tab, fid)
        return self.i8(p) if p is not None else default

    def f_i32(self, tab, fid, default=0):
        p = self.field(tab, fid)
        return self.i32(p) if p is not None else default

    def f_u32(self, tab, fid, default=0):
        p = self.field(tab, fid)
        return self.u32(p) if p is not None else default

    def f_f32(self, tab, fid, default=0.0):
        p = self.field(tab, fid)
        return self.f32(p) if p is not None else default

    def f_bool(self, tab, fid, default=False):
        p = self.field(tab, fid)
        return bool(self.u8(p)) if p is not None else default

    def f_tab(self, tab, fid) -> Optional[int]:
        p = self.field(tab, fid)
        return self.indirect(p) if p is not None else None

    def f_str(self, tab, fid, default=""):
        p = self.field(tab, fid)
        if p is None:
            return default
        s = self.indirect(p)
        n = self.u32(s)
        return self.d[s + 4:s + 4 + n].decode("utf-8", "replace")

    def _vec(self, tab, fid):
        p = self.field(tab, fid)
        if p is None:
            return None, 0
        v = self.indirect(p)
        return v + 4, self.u32(v)

    def f_vec_i32(self, tab, fid) -> Optional[List[int]]:
        base, n = self._vec(tab, fid)
        if base is None:
            return None
        return list(struct.unpack_from(f"<{n}i", self.d, base))

    def f_vec_f32(self, tab, fid) -> Optional[List[float]]:
        base, n = self._vec(tab, fid)
        if base is None:
            return None
        return list(struct.unpack_from(f"<{n}f", self.d, base))

    def f_vec_i64(self, tab, fid) -> Optional[List[int]]:
        base, n = self._vec(tab, fid)
        if base is None:
            return None
        return list(struct.unpack_from(f"<{n}q", self.d, base))

    def f_vec_bytes(self, tab, fid) -> Optional[bytes]:
        base, n = self._vec(tab, fid)
        if base is None:
            return None
        return self.d[base:base + n]

    def f_vec_tabs(self, tab, fid) -> List[int]:
        base, n = self._vec(tab, fid)
        if base is None:
            return []
        return [self.indirect(base + 4 * i) for i in range(n)]


# ---------------------------------------------------------------------------
# tflite schema constants (public schema.fbs)
# ---------------------------------------------------------------------------

_TENSOR_DTYPES = {
    0: np.float32, 1: np.float16, 2: np.int32, 3: np.uint8, 4: np.int64,
    6: np.bool_, 7: np.int16, 9: np.int8, 10: np.float64,
}

_OP_NAMES = {
    0: "ADD", 1: "AVERAGE_POOL_2D", 2: "CONCATENATION", 3: "CONV_2D",
    4: "DEPTHWISE_CONV_2D", 9: "FULLY_CONNECTED", 14: "LOGISTIC",
    17: "MAX_POOL_2D", 18: "MUL", 19: "RELU", 21: "RELU6", 22: "RESHAPE",
    23: "RESIZE_BILINEAR", 25: "SOFTMAX", 26: "SPACE_TO_DEPTH", 28: "TANH",
    34: "PAD", 39: "TRANSPOSE", 40: "MEAN", 41: "SUB", 42: "DIV",
    43: "SQUEEZE",
}

_PADDING = {0: "SAME", 1: "VALID"}
_ACT = {0: None, 1: "relu", 3: "relu6", 4: "tanh"}


class TFLiteError(ValueError):
    pass


def _act_fn(code: int, what: str):
    import jax.numpy as jnp

    if code not in _ACT:
        raise TFLiteError(f"{what}: unsupported fused activation {code}")
    name = _ACT[code]
    if name is None:
        return lambda x: x
    if name == "relu":
        return lambda x: jnp.maximum(x, 0)
    if name == "relu6":
        return lambda x: jnp.clip(x, 0, 6)
    return jnp.tanh


# ---------------------------------------------------------------------------
# Graph IR
# ---------------------------------------------------------------------------

class _Op:
    __slots__ = ("kind", "inputs", "outputs", "attrs")

    def __init__(self, kind, inputs, outputs, attrs):
        self.kind = kind
        self.inputs = inputs
        self.outputs = outputs
        self.attrs = attrs


class TFLiteGraph:
    """Parsed model: tensors, constant weights, op list, graph IO."""

    def __init__(self, data: bytes, name: str = "tflite"):
        if len(data) < 8:
            raise TFLiteError("file too short to be a flatbuffer")
        if data[4:8] != b"TFL3":
            raise TFLiteError(
                f"not a tflite flatbuffer (identifier {data[4:8]!r}, "
                "expected b'TFL3')")
        self.name = name
        fb = _FB(data)
        model = fb.root()
        opcodes = []
        for oc in fb.f_vec_tabs(model, 1):
            # effective builtin code: max of the deprecated int8 field (0)
            # and the extended int32 field (3) — the schema's own rule for
            # codes above 127
            opcodes.append(max(fb.f_i8(oc, 0), fb.f_i32(oc, 3)))
        buffers = [fb.f_vec_bytes(b, 0) for b in fb.f_vec_tabs(model, 4)]
        subgraphs = fb.f_vec_tabs(model, 2)
        if not subgraphs:
            raise TFLiteError("model has no subgraph")
        sg = subgraphs[0]

        self.shapes: List[List[int]] = []
        self.dtypes: List[np.dtype] = []
        self.tensor_names: List[str] = []
        self.constants: Dict[int, np.ndarray] = {}
        #: graph-IO quantization: tensor idx -> (scale, zero_point, dtype)
        #: for integer activation tensors (dequantized-execution contract)
        self.io_quant: Dict[int, tuple] = {}
        for idx, t in enumerate(fb.f_vec_tabs(sg, 0)):
            shape = fb.f_vec_i32(t, 0) or []
            tcode = fb.f_i8(t, 1, 0)
            if tcode not in _TENSOR_DTYPES:
                raise TFLiteError(
                    f"tensor {idx} ({fb.f_str(t, 3)}): unsupported tensor "
                    f"type code {tcode}")
            dt = np.dtype(_TENSOR_DTYPES[tcode])
            tname = fb.f_str(t, 3)
            self.shapes.append(shape)
            self.dtypes.append(dt)
            self.tensor_names.append(tname)
            q = fb.f_tab(t, 4)
            scale = fb.f_vec_f32(q, 2) if q is not None else None
            bufidx = fb.f_u32(t, 2, 0)
            raw = buffers[bufidx] if bufidx < len(buffers) else None
            if scale and not raw and np.issubdtype(dt, np.integer):
                # Quantized ACTIVATION (fully-quantized graph): the
                # interior runs float (dequantized execution, module
                # docstring); only per-tensor scales make sense here.
                zp = fb.f_vec_i64(q, 3) or [0]
                if len(scale) != 1:
                    raise TFLiteError(
                        f"tensor {idx} ({tname!r}): per-axis activation "
                        "quantization is not meaningful; file corrupt?")
                self.io_quant[idx] = (float(scale[0]), int(zp[0]), dt)
            if raw:
                arr = np.frombuffer(raw, dtype=dt)
                arr = arr.reshape(shape) if shape else arr
                # Only INTEGER weights dequantize; some converters leave a
                # stale scale on already-float tensors (schema-legal), and
                # re-scaling those would silently corrupt them.
                if scale and np.issubdtype(dt, np.integer):
                    arr = self._dequantize(fb, q, arr, scale, tname)
                    self.dtypes[idx] = np.dtype(np.float32)
                self.constants[idx] = arr

        self.inputs = fb.f_vec_i32(sg, 1) or []
        self.outputs = fb.f_vec_i32(sg, 2) or []
        self.ops: List[_Op] = []
        for op in fb.f_vec_tabs(sg, 3):
            oci = fb.f_u32(op, 0, 0)
            code = opcodes[oci]
            kind = _OP_NAMES.get(code)
            if kind is None:
                raise TFLiteError(
                    f"unsupported builtin operator code {code} "
                    f"(supported: {sorted(_OP_NAMES.values())})")
            ins = fb.f_vec_i32(op, 1) or []
            outs = fb.f_vec_i32(op, 2) or []
            bo = fb.f_tab(op, 4)
            self.ops.append(_Op(kind, ins, outs, self._attrs(fb, kind, bo)))

    @staticmethod
    def _dequantize(fb: _FB, q: int, arr: np.ndarray, scale, tname: str):
        """int8/uint8 weights -> float32 via (q - zero_point) * scale,
        per-tensor or per-axis (quantized_dimension)."""
        zp = fb.f_vec_i64(q, 3) or [0] * len(scale)
        axis = fb.f_i32(q, 6, 0)
        s = np.asarray(scale, np.float32)
        z = np.asarray(zp, np.float32)
        if s.size == 1:
            return (arr.astype(np.float32) - z[0]) * s[0]
        if arr.ndim == 0 or arr.shape[axis] != s.size:
            raise TFLiteError(
                f"tensor {tname!r}: per-axis scale count {s.size} does not "
                f"match dim {axis} of shape {arr.shape}")
        bshape = [1] * arr.ndim
        bshape[axis] = s.size
        return ((arr.astype(np.float32) - z.reshape(bshape))
                * s.reshape(bshape))

    @staticmethod
    def _attrs(fb: _FB, kind: str, bo: Optional[int]) -> Dict:
        """Decode the builtin-options table for ``kind`` (field ids from the
        public schema.fbs; all fields default like the schema does)."""
        a: Dict = {}
        if kind in ("CONV_2D",):
            a["padding"] = _PADDING[fb.f_i8(bo, 0, 0)] if bo else "SAME"
            a["strides"] = (fb.f_i32(bo, 2, 1), fb.f_i32(bo, 1, 1)) if bo else (1, 1)
            a["act"] = fb.f_i8(bo, 3, 0) if bo else 0
            a["dilation"] = (fb.f_i32(bo, 5, 1), fb.f_i32(bo, 4, 1)) if bo else (1, 1)
        elif kind == "DEPTHWISE_CONV_2D":
            a["padding"] = _PADDING[fb.f_i8(bo, 0, 0)] if bo else "SAME"
            a["strides"] = (fb.f_i32(bo, 2, 1), fb.f_i32(bo, 1, 1)) if bo else (1, 1)
            a["act"] = fb.f_i8(bo, 4, 0) if bo else 0
            a["dilation"] = (fb.f_i32(bo, 6, 1), fb.f_i32(bo, 5, 1)) if bo else (1, 1)
        elif kind in ("AVERAGE_POOL_2D", "MAX_POOL_2D"):
            a["padding"] = _PADDING[fb.f_i8(bo, 0, 0)] if bo else "SAME"
            a["strides"] = (fb.f_i32(bo, 2, 1), fb.f_i32(bo, 1, 1)) if bo else (1, 1)
            a["filter"] = (fb.f_i32(bo, 4, 1), fb.f_i32(bo, 3, 1)) if bo else (1, 1)
            a["act"] = fb.f_i8(bo, 5, 0) if bo else 0
        elif kind == "FULLY_CONNECTED":
            a["act"] = fb.f_i8(bo, 0, 0) if bo else 0
            a["keep_num_dims"] = fb.f_bool(bo, 2, False) if bo else False
        elif kind == "SOFTMAX":
            a["beta"] = fb.f_f32(bo, 0, 1.0) if bo else 1.0
        elif kind == "RESHAPE":
            a["new_shape"] = fb.f_vec_i32(bo, 0) if bo else None
        elif kind in ("ADD", "SUB", "MUL", "DIV"):
            a["act"] = fb.f_i8(bo, 0, 0) if bo else 0
        elif kind == "RESIZE_BILINEAR":
            a["align_corners"] = fb.f_bool(bo, 2, False) if bo else False
            a["half_pixel"] = fb.f_bool(bo, 3, False) if bo else False
        elif kind == "SPACE_TO_DEPTH":
            a["block"] = fb.f_i32(bo, 0, 1) if bo else 1
        elif kind == "CONCATENATION":
            a["axis"] = fb.f_i32(bo, 0, 0) if bo else 0
            a["act"] = fb.f_i8(bo, 1, 0) if bo else 0
        elif kind == "MEAN":
            a["keep_dims"] = fb.f_bool(bo, 0, False) if bo else False
        elif kind == "SQUEEZE":
            a["squeeze_dims"] = fb.f_vec_i32(bo, 0) if bo else None
        return a


# ---------------------------------------------------------------------------
# JAX execution
# ---------------------------------------------------------------------------

#: per-op input positions that are STATIC metadata (shapes/axes/paddings),
#: not data: they must resolve to concrete graph constants at trace time —
#: reading them through the traced params pytree would crash under jit.
_STATIC_OPERANDS = {"RESHAPE": (1,), "PAD": (1,), "MEAN": (1,),
                    "TRANSPOSE": (1,), "RESIZE_BILINEAR": (1,)}


def _resize_bilinear(x, oh: int, ow: int, align_corners: bool,
                     half_pixel: bool):
    """tflite ResizeBilinear semantics (all three coordinate mappings)."""
    import jax.numpy as jnp

    h, w = x.shape[1], x.shape[2]

    def coords(o, n):
        i = jnp.arange(o, dtype=jnp.float32)
        if align_corners and o > 1:
            return i * (n - 1) / (o - 1)
        if half_pixel:
            return jnp.maximum((i + 0.5) * n / o - 0.5, 0.0)
        return i * n / o

    yf = coords(oh, h)
    xf = coords(ow, w)
    y0 = jnp.clip(jnp.floor(yf).astype(jnp.int32), 0, h - 1)
    x0 = jnp.clip(jnp.floor(xf).astype(jnp.int32), 0, w - 1)
    y1 = jnp.minimum(y0 + 1, h - 1)
    x1 = jnp.minimum(x0 + 1, w - 1)
    wy = (yf - y0)[None, :, None, None]
    wx = (xf - x0)[None, None, :, None]
    f = x.astype(jnp.float32)
    top = f[:, y0][:, :, x0] * (1 - wx) + f[:, y0][:, :, x1] * wx
    bot = f[:, y1][:, :, x0] * (1 - wx) + f[:, y1][:, :, x1] * wx
    return (top * (1 - wy) + bot * wy).astype(x.dtype)


def _run_op(op: _Op, get, const, attrs_name: str):
    """Execute one op; ``get(idx)`` resolves a tensor index to a (possibly
    traced) array, ``const(idx)`` to a concrete numpy constant."""
    import jax.numpy as jnp
    from jax import lax

    k, a = op.kind, op.attrs
    if k == "CONV_2D":
        x, w = get(op.inputs[0]), get(op.inputs[1])
        # tflite kernel layout OHWI -> XLA HWIO
        y = lax.conv_general_dilated(
            x, jnp.transpose(w, (1, 2, 3, 0)),
            window_strides=a["strides"], padding=a["padding"],
            rhs_dilation=a["dilation"],
            dimension_numbers=("NHWC", "HWIO", "NHWC"))
        if len(op.inputs) > 2 and op.inputs[2] >= 0:
            y = y + get(op.inputs[2])
        return _act_fn(a["act"], attrs_name)(y)
    if k == "DEPTHWISE_CONV_2D":
        x, w = get(op.inputs[0]), get(op.inputs[1])
        cin = x.shape[-1]
        # tflite layout [1, kh, kw, cin*mult] -> HWIO with I=1, groups=cin
        y = lax.conv_general_dilated(
            x, jnp.transpose(w, (1, 2, 0, 3)),
            window_strides=a["strides"], padding=a["padding"],
            rhs_dilation=a["dilation"], feature_group_count=cin,
            dimension_numbers=("NHWC", "HWIO", "NHWC"))
        if len(op.inputs) > 2 and op.inputs[2] >= 0:
            y = y + get(op.inputs[2])
        return _act_fn(a["act"], attrs_name)(y)
    if k == "FULLY_CONNECTED":
        x, w = get(op.inputs[0]), get(op.inputs[1])
        if not a["keep_num_dims"] and x.ndim != 2:
            x = x.reshape(-1, w.shape[1])
        y = x @ w.T
        if len(op.inputs) > 2 and op.inputs[2] >= 0:
            y = y + get(op.inputs[2])
        return _act_fn(a["act"], attrs_name)(y)
    if k in ("AVERAGE_POOL_2D", "MAX_POOL_2D"):
        x = get(op.inputs[0])
        fh, fw = a["filter"]
        sh, sw = a["strides"]
        dims, strides = (1, fh, fw, 1), (1, sh, sw, 1)
        if k == "MAX_POOL_2D":
            y = lax.reduce_window(x, -jnp.inf, lax.max, dims, strides,
                                  a["padding"])
        else:
            s = lax.reduce_window(x, 0.0, lax.add, dims, strides,
                                  a["padding"])
            # SAME average pooling divides by the ACTUAL window size at the
            # edges (tflite semantics): count via the same reduce on ones
            ones = jnp.ones_like(x)
            cnt = lax.reduce_window(ones, 0.0, lax.add, dims, strides,
                                    a["padding"])
            y = s / cnt
        return _act_fn(a["act"], attrs_name)(y)
    if k == "RESHAPE":
        x = get(op.inputs[0])
        shape = a["new_shape"]
        if shape is None and len(op.inputs) > 1:
            shape = [int(v) for v in const(op.inputs[1])]
        if shape is None:
            raise TFLiteError(f"{attrs_name}: RESHAPE without a target shape")
        return x.reshape(shape)
    if k == "SOFTMAX":
        import jax

        return jax.nn.softmax(get(op.inputs[0]) * a["beta"], axis=-1)
    if k in ("ADD", "SUB", "MUL", "DIV"):
        import operator

        fn = {"ADD": operator.add, "SUB": operator.sub,
              "MUL": operator.mul, "DIV": operator.truediv}[k]
        z = fn(get(op.inputs[0]), get(op.inputs[1]))
        return _act_fn(a["act"], attrs_name)(z)
    if k == "TRANSPOSE":
        perm = [int(v) for v in const(op.inputs[1]).ravel()]
        return jnp.transpose(get(op.inputs[0]), perm)
    if k == "SPACE_TO_DEPTH":
        x = get(op.inputs[0])
        b = a["block"]
        B, H, W, C = x.shape
        x = x.reshape(B, H // b, b, W // b, b, C)
        return x.transpose(0, 1, 3, 2, 4, 5).reshape(
            B, H // b, W // b, C * b * b)
    if k == "RESIZE_BILINEAR":
        x = get(op.inputs[0])
        oh, ow = (int(v) for v in const(op.inputs[1]).ravel())
        return _resize_bilinear(x, oh, ow, a["align_corners"],
                                a["half_pixel"])
    if k == "CONCATENATION":
        parts = [get(i) for i in op.inputs]
        z = jnp.concatenate(parts, axis=a["axis"])
        return _act_fn(a["act"], attrs_name)(z)
    if k == "PAD":
        x = get(op.inputs[0])
        pads = const(op.inputs[1]).reshape(-1, 2)
        return jnp.pad(x, [(int(lo), int(hi)) for lo, hi in pads])
    if k == "MEAN":
        x = get(op.inputs[0])
        axes = [int(v) for v in const(op.inputs[1]).ravel()]
        return jnp.mean(x, axis=tuple(axes), keepdims=a["keep_dims"])
    if k == "SQUEEZE":
        x = get(op.inputs[0])
        dims = a["squeeze_dims"]
        axis = tuple(dims) if dims else None
        return jnp.squeeze(x, axis=axis)
    if k == "RELU":
        return jnp.maximum(get(op.inputs[0]), 0)
    if k == "RELU6":
        return jnp.clip(get(op.inputs[0]), 0, 6)
    if k == "LOGISTIC":
        import jax

        return jax.nn.sigmoid(get(op.inputs[0]))
    if k == "TANH":
        return jnp.tanh(get(op.inputs[0]))
    raise TFLiteError(f"unsupported op {k}")  # pragma: no cover


def load_bundle(path: str, opts: Optional[Dict[str, str]] = None) -> ModelBundle:
    """Parse a .tflite file into a jittable :class:`ModelBundle`.

    The file's weight tensors become the bundle's params pytree (so they
    ride HBM and donation/sharding machinery like any zoo model); the graph
    walk happens at trace time, producing one fused XLA program.

    ``custom=param_dtype:bfloat16`` casts the float weights (e.g. to feed
    the MXU at 2 bytes/param); other option keys are rejected so a typo'd
    pipeline string fails loudly instead of being silently ignored.
    """
    opts = dict(opts or {})
    param_dtype = opts.pop("param_dtype", None)
    if opts:
        raise TFLiteError(
            f"{path}: unsupported options {sorted(opts)} "
            "(tflite ingestion supports: param_dtype)")
    with open(path, "rb") as f:
        data = f.read()
    g = TFLiteGraph(data, name=path)
    # Static-metadata operands (reshape shapes, pad widths, mean axes) stay
    # OUT of params: they must be concrete at trace time, and shipping them
    # to device would be pointless anyway.  A constant ALSO consumed as
    # data by some other op keeps its params slot.
    static_ids = set()
    data_ids = set()
    for op in g.ops:
        static_pos = _STATIC_OPERANDS.get(op.kind, ())
        for pos, idx in enumerate(op.inputs):
            (static_ids if pos in static_pos else data_ids).add(idx)
    params = {f"t{i}": np.asarray(v) for i, v in g.constants.items()
              if i not in (static_ids - data_ids)}
    if param_dtype:
        from ..core.types import dtype_from_name

        dt = dtype_from_name(str(param_dtype))
        params = {k: v.astype(dt) if np.issubdtype(v.dtype, np.floating)
                  else v for k, v in params.items()}

    def apply_fn(p, *inputs):
        import jax.numpy as jnp

        if len(inputs) != len(g.inputs):
            raise TFLiteError(
                f"{path}: expected {len(g.inputs)} input(s), got "
                f"{len(inputs)}")
        env: Dict[int, object] = {}
        for idx, arr in zip(g.inputs, inputs):
            if idx in g.io_quant:
                # fully-quantized graph boundary: integer in, float inside
                scale, zp, _ = g.io_quant[idx]
                arr = (jnp.asarray(arr).astype(jnp.float32) - zp) * scale
            env[idx] = arr

        def get(i):
            if i in env:
                return env[i]
            key = f"t{i}"
            if key in p:
                return p[key]
            raise TFLiteError(
                f"{path}: tensor {i} ({g.tensor_names[i]!r}) used before "
                "produced — graph is not topologically ordered?")

        def const(i):
            if i not in g.constants:
                raise TFLiteError(
                    f"{path}: tensor {i} ({g.tensor_names[i]!r}) must be a "
                    "graph constant (shapes/axes/paddings are static under "
                    "XLA; dynamic values are unsupported)")
            return np.asarray(g.constants[i])

        for op in g.ops:
            outs = op.outputs
            res = _run_op(op, get, const, path)
            env[outs[0]] = res

        def requant(i):
            x = env[i]
            if i not in g.io_quant:
                return x
            scale, zp, dt = g.io_quant[i]
            info = np.iinfo(dt)
            q = jnp.round(jnp.asarray(x).astype(jnp.float32) / scale) + zp
            return jnp.clip(q, info.min, info.max).astype(dt)

        results = tuple(requant(i) for i in g.outputs)
        return results if len(results) > 1 else results[0]

    in_spec = TensorsSpec(tuple(
        TensorSpec.from_shape(g.shapes[i], g.dtypes[i], g.tensor_names[i])
        for i in g.inputs))
    out_spec = TensorsSpec(tuple(
        TensorSpec.from_shape(g.shapes[i], g.dtypes[i], g.tensor_names[i])
        for i in g.outputs))
    return ModelBundle(apply_fn=apply_fn, params=params, in_spec=in_spec,
                       out_spec=out_spec, name=path)
