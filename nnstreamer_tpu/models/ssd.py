"""SSD-MobileNet object detector — benchmark config #2.

Reference analog: the reference runs ``ssd_mobilenet_v2_coco.tflite``
through the tflite sub-plugin and decodes with
``tensordec-boundingbox.c`` mode ssd (SURVEY §2.5, BASELINE config #2).
TPU-first design notes:

* MobileNet-v1-style depthwise-separable backbone (NHWC, bfloat16, MXU
  tiling as models/mobilenet.py) with two detection scales; SSD extras are
  stride-2 separable convs.
* **Anchor decode lives inside the model** (like tflite SSD graphs embed
  their postprocess): apply() emits corner-format normalized boxes (B,N,4)
  and per-class scores (B,N,C) — exactly the ``bounding_boxes`` decoder's
  ssd contract, so the whole thing fuses into one XLA program and only the
  final small (N,4)+(N,C) tensors cross to host for NMS/overlay.
* Anchors are precomputed numpy constants baked into the jitted program
  (XLA folds them); scale/aspect grid matches the standard SSD recipe.

Weights are deterministic-random (no egress); real checkpoints map onto the
same pytree.
"""

from __future__ import annotations

import functools
from typing import Dict, List, Tuple

import numpy as np

from ..core.types import TensorsSpec
from .backbone import (
    he_conv,
    make_ops,
    rounded,
    sep_block_params,
    sep_block_pspecs,
    stem_params,
    stem_pspecs,
)
from .zoo import ModelBundle, register_model

# Backbone: (stride, out_ch) separable blocks after the stem (stride-2 conv).
_BACKBONE: Tuple[Tuple[int, int], ...] = (
    (1, 64), (2, 128), (1, 128), (2, 256), (1, 256), (2, 512),
    (1, 512), (1, 512),          # feature map A: stride 16
)
_EXTRA: Tuple[Tuple[int, int], ...] = (
    (2, 512), (1, 512),          # feature map B: stride 32
)
_ASPECTS = (1.0, 2.0, 0.5)


def _anchors_for(fm: int, scale: float, next_scale: float) -> np.ndarray:
    """SSD anchor grid for one fm x fm feature map -> (fm*fm*A, 4) cxcywh.

    Layout is cell-major (y, x, a) to match the head's
    ``(B,H,W,A*4) -> (B, H*W*A, 4)`` reshape: anchor index = (y*fm + x)*A + a.
    """
    centers = (np.arange(fm, dtype=np.float32) + 0.5) / fm
    cy, cx = np.meshgrid(centers, centers, indexing="ij")
    per_aspect = []
    for a in _ASPECTS:
        w = scale * np.sqrt(a)
        h = scale / np.sqrt(a)
        per_aspect.append(np.stack(
            [cx, cy, np.full_like(cx, w), np.full_like(cy, h)], axis=-1))
    s_extra = float(np.sqrt(scale * next_scale))
    per_aspect.append(np.stack(
        [cx, cy, np.full_like(cx, s_extra), np.full_like(cy, s_extra)],
        axis=-1))
    grid = np.stack(per_aspect, axis=2)  # (fm, fm, A, 4)
    return grid.reshape(-1, 4)


def num_anchors_per_cell() -> int:
    return len(_ASPECTS) + 1


def build_anchors(size: int) -> np.ndarray:
    """All anchors (N,4) cxcywh normalized, for strides 16 and 32."""
    fm_a, fm_b = size // 16, size // 32
    return np.concatenate(
        [_anchors_for(fm_a, 0.35, 0.6), _anchors_for(fm_b, 0.6, 0.9)], axis=0
    ).astype(np.float32)


def init_params(classes: int = 91, width: float = 1.0, seed: int = 0) -> Dict:
    import jax

    keys = iter(jax.random.split(jax.random.PRNGKey(seed), 80))
    params: Dict = {"stem": stem_params(keys, 3, rounded(32, width))}
    cin = rounded(32, width)
    for i, (_s, ch) in enumerate(_BACKBONE):
        params[f"block{i}"] = sep_block_params(keys, cin, rounded(ch, width))
        cin = rounded(ch, width)
    ca = cin
    for i, (_s, ch) in enumerate(_EXTRA):
        params[f"extra{i}"] = sep_block_params(keys, cin, rounded(ch, width))
        cin = rounded(ch, width)
    cb = cin
    A = num_anchors_per_cell()
    # Class-head bias at the standard low-prior init (-log((1-pi)/pi),
    # pi=0.01 — the RetinaNet/SSD convention): background dominates, so
    # even with random backbone weights the sigmoid scores sit near the
    # prior instead of 0.5 and detections are sparse like a trained
    # detector's.  Without it the synthetic model "detects" ~70 objects
    # per frame and benchmarks measure host NMS, not the pipeline.
    prior_bias = float(-np.log((1 - 0.01) / 0.01))
    for tag, ch in (("a", ca), ("b", cb)):
        params[f"head_{tag}"] = {
            "box": he_conv(next(keys), 3, 3, ch, A * 4),
            "box_bias": np.zeros((A * 4,), np.float32),
            "cls": he_conv(next(keys), 3, 3, ch, A * classes),
            "cls_bias": np.full((A * classes,), prior_bias, np.float32),
        }
    return params


def param_pspecs() -> Dict:
    from jax.sharding import PartitionSpec as P

    specs: Dict = {"stem": stem_pspecs()}
    for i in range(len(_BACKBONE)):
        specs[f"block{i}"] = sep_block_pspecs()
    for i in range(len(_EXTRA)):
        specs[f"extra{i}"] = sep_block_pspecs()
    for tag in ("a", "b"):
        specs[f"head_{tag}"] = {"box": P(), "box_bias": P(),
                                "cls": P(), "cls_bias": P()}
    return specs


def apply(params, x, *, anchors, classes: int, compute_dtype="bfloat16"):
    """NHWC image batch -> (boxes (B,N,4) corner [0,1], scores (B,N,C))."""
    import jax.numpy as jnp
    from jax import lax

    cdt = jnp.dtype(compute_dtype)
    x = x.astype(cdt)
    conv2d, sbr, sep = make_ops(cdt)

    p = params["stem"]
    x = sbr(conv2d(x, p["w"], 2), p["scale"], p["bias"])
    for i, (stride, _ch) in enumerate(_BACKBONE):
        x = sep(x, params[f"block{i}"], stride)
    fm_a = x
    for i, (stride, _ch) in enumerate(_EXTRA):
        x = sep(x, params[f"extra{i}"], stride)
    fm_b = x

    B = x.shape[0]
    A = num_anchors_per_cell()

    def head(fm, hp):
        box = conv2d(fm, hp["box"], 1) + hp["box_bias"].astype(cdt)
        cls = conv2d(fm, hp["cls"], 1) + hp["cls_bias"].astype(cdt)
        return (box.reshape(B, -1, 4).astype(jnp.float32),
                cls.reshape(B, -1, classes).astype(jnp.float32))

    box_a, cls_a = head(fm_a, params["head_a"])
    box_b, cls_b = head(fm_b, params["head_b"])
    deltas = jnp.concatenate([box_a, box_b], axis=1)  # (B,N,4)
    logits = jnp.concatenate([cls_a, cls_b], axis=1)  # (B,N,C)

    # Anchor decode (tflite SSD convention: deltas scaled by 10/5).
    anc = jnp.asarray(anchors)  # (N,4) cx,cy,w,h
    cx = deltas[..., 0] / 10.0 * anc[:, 2] + anc[:, 0]
    cy = deltas[..., 1] / 10.0 * anc[:, 3] + anc[:, 1]
    w = jnp.exp(jnp.clip(deltas[..., 2] / 5.0, -10.0, 10.0)) * anc[:, 2]
    h = jnp.exp(jnp.clip(deltas[..., 3] / 5.0, -10.0, 10.0)) * anc[:, 3]
    boxes = jnp.stack(
        [cx - w / 2, cy - h / 2, cx + w / 2, cy + h / 2], axis=-1)
    boxes = jnp.clip(boxes, 0.0, 1.0)
    import jax

    scores = jax.nn.sigmoid(logits)
    return boxes, scores


@register_model("ssd_mobilenet")
def _ssd(opts: Dict[str, str]) -> ModelBundle:
    classes = int(opts.get("classes", 91))
    width = float(opts.get("width", 1.0))
    seed = int(opts.get("seed", 0))
    size = int(opts.get("size", 320))
    batch = int(opts.get("batch", 1))
    dtype = opts.get("dtype", "bfloat16")
    if size % 32:
        raise ValueError(f"ssd size must be a multiple of 32, got {size}")

    params = init_params(classes=classes, width=width, seed=seed)
    anchors = build_anchors(size)
    apply_fn = functools.partial(
        apply, anchors=anchors, classes=classes, compute_dtype=dtype)
    n = anchors.shape[0]
    return ModelBundle(
        apply_fn=apply_fn,
        params=params,
        in_spec=TensorsSpec.from_string(f"3:{size}:{size}:{batch}", "float32"),
        out_spec=TensorsSpec.from_string(
            f"4:{n}:{batch},{classes}:{n}:{batch}", "float32,float32"),
        param_pspecs=param_pspecs(),
        name="ssd_mobilenet",
    )
