"""Shared MobileNet-style backbone building blocks.

One copy of the depthwise-separable conv recipe used by mobilenet.py (config
#1), ssd.py (config #2) and posenet.py (config #3) — param init, apply-time
conv helpers, and PartitionSpecs.  All NHWC, bfloat16-by-default, sized for
MXU lane tiling (channels kept multiples of 8).
"""

from __future__ import annotations

from typing import Dict

import numpy as np


def rounded(ch: int, width: float) -> int:
    """Width-multiplied channel count, kept a multiple of 8 for lane tiling."""
    return max(8, int(ch * width + 4) // 8 * 8)


def fm_size(size: int, stride: int) -> int:
    """SAME-padded feature-map edge after ``log2(stride)`` stride-2 convs.

    ceil-division chain, NOT ``size // stride`` — they differ whenever
    ``size`` is not a multiple of ``stride`` (e.g. posenet's 257x257).
    """
    n = stride.bit_length() - 1
    assert 1 << n == stride, f"stride must be a power of 2, got {stride}"
    for _ in range(n):
        size = -(-size // 2)
    return size


def he_conv(key, kh: int, kw: int, cin: int, cout: int) -> np.ndarray:
    """He-normal conv kernel (HWIO)."""
    import jax

    w = jax.random.normal(key, (kh, kw, cin, cout), np.float32)
    return w * np.sqrt(2.0 / (kh * kw * cin))


def stem_params(keys, cin: int, cout: int) -> Dict:
    return {
        "w": he_conv(next(keys), 3, 3, cin, cout),
        "scale": np.ones((cout,), np.float32),
        "bias": np.zeros((cout,), np.float32),
    }


def sep_block_params(keys, cin: int, cout: int) -> Dict:
    """Depthwise-separable block params: dw 3x3 (grouped) + pw 1x1."""
    return {
        "dw": he_conv(next(keys), 3, 3, 1, cin),
        "dw_scale": np.ones((cin,), np.float32),
        "dw_bias": np.zeros((cin,), np.float32),
        "pw": he_conv(next(keys), 1, 1, cin, cout),
        "pw_scale": np.ones((cout,), np.float32),
        "pw_bias": np.zeros((cout,), np.float32),
    }


def stem_pspecs():
    from jax.sharding import PartitionSpec as P

    return {"w": P(None, None, None, "model"), "scale": P("model"),
            "bias": P("model")}


def sep_block_pspecs():
    """TP sharding: pointwise kernels shard over output channels ("model"
    axis); depthwise/scale/bias replicate (tiny)."""
    from jax.sharding import PartitionSpec as P

    return {
        "dw": P(), "dw_scale": P(), "dw_bias": P(),
        "pw": P(None, None, None, "model"),
        "pw_scale": P("model"), "pw_bias": P("model"),
    }


def make_ops(compute_dtype):
    """Apply-time helpers closed over the compute dtype:
    (conv2d, scale_bias_relu6, sep_block)."""
    import jax.numpy as jnp
    from jax import lax

    cdt = jnp.dtype(compute_dtype)

    def conv2d(x, w, stride, groups=1):
        return lax.conv_general_dilated(
            x, w.astype(cdt), (stride, stride), "SAME",
            dimension_numbers=("NHWC", "HWIO", "NHWC"),
            feature_group_count=groups)

    def sbr(x, scale, bias):
        return jnp.clip(x * scale.astype(cdt) + bias.astype(cdt), 0.0, 6.0)

    def sep(x, p, stride):
        x = conv2d(x, p["dw"], stride, groups=x.shape[-1])
        x = sbr(x, p["dw_scale"], p["dw_bias"])
        x = conv2d(x, p["pw"], 1)
        return sbr(x, p["pw_scale"], p["pw_bias"])

    return conv2d, sbr, sep
