"""``.onnx`` model-file ingestion: protobuf wire parse -> JAX ``ModelBundle``.

Reference analog: the onnxruntime sub-plugin
(``ext/nnstreamer/tensor_filter/tensor_filter_onnxruntime.cc``, SURVEY
§2.4 [UNVERIFIED]) loads ``.onnx`` files into ORT.  No ORT exists in this
environment and none is needed: an ONNX graph is a static dataflow whose
natural executor here is XLA.  The file is parsed with a minimal
hand-rolled protobuf *wire-format* reader (varints + length-delimited
fields — the format is public and tiny; no protoc, no onnx package), and
the graph walks once at trace time into a single jittable JAX closure over
the file's real weights.  ``tensor_filter framework=jax model=/m.onnx``
then fuses into the pipeline's XLA program like any zoo model.

Execution stays in ONNX's native NCHW layout (lax convolutions take
dimension_numbers directly, so no transposes are inserted).  Supported op
set — the torchvision-class CNN vocabulary: Conv, Gemm, MatMul, Relu,
Sigmoid, Tanh, Clip, Softmax, MaxPool, AveragePool, GlobalAveragePool,
BatchNormalization, Add, Sub, Mul, Div, Concat, Reshape, Flatten,
Transpose, Pad, ReduceMean, Squeeze, Unsqueeze, Constant, Identity.

Fixtures in tests/test_onnx.py are exported by torch's own ONNX exporter
(a fully independent serializer), and numerics are compared against the
torch module — a true third-party interop check.
"""

from __future__ import annotations

import struct
from typing import Dict, List, Optional, Tuple

import numpy as np

from ..core.types import TensorSpec, TensorsSpec
from .zoo import ModelBundle


class ONNXError(ValueError):
    pass


# ---------------------------------------------------------------------------
# Minimal protobuf wire reader
# ---------------------------------------------------------------------------

def _varint(data: bytes, pos: int) -> Tuple[int, int]:
    result = 0
    shift = 0
    while True:
        if pos >= len(data):
            raise ONNXError("truncated varint")
        b = data[pos]
        pos += 1
        result |= (b & 0x7F) << shift
        if not b & 0x80:
            return result, pos
        shift += 7
        if shift > 70:
            raise ONNXError("varint too long")


def _signed(v: int) -> int:
    """protobuf int64: negatives ride as 10-byte two's-complement varints."""
    v &= (1 << 64) - 1
    return v - (1 << 64) if v >= (1 << 63) else v


def _fields(data: bytes):
    """Yield (field_number, wire_type, value); value is int for varint/fixed
    and bytes for length-delimited."""
    pos = 0
    n = len(data)
    while pos < n:
        tag, pos = _varint(data, pos)
        fnum, wtype = tag >> 3, tag & 7
        if wtype == 0:
            v, pos = _varint(data, pos)
            yield fnum, wtype, v
        elif wtype == 1:
            yield fnum, wtype, struct.unpack_from("<Q", data, pos)[0]
            pos += 8
        elif wtype == 2:
            ln, pos = _varint(data, pos)
            yield fnum, wtype, data[pos:pos + ln]
            pos += ln
        elif wtype == 5:
            yield fnum, wtype, struct.unpack_from("<I", data, pos)[0]
            pos += 4
        else:
            raise ONNXError(f"unsupported wire type {wtype}")


def _packed_varints(val, wtype) -> List[int]:
    if wtype == 0:
        return [_signed(val)]
    out = []
    pos = 0
    while pos < len(val):
        v, pos = _varint(val, pos)
        out.append(_signed(v))
    return out


# ---------------------------------------------------------------------------
# ONNX schema readers (field numbers from the public onnx.proto)
# ---------------------------------------------------------------------------

_TENSOR_DTYPES = {
    1: np.float32, 2: np.uint8, 3: np.int8, 4: np.uint16, 5: np.int16,
    6: np.int32, 7: np.int64, 9: np.bool_, 10: np.float16, 11: np.float64,
    12: np.uint32, 13: np.uint64,
}


def _tensor_proto(data: bytes, what: str) -> Tuple[str, np.ndarray]:
    dims: List[int] = []
    dtype_code = 1
    raw = None
    floats: List[float] = []
    i32s: List[int] = []
    i64s: List[int] = []
    name = ""
    for fnum, wtype, val in _fields(data):
        if fnum == 1:
            dims.extend(_packed_varints(val, wtype))
        elif fnum == 2:
            dtype_code = val
        elif fnum == 4:  # float_data
            if wtype == 2:
                floats.extend(struct.unpack(f"<{len(val) // 4}f", val))
            else:
                floats.append(struct.unpack("<f", struct.pack("<I", val))[0])
        elif fnum == 5:
            i32s.extend(_packed_varints(val, wtype))
        elif fnum == 7:
            i64s.extend(_packed_varints(val, wtype))
        elif fnum == 8:
            name = val.decode("utf-8", "replace")
        elif fnum == 9:
            raw = val
    if dtype_code not in _TENSOR_DTYPES:
        raise ONNXError(f"{what}: tensor {name!r} has unsupported "
                        f"data_type {dtype_code}")
    dt = np.dtype(_TENSOR_DTYPES[dtype_code])
    if raw is not None:
        arr = np.frombuffer(raw, dtype=dt)
    elif floats:
        arr = np.asarray(floats, dt)
    elif i64s:
        arr = np.asarray(i64s, dt)
    elif i32s:
        arr = np.asarray(i32s, dt)
    else:
        arr = np.zeros(0, dt)
    return name, arr.reshape(dims) if dims else arr.reshape(())


def _value_info(data: bytes) -> Tuple[str, Optional[np.dtype], List[int]]:
    """ValueInfoProto -> (name, dtype, dims); symbolic dims become 1."""
    name = ""
    dtype = None
    dims: List[int] = []
    for fnum, _w, val in _fields(data):
        if fnum == 1:
            name = val.decode("utf-8", "replace")
        elif fnum == 2:  # TypeProto
            for f2, _w2, v2 in _fields(val):
                if f2 != 1:  # tensor_type
                    continue
                for f3, _w3, v3 in _fields(v2):
                    if f3 == 1:  # elem_type
                        dtype = np.dtype(_TENSOR_DTYPES.get(v3, np.float32))
                    elif f3 == 2:  # TensorShapeProto
                        for f4, _w4, v4 in _fields(v3):
                            if f4 != 1:  # dim
                                continue
                            dim_value = 1
                            for f5, _w5, v5 in _fields(v4):
                                if f5 == 1:
                                    dim_value = _signed(v5)
                            dims.append(max(1, dim_value))
    return name, dtype, dims


class _Attr:
    __slots__ = ("f", "i", "s", "t", "floats", "ints")


def _attributes(node_fields) -> Dict[str, _Attr]:
    attrs: Dict[str, _Attr] = {}
    for data in node_fields:
        a = _Attr()
        a.f = a.i = a.s = a.t = None
        a.floats = []
        a.ints = []
        name = ""
        for fnum, wtype, val in _fields(data):
            if fnum == 1:
                name = val.decode("utf-8", "replace")
            elif fnum == 2:
                a.f = struct.unpack("<f", struct.pack("<I", val))[0]
            elif fnum == 3:
                a.i = _signed(val)
            elif fnum == 4:
                a.s = val.decode("utf-8", "replace")
            elif fnum == 5:
                a.t = _tensor_proto(val, "attribute")[1]
            elif fnum == 7:
                if wtype == 2:
                    a.floats.extend(
                        struct.unpack(f"<{len(val) // 4}f", val))
                else:
                    a.floats.append(
                        struct.unpack("<f", struct.pack("<I", val))[0])
            elif fnum == 8:
                a.ints.extend(_packed_varints(val, wtype))
        attrs[name] = a
    return attrs


class _Node:
    __slots__ = ("op", "inputs", "outputs", "attrs", "name")


class ONNXGraph:
    """Parsed .onnx model: initializers, node list, graph IO."""

    def __init__(self, data: bytes, name: str = "onnx"):
        self.name = name
        graph = None
        for fnum, _w, val in _fields(data):
            if fnum == 7:  # ModelProto.graph
                graph = val
        if graph is None:
            raise ONNXError(f"{name}: no GraphProto (not an ONNX file?)")
        self.initializers: Dict[str, np.ndarray] = {}
        self.nodes: List[_Node] = []
        inputs: List[Tuple[str, Optional[np.dtype], List[int]]] = []
        outputs: List[Tuple[str, Optional[np.dtype], List[int]]] = []
        for fnum, _w, val in _fields(graph):
            if fnum == 1:  # node
                n = _Node()
                n.inputs, n.outputs, attr_blobs = [], [], []
                n.op = ""
                n.name = ""
                for f2, _w2, v2 in _fields(val):
                    if f2 == 1:
                        n.inputs.append(v2.decode("utf-8", "replace"))
                    elif f2 == 2:
                        n.outputs.append(v2.decode("utf-8", "replace"))
                    elif f2 == 3:
                        n.name = v2.decode("utf-8", "replace")
                    elif f2 == 4:
                        n.op = v2.decode("utf-8", "replace")
                    elif f2 == 5:
                        attr_blobs.append(v2)
                n.attrs = _attributes(attr_blobs)
                self.nodes.append(n)
            elif fnum == 5:  # initializer
                tname, arr = _tensor_proto(val, name)
                self.initializers[tname] = arr
            elif fnum == 11:
                inputs.append(_value_info(val))
            elif fnum == 12:
                outputs.append(_value_info(val))
        # graph inputs exclude initializers (ONNX lists weights both ways
        # in old opsets)
        self.inputs = [(n, d, s) for n, d, s in inputs
                       if n not in self.initializers]
        self.outputs = outputs
        unsupported = sorted({n.op for n in self.nodes
                              if n.op not in _OPS})
        if unsupported:
            raise ONNXError(
                f"{name}: unsupported op(s) {unsupported} "
                f"(supported: {sorted(_OPS)})")


# ---------------------------------------------------------------------------
# JAX execution (NCHW-native)
# ---------------------------------------------------------------------------

def _conv(env, const, n):
    import jax.numpy as jnp
    from jax import lax

    x, w = env[n.inputs[0]], env[n.inputs[1]]
    a = n.attrs
    rank = w.ndim - 2
    strides = tuple(a["strides"].ints) if "strides" in a else (1,) * rank
    dil = tuple(a["dilations"].ints) if "dilations" in a else (1,) * rank
    group = a["group"].i if "group" in a else 1
    if "pads" in a:
        p = a["pads"].ints
        padding = [(p[i], p[i + rank]) for i in range(rank)]
    else:
        auto = a["auto_pad"].s if "auto_pad" in a else "NOTSET"
        if auto and auto.startswith("SAME"):
            # explicit per-dim pads: SAME_UPPER puts the extra element at
            # the end, SAME_LOWER at the beginning (lax "SAME" is UPPER
            # only, so both are computed here)
            padding = []
            for i in range(rank):
                size = x.shape[2 + i]
                eff_k = (w.shape[2 + i] - 1) * dil[i] + 1
                total = max((-(size // -strides[i]) - 1) * strides[i]
                            + eff_k - size, 0)
                lo = total // 2
                if auto == "SAME_LOWER":
                    padding.append((total - lo, lo))
                else:
                    padding.append((lo, total - lo))
        else:
            padding = [(0, 0)] * rank
    dn = ("NCHW", "OIHW", "NCHW") if rank == 2 else ("NCH", "OIH", "NCH")
    y = lax.conv_general_dilated(
        x, w, strides, padding, rhs_dilation=dil,
        feature_group_count=group, dimension_numbers=dn)
    if len(n.inputs) > 2:
        b = env[n.inputs[2]]
        y = y + b.reshape((1, -1) + (1,) * rank)
    return y


def _pool(env, const, n, kind):
    import jax.numpy as jnp
    from jax import lax

    x = env[n.inputs[0]]
    a = n.attrs
    k = tuple(a["kernel_shape"].ints)
    rank = len(k)
    strides = tuple(a["strides"].ints) if "strides" in a else (1,) * rank
    wdil = tuple(a["dilations"].ints) if "dilations" in a else (1,) * rank
    if "pads" in a:
        p = a["pads"].ints
        explicit = [[p[i], p[i + rank]] for i in range(rank)]
    else:
        explicit = [[0, 0] for _ in range(rank)]
    ceil_mode = "ceil_mode" in a and a["ceil_mode"].i == 1
    include = "count_include_pad" in a and a["count_include_pad"].i == 1
    ceil_ext = [0] * rank
    if ceil_mode:
        # grow the END pad so reduce_window (floor semantics) matches the
        # ceil output size
        for i in range(rank):
            size = x.shape[2 + i] + explicit[i][0] + explicit[i][1]
            eff_k = (k[i] - 1) * wdil[i] + 1
            out_ceil = -((size - eff_k) // -strides[i]) + 1
            ceil_ext[i] = (out_ceil - 1) * strides[i] + eff_k - size
    pad = [(0, 0), (0, 0)] + [(lo, hi + e) for (lo, hi), e
                              in zip(explicit, ceil_ext)]
    dims = (1, 1) + k
    strd = (1, 1) + strides
    wd = (1, 1) + wdil
    if kind == "max":
        return lax.reduce_window(x, -jnp.inf, lax.max, dims, strd, pad,
                                 window_dilation=wd)
    s = lax.reduce_window(x, 0.0, lax.add, dims, strd, pad,
                          window_dilation=wd)
    if include:
        # torch/ORT semantics: explicit pads count toward the divisor, the
        # implicit ceil extension does not — count ones over input +
        # explicit pads, reduce with only the ceil extension as padding
        ones = jnp.pad(jnp.ones_like(x),
                       [(0, 0), (0, 0)] + [tuple(e) for e in explicit],
                       constant_values=1.0)
        cnt = lax.reduce_window(
            ones, 0.0, lax.add, dims, strd,
            [(0, 0), (0, 0)] + [(0, e) for e in ceil_ext],
            window_dilation=wd)
    else:
        cnt = lax.reduce_window(jnp.ones_like(x), 0.0, lax.add, dims, strd,
                                pad, window_dilation=wd)
    cnt = jnp.maximum(cnt, 1.0)  # ceil pad can create all-pad windows
    return s / cnt


def _gemm(env, const, n):
    a = n.attrs
    A, B = env[n.inputs[0]], env[n.inputs[1]]
    if "transA" in a and a["transA"].i:
        A = A.T
    if "transB" in a and a["transB"].i:
        B = B.T
    y = (a["alpha"].f if "alpha" in a else 1.0) * (A @ B)
    if len(n.inputs) > 2:
        y = y + (a["beta"].f if "beta" in a else 1.0) * env[n.inputs[2]]
    return y


def _batchnorm(env, const, n):
    import jax.numpy as jnp

    x = env[n.inputs[0]]
    scale, bias, mean, var = (env[n.inputs[i]] for i in range(1, 5))
    eps = n.attrs["epsilon"].f if "epsilon" in n.attrs else 1e-5
    shape = (1, -1) + (1,) * (x.ndim - 2)
    inv = scale.reshape(shape) / jnp.sqrt(var.reshape(shape) + eps)
    return x * inv + (bias.reshape(shape) - mean.reshape(shape) * inv)


def _reshape(env, const, n):
    x = env[n.inputs[0]]
    if len(n.inputs) > 1:
        shape = [int(v) for v in const(n.inputs[1]).ravel()]
    else:
        shape = list(n.attrs["shape"].ints)
    allowzero = "allowzero" in n.attrs and n.attrs["allowzero"].i == 1
    if not allowzero:
        shape = [x.shape[i] if s == 0 else s for i, s in enumerate(shape)]
    return x.reshape(shape)


def _pad_op(env, const, n):
    import jax.numpy as jnp

    x = env[n.inputs[0]]
    if "pads" in n.attrs:
        p = n.attrs["pads"].ints
    else:
        p = [int(v) for v in const(n.inputs[1]).ravel()]
    mode = n.attrs["mode"].s if "mode" in n.attrs else "constant"
    rank = x.ndim
    widths = [(p[i], p[i + rank]) for i in range(rank)]
    if mode == "constant":
        cval = 0.0
        if len(n.inputs) > 2 and n.inputs[2]:
            cval = float(const(n.inputs[2]).ravel()[0])
        return jnp.pad(x, widths, constant_values=cval)
    if mode == "reflect":
        return jnp.pad(x, widths, mode="reflect")
    if mode == "edge":
        return jnp.pad(x, widths, mode="edge")
    raise ONNXError(f"Pad mode {mode!r} unsupported")


def _reduce_mean(env, const, n):
    import jax.numpy as jnp

    x = env[n.inputs[0]]
    if "axes" in n.attrs:
        axes = tuple(n.attrs["axes"].ints)
    elif len(n.inputs) > 1:
        axes = tuple(int(v) for v in const(n.inputs[1]).ravel())
    else:
        axes = None
    keep = ("keepdims" not in n.attrs) or n.attrs["keepdims"].i == 1
    return jnp.mean(x, axis=axes, keepdims=keep)


def _squeeze_axes(env, const, n):
    if "axes" in n.attrs:
        return tuple(n.attrs["axes"].ints)
    if len(n.inputs) > 1:
        return tuple(int(v) for v in const(n.inputs[1]).ravel())
    return None


def _clip(env, const, n):
    import jax.numpy as jnp

    x = env[n.inputs[0]]
    lo = hi = None
    if "min" in n.attrs:
        lo = n.attrs["min"].f
    elif len(n.inputs) > 1 and n.inputs[1]:
        lo = const(n.inputs[1])
    if "max" in n.attrs:
        hi = n.attrs["max"].f
    elif len(n.inputs) > 2 and n.inputs[2]:
        hi = const(n.inputs[2])
    if lo is not None:
        x = jnp.maximum(x, lo)
    if hi is not None:
        x = jnp.minimum(x, hi)
    return x


def _softmax(env, const, n):
    import jax

    axis = n.attrs["axis"].i if "axis" in n.attrs else -1
    return jax.nn.softmax(env[n.inputs[0]], axis=axis)


def _resize(env, const, n: _Node):
    """ONNX Resize (opset 11+), 4D NCHW over the spatial dims: nearest
    (asymmetric/floor — torch's interpolate export) and linear
    (half_pixel / align_corners / asymmetric)."""
    import jax.numpy as jnp

    x = env[n.inputs[0]]
    if x.ndim != 4:
        raise ONNXError(f"Resize: only 4D NCHW supported, got {x.ndim}D")
    for unsup in ("antialias", "exclude_outside"):
        if unsup in n.attrs and n.attrs[unsup].i:
            raise ONNXError(f"Resize: attribute {unsup}=1 unsupported")
    if "axes" in n.attrs and n.attrs["axes"].ints:
        raise ONNXError(
            "Resize: the opset-18 axes attribute is unsupported "
            "(full-rank scales/sizes only)")
    mode = n.attrs["mode"].s if "mode" in n.attrs else "nearest"
    coord = (n.attrs["coordinate_transformation_mode"].s
             if "coordinate_transformation_mode" in n.attrs else "half_pixel")
    h, w = x.shape[2], x.shape[3]
    oh = ow = None
    if len(n.inputs) > 3 and n.inputs[3]:  # sizes
        sizes = [int(v) for v in const(n.inputs[3]).ravel()]
        oh, ow = sizes[2], sizes[3]
    elif len(n.inputs) > 2 and n.inputs[2]:  # scales
        scales = [float(v) for v in const(n.inputs[2]).ravel()]
        if len(scales) != 4 or scales[0] != 1 or scales[1] != 1:
            raise ONNXError(f"Resize: unsupported scales {scales}")
        oh, ow = int(h * scales[2]), int(w * scales[3])
    if oh is None:
        raise ONNXError("Resize: neither scales nor sizes given")

    def src_idx(o, nsrc):
        i = jnp.arange(o, dtype=jnp.float32)
        if coord == "align_corners":
            return i * (nsrc - 1) / (o - 1) if o > 1 else i * 0.0
        if coord == "asymmetric":
            return i * nsrc / o
        if coord == "pytorch_half_pixel":
            # like half_pixel, but a length-1 output maps to source 0
            return (i + 0.5) * nsrc / o - 0.5 if o > 1 else i * 0.0
        if coord == "half_pixel":
            return (i + 0.5) * nsrc / o - 0.5
        raise ONNXError(
            f"Resize coordinate_transformation_mode {coord!r} unsupported")

    yf, xf = src_idx(oh, h), src_idx(ow, w)
    if mode == "nearest":
        near = (n.attrs["nearest_mode"].s if "nearest_mode" in n.attrs
                else "round_prefer_floor")  # the opset-11+ default
        rounders = {
            "round_prefer_floor": lambda v: jnp.ceil(v - 0.5),
            "round_prefer_ceil": lambda v: jnp.floor(v + 0.5),
            "floor": jnp.floor,
            "ceil": jnp.ceil,
        }
        if near not in rounders:
            raise ONNXError(f"Resize nearest_mode {near!r} unsupported")
        rnd = rounders[near]
        yi = jnp.clip(rnd(yf).astype(jnp.int32), 0, h - 1)
        xi = jnp.clip(rnd(xf).astype(jnp.int32), 0, w - 1)
        return x[:, :, yi][:, :, :, xi]
    if mode == "linear":
        y0 = jnp.clip(jnp.floor(yf).astype(jnp.int32), 0, h - 1)
        x0 = jnp.clip(jnp.floor(xf).astype(jnp.int32), 0, w - 1)
        y1 = jnp.minimum(y0 + 1, h - 1)
        x1 = jnp.minimum(x0 + 1, w - 1)
        wy = jnp.clip(yf - y0, 0.0, 1.0)[None, None, :, None]
        wx = jnp.clip(xf - x0, 0.0, 1.0)[None, None, None, :]
        f = x.astype(jnp.float32)
        top = f[:, :, y0][:, :, :, x0] * (1 - wx) + \
            f[:, :, y0][:, :, :, x1] * wx
        bot = f[:, :, y1][:, :, :, x0] * (1 - wx) + \
            f[:, :, y1][:, :, :, x1] * wx
        return (top * (1 - wy) + bot * wy).astype(x.dtype)
    raise ONNXError(f"Resize mode {mode!r} unsupported")


def _run_node(env, const, n: _Node):
    import jax
    import jax.numpy as jnp

    op = n.op
    if op == "Conv":
        return _conv(env, const, n)
    if op == "Gemm":
        return _gemm(env, const, n)
    if op == "MatMul":
        return jnp.matmul(env[n.inputs[0]], env[n.inputs[1]])
    if op == "Relu":
        return jnp.maximum(env[n.inputs[0]], 0)
    if op == "Sigmoid":
        return jax.nn.sigmoid(env[n.inputs[0]])
    if op == "Tanh":
        return jnp.tanh(env[n.inputs[0]])
    if op == "Clip":
        return _clip(env, const, n)
    if op == "Softmax":
        return _softmax(env, const, n)
    if op == "MaxPool":
        return _pool(env, const, n, "max")
    if op == "AveragePool":
        return _pool(env, const, n, "avg")
    if op == "GlobalAveragePool":
        x = env[n.inputs[0]]
        return jnp.mean(x, axis=tuple(range(2, x.ndim)), keepdims=True)
    if op == "BatchNormalization":
        return _batchnorm(env, const, n)
    if op in ("Add", "Sub", "Mul", "Div"):
        import operator

        x, y = env[n.inputs[0]], env[n.inputs[1]]
        if op == "Div":
            if (np.issubdtype(np.dtype(x.dtype), np.integer)
                    and np.issubdtype(np.dtype(y.dtype), np.integer)):
                # ONNX integer Div truncates toward zero
                dt = np.promote_types(x.dtype, y.dtype)
                return jnp.trunc(jnp.divide(x, y)).astype(dt)
            return x / y
        return {"Add": operator.add, "Sub": operator.sub,
                "Mul": operator.mul}[op](x, y)
    if op == "Concat":
        return jnp.concatenate([env[i] for i in n.inputs],
                               axis=n.attrs["axis"].i)
    if op == "Reshape":
        return _reshape(env, const, n)
    if op == "Flatten":
        axis = n.attrs["axis"].i if "axis" in n.attrs else 1
        x = env[n.inputs[0]]
        lead = int(np.prod(x.shape[:axis])) if axis else 1
        return x.reshape(lead, -1)
    if op == "Transpose":
        x = env[n.inputs[0]]
        perm = (tuple(n.attrs["perm"].ints) if "perm" in n.attrs
                else tuple(reversed(range(x.ndim))))
        return jnp.transpose(x, perm)
    if op == "Pad":
        return _pad_op(env, const, n)
    if op == "ReduceMean":
        return _reduce_mean(env, const, n)
    if op == "Squeeze":
        return jnp.squeeze(env[n.inputs[0]], axis=_squeeze_axes(env, const, n))
    if op == "Unsqueeze":
        x = env[n.inputs[0]]
        for ax in sorted(_squeeze_axes(env, const, n)):
            x = jnp.expand_dims(x, ax)
        return x
    if op == "Constant":
        for key in ("value", "value_float", "value_int"):
            if key in n.attrs:
                a = n.attrs[key]
                return a.t if a.t is not None else np.asarray(
                    a.f if a.f is not None else a.i)
        raise ONNXError(f"Constant node {n.name!r} without value")
    if op == "Identity":
        return env[n.inputs[0]]
    if op == "Erf":
        return jax.lax.erf(env[n.inputs[0]])
    if op == "Sqrt":
        return jnp.sqrt(env[n.inputs[0]])
    if op == "Exp":
        return jnp.exp(env[n.inputs[0]])
    if op == "Neg":
        return -env[n.inputs[0]]
    if op == "Pow":
        return jnp.power(env[n.inputs[0]], env[n.inputs[1]])
    if op == "LeakyRelu":
        alpha = n.attrs["alpha"].f if "alpha" in n.attrs else 0.01
        x = env[n.inputs[0]]
        return jnp.where(x >= 0, x, alpha * x)
    if op in ("Max", "Min"):
        import functools

        fn = jnp.maximum if op == "Max" else jnp.minimum
        return functools.reduce(fn, (env[i] for i in n.inputs))
    if op == "Shape":
        # static under XLA: emit concrete numpy so downstream shape math
        # (Gather/Slice/Concat chains) stays trace-time constant.
        # opset-15 start/end attributes slice the dims vector.
        shape = np.asarray(np.shape(env[n.inputs[0]]), np.int64)
        start = n.attrs["start"].i if "start" in n.attrs else 0
        end = n.attrs["end"].i if "end" in n.attrs else len(shape)
        return shape[start:end]
    if op == "Gather":
        axis = n.attrs["axis"].i if "axis" in n.attrs else 0
        return jnp.take(env[n.inputs[0]], env[n.inputs[1]], axis=axis)
    if op == "Split":
        x = env[n.inputs[0]]
        axis = n.attrs["axis"].i if "axis" in n.attrs else 0
        if "split" in n.attrs and n.attrs["split"].ints:
            sizes = list(n.attrs["split"].ints)
        elif len(n.inputs) > 1 and n.inputs[1]:
            sizes = [int(v) for v in const(n.inputs[1]).ravel()]
        else:
            # opset-18 equal split: ceil-sized chunks, LAST one smaller
            k = len(n.outputs)
            chunk = -(x.shape[axis] // -k)
            sizes = [chunk] * (k - 1) + [x.shape[axis] - chunk * (k - 1)]
        bounds = np.cumsum(sizes)[:-1].tolist()
        return tuple(jnp.split(x, bounds, axis=axis))
    if op == "Resize":
        return _resize(env, const, n)
    if op == "Cast":
        to = n.attrs["to"].i
        if to not in _TENSOR_DTYPES:
            raise ONNXError(f"Cast to unsupported data_type {to}")
        return env[n.inputs[0]].astype(_TENSOR_DTYPES[to])
    if op == "ConstantOfShape":
        shape = [int(v) for v in const(n.inputs[0]).ravel()]
        if "value" in n.attrs and n.attrs["value"].t is not None:
            v = n.attrs["value"].t.ravel()[0]
        else:
            v = np.float32(0)
        # numpy (not jnp) keeps shape-computation chains concrete, so a
        # downstream Pad/Reshape can consume them as trace-time statics
        return np.full(shape, v)
    if op == "Slice":
        x = env[n.inputs[0]]
        starts = [int(v) for v in const(n.inputs[1]).ravel()]
        ends = [int(v) for v in const(n.inputs[2]).ravel()]
        axes = ([int(v) for v in const(n.inputs[3]).ravel()]
                if len(n.inputs) > 3 and n.inputs[3]
                else list(range(len(starts))))
        steps = ([int(v) for v in const(n.inputs[4]).ravel()]
                 if len(n.inputs) > 4 and n.inputs[4]
                 else [1] * len(starts))
        idx = [slice(None)] * x.ndim
        for s, e, ax, st in zip(starts, ends, axes, steps):
            idx[ax] = slice(s, None if e >= (1 << 62) else e, st)
        return x[tuple(idx)]
    raise ONNXError(f"unsupported op {op}")  # pragma: no cover


#: ops whose inputs may be consumed as trace-time statics
_OPS = {"Conv", "Gemm", "MatMul", "Relu", "Sigmoid", "Tanh", "Clip",
        "Softmax", "MaxPool", "AveragePool", "GlobalAveragePool",
        "BatchNormalization", "Add", "Sub", "Mul", "Div", "Concat",
        "Reshape", "Flatten", "Transpose", "Pad", "ReduceMean", "Squeeze",
        "Unsqueeze", "Constant", "Identity", "Cast", "ConstantOfShape",
        "Slice", "Erf", "Sqrt", "Exp", "Neg", "Pow", "LeakyRelu", "Max",
        "Min", "Shape", "Gather", "Split", "Resize"}

#: per-op input positions that are static metadata (resolved from
#: initializers at trace time, kept OUT of the traced params pytree)
_STATIC_OPERANDS = {"Reshape": (1,), "Pad": (1, 2), "Clip": (1, 2),
                    "ReduceMean": (1,), "Squeeze": (1,), "Unsqueeze": (1,),
                    "ConstantOfShape": (0,), "Slice": (1, 2, 3, 4),
                    "Resize": (1, 2, 3), "Split": (1,)}

#: shape-computation ops that run in NUMPY when all inputs are concrete:
#: under jit, even constant-fed jnp ops stage to tracers, which would make
#: the torch exporter's pads/shape subgraphs (Cast/Slice/Concat chains)
#: unresolvable as trace-time statics downstream.
_HOSTABLE = {"Cast", "Slice", "Concat", "ConstantOfShape", "Unsqueeze",
             "Squeeze", "Reshape", "Transpose", "Identity", "Constant",
             "Gather", "Add", "Sub", "Mul", "Div", "Max", "Min"}


def _host_run(env, const, n: _Node):
    """Numpy execution of a _HOSTABLE node (concrete inputs only)."""
    op = n.op
    if op == "Constant":
        return _run_node(env, const, n)  # already returns numpy
    if op == "Identity":
        return np.asarray(env[n.inputs[0]])
    if op == "Cast":
        to = n.attrs["to"].i
        return np.asarray(env[n.inputs[0]]).astype(_TENSOR_DTYPES[to])
    if op == "Concat":
        return np.concatenate([np.asarray(env[i]) for i in n.inputs],
                              axis=n.attrs["axis"].i)
    if op == "ConstantOfShape":
        return _run_node(env, const, n)  # already numpy
    if op == "Unsqueeze":
        x = np.asarray(env[n.inputs[0]])
        for ax in sorted(_squeeze_axes(env, const, n)):
            x = np.expand_dims(x, ax)
        return x
    if op == "Squeeze":
        axes = _squeeze_axes(env, const, n)
        return np.squeeze(np.asarray(env[n.inputs[0]]), axis=axes)
    if op == "Slice":
        return _run_node(env, const, n)  # indexing works on numpy too
    if op == "Reshape":
        return np.asarray(_reshape(env, const, n))
    if op == "Transpose":
        x = np.asarray(env[n.inputs[0]])
        perm = (tuple(n.attrs["perm"].ints) if "perm" in n.attrs
                else tuple(reversed(range(x.ndim))))
        return np.transpose(x, perm)
    if op == "Gather":
        axis = n.attrs["axis"].i if "axis" in n.attrs else 0
        return np.take(np.asarray(env[n.inputs[0]]),
                       np.asarray(env[n.inputs[1]]), axis=axis)
    if op in ("Add", "Sub", "Mul", "Div", "Max", "Min"):
        import operator

        fn = {"Add": operator.add, "Sub": operator.sub,
              "Mul": operator.mul, "Div": operator.truediv,
              "Max": np.maximum, "Min": np.minimum}[op]
        out = np.asarray(env[n.inputs[0]])
        for i in n.inputs[1:]:
            out = fn(out, np.asarray(env[i]))
        if op == "Div" and all(
                np.issubdtype(np.asarray(env[i]).dtype, np.integer)
                for i in n.inputs):
            # ONNX integer Div truncates toward zero, result keeps the
            # promoted INPUT dtype (matching the traced path)
            dt = np.promote_types(*(np.asarray(env[i]).dtype
                                    for i in n.inputs[:2]))
            out = np.trunc(out).astype(dt)
        return out
    raise ONNXError(f"not hostable: {op}")  # pragma: no cover


def load_bundle(path: str, opts: Optional[Dict[str, str]] = None) -> ModelBundle:
    """Parse a .onnx file into a jittable :class:`ModelBundle` (NCHW IO,
    matching what an onnxruntime consumer of the same file would see).

    ``custom=param_dtype:bfloat16`` casts float weights; other option keys
    are rejected loudly.
    """
    opts = dict(opts or {})
    param_dtype = opts.pop("param_dtype", None)
    if opts:
        raise ONNXError(
            f"{path}: unsupported options {sorted(opts)} "
            "(onnx ingestion supports: param_dtype)")
    with open(path, "rb") as f:
        g = ONNXGraph(f.read(), name=path)

    static_names = set()
    data_names = set()
    for n in g.nodes:
        static_pos = _STATIC_OPERANDS.get(n.op, ())
        for pos, iname in enumerate(n.inputs):
            (static_names if pos in static_pos else data_names).add(iname)
    params = {k: v for k, v in g.initializers.items()
              if k not in (static_names - data_names)}
    if param_dtype:
        from ..core.types import dtype_from_name

        dt = dtype_from_name(str(param_dtype))
        params = {k: v.astype(dt) if np.issubdtype(v.dtype, np.floating)
                  else v for k, v in params.items()}

    def apply_fn(p, *inputs):
        if len(inputs) != len(g.inputs):
            raise ONNXError(
                f"{path}: expected {len(g.inputs)} input(s), got "
                f"{len(inputs)}")
        env: Dict[str, object] = {}
        for (iname, _dt, _shape), arr in zip(g.inputs, inputs):
            env[iname] = arr

        def lookup(name):
            if name in env:
                return env[name]
            if name in p:
                return p[name]
            if name in g.initializers:
                # static-classified initializer consumed as data elsewhere
                # is already kept in params; this branch serves the purely
                # static ones to hostable ops
                return np.asarray(g.initializers[name])
            raise ONNXError(f"{path}: tensor {name!r} used before produced")

        def const(name):
            if name in g.initializers:
                return np.asarray(g.initializers[name])
            if name in env:
                import jax.core

                v = env[name]
                # Constant-node outputs and shape-computation chains
                # (Cast/Slice/Concat over initializers) stay concrete at
                # trace time; only genuinely data-dependent values are
                # tracers and must be rejected.
                if not isinstance(v, jax.core.Tracer):
                    return np.asarray(v)
            raise ONNXError(
                f"{path}: tensor {name!r} must be a graph constant "
                "(shapes/axes/paddings are static under XLA)")

        class _Env(dict):
            def __getitem__(self, k):
                return lookup(k)

        import jax.core

        def concrete(name):
            if name == "":
                return True
            if name in env:
                return not isinstance(env[name], jax.core.Tracer)
            if name in g.initializers:
                # weights live in the traced params pytree under jit —
                # NOT concrete; only static-only initializers (excluded
                # from params) resolve as numpy
                return name not in p
            return False

        eview = _Env()
        for n in g.nodes:
            if n.op in _HOSTABLE and all(concrete(i) for i in n.inputs):
                out = _host_run(eview, const, n)
            else:
                out = _run_node(eview, const, n)
            if isinstance(out, tuple):  # multi-output ops (Split)
                for name, o in zip(n.outputs, out):
                    env[name] = o
            else:
                env[n.outputs[0]] = out
        results = tuple(lookup(nm) for nm, _d, _s in g.outputs)
        return results if len(results) > 1 else results[0]

    in_spec = TensorsSpec(tuple(
        TensorSpec.from_shape(shape or (1,), dt or np.float32, nm)
        for nm, dt, shape in g.inputs))
    out_spec = TensorsSpec(tuple(
        TensorSpec.from_shape(shape or (1,), dt or np.float32, nm)
        for nm, dt, shape in g.outputs))
    return ModelBundle(apply_fn=apply_fn, params=params, in_spec=in_spec,
                       out_spec=out_spec, name=path)
