"""MobileNet-v1 image classifier — benchmark config #1 flagship model.

Reference analog: the reference runs ``mobilenet_v1_1.0_224_quant.tflite``
through the tensorflow-lite sub-plugin
(``ext/nnstreamer/tensor_filter/tensor_filter_tensorflow_lite.cc`` — SURVEY
§2.4 [UNVERIFIED], reference mount empty).  Here the model is a pure JAX
program designed for the MXU:

* **NHWC layout** with channel counts that are multiples of 8/128 lane tiling
  where possible; all convs lower to ``lax.conv_general_dilated`` which XLA
  tiles onto the systolic array.
* **bfloat16 compute** by default (``custom=dtype:float32`` to override):
  params are stored float32 (optimizer-friendly) and cast at apply time, the
  standard TPU mixed-precision recipe.
* BatchNorm is represented as per-channel scale/bias (inference form).  It
  stays differentiable, so the same apply_fn serves the trainer path.
* ``param_pspecs`` shard pointwise-conv kernels over their output-channel
  axis ("model" mesh axis) so the parallel runner can TP-shard the classifier
  when a mesh is present; depthwise kernels are replicated (tiny).

Weights are deterministic he-normal random (seed via ``custom=seed:N``) —
this environment has zero egress, so no pretrained checkpoint download;
``utils/import_torch.py``-style converters can inject real weights into the
same pytree layout.
"""

from __future__ import annotations

import functools
from typing import Dict, Tuple

import numpy as np

from ..core.types import TensorsSpec
from .zoo import ModelBundle, register_model

# (stride, out_channels) per depthwise-separable block, after the stem conv.
# Standard MobileNet-v1 1.0 topology.
_V1_BLOCKS: Tuple[Tuple[int, int], ...] = (
    (1, 64),
    (2, 128),
    (1, 128),
    (2, 256),
    (1, 256),
    (2, 512),
    (1, 512),
    (1, 512),
    (1, 512),
    (1, 512),
    (1, 512),
    (2, 1024),
    (1, 1024),
)


def _rounded(ch: int, width: float) -> int:
    """Width-multiplied channel count, kept a multiple of 8 for lane tiling."""
    v = max(8, int(ch * width + 4) // 8 * 8)
    return v


def init_params(
    width: float = 1.0, classes: int = 1001, seed: int = 0
) -> Dict:
    """He-normal random params in the canonical pytree layout."""
    import jax

    keys = iter(jax.random.split(jax.random.PRNGKey(seed), 64))
    params: Dict = {}

    def conv(key, kh, kw, cin, cout):
        fan_in = kh * kw * cin
        w = jax.random.normal(key, (kh, kw, cin, cout), np.float32)
        return w * np.sqrt(2.0 / fan_in)

    c_in = 3
    c = _rounded(32, width)
    params["stem"] = {
        "w": conv(next(keys), 3, 3, c_in, c),
        "scale": np.ones((c,), np.float32),
        "bias": np.zeros((c,), np.float32),
    }
    cin = c
    for i, (_stride, cout_base) in enumerate(_V1_BLOCKS):
        cout = _rounded(cout_base, width)
        params[f"block{i}"] = {
            # depthwise 3x3: HWIO with feature_group_count=cin -> (3,3,1,cin)
            "dw": conv(next(keys), 3, 3, 1, cin),
            "dw_scale": np.ones((cin,), np.float32),
            "dw_bias": np.zeros((cin,), np.float32),
            # pointwise 1x1
            "pw": conv(next(keys), 1, 1, cin, cout),
            "pw_scale": np.ones((cout,), np.float32),
            "pw_bias": np.zeros((cout,), np.float32),
        }
        cin = cout
    params["head"] = {
        "w": conv(next(keys), 1, 1, cin, classes),
        "bias": np.zeros((classes,), np.float32),
    }
    return params


def param_pspecs() -> Dict:
    """PartitionSpecs for TP over a ``("data","model")`` mesh.

    Pointwise kernels shard over their output-channel axis; the following
    block's pointwise input axis shards to match, so XLA inserts at most one
    all-gather per block pair.  Depthwise/scale/bias tensors replicate.
    """
    from jax.sharding import PartitionSpec as P

    specs: Dict = {
        "stem": {"w": P(None, None, None, "model"), "scale": P("model"), "bias": P("model")}
    }
    for i in range(len(_V1_BLOCKS)):
        specs[f"block{i}"] = {
            "dw": P(),
            "dw_scale": P(),
            "dw_bias": P(),
            "pw": P(None, None, None, "model"),
            "pw_scale": P("model"),
            "pw_bias": P("model"),
        }
    specs["head"] = {"w": P(None, None, None, "model"), "bias": P("model")}
    return specs


def apply(params, x, *, compute_dtype="bfloat16", train: bool = False):
    """Forward pass.  ``x``: NHWC float (any float dtype), returns logits."""
    import jax
    import jax.numpy as jnp
    from jax import lax

    cdt = jnp.dtype(compute_dtype)
    x = x.astype(cdt)

    def conv2d(x, w, stride, groups=1):
        return lax.conv_general_dilated(
            x,
            w.astype(cdt),
            window_strides=(stride, stride),
            padding="SAME",
            dimension_numbers=("NHWC", "HWIO", "NHWC"),
            feature_group_count=groups,
        )

    def scale_bias_relu6(x, scale, bias):
        x = x * scale.astype(cdt) + bias.astype(cdt)
        return jnp.clip(x, 0.0, 6.0)

    p = params["stem"]
    x = conv2d(x, p["w"], 2)
    x = scale_bias_relu6(x, p["scale"], p["bias"])

    for i, (stride, _cout) in enumerate(_V1_BLOCKS):
        b = params[f"block{i}"]
        cin = x.shape[-1]
        x = conv2d(x, b["dw"], stride, groups=cin)
        x = scale_bias_relu6(x, b["dw_scale"], b["dw_bias"])
        x = conv2d(x, b["pw"], 1)
        x = scale_bias_relu6(x, b["pw_scale"], b["pw_bias"])

    x = jnp.mean(x, axis=(1, 2), keepdims=True)  # global average pool
    h = params["head"]
    x = conv2d(x, h["w"], 1) + h["bias"].astype(cdt)
    logits = x[:, 0, 0, :]
    return logits.astype(jnp.float32)


@register_model("mobilenet_v1")
def _mobilenet_v1(opts: Dict[str, str]) -> ModelBundle:
    width = float(opts.get("width", 1.0))
    classes = int(opts.get("classes", 1001))
    seed = int(opts.get("seed", 0))
    size = int(opts.get("size", 224))
    batch = int(opts.get("batch", 1))
    dtype = opts.get("dtype", "bfloat16")

    params = init_params(width=width, classes=classes, seed=seed)
    apply_fn = functools.partial(apply, compute_dtype=dtype)

    return ModelBundle(
        apply_fn=apply_fn,
        params=params,
        in_spec=TensorsSpec.from_string(f"3:{size}:{size}:{batch}", "float32"),
        out_spec=TensorsSpec.from_string(f"{classes}:{batch}", "float32"),
        param_pspecs=param_pspecs(),
        name="mobilenet_v1",
    )
