"""MobileNet-v1 image classifier — benchmark config #1 flagship model.

Reference analog: the reference runs ``mobilenet_v1_1.0_224_quant.tflite``
through the tensorflow-lite sub-plugin
(``ext/nnstreamer/tensor_filter/tensor_filter_tensorflow_lite.cc`` — SURVEY
§2.4 [UNVERIFIED], reference mount empty).  Here the model is a pure JAX
program designed for the MXU:

* **NHWC layout** with channel counts that are multiples of 8/128 lane tiling
  where possible; all convs lower to ``lax.conv_general_dilated`` which XLA
  tiles onto the systolic array.
* **bfloat16 compute** by default (``custom=dtype:float32`` to override):
  params are stored float32 (optimizer-friendly) and cast at apply time, the
  standard TPU mixed-precision recipe.
* BatchNorm is represented as per-channel scale/bias (inference form).  It
  stays differentiable, so the same apply_fn serves the trainer path.
* ``param_pspecs`` shard pointwise-conv kernels over their output-channel
  axis ("model" mesh axis) so the parallel runner can TP-shard the classifier
  when a mesh is present; depthwise kernels are replicated (tiny).

Weights are deterministic he-normal random (seed via ``custom=seed:N``) —
this environment has zero egress, so no pretrained checkpoint download;
``utils/import_torch.py``-style converters can inject real weights into the
same pytree layout.
"""

from __future__ import annotations

import functools
from typing import Dict, Tuple

import numpy as np

from ..core.types import TensorsSpec
from .backbone import (
    make_ops,
    rounded,
    sep_block_params,
    sep_block_pspecs,
    stem_params,
    stem_pspecs,
)
from .zoo import ModelBundle, register_model

# (stride, out_channels) per depthwise-separable block, after the stem conv.
# Standard MobileNet-v1 1.0 topology.
_V1_BLOCKS: Tuple[Tuple[int, int], ...] = (
    (1, 64),
    (2, 128),
    (1, 128),
    (2, 256),
    (1, 256),
    (2, 512),
    (1, 512),
    (1, 512),
    (1, 512),
    (1, 512),
    (1, 512),
    (2, 1024),
    (1, 1024),
)


def init_params(
    width: float = 1.0, classes: int = 1001, seed: int = 0
) -> Dict:
    """He-normal random params in the canonical pytree layout."""
    import jax

    from .backbone import he_conv

    keys = iter(jax.random.split(jax.random.PRNGKey(seed), 64))
    params: Dict = {"stem": stem_params(keys, 3, rounded(32, width))}
    cin = rounded(32, width)
    for i, (_stride, cout_base) in enumerate(_V1_BLOCKS):
        cout = rounded(cout_base, width)
        params[f"block{i}"] = sep_block_params(keys, cin, cout)
        cin = cout
    params["head"] = {
        "w": he_conv(next(keys), 1, 1, cin, classes),
        "bias": np.zeros((classes,), np.float32),
    }
    return params


def param_pspecs() -> Dict:
    """PartitionSpecs for TP over a ``("data","model")`` mesh.

    Pointwise kernels shard over their output-channel axis; the following
    block's pointwise input axis shards to match, so XLA inserts at most one
    all-gather per block pair.  Depthwise/scale/bias tensors replicate.
    """
    from jax.sharding import PartitionSpec as P

    specs: Dict = {"stem": stem_pspecs()}
    for i in range(len(_V1_BLOCKS)):
        specs[f"block{i}"] = sep_block_pspecs()
    specs["head"] = {"w": P(None, None, None, "model"), "bias": P("model")}
    return specs


def apply(params, x, *, compute_dtype="bfloat16", train: bool = False):
    """Forward pass.  ``x``: NHWC float (any float dtype), returns logits."""
    import jax.numpy as jnp

    cdt = jnp.dtype(compute_dtype)
    x = x.astype(cdt)
    conv2d, sbr, sep = make_ops(cdt)

    p = params["stem"]
    x = sbr(conv2d(x, p["w"], 2), p["scale"], p["bias"])
    for i, (stride, _cout) in enumerate(_V1_BLOCKS):
        x = sep(x, params[f"block{i}"], stride)

    x = jnp.mean(x, axis=(1, 2), keepdims=True)  # global average pool
    h = params["head"]
    x = conv2d(x, h["w"], 1) + h["bias"].astype(cdt)
    logits = x[:, 0, 0, :]
    return logits.astype(jnp.float32)


@register_model("mobilenet_v1")
def _mobilenet_v1(opts: Dict[str, str]) -> ModelBundle:
    width = float(opts.get("width", 1.0))
    classes = int(opts.get("classes", 1001))
    seed = int(opts.get("seed", 0))
    size = int(opts.get("size", 224))
    batch = int(opts.get("batch", 1))
    dtype = opts.get("dtype", "bfloat16")

    params = init_params(width=width, classes=classes, seed=seed)
    apply_fn = functools.partial(apply, compute_dtype=dtype)

    return ModelBundle(
        apply_fn=apply_fn,
        params=params,
        in_spec=TensorsSpec.from_string(f"3:{size}:{size}:{batch}", "float32"),
        out_spec=TensorsSpec.from_string(f"{classes}:{batch}", "float32"),
        param_pspecs=param_pspecs(),
        name="mobilenet_v1",
    )
