"""PoseNet keypoint heatmap model — benchmark config #3.

Reference analog: the reference runs
``posenet_mobilenet_v1_100_257x257...tflite`` through tflite and decodes
with ``tensordec-pose.c`` (SURVEY §2.5, BASELINE config #3).  Same backbone
recipe as models/mobilenet.py (depthwise-separable, NHWC, bfloat16 on the
MXU) at output stride 16, with two 1x1 heads:

* heatmaps (B, H/16, W/16, K) — sigmoid keypoint confidence;
* offsets (B, H/16, W/16, 2K) — short-range refinement (the decoder uses
  them when present).

Output layout matches the ``pose_estimation`` decoder contract: heatmaps
(H', W', K), PoseNet-style, batch leading.
"""

from __future__ import annotations

import functools
from typing import Dict, Tuple

import numpy as np

from ..core.types import TensorsSpec
from .backbone import (
    fm_size,
    he_conv,
    make_ops,
    rounded,
    sep_block_params,
    sep_block_pspecs,
    stem_params,
    stem_pspecs,
)
from .zoo import ModelBundle, register_model

_BACKBONE: Tuple[Tuple[int, int], ...] = (
    (1, 64), (2, 128), (1, 128), (2, 256), (1, 256), (2, 512),
    (1, 512), (1, 512), (1, 512),
)
KEYPOINTS = 17  # COCO-17


def init_params(width: float = 1.0, keypoints: int = KEYPOINTS,
                seed: int = 0) -> Dict:
    import jax

    keys = iter(jax.random.split(jax.random.PRNGKey(seed), 64))
    params: Dict = {"stem": stem_params(keys, 3, rounded(32, width))}
    cin = rounded(32, width)
    for i, (_s, ch) in enumerate(_BACKBONE):
        cout = rounded(ch, width)
        params[f"block{i}"] = sep_block_params(keys, cin, cout)
        cin = cout
    params["head_heat"] = {"w": he_conv(next(keys), 1, 1, cin, keypoints),
                           "bias": np.zeros((keypoints,), np.float32)}
    params["head_off"] = {"w": he_conv(next(keys), 1, 1, cin, 2 * keypoints),
                          "bias": np.zeros((2 * keypoints,), np.float32)}
    return params


def param_pspecs() -> Dict:
    from jax.sharding import PartitionSpec as P

    specs: Dict = {"stem": stem_pspecs()}
    for i in range(len(_BACKBONE)):
        specs[f"block{i}"] = sep_block_pspecs()
    specs["head_heat"] = {"w": P(), "bias": P()}
    specs["head_off"] = {"w": P(), "bias": P()}
    return specs


def apply(params, x, *, compute_dtype="bfloat16"):
    import jax
    import jax.numpy as jnp

    cdt = jnp.dtype(compute_dtype)
    x = x.astype(cdt)
    conv2d, sbr, sep = make_ops(cdt)

    p = params["stem"]
    x = sbr(conv2d(x, p["w"], 2), p["scale"], p["bias"])
    for i, (stride, _ch) in enumerate(_BACKBONE):
        x = sep(x, params[f"block{i}"], stride)
    heat = conv2d(x, params["head_heat"]["w"], 1) + \
        params["head_heat"]["bias"].astype(cdt)
    off = conv2d(x, params["head_off"]["w"], 1) + \
        params["head_off"]["bias"].astype(cdt)
    return (jax.nn.sigmoid(heat).astype(jnp.float32),
            off.astype(jnp.float32))


@register_model("posenet")
def _posenet(opts: Dict[str, str]) -> ModelBundle:
    width = float(opts.get("width", 1.0))
    keypoints = int(opts.get("keypoints", KEYPOINTS))
    seed = int(opts.get("seed", 0))
    size = int(opts.get("size", 256))
    batch = int(opts.get("batch", 1))
    dtype = opts.get("dtype", "bfloat16")

    params = init_params(width=width, keypoints=keypoints, seed=seed)
    apply_fn = functools.partial(apply, compute_dtype=dtype)
    # SAME-padded ceil-div chain, not size//16: the reference posenet's own
    # 257x257 input yields 17x17 heatmaps, not 16x16.
    fm = fm_size(size, 16)
    return ModelBundle(
        apply_fn=apply_fn,
        params=params,
        in_spec=TensorsSpec.from_string(f"3:{size}:{size}:{batch}", "float32"),
        out_spec=TensorsSpec.from_string(
            f"{keypoints}:{fm}:{fm}:{batch},{2 * keypoints}:{fm}:{fm}:{batch}",
            "float32,float32"),
        param_pspecs=param_pspecs(),
        name="posenet",
    )
