"""GGUF model-file reader: llama.cpp's checkpoint format -> numpy dict.

Reference analog: the llama.cpp sub-plugin
(``ext/nnstreamer/tensor_filter/tensor_filter_llamacpp.cc``, SURVEY §2.4
[UNVERIFIED]) consumes GGUF files.  The container is public and simple:
little-endian header (magic "GGUF", version, tensor count, kv count),
typed metadata key-values, tensor descriptors (name, dims in ggml
fastest-first order, ggml type, data offset), then an aligned data blob.
A pure-Python reader covers the UNQUANTIZED types (F32/F16/BF16) with
numpy memmaps; k-quant block formats raise a clear error naming the
tensor and type (dequantize offline with llama.cpp's tools).

``llama.load_checkpoint`` routes ``.gguf`` through here: tensor names map
from llama.cpp's ``blk.N.attn_q.weight`` convention, the model config is
read from the ``llama.*`` metadata keys, and q/k weights are re-laid from
ggml's interleaved-pair RoPE convention into the rotate-half layout
models/llama.py computes with (the same permutation HF applies when it
converts Meta checkpoints).
"""

from __future__ import annotations

import struct
from typing import Dict, Tuple

import numpy as np

from ..core.types import bfloat16


class GGUFError(ValueError):
    pass


_MAGIC = 0x46554747  # "GGUF"

#: ggml type id -> numpy dtype for the UNQUANTIZED types.  BF16 (30) is
#: included only when the real ml_dtypes extension dtype is present: the
#: core.types fallback aliases bfloat16 to float32, which would silently
#: reinterpret 2-byte BF16 payloads as 4-byte floats.
_GGML_DTYPES = {0: np.dtype(np.float32), 1: np.dtype(np.float16),
                24: np.dtype(np.int8), 25: np.dtype(np.int16),
                26: np.dtype(np.int32), 27: np.dtype(np.int64),
                28: np.dtype(np.float64)}
if np.dtype(bfloat16).itemsize == 2:
    _GGML_DTYPES[30] = np.dtype(bfloat16)

_GGML_QUANT_NAMES = {2: "Q4_0", 3: "Q4_1", 6: "Q5_0", 7: "Q5_1",
                     8: "Q8_0", 9: "Q8_1", 10: "Q2_K", 11: "Q3_K",
                     12: "Q4_K", 13: "Q5_K", 14: "Q6_K", 15: "Q8_K"}


class _Reader:
    def __init__(self, f, size: int):
        self.f = f
        self.size = size

    def _read(self, n: int) -> bytes:
        data = self.f.read(n)
        if len(data) != n:
            raise GGUFError(
                f"{self.f.name}: truncated GGUF (wanted {n} bytes at "
                f"offset {self.f.tell() - len(data)}, file is "
                f"{self.size} bytes)")
        return data

    def u32(self):
        return struct.unpack("<I", self._read(4))[0]

    def u64(self):
        return struct.unpack("<Q", self._read(8))[0]

    def s(self):
        n = self.u64()
        if n > self.size:
            raise GGUFError(
                f"{self.f.name}: corrupt GGUF (string length {n} exceeds "
                f"file size {self.size})")
        return self._read(n).decode("utf-8", "replace")

    _SCALARS = {0: ("<B", 1), 1: ("<b", 1), 2: ("<H", 2), 3: ("<h", 2),
                4: ("<I", 4), 5: ("<i", 4), 6: ("<f", 4), 7: ("<B", 1),
                10: ("<Q", 8), 11: ("<q", 8), 12: ("<d", 8)}

    def value(self, vtype: int):
        if vtype in self._SCALARS:
            fmt, size = self._SCALARS[vtype]
            v = struct.unpack(fmt, self._read(size))[0]
            return bool(v) if vtype == 7 else v
        if vtype == 8:
            return self.s()
        if vtype == 9:  # array
            et = self.u32()
            n = self.u64()
            return [self.value(et) for _ in range(n)]
        raise GGUFError(f"unknown metadata value type {vtype}")


def _read_header(path: str, r: "_Reader") -> Tuple[int, Dict]:
    """magic + version + counts + key-value section, shared by
    :func:`read` and :func:`read_metadata`.  Returns (n_tensors, meta)
    with the reader positioned at the tensor-descriptor table."""
    if r.u32() != _MAGIC:
        raise GGUFError(f"{path}: not a GGUF file (bad magic)")
    version = r.u32()
    if version not in (2, 3):
        raise GGUFError(f"{path}: unsupported GGUF version {version}")
    n_tensors = r.u64()
    n_kv = r.u64()
    meta: Dict = {}
    for _ in range(n_kv):
        key = r.s()
        vtype = r.u32()
        meta[key] = r.value(vtype)
    return n_tensors, meta


def read_metadata(path: str) -> Dict:
    """Parse only the header + key-value section (no tensor descriptors):
    the cheap path for vocab/config sniffing (models/tokenizer.py)."""
    import os

    with open(path, "rb") as f:
        _, meta = _read_header(path, _Reader(f, os.path.getsize(path)))
        return meta


def read(path: str) -> Tuple[Dict, Dict[str, np.ndarray]]:
    """Returns (metadata, tensors).  Tensor arrays are memmap-backed and
    shaped in numpy (outermost-first) order — ggml dims are stored
    fastest-first, so they are reversed here."""
    import os

    with open(path, "rb") as f:
        r = _Reader(f, os.path.getsize(path))
        n_tensors, meta = _read_header(path, r)
        infos = []
        for _ in range(n_tensors):
            name = r.s()
            n_dims = r.u32()
            dims = [r.u64() for _ in range(n_dims)]
            ggml_type = r.u32()
            offset = r.u64()
            infos.append((name, dims, ggml_type, offset))
        align = meta.get("general.alignment", 32)
        if not isinstance(align, int) or align <= 0:
            raise GGUFError(
                f"{path}: invalid general.alignment {align!r}")
        pos = f.tell()
        data_start = (pos + align - 1) // align * align
        file_size = r.size

    tensors: Dict[str, np.ndarray] = {}
    for name, dims, ggml_type, offset in infos:
        if ggml_type not in _GGML_DTYPES:
            qname = _GGML_QUANT_NAMES.get(ggml_type, str(ggml_type))
            raise GGUFError(
                f"{path}: tensor {name!r} uses quantized ggml type "
                f"{qname} — only F32/F16/BF16 GGUF loads here; dequantize "
                "offline (llama.cpp: llama-quantize --allow-requantize, "
                "or convert with outtype f16)")
        dt = _GGML_DTYPES[ggml_type]
        count = int(np.prod(dims)) if dims else 1
        nbytes = count * dt.itemsize
        if data_start + offset + nbytes > file_size:
            raise GGUFError(
                f"{path}: truncated GGUF — tensor {name!r} needs bytes "
                f"[{data_start + offset}, {data_start + offset + nbytes}) "
                f"but the file is {file_size} bytes")
        mm = np.memmap(path, dtype=np.uint8, mode="r",
                       offset=data_start + offset,
                       shape=(nbytes,))
        # ggml dims are fastest-first; numpy wants outermost-first
        tensors[name] = mm.view(dt).reshape(list(reversed(dims)))
    return meta, tensors


def llama_metadata(cfg) -> Dict:
    """The ``llama.*`` metadata keys llama.cpp reads for a model config."""
    return {
        "general.architecture": "llama",
        "llama.block_count": cfg.n_layers,
        "llama.embedding_length": cfg.dim,
        "llama.attention.head_count": cfg.n_heads,
        "llama.attention.head_count_kv": cfg.n_kv_heads,
        "llama.feed_forward_length": cfg.ffn_hidden,
        "llama.context_length": cfg.max_seq,
        "llama.rope.freq_base": cfg.rope_theta,
        "llama.attention.layer_norm_rms_epsilon": cfg.norm_eps,
    }


def _inv_rope_permute(w: np.ndarray, n_heads: int) -> np.ndarray:
    """rotate-half layout -> ggml interleaved-pair layout (inverse of
    llama._rope_permute; composing with it is identity, proven by the
    exact-logits round-trip test)."""
    out, dim2 = w.shape
    hd = out // n_heads
    return np.ascontiguousarray(
        w.reshape(n_heads, 2, hd // 2, dim2).swapaxes(1, 2).reshape(
            out, dim2))


def llama_to_tensors(params: Dict, cfg) -> Dict[str, np.ndarray]:
    """models/llama.py stacked pytree -> llama.cpp tensor naming/layout
    (2D mats transposed back to [out, in], q/k re-interleaved for ggml's
    RoPE convention) — what :func:`write` needs to emit a real-looking
    .gguf from this framework's weights."""
    lay = params["layers"]
    out = {"token_embd.weight": np.asarray(params["embed"]),
           "output_norm.weight": np.asarray(params["ln_out"]),
           "output.weight": np.ascontiguousarray(
               np.asarray(params["lm_head"]).T)}
    for i in range(cfg.n_layers):
        wq = np.ascontiguousarray(np.asarray(lay["wq"])[i].T)
        wk = np.ascontiguousarray(np.asarray(lay["wk"])[i].T)
        out[f"blk.{i}.attn_q.weight"] = _inv_rope_permute(wq, cfg.n_heads)
        out[f"blk.{i}.attn_k.weight"] = _inv_rope_permute(wk,
                                                          cfg.n_kv_heads)
        out[f"blk.{i}.attn_v.weight"] = np.ascontiguousarray(
            np.asarray(lay["wv"])[i].T)
        out[f"blk.{i}.attn_output.weight"] = np.ascontiguousarray(
            np.asarray(lay["wo"])[i].T)
        out[f"blk.{i}.ffn_gate.weight"] = np.ascontiguousarray(
            np.asarray(lay["w_gate"])[i].T)
        out[f"blk.{i}.ffn_up.weight"] = np.ascontiguousarray(
            np.asarray(lay["w_up"])[i].T)
        out[f"blk.{i}.ffn_down.weight"] = np.ascontiguousarray(
            np.asarray(lay["w_down"])[i].T)
        out[f"blk.{i}.attn_norm.weight"] = np.asarray(lay["ln_attn"])[i]
        out[f"blk.{i}.ffn_norm.weight"] = np.asarray(lay["ln_mlp"])[i]
    return out


def export_llama(path: str, params: Dict, cfg, tokenizer=None) -> None:
    """Write a llama-family pytree as a .gguf llama.cpp can identify.
    ``tokenizer``: optional models/tokenizer.py SentencePieceTokenizer —
    its vocab is embedded as ``tokenizer.ggml.*`` metadata so the file
    carries its own text path, like real llama.cpp checkpoints."""
    meta = llama_metadata(cfg)
    if tokenizer is not None:
        meta.update(tokenizer.to_gguf_meta())
    write(path, meta, llama_to_tensors(params, cfg))


def write(path: str, meta: Dict, tensors: Dict[str, np.ndarray],
          align: int = 32) -> None:
    """Emit a GGUF v3 file (tests / converting weights for reuse)."""
    inv = {v: k for k, v in _GGML_DTYPES.items()}

    def pack_s(s: str) -> bytes:
        raw = s.encode("utf-8")
        return struct.pack("<Q", len(raw)) + raw

    def pack_value(v) -> bytes:
        if isinstance(v, bool):
            return struct.pack("<IB", 7, int(v))
        if isinstance(v, int):
            return struct.pack("<Iq", 11, v)
        if isinstance(v, float):
            return struct.pack("<If", 6, v)
        if isinstance(v, str):
            return struct.pack("<I", 8) + pack_s(v)
        if isinstance(v, (list, tuple)):
            # element type from the first item (homogeneous arrays only —
            # what the tokenizer.ggml.* vocab keys need)
            if not v:
                raise GGUFError("cannot write an empty metadata array")
            e = v[0]
            if isinstance(e, str):
                body = b"".join(pack_s(str(x)) for x in v)
                et = 8
            elif isinstance(e, bool):
                body = b"".join(struct.pack("<B", int(x)) for x in v)
                et = 7
            elif isinstance(e, int):
                body = b"".join(struct.pack("<i", int(x)) for x in v)
                et = 5
            elif isinstance(e, float):
                body = b"".join(struct.pack("<f", float(x)) for x in v)
                et = 6
            else:
                raise GGUFError(
                    f"unsupported metadata array element {e!r}")
            return struct.pack("<IIQ", 9, et, len(v)) + body
        raise GGUFError(f"unsupported metadata value {v!r}")

    header = bytearray()
    header += struct.pack("<IIQQ", _MAGIC, 3, len(tensors), len(meta))
    for k, v in meta.items():
        header += pack_s(k)
        header += pack_value(v)
    offset = 0
    for name, arr in tensors.items():
        dt = np.dtype(arr.dtype)
        if dt not in inv:
            raise GGUFError(f"unsupported dtype {dt} for {name}")
        dims = list(reversed(arr.shape))  # ggml fastest-first
        header += pack_s(name)
        header += struct.pack("<I", len(dims))
        for d in dims:
            header += struct.pack("<Q", d)
        header += struct.pack("<IQ", inv[dt], offset)
        offset += (arr.nbytes + align - 1) // align * align
    header += b"\x00" * ((-len(header)) % align)
    # stream tensors to the file — a 7B export is ~14 GB; buffering
    # tobytes() copies would double peak RAM
    with open(path, "wb") as f:
        f.write(header)
        for name, arr in tensors.items():
            np.ascontiguousarray(arr).tofile(f)
            f.write(b"\x00" * ((-arr.nbytes) % align))
