"""YOLOv5-style single-shot detector — the second half of benchmark
config #2 ("SSD-MobileNet / YOLOv5 object detection", BASELINE.json).

Reference analog: the reference decodes YOLOv5/YOLOv8 raw output in
``tensordec-boundingbox.c``'s yolo modes (SURVEY §2.5 [UNVERIFIED]); the
model itself comes from a .tflite/.onnx file.  Zero-egress here, so the
zoo provides a compact YOLOv5-shaped network built from the shared
depthwise-separable blocks: a strided backbone with three detection
scales (strides 8/16/32), ``anchors_per_cell`` predictors per cell, and
the YOLOv5 head convention — sigmoid box/objectness/class activations
with per-cell offset decode — emitting ONE ``[B, N, 5+C]`` tensor in the
exact layout ``tensor_decoder mode=bounding_boxes option1=yolov5``
consumes (cx, cy, w, h normalized, objectness, class scores).

TPU-first: the whole predict-and-decode is one jitted program; the grid
offset/anchor math is folded into the fused pipeline program next to the
convs, and the decoder's device-NMS path (option7=device) keeps the
full decode on device.
"""

from __future__ import annotations

import functools
from typing import Dict

import numpy as np

from ..core.types import TensorsSpec
from .backbone import fm_size, he_conv, make_ops, rounded, sep_block_params, \
    sep_block_pspecs, stem_params, stem_pspecs
from .zoo import ModelBundle, register_model

#: (stride-2 steps between scales are built from these widths)
_BACKBONE = [64, 128, 256]   # strides 8, 16, 32 scale widths (pre width-mult)
_ANCHORS_PER_CELL = 3
#: YOLOv5-ish anchor sizes per scale, normalized to input size
_ANCHOR_SIZES = {
    8: [(0.04, 0.06), (0.08, 0.12), (0.12, 0.09)],
    16: [(0.14, 0.22), (0.26, 0.17), (0.24, 0.38)],
    32: [(0.45, 0.35), (0.38, 0.64), (0.75, 0.70)],
}


def _keygen(seed: int):
    import jax

    key = jax.random.PRNGKey(seed)
    while True:
        key, sub = jax.random.split(key)
        yield sub


def init_params(classes: int, width: float = 1.0, seed: int = 0,
                anchors_per_cell: int = _ANCHORS_PER_CELL,
                head_values: int = 5) -> Dict:
    """``anchors_per_cell``/``head_values`` let the anchor-free v8 head
    (1 predictor per cell, 4+C values) share the backbone with v5."""
    keys = _keygen(seed)
    params: Dict = {"stem": stem_params(keys, 3, rounded(32, width))}
    cin = rounded(32, width)
    # stem is stride 2; three stride-2 stages land strides 8/16/32 with one
    # refining block per scale
    for i, ch in enumerate(_BACKBONE):
        cout = rounded(ch, width)
        params[f"down{i}"] = sep_block_params(keys, cin, cout)   # stride 2
        params[f"block{i}"] = sep_block_params(keys, cout, cout)  # stride 1
        cin = cout
        nout = anchors_per_cell * (head_values + classes)
        params[f"head{i}"] = {
            "w": he_conv(next(keys), 1, 1, cout, nout),
            # objectness prior: like the SSD low-prior cls bias, random
            # weights should predict "no object" almost everywhere
            "b": np.full((nout,), -4.0, np.float32),
        }
    return params


def param_pspecs() -> Dict:
    from jax.sharding import PartitionSpec as P

    specs: Dict = {"stem": stem_pspecs()}
    for i in range(len(_BACKBONE)):
        specs[f"down{i}"] = sep_block_pspecs()
        specs[f"block{i}"] = sep_block_pspecs()
        specs[f"head{i}"] = {"w": P(), "b": P()}
    return specs


def num_predictions(size: int) -> int:
    return sum(
        fm_size(size, s) ** 2 * _ANCHORS_PER_CELL for s in (8, 16, 32))


def _backbone_feats(params, x, size: int, compute_dtype):
    """Shared stem + three-scale backbone: [B, size, size, 3] ->
    [(stride, feature_map, head_params)] at strides 8/16/32."""
    import jax
    import jax.numpy as jnp

    assert x.shape[1] == x.shape[2] == size, (
        f"yolo input must be {size}x{size}, got {x.shape}")
    conv2d, sbr, sep = make_ops(compute_dtype)
    cdt = jnp.dtype(compute_dtype)

    h = conv2d(x.astype(cdt), params["stem"]["w"], 2)
    h = sbr(h, params["stem"]["scale"], params["stem"]["bias"])
    # extra stride-2 maxpool after the stem puts the three down/block
    # stages at strides 8/16/32 — each head consumes its own stage's
    # feature map (channel counts match init_params' loop exactly)
    h = jax.lax.reduce_window(
        h, -jnp.inf, jax.lax.max, (1, 2, 2, 1), (1, 2, 2, 1), "SAME")
    feats = []
    for i, stride in enumerate((8, 16, 32)):
        h = sep(h, params[f"down{i}"], 2)
        h = sep(h, params[f"block{i}"], 1)
        feats.append((stride, h, params[f"head{i}"]))
    return feats


def _poly_coeffs(g: int, n_out: int, n_anchor: int, box_a):
    """Per-(position, channel) FMA coefficients for a yolo-family decode
    head, out = A*sigmoid(raw)^2 + B*sigmoid(raw) + C over the flattened
    [N_s, n_out] scale block — the whole box decode as ONE lane-friendly
    pass (the textbook slice/meshgrid/stack form builds minor-dim-3/4
    tensors that TPU pads to 128 lanes; measured 16 of 26 ms of the v5s
    step, PROFILE_YOLO_r5.json).  ``box_a``: [n_anchor, 2] quadratic
    coefficients for the w/h channels (4*anchor, already in the head's
    output units).  Channels: 0/1 affine cell-centers, 2/3 quadratic
    w/h, the rest identity (scores)."""
    gy, gx = np.meshgrid(np.arange(g), np.arange(g), indexing="ij")
    pos = np.stack([gx, gy], -1).reshape(-1, 2)
    pos = np.repeat(pos, n_anchor, axis=0)  # [N_s, 2], anchor-minor
    box_a = np.tile(np.asarray(box_a, np.float32), (g * g, 1))
    N_s = g * g * n_anchor
    A = np.zeros((N_s, n_out), np.float32)
    B = np.zeros((N_s, n_out), np.float32)
    C = np.zeros((N_s, n_out), np.float32)
    B[:, 4:] = 1.0
    B[:, 0] = B[:, 1] = 2.0 / g
    C[:, 0] = (pos[:, 0] - 0.5) / g
    C[:, 1] = (pos[:, 1] - 0.5) / g
    A[:, 2] = box_a[:, 0]
    A[:, 3] = box_a[:, 1]
    return A, B, C


def _poly_decode(raws, abc):
    """Concatenate per-scale raw head tensors and run the fused
    polynomial decode (see :func:`_poly_coeffs`)."""
    import jax
    import jax.numpy as jnp

    raw = jnp.concatenate(raws, axis=1).astype(jnp.float32)
    A = jnp.asarray(np.concatenate([a for a, _, _ in abc]))
    B = jnp.asarray(np.concatenate([b for _, b, _ in abc]))
    C = jnp.asarray(np.concatenate([c for _, _, c in abc]))
    s = jax.nn.sigmoid(raw)
    return (A * s + B) * s + C


def apply(params, x, *, classes: int, size: int, compute_dtype="bfloat16"):
    """[B, size, size, 3] float32 in [0,1] -> [B, N, 5+C] float32
    (yolov5 layout).  ``size`` pins the traced input so N matches the
    bundle's negotiated out_spec."""
    import jax.numpy as jnp

    conv2d, _, _ = make_ops(compute_dtype)
    cdt = jnp.dtype(compute_dtype)
    feats = _backbone_feats(params, x, size, compute_dtype)

    B = x.shape[0]
    raws, abc = [], []
    for stride, fm, hp in feats:
        g = fm.shape[1]
        raw = conv2d(fm, hp["w"], 1) + hp["b"].astype(cdt)
        raws.append(raw.reshape(B, g * g * _ANCHORS_PER_CELL,
                                5 + classes))
        anch = np.asarray(_ANCHOR_SIZES[stride], np.float32)  # [A, 2]
        abc.append(_poly_coeffs(g, 5 + classes, _ANCHORS_PER_CELL,
                                4.0 * anch))
    return _poly_decode(raws, abc)


def num_predictions_v8(size: int) -> int:
    return sum(fm_size(size, s) ** 2 for s in (8, 16, 32))


def apply_v8(params, x, *, classes: int, size: int,
             compute_dtype="bfloat16"):
    """[B, size, size, 3] float32 in [0,1] -> [B, 4+C, N] float32 — the
    YOLOv8 (ultralytics) channels-first export layout the reference's
    yolov8 decoder mode consumes: anchor-free (one predictor per cell, no
    objectness column), post-sigmoid class scores, normalized cx,cy,w,h."""
    import jax.numpy as jnp

    conv2d, _, _ = make_ops(compute_dtype)
    cdt = jnp.dtype(compute_dtype)
    B = x.shape[0]
    raws, abc = [], []
    for stride, fm, hp in _backbone_feats(params, x, size, compute_dtype):
        g = fm.shape[1]
        raw = conv2d(fm, hp["w"], 1) + hp["b"].astype(cdt)
        raws.append(raw.reshape(B, g * g, 4 + classes))
        # anchor-free decode: cell-offset centers; w/h from a per-scale
        # prior proportional to the stride (v8's dist2bbox analog)
        prior = 4.0 * (4.0 * stride / size)  # quadratic coeff = 4*prior
        abc.append(_poly_coeffs(g, 4 + classes, 1, [[prior, prior]]))
    return jnp.swapaxes(_poly_decode(raws, abc), 1, 2)


@register_model("yolov8")
def _yolov8(opts: Dict[str, str]) -> ModelBundle:
    classes = int(opts.get("classes", 80))
    width = float(opts.get("width", 1.0))
    seed = int(opts.get("seed", 0))
    size = int(opts.get("size", 224))
    batch = int(opts.get("batch", 1))
    dtype = opts.get("dtype", "bfloat16")
    if size % 32:
        raise ValueError(f"yolov8 size must be a multiple of 32, got {size}")

    params = init_params(classes=classes, width=width, seed=seed,
                         anchors_per_cell=1, head_values=4)
    apply_fn = functools.partial(
        apply_v8, classes=classes, size=size, compute_dtype=dtype)
    n = num_predictions_v8(size)
    return ModelBundle(
        apply_fn=apply_fn,
        params=params,
        in_spec=TensorsSpec.from_string(f"3:{size}:{size}:{batch}", "float32"),
        out_spec=TensorsSpec.from_string(
            f"{n}:{4 + classes}:{batch}", "float32"),
        param_pspecs=param_pspecs(),
        name="yolov8",
    )


@register_model("yolov5")
def _yolo(opts: Dict[str, str]) -> ModelBundle:
    classes = int(opts.get("classes", 80))
    width = float(opts.get("width", 1.0))
    seed = int(opts.get("seed", 0))
    size = int(opts.get("size", 224))
    batch = int(opts.get("batch", 1))
    dtype = opts.get("dtype", "bfloat16")
    if size % 32:
        raise ValueError(f"yolov5 size must be a multiple of 32, got {size}")

    params = init_params(classes=classes, width=width, seed=seed)
    apply_fn = functools.partial(
        apply, classes=classes, size=size, compute_dtype=dtype)
    n = num_predictions(size)
    return ModelBundle(
        apply_fn=apply_fn,
        params=params,
        in_spec=TensorsSpec.from_string(f"3:{size}:{size}:{batch}", "float32"),
        out_spec=TensorsSpec.from_string(
            f"{5 + classes}:{n}:{batch}", "float32"),
        param_pspecs=param_pspecs(),
        name="yolov5",
    )


# -- CSP-YOLOv5s: the real-geometry detector ------------------------------
#
# Faithful YOLOv5-v6 architecture (CSPDarknet backbone + SPPF + PANet
# head + anchor head), ~7M params / ~17 GFLOPs per frame at 640x640 with
# the default width 0.5 / depth 0.33 multipliers — the compute class of
# the reference's canonical yolov5s.tflite/onnx detector (BASELINE
# config #2), not the toy `yolov5` zoo stand-in above (which stays for
# cheap tests).  Weights are seeded (zero-egress); real checkpoints can
# enter via models/onnx.py.  All NHWC, SiLU, BN folded to per-channel
# scale/bias (inference form), one jitted program.

#: YOLOv5 anchor priors, pixels at the nominal 640 input (P3/P4/P5)
_V5S_ANCHORS_PX = {
    8: [(10, 13), (16, 30), (33, 23)],
    16: [(30, 61), (62, 45), (59, 119)],
    32: [(116, 90), (156, 198), (373, 326)],
}


def _conv_p(keys, k: int, cin: int, cout: int) -> Dict:
    return {"w": he_conv(next(keys), k, k, cin, cout),
            "scale": np.ones((cout,), np.float32),
            "bias": np.zeros((cout,), np.float32)}


def _c3_p(keys, cin: int, cout: int, n: int) -> Dict:
    ch = cout // 2
    return {
        "cv1": _conv_p(keys, 1, cin, ch),
        "cv2": _conv_p(keys, 1, cin, ch),
        "cv3": _conv_p(keys, 1, 2 * ch, cout),
        "m": [{"a": _conv_p(keys, 1, ch, ch), "b": _conv_p(keys, 3, ch, ch)}
              for _ in range(n)],
    }


def v5s_channels(width: float = 0.5):
    """Backbone channel plan after the width multiplier (c1..c5)."""
    return [rounded(c, width) for c in (64, 128, 256, 512, 1024)]


def v5s_depths(depth: float = 0.33):
    """C3 repeat counts after the depth multiplier (backbone stages)."""
    return [max(1, round(n * depth)) for n in (3, 6, 9, 3)]


def init_v5s_params(classes: int = 80, width: float = 0.5,
                    depth: float = 0.33, seed: int = 0) -> Dict:
    keys = _keygen(seed)
    c1, c2, c3, c4, c5 = v5s_channels(width)
    n1, n2, n3, n4 = v5s_depths(depth)
    nout = _ANCHORS_PER_CELL * (5 + classes)
    p: Dict = {
        "stem": _conv_p(keys, 6, 3, c1),
        "down1": _conv_p(keys, 3, c1, c2), "c3_1": _c3_p(keys, c2, c2, n1),
        "down2": _conv_p(keys, 3, c2, c3), "c3_2": _c3_p(keys, c3, c3, n2),
        "down3": _conv_p(keys, 3, c3, c4), "c3_3": _c3_p(keys, c4, c4, n3),
        "down4": _conv_p(keys, 3, c4, c5), "c3_4": _c3_p(keys, c5, c5, n4),
        "sppf_cv1": _conv_p(keys, 1, c5, c5 // 2),
        "sppf_cv2": _conv_p(keys, 1, c5 * 2, c5),
        # PANet head (top-down then bottom-up), shortcut-free C3s
        "h_lat5": _conv_p(keys, 1, c5, c4),
        "h_c3_4": _c3_p(keys, 2 * c4, c4, n4),
        "h_lat4": _conv_p(keys, 1, c4, c3),
        "h_c3_3": _c3_p(keys, 2 * c3, c3, n4),
        "h_down3": _conv_p(keys, 3, c3, c3),
        "h_c3_4b": _c3_p(keys, 2 * c3, c4, n4),
        "h_down4": _conv_p(keys, 3, c4, c4),
        "h_c3_5b": _c3_p(keys, 2 * c4, c5, n4),
    }
    for i, cin in enumerate((c3, c4, c5)):
        p[f"det{i}"] = {
            "w": he_conv(next(keys), 1, 1, cin, nout),
            "b": np.full((nout,), -4.0, np.float32),  # no-object prior
        }
    return p


def v5s_param_pspecs(params: Dict):
    """Replicated weights (DP/batch sharding is the detection serving
    axis; 7M bf16 params replicate for free)."""
    import jax
    from jax.sharding import PartitionSpec as P

    return jax.tree.map(lambda _: P(), params)


def num_predictions_v5s(size: int) -> int:
    return num_predictions(size)  # 3 anchors/cell at strides 8/16/32


def apply_v5s(params, x, *, classes: int, size: int,
              compute_dtype="bfloat16"):
    """[B, size, size, 3] float32 in [0,1] -> [B, N, 5+C] float32, the
    yolov5 layout ``tensor_decoder mode=bounding_boxes option1=yolov5``
    consumes — same contract as the toy ``apply`` above, real compute."""
    import jax
    import jax.numpy as jnp
    from jax import lax

    assert x.shape[1] == x.shape[2] == size
    cdt = jnp.dtype(compute_dtype)

    def conv(x, p, stride=1):
        y = lax.conv_general_dilated(
            x, jnp.asarray(p["w"]).astype(cdt), (stride, stride), "SAME",
            dimension_numbers=("NHWC", "HWIO", "NHWC"))
        y = y * jnp.asarray(p["scale"]).astype(cdt) \
            + jnp.asarray(p["bias"]).astype(cdt)
        return jax.nn.silu(y)

    def c3(x, p, shortcut=True):
        a = conv(x, p["cv1"])
        for bp in p["m"]:
            b = conv(conv(a, bp["a"]), bp["b"])
            a = a + b if shortcut else b
        return conv(jnp.concatenate([a, conv(x, p["cv2"])], -1), p["cv3"])

    def maxpool5(x):
        return lax.reduce_window(
            x, -jnp.inf, lax.max, (1, 5, 5, 1), (1, 1, 1, 1), "SAME")

    def up2(x):
        return jnp.repeat(jnp.repeat(x, 2, axis=1), 2, axis=2)

    h = conv(x.astype(cdt), params["stem"], 2)          # stride 2
    h = conv(h, params["down1"], 2)                     # stride 4
    h = c3(h, params["c3_1"])
    h = conv(h, params["down2"], 2)                     # stride 8
    p3 = h = c3(h, params["c3_2"])
    h = conv(h, params["down3"], 2)                     # stride 16
    p4 = h = c3(h, params["c3_3"])
    h = conv(h, params["down4"], 2)                     # stride 32
    h = c3(h, params["c3_4"])
    a = conv(h, params["sppf_cv1"])                     # SPPF
    m1 = maxpool5(a)
    m2 = maxpool5(m1)
    p5 = conv(jnp.concatenate([a, m1, m2, maxpool5(m2)], -1),
              params["sppf_cv2"])

    # PANet: top-down
    lat5 = conv(p5, params["h_lat5"])
    f4 = c3(jnp.concatenate([up2(lat5), p4], -1), params["h_c3_4"],
            shortcut=False)
    lat4 = conv(f4, params["h_lat4"])
    o3 = c3(jnp.concatenate([up2(lat4), p3], -1), params["h_c3_3"],
            shortcut=False)
    # bottom-up
    o4 = c3(jnp.concatenate([conv(o3, params["h_down3"], 2), lat4], -1),
            params["h_c3_4b"], shortcut=False)
    o5 = c3(jnp.concatenate([conv(o4, params["h_down4"], 2), lat5], -1),
            params["h_c3_5b"], shortcut=False)

    B = x.shape[0]
    # Detect head as the fused polynomial decode (see _poly_coeffs —
    # the textbook slice/meshgrid/stack form measured 16 of the 26 ms
    # batch-32 step, PROFILE_YOLO_r5.json).  Anchors are pixels of the
    # NETWORK INPUT (ultralytics convention): normalize by the actual
    # input size.
    raws, abc = [], []
    for stride, fm in ((8, o3), (16, o4), (32, o5)):
        hp = params[f"det{(stride.bit_length() - 4)}"]
        g = fm.shape[1]
        raw = lax.conv_general_dilated(
            fm, jnp.asarray(hp["w"]).astype(cdt), (1, 1), "SAME",
            dimension_numbers=("NHWC", "HWIO", "NHWC"))
        raw = raw + jnp.asarray(hp["b"]).astype(cdt)
        n_out = raw.shape[-1] // _ANCHORS_PER_CELL
        raws.append(raw.reshape(B, g * g * _ANCHORS_PER_CELL, n_out))
        anch = np.asarray(_V5S_ANCHORS_PX[stride], np.float32) / size
        abc.append(_poly_coeffs(g, n_out, _ANCHORS_PER_CELL, 4.0 * anch))
    return _poly_decode(raws, abc)


@register_model("yolov5s")
def _yolov5s(opts: Dict[str, str]) -> ModelBundle:
    classes = int(opts.get("classes", 80))
    width = float(opts.get("width", 0.5))
    depth = float(opts.get("depth", 0.33))
    seed = int(opts.get("seed", 0))
    size = int(opts.get("size", 640))
    batch = int(opts.get("batch", 1))
    dtype = opts.get("dtype", "bfloat16")
    if size % 32:
        raise ValueError(f"yolov5s size must be a multiple of 32, got {size}")
    params = init_v5s_params(classes=classes, width=width, depth=depth,
                             seed=seed)
    apply_fn = functools.partial(
        apply_v5s, classes=classes, size=size, compute_dtype=dtype)
    n = num_predictions_v5s(size)
    return ModelBundle(
        apply_fn=apply_fn,
        params=params,
        in_spec=TensorsSpec.from_string(f"3:{size}:{size}:{batch}", "float32"),
        out_spec=TensorsSpec.from_string(
            f"{5 + classes}:{n}:{batch}", "float32"),
        param_pspecs=v5s_param_pspecs(params),
        name="yolov5s",
    )
