"""YOLOv5-style single-shot detector — the second half of benchmark
config #2 ("SSD-MobileNet / YOLOv5 object detection", BASELINE.json).

Reference analog: the reference decodes YOLOv5/YOLOv8 raw output in
``tensordec-boundingbox.c``'s yolo modes (SURVEY §2.5 [UNVERIFIED]); the
model itself comes from a .tflite/.onnx file.  Zero-egress here, so the
zoo provides a compact YOLOv5-shaped network built from the shared
depthwise-separable blocks: a strided backbone with three detection
scales (strides 8/16/32), ``anchors_per_cell`` predictors per cell, and
the YOLOv5 head convention — sigmoid box/objectness/class activations
with per-cell offset decode — emitting ONE ``[B, N, 5+C]`` tensor in the
exact layout ``tensor_decoder mode=bounding_boxes option1=yolov5``
consumes (cx, cy, w, h normalized, objectness, class scores).

TPU-first: the whole predict-and-decode is one jitted program; the grid
offset/anchor math is folded into the fused pipeline program next to the
convs, and the decoder's device-NMS path (option7=device) keeps the
full decode on device.
"""

from __future__ import annotations

import functools
from typing import Dict

import numpy as np

from ..core.types import TensorsSpec
from .backbone import fm_size, he_conv, make_ops, rounded, sep_block_params, \
    sep_block_pspecs, stem_params, stem_pspecs
from .zoo import ModelBundle, register_model

#: (stride-2 steps between scales are built from these widths)
_BACKBONE = [64, 128, 256]   # strides 8, 16, 32 scale widths (pre width-mult)
_ANCHORS_PER_CELL = 3
#: YOLOv5-ish anchor sizes per scale, normalized to input size
_ANCHOR_SIZES = {
    8: [(0.04, 0.06), (0.08, 0.12), (0.12, 0.09)],
    16: [(0.14, 0.22), (0.26, 0.17), (0.24, 0.38)],
    32: [(0.45, 0.35), (0.38, 0.64), (0.75, 0.70)],
}


def _keygen(seed: int):
    import jax

    key = jax.random.PRNGKey(seed)
    while True:
        key, sub = jax.random.split(key)
        yield sub


def init_params(classes: int, width: float = 1.0, seed: int = 0,
                anchors_per_cell: int = _ANCHORS_PER_CELL,
                head_values: int = 5) -> Dict:
    """``anchors_per_cell``/``head_values`` let the anchor-free v8 head
    (1 predictor per cell, 4+C values) share the backbone with v5."""
    keys = _keygen(seed)
    params: Dict = {"stem": stem_params(keys, 3, rounded(32, width))}
    cin = rounded(32, width)
    # stem is stride 2; three stride-2 stages land strides 8/16/32 with one
    # refining block per scale
    for i, ch in enumerate(_BACKBONE):
        cout = rounded(ch, width)
        params[f"down{i}"] = sep_block_params(keys, cin, cout)   # stride 2
        params[f"block{i}"] = sep_block_params(keys, cout, cout)  # stride 1
        cin = cout
        nout = anchors_per_cell * (head_values + classes)
        params[f"head{i}"] = {
            "w": he_conv(next(keys), 1, 1, cout, nout),
            # objectness prior: like the SSD low-prior cls bias, random
            # weights should predict "no object" almost everywhere
            "b": np.full((nout,), -4.0, np.float32),
        }
    return params


def param_pspecs() -> Dict:
    from jax.sharding import PartitionSpec as P

    specs: Dict = {"stem": stem_pspecs()}
    for i in range(len(_BACKBONE)):
        specs[f"down{i}"] = sep_block_pspecs()
        specs[f"block{i}"] = sep_block_pspecs()
        specs[f"head{i}"] = {"w": P(), "b": P()}
    return specs


def num_predictions(size: int) -> int:
    return sum(
        fm_size(size, s) ** 2 * _ANCHORS_PER_CELL for s in (8, 16, 32))


def _backbone_feats(params, x, size: int, compute_dtype):
    """Shared stem + three-scale backbone: [B, size, size, 3] ->
    [(stride, feature_map, head_params)] at strides 8/16/32."""
    import jax
    import jax.numpy as jnp

    assert x.shape[1] == x.shape[2] == size, (
        f"yolo input must be {size}x{size}, got {x.shape}")
    conv2d, sbr, sep = make_ops(compute_dtype)
    cdt = jnp.dtype(compute_dtype)

    h = conv2d(x.astype(cdt), params["stem"]["w"], 2)
    h = sbr(h, params["stem"]["scale"], params["stem"]["bias"])
    # extra stride-2 maxpool after the stem puts the three down/block
    # stages at strides 8/16/32 — each head consumes its own stage's
    # feature map (channel counts match init_params' loop exactly)
    h = jax.lax.reduce_window(
        h, -jnp.inf, jax.lax.max, (1, 2, 2, 1), (1, 2, 2, 1), "SAME")
    feats = []
    for i, stride in enumerate((8, 16, 32)):
        h = sep(h, params[f"down{i}"], 2)
        h = sep(h, params[f"block{i}"], 1)
        feats.append((stride, h, params[f"head{i}"]))
    return feats


def apply(params, x, *, classes: int, size: int, compute_dtype="bfloat16"):
    """[B, size, size, 3] float32 in [0,1] -> [B, N, 5+C] float32
    (yolov5 layout).  ``size`` pins the traced input so N matches the
    bundle's negotiated out_spec."""
    import jax
    import jax.numpy as jnp

    conv2d, _, _ = make_ops(compute_dtype)
    cdt = jnp.dtype(compute_dtype)
    feats = _backbone_feats(params, x, size, compute_dtype)
    outs = []

    B = x.shape[0]
    for stride, fm, hp in feats:
        g = fm.shape[1]
        raw = conv2d(fm, hp["w"], 1) + hp["b"].astype(cdt)
        raw = raw.reshape(B, g, g, _ANCHORS_PER_CELL, 5 + classes)
        raw = raw.astype(jnp.float32)
        s = jax.nn.sigmoid(raw)
        # yolov5 decode: cell offset + sigmoid box, anchor-scaled w/h
        gy, gx = jnp.meshgrid(jnp.arange(g), jnp.arange(g), indexing="ij")
        cx = (s[..., 0] * 2.0 - 0.5 + gx[None, :, :, None]) / g
        cy = (s[..., 1] * 2.0 - 0.5 + gy[None, :, :, None]) / g
        anch = jnp.asarray(_ANCHOR_SIZES[stride], jnp.float32)  # [A, 2]
        w = (s[..., 2] * 2.0) ** 2 * anch[None, None, None, :, 0]
        hh = (s[..., 3] * 2.0) ** 2 * anch[None, None, None, :, 1]
        pred = jnp.concatenate(
            [jnp.stack([cx, cy, w, hh], axis=-1), s[..., 4:]], axis=-1)
        outs.append(pred.reshape(B, -1, 5 + classes))
    return jnp.concatenate(outs, axis=1)


def num_predictions_v8(size: int) -> int:
    return sum(fm_size(size, s) ** 2 for s in (8, 16, 32))


def apply_v8(params, x, *, classes: int, size: int,
             compute_dtype="bfloat16"):
    """[B, size, size, 3] float32 in [0,1] -> [B, 4+C, N] float32 — the
    YOLOv8 (ultralytics) channels-first export layout the reference's
    yolov8 decoder mode consumes: anchor-free (one predictor per cell, no
    objectness column), post-sigmoid class scores, normalized cx,cy,w,h."""
    import jax
    import jax.numpy as jnp

    conv2d, _, _ = make_ops(compute_dtype)
    cdt = jnp.dtype(compute_dtype)
    B = x.shape[0]
    outs = []
    for stride, fm, hp in _backbone_feats(params, x, size, compute_dtype):
        g = fm.shape[1]
        raw = conv2d(fm, hp["w"], 1) + hp["b"].astype(cdt)
        raw = raw.reshape(B, g, g, 4 + classes).astype(jnp.float32)
        s = jax.nn.sigmoid(raw)
        gy, gx = jnp.meshgrid(jnp.arange(g), jnp.arange(g), indexing="ij")
        # anchor-free decode: cell-offset centers; w/h from a per-scale
        # prior proportional to the stride (v8's dist2bbox analog)
        cx = (s[..., 0] * 2.0 - 0.5 + gx[None]) / g
        cy = (s[..., 1] * 2.0 - 0.5 + gy[None]) / g
        prior = 4.0 * stride / size
        w = (s[..., 2] * 2.0) ** 2 * prior
        hh = (s[..., 3] * 2.0) ** 2 * prior
        pred = jnp.concatenate(
            [jnp.stack([cx, cy, w, hh], axis=-1), s[..., 4:]], axis=-1)
        outs.append(pred.reshape(B, -1, 4 + classes))
    return jnp.swapaxes(jnp.concatenate(outs, axis=1), 1, 2)


@register_model("yolov8")
def _yolov8(opts: Dict[str, str]) -> ModelBundle:
    classes = int(opts.get("classes", 80))
    width = float(opts.get("width", 1.0))
    seed = int(opts.get("seed", 0))
    size = int(opts.get("size", 224))
    batch = int(opts.get("batch", 1))
    dtype = opts.get("dtype", "bfloat16")
    if size % 32:
        raise ValueError(f"yolov8 size must be a multiple of 32, got {size}")

    params = init_params(classes=classes, width=width, seed=seed,
                         anchors_per_cell=1, head_values=4)
    apply_fn = functools.partial(
        apply_v8, classes=classes, size=size, compute_dtype=dtype)
    n = num_predictions_v8(size)
    return ModelBundle(
        apply_fn=apply_fn,
        params=params,
        in_spec=TensorsSpec.from_string(f"3:{size}:{size}:{batch}", "float32"),
        out_spec=TensorsSpec.from_string(
            f"{n}:{4 + classes}:{batch}", "float32"),
        param_pspecs=param_pspecs(),
        name="yolov8",
    )


@register_model("yolov5")
def _yolo(opts: Dict[str, str]) -> ModelBundle:
    classes = int(opts.get("classes", 80))
    width = float(opts.get("width", 1.0))
    seed = int(opts.get("seed", 0))
    size = int(opts.get("size", 224))
    batch = int(opts.get("batch", 1))
    dtype = opts.get("dtype", "bfloat16")
    if size % 32:
        raise ValueError(f"yolov5 size must be a multiple of 32, got {size}")

    params = init_params(classes=classes, width=width, seed=seed)
    apply_fn = functools.partial(
        apply, classes=classes, size=size, compute_dtype=dtype)
    n = num_predictions(size)
    return ModelBundle(
        apply_fn=apply_fn,
        params=params,
        in_spec=TensorsSpec.from_string(f"3:{size}:{size}:{batch}", "float32"),
        out_spec=TensorsSpec.from_string(
            f"{5 + classes}:{n}:{batch}", "float32"),
        param_pspecs=param_pspecs(),
        name="yolov5",
    )
