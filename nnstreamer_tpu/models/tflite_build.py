"""Minimal .tflite flatbuffer WRITER — test fixtures and simple exports.

The ingestion path (models/tflite.py) needs real .tflite bytes to parse;
this environment has no TensorFlow to produce them, so this module emits
them directly (the flatbuffer wire format and the tflite schema are both
public).  It writes bottom-up exactly like the official flatbuffer
builder: bytes are PREPENDED, positions are tracked as offsets from the
buffer END, and uoffset/soffset values fall out as simple differences of
those offsets.

Only the subset the supported operator set needs: tables with scalar and
offset fields, typed vectors, strings.  See tests/test_tflite.py for the
fixture graphs built with :class:`Writer` and :func:`simple_cnn`.
"""

from __future__ import annotations

import struct
from typing import Dict, List, Optional, Sequence, Tuple

import numpy as np


class Writer:
    def __init__(self):
        self.buf = bytearray()

    # -- primitives --------------------------------------------------------
    def _prepend(self, data: bytes) -> int:
        """Prepend raw bytes; return offset-from-end of their start."""
        self.buf[:0] = data
        return len(self.buf)

    def _align(self, size: int, extra: int = 0) -> None:
        """Pad so the NEXT ``extra``-byte prepend ends ``size``-aligned."""
        while (len(self.buf) + extra) % size:
            self.buf[:0] = b"\x00"

    def _uoffset_value(self, target: int) -> int:
        """uoffset stored at the position about to be written (4 bytes)."""
        return (len(self.buf) + 4) - target

    # -- vectors / strings -------------------------------------------------
    def vector_scalar(self, fmt: str, values: Sequence) -> int:
        """Typed vector (e.g. fmt '<i' for int32); returns its offset."""
        elem = struct.calcsize(fmt)
        payload = b"".join(struct.pack(fmt, v) for v in values)
        self._align(4, extra=len(payload) + 4)
        self._prepend(payload)
        return self._prepend(struct.pack("<I", len(values)))

    def vector_bytes(self, data: bytes) -> int:
        self._align(4, extra=len(data) + 4)
        self._prepend(bytes(data))
        return self._prepend(struct.pack("<I", len(data)))

    def string(self, s: str) -> int:
        raw = s.encode("utf-8") + b"\x00"
        self._align(4, extra=len(raw) + 4)
        self._prepend(raw)
        return self._prepend(struct.pack("<I", len(raw) - 1))

    def vector_offsets(self, targets: Sequence[int]) -> int:
        """Vector of uoffsets to already-written tables/strings."""
        self._align(4, extra=4 * len(targets) + 4)
        for t in reversed(targets):
            self._prepend(struct.pack("<I", self._uoffset_value(t)))
        return self._prepend(struct.pack("<I", len(targets)))

    # -- tables ------------------------------------------------------------
    def table(self, scalars: Dict[int, Tuple[str, object]] = None,
              offsets: Dict[int, int] = None) -> int:
        """Write a table.

        ``scalars``: field id -> (struct fmt, value); ``offsets``: field id
        -> offset-from-end of an already-written child.  Fields equal to
        schema defaults should simply be omitted by the caller.
        """
        scalars = dict(scalars or {})
        offsets = dict(offsets or {})
        field_off: Dict[int, int] = {}
        # Fields in descending id order (layout order is arbitrary; the
        # vtable records wherever each lands).
        for fid in sorted(set(scalars) | set(offsets), reverse=True):
            if fid in scalars:
                fmt, v = scalars[fid]
                size = struct.calcsize(fmt)
                self._align(size, extra=size)
                field_off[fid] = self._prepend(struct.pack(fmt, v))
            else:
                self._align(4, extra=4)
                field_off[fid] = self._prepend(
                    struct.pack("<I", self._uoffset_value(offsets[fid])))
        self._align(4, extra=4)
        table_off = self._prepend(struct.pack("<i", 0))  # soffset patched below
        n_fields = (max(field_off) + 1) if field_off else 0
        vsize = 4 + 2 * n_fields
        tsize = (table_off - min(field_off.values())) if field_off else 4
        entries = b"".join(
            struct.pack("<H", table_off - field_off[i] if i in field_off else 0)
            for i in range(n_fields))
        self._align(2, extra=vsize)
        vt_off = self._prepend(
            struct.pack("<HH", vsize, tsize) + entries)
        # soffset: table_pos - vtable_pos == vt_off - table_off
        idx = len(self.buf) - table_off
        struct.pack_into("<i", self.buf, idx, vt_off - table_off)
        return table_off

    def finish(self, root: int, file_id: bytes = b"TFL3") -> bytes:
        self._align(4, extra=8)
        self._prepend(file_id)
        self._prepend(struct.pack("<I", self._uoffset_value(root)))
        return bytes(self.buf)


# ---------------------------------------------------------------------------
# tflite model assembly
# ---------------------------------------------------------------------------

_DTYPE_CODES = {np.dtype(np.float32): 0, np.dtype(np.int32): 2,
                np.dtype(np.uint8): 3, np.dtype(np.int64): 4,
                np.dtype(np.int8): 9}

_PAD_CODES = {"SAME": 0, "VALID": 1}
_ACT_CODES = {None: 0, "relu": 1, "relu6": 3, "tanh": 4}
_OP_CODES = {"ADD": 0, "AVERAGE_POOL_2D": 1, "CONCATENATION": 2,
             "CONV_2D": 3, "DEPTHWISE_CONV_2D": 4, "FULLY_CONNECTED": 9,
             "LOGISTIC": 14, "MAX_POOL_2D": 17, "MUL": 18, "RELU": 19,
             "RELU6": 21, "RESHAPE": 22, "RESIZE_BILINEAR": 23,
             "SOFTMAX": 25, "SPACE_TO_DEPTH": 26, "TANH": 28, "PAD": 34,
             "TRANSPOSE": 39, "MEAN": 40, "SUB": 41, "DIV": 42,
             "SQUEEZE": 43}


class ModelWriter:
    """Assemble a single-subgraph float32 tflite model op by op.

    >>> mw = ModelWriter()
    >>> x = mw.add_input([1, 8, 8, 3])
    >>> w = mw.add_const(np.zeros((4, 3, 3, 3), np.float32))
    >>> y = mw.add_op("CONV_2D", [x, w], out_shape=[1, 4, 4, 4],
    ...               options={"padding": "SAME", "stride": (2, 2)})
    >>> blob = mw.finish(outputs=[y])
    """

    def __init__(self):
        self.tensors: List[Tuple[List[int], np.dtype, str, int]] = []
        self.buffers: List[Optional[bytes]] = [None]  # buffer 0 = empty
        self.inputs: List[int] = []
        self.ops: List[Tuple[str, List[int], List[int], Dict]] = []

    def _tensor(self, shape, dtype, name, data: Optional[np.ndarray],
                quant: Optional[Dict] = None) -> int:
        if data is not None:
            self.buffers.append(np.ascontiguousarray(data).tobytes())
            bufidx = len(self.buffers) - 1
        else:
            bufidx = 0
        self.tensors.append(
            (list(shape), np.dtype(dtype), name, bufidx, quant))
        return len(self.tensors) - 1

    def add_input(self, shape, dtype=np.float32, name="input",
                  quant_scale: Optional[Sequence[float]] = None,
                  quant_zero_point: Optional[Sequence[int]] = None) -> int:
        quant = None
        if quant_scale:
            quant = {"scale": list(quant_scale)}
            if quant_zero_point:
                quant["zero_point"] = [int(z) for z in quant_zero_point]
        idx = self._tensor(shape, dtype, name, None, quant)
        self.inputs.append(idx)
        return idx

    def add_const(self, array: np.ndarray, name="const",
                  quant_scale: Optional[Sequence[float]] = None,
                  quant_zero_point: Optional[Sequence[int]] = None,
                  quant_axis: int = 0) -> int:
        """``quant_scale``/``quant_zero_point``/``quant_axis`` write a
        QuantizationParameters table (per-tensor or per-axis) — exercised
        by the reader's weight dequantization and the quantized-activation
        IO contract."""
        quant = None
        if quant_scale:
            quant = {"scale": list(quant_scale), "axis": int(quant_axis)}
            if quant_zero_point:
                quant["zero_point"] = [int(z) for z in quant_zero_point]
        return self._tensor(array.shape, array.dtype, name, array, quant)

    def add_op(self, kind: str, inputs: List[int], out_shape,
               out_dtype=np.float32, options: Optional[Dict] = None,
               quant_scale: Optional[Sequence[float]] = None,
               quant_zero_point: Optional[Sequence[int]] = None) -> int:
        """``quant_scale``/``quant_zero_point`` annotate the op's OUTPUT
        activation — with an integer ``out_dtype`` this is how a
        fully-quantized graph's interior is written."""
        quant = None
        if quant_scale:
            quant = {"scale": list(quant_scale)}
            if quant_zero_point:
                quant["zero_point"] = [int(z) for z in quant_zero_point]
        out = self._tensor(out_shape, out_dtype, f"{kind.lower()}_out",
                           None, quant)
        self.ops.append((kind, list(inputs), [out], dict(options or {})))
        return out

    # -- serialization -----------------------------------------------------
    @staticmethod
    def _options(w: Writer, kind: str, o: Dict) -> Tuple[int, Optional[int]]:
        """Returns (builtin_options_type enum, options table offset)."""
        act = _ACT_CODES[o.get("act")]
        pad = _PAD_CODES[o.get("padding", "SAME")]
        sh, sw = o.get("stride", (1, 1))
        if kind == "CONV_2D":
            return 1, w.table(scalars={0: ("<b", pad), 1: ("<i", sw),
                                       2: ("<i", sh), 3: ("<b", act)})
        if kind == "DEPTHWISE_CONV_2D":
            return 2, w.table(scalars={0: ("<b", pad), 1: ("<i", sw),
                                       2: ("<i", sh),
                                       3: ("<i", o.get("multiplier", 1)),
                                       4: ("<b", act)})
        if kind in ("AVERAGE_POOL_2D", "MAX_POOL_2D"):
            fh, fw = o["filter"]
            return 5, w.table(scalars={0: ("<b", pad), 1: ("<i", sw),
                                       2: ("<i", sh), 3: ("<i", fw),
                                       4: ("<i", fh), 5: ("<b", act)})
        if kind == "FULLY_CONNECTED":
            return 8, w.table(scalars={0: ("<b", act)})
        if kind == "SOFTMAX":
            return 9, w.table(scalars={0: ("<f", o.get("beta", 1.0))})
        if kind == "RESHAPE":
            if "new_shape" in o:
                vec = w.vector_scalar("<i", o["new_shape"])
                return 17, w.table(offsets={0: vec})
            return 17, None
        if kind == "ADD":
            return 11, w.table(scalars={0: ("<b", act)})
        if kind == "MUL":
            return 21, w.table(scalars={0: ("<b", act)})
        if kind == "SUB":
            return 28, w.table(scalars={0: ("<b", act)})
        if kind == "DIV":
            return 29, w.table(scalars={0: ("<b", act)})
        if kind == "TRANSPOSE":
            return 26, w.table()
        if kind == "SPACE_TO_DEPTH":
            return 19, w.table(scalars={0: ("<i", o["block"])})
        if kind == "RESIZE_BILINEAR":
            return 15, w.table(scalars={
                2: ("<B", 1 if o.get("align_corners") else 0),
                3: ("<B", 1 if o.get("half_pixel") else 0)})
        if kind == "CONCATENATION":
            return 10, w.table(scalars={0: ("<i", o.get("axis", 0)),
                                        1: ("<b", act)})
        if kind == "MEAN":
            return 27, w.table(scalars={0: ("<b", 1 if o.get("keep_dims") else 0)})
        if kind == "SQUEEZE":
            if "squeeze_dims" in o:
                vec = w.vector_scalar("<i", o["squeeze_dims"])
                return 30, w.table(offsets={0: vec})
            return 30, None
        if o:
            raise ValueError(
                f"{kind}: options {sorted(o)} given but this writer emits "
                "no options table for the op — they would be silently lost")
        return 0, None

    def finish(self, outputs: List[int]) -> bytes:
        w = Writer()
        # op codes, deduped, in first-use order
        kinds = []
        for kind, *_ in self.ops:
            if kind not in kinds:
                kinds.append(kind)
        opcode_tabs = []
        for kind in kinds:
            code = _OP_CODES[kind]
            # write both the deprecated byte field and the int32 field the
            # way current TF exports do
            opcode_tabs.append(w.table(
                scalars={0: ("<b", min(code, 127)), 3: ("<i", code)}))
        opcodes_vec = w.vector_offsets(opcode_tabs)

        buffer_tabs = []
        for data in self.buffers:
            if data is None:
                buffer_tabs.append(w.table())
            else:
                buffer_tabs.append(w.table(offsets={0: w.vector_bytes(data)}))
        buffers_vec = w.vector_offsets(buffer_tabs)

        tensor_tabs = []
        for shape, dtype, name, bufidx, quant in self.tensors:
            shape_vec = w.vector_scalar("<i", shape)
            name_off = w.string(name)
            offs = {0: shape_vec, 3: name_off}
            if quant is not None:
                q_offs = {2: w.vector_scalar("<f", quant["scale"])}
                if quant.get("zero_point"):
                    q_offs[3] = w.vector_scalar("<q", quant["zero_point"])
                q_scal = {}
                if quant.get("axis"):
                    q_scal[6] = ("<i", quant["axis"])
                offs[4] = w.table(scalars=q_scal, offsets=q_offs)
            tensor_tabs.append(w.table(
                scalars={1: ("<b", _DTYPE_CODES[dtype]),
                         2: ("<I", bufidx)},
                offsets=offs))
        tensors_vec = w.vector_offsets(tensor_tabs)

        op_tabs = []
        for kind, ins, outs, opts in self.ops:
            in_vec = w.vector_scalar("<i", ins)
            out_vec = w.vector_scalar("<i", outs)
            otype, otab = self._options(w, kind, opts)
            offs = {1: in_vec, 2: out_vec}
            scal = {0: ("<I", kinds.index(kind))}
            if otab is not None:
                scal[3] = ("<B", otype)
                offs[4] = otab
            op_tabs.append(w.table(scalars=scal, offsets=offs))
        ops_vec = w.vector_offsets(op_tabs)

        in_vec = w.vector_scalar("<i", self.inputs)
        out_vec = w.vector_scalar("<i", outputs)
        sg = w.table(offsets={0: tensors_vec, 1: in_vec, 2: out_vec,
                              3: ops_vec})
        sg_vec = w.vector_offsets([sg])
        desc = w.string("nnstreamer_tpu tflite_build")
        model = w.table(scalars={0: ("<I", 3)},
                        offsets={1: opcodes_vec, 2: sg_vec, 3: desc,
                                 4: buffers_vec})
        return w.finish(model)
