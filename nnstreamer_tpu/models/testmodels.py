"""Tiny deterministic zoo models for tests and examples.

Reference analog: the custom example models used by the reference's test
suites (``custom_example_passthrough/scaler/average`` — SURVEY §4).
"""

from __future__ import annotations

from typing import Dict

import numpy as np

from ..core.types import TensorsSpec
from .zoo import ModelBundle, register_model


@register_model("passthrough")
def _passthrough(opts: Dict[str, str]) -> ModelBundle:
    dims = opts.get("dims", "3:4:4:1")
    dtype = opts.get("dtype", "float32")
    spec = TensorsSpec.from_string(dims, dtype)
    return ModelBundle(
        apply_fn=lambda params, *xs: tuple(xs),
        params=(),
        in_spec=spec,
        out_spec=spec,
        name="passthrough",
    )


@register_model("scaler")
def _scaler(opts: Dict[str, str]) -> ModelBundle:
    scale = float(opts.get("scale", 2.0))
    dims = opts.get("dims", "3:4:4:1")
    spec = TensorsSpec.from_string(dims, "float32")
    return ModelBundle(
        apply_fn=lambda params, x: x * params["scale"],
        params={"scale": np.float32(scale)},
        in_spec=spec,
        out_spec=spec,
        name="scaler",
    )


@register_model("average")
def _average(opts: Dict[str, str]) -> ModelBundle:
    """Mean over all non-batch axes -> one scalar per batch item."""
    dims = opts.get("dims", "3:4:4:1")
    in_spec = TensorsSpec.from_string(dims, "float32")
    n = in_spec[0].dims[-1]

    def apply_fn(params, x):
        import jax.numpy as jnp

        return jnp.mean(
            x.astype(jnp.float32), axis=tuple(range(1, x.ndim))
        ).reshape(n, 1)

    return ModelBundle(
        apply_fn=apply_fn,
        params=(),
        in_spec=in_spec,
        out_spec=TensorsSpec.from_string(f"1:{n}", "float32"),
        name="average",
    )
