"""Llama-family decoder-only LM — benchmark config #5 (token streaming).

Reference analog: the reference's LLM capability is the llama.cpp
sub-plugin (``ext/nnstreamer/tensor_filter/tensor_filter_llamacpp.cc``,
SURVEY §2.4 [UNVERIFIED]) — prompt in, generated tokens streamed out as
flexible tensors, with the KV cache and sampling living inside the wrapped
C++ runtime.  Here the whole decode loop is a JAX program designed for TPU:

* **Stacked layers + ``lax.scan``**: all L transformer blocks live in one
  pytree with a leading layer axis, so XLA compiles ONE block and scans it —
  compile time stays flat as the model deepens, and remat slots in cleanly.
* **KV cache as a functional carry**: ``[L, B, S_max, H_kv, D]`` bf16
  buffers updated with ``lax.dynamic_update_slice`` at the decode position;
  one fused XLA program per decode step, weights resident in HBM.
* **GQA** (n_kv_heads <= n_heads), **RoPE**, **RMSNorm**, **SwiGLU** — the
  Llama-2/3 block, dims kept multiples of 128 so matmuls tile onto the MXU.
* **TP via GSPMD**: ``param_pspecs`` shard attention heads and FFN hidden
  over the ``model`` mesh axis; jit with those shardings and XLA inserts the
  all-reduces on ICI (no hand-written collectives).
* **Sequence parallel**: :func:`forward_seq_parallel` runs the full forward
  under ``shard_map`` over the ``seq`` axis with ring attention
  (parallel/ring.py) — long-context prefill where no chip ever holds the
  whole sequence.

No egress in this environment, so weights are deterministic-random; real
checkpoints enter by filling the same pytree layout.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Dict, List, Optional, Tuple

import numpy as np

from ..core.types import TensorFormat, TensorsSpec
from .zoo import ModelBundle, register_model


@dataclasses.dataclass(frozen=True)
class LlamaConfig:
    vocab: int = 32000
    dim: int = 4096
    n_layers: int = 32
    n_heads: int = 32
    n_kv_heads: int = 32
    ffn_hidden: int = 11008
    max_seq: int = 4096
    rope_theta: float = 10000.0
    norm_eps: float = 1e-5

    @property
    def head_dim(self) -> int:
        return self.dim // self.n_heads


#: Named size presets.  ``llama2_7b`` is the reference benchmark config #5
#: shape; the tiny presets serve tests and the CPU-mesh dry run.
PRESETS: Dict[str, LlamaConfig] = {
    "llama2_7b": LlamaConfig(),
    "llama_tiny": LlamaConfig(
        vocab=512, dim=128, n_layers=2, n_heads=4, n_kv_heads=2,
        ffn_hidden=256, max_seq=256,
    ),
    "llama_small": LlamaConfig(
        vocab=2048, dim=512, n_layers=4, n_heads=8, n_kv_heads=4,
        ffn_hidden=1024, max_seq=1024,
    ),
}


def init_params(cfg: LlamaConfig, seed: int = 0, dtype="float32") -> Dict:
    """Deterministic-random params; layer weights stacked on a leading axis.

    ``dtype`` is the storage dtype of the generated weights.  7B-scale runs
    pass ``bfloat16`` so the full parameter set is materialized directly on
    device at 2 bytes/param (13.5 GB — fits one v5e chip's HBM; an f32
    intermediate would not), standing in for a real checkpoint upload the
    zero-egress environment can't do.  Real checkpoints enter by filling
    the same pytree layout.
    """
    import jax
    import jax.numpy as jnp

    dt = jnp.dtype(dtype)
    k_embed, k_layers, k_out = jax.random.split(jax.random.PRNGKey(seed), 3)

    def norm_init(key, shape, fan_in):
        scale = np.sqrt(2.0 / max(1, fan_in)).astype(np.float32)
        return jax.random.normal(key, shape, dt) * scale.astype(dt)

    L, D, H, Hkv, F = (cfg.n_layers, cfg.dim, cfg.n_heads, cfg.n_kv_heads,
                       cfg.ffn_hidden)
    hd = cfg.head_dim
    ks = jax.random.split(k_layers, 7)
    layers = {
        "wq": norm_init(ks[0], (L, D, H * hd), D),
        "wk": norm_init(ks[1], (L, D, Hkv * hd), D),
        "wv": norm_init(ks[2], (L, D, Hkv * hd), D),
        "wo": norm_init(ks[3], (L, H * hd, D), H * hd),
        "w_gate": norm_init(ks[4], (L, D, F), D),
        "w_up": norm_init(ks[5], (L, D, F), D),
        "w_down": norm_init(ks[6], (L, F, D), F),
        "ln_attn": np.ones((L, D), np.float32),
        "ln_mlp": np.ones((L, D), np.float32),
    }
    return {
        "embed": norm_init(k_embed, (cfg.vocab, D), D) * 0.5,
        "layers": layers,
        "ln_out": np.ones((D,), np.float32),
        "lm_head": norm_init(k_out, (D, cfg.vocab), D),
    }


def _init_params_quant(cfg: LlamaConfig, seed: int, gen_dtype,
                       qmat, q2d, suffix: str, groups=None) -> Dict:
    """Generate-then-quantize one matrix at a time.

    ``quantize_*(init_params(cfg))`` needs the full-precision tree AND
    the growing quantized tree resident together — at 7B that transient
    (13.5 GB bf16 + quantized outputs) overflows a 16 GB v5e chip, which
    the round-3 on-chip session hit as RESOURCE_EXHAUSTED.  Here each big
    mat is generated, quantized (donated), and freed before the next is
    drawn: peak HBM ~ final quantized tree + ONE bf16 mat.  Draws the
    identical RNG stream as :func:`init_params` — key order and shapes
    here are the single place that invariant lives for BOTH int8 and
    int4 — so the result is exactly
    ``quantize_*(init_params(cfg, seed, gen_dtype))`` (asserted by tests
    on the small presets)."""
    import jax
    import jax.numpy as jnp

    dt = jnp.dtype(gen_dtype)
    k_embed, k_layers, k_out = jax.random.split(jax.random.PRNGKey(seed), 3)

    def norm_init(key, shape, fan_in):
        scale = np.sqrt(2.0 / max(1, fan_in)).astype(np.float32)
        return jax.random.normal(key, shape, dt) * scale.astype(dt)

    L, D, H, Hkv, F = (cfg.n_layers, cfg.dim, cfg.n_heads, cfg.n_kv_heads,
                       cfg.ffn_hidden)
    hd = cfg.head_dim
    ks = jax.random.split(k_layers, 7)
    shapes = {
        "wq": ((L, D, H * hd), D),
        "wk": ((L, D, Hkv * hd), D),
        "wv": ((L, D, Hkv * hd), D),
        "wo": ((L, H * hd, D), H * hd),
        "w_gate": ((L, D, F), D),
        "w_up": ((L, D, F), D),
        "w_down": ((L, F, D), F),
    }
    import jax.numpy as _jnp

    qlay: Dict = {
        "ln_attn": np.ones((L, D), np.float32),
        "ln_mlp": np.ones((L, D), np.float32),
    }
    key_of = {name: ks[i] for i, name in enumerate(_QUANT_MATS)}
    if groups is None:
        groups = tuple((name, (name,)) for name in _QUANT_MATS)
    for gname, members in groups:
        # quantize each member with ITS ORIGINAL DONATED — per-output-
        # channel scales make member-wise quantization exactly equal to
        # quantizing the concatenation, so fused groups concatenate the
        # PACKED outputs (0.5-1 byte/param) and the one-bf16-mat peak
        # holds for fused layouts too
        qs = []
        for name in members:
            shape, fan = shapes[name]
            qs.append(qmat(norm_init(key_of[name], shape, fan)))
        if len(qs) == 1:
            q, s = qs[0]
        else:
            q = _jnp.concatenate([p for p, _ in qs], axis=-1)
            s = _jnp.concatenate([sc for _, sc in qs], axis=-1)
        qlay[gname + suffix] = q
        qlay[gname + "_s"] = s
    q, s = q2d(norm_init(k_out, (D, cfg.vocab), D))
    return {
        "embed": norm_init(k_embed, (cfg.vocab, D), D) * 0.5,
        "layers": qlay,
        "ln_out": np.ones((D,), np.float32),
        "lm_head" + suffix: q,
        "lm_head_s": s,
    }


def init_params_int8(cfg: LlamaConfig, seed: int = 0,
                     gen_dtype="bfloat16") -> Dict:
    """int8 per-mat generate-quantize-donate init (see
    :func:`_init_params_quant`)."""
    return _init_params_quant(cfg, seed, gen_dtype, _qmat_layered(),
                              _qmat_2d(), "_q")


def init_params_int4(cfg: LlamaConfig, seed: int = 0,
                     gen_dtype="bfloat16") -> Dict:
    """int4 generate-quantize-pack-donate init (see
    :func:`_init_params_quant`), grouped per ``_INT4_GROUPS`` — members
    quantize one at a time (donated) and only the PACKED nibbles
    concatenate, so the one-bf16-mat HBM peak holds."""
    import jax

    from ..ops import int4_matmul as _i4

    q2d = jax.jit(_i4.quantize_int4, donate_argnums=(0,))
    return _init_params_quant(cfg, seed, gen_dtype, _qmat4_layered(),
                              q2d, "_p", groups=_INT4_GROUPS)


def load_checkpoint(path: str, cfg: Optional[LlamaConfig] = None,
                    dtype="bfloat16") -> Tuple[Dict, LlamaConfig]:
    """Fill the documented pytree layout from a REAL checkpoint file.

    ``path``: a ``.safetensors`` file, a HF sharded checkpoint directory /
    ``*.safetensors.index.json``, an ``.npz`` (models/checkpoint.py), or a
    llama.cpp ``.gguf`` (models/gguf.py — F32/F16/BF16, config from the
    ``llama.*`` metadata keys, RoPE layout converted).
    Accepts HF ``model.layers.N.self_attn.q_proj.weight`` naming (weights
    transposed from [out,in] linear layout to this module's [in,out]
    matmul layout — no RoPE re-permutation is needed because :func:`_rope`
    uses the same rotate-half convention HF checkpoints are stored for) or
    this module's own stacked naming (``layers.wq`` etc., the npz
    round-trip).  Per-layer tensors are stacked on the leading layer axis
    for the ``lax.scan`` block.

    ``cfg=None`` reads a HF ``config.json`` next to the checkpoint; without
    one, dims are inferred from tensor shapes with head_dim assumed 128
    (the Llama convention) — pass an explicit cfg when that's wrong.
    Returns ``(params, cfg)``; weights cast to ``dtype`` (norms stay f32,
    matching :func:`init_params`).
    """
    import os

    from . import checkpoint as ckpt

    dt = _resolve_param_dtype(dtype)
    if path.endswith(".gguf"):
        params, cfg, _tok = _load_gguf(path, cfg, dt)
        return params, cfg
    tensors = ckpt.load_tensors(path)

    if "embed" in tensors and "layers.wq" in tensors:  # native stacked npz
        if cfg is None:
            cfg = _read_config_json(path) or _infer_config_native(tensors)
        params = {
            "embed": np.asarray(tensors["embed"]).astype(dt),
            "layers": {k.split(".", 1)[1]:
                       np.asarray(tensors[k]).astype(
                           np.float32 if k.startswith("layers.ln") else dt)
                       for k in tensors if k.startswith("layers.")},
            "ln_out": np.asarray(tensors["ln_out"]).astype(np.float32),
            "lm_head": np.asarray(tensors["lm_head"]).astype(dt),
        }
        return params, cfg

    if cfg is None:
        cfg = _infer_config_hf(path, tensors)

    def get(name):
        if name not in tensors:
            raise ckpt.CheckpointError(
                f"{path}: missing tensor {name!r} "
                f"(have {len(tensors)} tensors, e.g. "
                f"{sorted(tensors)[:3]})")
        return np.asarray(tensors[name])

    def stack_T(fmt):
        return np.stack([get(fmt.format(i)).T.astype(dt)
                         for i in range(cfg.n_layers)])

    def stack_f32(fmt):
        return np.stack([get(fmt.format(i)).astype(np.float32)
                         for i in range(cfg.n_layers)])

    p = "model.layers.{}."
    layers = {
        "wq": stack_T(p + "self_attn.q_proj.weight"),
        "wk": stack_T(p + "self_attn.k_proj.weight"),
        "wv": stack_T(p + "self_attn.v_proj.weight"),
        "wo": stack_T(p + "self_attn.o_proj.weight"),
        "w_gate": stack_T(p + "mlp.gate_proj.weight"),
        "w_up": stack_T(p + "mlp.up_proj.weight"),
        "w_down": stack_T(p + "mlp.down_proj.weight"),
        "ln_attn": stack_f32(p + "input_layernorm.weight"),
        "ln_mlp": stack_f32(p + "post_attention_layernorm.weight"),
    }
    embed = get("model.embed_tokens.weight").astype(dt)
    if "lm_head.weight" in tensors:
        lm_head = get("lm_head.weight").T.astype(dt)
    else:  # tied embeddings
        lm_head = np.ascontiguousarray(embed.T)
    params = {
        "embed": embed,
        "layers": layers,
        "ln_out": get("model.norm.weight").astype(np.float32),
        "lm_head": lm_head,
    }
    _check_shapes(params, cfg, path)
    return params, cfg


def _np_bf16():
    from ..core.types import bfloat16

    return bfloat16


def _resolve_param_dtype(dtype) -> np.dtype:
    """ONE home for the checkpoint param-dtype rule (bfloat16 through the
    core.types alias, anything else verbatim) — load_checkpoint and the
    gguf bundle path must never drift apart here."""
    if dtype == "float32":
        return np.dtype("float32")
    if dtype == "bfloat16":
        return _np_bf16()
    return np.dtype(dtype)


def _rope_permute(w: np.ndarray, n_heads: int) -> np.ndarray:
    """ggml/Meta interleaved-pair RoPE layout -> rotate-half layout (the
    permutation HF applies converting Meta checkpoints; models/llama.py's
    _rope is rotate-half).  ``w``: [n_heads*head_dim, in_features]."""
    out, dim2 = w.shape
    hd = out // n_heads
    return np.ascontiguousarray(
        w.reshape(n_heads, hd // 2, 2, dim2).swapaxes(1, 2).reshape(
            out, dim2))


def _load_gguf(path: str, cfg: Optional[LlamaConfig],
               dt) -> Tuple[Dict, LlamaConfig]:
    """llama.cpp GGUF -> the stacked pytree (reference: the llamacpp
    sub-plugin's model format, SURVEY §2.4)."""
    from . import gguf

    meta, tensors = gguf.read(path)

    def get(name):
        if name not in tensors:
            raise gguf.GGUFError(
                f"{path}: missing tensor {name!r} (have e.g. "
                f"{sorted(tensors)[:3]})")
        return np.asarray(tensors[name])

    if cfg is None:
        arch = str(meta.get("general.architecture", "llama"))

        def m(key, default=None):
            v = meta.get(f"{arch}.{key}", default)
            if v is None:
                raise gguf.GGUFError(
                    f"{path}: metadata {arch}.{key} missing and no cfg "
                    "given")
            return v

        vocab = get("token_embd.weight").shape[0]
        n_heads = int(m("attention.head_count"))
        ctx = int(m("context_length", 4096))
        if ctx > 8192:
            from ..core.log import logger

            logger(__name__).warning(
                "%s: clamping context_length %d to 8192 (KV-cache HBM "
                "budget); pass custom=max_seq:%d to tensor_filter to "
                "raise it", path, ctx, ctx)
        cfg = LlamaConfig(
            vocab=vocab,
            dim=int(m("embedding_length")),
            n_layers=int(m("block_count")),
            n_heads=n_heads,
            n_kv_heads=int(m("attention.head_count_kv", n_heads)),
            ffn_hidden=int(m("feed_forward_length")),
            max_seq=min(ctx, 8192),
            rope_theta=float(m("rope.freq_base", 10000.0)),
            norm_eps=float(m("attention.layer_norm_rms_epsilon", 1e-5)),
        )

    def stack(fmt, heads=None):
        mats = []
        for i in range(cfg.n_layers):
            w = get(fmt.format(i))
            if heads is not None:
                w = _rope_permute(w, heads)
            mats.append(w.T.astype(dt))
        return np.stack(mats)

    def stack_norm(fmt):
        return np.stack([get(fmt.format(i)).astype(np.float32)
                         for i in range(cfg.n_layers)])

    p = "blk.{}."
    layers = {
        "wq": stack(p + "attn_q.weight", heads=cfg.n_heads),
        "wk": stack(p + "attn_k.weight", heads=cfg.n_kv_heads),
        "wv": stack(p + "attn_v.weight"),
        "wo": stack(p + "attn_output.weight"),
        "w_gate": stack(p + "ffn_gate.weight"),
        "w_up": stack(p + "ffn_up.weight"),
        "w_down": stack(p + "ffn_down.weight"),
        "ln_attn": stack_norm(p + "attn_norm.weight"),
        "ln_mlp": stack_norm(p + "ffn_norm.weight"),
    }
    embed = get("token_embd.weight").astype(dt)
    if "output.weight" in tensors:
        lm_head = get("output.weight").T.astype(dt)
    else:  # tied embeddings
        lm_head = np.ascontiguousarray(embed.T)
    params = {
        "embed": embed,
        "layers": layers,
        "ln_out": get("output_norm.weight").astype(np.float32),
        "lm_head": lm_head,
    }
    _check_shapes(params, cfg, path)
    # the vocab rode along in the SAME metadata parse — build the
    # tokenizer here instead of re-reading the file; returned alongside
    # the weights so build_from_checkpoint can attach it to the bundle
    tok = None
    if "tokenizer.ggml.tokens" in meta:
        from .tokenizer import SentencePieceTokenizer

        tok = SentencePieceTokenizer.from_gguf_meta(meta)
    return params, cfg, tok


def _read_config_json(path: str) -> Optional[LlamaConfig]:
    """HF-style config.json next to (or inside) ``path``, if present."""
    import json
    import os

    base = path if os.path.isdir(path) else os.path.dirname(path)
    cfg_path = os.path.join(base, "config.json")
    if not os.path.exists(cfg_path):
        return None
    with open(cfg_path) as f:
        c = json.load(f)
    return LlamaConfig(
        vocab=c["vocab_size"], dim=c["hidden_size"],
        n_layers=c["num_hidden_layers"],
        n_heads=c["num_attention_heads"],
        n_kv_heads=c.get("num_key_value_heads",
                         c["num_attention_heads"]),
        ffn_hidden=c["intermediate_size"],
        max_seq=min(c.get("max_position_embeddings", 4096), 8192),
        rope_theta=float(c.get("rope_theta", 10000.0)),
        norm_eps=float(c.get("rms_norm_eps", 1e-5)),
    )


def _infer_config_hf(path: str, tensors: Dict) -> LlamaConfig:
    cfg = _read_config_json(path)
    if cfg is not None:
        return cfg
    # shape inference: head_dim is 128 by Llama convention
    from . import checkpoint as ckpt

    try:
        vocab, dim = tensors["model.embed_tokens.weight"].shape
        layer_ids = [int(k.split(".")[2]) for k in tensors
                     if k.startswith("model.layers.")]
        n_layers = 1 + max(layer_ids)
        ffn = tensors["model.layers.0.mlp.gate_proj.weight"].shape[0]
        kv_out = tensors["model.layers.0.self_attn.k_proj.weight"].shape[0]
    except (KeyError, ValueError) as e:
        raise ckpt.CheckpointError(
            f"{path}: not a Llama-family checkpoint (no config.json and "
            f"HF tensor names absent: {e}; have e.g. "
            f"{sorted(tensors)[:3]})") from e
    hd = 128 if dim % 128 == 0 and dim >= 128 else 64
    return LlamaConfig(vocab=vocab, dim=dim, n_layers=n_layers,
                       n_heads=dim // hd, n_kv_heads=kv_out // hd,
                       ffn_hidden=ffn)


def _infer_config_native(tensors: Dict) -> LlamaConfig:
    L, D, qout = tensors["layers.wq"].shape
    vocab = tensors["embed"].shape[0]
    F = tensors["layers.w_gate"].shape[2]
    kvout = tensors["layers.wk"].shape[2]
    hd = 128 if D % 128 == 0 and D >= 128 else 64
    if qout % hd:
        hd = qout  # degenerate tiny models: one head
    return LlamaConfig(vocab=vocab, dim=D, n_layers=L, n_heads=qout // hd,
                       n_kv_heads=kvout // hd, ffn_hidden=F)


def _check_shapes(params: Dict, cfg: LlamaConfig, path: str) -> None:
    L, D, H, Hkv, F = (cfg.n_layers, cfg.dim, cfg.n_heads, cfg.n_kv_heads,
                       cfg.ffn_hidden)
    hd = cfg.head_dim
    want = {
        ("embed",): (cfg.vocab, D),
        ("layers", "wq"): (L, D, H * hd),
        ("layers", "wk"): (L, D, Hkv * hd),
        ("layers", "wv"): (L, D, Hkv * hd),
        ("layers", "wo"): (L, H * hd, D),
        ("layers", "w_gate"): (L, D, F),
        ("layers", "w_up"): (L, D, F),
        ("layers", "w_down"): (L, F, D),
        ("lm_head",): (D, cfg.vocab),
    }
    for keys, shape in want.items():
        node = params
        for k in keys:
            node = node[k]
        if tuple(node.shape) != shape:
            raise ValueError(
                f"{path}: {'.'.join(keys)} has shape {tuple(node.shape)}, "
                f"config wants {shape} — wrong config for this checkpoint?")


_QUANT_MATS = ("wq", "wk", "wv", "wo", "w_gate", "w_up", "w_down")


@functools.cache
def _qmat_layered():
    """jit: [L, in, out] weights -> (int8 [L, in, out], f32 [L, 1, out])
    per-output-channel scales; input donated so the full-precision buffer
    frees as soon as its int8 replacement lands."""
    import jax
    import jax.numpy as jnp

    @functools.partial(jax.jit, donate_argnums=(0,))
    def qmat(w):
        def one(wl):
            w32 = wl.astype(jnp.float32)
            s = jnp.maximum(jnp.abs(w32).max(axis=0, keepdims=True) / 127.0,
                            1e-8)
            q = jnp.clip(jnp.round(w32 / s), -127, 127).astype(jnp.int8)
            return q, s
        return jax.lax.map(one, w)

    return qmat


@functools.cache
def _qmat_2d():
    import jax
    import jax.numpy as jnp

    @functools.partial(jax.jit, donate_argnums=(0,))
    def qmat2d(w):  # [D, vocab]
        w32 = w.astype(jnp.float32)
        s = jnp.maximum(jnp.abs(w32).max(axis=0, keepdims=True) / 127.0,
                        1e-8)
        q = jnp.clip(jnp.round(w32 / s), -127, 127).astype(jnp.int8)
        return q, s

    return qmat2d


@functools.cache
def _qmat4_layered():
    """jit: [L, in, out] weights -> (packed int4 [L, in/2, out] int8,
    f32 [L, 1, out] scales); input donated."""
    import jax

    from ..ops import int4_matmul as _i4

    @functools.partial(jax.jit, donate_argnums=(0,))
    def qmat(w):
        return jax.lax.map(_i4.quantize_int4, w)

    return qmat


#: int4 fused-mat grouping: per-call fixed cost halves the Pallas
#: kernel's throughput on the 4096-out mats (8.4 MB/call measured
#: 176 GB/s vs 373 at >=22 MB), so q/k/v and gate/up quantize into ONE
#: packed mat each — per-output-channel scales make the concatenation
#: exactly equal to quantizing separately.
_INT4_GROUPS = (("wqkv", ("wq", "wk", "wv")), ("wo", ("wo",)),
                ("wgu", ("w_gate", "w_up")), ("w_down", ("w_down",)))


def quantize_int4_params(params: Dict) -> Dict:
    """Weight-only int4 with per-output-channel scales, nibble-packed
    for the Pallas decode kernel (ops/int4_matmul.py): 0.5 bytes/param
    on the seven big mats + lm_head -> ~3.4 GB/token at 7B vs 6.5 int8.
    q/k/v and gate/up fuse into single packed mats (_INT4_GROUPS).
    Same on-device, donated discipline as :func:`quantize_int8`.
    """
    import jax
    import jax.numpy as jnp

    from ..ops import int4_matmul as _i4

    qmat = _qmat4_layered()
    q2d = jax.jit(_i4.quantize_int4, donate_argnums=(0,))
    lay = params["layers"]
    qlay: Dict = {"ln_attn": lay["ln_attn"], "ln_mlp": lay["ln_mlp"]}
    for name, members in _INT4_GROUPS:
        # member-wise quantize with each ORIGINAL donated (the 7B HBM
        # discipline: full-precision mats free as their packed
        # replacements land); per-output-channel scales make this
        # exactly equal to quantizing the concatenation, so only the
        # tiny packed nibbles + scales concatenate
        qs = [qmat(jnp.asarray(lay[k])) for k in members]
        if len(qs) == 1:
            p, s = qs[0]
        else:
            p = jnp.concatenate([q for q, _ in qs], axis=-1)
            s = jnp.concatenate([sc for _, sc in qs], axis=-1)
        qlay[name + "_p"] = p
        qlay[name + "_s"] = s  # [L, 1, out]
    p, s = q2d(jnp.asarray(params["lm_head"]))
    return {
        "embed": params["embed"],
        "layers": qlay,
        "ln_out": params["ln_out"],
        "lm_head_p": p,
        "lm_head_s": s,  # [1, vocab]
    }


def quantize_int8(params: Dict) -> Dict:
    """Weight-only int8 with per-output-channel scales.

    The decode step is HBM-bandwidth-bound (every generated token streams
    the full parameter set through the MXU); storing the seven big layer
    mats + lm_head as int8 halves bytes/token vs bf16.  Consumption is
    scale-AFTER-dot (see :func:`_mm`): the int8->bf16 convert fuses into
    the dot's operand read so dequant costs no extra HBM traffic, which
    premultiplying the scale would break (measured 4x/mat on chip —
    PROFILE_LLM_r5.json).  Norms and the embedding table (gather — tiny
    per-token traffic) stay full precision.

    Quantization runs ON DEVICE via jit: 7B params are materialized in
    HBM (13.5 GB bf16) and must never round-trip to the host — a numpy
    path would pull the full set over D2H and expand it to f32.  The
    lax.map over the layer axis keeps the f32 transient to ONE layer's
    mat, and input donation releases each original right as its int8
    replacement lands.
    """
    import jax.numpy as jnp

    qmat, qmat2d = _qmat_layered(), _qmat_2d()
    lay = params["layers"]
    qlay: Dict = {"ln_attn": lay["ln_attn"], "ln_mlp": lay["ln_mlp"]}
    for k in _QUANT_MATS:
        q, s = qmat(jnp.asarray(lay[k]))
        qlay[k + "_q"] = q
        qlay[k + "_s"] = s  # [L, 1, out]
    q, s = qmat2d(jnp.asarray(params["lm_head"]))
    return {
        "embed": params["embed"],
        "layers": qlay,
        "ln_out": params["ln_out"],
        "lm_head_q": q,
        "lm_head_s": s,  # [1, vocab]
    }


def _apply_quant(params: Dict, opts: Dict) -> Dict:
    """Shared ``custom=quant:...`` handling for the zoo builders."""
    quant = str(opts.get("quant", "")).lower()
    if quant == "int8":
        return quantize_int8(params)
    if quant == "int4":
        return quantize_int4_params(params)
    if quant:
        raise ValueError(f"unsupported quant {quant!r} (int8, int4)")
    return params


def _mm(h, lp: Dict, key: str, dt):
    """``h @ W`` for a layer dict that stores ``key`` either full-precision
    or as int8+scale leaves (``key_q``/``key_s``).

    Quantized mats are applied SCALE-AFTER-DOT: ``(h @ q.astype(dt)) * s``,
    exact algebra for per-output-channel scales.  The int8->bf16 convert
    fuses into the dot's operand read, so the weights stream through the
    MXU at 1 byte/param; premultiplying the scale instead
    (``h @ (q.astype(dt) * s)``) forces XLA to materialize a full bf16
    copy of every mat in HBM — measured 4x slower per mat on v5e
    (tools/probe_int8_dot.py).  int8 values are integers <= 127, exactly
    representable in bf16, so postscale is also the more accurate order.
    """
    if key + "_q" in lp:
        return (h @ lp[key + "_q"].astype(dt)) * lp[key + "_s"].astype(dt)
    if key + "_p" in lp:  # int4 nibble-packed (ops/int4_matmul.py)
        from ..ops.int4_matmul import matmul_int4

        B, T, D = h.shape
        y = matmul_int4(h.reshape(B * T, D), lp[key + "_p"],
                        lp[key + "_s"])
        return y.reshape(B, T, -1)
    return h @ lp[key].astype(dt)


def _lm_head(params: Dict, x, dt):
    import jax.numpy as jnp

    if "lm_head_q" in params:
        # scale-after-dot (see _mm); scales are f32 so the output is
        # promoted to f32 by the multiply itself
        y = x @ params["lm_head_q"].astype(dt)
        return y.astype(jnp.float32) * params["lm_head_s"]
    if "lm_head_p" in params:
        from ..ops.int4_matmul import matmul_int4

        # out_dtype=f32: logits must not round through bf16 — near-tie
        # greedy argmax has to match the int8/dense paths' precision
        B, T, D = x.shape
        y = matmul_int4(x.reshape(B * T, D), params["lm_head_p"],
                        params["lm_head_s"], out_dtype=jnp.float32)
        return y.reshape(B, T, -1)
    return (x @ params["lm_head"].astype(dt)).astype(jnp.float32)


def param_pspecs(quant: bool = False) -> Dict:
    """TP shardings over the ``model`` mesh axis: split heads / FFN hidden
    on the contraction-free dim, so each matmul is local and XLA all-reduces
    the block output once (Megatron layout, GSPMD-inserted collectives).
    ``quant=True``/``"int8"`` returns specs matching the
    :func:`quantize_int8` pytree, ``quant="int4"`` the
    :func:`quantize_int4_params` pytree (scales follow their mat's OUT
    axis; in-sharded mats keep scales replicated since scales are
    per-output-channel)."""
    from jax.sharding import PartitionSpec as P

    if not quant:
        return {
            "embed": P(None, None),
            "layers": {
                "wq": P(None, None, "model"),
                "wk": P(None, None, "model"),
                "wv": P(None, None, "model"),
                "wo": P(None, "model", None),
                "w_gate": P(None, None, "model"),
                "w_up": P(None, None, "model"),
                "w_down": P(None, "model", None),
                "ln_attn": P(None, None),
                "ln_mlp": P(None, None),
            },
            "ln_out": P(None),
            "lm_head": P(None, "model"),
        }
    # int8 stores q-mats under _q; int4 packs nibbles under _p (with
    # q|k|v and gate|up FUSED along the out axis, _INT4_GROUPS) — the
    # [L, in(/2), out] axis meaning is shared, so out-sharded mats split
    # 'model' on the last axis either way (int4 TP runs through the
    # shardable XLA reference path of the kernel; the in-program q/k/v
    # split of a sharded fused mat reshards via GSPMD).
    if str(quant) == "int4":
        out_sharded = {"wqkv": True, "wo": False, "wgu": True,
                       "w_down": False}
        suffix = "_p"
    else:
        out_sharded = {"wq": True, "wk": True, "wv": True, "wo": False,
                       "w_gate": True, "w_up": True, "w_down": False}
        suffix = "_q"
    lay = {"ln_attn": P(None, None), "ln_mlp": P(None, None)}
    for k, on_out in out_sharded.items():
        lay[k + suffix] = (P(None, None, "model") if on_out
                           else P(None, "model", None))
        lay[k + "_s"] = (P(None, None, "model") if on_out
                         else P(None, None, None))
    return {
        "embed": P(None, None),
        "layers": lay,
        "ln_out": P(None),
        "lm_head" + suffix: P(None, "model"),
        "lm_head_s": P(None, "model"),
    }


def _rmsnorm(x, w, eps):
    import jax.numpy as jnp

    x32 = x.astype(jnp.float32)
    inv = jnp.reciprocal(jnp.sqrt(jnp.mean(x32 * x32, axis=-1, keepdims=True) + eps))
    return (x32 * inv).astype(x.dtype) * w.astype(x.dtype)


def _rope(x, positions, theta):
    """Rotary embedding. x: [B, T, H, D_head]; positions: [B, T] or [T]."""
    import jax.numpy as jnp

    d = x.shape[-1]
    half = d // 2
    freqs = theta ** (-jnp.arange(0, half, dtype=jnp.float32) / half)
    pos = jnp.asarray(positions, jnp.float32)
    if pos.ndim == 1:
        pos = pos[None, :]
    ang = pos[..., None] * freqs  # [B, T, half]
    cos = jnp.cos(ang)[:, :, None, :]
    sin = jnp.sin(ang)[:, :, None, :]
    x1, x2 = x[..., :half], x[..., half:]
    out = jnp.concatenate(
        [x1 * cos - x2 * sin, x2 * cos + x1 * sin], axis=-1)
    return out.astype(x.dtype)


def _repeat_kv(x, n_rep: int):
    import jax.numpy as jnp

    if n_rep == 1:
        return x
    B, T, Hkv, D = x.shape
    return jnp.broadcast_to(
        x[:, :, :, None, :], (B, T, Hkv, n_rep, D)
    ).reshape(B, T, Hkv * n_rep, D)


def _block(cfg: LlamaConfig, lp, x, positions, kv=None, pos_offset=None,
           attn_fn=None, paged_tables=None):
    """One transformer block.  ``kv=(k_cache, v_cache)`` enables cached
    decode (x is the new suffix, written at ``pos_offset``); ``attn_fn``
    overrides plain causal attention (ring attention under shard_map);
    ``paged_tables`` ([B, max_blocks] int32) switches ``kv`` to the
    block-pool layout ([n_blocks, bs, Hkv, hd] per layer) with per-row
    positions — the continuous-serving paged path."""
    import jax.numpy as jnp
    from jax import lax

    B, T, D = x.shape
    H, Hkv, hd = cfg.n_heads, cfg.n_kv_heads, cfg.head_dim
    dt = x.dtype

    h = _rmsnorm(x, lp["ln_attn"], cfg.norm_eps)
    if "wqkv_p" in lp:  # int4 fused q|k|v (one kernel call per layer)
        qkv = _mm(h, lp, "wqkv", dt)
        q = qkv[..., :H * hd].reshape(B, T, H, hd)
        k = qkv[..., H * hd:(H + Hkv) * hd].reshape(B, T, Hkv, hd)
        v = qkv[..., (H + Hkv) * hd:].reshape(B, T, Hkv, hd)
    else:
        q = _mm(h, lp, "wq", dt).reshape(B, T, H, hd)
        k = _mm(h, lp, "wk", dt).reshape(B, T, Hkv, hd)
        v = _mm(h, lp, "wv", dt).reshape(B, T, Hkv, hd)
    q = _rope(q, positions, cfg.rope_theta)
    k = _rope(k, positions, cfg.rope_theta)

    mask = None
    if paged_tables is not None:
        # Block-pool write + paged attention.  Writes scatter each new
        # K/V row into (pool block, offset) looked up through the row's
        # block table; a parked/overshooting position resolves to the
        # n_blocks sentinel and the write DROPS — idle slots decode
        # garbage without touching live blocks, recycled blocks can't be
        # written through a stale (cleared) table.
        from ..ops.attention import paged_attention

        k_pool, v_pool = kv  # [n_blocks, bs, Hkv, hd]
        n_blocks, bs = k_pool.shape[0], k_pool.shape[1]
        max_blocks = paged_tables.shape[1]
        idx = pos_offset[:, None] + jnp.arange(T)[None, :]  # [B, T]
        valid = (idx >= 0) & (idx < max_blocks * bs)
        slot_blk = jnp.clip(idx // bs, 0, max_blocks - 1)
        blk = jnp.where(
            valid,
            jnp.take_along_axis(paged_tables, slot_blk, axis=1),
            n_blocks)  # sentinel -> dropped scatter
        off = idx % bs
        k_pool = k_pool.at[blk, off].set(k.astype(k_pool.dtype),
                                         mode="drop")
        v_pool = v_pool.at[blk, off].set(v.astype(v_pool.dtype),
                                         mode="drop")
        # context = everything written so far incl. this suffix; a parked
        # row (pos >= max_blocks*bs) gets len 0 — the paged kernel then
        # issues ZERO block DMAs for it, which is the whole traffic story
        lens = jnp.where(pos_offset + T <= max_blocks * bs,
                         pos_offset + T, 0).astype(jnp.int32)
        attn = paged_attention(q, k_pool, v_pool, paged_tables,
                               lens).astype(dt)
        kv = (k_pool, v_pool)
        # falls through to the shared wo/residual/MLP tail below
    elif kv is not None:
        k_cache, v_cache = kv  # [B, S_max, Hkv, hd]
        if getattr(pos_offset, "ndim", 0) == 1:
            # Per-row positions ([B] int32, T==1): each batch row writes
            # its own cache slot row — the continuous-batching decode,
            # where concurrent streams sit at different depths.  An
            # out-of-range row position (an idle slot parked at max_seq)
            # drops the write (jax scatter default), so idle slots decode
            # garbage without corrupting live rows.
            k_cache = k_cache.at[jnp.arange(B), pos_offset].set(
                k[:, 0].astype(k_cache.dtype), mode="drop")
            v_cache = v_cache.at[jnp.arange(B), pos_offset].set(
                v[:, 0].astype(v_cache.dtype), mode="drop")
            q_pos = pos_offset[:, None] + jnp.arange(T)  # [B, T]
        else:
            k_cache = lax.dynamic_update_slice(
                k_cache, k.astype(k_cache.dtype), (0, pos_offset, 0, 0))
            v_cache = lax.dynamic_update_slice(
                v_cache, v.astype(v_cache.dtype), (0, pos_offset, 0, 0))
            q_pos = (pos_offset + jnp.arange(T))[None, :]  # [1, T]
        kv = (k_cache, v_cache)
        k_all, v_all = k_cache.astype(dt), v_cache.astype(dt)
        S = k_all.shape[1]
        # Rows beyond the filled prefix are masked by key-position validity
        # (consumed only by the masked decode path below).
        k_pos = jnp.arange(S)
        mask = (k_pos[None, None, :] <= q_pos[:, :, None])[:, None]
        # [B or 1, 1, T, S]
    else:
        k_all, v_all = k, v

    # Static pos_offset=0 means "prefill into an empty cache": the fresh
    # k/v ARE the filled cache rows, so attention reduces to causal
    # attention over the prompt — the flash kernel's case — instead of a
    # masked sweep over all S_max cache rows.
    prefill = (paged_tables is None and kv is not None
               and type(pos_offset) is int and pos_offset == 0)

    if paged_tables is not None:
        pass  # paged attention computed above; shared tail below
    elif attn_fn is not None:
        attn = attn_fn(q, _repeat_kv(k_all, H // Hkv), _repeat_kv(v_all, H // Hkv))
    elif kv is None or prefill:
        # Blockwise flash kernel (Pallas; falls back to plain XLA attention
        # internally when T doesn't tile into its blocks).  K/V go in
        # UNREPEATED — the kernel shares each streamed block across the
        # query-head group, and the XLA fallback repeats internally.
        from ..ops.attention import flash_attention

        attn = flash_attention(q, k, v, causal=True)
    else:
        kr = _repeat_kv(k_all, H // Hkv)
        vr = _repeat_kv(v_all, H // Hkv)
        s = jnp.einsum("bqhd,bkhd->bhqk", q, kr,
                       preferred_element_type=jnp.float32)
        s = s * (1.0 / np.sqrt(hd))
        s = jnp.where(mask, s, jnp.float32(-1e30))
        p = jnp.exp(s - jnp.max(s, axis=-1, keepdims=True))
        p = p / jnp.sum(p, axis=-1, keepdims=True)
        attn = jnp.einsum("bhqk,bkhd->bqhd", p.astype(dt), vr)

    out = _mm(attn.reshape(B, T, H * hd), lp, "wo", dt)
    x = x + out

    h = _rmsnorm(x, lp["ln_mlp"], cfg.norm_eps)
    import jax.nn as jnn

    if "wgu_p" in lp:  # int4 fused gate|up
        F = lp["wgu_p"].shape[-1] // 2
        gu = _mm(h, lp, "wgu", dt)
        gate = jnn.silu(gu[..., :F])
        up = gu[..., F:]
    else:
        gate = jnn.silu(_mm(h, lp, "w_gate", dt))
        up = _mm(h, lp, "w_up", dt)
    x = x + _mm(gate * up, lp, "w_down", dt)
    return x, kv


def forward(params, tokens, cfg: LlamaConfig, compute_dtype="bfloat16"):
    """Full-sequence forward -> logits [B, T, vocab] (training/eval path)."""
    import jax
    import jax.numpy as jnp

    dt = jnp.dtype(compute_dtype)
    B, T = tokens.shape
    x = jnp.asarray(params["embed"]).astype(dt)[tokens]
    positions = jnp.arange(T)

    def body(x, lp):
        x, _ = _block(cfg, lp, x, positions)
        return x, None

    x, _ = jax.lax.scan(body, x, params["layers"])
    x = _rmsnorm(x, params["ln_out"], cfg.norm_eps)
    return _lm_head(params, x, dt)


def init_cache(cfg: LlamaConfig, batch: int, dtype="bfloat16"):
    """KV cache pytree: k/v of [L, B, S_max, H_kv, head_dim]."""
    import jax.numpy as jnp

    shape = (cfg.n_layers, batch, cfg.max_seq, cfg.n_kv_heads, cfg.head_dim)
    return {"k": jnp.zeros(shape, dtype), "v": jnp.zeros(shape, dtype)}


def cache_pspecs() -> Dict:
    from jax.sharding import PartitionSpec as P

    return {"k": P(None, None, None, "model", None),
            "v": P(None, None, None, "model", None)}


# -- block-paged KV cache (continuous serving) ------------------------------

def init_paged_cache(cfg: LlamaConfig, n_blocks: int, block_size: int,
                     dtype="bfloat16"):
    """Block-pool KV cache: k/v of [L, n_blocks, block_size, H_kv, head_dim].

    The pool replaces the dense per-slot [L, B, S_max, ...] cache for
    continuous serving: streams own BLOCKS (via a per-slot block table),
    not S_max rows, so per-decode-step HBM traffic scales with the sum of
    live sequence lengths (ops/attention.py paged kernel) and a short
    stream stops paying for the longest one."""
    import jax.numpy as jnp

    shape = (cfg.n_layers, n_blocks, block_size, cfg.n_kv_heads,
             cfg.head_dim)
    return {"k": jnp.zeros(shape, dtype), "v": jnp.zeros(shape, dtype)}


def paged_cache_pspecs() -> Dict:
    """TP sharding of the block pool over the ``model`` mesh axis: the
    K/V head dim (axis 3 of ``[L, n_blocks, block_size, H_kv, hd]``)
    splits exactly like the dense cache's (:func:`cache_pspecs`), so a
    ``model_parallel=M`` serving loop holds ``pool_bytes / M`` per chip
    and each chip's attention reads only its own heads' blocks.
    Requires ``n_kv_heads % M == 0``
    (:func:`tp_divisibility_problems` reports the violation; the deep
    lint surfaces it statically).

    The spec deliberately omits the trailing ``None``: GSPMD normalizes
    output specs by trimming trailing unsharded dims, and the serving
    loop DONATES the pool through its programs — an untrimmed input spec
    would compare unequal to the donated output's and cost one spurious
    recompile, breaking the 3-program census the compile-counter pin
    protects."""
    from jax.sharding import PartitionSpec as P

    return {"k": P(None, None, None, "model"),
            "v": P(None, None, None, "model")}


def tp_divisibility_problems(cfg: LlamaConfig, tp: int) -> List[str]:
    """Dims tensor parallelism over ``tp`` ways cannot split evenly —
    empty when the geometry is TP-clean.  ONE home for the arithmetic
    the runtime's setup error (filters/llm.py) and the deep lint's
    static ``model-divisibility`` diagnostic must agree on."""
    if tp <= 1:
        return []
    probs: List[str] = []
    if (cfg.n_heads * cfg.head_dim) % tp:
        probs.append(f"attention out dim n_heads*head_dim="
                     f"{cfg.n_heads * cfg.head_dim}")
    if (cfg.n_kv_heads * cfg.head_dim) % tp:
        probs.append(f"kv out dim n_kv_heads*head_dim="
                     f"{cfg.n_kv_heads * cfg.head_dim}")
    if cfg.ffn_hidden % tp:
        probs.append(f"ffn_hidden={cfg.ffn_hidden}")
    if cfg.vocab % tp:
        probs.append(f"vocab={cfg.vocab} (lm_head out)")
    if cfg.n_kv_heads % tp:
        probs.append(f"n_kv_heads={cfg.n_kv_heads} "
                     "(the KV cache/pool shards the head axis)")
    return probs


def paged_cache_bytes(cfg: LlamaConfig, n_blocks: int, block_size: int,
                      dtype="bfloat16") -> int:
    """Static HBM footprint of :func:`init_paged_cache` (k + v), without
    building anything — the deep-lint resource report prices the pool
    through this, so the arithmetic lives next to the allocation."""
    itemsize = 2 if str(dtype) in ("bfloat16", "float16") else 4
    return (2 * cfg.n_layers * n_blocks * block_size * cfg.n_kv_heads
            * cfg.head_dim * itemsize)


def resolve_config(model: str, opts: Dict) -> Optional[LlamaConfig]:
    """The preset + ``custom=`` override arithmetic of :func:`_build`,
    WITHOUT building weights — static analysis (deep lint) resolves the
    serving config through this so pricing a 7B pool never materializes
    7B params.  None for checkpoint paths (their config lives in the
    file; static passes must not open it)."""
    if model not in PRESETS:
        return None
    cfg = PRESETS[model]
    overrides = {}
    for field in ("vocab", "dim", "n_layers", "n_heads", "n_kv_heads",
                  "ffn_hidden", "max_seq"):
        if field in opts:
            overrides[field] = int(opts[field])
    return dataclasses.replace(cfg, **overrides) if overrides else cfg


def param_bytes_estimate(cfg: LlamaConfig, quant: str = "",
                         param_dtype: str = "float32") -> int:
    """Static parameter-set HBM footprint for one replica, by arithmetic
    (no weights built): the seven big layer mats + lm_head at the quant
    width (int8 1 B + f32 scales, int4 0.5 B + scales, else the param
    dtype's width), embed at param dtype, norms f32."""
    L, D, H, Hkv, F = (cfg.n_layers, cfg.dim, cfg.n_heads, cfg.n_kv_heads,
                       cfg.ffn_hidden)
    hd = cfg.head_dim
    big_elems = L * (D * H * hd + 2 * D * Hkv * hd + H * hd * D
                     + 2 * D * F + F * D)
    head_elems = D * cfg.vocab
    out_channels = L * (H * hd + 2 * Hkv * hd + D + 2 * F + D)
    itemsize = 2 if str(param_dtype) in ("bfloat16", "float16") else 4
    q = str(quant).lower()
    if q == "int8":
        mats = big_elems + head_elems
        scales = 4 * (out_channels + cfg.vocab)
    elif q == "int4":
        mats = (big_elems + head_elems) // 2
        scales = 4 * (out_channels + cfg.vocab)
    else:
        mats = (big_elems + head_elems) * itemsize
        scales = 0
    embed = cfg.vocab * D * itemsize
    norms = 4 * (2 * L * D + D)
    return mats + scales + embed + norms


def param_bytes_split(cfg: LlamaConfig, quant: str = "",
                      param_dtype: str = "float32") -> Tuple[int, int]:
    """Static ``(sharded, replicated)`` byte split of
    :func:`param_bytes_estimate` under the :func:`param_pspecs` TP
    layout: the big layer mats + lm_head (and their scales) carry a
    ``model`` axis and divide by the mesh's model size per chip; embed
    and the norms replicate.  The deep lint prices a
    ``model_parallel=M`` pipeline's per-chip params as
    ``sharded / M + replicated``."""
    total = param_bytes_estimate(cfg, quant=quant, param_dtype=param_dtype)
    itemsize = 2 if str(param_dtype) in ("bfloat16", "float16") else 4
    replicated = cfg.vocab * cfg.dim * itemsize \
        + 4 * (2 * cfg.n_layers * cfg.dim + cfg.dim)
    return total - replicated, replicated


def forward_paged(params, tokens, pool, block_tables, pos,
                  cfg: LlamaConfig, compute_dtype="bfloat16",
                  logit_off=None):
    """Forward a suffix against the block-paged KV pool.

    ``tokens``: [B, T] (T == 1 for the continuous decode step, B == 1 with
    T == prefill_chunk for a chunked-prefill step); ``pool``: the
    :func:`init_paged_cache` pytree; ``block_tables``: [B, max_blocks]
    int32 (entries >= n_blocks are unallocated sentinels); ``pos``: [B]
    int32 — the position token 0 of each row writes at (a parked row
    passes ``max_blocks * block_size`` or larger and neither writes nor
    attends).  Every shape here is static in (B, T, pool, max_blocks):
    stream join/leave/retire only changes VALUES, which is what pins the
    continuous loop at zero recompiles.

    ``logit_off`` (traced scalar): return logits for ONLY that suffix
    position — [B, 1, vocab].  A chunked-prefill step needs one
    position's logits (the last REAL token; pad rows fill the chunk
    tail), and slicing before the lm_head keeps the vocab matmul at one
    row instead of T."""
    import jax
    import jax.numpy as jnp
    from jax import lax

    dt = jnp.dtype(compute_dtype)
    B, T = tokens.shape
    x = jnp.asarray(params["embed"]).astype(dt)[tokens]
    positions = pos[:, None] + jnp.arange(T)[None, :]

    def body(x, layer):
        lp, kc, vc = layer
        x, (kc, vc) = _block(cfg, lp, x, positions, kv=(kc, vc),
                             pos_offset=pos, paged_tables=block_tables)
        return x, (kc, vc)

    x, (k_new, v_new) = jax.lax.scan(
        body, x, (params["layers"], pool["k"], pool["v"]))
    x = _rmsnorm(x, params["ln_out"], cfg.norm_eps)
    if logit_off is not None:
        x = lax.dynamic_slice_in_dim(x, logit_off, 1, axis=1)
    return _lm_head(params, x, dt), {"k": k_new, "v": v_new}


def forward_cached(params, tokens, cache, pos_offset, cfg: LlamaConfig,
                   compute_dtype="bfloat16"):
    """Forward a suffix with KV cache: prefill (T=prompt) and decode (T=1)
    are the SAME program at different T -> two XLA compilations total.

    ``pos_offset`` may be a scalar (all rows at the same depth — the
    single-stream path) or a [B] int32 vector (each row at its own depth
    — the continuous-batching decode; requires T == 1)."""
    import jax
    import jax.numpy as jnp

    dt = jnp.dtype(compute_dtype)
    B, T = tokens.shape
    x = jnp.asarray(params["embed"]).astype(dt)[tokens]
    if getattr(pos_offset, "ndim", 0) == 1:  # per-row positions ([B])
        positions = pos_offset[:, None] + jnp.arange(T)[None, :]
    else:
        positions = pos_offset + jnp.arange(T)[None, :]

    def body(x, layer):
        lp, kc, vc = layer
        x, (kc, vc) = _block(cfg, lp, x, positions, kv=(kc, vc),
                             pos_offset=pos_offset)
        return x, (kc, vc)

    x, (k_new, v_new) = jax.lax.scan(
        body, x, (params["layers"], cache["k"], cache["v"]))
    x = _rmsnorm(x, params["ln_out"], cfg.norm_eps)
    return _lm_head(params, x, dt), {"k": k_new, "v": v_new}


def forward_seq_parallel(mesh, params, tokens, cfg: LlamaConfig,
                         compute_dtype="bfloat16"):
    """Sequence-parallel full forward: tokens sharded [B, T/seq] over the
    ``seq`` mesh axis, ring attention rotating K/V shards over ICI.

    No device ever materializes the full sequence — the long-context path
    the reference cannot express (SURVEY §2.9: SP "absent in reference").
    """
    import jax
    import jax.numpy as jnp
    from jax import lax
    from jax.sharding import PartitionSpec as P

    from ..parallel.mesh import shard_map
    from ..parallel.ring import ring_attention_local

    n_seq = int(mesh.shape.get("seq", 1))
    if n_seq <= 1:
        return forward(params, tokens, cfg, compute_dtype)

    dt = jnp.dtype(compute_dtype)

    def local_fwd(params, tokens):
        B, Tl = tokens.shape
        my = lax.axis_index("seq")
        positions = my * Tl + jnp.arange(Tl)
        x = jnp.asarray(params["embed"]).astype(dt)[tokens]

        def attn_fn(q, k, v):
            return ring_attention_local(q, k, v, axis_name="seq", causal=True)

        def body(x, lp):
            x, _ = _block(cfg, lp, x, positions, attn_fn=attn_fn)
            return x, None

        x, _ = lax.scan(body, x, params["layers"])
        x = _rmsnorm(x, params["ln_out"], cfg.norm_eps)
        return _lm_head(params, x, dt)

    fn = shard_map(
        local_fwd, mesh=mesh,
        in_specs=(P(), P(None, "seq")),
        out_specs=P(None, "seq", None),
        check_vma=False,
    )
    return jax.jit(fn)(params, tokens)


def filter_logits(logits, temperature: float, top_k: int = 0,
                  top_p: float = 1.0):
    """Apply the sampler chain's logit filters: [.., vocab] -> [.., vocab].

    ``temperature`` scales, ``top_k`` (0 = off) keeps the k highest
    logits, ``top_p`` (1.0 = off) keeps the smallest set whose
    probability mass reaches p (nucleus); masked positions go to -inf.
    All knobs are STATIC (Python) values baked into the compiled
    program — masking is where/inf over the fixed vocab axis, so the
    MXU shape never changes and no host roundtrip happens mid-decode.
    ``softmax(filter_logits(...))`` is the exact sampling distribution,
    which is what speculative rejection sampling needs on both the
    draft and target sides (filters/llm.py verify).  Caller must have
    temperature > 0.
    """
    import jax
    import jax.numpy as jnp

    logits = logits / temperature
    neg = jnp.asarray(-jnp.inf, logits.dtype)
    if top_k and 0 < top_k < logits.shape[-1]:
        kth = jax.lax.top_k(logits, top_k)[0][..., -1:]
        logits = jnp.where(logits < kth, neg, logits)
    if top_p < 1.0:
        sort = jnp.sort(logits, axis=-1)[..., ::-1]
        probs = jax.nn.softmax(sort, axis=-1)
        # exclusive cumulative mass before each sorted position; the first
        # position where it already reaches p is cut (the kept set is the
        # smallest prefix with mass >= p).  Position 0 is never cut, so
        # the top token survives any top_p — including a degenerate
        # top_p<=0, where exclusive mass 0 >= p would otherwise mask
        # EVERY logit and categorical would return id 0 unconditionally.
        cut = ((jnp.cumsum(probs, axis=-1) - probs) >= top_p) \
            & (jnp.arange(sort.shape[-1]) > 0)
        kept = jnp.where(cut, jnp.asarray(jnp.inf, logits.dtype), sort)
        thresh = jnp.min(kept, axis=-1, keepdims=True)
        logits = jnp.where(logits < thresh, neg, logits)
    return logits


def sample_token(logits, key, temperature: float, top_k: int = 0,
                 top_p: float = 1.0):
    """logits [B, vocab] -> token ids [B], one shared PRNG key.

    Reference analog: llama.cpp's sampler chain
    (tensor_filter_llamacpp.cc, SURVEY §2.4 [UNVERIFIED]).  Filter
    semantics live in :func:`filter_logits`.
    """
    import jax
    import jax.numpy as jnp

    if temperature <= 0.0:
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)
    logits = filter_logits(logits, temperature, top_k, top_p)
    return jax.random.categorical(key, logits, axis=-1).astype(jnp.int32)


def sample_token_per_slot(logits, keys, temperature: float, top_k: int = 0,
                          top_p: float = 1.0):
    """logits [B, vocab] + per-slot keys [B, 2] uint32 -> token ids [B].

    The continuous-serving sampler: each slot draws from its OWN PRNG
    stream, so a slot's emitted tokens are a pure function of its slot
    key and token positions — independent of which other slots share
    the batch.  Join/leave churn changes the VALUES in ``keys``, never
    a shape, so the compiled decode program is reused as-is
    (filters/llm.py census pins).
    """
    import jax
    import jax.numpy as jnp

    if temperature <= 0.0:
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)
    logits = filter_logits(logits, temperature, top_k, top_p)
    draw = jax.vmap(lambda kd, lg: jax.random.categorical(kd, lg, axis=-1))
    return draw(keys, logits).astype(jnp.int32)


def generate_scan(params, prompt, cfg: LlamaConfig, max_new: int,
                  temperature: float = 0.0, seed: int = 0,
                  compute_dtype="bfloat16", top_k: int = 0,
                  top_p: float = 1.0):
    """Whole generation as ONE jitted program (prefill + lax.scan decode):
    the throughput path for benchmarking — no host round-trip per token."""
    import jax
    import jax.numpy as jnp

    B, T = prompt.shape
    cache = init_cache(cfg, B, dtype=compute_dtype)
    logits, cache = forward_cached(params, prompt, cache, 0, cfg, compute_dtype)
    key = jax.random.PRNGKey(seed)
    tok0 = sample_token(logits[:, -1], key, temperature, top_k, top_p)

    def step(carry, i):
        tok, cache, key = carry
        key, sub = jax.random.split(key)
        logits, cache = forward_cached(params, tok[:, None], cache, T + i,
                                       cfg, compute_dtype)
        nxt = sample_token(logits[:, -1], sub, temperature, top_k, top_p)
        return (nxt, cache, key), tok

    (_, _, _), toks = jax.lax.scan(
        step, (tok0, cache, key), jnp.arange(max_new))
    return jnp.moveaxis(toks, 0, 1)  # [B, max_new]


# -- zoo builders ---------------------------------------------------------

def _build(preset: str, opts: Dict[str, str]) -> ModelBundle:
    cfg = PRESETS[preset]
    overrides = {}
    for field in ("vocab", "dim", "n_layers", "n_heads", "n_kv_heads",
                  "ffn_hidden", "max_seq"):
        if field in opts:
            overrides[field] = int(opts[field])
    if overrides:
        cfg = dataclasses.replace(cfg, **overrides)
    seed = int(opts.get("seed", 0))
    dtype = opts.get("dtype", "bfloat16")
    # param_dtype=bfloat16 generates weights directly at 2 bytes/param on
    # device (required to fit 7B in one chip's HBM); default float32 keeps
    # the test presets' numerics unchanged.
    quant = str(opts.get("quant", "")).lower()
    if quant in ("int8", "int4"):
        # per-mat generate+quantize+donate: the full-precision tree is
        # never resident, so quantized 7B fits where generate-everything-
        # then-quantize OOMs a 16 GB chip
        init_q = init_params_int8 if quant == "int8" else init_params_int4
        params = init_q(cfg, seed=seed,
                        gen_dtype=opts.get("param_dtype", "float32"))
    else:
        params = init_params(cfg, seed=seed,
                             dtype=opts.get("param_dtype", "float32"))
        params = _apply_quant(params, opts)

    def apply_fn(params, tokens):
        return forward(params, tokens, cfg, compute_dtype=dtype)

    # Token streams are variable-length: FLEXIBLE format, spec per buffer.
    in_spec = TensorsSpec.from_string("1:1", "int32").replace(
        format=TensorFormat.FLEXIBLE)
    out_spec = TensorsSpec.from_string(f"{cfg.vocab}:1:1", "float32").replace(
        format=TensorFormat.FLEXIBLE)
    bundle = ModelBundle(
        apply_fn=apply_fn, params=params, in_spec=in_spec, out_spec=out_spec,
        param_pspecs=param_pspecs(quant=quant), name=preset,
    )
    bundle.config = cfg  # used by the llm framework for the decode loop
    return bundle


def build_from_checkpoint(path: str, opts: Dict[str, str]) -> ModelBundle:
    """Zoo entry for REAL weights: ``model=/path/llama.safetensors``.

    Same bundle contract as :func:`_build` but params come from
    :func:`load_checkpoint`; ``custom=param_dtype:...,max_seq:N`` apply.
    """
    pdt = opts.get("param_dtype", "bfloat16")
    if path.endswith(".gguf"):
        # gguf path: the tokenizer parses out of the SAME metadata read
        params, cfg, tok = _load_gguf(path, None, _resolve_param_dtype(pdt))
    else:
        params, cfg = load_checkpoint(path, dtype=pdt)
        tok = None
    if "max_seq" in opts:
        cfg = dataclasses.replace(cfg, max_seq=int(opts["max_seq"]))
    dtype = opts.get("dtype", "bfloat16")
    quant = str(opts.get("quant", "")).lower()
    params = _apply_quant(params, opts)

    def apply_fn(params, tokens):
        return forward(params, tokens, cfg, compute_dtype=dtype)

    in_spec = TensorsSpec.from_string("1:1", "int32").replace(
        format=TensorFormat.FLEXIBLE)
    out_spec = TensorsSpec.from_string(f"{cfg.vocab}:1:1", "float32").replace(
        format=TensorFormat.FLEXIBLE)
    bundle = ModelBundle(
        apply_fn=apply_fn, params=params, in_spec=in_spec, out_spec=out_spec,
        param_pspecs=param_pspecs(quant=quant), name=path,
        tokenizer=tok,
    )
    bundle.config = cfg
    return bundle


for _name in PRESETS:
    register_model(_name, functools.partial(_build, _name))
register_model("llama", functools.partial(_build, "llama_tiny"))
