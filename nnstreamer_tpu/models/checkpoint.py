"""Checkpoint-file readers: safetensors / npz -> name->array dicts.

Reference analog: the reference's llama.cpp sub-plugin ingests GGUF model
files; the HF ecosystem equivalent (and what users actually have for
Llama-family weights) is ``.safetensors``.  The format is deliberately
trivial — u64 little-endian header length, JSON header mapping tensor
names to ``{dtype, shape, data_offsets}``, then raw little-endian tensor
bytes — so a pure-Python reader with numpy memmaps covers it with no new
dependencies, and 13 GB checkpoints page in lazily instead of being read
through Python I/O.

Supports single files, HF sharded checkpoints via
``model.safetensors.index.json``, and ``.npz`` archives (same tensor
naming).  bfloat16 maps onto ml_dtypes' extension dtype (ships with jax).
"""

from __future__ import annotations

import json
import os
import struct
from typing import Dict

import numpy as np

from ..core.types import bfloat16

_ST_DTYPES = {
    "F64": np.dtype(np.float64), "F32": np.dtype(np.float32),
    "F16": np.dtype(np.float16), "BF16": bfloat16,
    "I64": np.dtype(np.int64), "I32": np.dtype(np.int32),
    "I16": np.dtype(np.int16), "I8": np.dtype(np.int8),
    "U8": np.dtype(np.uint8), "BOOL": np.dtype(np.bool_),
}


class CheckpointError(ValueError):
    pass


def read_safetensors(path: str) -> Dict[str, np.ndarray]:
    """Memmap-backed tensors of one .safetensors file."""
    with open(path, "rb") as f:
        head = f.read(8)
        if len(head) < 8:
            raise CheckpointError(f"{path}: truncated safetensors header")
        n = struct.unpack("<Q", head)[0]
        if n > 100 * 1024 * 1024:
            raise CheckpointError(
                f"{path}: implausible header size {n} — not safetensors?")
        try:
            header = json.loads(f.read(n))
        except ValueError as e:
            raise CheckpointError(f"{path}: bad safetensors JSON: {e}") from e
    base = 8 + n
    out: Dict[str, np.ndarray] = {}
    for name, info in header.items():
        if name == "__metadata__":
            continue
        code = info["dtype"]
        if code not in _ST_DTYPES:
            raise CheckpointError(
                f"{path}: tensor {name!r} has unsupported dtype {code}")
        dt = _ST_DTYPES[code]
        lo, hi = info["data_offsets"]
        shape = tuple(info["shape"])
        want = int(np.prod(shape, dtype=np.int64)) * dt.itemsize if shape \
            else dt.itemsize
        if hi - lo != want:
            raise CheckpointError(
                f"{path}: tensor {name!r} byte span {hi - lo} != "
                f"shape/dtype size {want}")
        mm = np.memmap(path, dtype=np.uint8, mode="r", offset=base + lo,
                       shape=(hi - lo,))
        out[name] = mm.view(dt).reshape(shape)
    return out


def load_tensors(path: str) -> Dict[str, np.ndarray]:
    """Load any supported checkpoint layout into a name->array dict.

    ``path`` may be a .safetensors file, a HF ``*.safetensors.index.json``
    shard index (or a directory containing one), or a .npz archive.
    """
    if os.path.isdir(path):
        idx = os.path.join(path, "model.safetensors.index.json")
        single = os.path.join(path, "model.safetensors")
        if os.path.exists(idx):
            path = idx
        elif os.path.exists(single):
            path = single
        else:
            raise CheckpointError(
                f"{path}: no model.safetensors[.index.json] in directory")
    if path.endswith(".index.json"):
        with open(path) as f:
            index = json.load(f)
        shards = {}
        base = os.path.dirname(path)
        out: Dict[str, np.ndarray] = {}
        for name, shard in index["weight_map"].items():
            if shard not in shards:
                shards[shard] = read_safetensors(os.path.join(base, shard))
            out[name] = shards[shard][name]
        return out
    if path.endswith(".safetensors"):
        return read_safetensors(path)
    if path.endswith(".npz"):
        with np.load(path) as z:
            return {k: z[k] for k in z.files}
    raise CheckpointError(
        f"{path}: unsupported checkpoint format (want .safetensors, "
        ".safetensors.index.json, or .npz)")


def write_safetensors(path: str, tensors: Dict[str, np.ndarray]) -> None:
    """Emit a .safetensors file (tests / converting weights for reuse)."""
    inv = {np.dtype(v): k for k, v in _ST_DTYPES.items()}
    header = {}
    off = 0
    blobs = []
    for name, arr in tensors.items():
        arr = np.ascontiguousarray(arr)
        dt = inv.get(np.dtype(arr.dtype))
        if dt is None:
            raise CheckpointError(f"unsupported dtype {arr.dtype} for {name}")
        blob = arr.tobytes()
        header[name] = {"dtype": dt, "shape": list(arr.shape),
                        "data_offsets": [off, off + len(blob)]}
        off += len(blob)
        blobs.append(blob)
    raw = json.dumps(header).encode()
    with open(path, "wb") as f:
        f.write(struct.pack("<Q", len(raw)))
        f.write(raw)
        for b in blobs:
            f.write(b)
