"""nnstreamer_tpu.models"""
