"""SentencePiece (SPM) tokenizer reconstructed from GGUF metadata.

Reference analog: the llama.cpp sub-plugin
(``ext/nnstreamer/tensor_filter/tensor_filter_llamacpp.cc``, SURVEY §2.4
[UNVERIFIED]) tokenizes prompts with the model's OWN vocabulary, carried
inside the ``.gguf`` file as ``tokenizer.ggml.tokens`` / ``.scores`` /
``.token_type`` metadata arrays.  This module implements the same
greedy-merge SentencePiece algorithm (the Llama tokenizer family) in pure
Python so a real checkpoint's text path works end-to-end without any
vendor tokenizer library:

* **encode**: NFC-free byte-exact normalization (space -> U+2581 ``▁``,
  optional prefix space), split into UTF-8 characters, then repeatedly
  merge the adjacent pair whose concatenation exists in the vocab with
  the highest score (a priority queue over bigrams — the exact
  ``llm_tokenizer_spm`` procedure).  Characters that never merge into a
  known piece fall back to byte tokens (``<0xXX>``), or UNK when the
  vocab has no byte pieces.
* **decode**: per-piece (streaming contract): ``▁`` -> space, byte
  tokens -> their raw byte, control tokens -> nothing.

The tokenizer drops into :class:`~..filters.llm.ByteTokenizer`'s slot on
the llm framework (same ``encode`` / ``decode_piece`` surface), and
models/gguf.py's writer can embed a vocab so framework-emitted .gguf
files round-trip text -> ids -> text in tests.
"""

from __future__ import annotations

import heapq
from typing import Dict, List, Optional, Sequence

# SentencePiece's visible-space marker (U+2581 LOWER ONE EIGHTH BLOCK)
_SPACE = "▁"

# llama.cpp token_type values (gguf.md vocab spec)
TYPE_NORMAL = 1
TYPE_UNKNOWN = 2
TYPE_CONTROL = 3
TYPE_USER_DEFINED = 4
TYPE_UNUSED = 5
TYPE_BYTE = 6


class SentencePieceTokenizer:
    """Greedy-merge SPM over a (pieces, scores, types) vocab.

    Same duck-typed surface the llm framework's ByteTokenizer exposes:
    ``encode(bytes) -> List[int]`` (BOS prepended) and
    ``decode_piece(id) -> bytes``.
    """

    def __init__(self, pieces: Sequence[str], scores: Sequence[float],
                 types: Optional[Sequence[int]] = None,
                 bos: int = 1, eos: int = 2, unk: int = 0,
                 add_prefix_space: bool = True):
        if len(pieces) != len(scores):
            raise ValueError(
                f"vocab size mismatch: {len(pieces)} pieces vs "
                f"{len(scores)} scores")
        self.pieces = list(pieces)
        self.scores = list(scores)
        self.types = list(types) if types is not None else \
            [TYPE_NORMAL] * len(self.pieces)
        if len(self.types) != len(self.pieces):
            raise ValueError(
                f"vocab size mismatch: {len(self.pieces)} pieces vs "
                f"{len(self.types)} token types")
        self.bos = bos
        self.eos = eos
        self.unk = unk
        self.add_prefix_space = add_prefix_space
        self.n_vocab = len(self.pieces)
        self._index: Dict[str, int] = {}
        for i, p in enumerate(self.pieces):
            # first occurrence wins (duplicate pieces exist in some vocabs)
            self._index.setdefault(p, i)
        self._byte_ids: Dict[int, int] = {}
        for i, (p, t) in enumerate(zip(self.pieces, self.types)):
            if t == TYPE_BYTE and len(p) == 6 and p.startswith("<0x"):
                try:
                    self._byte_ids[int(p[3:5], 16)] = i
                except ValueError:
                    pass
        # pre-decoded piece bytes for the streaming hot path
        self._piece_bytes: List[bytes] = [
            self._decode_one(i) for i in range(self.n_vocab)]

    # -- encode ------------------------------------------------------------
    def encode(self, text_bytes: bytes) -> List[int]:
        """UTF-8 text -> token ids, BOS prepended (the llm framework's
        prompt contract)."""
        text = text_bytes.decode("utf-8", "replace")
        return [self.bos] + self.encode_text(text)

    def encode_text(self, text: str) -> List[int]:
        """Core SPM encode, no BOS."""
        if not text:
            return []
        # SPM prepends the dummy space UNCONDITIONALLY (before escaping),
        # so " a" -> "▁▁a": two markers, not one.  Replacing first and
        # skipping the prefix when the text already starts with ▁ dropped
        # one marker on leading-space text (caught by the HF-tokenizers
        # oracle, tests/test_tokenizer_oracle.py).
        if self.add_prefix_space:
            text = " " + text
        text = text.replace(" ", _SPACE)
        sym = list(text)  # one symbol per unicode char to start
        n = len(sym)
        nxt = list(range(1, n)) + [-1]
        prv = [-1] + list(range(n - 1))
        alive = [True] * n

        def try_pair(l: int) -> None:
            r = nxt[l]
            if r < 0:
                return
            merged = sym[l] + sym[r]
            tid = self._index.get(merged)
            if tid is None:
                return
            # heap entry revalidated at pop via the merged string
            heapq.heappush(heap, (-self.scores[tid], l, merged))

        heap: List = []
        for i in range(n - 1):
            try_pair(i)
        while heap:
            _, l, merged = heapq.heappop(heap)
            if not alive[l]:
                continue
            r = nxt[l]
            if r < 0 or sym[l] + sym[r] != merged:
                continue  # stale entry: one side already merged away
            sym[l] = merged
            alive[r] = False
            nxt[l] = nxt[r]
            if nxt[r] >= 0:
                prv[nxt[r]] = l
            try_pair(l)
            if prv[l] >= 0:
                try_pair(prv[l])

        ids: List[int] = []
        i = 0
        while i >= 0:
            if alive[i]:
                tid = self._index.get(sym[i])
                if tid is not None and self.types[tid] != TYPE_UNUSED:
                    ids.append(tid)
                else:
                    # byte fallback: emit each UTF-8 byte's token
                    bs = sym[i].encode("utf-8")
                    if self._byte_ids:
                        ids.extend(self._byte_ids.get(b, self.unk)
                                   for b in bs)
                    else:
                        ids.append(self.unk)
            i = nxt[i]
        return ids

    # -- decode ------------------------------------------------------------
    def _decode_one(self, token_id: int) -> bytes:
        if not (0 <= token_id < self.n_vocab):
            return b""
        t = self.types[token_id]
        if t in (TYPE_CONTROL, TYPE_UNUSED, TYPE_UNKNOWN):
            return b""
        p = self.pieces[token_id]
        if t == TYPE_BYTE and len(p) == 6 and p.startswith("<0x"):
            try:
                return bytes([int(p[3:5], 16)])
            except ValueError:
                return b""
        return p.replace(_SPACE, " ").encode("utf-8")

    def decode_piece(self, token_id: int) -> bytes:
        """One token -> its byte contribution (streaming contract)."""
        if 0 <= token_id < self.n_vocab:
            return self._piece_bytes[token_id]
        return b""

    def decode(self, ids: Sequence[int]) -> str:
        """Full-sequence detokenize: pieces joined, the single leading
        prefix space stripped (SentencePiece convention)."""
        text = b"".join(self._piece_bytes[i] for i in ids
                        if 0 <= i < self.n_vocab).decode("utf-8", "replace")
        if self.add_prefix_space and text.startswith(" "):
            text = text[1:]
        return text

    # -- GGUF metadata -----------------------------------------------------
    @classmethod
    def from_gguf_meta(cls, meta: Dict) -> "SentencePieceTokenizer":
        """Build from the ``tokenizer.ggml.*`` keys of a GGUF file's
        metadata (the same keys llama.cpp reads)."""
        pieces = meta.get("tokenizer.ggml.tokens")
        if not pieces:
            raise ValueError(
                "GGUF metadata has no tokenizer.ggml.tokens array")
        scores = meta.get("tokenizer.ggml.scores")
        if scores is None:
            scores = [0.0] * len(pieces)
        types = meta.get("tokenizer.ggml.token_type")
        return cls(
            pieces, scores, types,
            bos=int(meta.get("tokenizer.ggml.bos_token_id", 1)),
            eos=int(meta.get("tokenizer.ggml.eos_token_id", 2)),
            unk=int(meta.get("tokenizer.ggml.unknown_token_id", 0)),
            add_prefix_space=bool(
                meta.get("tokenizer.ggml.add_space_prefix", True)),
        )

    def to_gguf_meta(self) -> Dict:
        """The metadata keys :func:`from_gguf_meta` reads — lets
        models/gguf.py embed this vocab when exporting a checkpoint."""
        return {
            "tokenizer.ggml.model": "llama",
            "tokenizer.ggml.tokens": list(self.pieces),
            "tokenizer.ggml.scores": [float(s) for s in self.scores],
            "tokenizer.ggml.token_type": list(self.types),
            "tokenizer.ggml.bos_token_id": self.bos,
            "tokenizer.ggml.eos_token_id": self.eos,
            "tokenizer.ggml.unknown_token_id": self.unk,
            "tokenizer.ggml.add_space_prefix": self.add_prefix_space,
        }


def load_gguf_tokenizer(path: str) -> Optional[SentencePieceTokenizer]:
    """Read only the metadata section of a .gguf and build the tokenizer;
    None when the file carries no vocab (weights-only exports)."""
    from . import gguf

    meta = gguf.read_metadata(path)
    if "tokenizer.ggml.tokens" not in meta:
        return None
    return SentencePieceTokenizer.from_gguf_meta(meta)


def toy_vocab(extra_pieces: Optional[Dict[str, float]] = None,
              n_normal_pad: int = 0) -> SentencePieceTokenizer:
    """A small but REAL SPM vocab for tests and demos: specials, the full
    byte range, single printable-ASCII characters, plus caller-supplied
    merge pieces with scores.  Deterministic id layout:
    0=<unk> 1=<s> 2=</s>, 3..258 = bytes, then ``▁`` + printable chars,
    then ``extra_pieces`` in insertion order."""
    pieces = ["<unk>", "<s>", "</s>"]
    types = [TYPE_UNKNOWN, TYPE_CONTROL, TYPE_CONTROL]
    scores = [0.0, 0.0, 0.0]
    for b in range(256):
        pieces.append(f"<0x{b:02X}>")
        types.append(TYPE_BYTE)
        scores.append(0.0)
    singles = [_SPACE] + [chr(c) for c in range(0x21, 0x7F)]
    for ch in singles:
        pieces.append(ch)
        types.append(TYPE_NORMAL)
        scores.append(-1e4)  # chars merge only when no better piece exists
    for p, s in (extra_pieces or {}).items():
        pieces.append(p)
        types.append(TYPE_NORMAL)
        scores.append(float(s))
    for i in range(n_normal_pad):
        pieces.append(f"<pad{i}>")
        types.append(TYPE_UNUSED)
        scores.append(0.0)
    return SentencePieceTokenizer(pieces, scores, types,
                                  bos=1, eos=2, unk=0)
