"""Non-maximum suppression + box utilities.

Reference analog: the NMS inside ``tensordec-boundingbox.c`` (SURVEY §2.5).
Two implementations with identical semantics:

* :func:`nms_numpy` — greedy IoU NMS on host (the decoder's default path);
* :func:`nms_jax` — fixed-size, branch-free variant usable inside jitted
  programs (SURVEY §7 "hard parts": data-dependent control flow -> use a
  masked O(K·N) sweep with static shapes instead of dynamic early-exit).

Boxes are corner-format [x1, y1, x2, y2].
"""

from __future__ import annotations

from typing import Tuple

import numpy as np


def iou_matrix(boxes: np.ndarray) -> np.ndarray:
    """Pairwise IoU for corner-format boxes (N,4) -> (N,N)."""
    x1, y1, x2, y2 = boxes[:, 0], boxes[:, 1], boxes[:, 2], boxes[:, 3]
    area = np.maximum(0.0, x2 - x1) * np.maximum(0.0, y2 - y1)
    ix1 = np.maximum(x1[:, None], x1[None, :])
    iy1 = np.maximum(y1[:, None], y1[None, :])
    ix2 = np.minimum(x2[:, None], x2[None, :])
    iy2 = np.minimum(y2[:, None], y2[None, :])
    inter = np.maximum(0.0, ix2 - ix1) * np.maximum(0.0, iy2 - iy1)
    union = area[:, None] + area[None, :] - inter
    return np.where(union > 0, inter / np.maximum(union, 1e-9), 0.0)


def nms_numpy(
    boxes: np.ndarray,
    scores: np.ndarray,
    iou_threshold: float = 0.5,
    max_out: int = 100,
) -> np.ndarray:
    """Greedy NMS; returns indices of kept boxes, best-first."""
    order = np.argsort(-scores)
    keep = []
    suppressed = np.zeros(len(boxes), bool)
    iou = iou_matrix(boxes.astype(np.float64))
    for i in order:
        if suppressed[i]:
            continue
        keep.append(i)
        if len(keep) >= max_out:
            break
        suppressed |= iou[i] > iou_threshold
        suppressed[i] = True
    return np.asarray(keep, np.int64)


def nms_jax(boxes, scores, iou_threshold: float = 0.5, max_out: int = 100):
    """Branch-free NMS for jit: returns (indices[max_out], valid[max_out]).

    Iterates max_out times: pick current best unsuppressed score, suppress
    its overlaps.  Static shapes throughout — MXU/VPU friendly, no host sync.
    """
    import jax
    import jax.numpy as jnp

    boxes = boxes.astype(jnp.float32)
    n = boxes.shape[0]
    x1, y1, x2, y2 = boxes[:, 0], boxes[:, 1], boxes[:, 2], boxes[:, 3]
    area = jnp.maximum(0.0, x2 - x1) * jnp.maximum(0.0, y2 - y1)
    ix1 = jnp.maximum(x1[:, None], x1[None, :])
    iy1 = jnp.maximum(y1[:, None], y1[None, :])
    ix2 = jnp.minimum(x2[:, None], x2[None, :])
    iy2 = jnp.minimum(y2[:, None], y2[None, :])
    inter = jnp.maximum(0.0, ix2 - ix1) * jnp.maximum(0.0, iy2 - iy1)
    union = area[:, None] + area[None, :] - inter
    iou = jnp.where(union > 0, inter / jnp.maximum(union, 1e-9), 0.0)

    def body(carry, _):
        live_scores = carry
        best = jnp.argmax(live_scores)
        best_score = live_scores[best]
        valid = best_score > -jnp.inf
        # suppress overlaps of best (including itself)
        kill = (iou[best] > iou_threshold) | (jnp.arange(n) == best)
        live_scores = jnp.where(valid & kill, -jnp.inf, live_scores)
        return live_scores, (best.astype(jnp.int32), valid)

    init = jnp.where(jnp.isfinite(scores), scores.astype(jnp.float32), -jnp.inf)
    _, (idx, valid) = jax.lax.scan(body, init, None, length=max_out)
    return idx, valid


def center_to_corner(boxes_cxcywh: np.ndarray) -> np.ndarray:
    """[cx, cy, w, h] -> [x1, y1, x2, y2] (works for numpy and jax arrays)."""
    cx, cy, w, h = (
        boxes_cxcywh[..., 0],
        boxes_cxcywh[..., 1],
        boxes_cxcywh[..., 2],
        boxes_cxcywh[..., 3],
    )
    if isinstance(boxes_cxcywh, np.ndarray):
        stack = np.stack
    else:  # jax
        import jax.numpy as jnp

        stack = jnp.stack
    return stack([cx - w / 2, cy - h / 2, cx + w / 2, cy + h / 2], axis=-1)
