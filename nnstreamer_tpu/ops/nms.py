"""Non-maximum suppression + box utilities.

Reference analog: the NMS inside ``tensordec-boundingbox.c`` (SURVEY §2.5).
Two implementations with identical semantics:

* :func:`nms_numpy` — greedy IoU NMS on host (the decoder's default path);
* :func:`nms_jax` — fixed-size, branch-free variant usable inside jitted
  programs (SURVEY §7 "hard parts": data-dependent control flow -> use a
  masked O(K·N) sweep with static shapes instead of dynamic early-exit).

Boxes are corner-format [x1, y1, x2, y2].
"""

from __future__ import annotations

from typing import Tuple

import numpy as np


def _box_areas(boxes: np.ndarray) -> np.ndarray:
    return np.maximum(0.0, boxes[..., 2] - boxes[..., 0]) * np.maximum(
        0.0, boxes[..., 3] - boxes[..., 1]
    )


def iou_row(box: np.ndarray, box_area: float, boxes: np.ndarray,
            areas: np.ndarray) -> np.ndarray:
    """IoU of one corner-format box against (N,4) boxes — the single
    implementation of the IoU convention (degenerate boxes -> 0, eps-guarded
    divide) shared by :func:`iou_matrix` and :func:`nms_numpy`."""
    ix1 = np.maximum(box[0], boxes[:, 0])
    iy1 = np.maximum(box[1], boxes[:, 1])
    ix2 = np.minimum(box[2], boxes[:, 2])
    iy2 = np.minimum(box[3], boxes[:, 3])
    inter = np.maximum(0.0, ix2 - ix1) * np.maximum(0.0, iy2 - iy1)
    union = box_area + areas - inter
    return np.where(union > 0, inter / np.maximum(union, 1e-9), 0.0)


def iou_matrix(boxes: np.ndarray) -> np.ndarray:
    """Pairwise IoU for corner-format boxes (N,4) -> (N,N)."""
    boxes = boxes.astype(np.float64)
    areas = _box_areas(boxes)
    return np.stack(
        [iou_row(boxes[i], areas[i], boxes, areas) for i in range(len(boxes))]
    ) if len(boxes) else np.zeros((0, 0))


def nms_numpy(
    boxes: np.ndarray,
    scores: np.ndarray,
    iou_threshold: float = 0.5,
    max_out: int = 100,
) -> np.ndarray:
    """Greedy NMS; returns indices of kept boxes, best-first.

    O(K·N) memory/work (one IoU row per kept box) — never materializes the
    N×N matrix, so large candidate sets (batched streams) stay cheap."""
    boxes = boxes.astype(np.float64)
    areas = _box_areas(boxes)
    order = np.argsort(-scores)
    keep = []
    suppressed = np.zeros(len(boxes), bool)
    for i in order:
        if suppressed[i]:
            continue
        keep.append(i)
        if len(keep) >= max_out:
            break
        iou = iou_row(boxes[i], areas[i], boxes, areas)
        suppressed |= iou > iou_threshold
        suppressed[i] = True
    return np.asarray(keep, np.int64)


def nms_jax(boxes, scores, iou_threshold: float = 0.5, max_out: int = 100):
    """Branch-free NMS for jit: returns (indices[max_out], valid[max_out]).

    Iterates max_out times: pick current best unsuppressed score, suppress
    its overlaps.  Static shapes throughout — MXU/VPU friendly, no host sync.
    """
    import jax
    import jax.numpy as jnp

    boxes = boxes.astype(jnp.float32)
    n = boxes.shape[0]
    x1, y1, x2, y2 = boxes[:, 0], boxes[:, 1], boxes[:, 2], boxes[:, 3]
    area = jnp.maximum(0.0, x2 - x1) * jnp.maximum(0.0, y2 - y1)
    ix1 = jnp.maximum(x1[:, None], x1[None, :])
    iy1 = jnp.maximum(y1[:, None], y1[None, :])
    ix2 = jnp.minimum(x2[:, None], x2[None, :])
    iy2 = jnp.minimum(y2[:, None], y2[None, :])
    inter = jnp.maximum(0.0, ix2 - ix1) * jnp.maximum(0.0, iy2 - iy1)
    union = area[:, None] + area[None, :] - inter
    iou = jnp.where(union > 0, inter / jnp.maximum(union, 1e-9), 0.0)

    def body(carry, _):
        live_scores = carry
        best = jnp.argmax(live_scores)
        best_score = live_scores[best]
        valid = best_score > -jnp.inf
        # suppress overlaps of best (including itself)
        kill = (iou[best] > iou_threshold) | (jnp.arange(n) == best)
        live_scores = jnp.where(valid & kill, -jnp.inf, live_scores)
        return live_scores, (best.astype(jnp.int32), valid)

    init = jnp.where(jnp.isfinite(scores), scores.astype(jnp.float32), -jnp.inf)
    _, (idx, valid) = jax.lax.scan(body, init, None, length=max_out)
    return idx, valid


def center_to_corner(boxes_cxcywh: np.ndarray) -> np.ndarray:
    """[cx, cy, w, h] -> [x1, y1, x2, y2] (works for numpy and jax arrays)."""
    cx, cy, w, h = (
        boxes_cxcywh[..., 0],
        boxes_cxcywh[..., 1],
        boxes_cxcywh[..., 2],
        boxes_cxcywh[..., 3],
    )
    if isinstance(boxes_cxcywh, np.ndarray):
        stack = np.stack
    else:  # jax
        import jax.numpy as jnp

        stack = jnp.stack
    return stack([cx - w / 2, cy - h / 2, cx + w / 2, cy + h / 2], axis=-1)
