"""Weight-only int4 (w4a16) matmul as a Pallas TPU kernel.

Why a kernel: the 7B decode step is HBM-bound at the chip's measured
~490 GB/s (PROFILE_LLM_r5.json), so bytes/token is the only lever left.
Nibble-packing weights halves bytes, but XLA cannot consume a packed
buffer in one pass — the natural two-dot formulation fuses each nibble's
unpack into its own dot and reads every packed byte TWICE (measured
271 GB/s effective = no win over int8).  The kernel streams each packed
block through VMEM once and runs both MXU dots against the resident
block.

Mosaic on this backend legalizes NO i8 vector arithmetic (arith.shli/
subi on i8 fail) and materializes i32 temporaries in VMEM, so the
unpack must be cheap in i32 ops.  The packing is chosen to need exactly
two: with byte ``t = 16*hi + (lo+8)`` (hi signed [-8,7] in the high
nibble, lo stored BIASED unsigned in the low nibble),

    M := t & 15          = lo + 8        (1 i32 op)
    T := t (sign-extend) = 16*hi + M

so   W_lo = M - 8  and  W_hi = (T - M) / 16, and the matmul

    y = h_lo @ W_lo + h_hi @ W_hi
      = (h_lo - h_hi/16) @ M  +  (h_hi/16) @ T  -  8 * rowsum(h_lo)

moves ALL the correction arithmetic to the tiny activation side
(computed in XLA outside the kernel): per packed byte the kernel does
one extend, one mask, and two converts, then two MXU dots.  Measured
422 GB/s effective on chip (86% of the measured read limit) = 7.7
ms/token at 7B vs 12.9 for int8.  The ``h_lo - h_hi/16`` mix rounds in
bf16 (~0.6% output rel err, well under int4's ~3% per-weight
quantization noise).

Reference analog: llama.cpp's Q4 weight blocks
(tensor_filter_llamacpp.cc, SURVEY §2.4 [UNVERIFIED]) — its entire
reason to exist is fast quantized decode on the host; this is the
TPU-native counterpart.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

try:  # pragma: no cover - environment probe
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    _HAVE_PALLAS = True
except ImportError:  # pragma: no cover
    _HAVE_PALLAS = False

#: Kernel applies only to decode-shaped activations: at large B*T the
#: f32 accumulator [B, F] would blow VMEM, and prefill amortizes weight
#: reads anyway, so the XLA reference path is the right tool there.
_MAX_KERNEL_ROWS = 32

# pallas_call has no GSPMD partitioning rule, so a program traced for a
# sharded (tensor-parallel) mesh must use the shardable XLA reference
# path instead — sharding is invisible at trace time, so the caller that
# builds TP programs (filters/llm.py) disables the kernel for the
# lifetime of its filter.  REFCOUNTED, not a bare flag: two concurrent
# TP filters must not clobber each other's save/restore, and a filter
# that dies mid-open must not leak a disabled kernel process-wide.
import threading as _threading

_disable_lock = _threading.Lock()
_disable_count = 0


def disable_kernel() -> None:
    global _disable_count
    with _disable_lock:
        _disable_count += 1


def enable_kernel() -> None:
    global _disable_count
    with _disable_lock:
        _disable_count = max(0, _disable_count - 1)


def kernel_enabled() -> bool:
    return _disable_count == 0


def pack_int4(wq):
    """[Din, F] int8 values in [-8, 7] -> [Din/2, F] packed int8.

    Split-halves layout: logical rows 0:Din/2 land in the LOW nibble
    (stored biased, +8), rows Din/2:Din in the HIGH nibble (signed) —
    no interleave, so the activation splits into two contiguous halves.
    """
    d = wq.shape[0]
    if d % 2:
        raise ValueError(f"contraction dim must be even, got {d}")
    lo = wq[: d // 2].astype(jnp.int32)
    hi = wq[d // 2:].astype(jnp.int32)
    return (((hi & 0xF) << 4) | ((lo + 8) & 0xF)).astype(jnp.int8)


def unpack_int4(packed):
    """Inverse of :func:`pack_int4` -> [Din, F] int8 in [-8, 7]."""
    t32 = packed.astype(jnp.int32)
    lo = (t32 & 15) - 8
    hi = jax.lax.shift_right_arithmetic(t32, 4)
    return jnp.concatenate([lo, hi], axis=0).astype(jnp.int8)


def quantize_int4(w):
    """[Din, F] float -> (packed [Din/2, F] int8, scale [1, F] f32).

    Symmetric per-output-channel: q = round(w/s) clipped to [-7, 7]
    (the -8 code is left unused so the grid stays symmetric)."""
    w32 = w.astype(jnp.float32)
    s = jnp.maximum(jnp.abs(w32).max(axis=0, keepdims=True) / 7.0, 1e-8)
    q = jnp.clip(jnp.round(w32 / s), -7, 7).astype(jnp.int8)
    return pack_int4(q), s


def matmul_int4_reference(h, packed, scale, out_dtype=None):
    """Plain-XLA semantics of the kernel: shardable under GSPMD (the TP
    path) and the right choice for prefill (reads packed bytes twice,
    which amortizes over many rows)."""
    d2 = packed.shape[0]
    dt = h.dtype
    t32 = packed.astype(jnp.int32)
    lo = ((t32 & 15) - 8).astype(dt)
    hi = jax.lax.shift_right_arithmetic(t32, 4).astype(dt)
    y = h[..., :d2] @ lo + h[..., d2:] @ hi
    return (y.astype(jnp.float32) * scale).astype(out_dtype or dt)


def _int4_kernel(ha_ref, hb_ref, p_ref, s_ref, o_ref, acc_ref):
    """One (F-block, contraction-block) grid step: two i32 VPU ops + two
    converts per packed byte, both nibble dots against the resident
    block.  Grid dim 0 tiles F (VMEM-bounded — a [B, 32000] f32
    accumulator plus unpack temps blew the 16 MB budget at B=32); dim 1
    walks the contraction, accumulating in the revisited scratch."""
    j = pl.program_id(1)

    @pl.when(j == 0)
    def _():
        acc_ref[...] = jnp.zeros_like(acc_ref)

    t32 = p_ref[...].astype(jnp.int32)
    dt = ha_ref.dtype
    M = (t32 & 15).astype(dt)   # lo + 8
    T = t32.astype(dt)          # 16*hi + lo + 8
    acc_ref[...] += (
        jnp.dot(ha_ref[...], M, preferred_element_type=jnp.float32)
        + jnp.dot(hb_ref[...], T, preferred_element_type=jnp.float32))

    @pl.when(j == pl.num_programs(1) - 1)
    def _():
        o_ref[...] = (acc_ref[...] * s_ref[...]).astype(o_ref.dtype)


def _pick_fb(F: int, B: int, block_d2: int) -> int:
    """Largest 128-multiple divisor of F whose per-block VMEM footprint
    fits; 0 if none.  Calibrated against Mosaic's observed scoped-vmem
    accounting for the 2-D grid: packed int8 block x2 pipeline buffers
    PLUS one materialized bf16 nibble plane (bd*fb*4 total) plus the
    f32 accumulator / output blocks.  Observed anchors: [512, 11008]
    full-F at B=1 OOM'd at 21.95 MB (bd*fb*4 = 22.5 MB -> must split);
    [128, 32000] at B=32 OOM'd at 19.2 MB; the split shapes compile."""
    budget = 14 << 20
    per_elem = block_d2 * 4 + B * 6
    fb_max = budget // per_elem  # no floor: fb=0 -> caller falls back
    best = 0
    for fb in range(128, F + 1, 128):
        if F % fb == 0 and fb <= fb_max:
            best = fb
    return best


def _pick_blocks(d2: int, F: int, B: int, block_d2):
    """(block_d2, fb) for the kernel grid.  Bigger contraction blocks
    amortize per-grid-step cost — measured 2x mat throughput at B=16
    for 512 vs 128 — so auto mode takes the largest of 512/256/128 that
    divides d2 and still leaves a VMEM-fitting F block."""
    cands = (block_d2,) if block_d2 else (512, 256, 128)
    for bd in cands:
        if d2 % bd == 0:
            fb = _pick_fb(F, B, bd)
            if fb:
                return bd, fb
    return 0, 0


def matmul_int4(h, packed, scale, *, block_d2: Optional[int] = None,
                interpret: Optional[bool] = None, out_dtype=None):
    """``h @ unpack(packed) * scale`` -> [B, F] in ``out_dtype``
    (default ``h.dtype``).

    h: [B, Din] (bf16/f32); packed: [Din/2, F] int8 (:func:`pack_int4`
    layout); scale: [1, F] f32.  Uses the Pallas kernel on TPU for
    decode-shaped B (or anywhere with ``interpret=True``); other
    backends, large B, non-tiling shapes, and refcount-disabled kernel
    states (TP traces, :func:`disable_kernel`) get
    :func:`matmul_int4_reference`.
    """
    B, din = h.shape
    d2, F = packed.shape
    if din != 2 * d2:
        raise ValueError(f"h dim {din} != 2 * packed rows {d2}")
    odt = out_dtype or h.dtype
    if interpret is None:
        interpret = False
        if jax.default_backend() != "tpu":
            return matmul_int4_reference(h, packed, scale, out_dtype=odt)
    bd, fb = _pick_blocks(d2, F, B, block_d2)  # (0, 0) -> fall back
    if (not _HAVE_PALLAS or not kernel_enabled() or not fb
            or B > _MAX_KERNEL_ROWS):
        return matmul_int4_reference(h, packed, scale, out_dtype=odt)

    hlo, hhi = h[:, :d2], h[:, d2:]
    hb = (hhi.astype(jnp.float32) * 0.0625).astype(h.dtype)
    ha = hlo - hb
    out = pl.pallas_call(
        _int4_kernel,
        grid=(F // fb, d2 // bd),
        in_specs=[
            pl.BlockSpec((B, bd), lambda i, j: (0, j)),   # h_lo - h_hi/16
            pl.BlockSpec((B, bd), lambda i, j: (0, j)),   # h_hi / 16
            pl.BlockSpec((bd, fb), lambda i, j: (j, i)),  # packed block
            pl.BlockSpec((1, fb), lambda i, j: (0, i)),   # scales
        ],
        out_specs=pl.BlockSpec((B, fb), lambda i, j: (0, i)),
        out_shape=jax.ShapeDtypeStruct((B, F), odt),
        scratch_shapes=[pltpu.VMEM((B, fb), jnp.float32)],
        interpret=interpret,
    )(ha, hb, packed, scale)
    # the -8 * rowsum(h_lo) bias correction, applied at full precision
    # outside the kernel (a [B,1] x [1,F] outer product is negligible)
    bias = -8.0 * jnp.sum(hlo.astype(jnp.float32), axis=1, keepdims=True)
    return out + (bias * scale).astype(out.dtype)
