"""Flash (blockwise, online-softmax) attention as a Pallas TPU kernel.

The reference delegates attention to whatever runtime it wraps (llama.cpp's
internal kernels for the LLM filter — SURVEY §5.7); the TPU build owns the
kernel.  This is the memory-bound case Pallas exists for: the naive path
materializes the [S, S] score matrix in HBM; the flash kernel never does.

Kernel structure (VMEM-bounded for any sequence length):

* q is tiled into ``block_q`` rows via BlockSpec (pipelined by Pallas);
* k/v stay in HBM (``memory_space=ANY``) and are streamed through a
  double-buffered VMEM scratch ``block_k`` rows at a time with explicit
  async DMA — so VMEM use is O(block_q·d + 2·block_k·d), independent of S;
* the softmax running max/sum ride in registers across k blocks;
* causal q-blocks stop their kv stream at the diagonal — skipped blocks are
  never even fetched from HBM.

Layouts: q is [B, S, H, D] (heads after seq, matching models/llama.py);
k/v are [B, S, Hkv, D] with ``H % Hkv == 0`` — GQA/MQA K/V arrive
UNREPEATED.  The kernel grid runs one cell per (batch, kv-head) and keeps
the whole query-head group resident against each streamed K/V block, so a
block is DMA'd into VMEM once per group instead of once per query head:
grouped decode/prefill HBM traffic is ``Hkv/H`` of the repeated layout's.
On non-TPU backends the public entry falls back to
:func:`attention_reference` (compiled XLA, which performs the repeat
internally so it stays a bit-faithful twin) unless ``interpret=True`` is
passed explicitly (tests do, for bit-faithful kernel coverage on CPU).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp

try:  # pragma: no cover - environment probe
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    _HAVE_PALLAS = True
except ImportError:  # pragma: no cover
    _HAVE_PALLAS = False


# pallas_call has no GSPMD partitioning rule, so a paged-attention
# program traced for a sharded (tensor-parallel) mesh must take the
# shardable XLA reference path instead — sharding is invisible at trace
# time, so the caller that builds TP programs (filters/llm.py) disables
# the kernel for the lifetime of its filter.  Same REFCOUNTED contract
# as ops/int4_matmul.py: concurrent TP filters must not clobber each
# other's save/restore, and a filter that dies mid-open must not leak a
# disabled kernel process-wide.
import threading as _threading

_disable_lock = _threading.Lock()
_disable_count = 0


def disable_paged_kernel() -> None:
    global _disable_count
    with _disable_lock:
        _disable_count += 1


def enable_paged_kernel() -> None:
    global _disable_count
    with _disable_lock:
        _disable_count = max(0, _disable_count - 1)


def paged_kernel_enabled() -> bool:
    return _disable_count == 0


def _repeat_kv_heads(x, n_rep: int):
    """[B, S, Hkv, D] -> [B, S, Hkv*n_rep, D]; query head i reads kv head
    i // n_rep (the models/llama.py ``_repeat_kv`` layout)."""
    if n_rep == 1:
        return x
    b, s, hkv, d = x.shape
    return jnp.broadcast_to(
        x[:, :, :, None, :], (b, s, hkv, n_rep, d)).reshape(
            b, s, hkv * n_rep, d)


def attention_reference(q, k, v, *, causal: bool = False, scale: Optional[float] = None):
    """Plain-XLA attention (the flash kernel's semantics, materialized).

    Accepts grouped K/V (``k.shape[2]`` dividing ``q.shape[2]``) and
    repeats internally — XLA fuses the broadcast into the einsum, so the
    repeated tree is never a real HBM allocation here.  This keeps the
    reference the bit-faithful twin of the grouped kernel.
    """
    d = q.shape[-1]
    h, hkv = q.shape[2], k.shape[2]
    if h != hkv:
        k = _repeat_kv_heads(k, h // hkv)
        v = _repeat_kv_heads(v, h // hkv)
    scale = (d ** -0.5) if scale is None else scale
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k, preferred_element_type=jnp.float32)
    s = s * scale
    if causal:
        sq, sk = q.shape[1], k.shape[1]
        # kv may be longer than q (prefix/cache): align q to the BACK of kv.
        qpos = jnp.arange(sq)[:, None] + (sk - sq)
        kpos = jnp.arange(sk)[None, :]
        s = jnp.where(kpos <= qpos, s, -jnp.inf)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", p.astype(v.dtype), v)


def _flash_kernel(q_ref, k_hbm, v_hbm, o_ref, *, block_k: int, causal: bool,
                  scale: float, q_offset: int):
    """One (batch*kv-head, q-block) grid cell.

    q_ref/o_ref: VMEM [block_q, G, d] tiles holding the WHOLE query-head
    group for this kv head (G = H // Hkv; G == 1 is plain MHA); k_hbm/v_hbm:
    the full [B*Hkv, Skv, d] arrays left in HBM — kv blocks are DMA'd
    through a 2-slot VMEM scratch ONCE per group, and all G query heads
    score against the resident block.  That single sharing is the whole
    GQA win: grouped HBM traffic is Hkv/H of the repeated layout's.
    """
    block_q, grp, d = q_ref.shape
    rows = block_q * grp
    skv = k_hbm.shape[1]
    nk = skv // block_k
    i = pl.program_id(0)
    j = pl.program_id(1)

    # flatten the group into the row dim: row r = q_row * G + g, so the
    # MXU sees one [block_q*G, d] x [d, block_k] contraction per block
    q = q_ref[:].astype(jnp.float32).reshape(rows, d) * scale
    qpos = jax.lax.broadcasted_iota(
        jnp.int32, (block_q, grp, block_k), 0).reshape(rows, block_k)
    kpos = jax.lax.broadcasted_iota(
        jnp.int32, (block_q, grp, block_k), 2).reshape(rows, block_k)

    if causal:
        # The last row of this q block attends up to j*block_q + block_q - 1
        # + q_offset; kv blocks past it are never fetched.
        last_k = j * block_q + block_q - 1 + q_offset
        upper = jnp.minimum(last_k // block_k + 1, nk)
    else:
        upper = nk

    def scoped(kbuf, vbuf, ksem, vsem):
        def kdma(slot, kb):
            return pltpu.make_async_copy(
                k_hbm.at[i, pl.ds(kb * block_k, block_k), :], kbuf.at[slot],
                ksem.at[slot])

        def vdma(slot, kb):
            return pltpu.make_async_copy(
                v_hbm.at[i, pl.ds(kb * block_k, block_k), :], vbuf.at[slot],
                vsem.at[slot])

        kdma(0, 0).start()
        vdma(0, 0).start()

        def body(kb, carry):
            m, l, acc = carry
            slot = jax.lax.rem(kb, 2)
            nxt = jax.lax.rem(kb + 1, 2)

            @pl.when(kb + 1 < upper)
            def _():  # prefetch next kv block while computing this one
                kdma(nxt, kb + 1).start()
                vdma(nxt, kb + 1).start()

            kdma(slot, kb).wait()
            vdma(slot, kb).wait()
            kblk = kbuf[slot].astype(jnp.float32)
            vblk = vbuf[slot].astype(jnp.float32)
            s = jax.lax.dot_general(
                q, kblk, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32)
            if causal:
                abs_q = qpos + j * block_q + q_offset
                abs_k = kpos + kb * block_k
                s = jnp.where(abs_k <= abs_q, s, -jnp.inf)
            m_new = jnp.maximum(m, jnp.max(s, axis=1, keepdims=True))
            # exp(-inf - -inf) would be nan; clamp the shift for masked rows
            shift = jnp.where(jnp.isfinite(m_new), m_new, 0.0)
            p = jnp.exp(s - shift)
            alpha = jnp.exp(jnp.where(jnp.isfinite(m), m, shift) - shift)
            l_new = l * alpha + jnp.sum(p, axis=1, keepdims=True)
            acc_new = acc * alpha + jax.lax.dot_general(
                p, vblk, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)
            return m_new, l_new, acc_new

        m0 = jnp.full((rows, 1), -jnp.inf, jnp.float32)
        l0 = jnp.zeros((rows, 1), jnp.float32)
        acc0 = jnp.zeros((rows, d), jnp.float32)
        m, l, acc = jax.lax.fori_loop(0, upper, body, (m0, l0, acc0))
        o_ref[:] = (acc / jnp.maximum(l, 1e-30)).reshape(
            block_q, grp, d).astype(o_ref.dtype)

    pl.run_scoped(
        scoped,
        kbuf=pltpu.VMEM((2, block_k, d), k_hbm.dtype),
        vbuf=pltpu.VMEM((2, block_k, d), v_hbm.dtype),
        ksem=pltpu.SemaphoreType.DMA((2,)),
        vsem=pltpu.SemaphoreType.DMA((2,)),
    )


def flash_attention(
    q,
    k,
    v,
    *,
    causal: bool = False,
    scale: Optional[float] = None,
    block_q: int = 128,
    block_k: int = 128,
    interpret: Optional[bool] = None,
):
    """Blockwise attention for [B, S, H, D] q and [B, S, Hkv, D] k/v.

    ``Hkv`` may divide ``H`` (GQA/MQA) — pass K/V UNREPEATED; the kernel
    shares each streamed K/V block across the whole query-head group, and
    the XLA fallback repeats internally, so both paths emit identical
    values from the grouped layout.

    Uses the Pallas kernel on TPU backends (or anywhere when
    ``interpret=True`` is forced); otherwise — including non-tiling shapes —
    falls back to :func:`attention_reference`.

    TPU-kernel shape requirements (else the XLA fallback runs): ``S_q`` a
    multiple of ``block_q``, ``S_kv`` of ``block_k``, and head dim ``D`` a
    multiple of 128 (Mosaic DMA lane tiling).  Llama-2-7B's head_dim=128
    qualifies; the toy test presets (head_dim 32/64) intentionally fall back.
    """
    b, sq, h, d = q.shape
    skv = k.shape[1]
    hkv = k.shape[2]
    scale_v = (d ** -0.5) if scale is None else scale
    if interpret is None:
        interpret = False
        if jax.default_backend() != "tpu":
            # Interpreter mode is for tests; production non-TPU backends get
            # the compiled XLA path.
            return attention_reference(q, k, v, causal=causal, scale=scale_v)
    if (
        not _HAVE_PALLAS
        or sq % block_q
        or skv % block_k
        or k.shape != v.shape
        or h % hkv
        # Mosaic DMA slices must align the minor dim to the 128-lane tiling;
        # interpreter mode has no such constraint.
        or (not interpret and d % 128)
    ):
        return attention_reference(q, k, v, causal=causal, scale=scale_v)

    grp = h // hkv
    # q: [B, S, H, D] -> [B*Hkv, S, G, D] (query head h = kv_head*G + g,
    # the models/llama.py _repeat_kv layout); k/v: [B, S, Hkv, D] ->
    # [B*Hkv, S, D] — one grid row per (batch, kv-head) so a K/V block is
    # fetched once for all G query heads of its group.
    qf = q.reshape(b, sq, hkv, grp, d).transpose(0, 2, 1, 3, 4).reshape(
        b * hkv, sq, grp, d)
    kf = k.transpose(0, 2, 1, 3).reshape(b * hkv, skv, d)
    vf = v.transpose(0, 2, 1, 3).reshape(b * hkv, skv, d)

    kernel = functools.partial(
        _flash_kernel,
        block_k=block_k,
        causal=causal,
        scale=scale_v,
        q_offset=skv - sq,
    )
    out = pl.pallas_call(
        kernel,
        grid=(b * hkv, sq // block_q),
        in_specs=[
            pl.BlockSpec((None, block_q, grp, d), lambda i, j: (i, j, 0, 0)),
            pl.BlockSpec(memory_space=pl.ANY),  # kv stay in HBM
            pl.BlockSpec(memory_space=pl.ANY),
        ],
        out_specs=pl.BlockSpec(
            (None, block_q, grp, d), lambda i, j: (i, j, 0, 0)),
        out_shape=jax.ShapeDtypeStruct((b * hkv, sq, grp, d), q.dtype),
        interpret=interpret,
    )(qf, kf, vf)
    return out.reshape(b, hkv, sq, grp, d).transpose(0, 2, 1, 3, 4).reshape(
        b, sq, h, d)


# ---------------------------------------------------------------------------
# Paged (block-pool) attention — the continuous-serving decode path
# ---------------------------------------------------------------------------

def paged_attention_reference(q, k_pool, v_pool, block_tables, context_lens,
                              *, scale: Optional[float] = None):
    """Plain-XLA paged attention (the kernel's semantics, materialized).

    ``q``: [B, T, H, D] query suffix (T=1 decode, T=C prefill chunk);
    ``k_pool``/``v_pool``: [n_blocks, block_size, H_kv, D] shared block
    pool; ``block_tables``: [B, max_blocks] int32 — row b's logical block
    j lives in pool block ``block_tables[b, j]`` (entries >= n_blocks are
    unallocated sentinels); ``context_lens``: [B] int32 — tokens
    attendable per row INCLUDING the suffix (the suffix's K/V must
    already be written into the pool).  Query t of row b sits at absolute
    position ``context_lens[b] - T + t``.

    Gathers each row's full table (B x max_blocks x block_size reads —
    correct everywhere, traffic-optimal nowhere; the TPU kernel below is
    the path that only touches live blocks) and applies EXACTLY the dense
    masked-decode formulation from models/llama.py so paged and dense
    caches emit identical greedy tokens.
    """
    B, T, H, D = q.shape
    n_blocks, bs, hkv, _ = k_pool.shape
    scale_v = (D ** -0.5) if scale is None else scale
    dt = q.dtype
    # Sentinel entries clip to a real block: their logical positions sit
    # at/after the allocated extent, so the position mask hides them.
    tbl = jnp.clip(block_tables, 0, n_blocks - 1)
    k_all = k_pool[tbl].reshape(B, -1, hkv, D).astype(dt)
    v_all = v_pool[tbl].reshape(B, -1, hkv, D).astype(dt)
    if H != hkv:  # GQA: mirror the dense path's repeat-then-einsum order
        rep = H // hkv
        S = k_all.shape[1]
        k_all = jnp.broadcast_to(
            k_all[:, :, :, None, :], (B, S, hkv, rep, D)).reshape(B, S, H, D)
        v_all = jnp.broadcast_to(
            v_all[:, :, :, None, :], (B, S, hkv, rep, D)).reshape(B, S, H, D)
    q_pos = (context_lens[:, None] - T) + jnp.arange(T)[None, :]  # [B, T]
    k_pos = jnp.arange(k_all.shape[1])
    mask = (k_pos[None, None, :] <= q_pos[:, :, None])[:, None]
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k_all,
                   preferred_element_type=jnp.float32) * scale_v
    s = jnp.where(mask, s, jnp.float32(-1e30))
    p = jnp.exp(s - jnp.max(s, axis=-1, keepdims=True))
    p = p / jnp.sum(p, axis=-1, keepdims=True)
    return jnp.einsum("bhqk,bkhd->bqhd", p.astype(dt), v_all)


def _paged_kernel(tbl_ref, len_ref, q_ref, k_hbm, v_hbm, o_ref, *,
                  scale: float):
    """One stream (batch row) per grid cell.

    The whole point of paging: the kv stream for row ``b`` is
    ``ceil(len/bs)`` DMA'd blocks — idle and short rows fetch nothing
    beyond their own live prefix, so per-step HBM traffic is the SUM of
    live lengths, not B x S_max.  ``tbl_ref``/``len_ref`` are
    scalar-prefetched SMEM (available before the body runs, so the block
    ids can steer the DMAs); k/v pools stay in HBM (ANY) and blocks
    stream through a 2-slot VMEM scratch like the flash kernel above.
    """
    H, D = q_ref.shape
    bs = k_hbm.shape[1]
    hkv = k_hbm.shape[2]
    G = H // hkv
    b = pl.program_id(0)
    L = len_ref[b]
    nb = (L + bs - 1) // bs  # live blocks only — the traffic contract

    q = q_ref[:].astype(jnp.float32) * scale  # [H, D]
    qg = q.reshape(1, hkv, G, D)

    def scoped(kbuf, vbuf, ksem, vsem):
        def kdma(slot, i):
            return pltpu.make_async_copy(
                k_hbm.at[tbl_ref[b, i]], kbuf.at[slot], ksem.at[slot])

        def vdma(slot, i):
            return pltpu.make_async_copy(
                v_hbm.at[tbl_ref[b, i]], vbuf.at[slot], vsem.at[slot])

        @pl.when(nb > 0)
        def _():
            kdma(0, 0).start()
            vdma(0, 0).start()

        def body(i, carry):
            m, l, acc = carry
            slot = jax.lax.rem(i, 2)
            nxt = jax.lax.rem(i + 1, 2)

            @pl.when(i + 1 < nb)
            def _():  # prefetch the next live block while computing
                kdma(nxt, i + 1).start()
                vdma(nxt, i + 1).start()

            kdma(slot, i).wait()
            vdma(slot, i).wait()
            kblk = kbuf[slot].astype(jnp.float32)  # [bs, hkv, D]
            vblk = vbuf[slot].astype(jnp.float32)
            # decode GEMV: VPU mul-reduce (no transposes — Mosaic keeps
            # the 128-lane minor dim intact); scores [bs, hkv, G]
            s = jnp.sum(qg * kblk[:, :, None, :], axis=-1)
            # the final block is partially valid: the single query sits
            # at position L-1 and attends positions < L
            pos = i * bs + jax.lax.broadcasted_iota(
                jnp.int32, (bs, hkv, G), 0)
            s = jnp.where(pos < L, s, -jnp.inf)
            m_new = jnp.maximum(m, jnp.max(s, axis=0))
            shift = jnp.where(jnp.isfinite(m_new), m_new, 0.0)
            p = jnp.exp(s - shift[None])
            alpha = jnp.exp(jnp.where(jnp.isfinite(m), m, shift) - shift)
            l_new = l * alpha + jnp.sum(p, axis=0)
            acc_new = acc * alpha[:, :, None] + jnp.sum(
                p[:, :, :, None] * vblk[:, :, None, :], axis=0)
            return m_new, l_new, acc_new

        m0 = jnp.full((hkv, G), -jnp.inf, jnp.float32)
        l0 = jnp.zeros((hkv, G), jnp.float32)
        acc0 = jnp.zeros((hkv, G, D), jnp.float32)
        m, l, acc = jax.lax.fori_loop(0, nb, body, (m0, l0, acc0))
        # L == 0 (idle slot): l stays 0 and the row emits zeros — finite
        # garbage the serve loop never reads
        o_ref[:] = (acc / jnp.maximum(l[:, :, None], 1e-30)).reshape(
            H, D).astype(o_ref.dtype)

    pl.run_scoped(
        scoped,
        kbuf=pltpu.VMEM((2,) + k_hbm.shape[1:], k_hbm.dtype),
        vbuf=pltpu.VMEM((2,) + v_hbm.shape[1:], v_hbm.dtype),
        ksem=pltpu.SemaphoreType.DMA((2,)),
        vsem=pltpu.SemaphoreType.DMA((2,)),
    )


def paged_attention(q, k_pool, v_pool, block_tables, context_lens, *,
                    scale: Optional[float] = None,
                    interpret: Optional[bool] = None):
    """Attention over a block-paged KV pool (continuous LLM serving).

    Shapes as in :func:`paged_attention_reference`.  The Pallas kernel
    runs on TPU (or under ``interpret=True``) for the decode shape
    (T == 1) when head dim tiles the 128-lane DMA; prefill chunks
    (T > 1) and non-TPU backends take the reference path.  Per-row HBM
    traffic on the kernel path is ``ceil(context_len / block_size)``
    blocks — the reason paged decode scales with the sum of live
    sequence lengths instead of B x S_max.
    """
    B, T, H, D = q.shape
    n_blocks, bs, hkv, _ = k_pool.shape
    scale_v = (D ** -0.5) if scale is None else scale
    if interpret is None:
        interpret = False
        if jax.default_backend() != "tpu":
            return paged_attention_reference(
                q, k_pool, v_pool, block_tables, context_lens, scale=scale_v)
    if (
        not _HAVE_PALLAS
        or not paged_kernel_enabled()  # TP traces need the shardable path
        or T != 1
        or H % hkv
        or k_pool.shape != v_pool.shape
        # Mosaic DMA lane tiling (the flash kernel's constraint)
        or (not interpret and D % 128)
    ):
        return paged_attention_reference(
            q, k_pool, v_pool, block_tables, context_lens, scale=scale_v)

    import functools as _ft

    # sentinel entries must not index past the pool when a DMA is (never)
    # issued for them; clip on host side of the call
    tbl = jnp.clip(block_tables, 0, n_blocks - 1).astype(jnp.int32)
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(B,),
        in_specs=[
            pl.BlockSpec((None, H, D), lambda b, *_: (b, 0, 0)),
            pl.BlockSpec(memory_space=pl.ANY),  # pools stay in HBM
            pl.BlockSpec(memory_space=pl.ANY),
        ],
        out_specs=pl.BlockSpec((None, H, D), lambda b, *_: (b, 0, 0)),
    )
    out = pl.pallas_call(
        _ft.partial(_paged_kernel, scale=scale_v),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((B, H, D), q.dtype),
        interpret=interpret,
    )(tbl, context_lens.astype(jnp.int32), q[:, 0], k_pool, v_pool)
    return out[:, None]
