"""Flash (blockwise, online-softmax) attention as a Pallas TPU kernel.

The reference delegates attention to whatever runtime it wraps (llama.cpp's
internal kernels for the LLM filter — SURVEY §5.7); the TPU build owns the
kernel.  This is the memory-bound case Pallas exists for: the naive path
materializes the [S, S] score matrix in HBM; the flash kernel never does.

Kernel structure (VMEM-bounded for any sequence length):

* q is tiled into ``block_q`` rows via BlockSpec (pipelined by Pallas);
* k/v stay in HBM (``memory_space=ANY``) and are streamed through a
  double-buffered VMEM scratch ``block_k`` rows at a time with explicit
  async DMA — so VMEM use is O(block_q·d + 2·block_k·d), independent of S;
* the softmax running max/sum ride in registers across k blocks;
* causal q-blocks stop their kv stream at the diagonal — skipped blocks are
  never even fetched from HBM.

Layouts: q/k/v are [B, S, H, D] (heads after seq, matching models/llama.py).
GQA is handled by the caller (repeat kv heads first).  On non-TPU backends
the public entry falls back to :func:`attention_reference` (compiled XLA)
unless ``interpret=True`` is passed explicitly (tests do, for bit-faithful
kernel coverage on CPU).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp

try:  # pragma: no cover - environment probe
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    _HAVE_PALLAS = True
except ImportError:  # pragma: no cover
    _HAVE_PALLAS = False


def attention_reference(q, k, v, *, causal: bool = False, scale: Optional[float] = None):
    """Plain-XLA attention (the flash kernel's semantics, materialized)."""
    d = q.shape[-1]
    scale = (d ** -0.5) if scale is None else scale
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k, preferred_element_type=jnp.float32)
    s = s * scale
    if causal:
        sq, sk = q.shape[1], k.shape[1]
        # kv may be longer than q (prefix/cache): align q to the BACK of kv.
        qpos = jnp.arange(sq)[:, None] + (sk - sq)
        kpos = jnp.arange(sk)[None, :]
        s = jnp.where(kpos <= qpos, s, -jnp.inf)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", p.astype(v.dtype), v)


def _flash_kernel(q_ref, k_hbm, v_hbm, o_ref, *, block_k: int, causal: bool,
                  scale: float, q_offset: int):
    """One (batch*head, q-block) grid cell.

    q_ref/o_ref: VMEM [block_q, d] tiles; k_hbm/v_hbm: the full [BH, Skv, d]
    arrays left in HBM — kv blocks are DMA'd through a 2-slot VMEM scratch.
    """
    block_q, d = q_ref.shape
    skv = k_hbm.shape[1]
    nk = skv // block_k
    i = pl.program_id(0)
    j = pl.program_id(1)

    q = q_ref[:].astype(jnp.float32) * scale
    qpos = jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0)
    kpos = jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 1)

    if causal:
        # The last row of this q block attends up to j*block_q + block_q - 1
        # + q_offset; kv blocks past it are never fetched.
        last_k = j * block_q + block_q - 1 + q_offset
        upper = jnp.minimum(last_k // block_k + 1, nk)
    else:
        upper = nk

    def scoped(kbuf, vbuf, ksem, vsem):
        def kdma(slot, kb):
            return pltpu.make_async_copy(
                k_hbm.at[i, pl.ds(kb * block_k, block_k), :], kbuf.at[slot],
                ksem.at[slot])

        def vdma(slot, kb):
            return pltpu.make_async_copy(
                v_hbm.at[i, pl.ds(kb * block_k, block_k), :], vbuf.at[slot],
                vsem.at[slot])

        kdma(0, 0).start()
        vdma(0, 0).start()

        def body(kb, carry):
            m, l, acc = carry
            slot = jax.lax.rem(kb, 2)
            nxt = jax.lax.rem(kb + 1, 2)

            @pl.when(kb + 1 < upper)
            def _():  # prefetch next kv block while computing this one
                kdma(nxt, kb + 1).start()
                vdma(nxt, kb + 1).start()

            kdma(slot, kb).wait()
            vdma(slot, kb).wait()
            kblk = kbuf[slot].astype(jnp.float32)
            vblk = vbuf[slot].astype(jnp.float32)
            s = jax.lax.dot_general(
                q, kblk, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32)
            if causal:
                abs_q = qpos + j * block_q + q_offset
                abs_k = kpos + kb * block_k
                s = jnp.where(abs_k <= abs_q, s, -jnp.inf)
            m_new = jnp.maximum(m, jnp.max(s, axis=1, keepdims=True))
            # exp(-inf - -inf) would be nan; clamp the shift for masked rows
            shift = jnp.where(jnp.isfinite(m_new), m_new, 0.0)
            p = jnp.exp(s - shift)
            alpha = jnp.exp(jnp.where(jnp.isfinite(m), m, shift) - shift)
            l_new = l * alpha + jnp.sum(p, axis=1, keepdims=True)
            acc_new = acc * alpha + jax.lax.dot_general(
                p, vblk, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)
            return m_new, l_new, acc_new

        m0 = jnp.full((block_q, 1), -jnp.inf, jnp.float32)
        l0 = jnp.zeros((block_q, 1), jnp.float32)
        acc0 = jnp.zeros((block_q, d), jnp.float32)
        m, l, acc = jax.lax.fori_loop(0, upper, body, (m0, l0, acc0))
        o_ref[:] = (acc / jnp.maximum(l, 1e-30)).astype(o_ref.dtype)

    pl.run_scoped(
        scoped,
        kbuf=pltpu.VMEM((2, block_k, d), k_hbm.dtype),
        vbuf=pltpu.VMEM((2, block_k, d), v_hbm.dtype),
        ksem=pltpu.SemaphoreType.DMA((2,)),
        vsem=pltpu.SemaphoreType.DMA((2,)),
    )


def flash_attention(
    q,
    k,
    v,
    *,
    causal: bool = False,
    scale: Optional[float] = None,
    block_q: int = 128,
    block_k: int = 128,
    interpret: Optional[bool] = None,
):
    """Blockwise attention for [B, S, H, D] tensors.

    Uses the Pallas kernel on TPU backends (or anywhere when
    ``interpret=True`` is forced); otherwise — including non-tiling shapes —
    falls back to :func:`attention_reference`.

    TPU-kernel shape requirements (else the XLA fallback runs): ``S_q`` a
    multiple of ``block_q``, ``S_kv`` of ``block_k``, and head dim ``D`` a
    multiple of 128 (Mosaic DMA lane tiling).  Llama-2-7B's head_dim=128
    qualifies; the toy test presets (head_dim 32/64) intentionally fall back.
    """
    b, sq, h, d = q.shape
    skv = k.shape[1]
    scale_v = (d ** -0.5) if scale is None else scale
    if interpret is None:
        interpret = False
        if jax.default_backend() != "tpu":
            # Interpreter mode is for tests; production non-TPU backends get
            # the compiled XLA path.
            return attention_reference(q, k, v, causal=causal, scale=scale_v)
    if (
        not _HAVE_PALLAS
        or sq % block_q
        or skv % block_k
        or k.shape != v.shape
        or k.shape[2] != h
        # Mosaic DMA slices must align the minor dim to the 128-lane tiling;
        # interpreter mode has no such constraint.
        or (not interpret and d % 128)
    ):
        return attention_reference(q, k, v, causal=causal, scale=scale_v)

    # [B, S, H, D] -> [B*H, S, D]
    qf = q.transpose(0, 2, 1, 3).reshape(b * h, sq, d)
    kf = k.transpose(0, 2, 1, 3).reshape(b * h, skv, d)
    vf = v.transpose(0, 2, 1, 3).reshape(b * h, skv, d)

    kernel = functools.partial(
        _flash_kernel,
        block_k=block_k,
        causal=causal,
        scale=scale_v,
        q_offset=skv - sq,
    )
    out = pl.pallas_call(
        kernel,
        grid=(b * h, sq // block_q),
        in_specs=[
            pl.BlockSpec((None, block_q, d), lambda i, j: (i, j, 0)),
            pl.BlockSpec(memory_space=pl.ANY),  # kv stay in HBM
            pl.BlockSpec(memory_space=pl.ANY),
        ],
        out_specs=pl.BlockSpec((None, block_q, d), lambda i, j: (i, j, 0)),
        out_shape=jax.ShapeDtypeStruct((b * h, sq, d), q.dtype),
        interpret=interpret,
    )(qf, kf, vf)
    return out.reshape(b, h, sq, d).transpose(0, 2, 1, 3)
