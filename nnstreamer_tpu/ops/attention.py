"""Flash (blockwise, online-softmax) attention as a Pallas TPU kernel.

The reference delegates attention to whatever runtime it wraps (llama.cpp's
internal kernels for the LLM filter — SURVEY §5.7); the TPU build owns the
kernel.  This is the memory-bound case Pallas exists for: the naive path
materializes the [S, S] score matrix in HBM, the flash kernel keeps one
[block_q, block_k] tile in VMEM and carries the softmax running max/sum so
HBM traffic stays O(S·D).

Layouts: q/k/v are [B, S, H, D] (heads after seq, matching models/llama.py).
GQA is handled by the caller (repeat kv heads first).  On non-TPU backends
the kernel runs in interpreter mode — bit-accurate, slow, test-friendly —
and :func:`attention_reference` provides the plain-XLA fallback used when
shapes don't tile.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp


def attention_reference(q, k, v, *, causal: bool = False, scale: Optional[float] = None):
    """Plain-XLA attention (the flash kernel's semantics, materialized)."""
    d = q.shape[-1]
    scale = (d ** -0.5) if scale is None else scale
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k, preferred_element_type=jnp.float32)
    s = s * scale
    if causal:
        sq, sk = q.shape[1], k.shape[1]
        # kv may be longer than q (prefix/cache): align q to the BACK of kv.
        qpos = jnp.arange(sq)[:, None] + (sk - sq)
        kpos = jnp.arange(sk)[None, :]
        s = jnp.where(kpos <= qpos, s, -jnp.inf)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", p.astype(v.dtype), v)


def _flash_kernel(q_ref, k_ref, v_ref, o_ref, *, block_k: int, causal: bool,
                  scale: float, q_offset: int):
    """One (batch*head, q-block) grid cell: stream kv blocks through VMEM."""
    block_q, d = q_ref.shape
    skv = k_ref.shape[0]
    nk = skv // block_k

    q = q_ref[:].astype(jnp.float32) * scale
    qpos = jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0)
    kpos = jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 1)
    j = pl.program_id(1)

    def body(kb, carry):
        m, l, acc = carry
        kblk = k_ref[pl.ds(kb * block_k, block_k), :].astype(jnp.float32)
        vblk = v_ref[pl.ds(kb * block_k, block_k), :].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, kblk, (((1,), (1,)), ((), ())), preferred_element_type=jnp.float32
        )  # [block_q, block_k]
        if causal:
            # absolute positions; q aligned to back of kv via q_offset
            abs_q = qpos + j * block_q + q_offset
            abs_k = kpos + kb * block_k
            s = jnp.where(abs_k <= abs_q, s, -jnp.inf)
        m_new = jnp.maximum(m, jnp.max(s, axis=1, keepdims=True))
        # exp(-inf - -inf) would be nan; clamp the shift for fully-masked rows
        shift = jnp.where(jnp.isfinite(m_new), m_new, 0.0)
        p = jnp.exp(s - shift)
        alpha = jnp.exp(jnp.where(jnp.isfinite(m), m, shift) - shift)
        l_new = l * alpha + jnp.sum(p, axis=1, keepdims=True)
        acc_new = acc * alpha + jax.lax.dot_general(
            p, vblk, (((1,), (0,)), ((), ())), preferred_element_type=jnp.float32
        )
        return m_new, l_new, acc_new

    m0 = jnp.full((block_q, 1), -jnp.inf, jnp.float32)
    l0 = jnp.zeros((block_q, 1), jnp.float32)
    acc0 = jnp.zeros((block_q, d), jnp.float32)
    if causal:
        # Skip kv blocks entirely above the causal diagonal: the last row of
        # this q block attends up to j*block_q + block_q - 1 + q_offset.
        last_k = j * block_q + block_q - 1 + q_offset
        upper = jnp.minimum(last_k // block_k + 1, nk)
    else:
        upper = nk
    m, l, acc = jax.lax.fori_loop(0, upper, body, (m0, l0, acc0))
    o_ref[:] = (acc / jnp.maximum(l, 1e-30)).astype(o_ref.dtype)


# Deferred import so `ops` stays importable without pallas (older jax).
try:  # pragma: no cover - environment probe
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu  # noqa: F401

    _HAVE_PALLAS = True
except ImportError:  # pragma: no cover
    _HAVE_PALLAS = False


def flash_attention(
    q,
    k,
    v,
    *,
    causal: bool = False,
    scale: Optional[float] = None,
    block_q: int = 128,
    block_k: int = 128,
    interpret: Optional[bool] = None,
):
    """Blockwise attention for [B, S, H, D] tensors.

    Falls back to :func:`attention_reference` when Pallas is unavailable or
    the sequence lengths don't tile into (block_q, block_k).
    """
    b, sq, h, d = q.shape
    skv = k.shape[1]
    scale_v = (d ** -0.5) if scale is None else scale
    if (
        not _HAVE_PALLAS
        or sq % block_q
        or skv % block_k
        or k.shape != v.shape
        or k.shape[2] != h
    ):
        return attention_reference(q, k, v, causal=causal, scale=scale_v)
    if interpret is None:
        interpret = jax.default_backend() != "tpu"

    # [B, S, H, D] -> [B*H, S, D]
    qf = q.transpose(0, 2, 1, 3).reshape(b * h, sq, d)
    kf = k.transpose(0, 2, 1, 3).reshape(b * h, skv, d)
    vf = v.transpose(0, 2, 1, 3).reshape(b * h, skv, d)

    kernel = functools.partial(
        _flash_kernel,
        block_k=block_k,
        causal=causal,
        scale=scale_v,
        q_offset=skv - sq,
    )
    out = pl.pallas_call(
        kernel,
        grid=(b * h, sq // block_q),
        in_specs=[
            pl.BlockSpec((None, block_q, d), lambda i, j: (i, j, 0)),
            pl.BlockSpec((None, skv, d), lambda i, j: (i, 0, 0)),
            pl.BlockSpec((None, skv, d), lambda i, j: (i, 0, 0)),
        ],
        out_specs=pl.BlockSpec((None, block_q, d), lambda i, j: (i, j, 0)),
        out_shape=jax.ShapeDtypeStruct((b * h, sq, d), q.dtype),
        interpret=interpret,
    )(qf, kf, vf)
    return out.reshape(b, h, sq, d).transpose(0, 2, 1, 3)
