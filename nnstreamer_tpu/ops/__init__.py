"""nnstreamer_tpu.ops"""
