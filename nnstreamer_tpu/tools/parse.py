"""Standalone pipeline-string parser / validator CLI.

Reference analog: ``tools/development/parser`` — a gst-parse
reimplementation used to validate pipeline strings without running
GStreamer (SURVEY §2.8).  Here:

    python -m nnstreamer_tpu.tools.parse "videotestsrc ! tensor_converter ! tensor_sink"
    python -m nnstreamer_tpu.tools.parse --dot ... > graph.dot
    python -m nnstreamer_tpu.tools.parse --plan ...   # instantiate + show fusion plan

Without ``--plan`` nothing is instantiated — parse + topology validation
only, so unknown models/files don't block validating the string's shape.
"""

from __future__ import annotations

import argparse
import sys


def graph_summary(graph) -> str:
    lines = []
    for node in graph.topo_order():
        props = " ".join(f"{k}={v}" for k, v in node.props.items())
        name = f" name={node.name}" if node.name else ""
        lines.append(f"  [{node.id}] {node.kind}{name}{' ' + props if props else ''}")
    lines.append("  links:")
    for e in graph.edges:
        lines.append(f"    {e.src}:{e.src_pad} -> {e.dst}:{e.dst_pad}")
    return "\n".join(lines)


def graph_dot(graph) -> str:
    out = ["digraph pipeline {", "  rankdir=LR;"]
    for node in graph.nodes.values():
        label = node.kind + (f"\\n{node.name}" if node.name else "")
        out.append(f'  n{node.id} [label="{label}" shape=box];')
    for e in graph.edges:
        out.append(f"  n{e.src} -> n{e.dst};")
    out.append("}")
    return "\n".join(out)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="nnstreamer_tpu.tools.parse",
        description="Validate a pipeline description without running it.",
    )
    ap.add_argument("pipeline", help="gst-launch-style description")
    ap.add_argument("--dot", action="store_true", help="emit graphviz dot")
    ap.add_argument(
        "--plan", action="store_true",
        help="instantiate elements and print the fused execution plan",
    )
    args = ap.parse_args(argv)

    from ..pipeline.parser import ParseError, parse

    try:
        graph = parse(args.pipeline)
        graph.validate()
        # Element kinds must exist (registry lookup only — nothing is
        # instantiated, so model files aren't needed to validate a string).
        from ..core.registry import KIND_ELEMENT, lookup, names

        for node in graph.nodes.values():
            if node.kind != "capsfilter" and lookup(KIND_ELEMENT, node.kind) is None:
                raise KeyError(
                    f"unknown element {node.kind!r}; known: "
                    f"{sorted(names(KIND_ELEMENT))}"
                )
    except (ParseError, KeyError, ValueError) as e:
        print(f"INVALID: {e}", file=sys.stderr)
        return 1

    if args.dot:
        print(graph_dot(graph))
        return 0

    print(f"VALID: {len(graph.nodes)} elements, {len(graph.edges)} links")
    print(graph_summary(graph))

    if args.plan:
        from ..pipeline.runtime import Pipeline

        try:
            p = Pipeline(graph, fuse=True)
        except Exception as e:  # noqa: BLE001 - surface anything to the user
            print(f"PLAN FAILED: {e}", file=sys.stderr)
            return 2
        print("plan:")
        for st in p.stages:
            kind = "fused" if len(st.node_ids) > 1 else "stage"
            print(f"  {kind}: {st.element.name} (nodes {st.node_ids})")
    return 0


if __name__ == "__main__":
    sys.exit(main())
