"""nns-slo CLI: validate SLO policies and evaluate them against a
Prometheus scrape (docs/SERVING.md "Front door").

    # schema-check a policy file (the shape the CI soak gate asserts)
    python -m nnstreamer_tpu.tools.slo validate slo.json

    # evaluate objectives against a live /metrics endpoint or a saved
    # exposition dump — per-tenant verdict table, exit 1 on breach
    python -m nnstreamer_tpu.tools.slo report slo.json --url \\
        http://127.0.0.1:9090/metrics
    python -m nnstreamer_tpu.tools.slo report slo.json --text scrape.txt

``report`` reads the tenant-labeled ``<sink>.e2e_latency`` histogram
families and the shed counter family out of the exposition and estimates
p50/p99 at bucket resolution (the upper bound of the bucket the target
rank falls into — conservative: a true quantile is never ABOVE the
estimate's bucket).  Throughput objectives need a rate, which one scrape
cannot provide; with ``--url`` the endpoint is scraped twice
``--interval`` seconds apart and fps derives from the count delta
(``--text`` reports latency/shed objectives only).

In-process, prefer ``Pipeline(slo=...)`` + ``Pipeline.slo_report()`` —
that path reads exact reservoir quantiles and attributes the dominant
span kind from the flight-recorder ring.
"""

from __future__ import annotations

import argparse
import json
import re
import sys
import time
from typing import Dict, Optional, Tuple

#: one labeled histogram bucket sample:
#: nnstpu_<family>_bucket{tenant="t",le="0.005"} 3
_BUCKET_RE = re.compile(
    r'^nnstpu_(\w+)_bucket\{tenant="([^"]*)",le="([^"}]+)"\}\s+(\d+)\s*$')
_COUNTER_RE = re.compile(r'^nnstpu_(\w+)\{tenant="([^"]*)"\}\s+([\d.eE+-]+)\s*$')


def _prom(name: str) -> str:
    from ..utils.profiler import _prom_name

    return _prom_name(name)


def parse_exposition(text: str) -> Tuple[dict, dict]:
    """(histograms, counters) keyed ``(family, tenant)`` from exposition
    text: histograms as {le_str: cumulative_count}, counters as float."""
    hists: Dict[Tuple[str, str], Dict[str, int]] = {}
    counters: Dict[Tuple[str, str], float] = {}
    for line in text.splitlines():
        m = _BUCKET_RE.match(line)
        if m:
            fam, tenant, le, cum = m.groups()
            hists.setdefault((fam, tenant), {})[le] = int(cum)
            continue
        m = _COUNTER_RE.match(line)
        if m:
            fam, tenant, val = m.groups()
            counters[(fam, tenant)] = float(val)
    return hists, counters


def quantile_from_buckets(buckets: Dict[str, int], q: float
                          ) -> Optional[float]:
    """q-th percentile (ms) at bucket resolution: the upper bound of the
    bucket the target rank lands in (+Inf clamps to the last finite
    bound)."""
    if not buckets:
        return None
    bounds = sorted((float("inf") if le == "+Inf" else float(le), cum)
                    for le, cum in buckets.items())
    total = bounds[-1][1]
    if total <= 0:
        return None
    rank = max(1, int(q / 100.0 * total + 0.999999))
    last_finite = max((b for b, _ in bounds if b != float("inf")),
                      default=0.0)
    for bound, cum in bounds:
        if cum >= rank:
            return (bound if bound != float("inf") else last_finite) * 1e3
    return last_finite * 1e3


def _scrape(url: str) -> str:
    import urllib.request

    return urllib.request.urlopen(url, timeout=10).read().decode()


def _cmd_validate(args) -> int:
    from ..utils.slo import validate_policy

    try:
        with open(args.policy) as f:
            doc = json.load(f)
    except (OSError, json.JSONDecodeError) as e:
        print(f"{args.policy}: unreadable: {e}", file=sys.stderr)
        return 1
    problems = validate_policy(doc)
    for p in problems:
        print(f"{args.policy}: {p}", file=sys.stderr)
    if not problems:
        print(f"{args.policy}: OK "
              f"({len(doc.get('tenants', []))} tenant objectives)")
    return 1 if problems else 0


def _cmd_report(args) -> int:
    from ..utils.slo import load_policy

    policy = load_policy(args.policy)
    sinks = policy.sinks or [args.sink]
    if args.text:
        with open(args.text) as f:
            text = text2 = f.read()
        dt = 0.0
    else:
        text = _scrape(args.url)
        dt = max(0.1, args.interval)
        time.sleep(dt)
        text2 = _scrape(args.url)
    h1, c1 = parse_exposition(text)
    h2, c2 = parse_exposition(text2)
    shed_fam = _prom(policy.shed_series)
    fams = [_prom(f"{s}.e2e_latency") for s in sinks]
    tenants = sorted({t for (fam, t) in h2 if fam in fams}
                     | {t.tenant for t in policy.tenants})
    breaches = []
    rows = []
    for tenant in tenants:
        slo = policy.for_tenant(tenant)
        merged: Dict[str, int] = {}
        n2 = n1 = 0
        for fam in fams:
            for le, cum in h2.get((fam, tenant), {}).items():
                merged[le] = merged.get(le, 0) + cum
            n2 += h2.get((fam, tenant), {}).get("+Inf", 0)
            n1 += h1.get((fam, tenant), {}).get("+Inf", 0)
        p50 = quantile_from_buckets(merged, 50.0)
        p99 = quantile_from_buckets(merged, 99.0)
        sheds = c2.get((shed_fam, tenant), 0.0)
        fps = (n2 - n1) / dt if dt > 0 else None
        violations = []
        if slo is not None:
            if slo.p50_ms > 0 and p50 is not None and p50 > slo.p50_ms:
                violations.append(f"p50 {p50:.1f}ms > {slo.p50_ms:g}ms")
            if slo.p99_ms > 0 and p99 is not None and p99 > slo.p99_ms:
                violations.append(f"p99 {p99:.1f}ms > {slo.p99_ms:g}ms")
            if slo.min_fps > 0 and fps is not None and fps < slo.min_fps:
                violations.append(
                    f"throughput {fps:.1f}fps < {slo.min_fps:g}fps")
        if violations:
            breaches.append(tenant)
        rows.append((tenant, n2, p50, p99, fps, sheds, violations))
    if args.json:
        print(json.dumps({
            "ok": not breaches, "breaches": breaches,
            "tenants": {t: {"requests": n, "p50_ms": p50, "p99_ms": p99,
                            "fps": fps, "sheds": sheds,
                            "violations": v}
                        for t, n, p50, p99, fps, sheds, v in rows}},
            indent=1))
    else:
        fmt = "{:<16} {:>8} {:>10} {:>10} {:>8} {:>6}  {}"
        print(fmt.format("tenant", "reqs", "p50(ms)", "p99(ms)", "fps",
                         "sheds", "verdict"))
        for t, n, p50, p99, fps, sheds, v in rows:
            print(fmt.format(
                t, n,
                "-" if p50 is None else f"{p50:.1f}",
                "-" if p99 is None else f"{p99:.1f}",
                "-" if fps is None else f"{fps:.1f}",
                int(sheds),
                "BREACH: " + "; ".join(v) if v else "ok"))
    return 1 if breaches else 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m nnstreamer_tpu.tools.slo",
        description=__doc__, formatter_class=argparse.RawDescriptionHelpFormatter)
    sub = ap.add_subparsers(dest="cmd", required=True)
    v = sub.add_parser("validate", help="schema-check a policy file")
    v.add_argument("policy")
    v.set_defaults(fn=_cmd_validate)
    r = sub.add_parser("report",
                       help="evaluate a policy against a scrape")
    r.add_argument("policy")
    src = r.add_mutually_exclusive_group(required=True)
    src.add_argument("--url", help="/metrics endpoint (scraped twice)")
    src.add_argument("--text", help="saved exposition text file")
    r.add_argument("--sink", default="out",
                   help="sink element name when the policy lists none")
    r.add_argument("--interval", type=float, default=2.0,
                   help="seconds between the two --url scrapes (fps)")
    r.add_argument("--json", action="store_true")
    r.set_defaults(fn=_cmd_report)
    args = ap.parse_args(argv)
    return args.fn(args)


if __name__ == "__main__":
    sys.exit(main())
