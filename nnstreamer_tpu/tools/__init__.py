"""Developer tools (reference analog: ``tools/development`` — SURVEY §2.8)."""
