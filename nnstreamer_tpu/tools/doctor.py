"""pipeline doctor: one predicted-vs-actual report for a live pipeline.

The observability counterpart of ``nnstreamer_tpu.tools.lint``: the lint
PREDICTS (closed program census, HBM high-water, fetch verdicts) —
the doctor runs a pipeline with nns-xray on and VERIFIES, joining plan,
residency, mesh, census (predicted budgets vs the live program set),
the per-category HBM ledger, device-time/MFU attribution, and the SLO
verdict into one report with a machine-readable JSON twin.

    # the built-in bench pipeline (appsrc -> scaler filter -> sink,
    # burst-pushed so the bucket ladder actually compiles)
    python -m nnstreamer_tpu.tools.doctor --json report.json

    # any self-driving pipeline string
    python -m nnstreamer_tpu.tools.doctor \\
        "videotestsrc num-buffers=64 ! tensor_converter ! fakesink"

    # CI gate mode: deterministic verdict lines (tools/xray_baseline.txt)
    python -m nnstreamer_tpu.tools.doctor --gate

    # bench mode: xray-off vs xray-on wall-time A/B (the bench_all
    # `doctor_overhead` sentinel row's {"metric": ...} contract)
    python -m nnstreamer_tpu.tools.doctor --bench

See docs/OBSERVABILITY.md "Predicted vs actual".
"""

from __future__ import annotations

import argparse
import json
import sys
import time

#: the built-in bench pipeline: the adaptive-batching bench's shape
#: (bench.py --config batching) at doctor scale — a backlogged device
#: filter whose bucket ladder, single-buffer program, and activation
#: window all exercise the census + ledger
BENCH_DIMS = 64
BENCH_DESC = (
    f"appsrc name=src caps=other/tensors,dimensions={BENCH_DIMS},"
    "types=float32 ! "
    f"tensor_filter framework=jax model=scaler "
    f"custom=scale:1.5,dims:{BENCH_DIMS} name=f ! "
    "tensor_sink name=out"
)


def _drive_bench(batch_max: int, frames_n: int, *, xray: bool,
                 trace_mode: str):
    """Run the built-in bench pipeline to completion; returns
    ``(report_or_None, drive_seconds)`` — explain() runs BEFORE stop()
    so the ledger still sees live frameworks/pools."""
    import numpy as np

    import nnstreamer_tpu as nt

    frames = [np.full((BENCH_DIMS,), float(i % 7), np.float32)
              for i in range(8)]
    p = nt.Pipeline(BENCH_DESC, queue_capacity=64, batch_max=batch_max,
                    xray=xray, trace_mode=trace_mode)
    try:
        p.start()
        t0 = time.perf_counter()
        # burst pushes so the runner actually drains micro-batches (the
        # bucket ladder compiles); pulls drain the sink
        for i in range(frames_n):
            p.push("src", frames[i % len(frames)])
        for _ in range(frames_n):
            p.pull("out", timeout=120)
        dt = time.perf_counter() - t0
        p.eos()
        p.wait(timeout=120)
        rep = p.explain() if xray else None
        return rep, dt
    finally:
        p.stop()


def _run_pipeline(desc: str, timeout: float):
    """Run a self-driving pipeline string with xray + the ring recorder
    on; explain() before stop()."""
    import nnstreamer_tpu as nt

    p = nt.Pipeline(desc, xray=True, trace_mode="ring")
    try:
        p.start()
        p.wait(timeout=timeout)
        return p.explain()
    finally:
        p.stop()


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m nnstreamer_tpu.tools.doctor",
        description=__doc__.splitlines()[0])
    ap.add_argument("pipeline", nargs="?", default=None,
                    help="self-driving pipeline string (default: the "
                         "built-in bench pipeline)")
    ap.add_argument("--batch-max", type=int, default=4,
                    help="bench pipeline batch_max (default 4)")
    ap.add_argument("--frames", type=int, default=192,
                    help="bench pipeline frames to push (default 192)")
    ap.add_argument("--timeout", type=float, default=300.0)
    ap.add_argument("--json", dest="json_out", default=None,
                    help="write the machine-readable report here")
    ap.add_argument("--gate", action="store_true",
                    help="print only the deterministic verdict lines "
                         "(the CI baseline contract) and exit non-zero "
                         "on drift")
    ap.add_argument("--bench", action="store_true",
                    help="xray-off vs xray-on wall A/B; prints the "
                         "bench_all {\"metric\": ...} JSON line")
    args = ap.parse_args(argv)

    from ..core.log import metrics
    from ..utils import tracing, xray

    if args.bench:
        # interleaved off/on pairs; medians keep one scheduler hiccup
        # from defining the row (the bench_armor discipline)
        offs, ons = [], []
        drift = 0
        for _ in range(3):
            metrics.reset()
            xray.registry.reset()
            _, dt_off = _drive_bench(args.batch_max, args.frames,
                                     xray=False, trace_mode="off")
            metrics.reset()
            xray.registry.reset()
            rep, dt_on = _drive_bench(args.batch_max, args.frames,
                                      xray=True, trace_mode="off")
            offs.append(dt_off)
            ons.append(dt_on)
            # EVERY measured round pins drift 0, not just the last one
            # (the reset between rounds must not launder an early drift)
            drift += rep["census"]["drift_total"]
        off_m = sorted(offs)[1]
        on_m = sorted(ons)[1]
        overhead = (on_m / off_m - 1.0) * 100.0 if off_m > 0 else 0.0
        print(json.dumps({
            "metric": "doctor_overhead_pct", "value": round(overhead, 2),
            "unit": "%",
            "off_s": offs, "on_s": ons,
            "census_drift": drift,
            "note": "xray-on vs xray-off wall time on the bench "
                    "pipeline (3 interleaved rounds, median); drift "
                    "must be 0",
        }))
        # the advertised pin: a bench row with live census drift is a
        # regression, not a measurement (bench_all fails the row on rc)
        return 0 if drift == 0 else 1

    metrics.reset()
    xray.registry.reset()
    tracing.recorder.clear()
    if args.pipeline:
        rep = _run_pipeline(args.pipeline, args.timeout)
    else:
        rep, _dt = _drive_bench(args.batch_max, args.frames, xray=True,
                                trace_mode="ring")
    if args.json_out:
        with open(args.json_out, "w") as f:
            json.dump(rep, f, indent=1)
    if args.gate:
        for line in xray.verdict_lines(rep):
            print(line)
    else:
        print(xray.render_report(rep))
        if args.json_out:
            print(f"json twin: {args.json_out}")
    return 0 if rep["ok"] else 1


if __name__ == "__main__":
    sys.exit(main())
