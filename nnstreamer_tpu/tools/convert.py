"""Checkpoint converter CLI: move Llama-family weights between formats.

Reference analog: the model-tooling the llama.cpp ecosystem ships
(convert_hf_to_gguf.py et al.) — here one command over the framework's
own readers/writers, so every format the ``llm`` filter ingests can also
be produced:

    python -m nnstreamer_tpu.tools.convert model.safetensors model.gguf
    python -m nnstreamer_tpu.tools.convert model.gguf model.npz
    python -m nnstreamer_tpu.tools.convert hf_dir/ model.safetensors

Input: anything ``llama.load_checkpoint`` reads (.safetensors / HF
sharded dir / .npz / .gguf).  Output format from the extension:
``.gguf`` (llama.cpp layout, f32/f16/bf16), ``.safetensors`` (HF
naming), ``.npz`` (this framework's stacked naming).  ``--dtype``
selects the stored weight dtype (norms stay f32).
"""

from __future__ import annotations

import argparse
import sys
from typing import Dict

import numpy as np


def _to_hf(params: Dict, cfg) -> Dict[str, np.ndarray]:
    """Stacked native pytree -> HF Llama tensor naming (the inverse of
    load_checkpoint's HF branch: transpose back to [out, in], unstack)."""
    out = {"model.embed_tokens.weight": np.asarray(params["embed"]),
           "model.norm.weight": np.asarray(params["ln_out"]),
           "lm_head.weight": np.ascontiguousarray(
               np.asarray(params["lm_head"]).T)}
    names = {"wq": "self_attn.q_proj", "wk": "self_attn.k_proj",
             "wv": "self_attn.v_proj", "wo": "self_attn.o_proj",
             "w_gate": "mlp.gate_proj", "w_up": "mlp.up_proj",
             "w_down": "mlp.down_proj"}
    lay = params["layers"]
    for i in range(cfg.n_layers):
        for k, n in names.items():
            out[f"model.layers.{i}.{n}.weight"] = np.ascontiguousarray(
                np.asarray(lay[k])[i].T)
        out[f"model.layers.{i}.input_layernorm.weight"] = np.asarray(
            lay["ln_attn"])[i]
        out[f"model.layers.{i}.post_attention_layernorm.weight"] = \
            np.asarray(lay["ln_mlp"])[i]
    return out


def _to_npz(params: Dict) -> Dict[str, np.ndarray]:
    flat = {"embed": params["embed"], "ln_out": params["ln_out"],
            "lm_head": params["lm_head"]}
    for k, v in params["layers"].items():
        flat[f"layers.{k}"] = v
    return {k: np.asarray(v) for k, v in flat.items()}


def _write_config_json(dst: str, cfg) -> None:
    """HF-style config.json next to the output, so reimport reconstructs
    the EXACT config (rope_theta/norm_eps/head counts) instead of
    shape-inference guesses — the .gguf path carries this in metadata."""
    import json
    import os

    path = os.path.join(os.path.dirname(os.path.abspath(dst)),
                        "config.json")
    with open(path, "w") as f:
        json.dump({
            "vocab_size": cfg.vocab, "hidden_size": cfg.dim,
            "num_hidden_layers": cfg.n_layers,
            "num_attention_heads": cfg.n_heads,
            "num_key_value_heads": cfg.n_kv_heads,
            "intermediate_size": cfg.ffn_hidden,
            "max_position_embeddings": cfg.max_seq,
            "rope_theta": cfg.rope_theta,
            "rms_norm_eps": cfg.norm_eps,
        }, f, indent=1)


def convert(src: str, dst: str, dtype: str = "float32") -> None:
    from ..models import checkpoint as ckpt
    from ..models import gguf, llama

    if not dst.endswith((".gguf", ".safetensors", ".npz")):
        # validate BEFORE the (potentially minutes-long, 13 GB) load
        raise ValueError(
            f"unsupported output format {dst!r} "
            "(want .gguf / .safetensors / .npz)")
    if dst.endswith(".npz") and dtype == "bfloat16":
        # np.savez silently stores ml_dtypes bfloat16 as raw void bytes,
        # producing an unloadable file — npz is float32/float16 only
        raise ValueError(
            "npz cannot represent bfloat16; use --dtype float32/float16 "
            "or a .gguf/.safetensors output")
    params, cfg = llama.load_checkpoint(src, dtype=dtype)
    if dst.endswith(".gguf"):
        gguf.export_llama(dst, params, cfg)
    elif dst.endswith(".safetensors"):
        ckpt.write_safetensors(dst, _to_hf(params, cfg))
        _write_config_json(dst, cfg)
    else:
        np.savez(dst, **_to_npz(params))
        _write_config_json(dst, cfg)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="Convert Llama-family checkpoints between the formats "
                    "the llm filter ingests")
    ap.add_argument("src", help="input: .safetensors / HF dir / .npz / .gguf")
    ap.add_argument("dst", help="output: .gguf / .safetensors / .npz")
    ap.add_argument("--dtype", default="float32",
                    choices=["float32", "bfloat16", "float16"],
                    help="stored weight dtype (norms stay float32)")
    args = ap.parse_args(argv)
    try:
        convert(args.src, args.dst, args.dtype)
    except Exception as e:  # noqa: BLE001 - CLI surface
        print(f"convert: {e}", file=sys.stderr)
        return 1
    print(f"wrote {args.dst}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
