"""nns-trace CLI: validate, summarize, and capture flight-recorder dumps.

    # schema-check a dump (traceEvents present, required keys, ts monotonic)
    python -m nnstreamer_tpu.tools.trace validate trace.json

    # per-(stage, kind) latency table of a dump
    python -m nnstreamer_tpu.tools.trace summary trace.json

    # run a self-driving pipeline string with the flight recorder on and
    # write the Chrome trace next to you (load in Perfetto / chrome://tracing)
    python -m nnstreamer_tpu.tools.trace run \\
        "videotestsrc num-buffers=64 ! tensor_converter ! tensor_sink" \\
        --out trace.json

    # join N per-process ring dumps (tracing.dump_ring) into ONE
    # offset-corrected Chrome trace with cross-wire flow arrows
    # (docs/OBSERVABILITY.md "Distributed tracing")
    python -m nnstreamer_tpu.tools.trace merge server.ring client.ring \\
        --out merged.json

See docs/OBSERVABILITY.md for the span taxonomy and how the per-buffer
trace ids link batched dispatches back to individual rows.
"""

from __future__ import annotations

import argparse
import json
import sys


def _cmd_validate(args) -> int:
    from ..utils.tracing import validate_chrome

    try:
        with open(args.file) as f:
            obj = json.load(f)
    except (OSError, json.JSONDecodeError) as e:
        print(f"{args.file}: unreadable: {e}", file=sys.stderr)
        return 1
    problems = validate_chrome(obj)
    if problems:
        for p in problems[:50]:
            print(f"{args.file}: {p}", file=sys.stderr)
        if len(problems) > 50:
            print(f"... and {len(problems) - 50} more", file=sys.stderr)
        return 1
    n = len(obj.get("traceEvents", []))
    linked = sum(1 for e in obj["traceEvents"]
                 if isinstance(e, dict)
                 and (e.get("args") or {}).get("trace_ids"))
    print(f"OK: {n} events, {linked} batch-linked spans")
    return 0


def _cmd_summary(args) -> int:
    try:
        with open(args.file) as f:
            obj = json.load(f)
    except (OSError, json.JSONDecodeError) as e:
        print(f"{args.file}: unreadable: {e}", file=sys.stderr)
        return 1
    # aggregate straight off the Chrome events (a dump may come from
    # another process — no recorder state needed)
    tracks = {e["tid"]: e["args"]["name"]
              for e in obj.get("traceEvents", [])
              if isinstance(e, dict) and e.get("ph") == "M"
              and e.get("name") == "thread_name"}
    agg: dict = {}
    for e in obj.get("traceEvents", []):
        if not isinstance(e, dict) or e.get("ph") != "X":
            continue
        key = (tracks.get(e.get("tid"), f"tid{e.get('tid')}"),
               e.get("name", "?"))
        a = agg.setdefault(key, [0, 0.0, 0.0])
        a[0] += 1
        dur_ms = float(e.get("dur", 0.0)) / 1e3
        a[1] += dur_ms
        a[2] = max(a[2], dur_ms)
    if not agg:
        print("no complete (ph=X) spans in dump")
        return 0
    print(f"{'stage':<22s} {'kind':<10s} {'count':>7s} {'total ms':>10s} "
          f"{'mean ms':>9s} {'max ms':>9s}")
    for (stage, kind), (n, total, mx) in sorted(
            agg.items(), key=lambda kv: -kv[1][1]):
        print(f"{stage:<22s} {kind:<10s} {n:>7d} {total:>10.3f} "
              f"{total / n:>9.3f} {mx:>9.3f}")
    return 0


def _cmd_run(args) -> int:
    import nnstreamer_tpu as nt
    from ..utils.tracing import recorder

    recorder.clear()
    p = nt.Pipeline(args.pipeline, trace_mode=args.mode)
    with p:
        p.wait(timeout=args.timeout)
    n = p.dump_trace(args.out)
    print(f"{args.out}: {n} spans "
          f"(load in https://ui.perfetto.dev or chrome://tracing)")
    return 0


def _cmd_merge(args) -> int:
    from ..utils.tracing import merge_ring_files, validate_chrome

    try:
        obj, stats = merge_ring_files(args.files)
    except (OSError, ValueError) as e:
        print(f"merge: {e}", file=sys.stderr)
        return 1
    problems = validate_chrome(obj)
    with open(args.out, "w") as f:
        json.dump(obj, f)
    align = obj.get("otherData", {}).get("weave", [])
    unaligned = [a["proc"] for a in align if not a.get("aligned", True)]
    print(f"{args.out}: {stats['rings']} rings, {stats['spans']} spans, "
          f"{stats['arrows']} cross-wire arrows"
          + (f"; UNALIGNED (no clock path): {', '.join(unaligned)}"
             if unaligned else ""))
    if problems:
        for p in problems[:20]:
            print(f"{args.out}: {p}", file=sys.stderr)
        return 1
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m nnstreamer_tpu.tools.trace",
        description=__doc__.splitlines()[0])
    sub = ap.add_subparsers(dest="cmd", required=True)
    v = sub.add_parser("validate", help="schema-check a Chrome trace dump")
    v.add_argument("file")
    s = sub.add_parser("summary", help="per-stage/kind latency table")
    s.add_argument("file")
    r = sub.add_parser(
        "run", help="run a self-driving pipeline string traced, dump JSON")
    r.add_argument("pipeline")
    r.add_argument("--out", default="trace.json")
    r.add_argument("--mode", default="ring", choices=["ring", "full"])
    r.add_argument("--timeout", type=float, default=120.0)
    m = sub.add_parser(
        "merge", help="join N per-process ring dumps into one Chrome "
        "trace (offset-corrected, cross-wire flow arrows)")
    m.add_argument("files", nargs="+")
    m.add_argument("--out", default="merged.json")
    args = ap.parse_args(argv)
    return {"validate": _cmd_validate, "summary": _cmd_summary,
            "run": _cmd_run, "merge": _cmd_merge}[args.cmd](args)


if __name__ == "__main__":
    sys.exit(main())
