"""gst-inspect analog: discover registered elements and sub-plugins.

Reference analog: ``gst-inspect-1.0`` is how users of the reference
discover elements and their properties; nothing in-repo implements it
(GStreamer ships it), so the TPU build supplies its own:

    python -m nnstreamer_tpu.tools.inspect                 # everything
    python -m nnstreamer_tpu.tools.inspect tensor_filter   # one element
    python -m nnstreamer_tpu.tools.inspect --kind filter   # one registry

Detail view prints the registered class, its aliases, and the docstring
(the framework documents element properties in docstrings, the analog of
gst-inspect's property table).
"""

from __future__ import annotations

import argparse
import inspect as _inspect
import sys
from typing import Optional

from ..core.registry import (
    KIND_CONVERTER,
    KIND_DECODER,
    KIND_ELEMENT,
    KIND_FILTER,
    KIND_TRAINER,
    aliases_of,
    lookup,
    names,
)

_KINDS = {
    "element": KIND_ELEMENT,
    "filter": KIND_FILTER,
    "decoder": KIND_DECODER,
    "converter": KIND_CONVERTER,
    "trainer": KIND_TRAINER,
}


def _first_line(doc: Optional[str]) -> str:
    if not doc:
        return ""
    return doc.strip().splitlines()[0]


def list_all(kind_filter: Optional[str] = None, out=sys.stdout) -> None:
    for label, kind in _KINDS.items():
        if kind_filter and label != kind_filter:
            continue
        entries = names(kind)
        if not entries:
            continue
        out.write(f"== {label} ({len(entries)}) ==\n")
        for n in sorted(entries):
            cls = lookup(kind, n)
            summary = _first_line(cls.__doc__)
            if not summary:  # some classes document in their module header
                mod = sys.modules.get(cls.__module__)
                summary = _first_line(getattr(mod, "__doc__", ""))
            out.write(f"  {n:28s} {summary}\n")
        out.write("\n")


def show(name: str, out=sys.stdout) -> bool:
    found = False
    for label, kind in _KINDS.items():
        cls = lookup(kind, name)
        if cls is None:
            continue
        found = True
        mod = cls.__module__
        out.write(f"{label}: {name}\n")
        out.write(f"  class:  {mod}.{cls.__name__}\n")
        al = aliases_of(kind, name)
        if al:
            out.write(f"  aliases: {', '.join(al)}\n")
        try:
            out.write(f"  source: {_inspect.getsourcefile(cls)}\n")
        except TypeError:
            pass
        doc = _inspect.getdoc(cls)
        if doc:
            out.write("\n" + "\n".join(f"  {l}" for l in doc.splitlines()))
            out.write("\n")
        out.write("\n")
    return found


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="List registered elements / sub-plugins (gst-inspect "
                    "analog)")
    ap.add_argument("name", nargs="?", help="show one entry in detail")
    ap.add_argument("--kind", choices=sorted(_KINDS),
                    help="restrict the listing to one registry")
    args = ap.parse_args(argv)
    if args.name:
        if not show(args.name):
            print(f"no element or sub-plugin named {args.name!r}",
                  file=sys.stderr)
            return 1
        return 0
    list_all(args.kind)
    return 0


if __name__ == "__main__":
    sys.exit(main())
