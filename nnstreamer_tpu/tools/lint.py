"""nns-lint CLI: verify a pipeline string without running it.

    python -m nnstreamer_tpu.tools.lint "videotestsrc ! tensor_converter ! tensor_sink"
    python -m nnstreamer_tpu.tools.lint --strict "<pipeline>"     # warnings fail too
    python -m nnstreamer_tpu.tools.lint --dogfood                 # lint OUR device_fns
    python -m nnstreamer_tpu.tools.lint --examples                # lint examples/ + e2e strings

Exit codes: 0 clean/ok, 1 errors (or warnings with --strict), 2 usage.

Reference analog: gst-launch's parse-only mode plus nnstreamer's strict
pipeline parser — but whole-graph: every caps incompatibility, topology
hazard, and jit-purity violation is reported in ONE run with element-path
locations and source carets.  Runs with ``JAX_PLATFORMS=cpu`` and performs
no device dispatch: the analyzer never executes JAX.
"""

from __future__ import annotations

import argparse
import ast
import os
import sys
from typing import Dict, List, Optional, Tuple


def _render(desc: str, report, *, verbose: bool) -> None:
    if report.clean:
        print(f"OK: {desc!r}")
        return
    print(f"LINT: {desc!r}")
    print(report.render())


def extract_pipeline_strings(path: str) -> Tuple[List[str], int]:
    """Pipeline strings passed to ``Pipeline(...)`` / ``parse_launch(...)``
    in a Python source file, resolved WITHOUT importing it (examples run
    pipelines at import time).

    f-string placeholders are resolved from module-level constant
    assignments (``SIZE = 224``) and function-call defaults where
    possible; calls whose first argument cannot be resolved statically are
    counted in the second return value so callers can report coverage
    instead of silently skipping.
    """
    with open(path) as f:
        tree = ast.parse(f.read(), filename=path)

    consts: Dict[str, object] = {}
    for stmt in ast.walk(tree):  # any scope; first literal binding wins
        if not isinstance(stmt, ast.Assign):
            continue
        for tgt in stmt.targets:  # W = H = 96 has two targets
            if isinstance(tgt, ast.Name):
                try:
                    consts.setdefault(tgt.id, ast.literal_eval(stmt.value))
                except (ValueError, TypeError):
                    pass
            elif isinstance(tgt, ast.Tuple):
                try:
                    vals = ast.literal_eval(stmt.value)
                    for t, v in zip(tgt.elts, vals):
                        if isinstance(t, ast.Name):
                            consts.setdefault(t.id, v)
                except (ValueError, TypeError):
                    pass

    def resolve(node) -> Optional[str]:
        if isinstance(node, ast.Constant) and isinstance(node.value, str):
            return node.value
        if isinstance(node, ast.JoinedStr):
            parts = []
            for v in node.values:
                if isinstance(v, ast.Constant):
                    parts.append(str(v.value))
                elif isinstance(v, ast.FormattedValue):
                    if isinstance(v.value, ast.Name) \
                            and v.value.id in consts:
                        parts.append(str(consts[v.value.id]))
                    else:
                        return None
                else:
                    return None
            return "".join(parts)
        if isinstance(node, ast.BinOp) and isinstance(node.op, ast.Add):
            left, right = resolve(node.left), resolve(node.right)
            if left is not None and right is not None:
                return left + right
        return None

    found: List[str] = []
    skipped = 0
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call) or not node.args:
            continue
        f = node.func
        name = f.attr if isinstance(f, ast.Attribute) else (
            f.id if isinstance(f, ast.Name) else "")
        if name not in ("Pipeline", "parse_launch", "parse"):
            continue
        got = resolve(node.args[0])
        if got is None:
            skipped += 1
        else:
            found.append(got)
    return found, skipped


def _diag_key(prefix: str, d, desc: Optional[str] = None) -> str:
    """Stable baseline key: file/source prefix + a short hash of the
    pipeline string + code + element path.  The hash pins the acceptance
    to ONE pipeline string — element labels like ``out`` repeat across the
    many strings in one file, and a baseline entry must not swallow a new
    defect in a different pipeline that happens to reuse a name.  No
    message text — line numbers in messages drift with unrelated edits."""
    import hashlib

    h = ""
    if desc is not None:
        h = hashlib.sha1(desc.encode()).hexdigest()[:8] + ":"
    return f"{prefix}:{h}{d.code}:{d.path}"


def lint_files(paths: List[str], *, strict: bool, verbose: bool,
               baseline: Optional[set] = None,
               collected: Optional[List[str]] = None) -> int:
    from ..analysis import analyze

    rc = 0
    total = skipped_total = accepted = 0
    for path in paths:
        strings, skipped = extract_pipeline_strings(path)
        skipped_total += skipped
        for desc in strings:
            total += 1
            report = analyze(desc)
            keys = [_diag_key(os.path.basename(path), d, desc)
                    for d in report]
            if collected is not None:
                collected.extend(keys)
            fails = [
                d for d, k in zip(report.diagnostics, keys)
                if (d.severity == "error" or strict)
                and (baseline is None or k not in baseline)
            ]
            accepted += sum(
                1 for k in keys if baseline is not None and k in baseline)
            if fails or verbose:
                print(f"-- {os.path.basename(path)}")
                _render(desc, report, verbose=verbose)
            if fails:
                rc = 1
    print(f"linted {total} pipeline string(s) from {len(paths)} file(s)"
          + (f"; {skipped_total} call(s) not statically resolvable"
             if skipped_total else "")
          + (f"; {accepted} baseline-accepted diagnostic(s)"
             if accepted else ""))
    return rc


def dogfood(*, strict: bool, baseline: Optional[set] = None,
            collected: Optional[List[str]] = None) -> int:
    """Lint the framework's OWN device_fns (every built-in plugin module):
    a host side effect sneaking into a shipped element's pure fn fails CI
    before it silently knocks that element off the fused-XLA path."""
    import importlib

    from ..analysis.purity import lint_module
    from ..core.registry import _BUILTIN_MODULES

    diags = []
    for modname in _BUILTIN_MODULES:
        try:
            mod = importlib.import_module(modname)
        except ImportError:
            continue
        diags.extend(lint_module(mod))
    keys = [_diag_key("dogfood", d) for d in diags]
    if collected is not None:
        collected.extend(keys)
    fails = [
        d for d, k in zip(diags, keys)
        if (d.severity == "error" or strict)
        and (baseline is None or k not in baseline)
    ]
    for d in fails:
        print(d)
    n_err = sum(1 for d in diags if d.severity == "error")
    n_warn = len(diags) - n_err
    print(f"dogfood: {len(_BUILTIN_MODULES)} modules, "
          f"{n_err} error(s), {n_warn} warning(s), {len(fails)} new")
    return 1 if fails else 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="nnstreamer_tpu.tools.lint",
        description="Statically verify pipeline strings (caps propagation, "
                    "topology/deadlock, jit-purity) without running them.",
    )
    ap.add_argument("pipeline", nargs="*",
                    help="pipeline description string(s)")
    ap.add_argument("--strict", action="store_true",
                    help="warnings also fail (exit 1)")
    ap.add_argument("--files", nargs="+", metavar="PY",
                    help="lint every Pipeline(...) string in python files")
    ap.add_argument("--examples", action="store_true",
                    help="lint examples/ and tests/test_pipeline_e2e.py")
    ap.add_argument("--dogfood", action="store_true",
                    help="lint nnstreamer_tpu's own device_fns")
    ap.add_argument("--baseline", metavar="FILE",
                    help="accepted-diagnostics file: only NEW diagnostics "
                         "fail (one key per line, '#' comments)")
    ap.add_argument("--update-baseline", action="store_true",
                    help="write the current diagnostics to --baseline")
    ap.add_argument("-v", "--verbose", action="store_true",
                    help="also print clean results")
    args = ap.parse_args(argv)

    if not args.pipeline and not args.files and not args.examples \
            and not args.dogfood:
        ap.print_usage(sys.stderr)
        return 2

    baseline: Optional[set] = None
    if args.baseline and os.path.exists(args.baseline) \
            and not args.update_baseline:
        with open(args.baseline) as f:
            baseline = {
                ln.strip() for ln in f
                if ln.strip() and not ln.startswith("#")
            }
    collected: List[str] = []

    rc = 0
    if args.pipeline:
        from ..analysis import analyze

        for desc in args.pipeline:
            report = analyze(desc)
            _render(desc, report, verbose=args.verbose)
            if report.errors or (args.strict and report.warnings):
                rc = 1

    files = list(args.files or [])
    if args.examples:
        repo = os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))))
        ex_dir = os.path.join(repo, "examples")
        if os.path.isdir(ex_dir):
            files += sorted(
                os.path.join(ex_dir, f) for f in os.listdir(ex_dir)
                if f.endswith(".py"))
        e2e = os.path.join(repo, "tests", "test_pipeline_e2e.py")
        if os.path.exists(e2e):
            files.append(e2e)
    if files:
        rc = max(rc, lint_files(files, strict=args.strict,
                                verbose=args.verbose, baseline=baseline,
                                collected=collected))

    if args.dogfood:
        rc = max(rc, dogfood(strict=args.strict, baseline=baseline,
                             collected=collected))

    if args.update_baseline:
        if not args.baseline:
            print("--update-baseline needs --baseline FILE", file=sys.stderr)
            return 2
        with open(args.baseline, "w") as f:
            f.write("# nns-lint accepted diagnostics "
                    "(tools/lint.py --update-baseline)\n")
            for k in sorted(set(collected)):
                f.write(k + "\n")
        print(f"baseline updated: {len(set(collected))} accepted "
              f"diagnostic(s) -> {args.baseline}")
        return 0
    return rc


if __name__ == "__main__":
    sys.exit(main())
