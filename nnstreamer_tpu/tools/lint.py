"""nns-lint CLI: verify a pipeline string without running it.

    python -m nnstreamer_tpu.tools.lint "videotestsrc ! tensor_converter ! tensor_sink"
    python -m nnstreamer_tpu.tools.lint --strict "<pipeline>"     # warnings fail too
    python -m nnstreamer_tpu.tools.lint --dogfood                 # lint OUR device_fns
    python -m nnstreamer_tpu.tools.lint --examples                # lint examples/ + e2e strings
    python -m nnstreamer_tpu.tools.lint --deep "<pipeline>"       # + abstract execution

Exit codes: 0 clean/ok, 1 errors (or warnings with --strict), 2 usage.

Reference analog: gst-launch's parse-only mode plus nnstreamer's strict
pipeline parser — but whole-graph: every caps incompatibility, topology
hazard, and jit-purity violation is reported in ONE run with element-path
locations and source carets.  Runs with ``JAX_PLATFORMS=cpu`` and performs
no device dispatch: the syntactic passes never execute JAX, and ``--deep``
(abstract shape execution + static HBM/recompile budgeting, see
docs/ANALYSIS.md "Deep pass") only ever traces with ``jax.eval_shape`` —
it also prints the per-pipeline resource report, and with ``--dogfood``
abstract-traces the bundled zoo model families.
"""

from __future__ import annotations

import argparse
import ast
import os
import sys
from typing import Dict, List, Optional, Tuple


def _render(desc: str, report, *, verbose: bool) -> None:
    if report.clean:
        print(f"OK: {desc!r}")
    else:
        print(f"LINT: {desc!r}")
        print(report.render())
    if getattr(report, "resources", None) is not None:
        print(report.resources.render())


def extract_pipeline_strings(path: str) -> Tuple[List[str], List[Tuple[int, str]]]:
    """Pipeline strings passed to ``Pipeline(...)`` / ``parse_launch(...)``
    in a Python source file, resolved WITHOUT importing it (examples run
    pipelines at import time).

    f-string placeholders are resolved from module-level constant
    assignments (``SIZE = 224``) and function-call defaults where
    possible; calls whose first argument cannot be resolved statically are
    returned in the second list as ``(lineno, source snippet)`` so callers
    can report each un-lintable call BY NAME instead of silently skipping
    (the CI gate baselines them: a new unresolvable call fails).
    """
    with open(path) as f:
        source = f.read()
    tree = ast.parse(source, filename=path)

    consts: Dict[str, object] = {}
    for stmt in ast.walk(tree):  # any scope; first literal binding wins
        if not isinstance(stmt, ast.Assign):
            continue
        for tgt in stmt.targets:  # W = H = 96 has two targets
            if isinstance(tgt, ast.Name):
                try:
                    consts.setdefault(tgt.id, ast.literal_eval(stmt.value))
                except (ValueError, TypeError):
                    pass
            elif isinstance(tgt, ast.Tuple):
                try:
                    vals = ast.literal_eval(stmt.value)
                    for t, v in zip(tgt.elts, vals):
                        if isinstance(t, ast.Name):
                            consts.setdefault(t.id, v)
                except (ValueError, TypeError):
                    pass

    def resolve(node) -> Optional[str]:
        if isinstance(node, ast.Constant) and isinstance(node.value, str):
            return node.value
        if isinstance(node, ast.JoinedStr):
            parts = []
            for v in node.values:
                if isinstance(v, ast.Constant):
                    parts.append(str(v.value))
                elif isinstance(v, ast.FormattedValue):
                    if isinstance(v.value, ast.Name) \
                            and v.value.id in consts:
                        parts.append(str(consts[v.value.id]))
                    else:
                        return None
                else:
                    return None
            return "".join(parts)
        if isinstance(node, ast.BinOp) and isinstance(node.op, ast.Add):
            left, right = resolve(node.left), resolve(node.right)
            if left is not None and right is not None:
                return left + right
        return None

    found: List[str] = []
    skipped: List[Tuple[int, str]] = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.Call) or not node.args:
            continue
        f = node.func
        name = f.attr if isinstance(f, ast.Attribute) else (
            f.id if isinstance(f, ast.Name) else "")
        if name not in ("Pipeline", "parse_launch", "parse"):
            continue
        got = resolve(node.args[0])
        if got is None:
            snippet = (ast.get_source_segment(source, node.args[0])
                       or f"{name}(...)")
            snippet = " ".join(snippet.split())
            if len(snippet) > 80:
                snippet = snippet[:77] + "..."
            skipped.append((node.lineno, snippet))
        else:
            found.append(got)
    return found, skipped


def _diag_key(prefix: str, d, desc: Optional[str] = None) -> str:
    """Stable baseline key: file/source prefix + a short hash of the
    pipeline string + code + element path.  The hash pins the acceptance
    to ONE pipeline string — element labels like ``out`` repeat across the
    many strings in one file, and a baseline entry must not swallow a new
    defect in a different pipeline that happens to reuse a name.  No
    message text — line numbers in messages drift with unrelated edits."""
    import hashlib

    h = ""
    if desc is not None:
        h = hashlib.sha1(desc.encode()).hexdigest()[:8] + ":"
    return f"{prefix}:{h}{d.code}:{d.path}"


def _unresolved_keys(fname: str, skipped: List[Tuple[int, str]]
                     ) -> List[str]:
    """Stable baseline keys for un-lintable ``Pipeline(...)`` calls: file +
    a hash of the (whitespace-normalized) argument source, so the key
    survives unrelated line drift; identical snippets in one file get an
    occurrence index.  No line numbers — those churn with every edit."""
    import hashlib

    seen: Dict[str, int] = {}
    keys = []
    for _, snippet in skipped:
        n = seen.get(snippet, 0)
        seen[snippet] = n + 1
        h = hashlib.sha1(f"{snippet}#{n}".encode()).hexdigest()[:8]
        keys.append(f"{fname}:unresolvable-pipeline:{h}")
    return keys


def lint_files(paths: List[str], *, strict: bool, verbose: bool,
               baseline: Optional[set] = None,
               collected: Optional[List[str]] = None,
               deep: bool = False, reconfig: Optional[dict] = None) -> int:
    from ..analysis import analyze

    rc = 0
    total = skipped_total = accepted = 0
    for path in paths:
        fname = os.path.basename(path)
        strings, skipped = extract_pipeline_strings(path)
        skipped_total += len(skipped)
        # Un-lintable calls are named findings, not a silent count: each
        # becomes a warning keyed into the baseline, so a NEW example the
        # analyzer cannot see fails the strict CI gate instead of
        # shrinking coverage.
        ukeys = _unresolved_keys(fname, skipped)
        if collected is not None:
            collected.extend(ukeys)
        for (lineno, snippet), k in zip(skipped, ukeys):
            is_new = baseline is None or k not in baseline
            accepted += 1 if (baseline is not None and k in baseline) else 0
            if strict and is_new:
                rc = 1
            if verbose or (strict and is_new):
                print(f"warning[unresolvable-pipeline] {fname}:{lineno}: "
                      f"Pipeline argument not statically resolvable: "
                      f"{snippet}")
        for desc in strings:
            total += 1
            report = analyze(desc, deep=deep, reconfig=reconfig)
            keys = [_diag_key(fname, d, desc) for d in report]
            if collected is not None:
                collected.extend(keys)
            fails = [
                d for d, k in zip(report.diagnostics, keys)
                if (d.severity == "error" or strict)
                and (baseline is None or k not in baseline)
            ]
            accepted += sum(
                1 for k in keys if baseline is not None and k in baseline)
            if fails or verbose:
                print(f"-- {fname}")
                _render(desc, report, verbose=verbose)
            elif deep and getattr(report, "resources", None) is not None:
                print(f"-- {fname}: deep: {report.resources.summary()}")
            if fails:
                rc = 1
    print(f"linted {total} pipeline string(s) from {len(paths)} file(s)"
          + (f"; {skipped_total} call(s) not statically resolvable"
             if skipped_total else "")
          + (f"; {accepted} baseline-accepted diagnostic(s)"
             if accepted else ""))
    return rc


def dogfood(*, strict: bool, baseline: Optional[set] = None,
            collected: Optional[List[str]] = None, deep: bool = False) -> int:
    """Lint the framework's OWN device_fns (every built-in plugin module):
    a host side effect sneaking into a shipped element's pure fn fails CI
    before it silently knocks that element off the fused-XLA path.  With
    ``deep``, additionally abstract-trace the bundled zoo model families
    (mobilenet/ssd/posenet/yolo/...) against their declared I/O specs via
    ``jax.eval_shape`` — a model whose apply_fn drifts from its declared
    out_spec fails here, statically, with zero dispatch."""
    import importlib

    from ..analysis.purity import lint_module
    from ..core.registry import _BUILTIN_MODULES

    diags = []
    for modname in _BUILTIN_MODULES:
        try:
            mod = importlib.import_module(modname)
        except ImportError:
            continue
        diags.extend(lint_module(mod))
    keys = [_diag_key("dogfood", d) for d in diags]
    zoo_note = ""
    if deep:
        from ..analysis.tracecheck import trace_zoo_models

        zdiags, traced, skipped = trace_zoo_models()
        diags.extend(zdiags)
        keys.extend(_diag_key("deep-zoo", d) for d in zdiags)
        zoo_note = (f", {traced} zoo model(s) abstract-traced"
                    + (f" ({skipped} skipped)" if skipped else ""))
    if collected is not None:
        collected.extend(keys)
    fails = [
        d for d, k in zip(diags, keys)
        if (d.severity == "error" or strict)
        and (baseline is None or k not in baseline)
    ]
    for d in fails:
        print(d)
    n_err = sum(1 for d in diags if d.severity == "error")
    n_warn = len(diags) - n_err
    print(f"dogfood: {len(_BUILTIN_MODULES)} modules{zoo_note}, "
          f"{n_err} error(s), {n_warn} warning(s), {len(fails)} new")
    return 1 if fails else 0


def lint_threads(*, strict: bool, verbose: bool,
                 baseline: Optional[set] = None,
                 collected: Optional[List[str]] = None,
                 files: Optional[List[str]] = None) -> int:
    """nns-tsan static side: run the concurrency passes (guarded-by,
    lock-order graph, thread lifecycle, bare condition waits) over the
    whole package (or ``files``) — docs/ANALYSIS.md "Threads pass"."""
    from ..analysis import concurrency

    if files:
        reports, stats = concurrency.lint_paths(files)
    else:
        reports, stats = concurrency.lint_package()
    rc = 0
    accepted = n_err = n_warn = n_new = 0
    for rep in reports:
        keys = [concurrency.baseline_key(d) for d in rep]
        if collected is not None:
            collected.extend(keys)
        fails = []
        for d, k in zip(rep.diagnostics, keys):
            n_err += 1 if d.severity == "error" else 0
            n_warn += 1 if d.severity == "warning" else 0
            if baseline is not None and k in baseline:
                accepted += 1
                continue
            if d.severity == "error" or strict:
                fails.append(d)
        if fails:
            rc = 1
            n_new += len(fails)
            sub = type(rep)(rep.source)
            sub.extend(fails)
            print(sub.render())
        elif verbose and rep.diagnostics:
            print(rep.render())
    print(f"threads: {stats['files']} file(s), {stats['threaded']} "
          f"threaded module(s), {stats['guarded_classes']} guarded "
          f"class(es), {stats['locks']} lock(s), {stats['edges']} "
          f"order edge(s); {n_err} error(s), {n_warn} warning(s), "
          f"{n_new} new"
          + (f", {accepted} baseline-accepted" if accepted else ""))
    return rc


def lint_proto(*, strict: bool, verbose: bool,
               baseline: Optional[set] = None,
               collected: Optional[List[str]] = None,
               files: Optional[List[str]] = None) -> int:
    """nns-proto: message-alphabet + handler-totality lint and the
    model-vs-code drift gate over the distributed serving protocol
    modules (docs/ANALYSIS.md "Protocol pass").  With ``files``, lints
    those files (no drift gate — the gate is a whole-surface claim)."""
    from ..analysis import protocol

    if files:
        reports, stats = protocol.lint_paths(files)
    else:
        reports, stats = protocol.lint_package()
    rc = 0
    accepted = n_err = n_warn = n_new = 0
    for rep in reports:
        keys = [protocol.baseline_key(d) for d in rep]
        if collected is not None:
            collected.extend(keys)
        fails = []
        for d, k in zip(rep.diagnostics, keys):
            n_err += 1 if d.severity == "error" else 0
            n_warn += 1 if d.severity == "warning" else 0
            if baseline is not None and k in baseline:
                accepted += 1
                continue
            if d.severity == "error" or strict:
                fails.append(d)
        if fails:
            rc = 1
            n_new += len(fails)
            sub = type(rep)(rep.source)
            sub.extend(fails)
            print(sub.render())
        elif verbose and rep.diagnostics:
            print(rep.render())
    print(f"proto: {stats['files']} file(s), {stats['keys']} meta key(s), "
          f"{stats['kinds']} control kind(s), {stats['handlers']} "
          f"handler(s) ({stats['proven']} proven), {stats['models']} "
          f"model(s); {n_err} error(s), {n_warn} warning(s), {n_new} new"
          + (f", {accepted} baseline-accepted" if accepted else ""))
    return rc


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="nnstreamer_tpu.tools.lint",
        description="Statically verify pipeline strings (caps propagation, "
                    "topology/deadlock, jit-purity) without running them.",
    )
    ap.add_argument("pipeline", nargs="*",
                    help="pipeline description string(s)")
    ap.add_argument("--strict", action="store_true",
                    help="warnings also fail (exit 1)")
    ap.add_argument("--files", nargs="+", metavar="PY",
                    help="lint every Pipeline(...) string in python files")
    ap.add_argument("--examples", action="store_true",
                    help="lint examples/ and tests/test_pipeline_e2e.py")
    ap.add_argument("--dogfood", action="store_true",
                    help="lint nnstreamer_tpu's own device_fns")
    ap.add_argument("--threads", action="store_true",
                    help="nns-tsan static side: lock discipline "
                         "(_GUARDED_BY), lock-order graph, thread "
                         "lifecycle, bare condition waits over the "
                         "package (docs/ANALYSIS.md 'Threads pass'); "
                         "with --files, over those files instead")
    ap.add_argument("--proto", action="store_true",
                    help="nns-proto: message-alphabet + handler-totality "
                         "lint, unanswered-path proof, and model-vs-code "
                         "drift gate over the serving protocol modules "
                         "(docs/ANALYSIS.md 'Protocol pass'); with "
                         "--files, over those files instead")
    ap.add_argument("--deep", action="store_true",
                    help="also abstractly execute every device stage "
                         "(jax.eval_shape: shape/dtype contract checks + "
                         "static HBM/recompile budgets; imports jax, zero "
                         "dispatch)")
    ap.add_argument("--reconfig", metavar="K:V[,K:V...]",
                    help="with --deep: propose a runtime config change "
                         "for continuous-serving stages (e.g. "
                         "slots:8,kv_blocks:256) — knobs whose change "
                         "would alter a compiled signature warn "
                         "recompile-on-reconfig with the drain/restart "
                         "remediation (docs/SERVING.md 'Elastic "
                         "serving')")
    ap.add_argument("--baseline", metavar="FILE",
                    help="accepted-diagnostics file: only NEW diagnostics "
                         "fail (one key per line, '#' comments)")
    ap.add_argument("--update-baseline", action="store_true",
                    help="write the current diagnostics to --baseline")
    ap.add_argument("-v", "--verbose", action="store_true",
                    help="also print clean results")
    args = ap.parse_args(argv)

    if not args.pipeline and not args.files and not args.examples \
            and not args.dogfood and not args.threads and not args.proto:
        ap.print_usage(sys.stderr)
        return 2

    baseline: Optional[set] = None
    if args.baseline and os.path.exists(args.baseline) \
            and not args.update_baseline:
        with open(args.baseline) as f:
            baseline = {
                ln.strip() for ln in f
                if ln.strip() and not ln.startswith("#")
            }
    collected: List[str] = []

    reconfig = None
    if args.reconfig:
        if not args.deep:
            # the check lives in the deep pass; silently ignoring the
            # flag would green-light the exact mutation it exists to
            # catch
            print("--reconfig requires --deep", file=sys.stderr)
            return 2
        from ..filters.base import parse_custom_options

        reconfig = parse_custom_options(args.reconfig)

    rc = 0
    if args.pipeline:
        from ..analysis import analyze

        for desc in args.pipeline:
            report = analyze(desc, deep=args.deep, reconfig=reconfig)
            _render(desc, report, verbose=args.verbose)
            if report.errors or (args.strict and report.warnings):
                rc = 1

    files = list(args.files or [])
    if args.examples:
        repo = os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))))
        ex_dir = os.path.join(repo, "examples")
        if os.path.isdir(ex_dir):
            files += sorted(
                os.path.join(ex_dir, f) for f in os.listdir(ex_dir)
                if f.endswith(".py"))
        e2e = os.path.join(repo, "tests", "test_pipeline_e2e.py")
        if os.path.exists(e2e):
            files.append(e2e)
    if files and not args.threads and not args.proto:
        rc = max(rc, lint_files(files, strict=args.strict,
                                verbose=args.verbose, baseline=baseline,
                                collected=collected, deep=args.deep,
                                reconfig=reconfig))

    if args.threads:
        rc = max(rc, lint_threads(strict=args.strict,
                                  verbose=args.verbose,
                                  baseline=baseline,
                                  collected=collected,
                                  files=files or None))

    if args.proto:
        rc = max(rc, lint_proto(strict=args.strict,
                                verbose=args.verbose,
                                baseline=baseline,
                                collected=collected,
                                files=files or None))

    if args.dogfood:
        rc = max(rc, dogfood(strict=args.strict, baseline=baseline,
                             collected=collected, deep=args.deep))

    if args.update_baseline:
        if not args.baseline:
            print("--update-baseline needs --baseline FILE", file=sys.stderr)
            return 2
        with open(args.baseline, "w") as f:
            f.write("# nns-lint accepted diagnostics "
                    "(tools/lint.py --update-baseline)\n")
            for k in sorted(set(collected)):
                f.write(k + "\n")
        print(f"baseline updated: {len(set(collected))} accepted "
              f"diagnostic(s) -> {args.baseline}")
        return 0
    return rc


if __name__ == "__main__":
    sys.exit(main())
