"""LLM token-streaming framework for tensor_filter.

Reference analog: the llama.cpp sub-plugin
(``ext/nnstreamer/tensor_filter/tensor_filter_llamacpp.cc``, SURVEY §2.4
[UNVERIFIED]): ``tensor_filter framework=llamacpp`` takes a prompt buffer
and streams generated tokens downstream as flexible tensors.  Here the
runtime is JAX, not a wrapped C++ library:

* prefill and per-token decode are TWO jitted XLA programs (same function,
  two sequence lengths — see models/llama.py ``forward_cached``); weights
  and KV cache never leave HBM between tokens;
* multi-chip: ``custom=tp:N`` builds/uses a ``model``-axis mesh and jits
  with NamedShardings from the model's ``param_pspecs`` — XLA places the
  TP all-reduces on ICI (config #5's multi-chip token streaming);
* tokens are pushed downstream from a generator in bursts of
  ``stream_chunk`` (default 8): each burst is ONE jitted lax.scan over the
  device (one host roundtrip per burst — over a remote chip this is the
  difference between ~5 and ~100s of tok/s); ``stream_chunk:1`` restores
  strict per-token delivery at per-token roundtrip cost.

Pipeline usage::

    appsrc name=prompt ! tensor_filter framework=llm model=llama_tiny
        custom=max_new:32,temperature:0.0 invoke-dynamic=true !
        tensor_sink name=tokens

Input: one uint8 tensor (UTF-8 prompt bytes) or int32 token ids ``[T]`` /
``[B, T]``.  Output per token: ``[B]`` int32 token ids + uint8 piece bytes
(batch 1 only), as FLEXIBLE tensors.  Tokenization uses the checkpoint's
own SentencePiece vocab when the model file carries one (GGUF
``tokenizer.ggml.*`` -> models/tokenizer.py) and falls back to byte-level
ids otherwise; with a real vocab, generation stops at the model's EOS
token like the reference sub-plugin.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, Iterator, List, Optional, Sequence

import numpy as np

from ..core.config import get_config
from ..core.log import logger, metrics
from ..core.registry import register_filter
from ..core.types import TensorFormat, TensorsSpec
from ..models import llama
from ..models.zoo import build as build_model
from .base import Framework, FrameworkError, parse_custom_options

log = logger(__name__)


def _next_bucket(t: int) -> int:
    """Smallest power-of-two >= t (min 32): bounds distinct prefill
    compilations at log2(max_seq) programs for arbitrary prompt mixes."""
    b = 32
    while b < t:
        b <<= 1
    return b


class ByteTokenizer:
    """Byte-level tokenizer: id = byte + n_special.  Deterministic, no vocab
    file.  ids 0..n_special-1 are special (0=pad, 1=bos, 2=eos)."""

    n_special = 3
    bos = 1
    eos = 2

    def encode(self, text_bytes: bytes) -> List[int]:
        return [self.bos] + [b + self.n_special for b in text_bytes]

    def decode_piece(self, token_id: int) -> bytes:
        if token_id < self.n_special:
            return b""
        b = token_id - self.n_special
        return bytes([b]) if b < 256 else b""


@register_filter("llm", aliases=("llamacpp", "llama.cpp"))
class LLMFramework(Framework):
    """Streaming generation.  ``custom=`` options:

    ``max_new:N`` (default 32), ``temperature:F`` (0 = greedy), ``seed:N``,
    ``top_k:N`` / ``top_p:F`` (sampler truncation, compiled into the
    decode program — llama.cpp's sampler-chain analog),
    ``tokenizer:PATH`` (a .gguf whose ``tokenizer.ggml.*`` vocab is used
    for text; defaults to the model file's own vocab when it has one,
    byte-level otherwise),
    ``stream_chunk:N`` (tokens decoded per device roundtrip, default 8;
    1 = strict per-token streaming),
    ``tp:N`` (tensor-parallel ways over a ``model`` mesh axis),
    ``serve:continuous`` + ``slots:N`` (continuous batching: a standing
    per-row-position decode loop that admits queued prompts into free
    slots at chunk boundaries — see :class:`_ContinuousLoop`),
    ``quant:int8`` / ``quant:int4`` (weight-only quantization; int4 is
    nibble-packed and decodes through the Pallas kernel in
    ops/int4_matmul.py on TPU),
    ``dtype:bfloat16|float32``, plus any model-builder options
    (``dim:…``, ``n_layers:…``) forwarded to the zoo.
    """

    name = "llm"
    streaming = True

    def __init__(self):
        super().__init__()
        self.bundle = None
        self.cfg: Optional[llama.LlamaConfig] = None
        self.tokenizer = ByteTokenizer()
        self.max_new = 32
        self.temperature = 0.0
        self.top_k = 0
        self.top_p = 1.0
        self.seed = 0
        self.stop_eos = False
        self.mesh = None
        self._fwd = None
        self.continuous = False
        self._serve: Optional["_ContinuousLoop"] = None
        self._serve_lock = threading.Lock()

    def open(self, props: Dict[str, object]) -> None:
        super().open(props)
        model = str(props.get("model") or "llama_tiny")
        opts = parse_custom_options(str(props.get("custom", "")))
        self.max_new = int(opts.pop("max_new", 32))
        self.temperature = float(opts.pop("temperature", 0.0))
        self.top_k = int(opts.pop("top_k", 0))
        self.top_p = float(opts.pop("top_p", 1.0))
        self.seed = int(opts.pop("seed", 0))
        tok_path = opts.pop("tokenizer", None)
        stop_opt = opts.pop("stop_eos", None)
        # Tokens decoded per device roundtrip (stream granularity): tokens
        # still stream downstream one-by-one, in bursts of this size.
        self.chunk = max(1, int(opts.pop("stream_chunk", 8)))
        tp = int(opts.pop("tp", 1))
        # serve:continuous — a standing decode loop with ``slots:N`` rows:
        # prompts are admitted into free slots of a RUNNING per-row-
        # position decode (each stream at its own depth), so a late
        # client never waits for earlier streams to finish the way a
        # static group would make it.  Modern "continuous batching"; no
        # reference analog.
        self.continuous = str(opts.pop("serve", "")).lower() == "continuous"
        self.slots = int(opts.pop("slots", 4))
        self.dtype = opts.get("dtype", "bfloat16")
        try:
            self.bundle = build_model(model, opts)
        except KeyError as e:
            raise FrameworkError(str(e)) from e
        self.cfg = getattr(self.bundle, "config", None)
        if self.cfg is None:
            raise FrameworkError(
                f"model {model!r} has no LlamaConfig; the llm framework needs "
                "a decoder-LM bundle (models/llama.py)"
            )
        # Tokenizer priority: explicit custom=tokenizer:PATH, then the
        # model file's own embedded vocab, then the byte-level fallback.
        if tok_path is not None:
            from ..models.tokenizer import load_gguf_tokenizer

            tok = load_gguf_tokenizer(str(tok_path))
            if tok is None:
                raise FrameworkError(
                    f"tokenizer file {tok_path!r} carries no "
                    "tokenizer.ggml.tokens vocab")
            self.tokenizer = tok
        elif getattr(self.bundle, "tokenizer", None) is not None:
            self.tokenizer = self.bundle.tokenizer
        n_tok = getattr(self.tokenizer, "n_vocab", 0)
        if n_tok > self.cfg.vocab:
            # XLA CLAMPS out-of-range embedding gathers instead of
            # raising — a vocab bigger than the model would silently
            # generate from wrong embeddings
            raise FrameworkError(
                f"tokenizer vocab ({n_tok}) exceeds model vocab "
                f"({self.cfg.vocab}); wrong tokenizer for this model")
        # EOS terminates generation when a real vocab is in play (the
        # llama.cpp contract); byte-level ids keep fixed-length decode so
        # synthetic-model tests and benches stay deterministic.
        # Override with custom=stop_eos:0/1.
        stop = stop_opt
        if stop is None:
            self.stop_eos = not isinstance(self.tokenizer, ByteTokenizer)
        else:
            self.stop_eos = str(stop).lower() not in ("0", "false", "no")
        self._setup(tp)

    def _setup(self, tp: int) -> None:
        import jax

        from ..parallel.mesh import make_mesh
        from ..parallel.sharding import shard_params

        cfg = self.cfg
        params = self.bundle.params

        if tp > 1:
            if len(jax.devices()) < tp:
                raise FrameworkError(
                    f"tp:{tp} needs {tp} devices, have {len(jax.devices())}")
            self.mesh = make_mesh(model=tp, data=1,
                                  devices=jax.devices()[:tp])
            # the bundle's pspecs match ITS pytree (quantized trees have
            # different leaves than llama.param_pspecs()'s default)
            pspecs = self.bundle.param_pspecs or llama.param_pspecs()
            params = shard_params(self.mesh, params, pspecs)
            self.bundle.params = params
            # pallas_call has no GSPMD partitioning rule: int4 programs
            # traced for this sharded mesh must take the shardable XLA
            # reference path.  Refcounted disable, taken LAST in the TP
            # block (nothing after it throws) and released in close(),
            # so a failed open can't leak a disabled kernel and two TP
            # filters don't clobber each other.
            from ..ops import int4_matmul as _i4

            _i4.disable_kernel()
            self._int4_disabled = True

        def fwd(params, tokens, cache, pos):
            return llama.forward_cached(params, tokens, cache, pos, cfg,
                                        compute_dtype=self.dtype)

        # Prefill program (only ever called with pos=0).  pos is STATIC so
        # the trace sees a Python int and models/llama.py's prefill branch
        # (flash attention over the prompt, not a masked sweep over all
        # max_seq cache rows) actually compiles in; a traced pos would make
        # `type(pos_offset) is int` False at trace time.  Cache donated so
        # prefill writes in place.
        self._fwd = jax.jit(fwd, static_argnums=(3,), donate_argnums=(2,))

        temperature = self.temperature
        top_k, top_p = self.top_k, self.top_p

        def decode_chunk(params, tok, cache, key, pos0, length):
            """`length` decode steps as ONE program (lax.scan): the host sees
            one roundtrip per chunk, not per token — over a remote/tunneled
            device this is the difference between ~5 and ~100s of tok/s."""
            import jax.numpy as jnp
            from jax import lax

            def step(carry, i):
                tok, cache, key = carry
                key, sub = jax.random.split(key)
                logits, cache = llama.forward_cached(
                    params, tok[:, None], cache, pos0 + i, cfg,
                    compute_dtype=self.dtype)
                nxt = llama.sample_token(logits[:, -1], sub, temperature,
                                         top_k, top_p)
                return (nxt, cache, key), nxt

            (tok, cache, key), toks = lax.scan(
                step, (tok, cache, key), jnp.arange(length))
            return jnp.moveaxis(toks, 0, 1), tok, cache, key  # [B, length]

        self._decode_chunk = jax.jit(
            decode_chunk, static_argnames=("length",), donate_argnums=(2,))

    def close(self) -> None:
        if self._serve is not None:
            self._serve.shutdown()
            self._serve = None
        if getattr(self, "_int4_disabled", False):
            from ..ops import int4_matmul as _i4

            _i4.enable_kernel()
            self._int4_disabled = False
        self.bundle = None
        self._fwd = None
        self._decode_chunk = None

    # -- continuous serving ------------------------------------------------
    def submit(self, inputs: Sequence, meta: Dict, emit) -> None:
        """Queue one prompt into the standing decode loop
        (``custom=serve:continuous``).  ``emit(tensors, meta)`` is called
        from the serve thread once per generated token, carrying the
        request's meta plus stream_index/stream_last."""
        # Lock the lazy creation: two first-submits racing from different
        # threads must not spawn two serve loops (duplicate slot caches,
        # split streams) — the framework API stays safe outside the
        # single-runner pipeline assumption.
        if self._serve is None:
            with self._serve_lock:
                if self._serve is None:
                    self._serve = _ContinuousLoop(self)
        self._serve.submit(self._to_tokens(inputs[0]), meta, emit)

    def drain(self, timeout: float = 600.0) -> bool:
        """Block until every admitted stream has finished (EOS path)."""
        return self._serve is None or self._serve.drain(timeout)

    def get_model_info(self):
        flex_in = TensorsSpec.from_string("1", "uint8").replace(
            format=TensorFormat.FLEXIBLE)
        flex_out = TensorsSpec.from_string("1", "int32").replace(
            format=TensorFormat.FLEXIBLE)
        return flex_in, flex_out

    # -- tokenization ------------------------------------------------------
    def _to_tokens(self, arr: np.ndarray) -> np.ndarray:
        arr = np.asarray(arr)
        if arr.dtype == np.uint8:
            ids = self.tokenizer.encode(arr.tobytes())
            return np.asarray([ids], np.int32)
        toks = arr.astype(np.int32)
        if toks.ndim == 1:
            toks = toks[None, :]
        if toks.ndim != 2:
            raise FrameworkError(f"prompt must be [T] or [B,T], got {arr.shape}")
        return toks

    # -- generation --------------------------------------------------------
    def _gen_tokens(self, prompt: np.ndarray) -> Iterator[np.ndarray]:
        import jax
        import jax.numpy as jnp

        cfg = self.cfg
        B, T = prompt.shape
        if T >= cfg.max_seq:
            raise FrameworkError(
                f"prompt length {T} >= max_seq {cfg.max_seq}")
        cache = llama.init_cache(cfg, B, dtype=self.dtype)
        if self.mesh is not None:
            from ..parallel.sharding import shard_params as _sp
            cache = _sp(self.mesh, cache, llama.cache_pspecs())
        params = self.bundle.params
        # Prompt-length bucketing (SURVEY §7 "dynamic shapes vs XLA static
        # shapes"): the prefill program compiles per SHAPE, so serving
        # mixed-length prompts would compile per length.  Right-pad to the
        # next bucket: causal attention keeps real tokens from seeing pad
        # rows, decode overwrites cache row `pos` before any later
        # position can attend it, and the sampled logit is read at the
        # REAL last position — numerics are untouched (asserted by test).
        P = T
        if get_config().shape_bucketing:
            P = min(_next_bucket(T), cfg.max_seq - 1)
        if P > T:
            prompt = np.pad(prompt, ((0, 0), (0, P - T)))
        logits, cache = self._fwd(params, jnp.asarray(prompt), cache, 0)
        key = jax.random.PRNGKey(self.seed)
        # At least one token is always safe: prefill wrote cache[0:P]
        # (real rows 0:T; rows T..P-1 hold pad-token K/V that stay hidden
        # behind the decode mask until sequentially overwritten) and the
        # first sample needs no further cache write.  Subsequent decode
        # steps feed at positions T..T+n-2, each of which must stay
        # < max_seq.
        n = max(1, min(self.max_new, cfg.max_seq - T))
        # EOS termination (batch-1 streams; batched rows finish at their
        # own depths, so callers slice on ids themselves)
        eos = getattr(self.tokenizer, "eos", -1) if self.stop_eos else -1
        tok = llama.sample_token(logits[:, T - 1], key, self.temperature,
                                 self.top_k, self.top_p)
        first = np.asarray(tok)
        yield first
        if B == 1 and int(first[0]) == eos:
            return
        done = 1
        pos = T
        while done < n:
            # Chunked decode; a shorter tail chunk costs one extra compile
            # (two cached programs total: full chunk + tail).  n's clamp
            # already guarantees every decode position stays < max_seq.
            length = min(self.chunk, n - done)
            toks, tok, cache, key = self._decode_chunk(
                params, tok, cache, key, pos, length=length)
            host = np.asarray(toks)  # ONE roundtrip per chunk
            for j in range(length):
                yield host[:, j]
                if B == 1 and int(host[0, j]) == eos:
                    return
            done += length
            pos += length

    def invoke_stream(self, inputs: Sequence) -> Iterator[List[np.ndarray]]:
        """Yield one output list per generated token: [ids [B] int32,
        piece bytes uint8] — flexible tensors, the reference's streaming
        contract.  Batched prompts ([B, T], B>1 — e.g. stacked by a
        ``tensor_query_serversrc max-batch=N``) yield [ids [B]] only: a
        per-row variable-length piece tensor is not batch-leading, so
        byte decoding is the consumer's job (ids are the contract; the
        query serversink row-splits ids back to each client)."""
        prompt = self._to_tokens(inputs[0])
        for ids in self._gen_tokens(prompt):
            metrics.count("llm.tokens", ids.shape[0])
            if ids.shape[0] != 1:
                yield [ids]
                continue
            piece = np.frombuffer(
                self.tokenizer.decode_piece(int(ids[0])), np.uint8)
            yield [ids, piece.copy()]

    def invoke(self, inputs: Sequence) -> List[np.ndarray]:
        """Non-streaming: all generated ids as one [B, N] tensor + the
        decoded bytes (batch-1 only; batched yields carry ids alone)."""
        chunks = [outs[0] for outs in self.invoke_stream(inputs)]
        ids = np.stack(chunks, axis=1)
        text = b"".join(self.tokenizer.decode_piece(int(t)) for t in ids[0])
        return [ids, np.frombuffer(text, np.uint8).copy()]


class _ContinuousLoop:
    """Standing decode loop for ``custom=serve:continuous``.

    One thread owns a ``slots``-row KV cache and a per-row position
    vector (models/llama.py per-row ``pos_offset``).  Each iteration:
    (1) admit queued prompts into idle slots — a bucketed batch-1 prefill
    written into the slot's cache rows (``llama.write_cache_slot``), its
    first token emitted immediately; (2) run ONE ``lax.scan`` decode
    chunk advancing every live slot, each at its own depth; (3) emit each
    live slot's tokens to its own requester and retire finished slots.
    A stream admitted mid-flight therefore starts decoding at the next
    chunk boundary instead of waiting for the running group to finish —
    continuous batching, the serving shape a static group cannot express.
    Idle slots decode garbage rows parked out of cache range (their
    writes are dropped); their FLOPs ride along — static shapes are the
    price of zero recompiles.
    """

    def __init__(self, fw: LLMFramework):
        import queue as _q
        import threading

        import jax
        import jax.numpy as jnp
        from jax import lax

        self.fw = fw
        cfg, temperature = fw.cfg, fw.temperature
        self._pending: "_q.Queue" = _q.Queue()
        self._wake = threading.Event()
        self._stop = threading.Event()
        self._idle = threading.Event()
        self._idle.set()
        # Guards the idle decision: without it, submit() could clear
        # _idle and THEN enqueue while the serve loop, between those two
        # steps, observes an empty queue and sets _idle — drain() would
        # return with a live request pending and EOS would cut it off.
        self._idle_lock = threading.Lock()
        self._error: Optional[BaseException] = None
        #: (meta, emit) entries mid-admission, crash-visible; a list —
        #: several async admissions can be in flight per iteration
        self._admitting: list = []

        def decode_rows(params, tok, cache, key, pos, length):
            def step(carry, _):
                tok, cache, key, pos = carry
                key, sub = jax.random.split(key)
                logits, cache = llama.forward_cached(
                    params, tok[:, None], cache, pos, cfg,
                    compute_dtype=fw.dtype)
                nxt = llama.sample_token(logits[:, -1], sub, temperature,
                                         fw.top_k, fw.top_p)
                return (nxt, cache, key, pos + 1), nxt

            (tok, cache, key, pos), toks = lax.scan(
                step, (tok, cache, key, pos), None, length=length)
            return jnp.moveaxis(toks, 0, 1), tok, cache, key, pos

        self._decode_rows = jax.jit(
            decode_rows, static_argnames=("length",), donate_argnums=(2,))
        # slot index passed as a traced scalar: ONE admission program
        self._write_slot = jax.jit(llama.write_cache_slot,
                                   donate_argnums=(0,))
        self._thread = threading.Thread(
            target=self._run, name="llm-serve", daemon=True)
        self._thread.start()

    # -- producer side -----------------------------------------------------
    def submit(self, prompt, meta: Dict, emit) -> None:
        # The error check lives INSIDE the lock: the crash handler drains
        # _pending and sets _idle under the same lock, so a submit cannot
        # slip a request into a dead loop's queue between its own error
        # check and its put (that request would never be dequeued or
        # aborted — a hung client).
        with self._idle_lock:
            if self._error is not None:
                raise FrameworkError(
                    f"continuous serve loop died: {self._error!r}")
            self._idle.clear()
            self._pending.put((prompt, meta, emit))
        self._wake.set()

    def drain(self, timeout: float) -> bool:
        return self._idle.wait(timeout)

    def shutdown(self) -> None:
        self._stop.set()
        self._wake.set()
        self._thread.join(timeout=30)

    # -- serve thread ------------------------------------------------------
    def _emit_token(self, emit, meta: Dict, token_id: int, index: int,
                    last: bool) -> None:
        out_meta = dict(meta)
        out_meta["stream_index"] = index
        # Serving telemetry: when THIS token left the decode loop
        # (monotonic seconds).  Lets consumers measure generation-window
        # throughput precisely instead of inferring it from pull times,
        # which lag emission by queue dwell.
        out_meta["emit_t"] = time.monotonic()
        if last:
            out_meta["stream_last"] = True
        piece = self.fw.tokenizer.decode_piece(token_id)
        emit([np.asarray([token_id], np.int32),
              np.frombuffer(piece, np.uint8).copy()], out_meta)
        metrics.count("llm.tokens")

    def _run(self) -> None:
        try:
            self._run_inner()
        except BaseException as e:  # noqa: BLE001 - daemon thread: report
            log.exception("continuous serve loop died")

            def abort(meta, emit, idx=0):
                try:
                    self._emit_token(
                        emit, {**meta, "stream_aborted": True}, 0, idx,
                        True)
                except Exception:  # noqa: BLE001
                    pass

            # Terminate every live, mid-admission, and queued stream so
            # no client hangs to its timeout waiting on a dead loop.  The
            # queue drain + idle-set run under _idle_lock, pairing with
            # submit(): no request can enter the queue after the drain.
            import queue as _q

            for slot in list(getattr(self, "_live_slots", []) or []):
                if slot is not None:
                    abort(slot[0], slot[1], 1 << 30)
            for entry in list(self._admitting):
                abort(*entry)
            with self._idle_lock:
                self._error = e
                while True:
                    try:
                        _, meta, emit = self._pending.get_nowait()
                    except _q.Empty:
                        break
                    abort(meta, emit)
                self._idle.set()

    def _run_inner(self) -> None:
        import queue as _q

        import jax
        import jax.numpy as jnp

        fw, cfg = self.fw, self.fw.cfg
        B = fw.slots
        params = fw.bundle.params
        cache = llama.init_cache(cfg, B, dtype=fw.dtype)
        # tok/pos live ON DEVICE between chunks (r4): materializing them
        # per chunk cost two tunnel roundtrips per iteration on top of
        # the one that delivers tokens.  Host keeps only bookkeeping
        # (remaining/sidx/slots) that never needs device values.
        pos = jnp.full((B,), cfg.max_seq, jnp.int32)  # parked = idle
        tok = jnp.zeros((B,), jnp.int32)
        remaining = np.zeros((B,), np.int64)
        sidx = np.zeros((B,), np.int64)
        slots: list = [None] * B  # (meta, emit) per live slot
        self._live_slots = slots  # visible to the crash terminator
        key = jax.random.PRNGKey(fw.seed)
        eos = getattr(fw.tokenizer, "eos", -1) if fw.stop_eos else -1

        # tiny jitted updates keeping tok/pos device-resident
        set_slot = jax.jit(lambda a, i, v: a.at[i].set(v),
                           donate_argnums=(0,))
        park_idle = jax.jit(
            lambda p, idle: jnp.where(idle, cfg.max_seq, p),
            donate_argnums=(0,))

        from ..core.config import get_config as _gc

        import os as _os
        trace = _os.environ.get("NNSTPU_SERVE_TRACE") == "1"

        def _tr(tag):
            if trace:
                # stderr: stdout carries bench.py's line-delimited JSON
                import sys as _sys

                print(f"[serve {time.monotonic():.3f}] {tag}",
                      file=_sys.stderr, flush=True)

        # Warm EVERY program the loop uses before admitting real work:
        # over a tunneled device, first-use costs (trace + compile +
        # program upload) run 0.5-2 s EACH and land on the first
        # requests' critical path otherwise (traced: park_idle's first
        # compile alone delayed a join by 0.7 s).  llama.cpp servers
        # warm up the same way.  The garbage this writes into slot 0's
        # cache rows stays masked behind parked positions until a real
        # admission overwrites it.
        warm_T = min(32, cfg.max_seq - 1)
        logits_w, small_w = fw._fwd(
            params, jnp.zeros((1, warm_T), jnp.int32),
            llama.init_cache(cfg, 1, dtype=fw.dtype), 0)
        cache = self._write_slot(cache, small_w, np.int32(0))
        key, sub = jax.random.split(key)
        first_w = llama.sample_token(logits_w[:, -1], sub, fw.temperature,
                                     fw.top_k, fw.top_p)[0]
        tok = set_slot(tok, np.int32(0), first_w)     # device-scalar variant
        pos = set_slot(pos, np.int32(0), np.int32(0))  # host-scalar variant
        toks_w, tok, cache, key, pos = self._decode_rows(
            params, tok, cache, key, pos, length=fw.chunk)
        np.asarray(toks_w)
        pos = park_idle(pos, jnp.asarray(np.ones((B,), bool)))
        _tr("warmup done")

        while not self._stop.is_set():
            progressed = False
            # 1. admission: dispatch EVERY pending prompt's prefill +
            # cache write + first-token sample asynchronously — no host
            # sync yet.  The syncs happen in step 3, AFTER the decode
            # chunk is dispatched, so admission work overlaps the running
            # group's compute instead of stalling it (the r3 gap: serve
            # ran at 60% of its own decode ceiling because prefills sat
            # on the decode critical path).
            free = np.flatnonzero(remaining == 0)
            fi = 0
            admitted = []  # (slot, meta, emit, first_dev, n)
            while fi < free.size:
                try:
                    prompt, meta, emit = self._pending.get_nowait()
                except _q.Empty:
                    break
                slot = int(free[fi])
                fi += 1
                # Crash-visibility marker: a request mid-admission is in
                # neither _pending nor a slot — without it, a loop
                # failure during ITS prefill would orphan it (client
                # hangs to timeout instead of seeing stream_aborted).
                # A LIST: several admissions can be in flight per
                # iteration now that prefills dispatch asynchronously.
                # Entries removed by IDENTITY (meta dicts may hold
                # arrays, so tuple == is not safe).
                entry = (meta, emit)
                self._admitting.append(entry)
                T = prompt.shape[1]
                if T >= cfg.max_seq:
                    # reject oversize prompts with a terminated stream
                    self._emit_token(emit, {**meta, "stream_aborted": True},
                                     0, 0, True)
                    self._admitting[:] = [
                        e for e in self._admitting if e is not entry]
                    continue
                small = llama.init_cache(cfg, 1, dtype=fw.dtype)
                P = T
                if _gc().shape_bucketing:
                    P = min(_next_bucket(T), cfg.max_seq - 1)
                if P > T:
                    prompt = np.pad(prompt, ((0, 0), (0, P - T)))
                logits, small = fw._fwd(params, jnp.asarray(prompt), small, 0)
                cache = self._write_slot(cache, small, np.int32(slot))
                key, sub = jax.random.split(key)
                first_dev = llama.sample_token(
                    logits[:, T - 1], sub, fw.temperature, fw.top_k,
                    fw.top_p)[0]
                n = max(1, min(fw.max_new, cfg.max_seq - T))
                if n > 1:
                    # provisional occupancy; step 3 retires it if the
                    # materialized first token turns out to be EOS
                    tok = set_slot(tok, np.int32(slot), first_dev)
                    pos = set_slot(pos, np.int32(slot), np.int32(T))
                    remaining[slot] = n - 1
                    sidx[slot] = 1
                    slots[slot] = (meta, emit)
                    # now covered by _live_slots: drop the _admitting
                    # marker so a crash between here and step 3 aborts the
                    # stream ONCE, not via both lists
                    self._admitting[:] = [
                        e for e in self._admitting if e is not entry]
                    entry = None
                admitted.append((slot, meta, emit, first_dev, n, entry))
                _tr(f"admitted slot {slot} (dispatched prefill)")
                progressed = True

            # 2. dispatch one chunk of per-row decode for the live slots
            # (still async).  The chunk length is ALWAYS fw.chunk: a
            # variable tail length would compile a fresh 7B program per
            # distinct value (the remote-compile cost dwarfs the tokens
            # it saves — measured 3x throughput loss).  Streams that
            # finish mid-chunk have their overshoot tokens discarded
            # (rows keep decoding garbage until chunk end; out-of-range
            # cache writes drop, outputs are never emitted).
            live = remaining > 0
            toks_dev = None
            if live.any():
                length = fw.chunk
                toks_dev, tok, cache, key, pos = self._decode_rows(
                    params, tok, cache, key, pos, length=length)
                _tr("chunk dispatched")
                progressed = True

            # 3. materialize + emit the admitted first tokens — the
            # device is already computing the chunk, so this sync rides
            # under it; the late joiner's first token leaves here, one
            # dispatch (not one drained queue) after submit.
            for slot, meta, emit, first_dev, n, entry in admitted:
                _tr(f"first-token sync begins slot {slot}")
                first = int(np.asarray(first_dev))
                _tr(f"first-token synced slot {slot}")
                first_last = n == 1 or first == eos
                self._emit_token(emit, meta, first, 0, first_last)
                if first_last and n > 1:
                    # provisional occupancy rolled back (EOS on token 0);
                    # the in-flight chunk's row decodes garbage that
                    # step 4 skips via remaining==0, and park_idle
                    # re-parks its position at chunk end
                    slots[slot] = None
                    remaining[slot] = 0
                if entry is not None:  # n==1: never entered _live_slots
                    self._admitting[:] = [
                        e for e in self._admitting if e is not entry]

            # 4. deliver the chunk's tokens
            if toks_dev is not None:
                host = np.asarray(toks_dev)  # ONE roundtrip per chunk
                _tr("chunk materialized")
                for j in range(host.shape[1]):
                    for s in np.flatnonzero(live):
                        if remaining[s] == 0:
                            continue  # finished mid-chunk: discard
                        meta, emit = slots[s]
                        tokid = int(host[s, j])
                        last = remaining[s] == 1 or tokid == eos
                        self._emit_token(emit, meta, tokid,
                                         int(sidx[s]), bool(last))
                        sidx[s] += 1
                        remaining[s] -= 1
                        if last:
                            slots[s] = None
                            remaining[s] = 0
                # Re-park EVERY idle row each chunk (the device advanced
                # all rows by `length`; a long-parked row's int32
                # position would otherwise creep toward wraparound,
                # where negative positions turn dropped cache writes
                # into corrupting in-range ones).
                pos = park_idle(pos, jnp.asarray(remaining == 0))

            if not progressed:
                with self._idle_lock:
                    if self._pending.empty() and not (remaining > 0).any():
                        self._idle.set()
                self._wake.wait(0.02)
                self._wake.clear()
