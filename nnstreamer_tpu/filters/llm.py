"""LLM token-streaming framework for tensor_filter.

Reference analog: the llama.cpp sub-plugin
(``ext/nnstreamer/tensor_filter/tensor_filter_llamacpp.cc``, SURVEY §2.4
[UNVERIFIED]): ``tensor_filter framework=llamacpp`` takes a prompt buffer
and streams generated tokens downstream as flexible tensors.  Here the
runtime is JAX, not a wrapped C++ library:

* prefill and per-token decode are TWO jitted XLA programs (same function,
  two sequence lengths — see models/llama.py ``forward_cached``); weights
  and KV cache never leave HBM between tokens;
* multi-chip: ``Pipeline(model_parallel=N)`` hands the filter the
  pipeline's shared ``(data x model)`` mesh and params/KV shard over the
  ``model`` axis per the model's ``param_pspecs`` — XLA places the TP
  all-reduces on ICI (config #5's multi-chip token streaming).
  ``custom=tp:N`` is the deprecated pre-2-D alias: inside a pipeline it
  is promoted to ``model_parallel=N`` at construction; a standalone
  framework still builds a private ``(model=tp, data=1)`` mesh;
* tokens are pushed downstream from a generator in bursts of
  ``stream_chunk`` (default 8): each burst is ONE jitted lax.scan over the
  device (one host roundtrip per burst — over a remote chip this is the
  difference between ~5 and ~100s of tok/s); ``stream_chunk:1`` restores
  strict per-token delivery at per-token roundtrip cost.

Pipeline usage::

    appsrc name=prompt ! tensor_filter framework=llm model=llama_tiny
        custom=max_new:32,temperature:0.0 invoke-dynamic=true !
        tensor_sink name=tokens

Input: one uint8 tensor (UTF-8 prompt bytes) or int32 token ids ``[T]`` /
``[B, T]``.  Output per token: ``[B]`` int32 token ids + uint8 piece bytes
(batch 1 only), as FLEXIBLE tensors.  Tokenization uses the checkpoint's
own SentencePiece vocab when the model file carries one (GGUF
``tokenizer.ggml.*`` -> models/tokenizer.py) and falls back to byte-level
ids otherwise; with a real vocab, generation stops at the model's EOS
token like the reference sub-plugin.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, Iterator, List, Optional, Sequence

import numpy as np

from ..core.config import get_config
from ..core.log import logger, metrics
from ..core.registry import register_filter
from ..core.types import TensorFormat, TensorsSpec
from ..models import llama
from ..models.zoo import build as build_model
from ..utils import elastic
from ..core.meta_keys import (META_ABORT_REASON, META_QUERY_CONN,
                              META_ENQUEUE_NS, META_STREAM_ABORTED,
                              META_STREAM_ID, META_STREAM_INDEX,
                              META_STREAM_LAST)
from ..core.meta_keys import META_TENANT as _META_TENANT
from .base import (Framework, FrameworkError, parse_custom_options,
                   place_swapped_params)

#: buffer-meta keys that must NOT ride a drain snapshot: the queue-stamp
#: map is the source pipeline's tracer plumbing, and the query
#: connection id routes sends on the SOURCE pipeline's server core — a
#: stale cid on the adopting side would deliver the stream's tokens to
#: whatever client holds that id there (the adopting deployment's front
#: door re-associates delivery; callers may re-stamp snapshot["meta"]
#: before adopt_stream).
_SNAPSHOT_META_DROP = (META_ENQUEUE_NS, META_QUERY_CONN)

log = logger(__name__)


def _next_bucket(t: int) -> int:
    """Smallest power-of-two >= t (min 32): bounds distinct prefill
    compilations at log2(max_seq) programs for arbitrary prompt mixes."""
    b = 32
    while b < t:
        b <<= 1
    return b


#: Per-slot PRNG draw tags (docs/SERVING.md §4d).  Every device-side
#: draw folds (absolute token position, tag) into the slot's own key;
#: the tag separates the four draw kinds one position can host — the
#: non-spec sample, the draft proposal, the k accept uniforms, and the
#: residual/bonus resample.
TAG_SAMPLE, TAG_DRAFT, TAG_ACCEPT, TAG_FINAL = 100, 101, 102, 103


def _fold_slot_keys(keys, p, tag):
    """Per-draw derived keys ([B, 2] uint32 slot keys + [B] absolute
    positions -> [B, 2]): fold the position, then the draw tag."""
    import jax

    kk = jax.vmap(jax.random.fold_in)(keys, p)
    return jax.vmap(lambda kd: jax.random.fold_in(kd, tag))(kk)


def spec_rejection_commit(pt, dprobs, props, keys, pos, live):
    """Standard speculative rejection sampling, vectorized per slot.

    ``pt`` [B, k+1, V]: the TARGET's filtered sampling distributions
    over (last committed token + k proposals); ``dprobs`` [B, k, V]:
    the DRAFT distributions each proposal was drawn from; ``props``
    [B, k]: the proposals; ``keys`` [B, 2]: slot base keys; ``pos``
    [B]: absolute positions (the fold anchor); ``live`` [B] bool:
    parked-row mask (parked rows commit nothing).

    Accepts proposal i iff ``u_i * q(x_i) < p(x_i)`` (u ~ U[0,1) from
    the slot key folded at (pos, TAG_ACCEPT)), keeps the longest
    accepted prefix, and resamples the first rejection from the
    normalized residual ``max(p - q, 0)`` — or the bonus distribution
    ``pt[k]`` when all k accept (padding q with a zero row makes that
    fall out of the same gather).  Emitted tokens are distributed
    EXACTLY as sampling the target one token at a time, which is the
    marginal tests/test_sampling.py chi-squares this helper against.

    Returns ``(em, acc)``: ``em`` [B, k+1] the emitted-token rows
    (accepted proposals, then the residual/bonus token at column
    ``acc``); ``acc`` [B] the accept counts.
    """
    import jax
    import jax.numpy as jnp

    k_spec = props.shape[1]
    q_x = jnp.take_along_axis(dprobs, props[:, :, None], axis=2)[:, :, 0]
    pt_x = jnp.take_along_axis(
        pt[:, :k_spec], props[:, :, None], axis=2)[:, :, 0]
    ka = _fold_slot_keys(keys, pos, TAG_ACCEPT)
    u = jax.vmap(lambda kd: jax.random.uniform(kd, (k_spec,)))(ka)
    ok = (u * q_x < pt_x).astype(jnp.int32)
    acc = jnp.sum(jnp.cumprod(ok, axis=1), axis=1)
    acc = jnp.where(live, acc, 0)
    # residual at the first rejected position; padding q with a zero
    # row makes acc == k fall through to the bonus distribution pt[k]
    # automatically.  A residual with (numerically) zero mass can only
    # mean p == q at that position — fall back to pt itself.
    qpad = jnp.concatenate([dprobs, jnp.zeros_like(dprobs[:, :1])], axis=1)
    resid = jnp.maximum(pt - qpad, 0.0)
    r_at = jnp.take_along_axis(resid, acc[:, None, None], axis=1)[:, 0]
    pt_at = jnp.take_along_axis(pt, acc[:, None, None], axis=1)[:, 0]
    rsum = jnp.sum(r_at, axis=-1, keepdims=True)
    r = jnp.where(rsum > 1e-20, r_at, pt_at)
    kf = _fold_slot_keys(keys, pos, TAG_FINAL)
    final = jax.vmap(jax.random.categorical)(
        kf, jnp.log(jnp.maximum(r, 1e-38))).astype(jnp.int32)
    # emitted rows: accepted proposals then the final (residual/bonus)
    # token at column ``acc``
    em = jnp.concatenate([props, jnp.zeros_like(props[:, :1])], axis=1)
    col = jnp.arange(k_spec + 1)[None, :]
    em = jnp.where(col == acc[:, None], final[:, None], em)
    return em, acc


def serving_plan(cfg, *, slots: int, block_size: int = 16,
                 kv_blocks: int = 0, prefill_chunk: int = 32,
                 dtype: str = "bfloat16", draft_cfg=None,
                 spec_k: int = 4, temperature: float = 0.0) -> Dict[str, int]:
    """Static sizing of the paged-KV serving state, WITHOUT building
    anything — one home for the arithmetic :class:`_ContinuousLoop` and
    the deep lint's resource report (analysis/tracecheck.py) must agree
    on, so pricing a 7B pool never materializes 7B params.

    Returns a dict:

    * ``max_blocks`` — block-table width per slot.  Prefill pads prompts
      to ``prefill_chunk`` multiples, so the table must span the largest
      padded prompt (its final chunk's END position), not just
      ``max_seq`` — otherwise that chunk's context length would clamp to
      zero mid-prefill.  The extra entries stay sentinel forever.
      (Prefix sharing keeps this bound: a cache-hit prompt starts its
      suffix prefill at a ``prefill_chunk`` multiple, so the padded END
      position never exceeds the cold-path's.)
    * ``n_blocks`` — pool size.  ``kv_blocks`` 0 = worst case
      (``slots * ceil(max_seq/block_size)``: admission never defers on
      blocks); larger is clamped (a slot can't use more than its table).
    * ``pool_bytes`` — HBM the k+v block pool occupies
      (:func:`~nnstreamer_tpu.models.llama.paged_cache_bytes`).
    * ``draft_pool_bytes`` — the draft model's block pool when
      speculative decoding is configured (``draft_cfg`` non-None): the
      draft shares the allocator, block tables, and ``n_blocks`` with
      the target, so its pool is the same geometry at the draft's
      (L, H_kv, hd) — 0 without a draft.
    * ``decode_bytes_per_ctx_token`` — per-decode-step HBM traffic the
      paged attention kernel reads PER LIVE CONTEXT TOKEN: K + V rows
      across every layer at the model's ``n_kv_heads`` — NOT
      ``n_heads``.  The kernel DMAs each K/V block once per query-head
      GROUP (ops/attention.py), so a GQA config's decode traffic is
      ``n_kv_heads/n_heads`` of the repeated-layout figure; predicted
      step bytes = (sum of live context lengths, block-rounded) x this
      coefficient.  nns-xray's roofline attribution and the deep lint
      consume it — pricing with ``n_heads`` here is exactly the stale
      over-prediction the reconciliation regression pins.
    * ``kv_groups`` — ``n_heads // n_kv_heads``, the per-block DMA
      sharing factor of the grouped kernel (1 = plain MHA, no win).
    * ``prng_state_bytes`` — the sampler's per-slot PRNG key state
      (one uint32[2] counter key per slot) carried device-resident when
      ``temperature > 0``; 0 for greedy loops.  Tiny, but the xray HBM
      ledger reconciles measured-vs-predicted by category, so an
      unpriced resident buffer is a drift seed.
    * ``programs`` — compiled XLA signatures the standing loop ever
      uses.  Without speculation: the ``[slots]``-row paged decode
      chunk, the ``[1, prefill_chunk]`` prefill step, and the slot-token
      setter (3).  With a draft model the decode chunk is REPLACED by
      the propose/verify pair and the draft gets its own prefill step:
      target prefill, draft prefill, draft propose (k draft steps + the
      refresh step as ONE scan), target verify (a ``[slots, k+1]``-wide
      paged step that commits tokens/positions in-program), and the
      slot-token setter (5).  Every shape is static in admission state —
      stream join/leave/complete AND accept/reject ratios change VALUES
      only — which is why this census is CLOSED (the compile-counter
      pins in tests/test_llm_continuous.py and tests/test_spec_decode
      .py).  Sampling (``temperature > 0``) swaps program BODIES (the
      sampler is compiled in, per-slot keys ride as values), never the
      count.
    """
    import math

    from ..models import llama as _llama

    bs = max(1, int(block_size))
    C = max(1, int(prefill_chunk))
    itemsize = 2 if str(dtype) in ("bfloat16", "float16") else 4
    hd = cfg.dim // cfg.n_heads
    pad_max = math.ceil((cfg.max_seq - 1) / C) * C
    # Speculation: the final rounds dispatch the fixed [slots, k+1]-wide
    # verify (and the k-step propose scan) even when fewer tokens remain,
    # so positions reach up to max_seq-1 + k.  The table must SPAN them
    # or forward_paged's stale-table clamp zeroes the whole row's context
    # and the committed tokens go bit-wrong near max_seq.  The extra
    # entries stay sentinel: overrun writes drop, and causal masking
    # keeps every COMMITTED token's logits independent of the dropped
    # tail — bit-identity holds right up to the last token.
    seq_span = cfg.max_seq + (max(1, int(spec_k))
                              if draft_cfg is not None else 0)
    max_blocks = math.ceil(max(seq_span, pad_max) / bs)
    worst = int(slots) * math.ceil(cfg.max_seq / bs)
    n_blocks = min(int(kv_blocks), worst) if kv_blocks else worst
    return {
        "max_blocks": max_blocks,
        "n_blocks": n_blocks,
        "pool_bytes": _llama.paged_cache_bytes(cfg, n_blocks, bs,
                                               dtype=dtype),
        "draft_pool_bytes": (
            _llama.paged_cache_bytes(draft_cfg, n_blocks, bs, dtype=dtype)
            if draft_cfg is not None else 0),
        # K + V, every layer, at the KV-head count — the grouped kernel's
        # per-context-token decode read (ops/attention.py shares each
        # block DMA across the whole query-head group)
        "decode_bytes_per_ctx_token": (
            2 * cfg.n_layers * cfg.n_kv_heads * hd * itemsize),
        "kv_groups": cfg.n_heads // cfg.n_kv_heads,
        "prng_state_bytes": (int(slots) * 2 * 4
                             if float(temperature) > 0.0 else 0),
        "programs": 5 if draft_cfg is not None else 3,
    }


class ByteTokenizer:
    """Byte-level tokenizer: id = byte + n_special.  Deterministic, no vocab
    file.  ids 0..n_special-1 are special (0=pad, 1=bos, 2=eos)."""

    n_special = 3
    bos = 1
    eos = 2

    def encode(self, text_bytes: bytes) -> List[int]:
        return [self.bos] + [b + self.n_special for b in text_bytes]

    def decode_piece(self, token_id: int) -> bytes:
        if token_id < self.n_special:
            return b""
        b = token_id - self.n_special
        return bytes([b]) if b < 256 else b""


@register_filter("llm", aliases=("llamacpp", "llama.cpp"))
class LLMFramework(Framework):
    """Streaming generation.  ``custom=`` options:

    ``max_new:N`` (default 32), ``temperature:F`` (0 = greedy), ``seed:N``,
    ``top_k:N`` / ``top_p:F`` (sampler truncation, compiled into the
    decode program — llama.cpp's sampler-chain analog),
    ``tokenizer:PATH`` (a .gguf whose ``tokenizer.ggml.*`` vocab is used
    for text; defaults to the model file's own vocab when it has one,
    byte-level otherwise),
    ``stream_chunk:N`` (tokens decoded per device roundtrip, default 8;
    1 = strict per-token streaming),
    ``tp:N`` (DEPRECATED alias of ``Pipeline(model_parallel=N)`` —
    promoted to the pipeline knob at construction so the filter runs on
    the shared ``(data x model)`` mesh; kept for standalone frameworks,
    which build a private ``model``-axis mesh),
    ``serve:continuous`` + ``slots:N`` (continuous batching: a standing
    decode loop over a block-paged KV cache that admits queued prompts
    into free slots via chunked prefill — see :class:`_ContinuousLoop`),
    ``block_size:N`` (KV pool block granularity, default 16),
    ``kv_blocks:N`` (pool size in blocks; default 0 = worst-case
    ``slots * ceil(max_seq/block_size)``; smaller pools defer admission
    instead of overflowing),
    ``prefill_chunk:N`` (tokens per chunked-prefill step, default 32) and
    ``prefill_budget:N`` (prefill tokens interleaved per decode
    iteration, default one chunk),
    ``quant:int8`` / ``quant:int4`` (weight-only quantization; int4 is
    nibble-packed and decodes through the Pallas kernel in
    ops/int4_matmul.py on TPU),
    ``dtype:bfloat16|float32``, plus any model-builder options
    (``dim:…``, ``n_layers:…``) forwarded to the zoo.
    """

    name = "llm"
    streaming = True

    def __init__(self):
        super().__init__()
        self.bundle = None
        self.cfg: Optional[llama.LlamaConfig] = None
        self.tokenizer = ByteTokenizer()
        self.max_new = 32
        self.temperature = 0.0
        self.top_k = 0
        self.top_p = 1.0
        self.seed = 0
        self.stop_eos = False
        self.mesh = None
        self._fwd = None
        self.continuous = False
        self.prefix_cache = True
        self.draft_name = ""
        self.draft_bundle = None
        self.draft_cfg = None
        self.spec_k = 4
        self._serve: Optional["_ContinuousLoop"] = None
        self._serve_lock = threading.Lock()

    def open(self, props: Dict[str, object]) -> None:
        super().open(props)
        model = str(props.get("model") or "llama_tiny")
        opts = parse_custom_options(str(props.get("custom", "")))
        self.max_new = int(opts.pop("max_new", 32))
        self.temperature = float(opts.pop("temperature", 0.0))
        self.top_k = int(opts.pop("top_k", 0))
        self.top_p = float(opts.pop("top_p", 1.0))
        self.seed = int(opts.pop("seed", 0))
        tok_path = opts.pop("tokenizer", None)
        stop_opt = opts.pop("stop_eos", None)
        # Tokens decoded per device roundtrip (stream granularity): tokens
        # still stream downstream one-by-one, in bursts of this size.
        self.chunk = max(1, int(opts.pop("stream_chunk", 8)))
        tp = int(opts.pop("tp", 1))
        # serve:continuous — a standing decode loop with ``slots:N`` rows:
        # prompts are admitted into free slots of a RUNNING per-row-
        # position decode (each stream at its own depth), so a late
        # client never waits for earlier streams to finish the way a
        # static group would make it.  Modern "continuous batching"; no
        # reference analog.
        self.continuous = str(opts.pop("serve", "")).lower() == "continuous"
        self.slots = int(opts.pop("slots", 4))
        # Paged-KV serving knobs (see _ContinuousLoop): pool granularity,
        # pool size (0 = worst case: no admission ever defers), chunked-
        # prefill step and the per-iteration prefill token budget.
        self.block_size = max(1, int(opts.pop("block_size", 16)))
        self.kv_blocks = max(0, int(opts.pop("kv_blocks", 0)))
        self.prefill_chunk = max(1, int(opts.pop("prefill_chunk", 32)))
        self.prefill_budget = max(
            1, int(opts.pop("prefill_budget", self.prefill_chunk)))
        # Prefix sharing (docs/SERVING.md §4b): hash token-block chains
        # so a shared system prompt / few-shot preamble prefills ONCE
        # and maps copy-on-write into every stream's block table.
        # Host-only behavior (refcounts, the hash index) — no compiled
        # signature changes, so it is runtime-safe to flip.
        self.prefix_cache = str(opts.pop("prefix_cache", "1")).lower() \
            not in ("0", "false", "no")
        # Speculative decoding (docs/SERVING.md §4c): ``draft:<preset>``
        # builds a small draft model that proposes ``spec_k`` tokens per
        # round; the target verifies them in ONE fixed-shape
        # [slots, k+1]-wide paged step.  Greedy (temperature:0):
        # acceptance is exact prefix match against the target's own
        # argmax, so the emitted stream is bit-identical to plain
        # decode.  Sampled (temperature>0): standard speculative
        # rejection sampling — each proposal is accepted with
        # min(1, p_target/p_draft) and rejections resample from the
        # normalized residual, so every emitted token is distributed
        # EXACTLY as non-speculative sampling (docs/SERVING.md §4d).
        self.draft_name = str(opts.pop("draft", "") or "")
        self.spec_k = max(1, int(opts.pop("spec_k", 4)))
        draft_seed = int(opts.pop("draft_seed", 0))
        # Elastic-serving knobs (docs/SERVING.md "Elastic serving"):
        # admit_timeout bounds how long a prompt may sit at the
        # admission queue's head waiting for capacity before it is
        # rejected with a typed abort (0 = wait forever, the pre-elastic
        # behavior); stream_idle_timeout is the grace between a stream
        # being marked orphaned (its connection died —
        # utils/elastic.cancel_stream) and its slot + KV blocks being
        # reaped back to the free list.
        self.admit_timeout = max(0.0, float(opts.pop("admit_timeout",
                                                     30.0)))
        self.stream_idle_timeout = max(
            0.0, float(opts.pop("stream_idle_timeout", 5.0)))
        # nns-armor (docs/ROBUSTNESS.md): ``nan_guard:1`` checks every
        # admitted prompt's final prefill logits for NaN/Inf — a
        # poisoned request is quarantined (DLQ, when the pipeline
        # configured one) and answered with a typed
        # ``abort_reason=poison`` terminator instead of decoding
        # garbage (or crashing the loop) from corrupt activations.
        # Pays one [1, vocab] host fetch per admitted prompt.
        self.nan_guard = str(opts.pop("nan_guard", "0")).lower() \
            in ("1", "true", "yes")
        self.dtype = opts.get("dtype", "bfloat16")
        try:
            self.bundle = build_model(model, opts)
        except KeyError as e:
            raise FrameworkError(str(e)) from e
        self.cfg = getattr(self.bundle, "config", None)
        if self.cfg is None:
            raise FrameworkError(
                f"model {model!r} has no LlamaConfig; the llm framework needs "
                "a decoder-LM bundle (models/llama.py)"
            )
        self.draft_bundle = None
        self.draft_cfg = None
        if self.draft_name:
            if not self.continuous:
                raise FrameworkError(
                    "draft: (speculative decoding) requires "
                    "serve:continuous — the per-request stream path has "
                    "no standing verify loop")
            # temperature > 0 composes with the draft: verify switches
            # from exact-prefix-match to speculative rejection sampling
            # (distribution-equivalent to the non-spec sampler, see
            # docs/SERVING.md §4d) — no guard needed here.
            if self.draft_name not in llama.PRESETS:
                raise FrameworkError(
                    f"draft model {self.draft_name!r} must be a preset "
                    "zoo name (the deep lint prices the draft's params "
                    "statically; a checkpoint path cannot be)")
            # the draft MUST share the target's token space and position
            # span: vocab/max_seq are overridden onto the draft preset so
            # its proposals are target token ids at target positions
            self.draft_bundle = build_model(self.draft_name, {
                "vocab": str(self.cfg.vocab),
                "max_seq": str(self.cfg.max_seq),
                "seed": str(draft_seed),
                "param_dtype": str(opts.get("param_dtype", "float32")),
            })
            self.draft_cfg = self.draft_bundle.config
        # Tokenizer priority: explicit custom=tokenizer:PATH, then the
        # model file's own embedded vocab, then the byte-level fallback.
        if tok_path is not None:
            from ..models.tokenizer import load_gguf_tokenizer

            tok = load_gguf_tokenizer(str(tok_path))
            if tok is None:
                raise FrameworkError(
                    f"tokenizer file {tok_path!r} carries no "
                    "tokenizer.ggml.tokens vocab")
            self.tokenizer = tok
        elif getattr(self.bundle, "tokenizer", None) is not None:
            self.tokenizer = self.bundle.tokenizer
        n_tok = getattr(self.tokenizer, "n_vocab", 0)
        if n_tok > self.cfg.vocab:
            # XLA CLAMPS out-of-range embedding gathers instead of
            # raising — a vocab bigger than the model would silently
            # generate from wrong embeddings
            raise FrameworkError(
                f"tokenizer vocab ({n_tok}) exceeds model vocab "
                f"({self.cfg.vocab}); wrong tokenizer for this model")
        # EOS terminates generation when a real vocab is in play (the
        # llama.cpp contract); byte-level ids keep fixed-length decode so
        # synthetic-model tests and benches stay deterministic.
        # Override with custom=stop_eos:0/1.
        stop = stop_opt
        if stop is None:
            self.stop_eos = not isinstance(self.tokenizer, ByteTokenizer)
        else:
            self.stop_eos = str(stop).lower() not in ("0", "false", "no")
        self._setup(tp)

    def _setup(self, tp: int) -> None:
        import jax

        from ..parallel.mesh import make_mesh, mesh_axis_size
        from ..parallel.sharding import shard_params

        cfg = self.cfg
        params = self.bundle.params

        mesh = None
        provider = getattr(self, "_mesh_provider", None)
        if provider is not None:
            # Pipeline-owned 2-D mesh (runtime.Pipeline._model_mesh): a
            # configured model_parallel — or the deprecated custom=tp:
            # alias, promoted at Pipeline construction — resolves to ONE
            # shared (data x model) mesh for the whole pipeline; None
            # when the pipeline runs model_parallel=1.
            try:
                mesh = provider()
            except Exception as e:
                from ..pipeline.runtime import PipelineError

                if isinstance(e, PipelineError):
                    # a pipeline-level placement error (over-asked
                    # dp x mp, non-divisible plan): propagate as-is —
                    # wrapping it in FrameworkError would make
                    # _load_framework try other frameworks and report
                    # "no framework could open", burying the real cause
                    raise
                raise FrameworkError(str(e)) from e
            if mesh is not None and mesh_axis_size(mesh, "model") <= 1:
                mesh = None
        if mesh is None and tp > 1:
            # standalone/legacy path (framework embedded outside a
            # pipeline): a private (model=tp, data=1) mesh, kept so
            # direct LLMFramework users keep working
            if len(jax.devices()) < tp:
                raise FrameworkError(
                    f"tp:{tp} needs {tp} devices, have {len(jax.devices())}")
            mesh = make_mesh(model=tp, data=1,
                             devices=jax.devices()[:tp])
        if mesh is not None:
            ways = mesh_axis_size(mesh, "model")
            problems = llama.tp_divisibility_problems(cfg, ways)
            if self.draft_cfg is not None:
                problems += [
                    f"draft {p}" for p in
                    llama.tp_divisibility_problems(self.draft_cfg, ways)]
            if problems:
                # fail with the dims named instead of a GSPMD/device_put
                # reshape error mid-shard (the deep lint reports the same
                # arithmetic statically — model-divisibility)
                raise FrameworkError(
                    f"model geometry does not divide model_parallel="
                    f"{ways}: " + "; ".join(problems))
            self.mesh = mesh
            # the bundle's pspecs match ITS pytree (quantized trees have
            # different leaves than llama.param_pspecs()'s default)
            pspecs = self.bundle.param_pspecs or llama.param_pspecs()
            params = shard_params(mesh, params, pspecs)
            self.bundle.params = params
            if self.draft_bundle is not None:
                # the draft shards over the same mesh — its pspecs match
                # its own (unquantized) pytree
                dspecs = self.draft_bundle.param_pspecs \
                    or llama.param_pspecs()
                self.draft_bundle.params = shard_params(
                    mesh, self.draft_bundle.params, dspecs)
            # pallas_call has no GSPMD partitioning rule: int4 and paged-
            # attention programs traced for this sharded mesh must take
            # their shardable XLA reference paths.  Refcounted disables,
            # taken LAST in the TP block (nothing after them throws) and
            # released in close(), so a failed open can't leak a disabled
            # kernel and two TP filters don't clobber each other.
            from ..ops import attention as _attn
            from ..ops import int4_matmul as _i4

            _i4.disable_kernel()
            _attn.disable_paged_kernel()
            self._int4_disabled = True

        def fwd(params, tokens, cache, pos):
            return llama.forward_cached(params, tokens, cache, pos, cfg,
                                        compute_dtype=self.dtype)

        # Prefill program (only ever called with pos=0).  pos is STATIC so
        # the trace sees a Python int and models/llama.py's prefill branch
        # (flash attention over the prompt, not a masked sweep over all
        # max_seq cache rows) actually compiles in; a traced pos would make
        # `type(pos_offset) is int` False at trace time.  Cache donated so
        # prefill writes in place.
        self._fwd = jax.jit(fwd, static_argnums=(3,), donate_argnums=(2,))

        temperature = self.temperature
        top_k, top_p = self.top_k, self.top_p

        def decode_chunk(params, tok, cache, key, pos0, length):
            """`length` decode steps as ONE program (lax.scan): the host sees
            one roundtrip per chunk, not per token — over a remote/tunneled
            device this is the difference between ~5 and ~100s of tok/s."""
            import jax.numpy as jnp
            from jax import lax

            def step(carry, i):
                tok, cache, key = carry
                key, sub = jax.random.split(key)
                logits, cache = llama.forward_cached(
                    params, tok[:, None], cache, pos0 + i, cfg,
                    compute_dtype=self.dtype)
                nxt = llama.sample_token(logits[:, -1], sub, temperature,
                                         top_k, top_p)
                return (nxt, cache, key), nxt

            (tok, cache, key), toks = lax.scan(
                step, (tok, cache, key), jnp.arange(length))
            return jnp.moveaxis(toks, 0, 1), tok, cache, key  # [B, length]

        self._decode_chunk = jax.jit(
            decode_chunk, static_argnames=("length",), donate_argnums=(2,))
        self._wrap_stream_xray()

    def attach_xray(self, registry, stage, rec=None):
        super().attach_xray(registry, stage, rec)
        self._wrap_stream_xray()

    def _wrap_stream_xray(self) -> None:
        """nns-xray: the per-request stream path's programs are recorded
        UNBOUNDED (no expectation) — prompt-length bucketing bounds them
        in practice, but the deep lint calls invoke-dynamic stages
        recompile-unbounded and the live census mirrors that verdict.
        The serve loop's closed 3-program census registers separately
        (_ContinuousLoop)."""
        xr = getattr(self, "_xray", None)
        if xr is None:
            return
        stage = getattr(self, "_xray_stage", "llm")
        rec = getattr(self, "_xray_rec", None)
        if getattr(self, "_fwd", None) is not None:
            self._fwd = xr.track(self._fwd, stage, "llm.prefill", rec=rec)
        if getattr(self, "_decode_chunk", None) is not None:
            self._decode_chunk = xr.track(self._decode_chunk, stage,
                                          "llm.decode", rec=rec)

    def close(self) -> None:
        if self._serve is not None:
            self._serve.shutdown()
            self._serve = None
        if getattr(self, "_int4_disabled", False):
            from ..ops import attention as _attn
            from ..ops import int4_matmul as _i4

            _i4.enable_kernel()
            _attn.enable_paged_kernel()
            self._int4_disabled = False
        self.bundle = None
        self.draft_bundle = None
        self._fwd = None
        self._decode_chunk = None

    # -- continuous serving ------------------------------------------------
    def submit(self, inputs: Sequence, meta: Dict, emit) -> int:
        """Queue one prompt into the standing decode loop
        (``custom=serve:continuous``).  ``emit(tensors, meta)`` is called
        from the serve thread once per generated token, carrying the
        request's meta plus stream_index/stream_last.  Returns the
        minted stream id (also stamped into every emitted token's meta
        — the :meth:`drain_stream`/utils.elastic handle)."""
        # Lock the lazy creation: two first-submits racing from different
        # threads must not spawn two serve loops (duplicate slot caches,
        # split streams) — the framework API stays safe outside the
        # single-runner pipeline assumption.
        if self._serve is None:
            with self._serve_lock:
                if self._serve is None:
                    self._serve = _ContinuousLoop(self)
        return self._serve.submit(self._to_tokens(inputs[0]), meta, emit)

    def drain(self, timeout: float = 600.0) -> bool:
        """Block until every admitted stream has finished (EOS path)."""
        return self._serve is None or self._serve.drain(timeout)

    # -- elastic serving: drain/adopt (docs/SERVING.md "Elastic serving")
    def serve_streams(self) -> Dict[int, Dict]:
        """Live/queued continuous-serving streams of THIS framework:
        ``stream_id -> {"state", "tenant", "slot", "blocks"}``."""
        if self._serve is None:
            return {}
        return self._serve.stream_table()

    def drain_stream(self, stream_id: int, timeout: float = 30.0) -> Dict:
        """Serialize one live (or still-queued) stream OFF the standing
        loop: its paged KV blocks, slot state, and request meta become a
        host-value snapshot (trainer/checkpoint.py's serialization
        substrate), and its slot + blocks return to the free list.
        Greedy continuation after :meth:`adopt_stream` is bit-identical
        to an undrained run; sampled (temperature > 0) streams carry
        their per-slot PRNG key in the snapshot (``prng_key``), so a
        same-seed continuation is ALSO bit-identical — the key is a
        pure function of (framework seed, admission number) and every
        draw folds in the absolute token position, never the slot or
        wall-clock step (docs/SERVING.md §4d)."""
        if self._serve is None:
            raise FrameworkError("no continuous serve loop is running")
        return self._serve.drain_stream(int(stream_id), timeout)

    def snapshot_problems(self, snapshot: Dict) -> List[str]:
        """Compatibility problems adopting ``snapshot`` here (empty =
        adoptable).  The drain/adopt contract: same model geometry,
        compute dtype, and block size — everything else (slots,
        kv_blocks, prefill knobs) may differ between the pipelines."""
        import dataclasses as _dc

        problems: List[str] = []
        if not isinstance(snapshot, dict):
            return ["snapshot must be a dict (drain_stream's return)"]
        if snapshot.get("version") not in (1, 2):
            problems.append(
                f"snapshot version {snapshot.get('version')!r} "
                "unsupported (expected 1 or 2)")
            return problems
        if self.draft_name and snapshot.get("kind") == "live" \
                and "tok_prev" not in snapshot:
            # the speculative refresh step re-feeds the second-to-last
            # committed token; a pre-speculation (v1) snapshot does not
            # carry it — still adoptable by any non-speculating loop
            problems.append(
                "snapshot predates speculative decoding (no tok_prev); "
                "adopt it on a loop without draft:, or re-drain from a "
                "current pipeline")
        if snapshot.get("cfg") != _dc.asdict(self.cfg):
            problems.append("model geometry differs from the snapshot's")
        if snapshot.get("kind") == "live":
            if str(snapshot.get("dtype")) != str(self.dtype):
                problems.append(
                    f"compute dtype {snapshot.get('dtype')!r} != "
                    f"{self.dtype!r} (KV block contents are dtype-exact)")
            if int(snapshot.get("block_size", -1)) != self.block_size:
                problems.append(
                    f"block_size {snapshot.get('block_size')!r} != "
                    f"{self.block_size} (block contents do not re-chunk)")
        return problems

    def adopt_stream(self, snapshot: Dict, emit,
                     timeout: float = 30.0) -> int:
        """Re-admit a drained stream into THIS framework's standing loop
        (creating it on first use, exactly like :meth:`submit`): its KV
        blocks are copied back into the pool, its slot state restored,
        and decode continues — ``emit(tensors, meta)`` receives the
        remaining tokens with ``stream_index`` continuing where the
        drained pipeline stopped.  Returns the stream id (stable across
        the handover unless it collides with a live local id)."""
        problems = self.snapshot_problems(snapshot)
        if problems:
            raise FrameworkError(
                "cannot adopt stream snapshot: " + "; ".join(problems))
        if self._serve is None:
            with self._serve_lock:
                if self._serve is None:
                    self._serve = _ContinuousLoop(self)
        return self._serve.adopt_stream(snapshot, emit, timeout)

    def swap_params(self, tree) -> Optional[int]:
        """Hot-swap the live weights (nns-learn train-while-serve).  With
        a standing serve loop the swap executes as a control command AT
        A CHUNK BOUNDARY — the drain/adopt discipline: every slot's host
        bookkeeping is consistent, the three compiled loop programs take
        params as arguments, and aval-identical leaves mean the census
        stays closed (zero recompiles, pinned by test) — and returns the
        loop's new param version.  Without a loop the stream path reads
        ``bundle.params`` per request, so the next request serves the
        new weights (returns None)."""
        if self.bundle is None:
            raise FrameworkError("framework is not open")
        if self._serve is not None:
            return self._serve.swap_params(tree)
        self.bundle.params = place_swapped_params(self.bundle.params, tree)
        return None

    def get_model_info(self):
        flex_in = TensorsSpec.from_string("1", "uint8").replace(
            format=TensorFormat.FLEXIBLE)
        flex_out = TensorsSpec.from_string("1", "int32").replace(
            format=TensorFormat.FLEXIBLE)
        return flex_in, flex_out

    def param_bytes(self) -> int:
        """Live parameter bytes (quantized trees included — nibble-packed
        int4 leaves report their packed nbytes).  Feeds the deep pass
        AND nns-xray's measured HBM ledger — without it an llm
        pipeline's ledger read 0 params against a priced estimate, which
        is exactly the under-prediction drift the reconciler warns on."""
        bundle = getattr(self, "bundle", None)
        if bundle is None or bundle.params is None:
            return 0
        from .base import tree_param_bytes

        total = tree_param_bytes(bundle.params)
        draft = getattr(self, "draft_bundle", None)
        if draft is not None and draft.params is not None:
            # the speculative-decoding draft lives in HBM beside the
            # target for the stage lifetime — the deep lint prices it
            # (draft params in the resource report), so the measured
            # side must include it or the ledger ratio drifts
            total += tree_param_bytes(draft.params)
        return total

    # -- tokenization ------------------------------------------------------
    def _to_tokens(self, arr: np.ndarray) -> np.ndarray:
        arr = np.asarray(arr)
        if arr.dtype == np.uint8:
            ids = self.tokenizer.encode(arr.tobytes())
            return np.asarray([ids], np.int32)
        toks = arr.astype(np.int32)
        if toks.ndim == 1:
            toks = toks[None, :]
        if toks.ndim != 2:
            raise FrameworkError(f"prompt must be [T] or [B,T], got {arr.shape}")
        return toks

    # -- generation --------------------------------------------------------
    def _gen_tokens(self, prompt: np.ndarray) -> Iterator[np.ndarray]:
        import jax
        import jax.numpy as jnp

        cfg = self.cfg
        B, T = prompt.shape
        if T >= cfg.max_seq:
            raise FrameworkError(
                f"prompt length {T} >= max_seq {cfg.max_seq}")
        cache = llama.init_cache(cfg, B, dtype=self.dtype)
        if self.mesh is not None:
            from ..parallel.sharding import shard_params as _sp
            cache = _sp(self.mesh, cache, llama.cache_pspecs())
        params = self.bundle.params
        # Prompt-length bucketing (SURVEY §7 "dynamic shapes vs XLA static
        # shapes"): the prefill program compiles per SHAPE, so serving
        # mixed-length prompts would compile per length.  Right-pad to the
        # next bucket: causal attention keeps real tokens from seeing pad
        # rows, decode overwrites cache row `pos` before any later
        # position can attend it, and the sampled logit is read at the
        # REAL last position — numerics are untouched (asserted by test).
        P = T
        if get_config().shape_bucketing:
            P = min(_next_bucket(T), cfg.max_seq - 1)
        if P > T:
            prompt = np.pad(prompt, ((0, 0), (0, P - T)))
        logits, cache = self._fwd(params, jnp.asarray(prompt), cache, 0)
        key = jax.random.PRNGKey(self.seed)
        # At least one token is always safe: prefill wrote cache[0:P]
        # (real rows 0:T; rows T..P-1 hold pad-token K/V that stay hidden
        # behind the decode mask until sequentially overwritten) and the
        # first sample needs no further cache write.  Subsequent decode
        # steps feed at positions T..T+n-2, each of which must stay
        # < max_seq.
        n = max(1, min(self.max_new, cfg.max_seq - T))
        # EOS termination (batch-1 streams; batched rows finish at their
        # own depths, so callers slice on ids themselves)
        eos = getattr(self.tokenizer, "eos", -1) if self.stop_eos else -1
        tok = llama.sample_token(logits[:, T - 1], key, self.temperature,
                                 self.top_k, self.top_p)
        first = np.asarray(tok)
        yield first
        if B == 1 and int(first[0]) == eos:
            return
        done = 1
        pos = T
        while done < n:
            # Chunked decode; a shorter tail chunk costs one extra compile
            # (two cached programs total: full chunk + tail).  n's clamp
            # already guarantees every decode position stays < max_seq.
            length = min(self.chunk, n - done)
            toks, tok, cache, key = self._decode_chunk(
                params, tok, cache, key, pos, length=length)
            host = np.asarray(toks)  # ONE roundtrip per chunk
            for j in range(length):
                yield host[:, j]
                if B == 1 and int(host[0, j]) == eos:
                    return
            done += length
            pos += length

    def invoke_stream(self, inputs: Sequence) -> Iterator[List[np.ndarray]]:
        """Yield one output list per generated token: [ids [B] int32,
        piece bytes uint8] — flexible tensors, the reference's streaming
        contract.  Batched prompts ([B, T], B>1 — e.g. stacked by a
        ``tensor_query_serversrc max-batch=N``) yield [ids [B]] only: a
        per-row variable-length piece tensor is not batch-leading, so
        byte decoding is the consumer's job (ids are the contract; the
        query serversink row-splits ids back to each client)."""
        prompt = self._to_tokens(inputs[0])
        for ids in self._gen_tokens(prompt):
            metrics.count("llm.tokens", ids.shape[0])
            if ids.shape[0] != 1:
                yield [ids]
                continue
            piece = np.frombuffer(
                self.tokenizer.decode_piece(int(ids[0])), np.uint8)
            yield [ids, piece.copy()]

    def invoke(self, inputs: Sequence) -> List[np.ndarray]:
        """Non-streaming: all generated ids as one [B, N] tensor + the
        decoded bytes (batch-1 only; batched yields carry ids alone)."""
        chunks = [outs[0] for outs in self.invoke_stream(inputs)]
        ids = np.stack(chunks, axis=1)
        text = b"".join(self.tokenizer.decode_piece(int(t)) for t in ids[0])
        return [ids, np.frombuffer(text, np.uint8).copy()]


class _ContinuousLoop:
    """Standing decode loop for ``custom=serve:continuous`` over a
    block-paged KV cache.

    **The pool.**  One thread owns a fixed block pool
    ``[L, n_blocks, block_size, H_kv, hd]`` (models/llama.py
    ``init_paged_cache``), a host-side free list of block ids, and a per-
    slot block table ``[slots, max_blocks]`` whose entries map a stream's
    logical block j to a pool block (``n_blocks`` = unallocated
    sentinel).  The paged decode step (``forward_paged`` →
    ops/attention.py ``paged_attention``) gathers ONLY each stream's live
    blocks, so per-step HBM traffic scales with the *sum of live sequence
    lengths* instead of ``slots × max_seq`` — a short stream stops paying
    cache bandwidth for the longest one, which is what lets full-
    occupancy throughput keep scaling past 8 streams.

    **Admission = reservation.**  A prompt is admitted when a slot AND
    ``ceil((T + max_new) / block_size)`` free blocks exist — the blocks a
    stream could ever write are reserved up front, so a LIVE stream can
    never stall mid-decode on an empty free list (no allocation
    deadlock; an undersized ``kv_blocks`` pool defers *admission*
    instead).  Reservation holds capacity, not bandwidth: the attention
    kernel still reads only ``ceil(len/block_size)`` blocks per row.
    Tables change only at admit/retire, on the host.

    **Chunked prefill.**  An admitted prompt pads to a multiple of
    ``prefill_chunk`` (waste < one chunk — vs the old power-of-two
    bucketing's up-to-2x; counted in ``llm.serve.prefill_pad_waste``)
    and prefills CHUNK BY CHUNK straight into its reserved blocks,
    interleaved between decode chunks under ``prefill_budget`` tokens
    per iteration — a long prompt no longer parks the whole loop behind
    one monolithic batch-1 prefill + cache-copy, which is what a late
    joiner's first-token latency was made of.

    **Prefix sharing (copy-on-write).**  With ``prefix_cache`` on
    (default), every full prompt block's token CHAIN hash indexes its
    pool block after prefill.  A new prompt walks the index: matched
    leading blocks map into its table with a reference count bump
    instead of a reservation — the shared system prompt / few-shot
    preamble that a million streams repeat is prefilled ONCE, and a
    cache-hit prompt's admission cost collapses to ~the non-shared
    suffix.  Blocks free only at refcount 0; cached blocks at refcount
    0 REST IN THE FREE LIST (content + index intact), so the cache
    never costs admission capacity and eviction is simply allocation.
    A matched block the suffix prefill would partially rewrite is
    copy-on-write FORKED first (``llm.serve.cow_forks``).  All host
    values — no compiled signature changes.

    **Speculative decoding.**  With ``draft:<preset>`` a small draft
    model proposes ``spec_k`` tokens per round (one scan; its paged
    pool shares this allocator's tables block-for-block) and the
    target verifies them in ONE fixed-shape ``[slots, spec_k+1]``-wide
    paged step — a k-wide prefill chunk that ALSO accepts and commits
    in-program: greedy loops take the longest proposal prefix matching
    the target's own argmax plus the target's bonus token (bit-
    identical to plain greedy decode at every accept rate); sampled
    loops run speculative rejection sampling (distribution-equivalent
    to the non-spec sampler).  1..k+1 tokens per TARGET dispatch; the
    host reads back only the accept count + emitted rows and the
    census grows to exactly 5 programs (serving_plan).

    **Fixed decode signature.**  Every program — the per-chunk paged
    decode ``[slots]``-row scan (or the propose/verify pair), the
    ``[1, prefill_chunk]`` prefill steps — takes (pool, tables,
    positions) with shapes static in every admission-state dimension;
    stream join/leave/complete, cache hits, CoW forks, and accept/
    reject ratios change VALUES only.  Warm once, recompile never
    (pinned by the compile-counter tests in tests/test_llm_continuous
    .py and tests/test_spec_decode.py and priced by the deep lint's
    resource report).  Idle slots decode garbage parked at position
    ``max_blocks * block_size`` — their table lookups resolve to the
    sentinel, writes drop, context length is 0, and the paged kernel
    issues ZERO block DMAs for them: an idle slot costs FLOPs, not HBM
    bandwidth.
    """

    def __init__(self, fw: LLMFramework):
        import queue as _q
        import threading

        import jax
        import jax.numpy as jnp
        from jax import lax

        self.fw = fw
        cfg, temperature = fw.cfg, fw.temperature
        bs = fw.block_size
        # Pool/table sizing shared with the deep lint (serving_plan's
        # docstring carries the rationale): table spans the largest
        # chunk-padded prompt, pool defaults to the worst case.
        plan = serving_plan(cfg, slots=fw.slots, block_size=bs,
                            kv_blocks=fw.kv_blocks,
                            prefill_chunk=fw.prefill_chunk, dtype=fw.dtype,
                            draft_cfg=fw.draft_cfg, spec_k=fw.spec_k,
                            temperature=temperature)
        self.max_blocks = plan["max_blocks"]
        self.n_blocks = plan["n_blocks"]
        self.sentinel = self.n_blocks  # unallocated table entry
        self.park = self.max_blocks * bs  # idle-slot position
        self._pending: "_q.Queue" = _q.Queue()
        self._wake = threading.Event()
        self._stop = threading.Event()
        self._idle = threading.Event()
        self._idle.set()
        # Guards the idle decision: without it, submit() could clear
        # _idle and THEN enqueue while the serve loop, between those two
        # steps, observes an empty queue and sets _idle — drain() would
        # return with a live request pending and EOS would cut it off.
        self._idle_lock = threading.Lock()
        self._error: Optional[BaseException] = None
        #: admission-order queue (drained from _pending; entries are
        #: ``(prompt, meta, emit, t_enqueued)``) + per-slot prefill-in-
        #: progress states; BOTH crash-visible: a request in either is
        #: in neither _pending nor a live slot, and a loop failure must
        #: abort it instead of stranding its client
        self._waiting: list = []
        self._admitting: list = []
        # -- elastic serving state (docs/SERVING.md "Elastic serving") --
        #: control commands (drain/adopt) from app threads, processed at
        #: chunk boundaries; each is a dict with an Event the caller
        #: waits on.  deque append/popleft are GIL-atomic.
        import collections as _collections

        self._ctl: "_collections.deque" = _collections.deque()
        #: stream_id -> (reason, reap_deadline): marked dead by
        #: utils/elastic.cancel_stream (the serversink's dead-connection
        #: backchannel); the slot + blocks are reaped at the first chunk
        #: boundary past the deadline (stream_idle_timeout grace, so a
        #: drain/handover can still pick the stream up)
        self._cancelled: Dict[int, tuple] = {}
        #: per-tenant cap on total reserved KV blocks (None = uncapped);
        #: a host-value quota the autoscaler raises/lowers at runtime —
        #: admission SKIPS (not blocks) over-quota tenants so one capped
        #: tenant never head-of-line-blocks the rest
        self._tenant_quota: Dict[str, Optional[int]] = {}
        #: stream ids this loop registered with utils/elastic (cleaned
        #: up on retire/abort/shutdown so the process-wide registry
        #: never leaks entries)
        self._owned_sids: set = set()
        #: per-swap version counter (nns-learn train-while-serve): bumps
        #: once per executed hot-swap, published as llm.serve.param_version
        self.param_version = 0

        # -- per-slot PRNG (docs/SERVING.md §4d) ------------------------
        # Slot keys ride slot state the way tok_prev does: every draw
        # folds (absolute token position, draw tag) into the slot's own
        # key, so a stream's sampled tokens are a pure function of
        # (framework seed, admission number, position) — independent of
        # batch composition, accept history, and wall-clock step.  Churn
        # changes key VALUES only; the compiled programs never see a new
        # signature, and drain/adopt carries the key in the snapshot.
        self._sampled = temperature > 0.0
        slot_keys = _fold_slot_keys  # module level so tests drive it raw

        def decode_chunk(params, tok, pool, tables, pos, keys, length):
            """``length`` paged decode steps as ONE program (lax.scan):
            every slot advances at its own depth through its own blocks.
            ``pos`` arrives fresh from host bookkeeping each call, so a
            parked row can never creep toward int32 wraparound.  ONE
            signature for greedy and sampled loops: at temperature 0
            the per-slot key folds are dead code XLA drops."""
            def step(carry, _):
                tok, pool, p = carry
                logits, pool = llama.forward_paged(
                    params, tok[:, None], pool, tables, p, cfg,
                    compute_dtype=fw.dtype)
                kstep = slot_keys(keys, p + 1, TAG_SAMPLE)
                nxt = llama.sample_token_per_slot(
                    logits[:, -1], kstep, temperature, fw.top_k, fw.top_p)
                return (nxt, pool, p + 1), nxt

            (tok, pool, _), toks = lax.scan(
                step, (tok, pool, pos), None, length=length)
            return jnp.moveaxis(toks, 0, 1), tok, pool

        self._decode = jax.jit(
            decode_chunk, static_argnames=("length",), donate_argnums=(2,))

        def prefill_step(params, toks, pool, table, pos0, logit_off):
            """One [1, prefill_chunk] prefill chunk written directly into
            the slot's blocks; returns the ``logit_off`` position's
            logits ([1, vocab] — the last REAL token on the final chunk)
            so the first-token sample needs no separate program."""
            logits, pool = llama.forward_paged(
                params, toks, pool, table, pos0, cfg,
                compute_dtype=fw.dtype, logit_off=logit_off)
            return logits[:, 0], pool

        self._prefill = jax.jit(prefill_step, donate_argnums=(2,))
        # tok updates keep the token vector device-resident (slot index
        # and value traced: ONE program for every admission)
        self._set_tok = jax.jit(lambda a, i, v: a.at[i].set(v),
                                donate_argnums=(0,))
        # -- speculative decoding (custom=draft:<preset>,spec_k:K) ------
        # The draft model shares the allocator, block tables, sentinel,
        # and n_blocks with the target: block id j holds target K/V in
        # the target pool and draft K/V in the draft pool, so a prefix-
        # cache hit shares BOTH models' cache rows and a CoW fork copies
        # both.  Three extra programs, all static-shaped — accept/reject
        # ratios are host VALUES: the census stays closed at 5.
        self._spec = fw.draft_bundle is not None
        if self._spec:
            dcfg = fw.draft_cfg
            k_spec = fw.spec_k
            park_bound = self.max_blocks * bs  # static python int

            def draft_prefill_step(dparams, toks, dpool, table, pos0):
                """The draft's twin of the target prefill chunk: writes
                the chunk's draft K/V into the SAME reserved blocks of
                the draft pool (logits discarded — ``logit_off=0``
                keeps the draft lm_head at one row)."""
                _, dpool = llama.forward_paged(
                    dparams, toks, dpool, table, pos0, dcfg,
                    compute_dtype=fw.dtype, logit_off=0)
                return dpool

            self._draft_prefill = jax.jit(draft_prefill_step,
                                          donate_argnums=(2,))

            def propose(dparams, tok_prev, tok, dpool, tables, pos, keys):
                """One speculative round's draft side: re-feed the
                PREVIOUS token at ``pos - 1`` (the refresh step — after
                a fully-accepted round the draft pool has a hole at the
                last committed position; recomputing it from identical
                context is bit-exact and keeps the pool hole-free), then
                ``k`` draft steps from ``tok``.  Greedy loops take the
                draft's argmax; sampled loops draw each proposal from
                the FILTERED draft distribution with the slot key folded
                at the proposal's absolute position, and return those
                distributions [B, k, vocab] so verify can run rejection
                sampling.  Parked rows stay parked: the refresh position
                is clamped to the park value so their table lookups
                still resolve to the sentinel and the paged kernel
                issues zero DMAs."""
                rpos = jnp.where(pos >= park_bound, pos, pos - 1)
                _, dpool = llama.forward_paged(
                    dparams, tok_prev[:, None], dpool, tables, rpos,
                    dcfg, compute_dtype=fw.dtype)

                def step(carry, _):
                    t, dpool, p = carry
                    logits, dpool = llama.forward_paged(
                        dparams, t[:, None], dpool, tables, p, dcfg,
                        compute_dtype=fw.dtype)
                    if temperature > 0.0:
                        filt = llama.filter_logits(
                            logits[:, -1], temperature, fw.top_k, fw.top_p)
                        probs = jax.nn.softmax(filt, axis=-1)
                        kstep = slot_keys(keys, p + 1, TAG_DRAFT)
                        nxt = jax.vmap(jax.random.categorical)(
                            kstep, filt).astype(jnp.int32)
                    else:
                        probs = jnp.zeros(
                            (logits.shape[0], 1), jnp.float32)  # unused
                        nxt = jnp.argmax(logits[:, -1],
                                         axis=-1).astype(jnp.int32)
                    return (nxt, dpool, p + 1), (nxt, probs)

                (_, dpool, _), (props, dprobs) = lax.scan(
                    step, (tok, dpool, pos), None, length=k_spec)
                return (jnp.moveaxis(props, 0, 1),
                        jnp.moveaxis(dprobs, 0, 1), dpool)

            self._propose = jax.jit(propose, donate_argnums=(3,))

            def verify(params, tok, tok_prev, props, dprobs, pool,
                       tables, pos, keys):
                """One speculative round's target side, FUSED: ONE
                fixed-shape ``[B, k+1]``-wide paged step over (last
                committed token + the k proposals), then accept/commit
                IN-PROGRAM — greedy loops take the longest proposal
                prefix matching the target's own argmax; sampled loops
                run standard speculative rejection sampling (accept
                x_i with min(1, p/q); resample rejections from the
                normalized residual max(p-q, 0)), which emits tokens
                distributed EXACTLY as the non-spec sampler.  The new
                tok/tok_prev/positions are computed here as device
                values, so the host reads back only the per-slot accept
                count + the emitted-token rows — no per-round
                accept-mask round-trip, no tok re-upload.  Parked rows
                pass through untouched."""
                toks = jnp.concatenate([tok[:, None], props], axis=1)
                logits, pool = llama.forward_paged(
                    params, toks, pool, tables, pos, cfg,
                    compute_dtype=fw.dtype)
                live = pos < park_bound
                if temperature > 0.0:
                    filt = llama.filter_logits(
                        logits, temperature, fw.top_k, fw.top_p)
                    pt = jax.nn.softmax(filt, axis=-1)  # [B, k+1, V]
                    em, acc = spec_rejection_commit(
                        pt, dprobs, props, keys, pos, live)
                else:
                    g = jnp.argmax(logits, axis=-1).astype(jnp.int32)
                    ok = (props == g[:, :k_spec]).astype(jnp.int32)
                    acc = jnp.sum(jnp.cumprod(ok, axis=1), axis=1)
                    acc = jnp.where(live, acc, 0)
                    # g[j] == props[j] for j < acc, and g[acc] is the
                    # bonus/correction token: g IS the emitted row
                    em = g
                # the last emitted token: em[acc] (the final/residual
                # draw in sampled loops, the target argmax in greedy)
                new_tok = jnp.take_along_axis(
                    em, acc[:, None], axis=1)[:, 0]
                prev_cand = jnp.take_along_axis(
                    em, jnp.maximum(acc - 1, 0)[:, None], axis=1)[:, 0]
                new_prev = jnp.where(acc > 0, prev_cand, tok)
                tok2 = jnp.where(live, new_tok, tok)
                prev2 = jnp.where(live, new_prev, tok_prev)
                pos2 = jnp.where(live, pos + acc + 1, pos)
                return em, acc, tok2, prev2, pos2, pool

            self._verify = jax.jit(verify, donate_argnums=(5,))
        xr = getattr(fw, "_xray", None)
        if xr is not None:
            # nns-xray: the standing loop's predicted census IS
            # serving_plan()'s fixed program set (plan["programs"] == 3:
            # decode chunk, prefill step, slot-token setter — the same
            # arithmetic the deep lint prices serve:continuous with), so
            # each program expects exactly ONE compile; anything more —
            # e.g. a numpy-scalar _set_tok argument minting a 4th
            # signature — fires census-drift with the signature diff.
            # Keyed by the owning ELEMENT's stage name (the attach_xray
            # handoff) + ".serve", so two serve loops in one process
            # never collide on one budget.
            stage = f"{getattr(fw, '_xray_stage', None) or 'llm'}.serve"
            rec = lambda: getattr(fw, "_trace_rec", None)  # noqa: E731
            # TP: the paged decode executes across the mesh's model
            # axis — MFU/roofline divide by the participating chips
            devs = 1
            if fw.mesh is not None:
                from ..parallel.mesh import mesh_axis_size

                devs = max(1, mesh_axis_size(fw.mesh, "model"))
            xr.expect(stage, "prefill", budget=1,
                      note="serving_plan fixed prefill signature")
            xr.expect(stage, "set_tok", budget=1,
                      note="serving_plan slot-token setter")
            self._prefill = xr.track(self._prefill, stage, "prefill",
                                     rec=rec, devices=devs)
            self._set_tok = xr.track(self._set_tok, stage, "set_tok",
                                     rec=rec)
            if self._spec:
                # speculation swaps the decode chunk for the draft
                # propose + target verify pair and adds the draft's
                # prefill twin — serving_plan()["programs"] == 5, each
                # expecting exactly one compile
                xr.expect(stage, "draft_prefill", budget=1,
                          note="serving_plan draft prefill twin")
                xr.expect(stage, "propose", budget=1,
                          note="serving_plan draft propose scan")
                xr.expect(stage, "verify", budget=1,
                          note="serving_plan k+1-wide verify step")
                self._draft_prefill = xr.track(
                    self._draft_prefill, stage, "draft_prefill", rec=rec,
                    devices=devs)
                self._propose = xr.track(self._propose, stage, "propose",
                                         rec=rec, devices=devs)
                self._verify = xr.track(self._verify, stage, "verify",
                                        rec=rec, devices=devs)
            else:
                xr.expect(stage, "decode", budget=1,
                          note="serving_plan fixed decode signature")
                self._decode = xr.track(self._decode, stage, "decode",
                                        rec=rec, devices=devs)
        self._thread = threading.Thread(
            target=self._run, name="llm-serve", daemon=True)
        self._thread.start()

    # -- producer side -----------------------------------------------------
    def submit(self, prompt, meta: Dict, emit) -> int:
        # Every stream gets a process-unique id minted HERE (server-
        # authoritative: a client-supplied meta value is overwritten) and
        # registered with utils/elastic so downstream failure detectors
        # (the query serversink's dead-connection path) can cancel it by
        # value.  The id rides every emitted token's meta.
        import functools as _ft

        meta = dict(meta)
        sid = elastic.next_stream_id()
        meta[elastic.META_STREAM_ID] = sid
        # The error check lives INSIDE the lock: the crash handler drains
        # _pending and sets _idle under the same lock, so a submit cannot
        # slip a request into a dead loop's queue between its own error
        # check and its put (that request would never be dequeued or
        # aborted — a hung client).
        with self._idle_lock:
            if self._error is not None:
                raise FrameworkError(
                    f"continuous serve loop died: {self._error!r}")
            self._idle.clear()
            self._owned_sids.add(sid)
            elastic.register_stream(
                sid, _ft.partial(self._mark_cancel, sid))
            self._pending.put((prompt, meta, emit, time.monotonic()))
        self._wake.set()
        return sid

    def drain(self, timeout: float) -> bool:
        return self._idle.wait(timeout)

    def shutdown(self) -> None:
        self._stop.set()
        self._wake.set()
        self._thread.join(timeout=30)
        # control callers blocked on a drain/adopt that raced the stop
        # get a prompt named error instead of riding out their timeout
        while self._ctl:
            cmd = self._ctl.popleft()
            cmd["error"] = "serve loop stopped"
            cmd["ev"].set()
        # the process-wide stream registry must not keep pointing at a
        # dead loop (stale cancel callbacks); owned ids are whatever
        # retire/abort did not already clean up
        for sid in list(self._owned_sids):
            elastic.unregister_stream(sid)
        self._owned_sids.clear()

    # -- elastic control surface -------------------------------------------
    def _mark_cancel(self, sid: int, reason: str = "cancelled",
                     force: bool = False) -> None:
        """The utils/elastic backchannel: mark one stream dead.  Reaped
        at the first chunk boundary past the ``stream_idle_timeout``
        grace (``force=True`` skips the grace).  Idempotent: an earlier
        (sooner) deadline is never extended."""
        grace = 0.0 if force else self.fw.stream_idle_timeout
        deadline = time.monotonic() + grace
        prev = self._cancelled.get(sid)
        if prev is None or deadline < prev[1]:
            self._cancelled[sid] = (reason, deadline)
            metrics.count("llm.serve.cancelled")
        self._wake.set()

    def set_tenant_quota(self, tenant: str,
                         max_blocks: Optional[int]) -> None:
        """Cap (or uncap, with None) a tenant's total reserved KV
        blocks.  A host-value move: admission enforces it on the next
        iteration, nothing recompiles — this is the autoscaler's
        ``kv_quota`` action."""
        if max_blocks is None:
            self._tenant_quota.pop(tenant, None)
        else:
            self._tenant_quota[tenant] = max(0, int(max_blocks))
        self._wake.set()

    def pool_stats(self) -> Dict[str, int]:
        """Allocator accounting snapshot (soak/chaos assertions): free
        and total block counts plus live stream count.  Reads host-side
        ints the serve thread mutates — values are a consistent-enough
        snapshot for accounting at quiesce points (post-drain)."""
        free = getattr(self, "_free", None)
        slots = getattr(self, "_live_slots", None) or []
        return {
            "blocks_total": self.n_blocks,
            "blocks_free": self.n_blocks if free is None else len(free),
            "live_streams": sum(1 for s in slots if s is not None),
            # prefix-sharing accounting: blocks whose content + chain
            # hash are indexed (many resting in the free list at
            # refcount 0), and blocks currently mapped by >1 stream
            "blocks_cached": len(getattr(self, "_block_hash", {}) or {}),
            "blocks_shared": int(
                (np.asarray(getattr(self, "_ref", [])) > 1).sum())
            if getattr(self, "_ref", None) is not None else 0,
        }

    def stream_table(self) -> Dict[int, Dict]:
        """``stream_id -> {"state", "tenant", "slot", "blocks"}`` for
        every stream this loop owns (queued, admitting, or live)."""
        out: Dict[int, Dict] = {}
        for ent in list(self._waiting):
            sid = ent[1].get(elastic.META_STREAM_ID)
            if sid is not None:
                out[sid] = {"state": "queued", "slot": None, "blocks": 0,
                            "tenant": ent[1].get(_META_TENANT)}
        for st in list(self._admitting):
            sid = st["meta"].get(elastic.META_STREAM_ID)
            if sid is not None:
                out[sid] = {"state": "admitting", "slot": st["slot"],
                            "blocks": len(
                                getattr(self, "_slot_blocks",
                                        [[]])[st["slot"]]),
                            "tenant": st["meta"].get(_META_TENANT)}
        slots = getattr(self, "_live_slots", None) or []
        sids = getattr(self, "_slot_sid", None) or []
        for s, slot in enumerate(slots):
            if slot is None or s >= len(sids) or sids[s] is None:
                continue
            out[sids[s]] = {"state": "live", "slot": s,
                            "blocks": len(self._slot_blocks[s]),
                            "tenant": slot[0].get(_META_TENANT)}
        return out

    def _ctl_call(self, cmd: Dict, timeout: float):
        """Enqueue one control command and wait for the serve thread to
        execute it at a chunk boundary."""
        cmd["ev"] = threading.Event()
        cmd["deadline"] = time.monotonic() + timeout
        with self._idle_lock:
            if self._error is not None:
                raise FrameworkError(
                    f"continuous serve loop died: {self._error!r}")
            self._idle.clear()
            self._ctl.append(cmd)
        self._wake.set()
        if not cmd["ev"].wait(timeout + 1.0):
            raise FrameworkError(
                f"serve-loop {cmd['kind']} command timed out "
                f"after {timeout}s")
        if cmd.get("error"):
            raise FrameworkError(cmd["error"])
        return cmd.get("result")

    def drain_stream(self, sid: int, timeout: float = 30.0) -> Dict:
        return self._ctl_call({"kind": "drain", "sid": int(sid)}, timeout)

    def adopt_stream(self, snapshot: Dict, emit,
                     timeout: float = 30.0) -> int:
        return self._ctl_call(
            {"kind": "adopt", "snapshot": snapshot, "emit": emit},
            timeout)

    def swap_params(self, tree, timeout: float = 30.0) -> int:
        """Enqueue a param hot-swap, executed at the next chunk boundary
        (nns-learn train-while-serve); returns the new param version."""
        return self._ctl_call({"kind": "swap", "tree": tree}, timeout)

    # -- serve thread ------------------------------------------------------
    def _emit_token(self, emit, meta: Dict, token_id: int, index: int,
                    last: bool, extra: Optional[Dict] = None) -> None:
        out_meta = dict(meta)
        if extra:
            out_meta.update(extra)
        out_meta[META_STREAM_INDEX] = index
        # Serving telemetry: when THIS token left the decode loop
        # (monotonic seconds).  Lets consumers measure generation-window
        # throughput precisely instead of inferring it from pull times,
        # which lag emission by queue dwell.
        out_meta["emit_t"] = time.monotonic()
        if last:
            out_meta[META_STREAM_LAST] = True
        piece = self.fw.tokenizer.decode_piece(token_id)
        emit([np.asarray([token_id], np.int32),
              np.frombuffer(piece, np.uint8).copy()], out_meta)
        metrics.count("llm.tokens")

    def _run(self) -> None:
        try:
            self._run_inner()
        except BaseException as e:  # noqa: BLE001 - daemon thread: report
            log.exception("continuous serve loop died")

            def abort(meta, emit, idx=0):
                try:
                    self._emit_token(
                        emit, {**meta, META_STREAM_ABORTED: True}, 0, idx,
                        True)
                except Exception:  # noqa: BLE001
                    pass
                sid = meta.get(elastic.META_STREAM_ID)
                if sid is not None:
                    elastic.unregister_stream(sid)
                    self._owned_sids.discard(sid)

            # Terminate every live, mid-prefill, waiting, and queued
            # stream so no client hangs to its timeout waiting on a dead
            # loop.  The queue drain + idle-set run under _idle_lock,
            # pairing with submit(): no request can enter the queue
            # after the drain.
            import queue as _q

            for slot in list(getattr(self, "_live_slots", []) or []):
                if slot is not None:
                    abort(slot[0], slot[1], 1 << 30)
            for st in list(self._admitting):
                abort(st["meta"], st["emit"])
            for ent in list(self._waiting):
                abort(ent[1], ent[2])
            with self._idle_lock:
                self._error = e
                while True:
                    try:
                        ent = self._pending.get_nowait()
                    except _q.Empty:
                        break
                    abort(ent[1], ent[2])
                # control callers (drain/adopt) blocked on their events
                # must see the crash, not their timeout
                while self._ctl:
                    cmd = self._ctl.popleft()
                    cmd["error"] = f"continuous serve loop died: {e!r}"
                    cmd["ev"].set()
                self._idle.set()

    def _span(self, rec, kind: str, t0_ns: int, **args) -> None:
        if rec is not None and rec.active:
            now = time.monotonic_ns()
            rec.record(kind, "llm.serve", None, t0_ns, now - t0_ns, **args)

    def _run_inner(self) -> None:
        import dataclasses as _dc
        import functools as _ft
        import math
        import queue as _q

        import jax
        import jax.numpy as jnp

        fw, cfg = self.fw, self.fw.cfg
        B, bs, C = fw.slots, fw.block_size, fw.prefill_chunk
        params = fw.bundle.params
        pool = llama.init_paged_cache(cfg, self.n_blocks, bs,
                                      dtype=fw.dtype)
        d_params = draft_pool = None
        if self._spec:
            d_params = fw.draft_bundle.params
            # the draft pool mirrors the target's (n_blocks, block_size)
            # at the draft's own (L, H_kv, hd): ONE allocator, ONE table
            # set steers both — block id j holds both models' K/V for
            # the same token positions
            draft_pool = llama.init_paged_cache(
                fw.draft_cfg, self.n_blocks, bs, dtype=fw.dtype)
        if fw.mesh is not None:
            # Tensor parallelism: the block pool shards over `model` on
            # the K/V head dim exactly like the dense cache, so a
            # model_parallel=M loop holds pool_bytes/M per chip and the
            # pool composes with the same allocator/tables (host-side
            # ints, replicated).  Geometry was validated at _setup
            # (n_kv_heads % M == 0, tp_divisibility_problems).
            from ..parallel.sharding import shard_params as _sp

            pool = _sp(fw.mesh, pool, llama.paged_cache_pspecs())
            if draft_pool is not None:
                draft_pool = _sp(fw.mesh, draft_pool,
                                 llama.paged_cache_pspecs())
        # published like the allocator bookkeeping below: tests and
        # post-mortems read the pool's actual placement off the loop
        self._pool_sharding = getattr(pool["k"], "sharding", None)
        # the MEASURED pool footprint (global bytes; /M per chip under
        # TP; target + draft pools) — nns-xray's HBM ledger reconciles
        # this against the deep lint's serving_plan pool_bytes +
        # draft_pool_bytes estimate
        from .base import tree_param_bytes as _tree_bytes

        self._pool_nbytes = _tree_bytes(pool) + (
            _tree_bytes(draft_pool) if draft_pool is not None else 0)
        # Device carries tok/pool (+ per-slot PRNG keys, and positions
        # under speculation) between chunks (r4: materializing them per
        # chunk cost tunnel roundtrips).  EVERYTHING ELSE is
        # host bookkeeping: positions advance deterministically (+length
        # per chunk for live rows, parked otherwise) and block tables
        # change only at admit/retire, so both live as numpy and ride to
        # the device as tiny async H2D args — never a fetch.
        tok = jnp.zeros((B,), jnp.int32)
        tok_prev = jnp.zeros((B,), jnp.int32) if self._spec else None
        key = jax.random.PRNGKey(fw.seed)
        # Per-slot PRNG state (docs/SERVING.md §4d): each slot's base
        # key is fold_in(PRNGKey(seed), admission number) — a pure
        # function of (seed, admission order), NOT of stream ids (those
        # are process-global and would differ between two same-seed
        # runs in one process, breaking bit-reproducibility).  The
        # device twin rebuilds by VALUE at admission/adopt events (a
        # transfer, never a compile); every per-token draw then folds
        # (absolute position, tag) inside the compiled programs.
        base_key = np.asarray(jax.random.PRNGKey(fw.seed), np.uint32)
        adm_no = 0
        keys_h = np.zeros((B, 2), np.uint32)
        keys_dev = jnp.asarray(keys_h)
        # the measured PRNG slot-state footprint the xray HBM ledger
        # reconciles against serving_plan's prng_state_bytes
        self._prng_nbytes = int(keys_h.nbytes) if self._sampled else 0
        # Speculative loops also carry positions as a device twin: the
        # fused verify commits pos += accepted+1 in-program, so the
        # host never re-uploads positions per round.  The host numpy
        # `pos` below stays authoritative for admission/drain
        # bookkeeping; park/admission/adopt events push its per-slot
        # values into pos_dev through the existing _set_tok signature.
        pos_dev = jnp.full((B,), self.park, jnp.int32) \
            if self._spec else None
        _rep = None
        if fw.mesh is not None:
            # Commit the carried device state to the mesh UP FRONT: the
            # first decode otherwise traces against single-device inputs
            # while every later call sees mesh-replicated outputs — one
            # avoidable extra signature that would break the fixed-
            # census pin TP must preserve (the compile-counter pin).
            from ..parallel.sharding import replicate as _rep

            tok = _rep(fw.mesh, tok)
            key = _rep(fw.mesh, key)
            keys_dev = _rep(fw.mesh, keys_dev)
            if tok_prev is not None:
                tok_prev = _rep(fw.mesh, tok_prev)
            if pos_dev is not None:
                pos_dev = _rep(fw.mesh, pos_dev)

        def push_keys() -> None:
            """Rebuild the device key vector from the host mirror — an
            admission/adopt-event VALUE move (replicated under TP), so
            steady-state rounds never touch it."""
            nonlocal keys_dev
            keys_dev = jnp.asarray(keys_h)
            if fw.mesh is not None:
                keys_dev = _rep(fw.mesh, keys_dev)

        def fresh_slot_key() -> np.ndarray:
            nonlocal adm_no
            k = np.asarray(
                jax.random.fold_in(jnp.asarray(base_key), adm_no),
                np.uint32)
            adm_no += 1
            return k

        pos = np.full((B,), self.park, np.int32)  # parked = idle
        tables = np.full((B, self.max_blocks), self.sentinel, np.int32)
        free = list(range(self.n_blocks))  # host free list (block ids)
        slot_blocks: list = [[] for _ in range(B)]
        #: per-block reference counts: 0 = on the free list, 1 = one
        #: private owner, >1 = a prefix-shared block mapped into several
        #: streams' tables.  A block returns to the free list ONLY at
        #: refcount 0 (release) — the prefix-sharing invariant the
        #: property tests in tests/test_spec_decode.py pin.
        ref = np.zeros((self.n_blocks,), np.int64)
        #: prefix cache: chain-hash -> pool block id.  Cached blocks with
        #: refcount 0 LIVE IN THE FREE LIST (content + index intact):
        #: the cache never shrinks admission capacity, and eviction is
        #: simply allocation — popping an indexed block drops its entry.
        prefix_index: Dict[bytes, int] = {}
        block_hash: Dict[int, bytes] = {}
        #: host mirrors of the carried token state (the last committed
        #: token and the one before it) per slot — the speculative
        #: round's accept/commit writes them and rebuilds the device
        #: vectors by value; drain snapshots read tok_prev from here.
        tok_h = np.zeros((B,), np.int32)
        tok_prev_h = np.zeros((B,), np.int32)
        # Bookkeeping published on self (mutated in place, so the refs
        # stay live): the leak/contamination tests read them after
        # drain(), and a post-mortem can see the pool state.
        self._pos, self._tables = pos, tables
        self._free, self._slot_blocks = free, slot_blocks
        self._ref, self._prefix_index = ref, prefix_index
        self._block_hash = block_hash
        remaining = np.zeros((B,), np.int64)
        sidx = np.zeros((B,), np.int64)
        slots: list = [None] * B  # (meta, emit) per live slot
        self._live_slots = slots  # visible to the crash terminator
        #: per-slot stream id / tenant / original prompt tokens — the
        #: elastic surface (cancel lookup, quota accounting, drain
        #: snapshots); set at admission, cleared by retire()
        self._slot_sid: list = [None] * B
        self._slot_tenant: list = [None] * B
        self._slot_prompt: list = [None] * B
        #: per-slot serving timeline (docs/OBSERVABILITY.md "Distributed
        #: tracing"): enqueue/admit/first-token/last-emit stamps
        #: (monotonic seconds) feeding the TTFT / ITL / phase-split
        #: histograms.  Values are MILLISECONDS (the ``_ms`` series are
        #: reservoir-quantile sources; the seconds-scaled fixed bucket
        #: ladder saturates for them).  None for adopted streams — their
        #: enqueue happened in another process, so TTFT is unknowable.
        self._slot_time: list = [None] * B
        eos = getattr(fw.tokenizer, "eos", -1) if fw.stop_eos else -1

        import os as _os
        trace = _os.environ.get("NNSTPU_SERVE_TRACE") == "1"

        def _tr(tag):
            if trace:
                # stderr: stdout carries bench.py's line-delimited JSON
                import sys as _sys

                print(f"[serve {time.monotonic():.3f}] {tag}",
                      file=_sys.stderr, flush=True)

        def take_blocks(need: int) -> list:
            """Allocate ``need`` private blocks (refcount 1) off the
            free list, preferring blocks that do NOT hold a cached
            prefix; when only cached blocks remain, the oldest-released
            ones are evicted (their index entries dropped) — eviction
            IS allocation, so the prefix cache can never make admission
            defer.

            O(need * len(free)) from the head-pops — per ADMISSION,
            not per token; at the worst-case bench pool (64 7B
            streams, ~4.6k blocks) that is ~1 ms of host time under
            the prefill dispatch it precedes.  Revisit with a deque +
            free-set if pools grow past that."""
            got: list = []
            cached: list = []
            while free and len(got) < need:
                b = free.pop(0)
                (cached if b in block_hash else got).append(b)
            while cached and len(got) < need:
                b = cached.pop(0)
                del prefix_index[block_hash.pop(b)]
                metrics.count("llm.serve.prefix_evictions")
                got.append(b)
            free[0:0] = cached  # skipped cached blocks keep their place
            if len(got) < need:
                # every caller pre-checks capacity (admission counts
                # resting matched blocks on top of phys; adopt checks
                # len(free)); a shortfall here is an allocator-invariant
                # bug — fail LOUDLY instead of handing back a short
                # list that becomes a silently truncated block table
                # and bit-wrong output
                free[0:0] = got
                for b in got:
                    ref[b] = 0
                raise RuntimeError(
                    f"KV allocator invariant violated: asked for {need} "
                    f"blocks, only {len(got)} allocatable")
            for b in got:
                ref[b] = 1
            return got

        def alloc(n_tokens: int) -> list:
            return take_blocks(math.ceil(n_tokens / bs))

        def release(blocks) -> None:
            """Drop one reference per block; a block returns to the
            free list ONLY at refcount 0 (prefix-shared blocks stay
            resident for their other holders; cached content + index
            survive until eviction-by-allocation)."""
            for b in blocks:
                ref[b] -= 1
                if ref[b] <= 0:
                    ref[b] = 0
                    free.append(b)

        def map_shared(bid: int) -> None:
            """Take one more reference on a cached/shared block — off
            the free list if it was resting there at refcount 0."""
            if ref[bid] == 0:
                free.remove(bid)
            ref[bid] += 1

        def cow_fork(src: int, rec=None) -> int:
            """Copy-on-write fork: a stream about to WRITE into a block
            it shares gets a private copy first (target AND draft pool
            rows — an eager value move like adopt's scatter; none of
            the compiled programs is touched).  The source keeps its
            other holders' references.

            Trade-off (shared with adopt): the eager ``.at[].set`` holds
            the old pool alive across the update, so XLA materializes a
            transient second pool buffer — at most one fork per
            admission, off the decode dispatch path.  A donated jitted
            fork would avoid the spike but mint a program the closed
            census (serving_plan/tracecheck/xray) would have to price;
            revisit if silicon pools sized to the HBM edge OOM here."""
            t0 = time.monotonic_ns()
            new = take_blocks(1)[0]
            src_i = np.asarray([src], np.int32)
            new_i = np.asarray([new], np.int32)
            pool["k"] = pool["k"].at[:, new_i].set(pool["k"][:, src_i])
            pool["v"] = pool["v"].at[:, new_i].set(pool["v"][:, src_i])
            if draft_pool is not None:
                draft_pool["k"] = draft_pool["k"].at[:, new_i].set(
                    draft_pool["k"][:, src_i])
                draft_pool["v"] = draft_pool["v"].at[:, new_i].set(
                    draft_pool["v"][:, src_i])
            metrics.count("llm.serve.cow_forks")
            self._span(rec, "serve.cow_fork", t0, src=int(src),
                       dst=int(new))
            return new

        def chain_hashes(row: np.ndarray, full: int) -> list:
            """Token-block chain hashes: hash j commits to ALL tokens
            of blocks 0..j, so two prompts share block j only when
            their entire prefixes match — which is exactly when the
            cached K/V rows (position-dependent through RoPE) are
            bit-valid for both."""
            import hashlib

            h = b"nns-prefix-v1"
            out = []
            for j in range(full):
                h = hashlib.sha1(
                    h + row[j * bs:(j + 1) * bs].tobytes()).digest()
                out.append(h)
            return out

        #: sid -> chain_hashes(prompt) memo for WAITING prompts: a
        #: capacity-deferred entry is re-scanned every loop iteration,
        #: and its prompt is immutable after submit — re-hashing a long
        #: prompt per spin would burn serve-thread time exactly when
        #: the system is saturated.  Pruned against the live waiting
        #: set each admission phase, so no path can leak entries.
        chain_cache: Dict[int, list] = {}

        def retire(s: int) -> None:
            nonlocal pos_dev
            release(slot_blocks[s])
            slot_blocks[s] = []
            tables[s, :] = self.sentinel
            pos[s] = self.park
            if pos_dev is not None:
                # re-park the device twin too: the fused verify carries
                # positions on device, and a retired row must stop
                # advancing (same int32[B] _set_tok signature — no new
                # program)
                pos_dev = self._set_tok(
                    pos_dev, np.int32(s),
                    jnp.asarray(np.int32(self.park)))
            slots[s] = None
            remaining[s] = 0
            sidx[s] = 0
            sid = self._slot_sid[s]
            if sid is not None:
                elastic.unregister_stream(sid)
                self._owned_sids.discard(sid)
                self._cancelled.pop(sid, None)
            tt = self._slot_time[s]
            if tt is not None and tt["first"] is not None:
                # per-stream phase splits at retirement: time queued,
                # time from admission to first token (prefill + first
                # dispatch), time spent decoding
                ten = self._slot_tenant[s]
                metrics.observe_latency(
                    "llm.serve.queue_ms",
                    (tt["admit"] - tt["enq"]) * 1e3, tenant=ten)
                metrics.observe_latency(
                    "llm.serve.prefill_ms",
                    (tt["first"] - tt["admit"]) * 1e3, tenant=ten)
                metrics.observe_latency(
                    "llm.serve.decode_ms",
                    (tt["last"] - tt["first"]) * 1e3, tenant=ten)
            self._slot_time[s] = None
            self._slot_sid[s] = None
            self._slot_tenant[s] = None
            self._slot_prompt[s] = None
            metrics.gauge(f"llm.serve.slot{s}.occupied", 0.0)

        def mark_emit(s: int) -> None:
            """One emitted token's wall stamp: first emission observes
            TTFT (enqueue → first token, the client-visible number),
            later ones observe the inter-token gap.  Chunked decode
            materializes a whole chunk at once, so intra-chunk ITL
            samples are ~0 and the chunk boundary carries the gap —
            that IS the emission timeline a streaming client sees."""
            tt = self._slot_time[s]
            if tt is None:
                return  # adopted stream (or warmup): no local enqueue
            now = time.monotonic()
            if tt["first"] is None:
                tt["first"] = tt["last"] = now
                metrics.observe_latency(
                    "llm.serve.ttft_ms", (now - tt["enq"]) * 1e3,
                    tenant=self._slot_tenant[s])
            else:
                metrics.observe_latency(
                    "llm.serve.itl_ms", (now - tt["last"]) * 1e3,
                    tenant=self._slot_tenant[s])
                tt["last"] = now

        def slot_of(sid) -> Optional[int]:
            if sid is None:
                return None
            for s in range(B):
                if self._slot_sid[s] == sid:
                    return s
            return None

        def reject(meta: Dict, emit, reason: str, idx: int = 0) -> None:
            """Typed stream abort: a ``stream_aborted`` terminator whose
            ``abort_reason`` names the policy that fired, plus registry
            cleanup — the elastic twin of the crash terminator."""
            try:
                self._emit_token(
                    emit, {**meta, META_STREAM_ABORTED: True,
                           META_ABORT_REASON: reason}, 0, idx, True)
            except Exception:  # noqa: BLE001 - downstream may be gone too
                pass
            sid = meta.get(elastic.META_STREAM_ID)
            if sid is not None:
                elastic.unregister_stream(sid)
                self._owned_sids.discard(sid)
                self._cancelled.pop(sid, None)

        def tenant_blocks(tenant) -> int:
            return sum(len(slot_blocks[s]) for s in range(B)
                       if self._slot_tenant[s] == tenant)

        # Warm EVERY program the loop uses before admitting real work:
        # over a tunneled device, first-use costs (trace + compile +
        # program upload) run 0.5-2 s EACH and land on the first
        # requests' critical path otherwise.  llama.cpp servers warm up
        # the same way.  Warmup allocates real blocks (exercising the
        # allocator), writes garbage through them, and frees them —
        # nothing real can attend it (the slot re-parks).
        warm_blocks = alloc(min(C, self.n_blocks * bs))
        tables[0, :len(warm_blocks)] = warm_blocks
        logits_w, pool = self._prefill(
            params, jnp.zeros((1, C), jnp.int32), pool, tables[:1],
            pos[:1] * 0, np.int32(C - 1))
        key, sub = jax.random.split(key)
        first_w = llama.sample_token(logits_w, sub, fw.temperature,
                                     fw.top_k, fw.top_p)[0]
        tok = self._set_tok(tok, np.int32(0), first_w)
        if self._spec:
            # every slot is parked: the propose/verify warm-ups compile
            # their (only) signatures, write nothing (sentinel tables),
            # and DMA nothing.  pos_dev rides through verify and comes
            # back all-parked (the in-program live mask passes parked
            # rows through untouched).
            draft_pool = self._draft_prefill(
                d_params, jnp.zeros((1, C), jnp.int32), draft_pool,
                tables[:1], pos[:1] * 0)
            props_w, dprobs_w, draft_pool = self._propose(
                d_params, tok_prev, tok, draft_pool, tables, pos_dev,
                keys_dev)
            em_w, acc_w, tok, tok_prev, pos_dev, pool = self._verify(
                params, tok, tok_prev, props_w, dprobs_w, pool, tables,
                pos_dev, keys_dev)
            np.asarray(em_w)
        else:
            toks_w, tok, pool = self._decode(
                params, tok, pool, tables, pos, keys_dev,
                length=fw.chunk)
            np.asarray(toks_w)
        release(warm_blocks)
        tables[0, :] = self.sentinel
        _tr("warmup done")

        while not self._stop.is_set():
            progressed = False
            rec = getattr(fw, "_trace_rec", None)
            # 0. drain the thread-handoff queue into the admission-order
            # list (FIFO preserved when the head defers on capacity)
            while True:
                try:
                    self._waiting.append(self._pending.get_nowait())
                except _q.Empty:
                    break

            # 0b. control commands (Pipeline.drain_stream/adopt_stream):
            # executed HERE, at a chunk boundary, where every slot's
            # host bookkeeping is consistent.  Both are host-side value
            # moves plus eager gather/scatter on the pool — none of the
            # three compiled loop programs is touched, so the census pin
            # holds across drain/adopt (tests/test_elastic.py).
            deferred_cmds = []
            while self._ctl:
                cmd = self._ctl.popleft()
                if time.monotonic() > cmd["deadline"]:
                    cmd["error"] = (f"{cmd['kind']} timed out inside the "
                                    "serve loop")
                    cmd["ev"].set()
                    continue
                if cmd["kind"] == "drain":
                    sid = cmd["sid"]
                    s = slot_of(sid)
                    if s is not None and slots[s] is None:
                        s = None  # mid-prefill: not drainable yet
                    wi = next(
                        (i for i, ent in enumerate(self._waiting)
                         if ent[1].get(elastic.META_STREAM_ID) == sid),
                        None)
                    if s is not None:
                        t0 = time.monotonic_ns()
                        n_used = math.ceil(int(pos[s]) / bs)
                        ids = np.asarray(slot_blocks[s][:n_used],
                                         np.int32)
                        meta, _emit_cb = slots[s]
                        n_shared = sum(
                            1 for b in slot_blocks[s][:n_used]
                            if ref[b] > 1)
                        cmd["result"] = {
                            # v2: adds tok_prev (the speculative
                            # refresh step's input) + shared_blocks;
                            # v1 snapshots stay adoptable (the gather
                            # below MATERIALIZES every block — shared
                            # ones included — as host copies, so a
                            # snapshot never aliases pool blocks
                            # another live stream still holds)
                            "version": 2, "kind": "live",
                            META_STREAM_ID: sid,
                            "cfg": _dc.asdict(cfg), "dtype": fw.dtype,
                            "block_size": bs, "pos": int(pos[s]),
                            "remaining": int(remaining[s]),
                            "sidx": int(sidx[s]),
                            "tok": int(np.asarray(tok)[s]),
                            "tok_prev": int(tok_prev_h[s]),
                            "shared_blocks": n_shared,
                            "greedy": fw.temperature == 0.0,
                            # per-slot PRNG key (docs/SERVING.md §4d):
                            # same-seed sampled continuation after
                            # adopt_stream is bit-identical because
                            # draws fold the absolute position, not the
                            # slot or step
                            "prng_key": [int(v) for v in keys_h[s]],
                            "meta": {k: v for k, v in meta.items()
                                     if k not in _SNAPSHOT_META_DROP},
                            "prompt": np.asarray(self._slot_prompt[s]),
                            # valid cache rows [0, pos) gathered to
                            # host, whole blocks at a time — a COPY,
                            # never an alias (np.asarray of a device
                            # gather materializes)
                            "blocks_k": np.asarray(pool["k"][:, ids]),
                            "blocks_v": np.asarray(pool["v"][:, ids]),
                        }
                        nb = len(slot_blocks[s])
                        retire(s)
                        self._span(rec, "elastic.drain", t0,
                                   stream_id=sid, state="live",
                                   blocks=nb)
                        _tr(f"drained slot {s} (stream {sid})")
                        progressed = True
                        cmd["ev"].set()
                    elif wi is not None:
                        t0 = time.monotonic_ns()
                        ent = self._waiting.pop(wi)
                        cmd["result"] = {
                            "version": 2, "kind": "queued",
                            META_STREAM_ID: sid,
                            "cfg": _dc.asdict(cfg), "dtype": fw.dtype,
                            "block_size": bs,
                            "greedy": fw.temperature == 0.0,
                            "meta": {k: v for k, v in ent[1].items()
                                     if k not in _SNAPSHOT_META_DROP},
                            "prompt": np.asarray(ent[0]),
                        }
                        elastic.unregister_stream(sid)
                        self._owned_sids.discard(sid)
                        self._cancelled.pop(sid, None)
                        self._span(rec, "elastic.drain", t0,
                                   stream_id=sid, state="queued",
                                   blocks=0)
                        progressed = True
                        cmd["ev"].set()
                    elif any(st["meta"].get(elastic.META_STREAM_ID)
                             == sid for st in self._admitting):
                        # mid-prefill: goes live within a few
                        # iterations — re-check then
                        deferred_cmds.append(cmd)
                    else:
                        cmd["error"] = (f"unknown or already-finished "
                                        f"stream {sid}")
                        cmd["ev"].set()
                elif cmd["kind"] == "adopt":
                    snap = cmd["snapshot"]
                    t0 = time.monotonic_ns()
                    sid = int(snap.get(META_STREAM_ID, 0))
                    if sid <= 0 or sid in elastic.live_stream_ids():
                        # cross-process snapshots may collide with a
                        # live local id — remint, the snapshot id is
                        # only a continuity hint
                        sid = elastic.next_stream_id()
                    meta = dict(snap.get("meta") or {})
                    meta[elastic.META_STREAM_ID] = sid
                    if snap.get("kind") == "queued":
                        self._owned_sids.add(sid)
                        elastic.register_stream(
                            sid, _ft.partial(self._mark_cancel, sid))
                        self._waiting.append(
                            (np.asarray(snap["prompt"], np.int32), meta,
                             cmd["emit"], time.monotonic()))
                        self._span(rec, "elastic.adopt", t0,
                                   stream_id=sid, state="queued",
                                   blocks=0)
                        cmd["result"] = sid
                        progressed = True
                        cmd["ev"].set()
                        continue
                    p_next = int(snap["pos"])
                    rem = int(snap["remaining"])
                    need_tok = p_next + rem
                    freeslots = [
                        s for s in range(B)
                        if slots[s] is None and remaining[s] == 0
                        and not any(st["slot"] == s
                                    for st in self._admitting)]
                    if not freeslots:
                        cmd["error"] = "no free slot to adopt into"
                    elif math.ceil(need_tok / bs) > self.max_blocks:
                        cmd["error"] = (
                            f"stream needs {math.ceil(need_tok / bs)} "
                            f"blocks > table span {self.max_blocks}")
                    elif len(free) * bs < need_tok:
                        cmd["error"] = (
                            f"insufficient free KV blocks "
                            f"({len(free)} free, "
                            f"{math.ceil(need_tok / bs)} needed)")
                    else:
                        s = freeslots[0]
                        blocks = alloc(need_tok)
                        slot_blocks[s] = blocks
                        tables[s, :len(blocks)] = blocks
                        n_used = math.ceil(p_next / bs)
                        ids = np.asarray(blocks[:n_used], np.int32)
                        # eager scatter of the snapshot's cache rows
                        # into the newly reserved pool blocks (a value
                        # move — the compiled census is untouched)
                        pool["k"] = pool["k"].at[:, ids].set(
                            jnp.asarray(np.asarray(snap["blocks_k"])))
                        pool["v"] = pool["v"].at[:, ids].set(
                            jnp.asarray(np.asarray(snap["blocks_v"])))
                        # jnp.asarray: the jit fast path keys on arg
                        # TYPE, not just aval — a raw numpy scalar here
                        # would mint a 4th signature and break the
                        # 3-program census pin
                        tok = self._set_tok(tok, np.int32(s),
                                            jnp.asarray(
                                                np.int32(snap["tok"])))
                        tok_h[s] = int(snap["tok"])
                        tok_prev_h[s] = int(snap.get("tok_prev", 0))
                        if self._spec:
                            # the refresh step re-feeds tok_prev at
                            # pos-1; adopting into a spec loop requires
                            # it (snapshot_problems gates v1 snapshots
                            # out).  The DRAFT pool stays unwritten for
                            # the adopted rows — proposals degrade
                            # until positions rewrite, greedy
                            # continuation is target-decided and stays
                            # bit-identical.
                            tok_prev = self._set_tok(
                                tok_prev, np.int32(s),
                                jnp.asarray(np.int32(
                                    snap.get("tok_prev", 0))))
                            pos_dev = self._set_tok(
                                pos_dev, np.int32(s),
                                jnp.asarray(np.int32(p_next)))
                        # sampled streams continue their own PRNG
                        # stream: the snapshot key (if present) slots
                        # in; pre-sampling snapshots get a fresh one
                        pk = snap.get("prng_key")
                        keys_h[s] = (np.asarray(pk, np.uint32)
                                     if pk is not None
                                     else fresh_slot_key())
                        push_keys()
                        pos[s] = p_next
                        remaining[s] = rem
                        sidx[s] = int(snap["sidx"])
                        slots[s] = (meta, cmd["emit"])
                        self._slot_sid[s] = sid
                        self._slot_tenant[s] = meta.get(_META_TENANT)
                        self._slot_prompt[s] = (
                            np.asarray(snap["prompt"], np.int32)
                            if snap.get("prompt") is not None else
                            np.zeros((1, 0), np.int32))
                        self._owned_sids.add(sid)
                        elastic.register_stream(
                            sid, _ft.partial(self._mark_cancel, sid))
                        metrics.gauge(f"llm.serve.slot{s}.occupied", 1.0)
                        self._span(rec, "elastic.adopt", t0,
                                   stream_id=sid, state="live", slot=s,
                                   blocks=len(blocks))
                        _tr(f"adopted stream {sid} into slot {s}")
                        cmd["result"] = sid
                        progressed = True
                    cmd["ev"].set()
                elif cmd["kind"] == "swap":
                    # nns-learn param hot-swap (docs/TRAINING.md): a pure
                    # VALUE move executed where drain/adopt execute — the
                    # decode/prefill programs take params as arguments,
                    # so aval-identical leaves re-use the standing
                    # 3-program census (zero recompiles, pinned by test).
                    # Placement copies onto the live leaves' shardings
                    # (TP pspecs carry over) with FRESH buffers, so a
                    # trainer donating its own tree can't invalidate us.
                    t0 = time.monotonic_ns()
                    try:
                        params = place_swapped_params(params, cmd["tree"])
                    except Exception as e:  # noqa: BLE001 - caller's error
                        cmd["error"] = str(e)
                    else:
                        fw.bundle.params = params
                        self.param_version += 1
                        metrics.count("llm.serve.param_swaps")
                        metrics.gauge("llm.serve.param_version",
                                      float(self.param_version))
                        self._span(rec, "learn.swap", t0,
                                   version=self.param_version)
                        _tr(f"params swapped (v{self.param_version})")
                        cmd["result"] = self.param_version
                        progressed = True
                    cmd["ev"].set()
                else:
                    cmd["error"] = f"unknown command {cmd['kind']!r}"
                    cmd["ev"].set()
            if deferred_cmds:
                self._ctl.extend(deferred_cmds)

            # 0c. reap orphaned streams: a stream marked dead
            # (utils/elastic.cancel_stream — the serversink's dead-
            # connection backchannel) gets stream_idle_timeout of grace
            # (a drain/handover may still pick it up), then its slot +
            # KV blocks return to the free list and a typed terminator
            # goes downstream instead of the pool leaking capacity
            # until max_new runs out.  Queued marks are consumed by the
            # admission scan below.
            if self._cancelled:
                now_m = time.monotonic()
                for sid, (reason, deadline) in list(
                        self._cancelled.items()):
                    if now_m < deadline:
                        continue
                    s = slot_of(sid)
                    st = next(
                        (st for st in self._admitting
                         if st["meta"].get(elastic.META_STREAM_ID)
                         == sid), None)
                    if st is not None:
                        # mid-prefill: drop the prefill state first so
                        # step 2 cannot keep writing into freed blocks
                        self._admitting.remove(st)
                        s = st["slot"]
                    if s is not None:
                        t0 = time.monotonic_ns()
                        nb = len(slot_blocks[s])
                        live_slot = slots[s] is not None
                        meta, emit_cb = (slots[s] if live_slot
                                         else (st["meta"], st["emit"]))
                        metrics.count("llm.serve.reaped")
                        metrics.count("llm.serve.reaped_blocks", nb)
                        self._span(rec, "serve.reap", t0, slot=s,
                                   stream_id=sid, blocks=nb,
                                   reason=reason)
                        _tr(f"reaped slot {s} (stream {sid}: {reason})")
                        # mid-prefill streams emitted nothing: their
                        # terminator is index 0, not the slot's stale
                        # previous-occupant counter
                        reject(meta, emit_cb, reason,
                               idx=int(sidx[s]) if live_slot else 0)
                        retire(s)
                        progressed = True
                    elif not any(
                            ent[1].get(elastic.META_STREAM_ID) == sid
                            for ent in self._waiting):
                        # already finished/unknown: clear the mark
                        self._cancelled.pop(sid, None)

            # 1. admission: move waiting prompts into free slots while a
            # slot AND the stream's full block reservation are available.
            # Host-only bookkeeping — no device work yet.  Strict FIFO
            # for capacity deferral (a huge prompt waits rather than
            # being overtaken forever) with two elastic carve-outs: an
            # entry stuck past admit_timeout is rejected with a TYPED
            # abort instead of wedging every tenant queued behind it,
            # and a tenant over its kv-block quota is SKIPPED — tenant-
            # attributed deferral must not head-of-line-block the rest.
            if chain_cache:
                waiting_sids = {e[1].get(elastic.META_STREAM_ID)
                                for e in self._waiting}
                for k in [k for k in chain_cache
                          if k not in waiting_sids]:
                    del chain_cache[k]
            wi = 0
            while wi < len(self._waiting):
                prompt, meta, emit, t_enq = self._waiting[wi]
                sid = meta.get(elastic.META_STREAM_ID)
                mark = self._cancelled.get(sid)
                if mark is not None and time.monotonic() >= mark[1]:
                    # grace expired (same deadline the reap path honors
                    # — a drain/handover may still claim the stream
                    # inside it, queued or live)
                    self._waiting.pop(wi)
                    reject(meta, emit, mark[0])
                    progressed = True
                    continue
                T = prompt.shape[1]
                if T >= cfg.max_seq:
                    # reject oversize prompts with a terminated stream
                    self._waiting.pop(wi)
                    reject(meta, emit, "prompt-oversize")
                    progressed = True
                    continue
                n = max(1, min(fw.max_new, cfg.max_seq - T))
                if T + n > self.n_blocks * bs:
                    # the reservation exceeds the WHOLE pool: no amount
                    # of retiring ever satisfies it, so deferring would
                    # wedge the loop (head-of-line FIFO) — reject like
                    # the oversize case instead
                    self._waiting.pop(wi)
                    reject(meta, emit, "reservation-impossible")
                    progressed = True
                    continue
                overdue = (fw.admit_timeout > 0 and
                           time.monotonic() - t_enq > fw.admit_timeout)
                tenant = meta.get(_META_TENANT)
                quota = (self._tenant_quota.get(tenant)
                         if tenant is not None else None)
                # Quota charges LOGICAL blocks (per reference): a tenant
                # pays for every block its streams MAP, shared or not —
                # a shared prefix neither lets it exceed its cap for
                # free nor double-charges the physical pool (the free-
                # list check below is the physical side and charges the
                # non-shared suffix only).
                logical = math.ceil((T + n) / bs)
                if quota is not None and \
                        tenant_blocks(tenant) + logical > quota:
                    if overdue:
                        self._waiting.pop(wi)
                        metrics.count("llm.serve.admit_timeouts")
                        reject(meta, emit, "admit-timeout")
                        progressed = True
                        continue
                    metrics.count("llm.serve.quota_deferred")
                    wi += 1  # skip: quota deferral is tenant-scoped
                    continue
                # Prefix lookup BEFORE the capacity check: a cache hit
                # shrinks the PHYSICAL reservation to ~the non-shared
                # suffix, so a hit prompt admits where a cold one
                # defers.  The suffix prefill starts at p0 — the
                # largest prefill_chunk multiple not past the shared
                # extent (or the last real token): chunk ends stay on
                # the cold path's grid, so the table-span arithmetic in
                # serving_plan() is untouched.  A matched block
                # straddling p0 is copy-on-write FORKED (the chunk
                # rewrites part of it); matched blocks past p0 are
                # simply re-prefilled into fresh private blocks.
                hashes: list = []
                matched_ids: list = []
                if fw.prefix_cache:
                    hashes = chain_cache.get(sid)
                    if hashes is None:
                        hashes = chain_cache[sid] = chain_hashes(
                            prompt[0], T // bs)
                    for h in hashes:
                        bid = prefix_index.get(h)
                        if bid is None:
                            break
                        matched_ids.append(bid)
                s0 = len(matched_ids) * bs
                p0 = min(s0 // C, (T - 1) // C) * C if s0 else 0
                shared = p0 // bs
                fork = 1 if p0 % bs else 0
                phys = logical - shared
                # matched blocks RESTING in the free list (refcount 0,
                # cached content) still count as free right now, but
                # map_shared pulls each one OUT of the list below — the
                # capacity check must demand phys blocks ON TOP of
                # them, or take_blocks comes up short and the stream
                # gets a silently truncated table
                resting = sum(1 for b in matched_ids[:shared]
                              if ref[b] == 0)
                freeslots = np.flatnonzero(remaining == 0)
                freeslots = [int(s) for s in freeslots
                             if slots[s] is None and not any(
                                 st["slot"] == s
                                 for st in self._admitting)]
                if not freeslots or len(free) < phys + resting:
                    if overdue:
                        # head-of-line fix: a wedged/dead/huge stream at
                        # the queue head times out instead of blocking
                        # every tenant behind it forever
                        self._waiting.pop(wi)
                        metrics.count("llm.serve.admit_timeouts")
                        reject(meta, emit, "admit-timeout")
                        progressed = True
                        continue
                    break  # pool full: defer admission, never overflow
                t_admit = time.monotonic_ns()
                self._waiting.pop(wi)
                s = freeslots[0]
                blocks = list(matched_ids[:shared])
                for bid in blocks:
                    map_shared(bid)
                if fork:
                    blocks.append(cow_fork(matched_ids[shared], rec=rec))
                blocks.extend(take_blocks(phys - fork))
                slot_blocks[s] = blocks
                tables[s, :len(blocks)] = blocks
                self._slot_sid[s] = sid
                self._slot_tenant[s] = tenant
                self._slot_prompt[s] = prompt[:, :T].copy()
                self._slot_time[s] = {"enq": t_enq, "admit": t_admit / 1e9,
                                      "first": None, "last": None}
                if shared:
                    metrics.count("llm.serve.prefix_hits")
                    metrics.count("llm.serve.prefix_hit_blocks", shared)
                    self._span(rec, "serve.prefix_hit", t_admit, slot=s,
                               blocks=shared, tokens=p0)
                # chunk-multiple padding (replaces the old power-of-two
                # prompt bucketing on this path: waste < one chunk);
                # only the suffix [p0, P) is prefilled
                P = p0 + math.ceil((T - p0) / C) * C
                if P > T:
                    prompt = np.pad(prompt, ((0, 0), (0, P - T)))
                metrics.count("llm.serve.prefill_tokens", P - p0)
                metrics.count("llm.serve.prefill_pad_waste", P - T)
                self._admitting.append({
                    "slot": s, "prompt": prompt.astype(np.int32), "T": T,
                    "P": P, "p": p0, "n": n, "meta": meta, "emit": emit,
                    "first": None, "hashes": hashes,
                    "last_tok": int(prompt[0, T - 1])})
                self._span(rec, "serve.admit", t_admit, slot=s, tokens=T,
                           blocks=phys, shared=shared)
                _tr(f"admitted slot {s} ({T} tokens, {len(blocks)} "
                    f"blocks, {shared} shared)")
                progressed = True

            # 2. chunked prefill: dispatch up to prefill_budget tokens of
            # [1, C] prefill chunks straight into the admitting streams'
            # blocks (async — no host sync here).  With no live decode
            # the budget is waived: there is nothing to interleave with,
            # and finishing the prompt sooner IS the latency win.
            budget = fw.prefill_budget if (remaining > 0).any() else 1 << 30
            newly_live = []  # (slot, state) — first token syncs in step 4
            for st in list(self._admitting):
                while budget > 0 and st["p"] < st["P"]:
                    t_pf = time.monotonic_ns()
                    s, p = st["slot"], st["p"]
                    final = p + C >= st["P"]
                    # last REAL token's offset within this chunk (only
                    # meaningful on the final chunk; intermediate chunks
                    # are all real tokens and their logits are unused)
                    off = np.int32(st["T"] - 1 - p if final else 0)
                    logits, pool = self._prefill(
                        params, jnp.asarray(st["prompt"][:, p:p + C]),
                        pool, tables[s:s + 1],
                        np.asarray([p], np.int32), off)
                    if self._spec:
                        # the draft's prefill twin writes the chunk's
                        # draft K/V into the SAME blocks of the draft
                        # pool — a later prefix hit shares both models'
                        # rows
                        draft_pool = self._draft_prefill(
                            d_params,
                            jnp.asarray(st["prompt"][:, p:p + C]),
                            draft_pool, tables[s:s + 1],
                            np.asarray([p], np.int32))
                    st["p"] = p + C
                    budget -= C
                    self._span(rec, "serve.prefill_chunk", t_pf, slot=s,
                               pos=p, final=bool(final))
                    progressed = True
                    if final:
                        if fw.nan_guard and \
                                not np.isfinite(
                                    np.asarray(logits)).all():
                            # poison pill: the prompt's own prefill
                            # produced non-finite logits — quarantine
                            # it (DLQ + breaker accounting through the
                            # pipeline's armor) and answer the client
                            # with the typed poison terminator; the
                            # loop keeps serving every other stream
                            err = FloatingPointError(
                                "non-finite prefill logits (nan_guard)")
                            armor_obj = getattr(fw, "_armor", None)
                            if armor_obj is not None:
                                from ..core.buffer import Buffer as _Buf

                                armor_obj.quarantine(
                                    _Buf([st["prompt"][:, :st["T"]]
                                          .copy()],
                                         meta=dict(st["meta"])),
                                    error=err, stage="llm.serve")
                            metrics.count("llm.serve.poisoned")
                            _tr(f"poisoned prompt quarantined slot {s}")
                            self._admitting.remove(st)
                            reject(st["meta"], st["emit"], "poison")
                            retire(s)
                            progressed = True
                            break
                        # first-token sample stays EAGER (outside jit):
                        # logits are already device-resident and the
                        # dispatch overlaps the decode chunk below.
                        # The admitted stream gets its slot PRNG key
                        # here; the first token sits at position T, so
                        # its draw folds (T, sample tag) — the same
                        # convention the decode scan uses, making the
                        # whole stream a pure function of (seed,
                        # admission number, positions).
                        keys_h[s] = fresh_slot_key()
                        push_keys()
                        kft = jax.random.fold_in(jax.random.fold_in(
                            jnp.asarray(keys_h[s]), st["T"]), 100)
                        st["first"] = llama.sample_token(
                            logits, kft, fw.temperature, fw.top_k,
                            fw.top_p)[0]
                        tok = self._set_tok(tok, np.int32(s), st["first"])
                        tok_prev_h[s] = st["last_tok"]
                        if self._spec:
                            # the round's refresh step re-feeds the
                            # LAST PROMPT token at T-1 (bit-exact
                            # rewrite); must be device-resident before
                            # this iteration's propose dispatch — and
                            # the device position twin goes live at T
                            tok_prev = self._set_tok(
                                tok_prev, np.int32(s),
                                jnp.asarray(np.int32(st["last_tok"])))
                            pos_dev = self._set_tok(
                                pos_dev, np.int32(s),
                                jnp.asarray(np.int32(st["T"])))
                        # register the prompt's full blocks in the
                        # prefix index (content is in-flight on device;
                        # pool donation chains order any reader after
                        # this prefill).  Forked/shared blocks' hashes
                        # are already present — only fresh tails
                        # register.
                        if fw.prefix_cache:
                            for j, h in enumerate(st["hashes"]):
                                if h not in prefix_index:
                                    bid = slot_blocks[s][j]
                                    prefix_index[h] = bid
                                    block_hash[bid] = h
                        pos[s] = st["T"]
                        remaining[s] = st["n"] - 1
                        sidx[s] = 1
                        # provisional occupancy for EVERY newly-live
                        # stream (n==1 included): between leaving
                        # _admitting and its step-4 first-token emission
                        # the stream must be visible to the crash
                        # terminator, and slots[] is the only place it
                        # looks.  Step 4 retires n==1/EOS immediately.
                        slots[s] = (st["meta"], st["emit"])
                        newly_live.append(st)
                        self._admitting.remove(st)
                        metrics.gauge(f"llm.serve.slot{s}.occupied", 1.0)
                        _tr(f"prefill complete slot {s}")
                        break

            # 3. dispatch one chunk of per-row paged decode for the live
            # slots (still async).  The chunk length is ALWAYS fw.chunk:
            # a variable tail would compile a fresh 7B program per
            # distinct value.  Streams that finish mid-chunk keep
            # decoding garbage until chunk end (writes stay inside their
            # reserved blocks or drop; outputs are never emitted).
            live = remaining > 0
            toks_dev = None
            em_dev = acc_dev = None
            if live.any():
                t_dec = time.monotonic_ns()
                if self._spec:
                    # one speculative round: draft proposes k tokens,
                    # the target verifies AND COMMITS them in ONE
                    # [slots, k+1]-wide paged step — tok/tok_prev/
                    # positions come back as device values (async
                    # futures; rebinding them here is free), so the
                    # host never re-uploads token state per round.
                    # Step 4's retires re-park pos_dev AFTER this
                    # rebind, so a first-token EOS still wins.
                    props_dev, dprobs_dev, draft_pool = self._propose(
                        d_params, tok_prev, tok, draft_pool, tables,
                        pos_dev, keys_dev)
                    (em_dev, acc_dev, tok, tok_prev, pos_dev,
                     pool) = self._verify(
                        params, tok, tok_prev, props_dev, dprobs_dev,
                        pool, tables, pos_dev, keys_dev)
                    metrics.count("llm.serve.spec_rounds")
                    _tr("spec round dispatched")
                else:
                    toks_dev, tok, pool = self._decode(
                        params, tok, pool, tables, pos, keys_dev,
                        length=fw.chunk)
                    pos[live] += fw.chunk  # parked rows stay parked
                    _tr("chunk dispatched")
                progressed = True
            metrics.gauge("llm.serve.occupancy", float(live.sum()))
            metrics.gauge("llm.serve.free_blocks", float(len(free)))
            metrics.gauge("llm.serve.waiting",
                          float(len(self._waiting) + len(self._admitting)))

            # 4. materialize + emit the admitted first tokens — the
            # device is already computing the chunk, so this sync rides
            # under it; the late joiner's first token leaves here, one
            # dispatch (not one drained queue) after submit.
            for st in newly_live:
                s = st["slot"]
                _tr(f"first-token sync begins slot {s}")
                first = int(np.asarray(st["first"]))
                _tr(f"first-token synced slot {s}")
                tok_h[s] = first
                first_last = st["n"] == 1 or first == eos
                self._emit_token(st["emit"], st["meta"], first, 0,
                                 first_last)
                mark_emit(s)
                if first_last:
                    # n==1 or EOS on token 0: the in-flight chunk's row
                    # decodes garbage that step 5 skips via remaining==0
                    retire(s)

            # 5. deliver the chunk's tokens
            if toks_dev is not None:
                host = np.asarray(toks_dev)  # ONE roundtrip per chunk
                # the decode span closes HERE, at materialization: the
                # jit call above only enqueued the async dispatch, so a
                # span closed there would time host dispatch (~us) and
                # hide the actual device time — the number the trace
                # exists to attribute
                self._span(rec, "serve.decode", t_dec,
                           occupancy=int(live.sum()), chunk=fw.chunk)
                _tr("chunk materialized")
                for j in range(host.shape[1]):
                    for s in np.flatnonzero(live):
                        if remaining[s] == 0:
                            continue  # finished mid-chunk: discard
                        meta, emit = slots[s]
                        tokid = int(host[s, j])
                        last = remaining[s] == 1 or tokid == eos
                        self._emit_token(emit, meta, tokid,
                                         int(sidx[s]), bool(last))
                        mark_emit(int(s))
                        tok_prev_h[s] = tok_h[s]
                        tok_h[s] = tokid
                        sidx[s] += 1
                        remaining[s] -= 1
                        if last:
                            retire(int(s))

            # 5b. speculative emit: the fused verify already accepted
            # and COMMITTED on device (tok/tok_prev/pos_dev rebound at
            # dispatch); the host materializes only the per-slot accept
            # count + the emitted-token rows — one [B] + one [B, k+1]
            # D2H per round, no accept-mask round-trip, no proposal
            # fetch, no token re-upload.  Everything after the first
            # rejection is discarded (its K/V rows get overwritten
            # before they can ever be attended, the same overwrite-
            # before-attend discipline chunked prefill relies on).
            # Host mirrors (tok_h/tok_prev_h/pos) update from the same
            # values, so drain snapshots stay exact.
            if em_dev is not None:
                em_host = np.asarray(em_dev)    # [B, k+1]
                acc_host = np.asarray(acc_dev)  # [B] — one sync
                self._span(rec, "serve.spec_verify", t_dec,
                           occupancy=int(live.sum()), k=fw.spec_k)
                _tr("spec round materialized")
                K = fw.spec_k
                for s in np.flatnonzero(live):
                    s = int(s)
                    if remaining[s] == 0:
                        continue  # retired at its first token (EOS)
                    meta, emit = slots[s]
                    acc = int(acc_host[s])
                    metrics.count("llm.serve.spec_accepted", acc)
                    metrics.count("llm.serve.spec_rejected", K - acc)
                    if K:
                        # accept rate = accepted drafts / proposed (the
                        # +1 bonus/fallback token is not a draft)
                        metrics.gauge("llm.serve.spec_accept_rate",
                                      acc / K)
                        ten = self._slot_tenant[s]
                        if ten is not None:
                            metrics.gauge("llm.serve.spec_accept_rate",
                                          acc / K, tenant=ten)
                    emitted = []
                    finished = False
                    for j in range(acc + 1):
                        tokid = int(em_host[s, j])
                        last = remaining[s] == 1 or tokid == eos
                        # accepted draft tokens vs the target-sampled
                        # bonus/fallback token: the accept/reject path's
                        # pipeline-native surface (tensor_if
                        # compared_value=META_VALUE, tensor_demux
                        # by-meta= — docs/SERVING.md §4c)
                        self._emit_token(
                            emit, meta, tokid, int(sidx[s]), bool(last),
                            extra={"spec_draft": 1 if j < acc else 0})
                        mark_emit(s)
                        emitted.append(tokid)
                        sidx[s] += 1
                        remaining[s] -= 1
                        if last:
                            # retire() re-parks pos_dev, overriding the
                            # in-program advance for this row — device
                            # tok/tok_prev keep stale values there,
                            # which parked rows never read
                            retire(s)
                            finished = True
                            break
                    if not finished:
                        pos[s] += len(emitted)
                        seq = [int(tok_h[s])] + emitted
                        tok_h[s] = seq[-1]
                        tok_prev_h[s] = seq[-2]

            if not progressed:
                with self._idle_lock:
                    if self._pending.empty() and not self._waiting \
                            and not self._admitting and not self._ctl \
                            and not (remaining > 0).any():
                        self._idle.set()
                self._wake.wait(0.02)
                self._wake.clear()
