"""nnstreamer_tpu.filters"""
