"""PyTorch framework sub-plugin (host CPU) + torch->JAX weight import.

Reference analog: ``ext/nnstreamer/tensor_filter/tensor_filter_pytorch.cc``
(SURVEY §2.4) — wraps libtorch TorchScript models.  Here: ``torch`` (CPU
build) executes TorchScript files or registered ``nn.Module`` objects as a
host filter stage.  This is the interop path; the TPU-first route is
importing the weights into a JAX model (:func:`state_dict_to_tree`) so the
model fuses and runs on-device like everything else.

Props:

* ``model`` — path to a TorchScript ``.pt``/``.pth`` file, a registered
  object name (see :func:`register_torch_module`), or an ``nn.Module`` /
  callable passed programmatically;
* ``input``/``inputtype`` on the element supply specs (TorchScript does not
  expose shapes).
"""

from __future__ import annotations

from typing import Callable, Dict, List, Optional, Sequence

import numpy as np

from ..core.registry import register_filter
from ..core.types import TensorsSpec
from .base import Framework, FrameworkError

_registered: Dict[str, object] = {}


def register_torch_module(name: str, module) -> None:
    """Expose an ``nn.Module``/callable to pipelines as ``model=<name>``."""
    _registered[name] = module


@register_filter("torch")
@register_filter("pytorch")
class TorchFramework(Framework):
    name = "torch"

    def __init__(self):
        super().__init__()
        self._mod = None

    def open(self, props: Dict[str, object]) -> None:
        super().open(props)
        try:
            import torch
        except ImportError as e:  # pragma: no cover - torch is baked in here
            raise FrameworkError(f"torch not available: {e}") from e
        model = props.get("model")
        if callable(model) or hasattr(model, "forward"):
            self._mod = model
        elif isinstance(model, str) and model in _registered:
            self._mod = _registered[model]
        elif isinstance(model, str) and model.endswith((".pt", ".pth", ".ts")):
            try:
                self._mod = torch.jit.load(model, map_location="cpu")
            except (OSError, RuntimeError) as e:
                raise FrameworkError(f"cannot load TorchScript {model!r}: {e}") from e
        else:
            raise FrameworkError(
                f"torch framework: model {model!r} is neither a TorchScript "
                f"path, a registered module {sorted(_registered)}, nor a Module"
            )
        if hasattr(self._mod, "eval"):
            self._mod.eval()

    def invoke(self, inputs: Sequence[np.ndarray]) -> List[np.ndarray]:
        import torch

        with torch.no_grad():
            tins = [torch.from_numpy(np.ascontiguousarray(a)) for a in inputs]
            out = self._mod(*tins)
        if isinstance(out, (list, tuple)):
            return [o.detach().cpu().numpy() for o in out]
        return [out.detach().cpu().numpy()]

    def pure_fn(self) -> Optional[Callable]:
        return None  # host-only runtime: not fusable into XLA

    def get_model_info(self):
        return None, None  # TorchScript carries no shape metadata

    def close(self) -> None:
        self._mod = None


# -- torch -> JAX weight import ---------------------------------------------

_EMBED_SEGMENTS = frozenset(
    {"embed", "embedding", "embeddings", "embed_tokens", "tok_embeddings",
     "wte", "wpe"}
)


def state_dict_to_tree(
    state_dict,
    *,
    transpose_linear: bool = True,
    embed_keys: Sequence[str] = (),
) -> Dict[str, np.ndarray]:
    """Convert a torch ``state_dict`` into a flat {name: numpy} tree with
    JAX-conventional layouts: 4-D (conv) weights OIHW -> HWIO, 2-D linear
    weights [out, in] -> [in, out].  Embedding tables ([vocab, dim]) keep
    their layout — transposing them would break token-indexed lookup.
    Embeddings are recognized by EXACT dotted-path segments (``embed``,
    ``embed_tokens``, ``wte``, ...; extend via ``embed_keys``) so linear
    layers that merely contain the substring (GPT-NeoX's ``embed_out`` LM
    head) are still transposed.  The caller maps the flat names onto its
    model's pytree structure.
    """
    embed_names = _EMBED_SEGMENTS | {str(k).lower() for k in embed_keys}
    out: Dict[str, np.ndarray] = {}
    for key, tensor in state_dict.items():
        a = tensor.detach().cpu().numpy() if hasattr(tensor, "detach") else np.asarray(tensor)
        segments = {s for s in key.lower().split(".")}
        if a.ndim == 4:
            a = np.transpose(a, (2, 3, 1, 0))  # OIHW -> HWIO
        elif (
            a.ndim == 2
            and transpose_linear
            and key.endswith(("weight", "w"))
            and not (segments & embed_names)
        ):
            a = a.T
        out[key] = a
    return out
