"""Loadable custom-filter framework: ``framework=custom model=<.so path>``.

Reference analog: ``tensor_filter_custom.c`` (SURVEY §2.3 [UNVERIFIED]) —
dlopen a user-compiled shared object exposing a filter vtable and drive it
as a model; plus ``tensor_filter_cpp.cc`` via the C++ subclass header.
The ABI is ``native/include/nnstpu_custom.h``: the .so exports

    const nnstpu_custom_class *nnstpu_custom_get(void);

This is the "bring a compiled artifact" capability — host-side compute by
construction (raw malloc'd buffers); models that should run on TPU enter
through ``framework=jax`` instead, and the two compose in one pipeline.
"""

from __future__ import annotations

import ctypes
import os
from typing import Dict, List, Optional, Sequence

import numpy as np

from ..core.registry import register_filter
from ..core.types import TensorSpec, TensorsSpec
from .base import Framework, FrameworkError

ABI_VERSION = 1
RANK_LIMIT = 8
TENSOR_LIMIT = 16

#: enum order in nnstpu_custom.h
_DTYPES = [
    np.dtype(np.int8), np.dtype(np.uint8), np.dtype(np.int16),
    np.dtype(np.uint16), np.dtype(np.int32), np.dtype(np.uint32),
    np.dtype(np.int64), np.dtype(np.uint64), np.dtype(np.float16),
    np.dtype(np.float32), np.dtype(np.float64),
]


class _TensorInfo(ctypes.Structure):
    _fields_ = [
        ("rank", ctypes.c_uint32),
        ("dims", ctypes.c_uint64 * RANK_LIMIT),
        ("dtype", ctypes.c_int32),
    ]


class _TensorsInfo(ctypes.Structure):
    _fields_ = [
        ("num", ctypes.c_uint32),
        ("info", _TensorInfo * TENSOR_LIMIT),
    ]


_INIT = ctypes.CFUNCTYPE(ctypes.c_void_p, ctypes.c_char_p)
_FINISH = ctypes.CFUNCTYPE(None, ctypes.c_void_p)
_GETINFO = ctypes.CFUNCTYPE(ctypes.c_int, ctypes.c_void_p,
                            ctypes.POINTER(_TensorsInfo))
_INVOKE = ctypes.CFUNCTYPE(ctypes.c_int, ctypes.c_void_p,
                           ctypes.POINTER(ctypes.c_void_p),
                           ctypes.POINTER(ctypes.c_void_p))


class _CustomClass(ctypes.Structure):
    _fields_ = [
        ("abi_version", ctypes.c_uint32),
        ("init", _INIT),
        ("finish", _FINISH),
        ("get_input_info", _GETINFO),
        ("get_output_info", _GETINFO),
        ("invoke", _INVOKE),
    ]


def _spec_from_info(ti: _TensorsInfo) -> TensorsSpec:
    specs = []
    for i in range(int(ti.num)):
        info = ti.info[i]
        if not (0 <= info.dtype < len(_DTYPES)):
            raise FrameworkError(f"custom filter tensor {i}: bad dtype code "
                                 f"{info.dtype}")
        if not (1 <= info.rank <= RANK_LIMIT):
            raise FrameworkError(f"custom filter tensor {i}: bad rank "
                                 f"{info.rank}")
        shape = tuple(int(info.dims[r]) for r in range(int(info.rank)))
        specs.append(TensorSpec.from_shape(shape, _DTYPES[info.dtype]))
    return TensorsSpec(tuple(specs))


@register_filter("custom", aliases=("custom-so", "cpp"))
class CustomSoFramework(Framework):
    """dlopen'd vtable filter.  ``model`` = path to the .so; the
    ``custom=`` property string is passed verbatim to the filter's init."""

    name = "custom"

    def __init__(self):
        super().__init__()
        self._lib: Optional[ctypes.CDLL] = None
        self._vt: Optional[_CustomClass] = None
        self._priv: Optional[ctypes.c_void_p] = None
        self._in: Optional[TensorsSpec] = None
        self._out: Optional[TensorsSpec] = None

    def open(self, props: Dict[str, object]) -> None:
        super().open(props)
        model = str(props.get("model", ""))
        if not model.endswith(".so") or not os.path.exists(model):
            raise FrameworkError(
                f"custom filter needs an existing .so path, got {model!r}")
        try:
            self._lib = ctypes.CDLL(model)
        except OSError as e:
            raise FrameworkError(f"cannot dlopen {model!r}: {e}") from e
        try:
            get = self._lib.nnstpu_custom_get
        except AttributeError as e:
            raise FrameworkError(
                f"{model!r} exports no nnstpu_custom_get symbol "
                "(see native/include/nnstpu_custom.h)") from e
        get.restype = ctypes.POINTER(_CustomClass)
        vt_ptr = get()
        if not vt_ptr:
            raise FrameworkError(f"{model!r}: nnstpu_custom_get returned NULL")
        vt = vt_ptr.contents
        if int(vt.abi_version) != ABI_VERSION:
            raise FrameworkError(
                f"{model!r}: ABI version {int(vt.abi_version)} != "
                f"{ABI_VERSION}")
        self._vt = vt
        custom = props.get("custom")
        priv = vt.init(str(custom).encode() if custom else None)
        # NULL priv is legal for stateless filters UNLESS init signals
        # failure; the ABI uses NULL for failure, so require non-NULL when
        # the filter was given props to parse.
        self._priv = ctypes.c_void_p(priv)
        if custom and not priv:
            raise FrameworkError(f"{model!r}: init({custom!r}) failed")
        try:
            ti = _TensorsInfo()
            if vt.get_input_info(self._priv, ctypes.byref(ti)) != 0:
                raise FrameworkError(f"{model!r}: get_input_info failed")
            self._in = _spec_from_info(ti)
            to = _TensorsInfo()
            if vt.get_output_info(self._priv, ctypes.byref(to)) != 0:
                raise FrameworkError(f"{model!r}: get_output_info failed")
            self._out = _spec_from_info(to)
        except FrameworkError:
            # framework=auto probes discard failed candidates without
            # close(): release the .so's init-allocated state here.
            vt.finish(self._priv)
            self._vt = None
            self._priv = None
            raise

    def get_model_info(self):
        return self._in, self._out

    def invoke(self, inputs: Sequence[np.ndarray]) -> List[np.ndarray]:
        vt, out_spec = self._vt, self._out
        if vt is None:
            raise FrameworkError("custom filter not opened")
        if len(inputs) != len(self._in):
            raise FrameworkError(
                f"custom filter expects {len(self._in)} inputs, got "
                f"{len(inputs)}")
        arrs = []
        for a, spec in zip(inputs, self._in):
            a = np.ascontiguousarray(np.asarray(a), dtype=spec.dtype)
            if a.size != int(np.prod(spec.shape)):
                raise FrameworkError(
                    f"custom filter input size {a.size} != spec {spec.shape}")
            arrs.append(a)
        outs = [np.empty(s.shape, s.dtype) for s in out_spec]
        in_ptrs = (ctypes.c_void_p * len(arrs))(
            *[a.ctypes.data_as(ctypes.c_void_p).value for a in arrs])
        out_ptrs = (ctypes.c_void_p * len(outs))(
            *[o.ctypes.data_as(ctypes.c_void_p).value for o in outs])
        rc = vt.invoke(self._priv, in_ptrs, out_ptrs)
        if rc != 0:
            raise FrameworkError(f"custom filter invoke failed (rc={rc})")
        return outs

    def close(self) -> None:
        if self._vt is not None and self._priv is not None:
            self._vt.finish(self._priv)
        self._vt = None
        self._priv = None
        self._lib = None


def include_dir() -> str:
    """Directory holding nnstpu_custom.h / nnstpu_cppclass.hh — for user
    build scripts: ``g++ -I$(python -c 'from nnstreamer_tpu.filters import
    custom_so; print(custom_so.include_dir())') ...``"""
    return os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "native", "include")
