"""Framework sub-plugin API for tensor_filter.

Reference analog: ``GstTensorFilterFramework`` vtable in
``nnstreamer_plugin_api_filter.h`` — open/close/invoke_NN/getInputDimension/
getOutputDimension/setInputDimension/getModelInfo/eventHandler (SURVEY §2.3).
Each reference framework (.so per vendor SDK, §2.4) becomes a Python class
registered under KIND_FILTER; the CUDA/NPU zero-copy paths collapse into the
single JAX/PJRT framework (filters/jax_fw.py).

Contract:

* :meth:`open` loads the model named by ``props['model']``.
* :meth:`invoke` maps input arrays -> output arrays (host path; must work on
  numpy inputs).
* :meth:`pure_fn` — TPU-first extension — returns a *pure, traceable* JAX
  function so the planner can fuse the model with surrounding preprocess/
  postprocess stages into one XLA program.  Frameworks that wrap host-only
  code (custom callbacks, external runtimes) return None.
"""

from __future__ import annotations

from typing import Callable, Dict, List, Optional, Sequence, Tuple

from ..core.types import TensorsSpec


class FrameworkError(RuntimeError):
    pass


class Framework:
    """Base class for tensor_filter framework sub-plugins."""

    #: registered name, e.g. "jax", "custom-easy"
    name: str = "base"
    #: whether invoke() accepts batched leading dim natively
    handles_batch: bool = True

    def __init__(self):
        self.props: Dict[str, object] = {}

    # -- lifecycle ---------------------------------------------------------
    def open(self, props: Dict[str, object]) -> None:
        """Load the model; raise FrameworkError when the model prop is
        unusable (framework=auto uses this to fall through the priority
        list)."""
        # Keep the element's own (tracked) dict: reads here and in
        # subclasses count toward the pipeline's unknown-property check.
        self.props = props if isinstance(props, dict) else dict(props)

    def close(self) -> None:
        pass

    # -- model metadata ----------------------------------------------------
    def get_model_info(self) -> Tuple[Optional[TensorsSpec], Optional[TensorsSpec]]:
        """(input spec, output spec); either may be None when the framework
        cannot know (then the element's input/output props must say)."""
        return None, None

    def set_input_spec(self, spec: TensorsSpec) -> None:
        """Reference setInputDimension: reconfigure for a new input shape."""

    # -- execution ---------------------------------------------------------
    def invoke(self, inputs: Sequence) -> List:
        raise NotImplementedError

    def pure_fn(self) -> Optional[Callable]:
        """Optional pure JAX function ``tuple(arrays) -> tuple(arrays)``."""
        return None

    def select_reduced_output(self) -> Optional[str]:
        """Switch the loaded model to its REDUCED output variant, when one
        exists (``ModelBundle.reduced_variant`` — e.g. deeplab's
        native-stride score map).  Called by tensor_filter during caps
        negotiation, only after the HBM-residency planner proved every
        downstream consumer admits the reduced geometry
        (pipeline/residency.py, docs/FETCH.md).  Returns a human-readable
        description of the switch, or None when the model has no reduced
        form.  Default: none."""
        return None

    # -- abstract execution (nns-lint --deep) -------------------------------
    def abstract_invoke(self, in_sds: Sequence) -> Optional[List]:
        """Trace the model SYMBOLICALLY: map input ``jax.ShapeDtypeStruct``s
        to output ShapeDtypeStructs via :func:`jax.eval_shape` — no device
        dispatch, no buffer ever materializes.  The deep analysis pass
        (``analysis/tracecheck.py``) uses this to check the model's *actual*
        traced output shapes/dtypes against its declared spec before a
        pipeline ever starts.  Default: eval_shape over :meth:`pure_fn`;
        frameworks whose params are heavyweight override to abstract the
        params too (see jax_fw).  Returns None when the framework has no
        traceable path (host-only runtimes, streaming decode loops)."""
        fn = self.pure_fn()
        if fn is None:
            return None
        import jax

        out = jax.eval_shape(lambda xs: fn(xs), tuple(in_sds))
        if not isinstance(out, (tuple, list)):
            out = (out,)
        return list(out)

    def param_bytes(self) -> int:
        """Bytes of model parameters resident in device memory while the
        pipeline runs (0 = none / unknown).  Feeds the deep pass's static
        HBM high-water estimate."""
        return 0

    # -- nns-xray (docs/OBSERVABILITY.md "Predicted vs actual") -------------
    def attach_xray(self, registry, stage: str, rec=None) -> None:
        """Hand the framework the owning pipeline's program registry (the
        ``_trace_rec`` handoff pattern): ``stage`` is the element name
        compiles are counted under, ``rec`` the pipeline's flight
        recorder for the device track.  Subclasses with jitted paths
        override to wrap them via ``registry.track`` — the base just
        stores the handles for lazily-built programs (the llm serve
        loop).  Never called when xray is off: the disabled path stays
        one pointer check at the element."""
        self._xray = registry
        self._xray_stage = stage
        self._xray_rec = rec

    # -- nns-learn: train-while-serve param hot-swap ------------------------
    def swap_params(self, tree) -> None:
        """Replace the live parameter tree with ``tree`` as a VALUE move
        — same tree structure, same per-leaf shapes/dtypes, so the
        compiled programs' abstract signatures are untouched and NOTHING
        recompiles (docs/TRAINING.md "Hot-swap").  Raises
        :class:`FrameworkError` when this framework's dispatch path
        bakes params into closures (swap would silently not take) or
        the tree does not match."""
        raise FrameworkError(
            f"{self.name} framework does not support param hot-swap")

    # -- events ------------------------------------------------------------
    def handle_event(self, kind: str, payload=None) -> None:
        """Reference eventHandler (model reload etc.)."""


def place_swapped_params(current, tree):
    """Validate + place one hot-swap tree against the LIVE params
    (the one walk every ``Framework.swap_params`` shares): structure and
    per-leaf shape/dtype must match exactly (a mismatch raises
    :class:`FrameworkError` naming the first offending leaf), and each
    new leaf is copied onto the corresponding live leaf's placement —
    a FRESH buffer per leaf (``jnp.array(copy=True)``), never an alias,
    so a trainer that later DONATES its own params through an update
    step cannot invalidate the serving copy."""
    import jax
    import jax.numpy as jnp

    cur_leaves, cur_def = jax.tree_util.tree_flatten(current)
    new_leaves, new_def = jax.tree_util.tree_flatten(tree)
    if cur_def != new_def:
        raise FrameworkError(
            f"swap_params tree structure mismatch: got {new_def}, "
            f"serving {cur_def}")
    placed = []
    for i, (c, n) in enumerate(zip(cur_leaves, new_leaves)):
        c_shape = tuple(getattr(c, "shape", ()) or ())
        n_shape = tuple(getattr(n, "shape", ()) or ())
        c_dt = getattr(c, "dtype", None)
        n_dt = getattr(n, "dtype", None)
        if c_shape != n_shape or str(c_dt) != str(n_dt):
            raise FrameworkError(
                f"swap_params leaf {i} mismatch: got "
                f"{list(n_shape)}{n_dt}, serving {list(c_shape)}{c_dt} "
                "— hot-swap is a value move, shapes/dtypes are frozen")
        sh = getattr(c, "sharding", None)
        if sh is None:
            # live leaf is HOST numpy (some trees mix host norms with
            # device mats): keep it numpy — jit's fast path keys on
            # argument type, and a jax-array copy here would mint a
            # second cache entry (census drift) despite identical avals
            import numpy as _np

            placed.append(_np.array(_np.asarray(n), copy=True))
            continue
        fresh = jnp.array(n, copy=True)
        # match the live leaf's COMMITTED-ness too — same cache-key rule
        if bool(getattr(c, "committed", False)):
            fresh = jax.device_put(fresh, sh)
        placed.append(fresh)
    return jax.tree_util.tree_unflatten(cur_def, placed)


def tree_param_bytes(tree) -> int:
    """Total bytes of a params pytree's leaves — ``nbytes`` when the
    leaf carries it, shape x dtype itemsize otherwise (lazy/proxy
    leaves).  The ONE accounting walk shared by the frameworks'
    ``param_bytes`` hooks and nns-xray's measured HBM ledger."""
    import jax
    import numpy as _np

    total = 0
    for leaf in jax.tree_util.tree_leaves(tree):
        nb = getattr(leaf, "nbytes", None)
        if nb is None and hasattr(leaf, "shape") and hasattr(leaf, "dtype"):
            nb = int(_np.prod(leaf.shape)) * _np.dtype(leaf.dtype).itemsize
        total += int(nb or 0)
    return total


def parse_custom_options(custom: str) -> Dict[str, str]:
    """Parse the tensor_filter ``custom=key:val,key2:val2`` option string."""
    out: Dict[str, str] = {}
    for part in str(custom or "").split(","):
        part = part.strip()
        if not part:
            continue
        if ":" in part:
            k, v = part.split(":", 1)
            out[k.strip()] = v.strip()
        else:
            out[part] = "true"
    return out


def parse_accelerator(acc: str) -> List[str]:
    """Parse ``accelerator=true:tpu,cpu`` into an ordered device preference
    list (reference: hw accel string in tensor_filter_common.c)."""
    s = str(acc or "").strip()
    if not s or s.lower() in ("false", "none"):
        return []
    if ":" in s:
        flag, devs = s.split(":", 1)
        if flag.lower() == "false":
            return []
        return [d.strip() for d in devs.split(",") if d.strip()]
    return []
