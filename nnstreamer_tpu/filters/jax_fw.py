"""The JAX/PJRT framework: TPU-native model execution for tensor_filter.

This is the component the north star names: the replacement for the
reference's TensorRT/SNPE/EdgeTPU CUDA/NPU sub-plugins
(``ext/nnstreamer/tensor_filter/tensor_filter_tensorrt.cc`` with its
``cudaMallocManaged`` zero-copy path — SURVEY §2.4).  Differences by design:

* models are pure JAX programs (from the zoo, an import string, or a bundle
  object) compiled once by XLA; no per-vendor runtime;
* zero-copy: invoke keeps outputs as jax Arrays in HBM; when the element is
  fused (pure_fn), inputs never materialize on host at all;
* ``accelerator=true:tpu`` etc. maps to jax device selection; bfloat16
  execution via ``custom=dtype:bfloat16``;
* batching: the model's leading dim is the batch dim (NHWC video batches map
  straight onto the MXU).
"""

from __future__ import annotations

from typing import Callable, List, Optional

import numpy as np

from ..core.log import logger
from ..core.registry import register_filter
from ..core.types import TensorsSpec
from ..models.zoo import ModelBundle, build as build_model
from .base import Framework, FrameworkError, parse_custom_options

log = logger(__name__)


@register_filter("jax", aliases=("tpu-xla", "xla", "pjrt"))
class JaxFramework(Framework):
    name = "jax"

    def __init__(self):
        super().__init__()
        self.bundle: Optional[ModelBundle] = None
        self._jitted: Optional[Callable] = None
        self._device = None

    def open(self, props):
        super().open(props)
        model = props.get("model")
        if model in (None, ""):
            raise FrameworkError("jax framework needs model=<zoo name|module:attr>")
        opts = parse_custom_options(str(props.get("custom", "")))
        try:
            self.bundle = build_model(model, opts)
        except KeyError as e:
            raise FrameworkError(str(e)) from e
        except ImportError as e:
            raise FrameworkError(f"cannot import model {model!r}: {e}") from e

        import jax

        accel = [a.lower() for a in _accel_list(props)]
        if accel:
            for kind in accel:
                devs = [d for d in jax.devices() if kind in d.platform.lower()]
                if devs:
                    self._device = devs[0]
                    break

        apply_fn = self.bundle.apply_fn
        params = self.bundle.params
        if self._device is not None:
            params = jax.device_put(params, self._device)
            self.bundle.params = params

        def run(*inputs):
            out = apply_fn(params, *inputs)
            return out if isinstance(out, (tuple, list)) else (out,)

        self._jitted = jax.jit(run)

    def close(self):
        self.bundle = None
        self._jitted = None

    def get_model_info(self):
        if self.bundle is None:
            return None, None
        return self.bundle.in_spec, self.bundle.out_spec

    def invoke(self, inputs) -> List:
        import jax.numpy as jnp

        arrays = [jnp.asarray(x) for x in inputs]
        outs = self._jitted(*arrays)
        return list(outs)

    def pure_fn(self):
        if self.bundle is None:
            return None
        apply_fn = self.bundle.apply_fn
        params = self.bundle.params

        def fn(arrays):
            out = apply_fn(params, *arrays)
            return out if isinstance(out, tuple) else (
                tuple(out) if isinstance(out, list) else (out,)
            )

        return fn


def _accel_list(props) -> List[str]:
    from .base import parse_accelerator

    return parse_accelerator(str(props.get("accelerator", "")))
