"""The JAX/PJRT framework: TPU-native model execution for tensor_filter.

This is the component the north star names: the replacement for the
reference's TensorRT/SNPE/EdgeTPU CUDA/NPU sub-plugins
(``ext/nnstreamer/tensor_filter/tensor_filter_tensorrt.cc`` with its
``cudaMallocManaged`` zero-copy path — SURVEY §2.4).  Differences by design:

* models are pure JAX programs (from the zoo, an import string, or a bundle
  object) compiled once by XLA; no per-vendor runtime;
* zero-copy: invoke keeps outputs as jax Arrays in HBM; when the element is
  fused (pure_fn), inputs never materialize on host at all;
* ``accelerator=true:tpu`` etc. maps to jax device selection; bfloat16
  execution via ``custom=dtype:bfloat16``;
* batching: the model's leading dim is the batch dim (NHWC video batches map
  straight onto the MXU);
* multi-chip: ``mesh=data:N`` (element prop or ``custom=mesh:data:N``)
  shards the batch dim over an N-device ``data`` mesh axis — the north
  star's "query layer shards camera-stream batches over ICI"; params are
  replicated and XLA places the collectives.
"""

from __future__ import annotations

from typing import Callable, List, Optional

import numpy as np

from ..core.log import logger
from ..core.registry import register_filter
from ..core.types import TensorsSpec
from ..models.zoo import ModelBundle, build as build_model
from .base import Framework, FrameworkError, parse_custom_options

log = logger(__name__)


@register_filter("jax", aliases=("tpu-xla", "xla", "pjrt"))
class JaxFramework(Framework):
    name = "jax"

    def __init__(self):
        super().__init__()
        self.bundle: Optional[ModelBundle] = None
        self._jitted: Optional[Callable] = None
        self._device = None

    def open(self, props):
        super().open(props)
        model = props.get("model")
        if model in (None, ""):
            raise FrameworkError("jax framework needs model=<zoo name|module:attr>")
        opts = parse_custom_options(str(props.get("custom", "")))
        mesh_prop = str(props.get("mesh", "") or "")
        mesh_custom = str(opts.pop("mesh", "") or "")
        if mesh_prop and mesh_custom and mesh_prop != mesh_custom:
            raise FrameworkError(
                f"conflicting mesh specs: prop mesh={mesh_prop!r} vs "
                f"custom=mesh:{mesh_custom!r}")
        mesh_spec = mesh_prop or mesh_custom
        try:
            self.bundle = build_model(model, opts)
        except KeyError as e:
            raise FrameworkError(str(e)) from e
        except ImportError as e:
            raise FrameworkError(f"cannot import model {model!r}: {e}") from e

        import jax

        accel = [a.lower() for a in _accel_list(props)]
        if accel:
            for kind in accel:
                devs = [d for d in jax.devices() if kind in d.platform.lower()]
                if devs:
                    self._device = devs[0]
                    break

        params = self.bundle.params
        if self._device is not None:
            params = jax.device_put(params, self._device)
            self.bundle.params = params
        #: params commit to jax arrays at FIRST invoke, not here: the
        #: deep pass opens frameworks to learn model I/O and must stay
        #: zero-dispatch (jnp.asarray transfers) — see _commit_params
        self._committed = self._device is not None

        self._sharding = None
        if mesh_spec:
            self._setup_mesh(mesh_spec, params)
        self._rebuild_jitted()

    def _rebuild_jitted(self):
        """(Re)build the standalone jitted path over the CURRENT bundle —
        one implementation shared by open() and select_reduced_output()
        so dispatch-path changes apply to both.

        Params are an ARGUMENT of the jitted program, not a closure
        capture: jit caches on the abstract signature, so
        :meth:`swap_params` replacing the tree with aval-identical
        leaves is a pure VALUE move — the standing program serves the
        new weights with ZERO recompiles (nns-learn's train-while-serve
        contract, docs/TRAINING.md).  The fused/batched paths still
        close over params (``pure_fn``) — those snapshot weights at
        build time and are not hot-swappable."""
        import jax

        apply_fn = self.bundle.apply_fn
        constrain = self._constrain

        def run(params, *inputs):
            out = apply_fn(params, *constrain(inputs))
            return out if isinstance(out, (tuple, list)) else (out,)

        self._jitted = jax.jit(run)
        self._wrap_xray()

    def attach_xray(self, registry, stage, rec=None):
        super().attach_xray(registry, stage, rec)
        self._wrap_xray()

    def _wrap_xray(self):
        """nns-xray: the standalone invoke program registers its compiles
        under the element's stage name (re-applied across reload /
        reduced-output rebuilds; track() is idempotent)."""
        xr = getattr(self, "_xray", None)
        if xr is not None and self._jitted is not None:
            self._jitted = xr.track(
                self._jitted, getattr(self, "_xray_stage", self.name),
                "stage", rec=getattr(self, "_xray_rec", None))

    def _constrain(self, arrays):
        """Apply the data-parallel sharding constraint to every input (one
        implementation shared by the standalone and fused paths)."""
        if self._sharding is None:
            return tuple(arrays)
        import jax

        return tuple(
            jax.lax.with_sharding_constraint(x, self._sharding)
            for x in arrays
        )

    def _setup_mesh(self, spec: str, params) -> None:
        """``data:N`` — batch-dim sharding over an ICI mesh; params are
        replicated explicitly so every chip holds a copy."""
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        from ..parallel.mesh import make_mesh

        parts = spec.split(":")
        axis = parts[0] or "data"
        if axis != "data":
            raise FrameworkError(
                f"jax framework shards the batch dim only (mesh=data:N); "
                f"got axis {axis!r} — model/tensor parallel belongs to the "
                "llm framework (custom=tp:N)"
            )
        try:
            n = int(parts[1]) if len(parts) > 1 else len(jax.devices())
        except ValueError:
            raise FrameworkError(
                f"bad mesh spec {spec!r}: expected data:N") from None
        if len(jax.devices()) < n:
            raise FrameworkError(
                f"mesh=data:{n} needs {n} devices, have {len(jax.devices())}")
        mesh = make_mesh(data=n, devices=jax.devices()[:n])
        self._sharding = NamedSharding(mesh, P("data"))
        replicated = NamedSharding(mesh, P())
        self.bundle.params = jax.device_put(params, replicated)
        self._committed = True

    def swap_params(self, tree) -> None:
        """Hot-swap the live weights (nns-learn train-while-serve): the
        tree must match the serving bundle's structure and per-leaf
        avals exactly; each leaf is copied onto the live leaf's
        placement (mesh replication / device selection carries over).
        Because the standalone jitted path takes params as an argument,
        the swap is a VALUE move — zero recompiles, pinned by test.
        Callers serialize against in-flight invokes (the element holds
        ``_fw_lock``)."""
        if self.bundle is None:
            raise FrameworkError("framework is not open")
        from .base import place_swapped_params

        # the live leaves' shardings already encode accelerator= device
        # selection AND mesh replication — the shared placement walk
        # copies onto them, so both carry over
        self.bundle.params = place_swapped_params(self.bundle.params, tree)

    def select_reduced_output(self):
        """Swap in the bundle's reduced output variant (residency planner
        contract, filters/base.py).  The variant thunk shares the live
        bundle's params — device placement / mesh replication applied at
        open() carries over — so only the apply closure and out spec
        change; the standalone jitted path is rebuilt over them."""
        b = self.bundle
        if b is None or b.reduced_variant is None:
            return None
        desc = b.reduced_desc or "reduced output"
        self.bundle = b.reduced_variant()
        self._rebuild_jitted()
        return desc

    def close(self):
        self.bundle = None
        self._jitted = None

    def get_model_info(self):
        if self.bundle is None:
            return None, None
        return self.bundle.in_spec, self.bundle.out_spec

    def _commit_params(self) -> None:
        """Commit params to device arrays ONCE, at first dispatch (the
        serve loop's carried-state discipline): jit's fast path keys on
        argument TYPE, so a swap_params replacing numpy leaves with jax
        arrays would otherwise mint a second cache entry and break the
        zero-recompile census pin.  Deferred off open() so the deep
        pass's framework probing stays zero-dispatch."""
        import jax
        import jax.numpy as jnp

        self.bundle.params = jax.tree_util.tree_map(
            lambda a: jnp.asarray(a) if hasattr(a, "shape") else a,
            self.bundle.params)
        self._committed = True

    def invoke(self, inputs) -> List:
        import jax.numpy as jnp

        if not self._committed:
            self._commit_params()
        if self._device is not None:
            # accelerator= selected a non-default device: params were
            # placed there at open(), so inputs must follow — a bare
            # asarray lands on the default device and the invoke pays a
            # cross-device transfer (or fails outright on backends
            # without implicit transfers) per buffer
            import jax

            arrays = [jax.device_put(x, self._device) for x in inputs]
        else:
            arrays = [jnp.asarray(x) for x in inputs]
        outs = self._jitted(self.bundle.params, *arrays)
        return list(outs)

    def pure_fn(self):
        if self.bundle is None:
            return None
        apply_fn = self.bundle.apply_fn
        params = self.bundle.params
        constrain = self._constrain

        def fn(arrays):
            out = apply_fn(params, *constrain(arrays))
            return out if isinstance(out, tuple) else (
                tuple(out) if isinstance(out, list) else (out,)
            )

        return fn

    # -- abstract execution (nns-lint --deep) -------------------------------
    def abstract_invoke(self, in_sds):
        """eval_shape through ``apply_fn`` with the params ALSO abstracted
        (``jax.ShapeDtypeStruct`` per leaf): the trace sees only shapes, so
        even a multi-GiB checkpoint costs nothing here and a bundle whose
        params were never materialized (lazy loaders) still traces.  The
        sharding constraint is skipped — it is shape-preserving and needs a
        live mesh."""
        if self.bundle is None:
            return None
        import jax

        apply_fn = self.bundle.apply_fn
        p_sds = jax.tree_util.tree_map(
            lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype)
            if hasattr(a, "shape") and hasattr(a, "dtype") else a,
            self.bundle.params)

        def run(p, xs):
            out = apply_fn(p, *xs)
            return out if isinstance(out, (tuple, list)) else (out,)

        out = jax.eval_shape(run, p_sds, tuple(in_sds))
        return list(out)

    def param_bytes(self) -> int:
        if self.bundle is None:
            return 0
        from .base import tree_param_bytes

        return tree_param_bytes(self.bundle.params)


def _accel_list(props) -> List[str]:
    from .base import parse_accelerator

    return parse_accelerator(str(props.get("accelerator", "")))
