"""custom-easy framework: register a Python callable as a model.

Reference analog: ``tensor_filter_custom_easy.c`` — "register a C callback as
a model, in-process, no .so — heavily used by tests as a fake framework"
(SURVEY §2.3).  Same role here: tests exercise the entire filter machinery
with passthrough/scale callables and no real model.

API::

    from nnstreamer_tpu.filters.custom_easy import register_custom_easy

    register_custom_easy(
        "scale2", lambda ins: [ins[0] * 2],
        in_spec=TensorsSpec.from_string("3:4:4:1", "float32"),
        out_spec=TensorsSpec.from_string("3:4:4:1", "float32"),
        jax_traceable=True,   # lets the planner fuse it
    )
    ... tensor_filter framework=custom-easy model=scale2 ...
"""

from __future__ import annotations

import threading
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from ..core.registry import register_filter
from ..core.types import TensorsSpec
from .base import Framework, FrameworkError

#: name -> (fn, in_spec, out_spec, jax_traceable, param_bytes)
_models: Dict[str, Tuple[Callable, Optional[TensorsSpec],
                         Optional[TensorsSpec], bool, int]] = {}
_lock = threading.Lock()


def register_custom_easy(
    name: str,
    fn: Callable[[Sequence], List],
    in_spec: Optional[TensorsSpec] = None,
    out_spec: Optional[TensorsSpec] = None,
    jax_traceable: bool = False,
    param_bytes: int = 0,
) -> None:
    """Register ``fn(list_of_arrays) -> list_of_arrays`` as model ``name``.

    ``param_bytes`` declares device-resident weight bytes the callable
    closes over, feeding the deep analyzer's static HBM estimate (0 =
    none/unknown).
    """
    with _lock:
        _models[name] = (fn, in_spec, out_spec, jax_traceable,
                         int(param_bytes))


def unregister_custom_easy(name: str) -> bool:
    with _lock:
        return _models.pop(name, None) is not None


@register_filter("custom-easy")
class CustomEasyFramework(Framework):
    name = "custom-easy"

    def __init__(self):
        super().__init__()
        self._fn: Optional[Callable] = None
        self._in: Optional[TensorsSpec] = None
        self._out: Optional[TensorsSpec] = None
        self._traceable = False
        self._param_bytes = 0

    def open(self, props):
        super().open(props)
        model = props.get("model")
        key = str(model)
        with _lock:
            entry = _models.get(key)
        if entry is None:
            if callable(model):  # allow passing the callable directly
                self._fn, self._in, self._out, self._traceable = model, None, None, False
                return
            raise FrameworkError(f"no custom-easy model registered as {key!r}")
        (self._fn, self._in, self._out, self._traceable,
         self._param_bytes) = entry

    def get_model_info(self):
        return self._in, self._out

    def set_input_spec(self, spec: TensorsSpec) -> None:
        if self._in is None:
            self._in = spec

    def invoke(self, inputs):
        return list(self._fn(list(inputs)))

    def pure_fn(self):
        if not self._traceable:
            return None
        fn = self._fn
        return lambda arrays: tuple(fn(list(arrays)))

    def param_bytes(self) -> int:
        # declared at registration; abstract_invoke inherits the base
        # eval_shape-over-pure_fn path (non-traceable models return None
        # there, so the deep pass never executes host-only callables)
        return self._param_bytes
