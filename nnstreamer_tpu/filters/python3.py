"""python3 framework: user script as a model.

Reference analog: ``tensor_filter_python3.cc`` + helper (SURVEY §2.4):
embedded CPython running a user class with ``invoke``/``getInputDimension``.
Here the script is named ``model=module.path:attr`` where attr is either

* a class: instantiated; must provide ``invoke(list) -> list`` and may
  provide ``in_spec``/``out_spec`` attributes (TensorsSpec) or
  ``get_spec() -> (in_spec, out_spec)``;
* a plain callable: ``fn(list_of_arrays) -> list_of_arrays``.

(No GIL gymnastics needed: we *are* Python; numpy bridging is the native
data model.)
"""

from __future__ import annotations

import importlib
from typing import Optional

from ..core.registry import register_filter
from ..core.types import TensorsSpec
from .base import Framework, FrameworkError


@register_filter("python3", aliases=("python",))
class Python3Framework(Framework):
    name = "python3"

    def __init__(self):
        super().__init__()
        self._obj = None
        self._in: Optional[TensorsSpec] = None
        self._out: Optional[TensorsSpec] = None

    def open(self, props):
        super().open(props)
        target = str(props.get("model", ""))
        if ":" not in target:
            raise FrameworkError("python3 framework needs model=module.path:attr")
        mod_name, attr = target.split(":", 1)
        try:
            mod = importlib.import_module(mod_name)
        except ImportError as e:
            raise FrameworkError(f"cannot import {mod_name!r}: {e}") from e
        try:
            obj = getattr(mod, attr)
        except AttributeError as e:
            raise FrameworkError(str(e)) from e
        if isinstance(obj, type):
            obj = obj()
        if not callable(obj) and not hasattr(obj, "invoke"):
            raise FrameworkError(f"{target} is neither callable nor has .invoke")
        self._obj = obj
        if hasattr(obj, "get_spec"):
            self._in, self._out = obj.get_spec()
        else:
            self._in = getattr(obj, "in_spec", None)
            self._out = getattr(obj, "out_spec", None)

    def get_model_info(self):
        return self._in, self._out

    def set_input_spec(self, spec):
        if self._in is None:
            self._in = spec
        if hasattr(self._obj, "set_input_spec"):
            self._obj.set_input_spec(spec)

    def invoke(self, inputs):
        if hasattr(self._obj, "invoke"):
            return list(self._obj.invoke(list(inputs)))
        return list(self._obj(list(inputs)))
