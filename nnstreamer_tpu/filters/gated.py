"""Gated framework sub-plugins for runtimes absent in this environment.

Reference analog: the reference gates every vendor sub-plugin behind meson
build options (SURVEY §5.6); a framework that wasn't built simply isn't on
disk.  The TPU build registers the names so ``framework=onnxruntime`` etc.
resolve to a clear "runtime not installed" error — or work, when the
import succeeds (these wrappers are complete, just environment-gated).
"""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence

import numpy as np

from ..core.registry import register_filter
from .base import Framework, FrameworkError


@register_filter("onnxruntime")
@register_filter("onnx")
class OnnxRuntimeFramework(Framework):
    """ONNX Runtime wrapper (reference: tensor_filter_onnxruntime.cc)."""

    name = "onnxruntime"

    def __init__(self):
        super().__init__()
        self._sess = None
        self._in_names: List[str] = []

    def open(self, props: Dict[str, object]) -> None:
        super().open(props)
        try:
            import onnxruntime as ort
        except ImportError as e:
            raise FrameworkError(
                "onnxruntime is not installed in this environment; convert "
                "the model to JAX (framework=jax) or install onnxruntime"
            ) from e
        model = str(props.get("model", ""))
        try:
            self._sess = ort.InferenceSession(model, providers=["CPUExecutionProvider"])
        except Exception as e:  # noqa: BLE001 - ort raises its own hierarchy
            raise FrameworkError(f"cannot load ONNX model {model!r}: {e}") from e
        self._in_names = [i.name for i in self._sess.get_inputs()]

    def invoke(self, inputs: Sequence[np.ndarray]) -> List[np.ndarray]:
        feed = {n: np.ascontiguousarray(a) for n, a in zip(self._in_names, inputs)}
        return list(self._sess.run(None, feed))

    def close(self) -> None:
        self._sess = None


@register_filter("tensorflow-lite")
@register_filter("tensorflow1-lite")
@register_filter("tensorflow2-lite")
class TFLiteFramework(Framework):
    """TFLite interpreter wrapper (reference: tensor_filter_tensorflow_lite.cc,
    the reference's default benchmark path)."""

    name = "tensorflow-lite"

    def __init__(self):
        super().__init__()
        self._interp = None

    def open(self, props: Dict[str, object]) -> None:
        super().open(props)
        interp_cls = None
        try:
            from tflite_runtime.interpreter import Interpreter as interp_cls  # noqa: N813
        except ImportError:
            try:
                from tensorflow.lite import Interpreter as interp_cls  # noqa: N813
            except ImportError:
                pass
        if interp_cls is None:
            raise FrameworkError(
                "no TFLite runtime in this environment; convert the model to "
                "JAX (framework=jax) or install tflite_runtime/tensorflow"
            )
        model = str(props.get("model", ""))
        try:
            self._interp = interp_cls(model_path=model)
            self._interp.allocate_tensors()
        except (OSError, ValueError, RuntimeError) as e:
            raise FrameworkError(f"cannot load TFLite model {model!r}: {e}") from e

    def invoke(self, inputs: Sequence[np.ndarray]) -> List[np.ndarray]:
        interp = self._interp
        for detail, a in zip(interp.get_input_details(), inputs):
            interp.set_tensor(detail["index"], np.ascontiguousarray(a))
        interp.invoke()
        return [interp.get_tensor(d["index"]) for d in interp.get_output_details()]

    def get_model_info(self):
        if self._interp is None:
            return None, None
        from ..core.types import TensorSpec, TensorsSpec

        def spec_of(details):
            return TensorsSpec(
                tuple(
                    TensorSpec.from_shape(tuple(d["shape"]), d["dtype"], d.get("name", ""))
                    for d in details
                )
            )

        return (
            spec_of(self._interp.get_input_details()),
            spec_of(self._interp.get_output_details()),
        )

    def close(self) -> None:
        self._interp = None
