"""nnstreamer_tpu.native"""
