"""Native (C++) runtime support, loaded via ctypes.

Reference analog: the reference keeps its transport (nnstreamer-edge, C),
buffer pools, and per-frame repack loops native (SURVEY §2.7, §7 "Native
where the reference is native").  This package compiles ``src/nnstpu.cpp``
with the system toolchain on first use (cached by source hash) and exposes:

* :func:`crc32` — wire-frame integrity checksum;
* :func:`strip_stride` — video rowstride removal into a contiguous frame;
* :func:`wire_gather` — single-copy frame assembly (length prefix + crc);
* :class:`ShmRing` — SPSC shared-memory ring for zero-copy same-host
  pipeline hand-off (GStreamer shmsink/shmsrc analog).

Everything degrades gracefully: :func:`available` is False when no
compiler exists and callers fall back to pure Python.
"""

from __future__ import annotations

import ctypes
import hashlib
import os
import subprocess
import threading
from typing import Optional

import numpy as np

_SRC = os.path.join(os.path.dirname(__file__), "src", "nnstpu.cpp")
_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_load_failed = False


def _cache_dir() -> str:
    base = os.environ.get("NNSTPU_CACHE", "") or os.path.join(
        os.path.expanduser("~"), ".cache", "nnstpu"
    )
    os.makedirs(base, exist_ok=True)
    return base


def _build() -> Optional[str]:
    with open(_SRC, "rb") as f:
        digest = hashlib.sha256(f.read()).hexdigest()[:16]
    out = os.path.join(_cache_dir(), f"libnnstpu-{digest}.so")
    if os.path.exists(out):
        return out
    tmp = out + f".tmp{os.getpid()}"
    cmd = [
        "g++", "-O3", "-shared", "-fPIC", "-std=c++17", "-o", tmp, _SRC, "-lrt",
    ]
    try:
        subprocess.run(cmd, check=True, capture_output=True, timeout=120)
    except (OSError, subprocess.SubprocessError):
        return None
    os.replace(tmp, out)  # atomic: concurrent builders race safely
    return out


def _load(block: bool = True) -> Optional[ctypes.CDLL]:
    global _lib, _load_failed
    if _lib is not None or _load_failed:
        return _lib
    # Hot-path callers pass block=False: while another thread (prewarm) holds
    # the lock for the up-to-120s first compile, they get None immediately and
    # use their pure-Python fallback instead of stalling the stream.
    if not _lock.acquire(blocking=block):
        return None
    try:
        if _lib is not None or _load_failed:
            return _lib
        path = _build()
        if path is None:
            _load_failed = True
            return None
        lib = ctypes.CDLL(path)
        u8p = ctypes.POINTER(ctypes.c_uint8)
        lib.nns_crc32.restype = ctypes.c_uint32
        lib.nns_crc32.argtypes = [u8p, ctypes.c_uint64, ctypes.c_uint32]
        lib.nns_strip_stride.restype = None
        lib.nns_strip_stride.argtypes = [u8p, u8p, ctypes.c_uint64, ctypes.c_uint64, ctypes.c_uint64]
        lib.nns_wire_frame_size.restype = ctypes.c_uint64
        lib.nns_wire_frame_size.argtypes = [ctypes.POINTER(ctypes.c_uint64), ctypes.c_uint32]
        lib.nns_wire_gather.restype = None
        lib.nns_wire_gather.argtypes = [
            ctypes.POINTER(u8p), ctypes.POINTER(ctypes.c_uint64), ctypes.c_uint32, u8p,
        ]
        lib.nns_wire_check.restype = ctypes.c_int
        lib.nns_wire_check.argtypes = [u8p, ctypes.c_uint64, ctypes.c_uint32]
        lib.nns_ring_create.restype = ctypes.c_void_p
        lib.nns_ring_create.argtypes = [ctypes.c_char_p, ctypes.c_uint32, ctypes.c_uint64]
        lib.nns_ring_open.restype = ctypes.c_void_p
        lib.nns_ring_open.argtypes = [ctypes.c_char_p]
        lib.nns_ring_slot_bytes.restype = ctypes.c_uint64
        lib.nns_ring_slot_bytes.argtypes = [ctypes.c_void_p]
        lib.nns_ring_nslots.restype = ctypes.c_uint32
        lib.nns_ring_nslots.argtypes = [ctypes.c_void_p]
        lib.nns_ring_acquire.restype = u8p
        lib.nns_ring_acquire.argtypes = [ctypes.c_void_p]
        lib.nns_ring_commit.restype = ctypes.c_int
        lib.nns_ring_commit.argtypes = [ctypes.c_void_p, ctypes.c_uint64]
        lib.nns_ring_peek.restype = u8p
        lib.nns_ring_peek.argtypes = [ctypes.c_void_p, ctypes.POINTER(ctypes.c_uint64)]
        lib.nns_ring_release.restype = None
        lib.nns_ring_release.argtypes = [ctypes.c_void_p]
        lib.nns_ring_closed.restype = ctypes.c_int
        lib.nns_ring_closed.argtypes = [ctypes.c_void_p]
        lib.nns_ring_close.restype = None
        lib.nns_ring_close.argtypes = [ctypes.c_void_p]
        lib.nns_ring_free.restype = None
        lib.nns_ring_free.argtypes = [ctypes.c_void_p]
        lib.nns_v4l2_open.restype = ctypes.c_void_p
        lib.nns_v4l2_open.argtypes = [
            ctypes.c_char_p, ctypes.POINTER(ctypes.c_int),
            ctypes.POINTER(ctypes.c_int), ctypes.POINTER(ctypes.c_uint32),
            ctypes.c_int, ctypes.c_char_p, ctypes.c_int,
        ]
        lib.nns_v4l2_frame_bytes.restype = ctypes.c_long
        lib.nns_v4l2_frame_bytes.argtypes = [ctypes.c_void_p]
        lib.nns_v4l2_stride.restype = ctypes.c_long
        lib.nns_v4l2_stride.argtypes = [ctypes.c_void_p]
        lib.nns_v4l2_capture.restype = ctypes.c_long
        lib.nns_v4l2_capture.argtypes = [
            ctypes.c_void_p, u8p, ctypes.c_uint64, ctypes.c_int,
        ]
        lib.nns_v4l2_close.restype = None
        lib.nns_v4l2_close.argtypes = [ctypes.c_void_p]
        _lib = lib
        return _lib
    finally:
        _lock.release()


def available() -> bool:
    return _load() is not None


def prewarm() -> None:
    """Kick off the (first-use) compile+load on a background thread so the
    streaming hot paths never block on g++.  Idempotent and cheap once
    loaded; failures just leave the pure-Python fallbacks active."""
    if _lib is not None or _load_failed:
        return
    threading.Thread(target=_load, name="nnstpu-build", daemon=True).start()


def _as_u8p(arr: np.ndarray):
    return arr.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8))


def _to_u8(data) -> np.ndarray:
    if isinstance(data, (bytes, bytearray, memoryview)):
        return np.frombuffer(data, np.uint8)
    return np.ascontiguousarray(data).view(np.uint8).reshape(-1)


# -- v4l2 capture ------------------------------------------------------------

def fourcc(code: str) -> int:
    """'RGB3' -> the v4l2 32-bit fourcc."""
    if len(code) != 4:
        raise ValueError(f"fourcc must be 4 chars, got {code!r}")
    b = code.encode("ascii")
    return b[0] | (b[1] << 8) | (b[2] << 16) | (b[3] << 24)


class V4L2Capture:
    """mmap-streaming v4l2 capture (nns_v4l2_* in native/src/nnstpu.cpp).

    Negotiates (width, height, fourcc) with the driver — the actual
    values land on the instance; ``capture(timeout_ms)`` returns one raw
    frame as a uint8 array, None on timeout (poll your stop event and
    retry).  Raises RuntimeError with the driver's errno message when
    the device is not a streaming v4l2 capture node."""

    def __init__(self, device: str, width: int, height: int,
                 pixfmt: str = "RGB3", n_bufs: int = 4):
        lib = _load()
        if lib is None:
            raise RuntimeError(
                "native library unavailable (g++ build failed); "
                "v4l2 capture requires it")
        self._lib = lib
        w = ctypes.c_int(width)
        h = ctypes.c_int(height)
        fc = ctypes.c_uint32(fourcc(pixfmt))
        err = ctypes.create_string_buffer(256)
        self._h = lib.nns_v4l2_open(device.encode(), ctypes.byref(w),
                                    ctypes.byref(h), ctypes.byref(fc),
                                    n_bufs, err, len(err))
        if not self._h:
            raise RuntimeError(
                f"v4l2 open {device!r}: {err.value.decode(errors='replace')}")
        self.width = int(w.value)
        self.height = int(h.value)
        self.pixfmt = ctypes.string_at(
            ctypes.byref(ctypes.c_uint32(fc.value)), 4).decode(
                errors="replace")
        self.frame_bytes = int(lib.nns_v4l2_frame_bytes(self._h))
        self.stride = int(lib.nns_v4l2_stride(self._h))  # bytesperline

    def capture(self, timeout_ms: int = 200) -> Optional[np.ndarray]:
        out = np.empty(self.frame_bytes, np.uint8)
        n = self._lib.nns_v4l2_capture(self._h, _as_u8p(out), out.nbytes,
                                       int(timeout_ms))
        if n == 0:
            return None
        if n < 0:
            raise RuntimeError("v4l2 capture failed (device error)")
        return out[:n]

    def close(self) -> None:
        if getattr(self, "_h", None):
            self._lib.nns_v4l2_close(self._h)
            self._h = None


# -- crc32 -------------------------------------------------------------------

def crc32(data, seed: int = 0) -> int:
    a = _to_u8(data)
    lib = _load(block=False)
    if lib is None:
        import zlib

        return zlib.crc32(a.tobytes(), seed) & 0xFFFFFFFF
    return int(lib.nns_crc32(_as_u8p(a), a.nbytes, seed))


# -- stride repack -----------------------------------------------------------

def strip_stride(src, rows: int, row_bytes: int, src_stride: int) -> np.ndarray:
    """Copy ``rows`` rows of ``row_bytes`` out of a strided byte buffer
    (video frames whose rowstride != width*bpp — reference:
    gsttensor_converter.c stride removal)."""
    flat = _to_u8(src)
    if flat.nbytes < rows * src_stride - (src_stride - row_bytes):
        raise ValueError("source smaller than rows*stride")
    lib = _load(block=False)
    if lib is None:
        view = np.lib.stride_tricks.as_strided(
            flat, shape=(rows, row_bytes), strides=(src_stride, 1)
        )
        return np.ascontiguousarray(view).reshape(-1)
    out = np.empty(rows * row_bytes, np.uint8)
    lib.nns_strip_stride(_as_u8p(flat), _as_u8p(out), rows, row_bytes, src_stride)
    return out


# -- wire gather -------------------------------------------------------------

def wire_gather(segments: list):
    """Assemble segments into one frame: ``u64 len | payload | u32 crc``.

    Returns a buffer-protocol object (memoryview on the native path — no
    second copy; ``socket.sendall`` and slicing both accept it)."""
    arrs = [_to_u8(s) for s in segments]
    lib = _load(block=False)
    if lib is None:
        import struct as _struct
        import zlib

        payload = b"".join(a.tobytes() for a in arrs)
        return _struct.pack("<Q", len(payload)) + payload + _struct.pack(
            "<I", zlib.crc32(payload) & 0xFFFFFFFF
        )
    n = len(arrs)
    lens = (ctypes.c_uint64 * n)(*[a.nbytes for a in arrs])
    u8p = ctypes.POINTER(ctypes.c_uint8)
    ptrs = (u8p * n)(*[_as_u8p(a) for a in arrs])
    total = lib.nns_wire_frame_size(lens, n)
    out = np.empty(int(total), np.uint8)
    lib.nns_wire_gather(ptrs, lens, n, _as_u8p(out))
    return out.data


def wire_check(payload, crc: int) -> bool:
    a = _to_u8(payload)
    lib = _load(block=False)
    if lib is None:
        import zlib

        return (zlib.crc32(a.tobytes()) & 0xFFFFFFFF) == crc
    return bool(lib.nns_wire_check(_as_u8p(a), a.nbytes, crc))


# -- shared-memory ring ------------------------------------------------------

class ShmRing:
    """SPSC shared-memory ring of fixed-size slots (zero-copy same-host IPC).

    Producer: ``ring = ShmRing.create(name, nslots, slot_bytes)`` then
    ``ring.try_put(bytes)``.  Consumer (other process): ``ShmRing.open(name)``
    then ``ring.try_get()``.  Requires the native library (raises otherwise —
    there is no pure-Python shm ring; callers gate on :func:`available`).
    """

    def __init__(self, handle, name: str):
        self._h = handle
        self.name = name
        self._lib = _load()

    @classmethod
    def create(cls, name: str, nslots: int = 8, slot_bytes: int = 1 << 20) -> "ShmRing":
        lib = _load()
        if lib is None:
            raise RuntimeError("native library unavailable (no C++ toolchain?)")
        h = lib.nns_ring_create(name.encode(), nslots, slot_bytes)
        if not h:
            raise OSError(f"shm ring create failed for {name!r}")
        return cls(h, name)

    @classmethod
    def open(cls, name: str) -> "ShmRing":
        lib = _load()
        if lib is None:
            raise RuntimeError("native library unavailable (no C++ toolchain?)")
        h = lib.nns_ring_open(name.encode())
        if not h:
            raise OSError(f"shm ring open failed for {name!r} (producer not up?)")
        return cls(h, name)

    @property
    def slot_bytes(self) -> int:
        return int(self._lib.nns_ring_slot_bytes(self._h))

    @property
    def nslots(self) -> int:
        return int(self._lib.nns_ring_nslots(self._h))

    def try_put(self, data) -> bool:
        a = _to_u8(data)
        if a.nbytes > self.slot_bytes:
            raise ValueError(f"payload {a.nbytes}B > slot {self.slot_bytes}B")
        slot = self._lib.nns_ring_acquire(self._h)
        if not slot:
            return False
        ctypes.memmove(slot, a.ctypes.data, a.nbytes)
        return bool(self._lib.nns_ring_commit(self._h, a.nbytes))

    def try_get(self) -> Optional[bytes]:
        ln = ctypes.c_uint64()
        p = self._lib.nns_ring_peek(self._h, ctypes.byref(ln))
        if not p:
            return None
        data = ctypes.string_at(p, ln.value)
        self._lib.nns_ring_release(self._h)
        return data

    @property
    def closed(self) -> bool:
        return bool(self._lib.nns_ring_closed(self._h))

    def close_write(self) -> None:
        self._lib.nns_ring_close(self._h)

    def free(self) -> None:
        if self._h:
            self._lib.nns_ring_free(self._h)
            self._h = None
