/* Loadable custom-filter C ABI.
 *
 * Reference analog: tensor_filter_custom.c + tensor_filter_custom.h
 * (SURVEY §2.3 [UNVERIFIED]) — a user-compiled .so registers an
 * NNStreamer_custom_class vtable and becomes a tensor_filter model.  This
 * is the TPU build's own ABI (host-side compute; device compute enters
 * through the jax framework instead): a filter shared object exports ONE
 * symbol,
 *
 *     const nnstpu_custom_class *nnstpu_custom_get(void);
 *
 * and the "custom" framework (filters/custom_so.py) dlopens it, queries
 * I/O specs, and drives invoke() with raw host buffers.  C++ authors can
 * subclass nnstpu::Filter (nnstpu_cppclass.hh) instead of hand-rolling
 * the vtable — the reference's tensor_filter_cpp.cc analog.
 */
#ifndef NNSTPU_CUSTOM_H
#define NNSTPU_CUSTOM_H

#include <stdint.h>

#ifdef __cplusplus
extern "C" {
#endif

#define NNSTPU_CUSTOM_ABI_VERSION 1u
#define NNSTPU_RANK_LIMIT 8u
#define NNSTPU_TENSOR_LIMIT 16u

/* Order matches nnstreamer_tpu.core.types dtype naming. */
typedef enum {
  NNSTPU_INT8 = 0,
  NNSTPU_UINT8 = 1,
  NNSTPU_INT16 = 2,
  NNSTPU_UINT16 = 3,
  NNSTPU_INT32 = 4,
  NNSTPU_UINT32 = 5,
  NNSTPU_INT64 = 6,
  NNSTPU_UINT64 = 7,
  NNSTPU_FLOAT16 = 8,
  NNSTPU_FLOAT32 = 9,
  NNSTPU_FLOAT64 = 10,
} nnstpu_dtype;

typedef struct {
  uint32_t rank;                       /* dims[0..rank), numpy (row-major) order */
  uint64_t dims[NNSTPU_RANK_LIMIT];
  int32_t dtype;                       /* nnstpu_dtype */
} nnstpu_tensor_info;

typedef struct {
  uint32_t num;
  nnstpu_tensor_info info[NNSTPU_TENSOR_LIMIT];
} nnstpu_tensors_info;

typedef struct {
  uint32_t abi_version;                /* must be NNSTPU_CUSTOM_ABI_VERSION */
  /* Build the filter from the tensor_filter `custom=` property string
   * (may be NULL); returns a private handle passed to every other hook. */
  void *(*init)(const char *props);
  void (*finish)(void *priv);
  /* Fill `info`; return 0 on success. */
  int (*get_input_info)(void *priv, nnstpu_tensors_info *info);
  int (*get_output_info)(void *priv, nnstpu_tensors_info *info);
  /* inputs/outputs: one contiguous host buffer per tensor, sized and
   * typed per the info structs; outputs are caller-allocated.  Return 0
   * on success. */
  int (*invoke)(void *priv, const void *const *inputs, void *const *outputs);
} nnstpu_custom_class;

typedef const nnstpu_custom_class *(*nnstpu_custom_get_fn)(void);

#ifdef __cplusplus
}
#endif

#endif /* NNSTPU_CUSTOM_H */
