/* C++ class filter API: subclass-as-model.
 *
 * Reference analog: tensor_filter_cpp.cc + nnstreamer_cppplugin_api_filter.hh
 * (SURVEY §2.3 [UNVERIFIED]).  Subclass nnstpu::Filter, then emit the C ABI
 * vtable with one macro:
 *
 *     class Scale2 : public nnstpu::Filter {
 *      public:
 *       explicit Scale2(const char *props) {}
 *       int getInputInfo(nnstpu_tensors_info *i) override { ... }
 *       int getOutputInfo(nnstpu_tensors_info *i) override { ... }
 *       int invoke(const void *const *in, void *const *out) override { ... }
 *     };
 *     NNSTPU_REGISTER_FILTER(Scale2)
 *
 * Compile:  g++ -O2 -shared -fPIC -I<this dir> -o libmyfilter.so my.cc
 * Use:      tensor_filter framework=custom model=/path/libmyfilter.so
 */
#ifndef NNSTPU_CPPCLASS_HH
#define NNSTPU_CPPCLASS_HH

#include "nnstpu_custom.h"

namespace nnstpu {

class Filter {
 public:
  virtual ~Filter() = default;
  virtual int getInputInfo(nnstpu_tensors_info *info) = 0;
  virtual int getOutputInfo(nnstpu_tensors_info *info) = 0;
  virtual int invoke(const void *const *inputs, void *const *outputs) = 0;
};

}  // namespace nnstpu

#define NNSTPU_REGISTER_FILTER(Cls)                                          \
  extern "C" {                                                               \
  static void *nnstpu_reg_init_(const char *props) {                         \
    try {                                                                    \
      return new Cls(props ? props : "");                                    \
    } catch (...) {                                                          \
      return nullptr;                                                        \
    }                                                                        \
  }                                                                          \
  static void nnstpu_reg_finish_(void *p) {                                  \
    delete static_cast<Cls *>(p);                                            \
  }                                                                          \
  static int nnstpu_reg_in_(void *p, nnstpu_tensors_info *i) {               \
    return static_cast<Cls *>(p)->getInputInfo(i);                           \
  }                                                                          \
  static int nnstpu_reg_out_(void *p, nnstpu_tensors_info *i) {              \
    return static_cast<Cls *>(p)->getOutputInfo(i);                          \
  }                                                                          \
  static int nnstpu_reg_invoke_(void *p, const void *const *in,              \
                                void *const *out) {                          \
    return static_cast<Cls *>(p)->invoke(in, out);                           \
  }                                                                          \
  static const nnstpu_custom_class nnstpu_reg_vtable_ = {                    \
      NNSTPU_CUSTOM_ABI_VERSION, nnstpu_reg_init_,   nnstpu_reg_finish_,     \
      nnstpu_reg_in_,            nnstpu_reg_out_,    nnstpu_reg_invoke_};    \
  const nnstpu_custom_class *nnstpu_custom_get(void) {                       \
    return &nnstpu_reg_vtable_;                                              \
  }                                                                          \
  }

#endif /* NNSTPU_CPPCLASS_HH */
