/* nnstpu C API — single-shot model invoke from C/C++ programs.
 *
 * Reference analog: the ML C-API's ml_single_open / ml_single_invoke /
 * ml_single_close surface over gsttensor_filter_single.c (SURVEY §3.5).
 * The library embeds CPython: link against libnnstpu_capi.so (built from
 * ../src/nnstpu_capi.cpp with `python3-config --includes --embed`) and
 * make sure PYTHONPATH reaches the nnstreamer_tpu package.
 *
 * Thread-safety: all entry points acquire the embedded interpreter's GIL;
 * handles may be used from any thread, one invoke at a time per handle.
 *
 * Minimal use:
 *
 *   nnstpu_single_h h = nnstpu_single_open("mobilenet_v1", "jax",
 *                                          "size:224,batch:1",
 *                                          err, sizeof err);
 *   const void *in[1] = {frame};  size_t in_sz[1] = {frame_bytes};
 *   void *out[4]; size_t out_sz[4];
 *   int n = nnstpu_single_invoke(h, in, in_sz, 1, out, out_sz, 4,
 *                                err, sizeof err);
 *   ... use out[0..n-1] ...
 *   for (int i = 0; i < n; i++) nnstpu_free(out[i]);
 *   nnstpu_single_close(h);
 */
#ifndef NNSTPU_CAPI_H
#define NNSTPU_CAPI_H

#include <stddef.h>

#ifdef __cplusplus
extern "C" {
#endif

typedef long long nnstpu_single_h; /* < 0 means error */

/* Initialize the embedded interpreter and import the bridge module.
 * Idempotent; called automatically by nnstpu_single_open.  Returns 0 on
 * success, -1 on failure (diagnostics on stderr). */
int nnstpu_init(void);

/* Open a model for single-shot invoke.  `framework` may be NULL/"auto";
 * `custom` may be NULL/"" (same syntax as the pipeline `custom=` prop);
 * `model` is a zoo name or a model FILE path (.tflite/.onnx/.gguf/
 * .safetensors...).  On failure returns < 0 and writes a message into
 * `err` (errlen bytes, always NUL-terminated). */
nnstpu_single_h nnstpu_single_open(const char *model, const char *framework,
                                   const char *custom,
                                   char *err, size_t errlen);

/* Input/output tensor descriptions as "dims,dtype;dims,dtype" strings
 * (dims innermost-first, e.g. "3:224:224:1,float32").  Returns 0/-1. */
int nnstpu_single_info(nnstpu_single_h h, char *in_desc, size_t in_len,
                       char *out_desc, size_t out_len,
                       char *err, size_t errlen);

/* Invoke with n_in raw little-endian tensor payloads (sizes must match
 * the input spec exactly).  On success returns the number of output
 * tensors (<= max_out) and fills out_data/out_sizes with malloc'd
 * buffers the caller releases via nnstpu_free.  Returns -1 on error. */
int nnstpu_single_invoke(nnstpu_single_h h,
                         const void *const *in_data, const size_t *in_sizes,
                         int n_in, void **out_data, size_t *out_sizes,
                         int max_out, char *err, size_t errlen);

void nnstpu_single_close(nnstpu_single_h h);

/* ---- pipeline surface (ml_pipeline_* analog) -------------------------
 * Construct + start a pipeline from the gst-launch-style description,
 * feed named appsrc elements, pull named tensor_sink elements. */

typedef long long nnstpu_pipeline_h; /* < 0 means error */

nnstpu_pipeline_h nnstpu_pipeline_open(const char *description,
                                       char *err, size_t errlen);

/* Push one buffer (n_in raw tensor payloads) into appsrc `name`.  Sizes
 * must match the source's negotiated caps spec when it carries one. */
int nnstpu_pipeline_push(nnstpu_pipeline_h h, const char *name,
                         const void *const *in_data, const size_t *in_sizes,
                         int n_in, char *err, size_t errlen);

/* Pull one buffer from tensor_sink `name` (blocks up to timeout_ms).
 * Returns the number of tensors (<= max_out); fills out_data/out_sizes
 * with malloc'd buffers (caller frees via nnstpu_free) and writes the
 * per-tensor "dims,dtype;..." description into desc. */
int nnstpu_pipeline_pull(nnstpu_pipeline_h h, const char *name,
                         long timeout_ms, void **out_data,
                         size_t *out_sizes, int max_out,
                         char *desc, size_t desc_len,
                         char *err, size_t errlen);

/* Signal end-of-stream on appsrc `name`, or on every app source when
 * name is NULL/"". */
int nnstpu_pipeline_eos(nnstpu_pipeline_h h, const char *name,
                        char *err, size_t errlen);

void nnstpu_pipeline_close(nnstpu_pipeline_h h);

void nnstpu_free(void *p);

#ifdef __cplusplus
}
#endif

#endif /* NNSTPU_CAPI_H */
