/* C ABI for single-shot invoke, embedding CPython.
 *
 * Reference analog: the ML C-API implementation over
 * gsttensor_filter_single.c (SURVEY §3.5).  All Python-object lifetime
 * stays on this side of the boundary; the C caller sees integer handles
 * and malloc'd byte buffers.  See ../include/nnstpu_capi.h for the
 * contract and tests/test_capi.py for a real C driver program built and
 * executed against this library.
 */
#include <Python.h>

#include <atomic>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <mutex>

#include "../include/nnstpu_capi.h"

/* g_mod is published with release ordering after a successful init and
 * read with acquire in every entry point: the any-thread promise in the
 * header must not rest on a data race. */
static std::atomic<PyObject *> g_mod{NULL};
static std::atomic<int> g_inited{0};
static std::mutex g_init_mu;

static PyObject *mod_acquire(void) {
    return g_mod.load(std::memory_order_acquire);
}

static void set_err(char *err, size_t errlen, const char *msg);

/* Build a Python list of bytes from C payloads; NULL on failure (GIL
 * held).  Shared by single_invoke and pipeline_push. */
static PyObject *make_blob_list(const void *const *in_data,
                                const size_t *in_sizes, int n_in) {
    PyObject *blobs = PyList_New(n_in);
    if (!blobs) {
        return NULL;
    }
    for (int i = 0; i < n_in; i++) {
        PyObject *b = PyBytes_FromStringAndSize(
            (const char *)in_data[i], (Py_ssize_t)in_sizes[i]);
        if (!b) {
            Py_DECREF(blobs);
            return NULL;
        }
        PyList_SET_ITEM(blobs, i, b); /* steals */
    }
    return blobs;
}

/* Copy a Python list of bytes into malloc'd C buffers.  Returns the
 * count, or -1 (err set, any partially-written buffers freed).  GIL
 * held.  Shared by single_invoke and pipeline_pull. */
static int copy_out_blobs(PyObject *list, void **out_data,
                          size_t *out_sizes, int max_out, char *err,
                          size_t errlen) {
    Py_ssize_t n = PyList_Size(list);
    if ((int)n > max_out) {
        set_err(err, errlen, "max_out too small for outputs");
        return -1;
    }
    int written = 0;
    for (Py_ssize_t i = 0; i < n; i++) {
        char *p = NULL;
        Py_ssize_t len = 0;
        if (PyBytes_AsStringAndSize(PyList_GET_ITEM(list, i), &p, &len) !=
            0) {
            break;
        }
        void *buf = malloc((size_t)len ? (size_t)len : 1);
        if (!buf) {
            set_err(err, errlen, "out of memory");
            break;
        }
        memcpy(buf, p, (size_t)len);
        out_data[i] = buf;
        out_sizes[i] = (size_t)len;
        written++;
    }
    if (written == (int)n) {
        return (int)n;
    }
    /* free exactly the buffers handed out before the failure (later
     * slots are caller-owned uninitialized memory) */
    for (int i = 0; i < written; i++) {
        free(out_data[i]);
        out_data[i] = NULL;
    }
    return -1;
}

static void set_err(char *err, size_t errlen, const char *msg) {
    if (err && errlen) {
        snprintf(err, errlen, "%s", msg ? msg : "unknown error");
    }
}

/* Capture the pending Python exception into err (GIL held).  Always
 * leaves NO exception pending — a secondary failure in str()/utf-8 must
 * not leak into the caller's next Python call. */
static void fetch_py_err(char *err, size_t errlen) {
    PyObject *type = NULL, *value = NULL, *tb = NULL;
    PyErr_Fetch(&type, &value, &tb);
    PyErr_NormalizeException(&type, &value, &tb);
    if (value) {
        PyObject *s = PyObject_Str(value);
        if (s) {
            const char *msg = PyUnicode_AsUTF8(s);
            set_err(err, errlen, msg ? msg : "python error (undecodable)");
            Py_DECREF(s);
        } else {
            set_err(err, errlen, "python error (unprintable)");
        }
    } else {
        set_err(err, errlen, "python error (no value)");
    }
    Py_XDECREF(type);
    Py_XDECREF(value);
    Py_XDECREF(tb);
    PyErr_Clear();
}

extern "C" int nnstpu_init(void) {
    /* Serialized: concurrent first calls must not race Py_InitializeEx or
     * observe a half-published g_mod (header promises any-thread use). */
    std::lock_guard<std::mutex> lk(g_init_mu);
    if (g_inited.load(std::memory_order_acquire)) {
        return 0;
    }
    if (!Py_IsInitialized()) {
        /* InitializeEx(0): skip signal handlers — the host C program owns
         * its signal disposition. */
        Py_InitializeEx(0);
        PyObject *mod = PyImport_ImportModule("nnstreamer_tpu.capi");
        if (mod) {
            /* Fresh embed: the process env (JAX_PLATFORMS etc.) is the
             * only configuration channel, so honor it now.  When loaded
             * into an existing interpreter (branch below) this is NOT
             * done — a host app's programmatic jax.config pin wins. */
            PyObject *r = PyObject_CallMethod(mod, "_on_fresh_embed", NULL);
            if (!r) {
                PyErr_Clear();
            }
            Py_XDECREF(r);
            g_mod.store(mod, std::memory_order_release);
            g_inited.store(1, std::memory_order_release);
        } else {
            PyErr_Print();
        }
        /* Release the GIL the init thread holds — on SUCCESS so other
         * threads can PyGILState_Ensure, and on FAILURE so they don't
         * deadlock behind a dead init. */
        PyEval_SaveThread();
        return g_inited.load(std::memory_order_acquire) ? 0 : -1;
    }
    /* Already-initialized interpreter (e.g. loaded from a Python
     * process): just import the bridge under the GIL. */
    PyGILState_STATE st = PyGILState_Ensure();
    PyObject *mod = PyImport_ImportModule("nnstreamer_tpu.capi");
    int rc = -1;
    if (mod) {
        g_mod.store(mod, std::memory_order_release);
        g_inited.store(1, std::memory_order_release);
        rc = 0;
    } else {
        PyErr_Print();
    }
    PyGILState_Release(st);
    return rc;
}

extern "C" nnstpu_single_h nnstpu_single_open(const char *model,
                                              const char *framework,
                                              const char *custom,
                                              char *err, size_t errlen) {
    if (!model || !*model) {
        set_err(err, errlen, "model must be non-empty");
        return -1;
    }
    if (!g_inited.load(std::memory_order_acquire) && nnstpu_init() != 0) {
        set_err(err, errlen, "nnstpu_init failed (see stderr)");
        return -1;
    }
    PyGILState_STATE st = PyGILState_Ensure();
    PyObject *r = PyObject_CallMethod(mod_acquire(), "single_open", "sss", model,
                                      framework && *framework ? framework
                                                              : "auto",
                                      custom ? custom : "");
    long long h = -1;
    if (r) {
        h = PyLong_AsLongLong(r);
        Py_DECREF(r);
    } else {
        fetch_py_err(err, errlen);
    }
    PyGILState_Release(st);
    return h;
}

extern "C" int nnstpu_single_info(nnstpu_single_h h, char *in_desc,
                                  size_t in_len, char *out_desc,
                                  size_t out_len, char *err, size_t errlen) {
    if (!g_inited.load(std::memory_order_acquire)) {
        set_err(err, errlen, "not initialized");
        return -1;
    }
    PyGILState_STATE st = PyGILState_Ensure();
    PyObject *r = PyObject_CallMethod(mod_acquire(), "single_info", "L", h);
    int rc = -1;
    if (r && PyTuple_Check(r) && PyTuple_Size(r) == 2) {
        const char *a = PyUnicode_AsUTF8(PyTuple_GET_ITEM(r, 0));
        const char *b = PyUnicode_AsUTF8(PyTuple_GET_ITEM(r, 1));
        if (a && b) {
            if (in_desc && in_len) {
                snprintf(in_desc, in_len, "%s", a);
            }
            if (out_desc && out_len) {
                snprintf(out_desc, out_len, "%s", b);
            }
            rc = 0;
        }
    }
    if (rc != 0 && PyErr_Occurred()) {
        fetch_py_err(err, errlen);
    }
    Py_XDECREF(r);
    PyGILState_Release(st);
    return rc;
}

extern "C" int nnstpu_single_invoke(nnstpu_single_h h,
                                    const void *const *in_data,
                                    const size_t *in_sizes, int n_in,
                                    void **out_data, size_t *out_sizes,
                                    int max_out, char *err, size_t errlen) {
    if (!g_inited.load(std::memory_order_acquire)) {
        set_err(err, errlen, "not initialized");
        return -1;
    }
    if (n_in < 0 || (n_in > 0 && (!in_data || !in_sizes))) {
        set_err(err, errlen, "bad input arguments");
        return -1;
    }
    PyGILState_STATE st = PyGILState_Ensure();
    int n_out = -1;
    PyObject *r = NULL;
    PyObject *blobs = make_blob_list(in_data, in_sizes, n_in);
    if (blobs) {
        r = PyObject_CallMethod(mod_acquire(), "single_invoke_bytes", "LO",
                                h, blobs);
        Py_DECREF(blobs);
    }
    if (r && PyList_Check(r)) {
        n_out = copy_out_blobs(r, out_data, out_sizes, max_out, err,
                               errlen);
    }
    if (n_out < 0 && PyErr_Occurred()) {
        fetch_py_err(err, errlen);
    }
    Py_XDECREF(r);
    PyGILState_Release(st);
    return n_out;
}

extern "C" nnstpu_pipeline_h nnstpu_pipeline_open(const char *description,
                                                  char *err, size_t errlen) {
    if (!description || !*description) {
        set_err(err, errlen, "description must be non-empty");
        return -1;
    }
    if (!g_inited.load(std::memory_order_acquire) && nnstpu_init() != 0) {
        set_err(err, errlen, "nnstpu_init failed (see stderr)");
        return -1;
    }
    PyGILState_STATE st = PyGILState_Ensure();
    PyObject *r = PyObject_CallMethod(mod_acquire(), "pipeline_open", "s",
                                      description);
    long long h = -1;
    if (r) {
        h = PyLong_AsLongLong(r);
        Py_DECREF(r);
    } else {
        fetch_py_err(err, errlen);
    }
    PyGILState_Release(st);
    return h;
}

extern "C" int nnstpu_pipeline_push(nnstpu_pipeline_h h, const char *name,
                                    const void *const *in_data,
                                    const size_t *in_sizes, int n_in,
                                    char *err, size_t errlen) {
    if (!g_inited.load(std::memory_order_acquire)) {
        set_err(err, errlen, "not initialized");
        return -1;
    }
    if (!name || n_in <= 0 || !in_data || !in_sizes) {
        set_err(err, errlen, "bad input arguments");
        return -1;
    }
    PyGILState_STATE st = PyGILState_Ensure();
    int rc = -1;
    PyObject *blobs = make_blob_list(in_data, in_sizes, n_in);
    if (blobs) {
        PyObject *r = PyObject_CallMethod(
            mod_acquire(), "pipeline_push", "LsO", h, name, blobs);
        if (r) {
            rc = 0;
            Py_DECREF(r);
        }
        Py_DECREF(blobs);
    }
    if (rc != 0) {
        fetch_py_err(err, errlen);
    }
    PyGILState_Release(st);
    return rc;
}

extern "C" int nnstpu_pipeline_pull(nnstpu_pipeline_h h, const char *name,
                                    long timeout_ms, void **out_data,
                                    size_t *out_sizes, int max_out,
                                    char *desc, size_t desc_len,
                                    char *err, size_t errlen) {
    if (!g_inited.load(std::memory_order_acquire)) {
        set_err(err, errlen, "not initialized");
        return -1;
    }
    int n_out = -1;
    PyGILState_STATE st = PyGILState_Ensure();
    PyObject *r = PyObject_CallMethod(mod_acquire(), "pipeline_pull", "Lsd",
                                      h, name, timeout_ms / 1000.0);
    if (r && PyTuple_Check(r) && PyTuple_Size(r) == 2) {
        PyObject *blobs = PyTuple_GET_ITEM(r, 0);
        const char *d = PyUnicode_AsUTF8(PyTuple_GET_ITEM(r, 1));
        if (PyList_Check(blobs) && d) {
            if (desc && desc_len) {
                snprintf(desc, desc_len, "%s", d);
            }
            n_out = copy_out_blobs(blobs, out_data, out_sizes, max_out,
                                   err, errlen);
        }
    }
    if (n_out < 0 && PyErr_Occurred()) {
        fetch_py_err(err, errlen);
    }
    Py_XDECREF(r);
    PyGILState_Release(st);
    return n_out;
}

extern "C" int nnstpu_pipeline_eos(nnstpu_pipeline_h h, const char *name,
                                   char *err, size_t errlen) {
    if (!g_inited.load(std::memory_order_acquire)) {
        set_err(err, errlen, "not initialized");
        return -1;
    }
    PyGILState_STATE st = PyGILState_Ensure();
    PyObject *r = PyObject_CallMethod(mod_acquire(), "pipeline_eos", "Ls",
                                      h, name ? name : "");
    int rc = 0;
    if (!r) {
        fetch_py_err(err, errlen);
        rc = -1;
    }
    Py_XDECREF(r);
    PyGILState_Release(st);
    return rc;
}

extern "C" void nnstpu_pipeline_close(nnstpu_pipeline_h h) {
    if (!g_inited.load(std::memory_order_acquire)) {
        return;
    }
    PyGILState_STATE st = PyGILState_Ensure();
    PyObject *r = PyObject_CallMethod(mod_acquire(), "pipeline_close", "L",
                                      h);
    if (!r) {
        PyErr_Clear();
    }
    Py_XDECREF(r);
    PyGILState_Release(st);
}

extern "C" void nnstpu_single_close(nnstpu_single_h h) {
    if (!g_inited.load(std::memory_order_acquire)) {
        return;
    }
    PyGILState_STATE st = PyGILState_Ensure();
    PyObject *r = PyObject_CallMethod(mod_acquire(), "single_close", "L", h);
    if (!r) {
        PyErr_Clear();
    }
    Py_XDECREF(r);
    PyGILState_Release(st);
}

extern "C" void nnstpu_free(void *p) {
    free(p);
}
