// nnstpu: native runtime support for nnstreamer_tpu.
//
// Reference analogs (upstream-reconstructed, SURVEY §2.7/§2.2):
//   * nnstreamer-edge — the C transport library carrying other/tensors
//     frames between processes/hosts (framing + integrity);
//   * GStreamer's shmsrc/shmsink + GstBufferPool — zero-copy same-host
//     hand-off between pipelines via a shared-memory ring;
//   * gsttensor_converter.c's row-stride repack — the per-frame host hot
//     loop before tensors reach the device.
//
// The TPU build keeps orchestration in Python but puts these per-byte hot
// paths in C++ behind a small C ABI (ctypes-friendly; no pybind11 in this
// environment).  Everything is single-file on purpose: one .so, no deps
// beyond libc/librt.

#include <atomic>
#include <mutex>
#include <cstdint>
#include <cstring>
#include <cstdio>
#include <cstdlib>

#include <errno.h>
#include <fcntl.h>
#include <signal.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <unistd.h>

extern "C" {

// ---------------------------------------------------------------------------
// CRC32 (IEEE, table-driven) — wire-frame integrity on DCN transports.
// ---------------------------------------------------------------------------

static uint32_t g_crc_table[8][256];
static std::once_flag g_crc_once;  // ctypes calls drop the GIL: real races

static void crc32_init() {
  for (uint32_t i = 0; i < 256; i++) {
    uint32_t c = i;
    for (int k = 0; k < 8; k++) c = (c >> 1) ^ (0xEDB88320u & (-(int32_t)(c & 1)));
    g_crc_table[0][i] = c;
  }
  for (uint32_t i = 0; i < 256; i++)
    for (int s = 1; s < 8; s++)
      g_crc_table[s][i] =
          (g_crc_table[s - 1][i] >> 8) ^ g_crc_table[0][g_crc_table[s - 1][i] & 0xff];
}

uint32_t nns_crc32(const uint8_t *data, uint64_t len, uint32_t seed) {
  std::call_once(g_crc_once, crc32_init);
  uint32_t c = ~seed;
  // slice-by-8
  while (len >= 8) {
    c ^= *(const uint32_t *)data;
    uint32_t hi = *(const uint32_t *)(data + 4);
    c = g_crc_table[7][c & 0xff] ^ g_crc_table[6][(c >> 8) & 0xff] ^
        g_crc_table[5][(c >> 16) & 0xff] ^ g_crc_table[4][c >> 24] ^
        g_crc_table[3][hi & 0xff] ^ g_crc_table[2][(hi >> 8) & 0xff] ^
        g_crc_table[1][(hi >> 16) & 0xff] ^ g_crc_table[0][hi >> 24];
    data += 8;
    len -= 8;
  }
  while (len--) c = g_crc_table[0][(c ^ *data++) & 0xff] ^ (c >> 8);
  return ~c;
}

// ---------------------------------------------------------------------------
// Stride repack: drop per-row padding (video rowstride != width*bpp).
// src rows of src_stride bytes -> dst rows of row_bytes, for h rows of
// depth planes (plane_stride covers planar layouts; 0 = packed single plane).
// ---------------------------------------------------------------------------

void nns_strip_stride(const uint8_t *src, uint8_t *dst, uint64_t rows,
                      uint64_t row_bytes, uint64_t src_stride) {
  if (src_stride == row_bytes) {
    memcpy(dst, src, rows * row_bytes);
    return;
  }
  for (uint64_t r = 0; r < rows; r++)
    memcpy(dst + r * row_bytes, src + r * src_stride, row_bytes);
}

// ---------------------------------------------------------------------------
// Wire assembly: gather N segments into one contiguous frame with a
// length prefix and trailing crc32.  (The Python codec builds the segments;
// the native path does the single-copy gather + checksum in C.)
// layout: u64 payload_len | payload | u32 crc32(payload)
// ---------------------------------------------------------------------------

uint64_t nns_wire_frame_size(const uint64_t *seg_lens, uint32_t nsegs) {
  uint64_t total = 8 + 4;
  for (uint32_t i = 0; i < nsegs; i++) total += seg_lens[i];
  return total;
}

void nns_wire_gather(const uint8_t *const *segs, const uint64_t *seg_lens,
                     uint32_t nsegs, uint8_t *out) {
  uint64_t payload = 0;
  for (uint32_t i = 0; i < nsegs; i++) payload += seg_lens[i];
  memcpy(out, &payload, 8);
  uint8_t *p = out + 8;
  for (uint32_t i = 0; i < nsegs; i++) {
    memcpy(p, segs[i], seg_lens[i]);
    p += seg_lens[i];
  }
  uint32_t crc = nns_crc32(out + 8, payload, 0);
  memcpy(p, &crc, 4);
}

// Verify a received frame payload against its trailing crc. 1 = ok.
int nns_wire_check(const uint8_t *payload, uint64_t len, uint32_t crc) {
  return nns_crc32(payload, len, 0) == crc ? 1 : 0;
}

// ---------------------------------------------------------------------------
// SPSC shared-memory ring — same-host zero-copy pipeline hand-off
// (GStreamer shmsink/shmsrc analog).  Fixed slot size, single producer,
// single consumer, lock-free via acquire/release atomics on head/tail.
//
// Shm layout: Header | slot_lens[nslots] (u64) | slots (nslots*slot_bytes)
// ---------------------------------------------------------------------------

struct RingHeader {
  std::atomic<uint32_t> magic;  // 'NSRG'; stored LAST (release) at create
  uint32_t nslots;
  uint64_t slot_bytes;
  uint64_t owner_pid;          // producer pid, for stale-ring detection
  std::atomic<uint64_t> head;  // next slot to write (producer)
  std::atomic<uint64_t> tail;  // next slot to read (consumer)
  std::atomic<uint32_t> closed;
};

static const uint32_t RING_MAGIC = 0x4E535247u;

struct Ring {
  RingHeader *hdr;
  uint64_t *lens;
  uint8_t *slots;
  uint64_t map_bytes;
  int fd;
  char name[256];
  int owner;
};

static uint64_t ring_bytes(uint32_t nslots, uint64_t slot_bytes) {
  return sizeof(RingHeader) + nslots * sizeof(uint64_t) + (uint64_t)nslots * slot_bytes;
}

// Is the ring at `name` owned by a live process?  0 = dead/invalid (safe to
// unlink), 1 = live, -1 = can't tell.
static int ring_owner_alive(const char *name) {
  int fd = shm_open(name, O_RDONLY, 0600);
  if (fd < 0) return 0;
  struct stat st;
  if (fstat(fd, &st) != 0 || (uint64_t)st.st_size < sizeof(RingHeader)) {
    close(fd);
    return 0;
  }
  void *mem = mmap(nullptr, sizeof(RingHeader), PROT_READ, MAP_SHARED, fd, 0);
  close(fd);
  if (mem == MAP_FAILED) return -1;
  RingHeader *h = (RingHeader *)mem;
  int alive = 0;
  if (h->magic.load(std::memory_order_acquire) == RING_MAGIC && h->owner_pid > 0)
    alive = (kill((pid_t)h->owner_pid, 0) == 0 || errno == EPERM) ? 1 : 0;
  munmap(mem, sizeof(RingHeader));
  return alive;
}

void *nns_ring_create(const char *name, uint32_t nslots, uint64_t slot_bytes) {
  int fd = shm_open(name, O_CREAT | O_RDWR | O_EXCL, 0600);
  if (fd < 0 && errno == EEXIST) {
    // Only reclaim a ring whose owning producer is demonstrably gone —
    // unlinking a live producer's ring would silently fork the stream.
    if (ring_owner_alive(name) != 0) return nullptr;
    shm_unlink(name);
    fd = shm_open(name, O_CREAT | O_RDWR | O_EXCL, 0600);
  }
  if (fd < 0) return nullptr;
  uint64_t total = ring_bytes(nslots, slot_bytes);
  if (ftruncate(fd, (off_t)total) != 0) {
    close(fd);
    shm_unlink(name);
    return nullptr;
  }
  void *mem = mmap(nullptr, total, PROT_READ | PROT_WRITE, MAP_SHARED, fd, 0);
  if (mem == MAP_FAILED) {
    close(fd);
    shm_unlink(name);
    return nullptr;
  }
  Ring *r = new Ring();
  r->hdr = (RingHeader *)mem;
  r->hdr->nslots = nslots;
  r->hdr->slot_bytes = slot_bytes;
  r->hdr->owner_pid = (uint64_t)getpid();
  r->hdr->head.store(0);
  r->hdr->tail.store(0);
  r->hdr->closed.store(0);
  // Publish last: a concurrent nns_ring_open polling this mapping must not
  // see the magic before the geometry fields are valid.
  r->hdr->magic.store(RING_MAGIC, std::memory_order_release);
  r->lens = (uint64_t *)((uint8_t *)mem + sizeof(RingHeader));
  r->slots = (uint8_t *)(r->lens + nslots);
  r->map_bytes = total;
  r->fd = fd;
  snprintf(r->name, sizeof(r->name), "%s", name);
  r->owner = 1;
  return r;
}

void *nns_ring_open(const char *name) {
  int fd = shm_open(name, O_RDWR, 0600);
  if (fd < 0) return nullptr;
  struct stat st;
  if (fstat(fd, &st) != 0 || (uint64_t)st.st_size < sizeof(RingHeader)) {
    close(fd);
    return nullptr;
  }
  void *mem = mmap(nullptr, (size_t)st.st_size, PROT_READ | PROT_WRITE, MAP_SHARED, fd, 0);
  if (mem == MAP_FAILED) {
    close(fd);
    return nullptr;
  }
  RingHeader *h = (RingHeader *)mem;
  if (h->magic.load(std::memory_order_acquire) != RING_MAGIC ||
      (uint64_t)st.st_size < ring_bytes(h->nslots, h->slot_bytes)) {
    munmap(mem, (size_t)st.st_size);
    close(fd);
    return nullptr;
  }
  Ring *r = new Ring();
  r->hdr = h;
  r->lens = (uint64_t *)((uint8_t *)mem + sizeof(RingHeader));
  r->slots = (uint8_t *)(r->lens + h->nslots);
  r->map_bytes = (uint64_t)st.st_size;
  r->fd = fd;
  snprintf(r->name, sizeof(r->name), "%s", name);
  r->owner = 0;
  return r;
}

uint64_t nns_ring_slot_bytes(void *ring) { return ((Ring *)ring)->hdr->slot_bytes; }
uint32_t nns_ring_nslots(void *ring) { return ((Ring *)ring)->hdr->nslots; }

// Producer: returns slot pointer to write into, or NULL when full/closed.
uint8_t *nns_ring_acquire(void *ring) {
  Ring *r = (Ring *)ring;
  if (r->hdr->closed.load(std::memory_order_acquire)) return nullptr;
  uint64_t head = r->hdr->head.load(std::memory_order_relaxed);
  uint64_t tail = r->hdr->tail.load(std::memory_order_acquire);
  if (head - tail >= r->hdr->nslots) return nullptr;  // full
  return r->slots + (head % r->hdr->nslots) * r->hdr->slot_bytes;
}

// Producer: publish the acquired slot with `len` valid bytes. 1 = ok.
int nns_ring_commit(void *ring, uint64_t len) {
  Ring *r = (Ring *)ring;
  if (len > r->hdr->slot_bytes) return 0;
  uint64_t head = r->hdr->head.load(std::memory_order_relaxed);
  r->lens[head % r->hdr->nslots] = len;
  r->hdr->head.store(head + 1, std::memory_order_release);
  return 1;
}

// Consumer: returns pointer to the next filled slot (sets *len), or NULL
// when empty.  Call nns_ring_release after copying/consuming.
const uint8_t *nns_ring_peek(void *ring, uint64_t *len) {
  Ring *r = (Ring *)ring;
  uint64_t tail = r->hdr->tail.load(std::memory_order_relaxed);
  uint64_t head = r->hdr->head.load(std::memory_order_acquire);
  if (tail == head) return nullptr;  // empty
  *len = r->lens[tail % r->hdr->nslots];
  return r->slots + (tail % r->hdr->nslots) * r->hdr->slot_bytes;
}

void nns_ring_release(void *ring) {
  Ring *r = (Ring *)ring;
  uint64_t tail = r->hdr->tail.load(std::memory_order_relaxed);
  r->hdr->tail.store(tail + 1, std::memory_order_release);
}

int nns_ring_closed(void *ring) {
  return (int)((Ring *)ring)->hdr->closed.load(std::memory_order_acquire);
}

void nns_ring_close(void *ring) {
  ((Ring *)ring)->hdr->closed.store(1, std::memory_order_release);
}

void nns_ring_free(void *ring) {
  Ring *r = (Ring *)ring;
  munmap((void *)r->hdr, r->map_bytes);
  close(r->fd);
  if (r->owner) shm_unlink(r->name);
  delete r;
}

// ---------------------------------------------------------------------------
// v4l2 capture (ioctl + mmap buffer ring) — the literal camera ingest hot
// path (reference analog: v4l2src feeding tensor_converter; SURVEY §7's
// "v4l2src -> tensor_filter" north star).  Streaming I/O: REQBUFS(MMAP),
// QBUF all, STREAMON; each nns_v4l2_capture select()s with a timeout,
// DQBUFs one filled buffer, copies the payload out, and immediately QBUFs
// the slot back — the driver always owns n-1 buffers, so frame drops under
// a slow consumer happen in the DRIVER ring (newest-overwrites policy per
// driver), never by unbounded host queueing.
// ---------------------------------------------------------------------------

}  // extern "C"

#include <linux/videodev2.h>
#include <sys/ioctl.h>
#include <sys/select.h>

namespace {

struct V4l2Cap {
  int fd = -1;
  uint32_t n_bufs = 0;
  void *maps[16] = {nullptr};
  size_t lens[16] = {0};
  uint32_t frame_bytes = 0;
  uint32_t stride = 0;  // bytesperline: drivers may pad rows
};

static int xioctl(int fd, unsigned long req, void *arg) {
  int r;
  do {
    r = ioctl(fd, req, arg);
  } while (r == -1 && errno == EINTR);
  return r;
}

static void set_err(char *err, int errlen, const char *msg) {
  if (err && errlen > 0) {
    snprintf(err, (size_t)errlen, "%s (errno %d)", msg, errno);
  }
}

}  // namespace

extern "C" {

void nns_v4l2_close(void *handle);  // used by open's error paths

// Negotiates *width/*height/*fourcc with the driver (values updated to
// what the device actually delivers); returns an opaque handle or null
// with `err` filled.
void *nns_v4l2_open(const char *dev, int *width, int *height,
                    uint32_t *fourcc, int n_bufs, char *err, int errlen) {
  int fd = open(dev, O_RDWR | O_NONBLOCK);
  if (fd < 0) {
    set_err(err, errlen, "open failed");
    return nullptr;
  }
  v4l2_capability cap;
  memset(&cap, 0, sizeof(cap));
  if (xioctl(fd, VIDIOC_QUERYCAP, &cap) < 0) {
    set_err(err, errlen, "VIDIOC_QUERYCAP failed (not a v4l2 device?)");
    close(fd);
    return nullptr;
  }
  if (!(cap.capabilities & V4L2_CAP_VIDEO_CAPTURE) ||
      !(cap.capabilities & V4L2_CAP_STREAMING)) {
    set_err(err, errlen, "device lacks CAPTURE+STREAMING capabilities");
    close(fd);
    return nullptr;
  }
  v4l2_format fmt;
  memset(&fmt, 0, sizeof(fmt));
  fmt.type = V4L2_BUF_TYPE_VIDEO_CAPTURE;
  fmt.fmt.pix.width = (uint32_t)*width;
  fmt.fmt.pix.height = (uint32_t)*height;
  fmt.fmt.pix.pixelformat = *fourcc;
  fmt.fmt.pix.field = V4L2_FIELD_NONE;
  if (xioctl(fd, VIDIOC_S_FMT, &fmt) < 0) {
    set_err(err, errlen, "VIDIOC_S_FMT failed");
    close(fd);
    return nullptr;
  }
  *width = (int)fmt.fmt.pix.width;
  *height = (int)fmt.fmt.pix.height;
  *fourcc = fmt.fmt.pix.pixelformat;

  auto *h = new V4l2Cap();
  h->fd = fd;
  h->frame_bytes = fmt.fmt.pix.sizeimage;
  h->stride = fmt.fmt.pix.bytesperline;

  v4l2_requestbuffers req;
  memset(&req, 0, sizeof(req));
  req.count = (uint32_t)(n_bufs < 2 ? 2 : (n_bufs > 16 ? 16 : n_bufs));
  req.type = V4L2_BUF_TYPE_VIDEO_CAPTURE;
  req.memory = V4L2_MEMORY_MMAP;
  if (xioctl(fd, VIDIOC_REQBUFS, &req) < 0 || req.count < 2) {
    set_err(err, errlen, "VIDIOC_REQBUFS(MMAP) failed");
    close(fd);
    delete h;
    return nullptr;
  }
  h->n_bufs = req.count;
  for (uint32_t i = 0; i < req.count; i++) {
    v4l2_buffer buf;
    memset(&buf, 0, sizeof(buf));
    buf.type = V4L2_BUF_TYPE_VIDEO_CAPTURE;
    buf.memory = V4L2_MEMORY_MMAP;
    buf.index = i;
    if (xioctl(fd, VIDIOC_QUERYBUF, &buf) < 0) {
      set_err(err, errlen, "VIDIOC_QUERYBUF failed");
      nns_v4l2_close(h);
      return nullptr;
    }
    h->lens[i] = buf.length;
    h->maps[i] = mmap(nullptr, buf.length, PROT_READ | PROT_WRITE,
                      MAP_SHARED, fd, buf.m.offset);
    if (h->maps[i] == MAP_FAILED) {
      h->maps[i] = nullptr;
      set_err(err, errlen, "mmap of capture buffer failed");
      nns_v4l2_close(h);
      return nullptr;
    }
    if (xioctl(fd, VIDIOC_QBUF, &buf) < 0) {
      set_err(err, errlen, "initial VIDIOC_QBUF failed");
      nns_v4l2_close(h);
      return nullptr;
    }
  }
  v4l2_buf_type type = V4L2_BUF_TYPE_VIDEO_CAPTURE;
  if (xioctl(fd, VIDIOC_STREAMON, &type) < 0) {
    set_err(err, errlen, "VIDIOC_STREAMON failed");
    nns_v4l2_close(h);
    return nullptr;
  }
  return h;
}

long nns_v4l2_frame_bytes(void *handle) {
  return (long)((V4l2Cap *)handle)->frame_bytes;
}

long nns_v4l2_stride(void *handle) {
  return (long)((V4l2Cap *)handle)->stride;
}

// One frame into `out` (<= cap bytes).  Returns payload bytes, 0 on
// timeout (caller polls its stop event and retries), <0 on device error.
long nns_v4l2_capture(void *handle, uint8_t *out, uint64_t cap,
                      int timeout_ms) {
  V4l2Cap *h = (V4l2Cap *)handle;
  fd_set fds;
  FD_ZERO(&fds);
  FD_SET(h->fd, &fds);
  timeval tv;
  tv.tv_sec = timeout_ms / 1000;
  tv.tv_usec = (timeout_ms % 1000) * 1000;
  int r = select(h->fd + 1, &fds, nullptr, nullptr, &tv);
  if (r == 0) return 0;
  if (r < 0) return -1;
  v4l2_buffer buf;
  memset(&buf, 0, sizeof(buf));
  buf.type = V4L2_BUF_TYPE_VIDEO_CAPTURE;
  buf.memory = V4L2_MEMORY_MMAP;
  if (xioctl(h->fd, VIDIOC_DQBUF, &buf) < 0) {
    return errno == EAGAIN ? 0 : -1;
  }
  uint64_t n = buf.bytesused ? buf.bytesused : h->frame_bytes;
  if (n > cap) n = cap;
  memcpy(out, h->maps[buf.index], n);
  if (xioctl(h->fd, VIDIOC_QBUF, &buf) < 0) return -1;
  return (long)n;
}

void nns_v4l2_close(void *handle) {
  V4l2Cap *h = (V4l2Cap *)handle;
  if (h->fd >= 0) {
    v4l2_buf_type type = V4L2_BUF_TYPE_VIDEO_CAPTURE;
    xioctl(h->fd, VIDIOC_STREAMOFF, &type);
  }
  for (uint32_t i = 0; i < 16; i++) {
    if (h->maps[i]) munmap(h->maps[i], h->lens[i]);
  }
  if (h->fd >= 0) close(h->fd);
  delete h;
}

}  // extern "C"
