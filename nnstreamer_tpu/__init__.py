"""nnstreamer_tpu — a TPU-native streaming AI pipeline framework.

A ground-up re-design of the NNStreamer capability surface
(reference: suehdn/nnstreamer; see SURVEY.md) for TPU hardware:

* gst-launch-style declarative pipelines of tensor elements
  (``tensor_converter``, ``tensor_transform``, ``tensor_filter``,
  ``tensor_decoder``, mux/demux/if/crop/aggregator, query/edge distribution,
  on-device training),
* executed by an async stage executor whose device stages are **fused into
  single jitted XLA programs** with buffers resident in HBM between stages,
* models dispatched through JAX/PJRT instead of per-vendor NPU SDKs,
* multi-chip scale via ``jax.sharding`` meshes + XLA collectives over ICI,
  multi-host feed over DCN/gRPC instead of TCP/MQTT.

Quick start::

    import nnstreamer_tpu as nt

    pipe = nt.parse_launch(
        "appsrc name=src ! tensor_converter ! "
        "tensor_transform mode=arithmetic option=typecast:float32,add:-127.5,div:127.5 ! "
        "tensor_filter framework=jax model=mobilenet_v1 ! "
        "tensor_decoder mode=image_labeling labels=imagenet ! tensor_sink name=out"
    )
    with nt.Pipeline(pipe) as p:
        p.push("src", frame)            # numpy HWC uint8 frame
        label = p.pull("out")
"""

from .core.types import (  # noqa: F401
    TENSOR_COUNT_LIMIT,
    TENSOR_RANK_LIMIT,
    TensorFormat,
    TensorSpec,
    TensorsSpec,
    dtype_from_name,
    dtype_name,
    parse_dims,
)
from .core.buffer import Buffer, Event  # noqa: F401
from .core.caps import Caps, MediaType  # noqa: F401
from .core import registry  # noqa: F401
from .core.registry import (  # noqa: F401
    register_converter,
    register_decoder,
    register_element,
    register_filter,
    register_trainer,
)
from .pipeline.parser import parse as parse_launch  # noqa: F401
from .pipeline.parser import ParseError  # noqa: F401
from .pipeline.graph import PipelineGraph  # noqa: F401
from .pipeline.runtime import Pipeline  # noqa: F401
from .elements.filter import SingleShot  # noqa: F401
from .analysis import PipelineLintError, analyze  # noqa: F401

__version__ = "0.1.0"
