"""Trainer sub-plugin API + the JAX/optax trainer (nns-learn).

Reference analog: the trainer sub-plugin vtable
(``nnstreamer_plugin_api_trainer.h``: create/destroy/start/stop/push_data/
getStatus) and its one implementation
``ext/nnstreamer/tensor_trainer/tensor_trainer_nntrainer.cc`` (SURVEY §2.8,
upstream-reconstructed).  The reference bridges to the external nntrainer C++
library; the TPU-native build trains with jitted optax steps instead.

TPU-first design (docs/TRAINING.md):

* **Device-resident state.**  Params and optimizer state live in HBM for
  the stage lifetime; the update step donates both, so steady-state
  training allocates nothing — the PR 10 aggregator-ring discipline.
* **Streaming window, not host accumulation.**  Samples append into a
  fixed ``[batch_size, ...]`` HBM window IN-PROGRAM
  (``dynamic_update_slice`` at a traced index — the device-aggregator
  ring's exact move) and a full window dispatches one update step; the
  host never holds an epoch of samples.  ``host-accumulate=true`` keeps
  the legacy stack-the-epoch path for A/B comparison
  (``bench.py --config train_stream``).
* **Closed census.**  The stage compiles exactly
  :data:`TRAINER_PROGRAMS` programs for its lifetime — append, step,
  eval — with every shape static (a partial tail window steps through
  the SAME program via a masked loss with the live-count as a VALUE).
  ``jit._cache_size`` is pinned by tests and the deep lint prices the
  census via :func:`train_plan`, the same shared-arithmetic discipline
  as ``filters/llm.serving_plan``.
* **Mesh sharding.**  ``mesh=data:N`` (or ``data:N,model:M``) runs the
  step over an ICI mesh: the window's batch dim shards over ``data``
  (gradients all-reduced by GSPMD), params place per the zoo bundle's
  ``param_pspecs`` — model-axis leaves shard M ways, the rest replicate
  — so training scales exactly like serving (docs/BATCHING.md "2-D
  sharded dispatch").
"""

from __future__ import annotations

import threading
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..core.log import logger, metrics
from ..core.registry import register_trainer

log = logger("trainer")

#: compiled programs one streaming JaxTrainer runs for its LIFETIME
#: (append, update step, validation eval) — the fixed-signature census
#: the deep lint prices (analysis/tracecheck.py) and nns-xray verifies
#: live (the llm serve loop's 3-program discipline)
TRAINER_PROGRAMS = 3


class TrainerError(RuntimeError):
    pass


class TrainerSubplugin:
    """Base class for tensor_trainer sub-plugins.

    Lifecycle (driven by the tensor_trainer element):
    ``open(props)`` → N× ``push_data(inputs, labels, is_validation)`` →
    ``train_epoch()`` per completed epoch → ``save(path)`` → ``close()``.
    """

    name: str = "base"

    def __init__(self):
        self.props: Dict[str, object] = {}

    def open(self, props: Dict[str, object]) -> None:
        # Keep the element's own (tracked) dict — see filters/base.py.
        self.props = props if isinstance(props, dict) else dict(props)

    def push_data(
        self, inputs: Sequence[np.ndarray], labels: Sequence[np.ndarray], is_validation: bool
    ) -> None:
        raise NotImplementedError

    def train_epoch(self) -> Dict[str, float]:
        """Consume the queued epoch of samples; returns stats:
        training_loss / training_accuracy / validation_loss /
        validation_accuracy (NaN where not applicable)."""
        raise NotImplementedError

    def save(self, path: str) -> str:
        raise NotImplementedError

    def load(self, path: str) -> None:
        raise NotImplementedError

    def queued(self) -> Tuple[int, int]:
        """(n_train, n_valid) samples awaiting train_epoch; the element uses
        this at EOS to decide whether a partial epoch remains."""
        return (0, 0)

    def close(self) -> None:
        pass


def _mlp_layer_shapes(layer_sizes: List[int]) -> List[Dict[str, tuple]]:
    """Shapes of :func:`_build_mlp`'s param tree without materializing it
    — the static pricing path (:func:`train_plan`) derives opt-state and
    gradient bytes from these via ``jax.eval_shape``."""
    return [
        {"w": (fan_in, fan_out), "b": (fan_out,)}
        for fan_in, fan_out in zip(layer_sizes[:-1], layer_sizes[1:])
    ]


def _build_mlp(layer_sizes: List[int], seed: int):
    """Tiny trainable MLP used when no zoo model is named.

    Returns (params, apply).  Kept deliberately simple — real models come
    from the zoo (models/mobilenet.py has init_params/param_pspecs).
    """
    rng = np.random.default_rng(seed)
    params = []
    for fan_in, fan_out in zip(layer_sizes[:-1], layer_sizes[1:]):
        scale = np.sqrt(2.0 / fan_in)
        params.append(
            {
                "w": (rng.standard_normal((fan_in, fan_out)) * scale).astype(np.float32),
                "b": np.zeros((fan_out,), np.float32),
            }
        )

    def apply(params, x):
        import jax.numpy as jnp

        h = x.reshape((x.shape[0], -1)).astype(jnp.float32)
        for i, layer in enumerate(params):
            h = h @ layer["w"] + layer["b"]
            if i < len(params) - 1:
                h = jnp.maximum(h, 0.0)
        return h

    return params, apply


def _make_optimizer(opt: str, lr: float):
    import optax

    if opt == "sgd":
        return optax.sgd(lr)
    if opt == "momentum":
        return optax.sgd(lr, momentum=0.9)
    return optax.adam(lr)


def _tree_nbytes(tree) -> int:
    """The ONE accounting walk (``filters/base.tree_param_bytes`` —
    nbytes when the leaf carries it, shape x itemsize for abstract
    leaves like eval_shape's ShapeDtypeStructs), so static pricing and
    the live ledger can never diverge arithmetically."""
    from ..filters.base import tree_param_bytes

    return tree_param_bytes(tree)


def train_plan(props: Dict[str, object]) -> Optional[Dict[str, object]]:
    """Static resource plan for one jax tensor_trainer stage — the ONE
    home for the arithmetic the deep lint prices "train state" with
    (analysis/tracecheck.py) and the runtime publishes to nns-xray, the
    ``filters/llm.serving_plan`` discipline.  Returns::

        {"param_bytes", "opt_bytes", "grad_bytes", "window_bytes",
         "programs", "batch_size", "pspecs", "params"}

    * ``opt_bytes`` — the optax state tree ABSTRACTED via
      ``jax.eval_shape(tx.init, params)``: no optimizer state ever
      materializes here;
    * ``grad_bytes`` — one gradient tree (== param bytes), transient per
      step (priced as activation-class HBM, not resident state);
    * ``window_bytes`` — the device-resident streaming sample window
      (``batch_size`` x (input + label bytes), label approximated as one
      int32 class id for ``softmax_ce`` when the stream's spec is not
      known statically);
    * ``pspecs`` / ``params`` — for the ``_pspec_audit`` model-axis walk
      (zoo bundles; ``None`` for the ad-hoc MLP).

    ``None`` when the model config cannot be resolved statically (the
    caller diagnoses ``training-unpriced``).  MLP params ARE materialized
    (a few KiB); zoo builds are the same test-scale bundles the deep
    pass already traces in ``_trace_node``.
    """
    model = str(props.get("model", props.get("model_config", "mlp:4:16:3")))
    bs = int(props.get("batch_size", props.get("batch-size", 16)))
    opt = str(props.get("optimizer", "adam"))
    lr = float(props.get("learning_rate", props.get("learning-rate", 1e-3)))
    import jax

    pspecs = None
    if model.startswith("mlp:"):
        try:
            sizes = [int(s) for s in model.split(":")[1:]]
        except ValueError:
            return None
        if len(sizes) < 2:
            return None
        params = [
            {"w": jax.ShapeDtypeStruct(s["w"], np.float32),
             "b": jax.ShapeDtypeStruct(s["b"], np.float32)}
            for s in _mlp_layer_shapes(sizes)
        ]
        in_bytes = sizes[0] * 4
        live_params = None
    else:
        from ..models import zoo

        try:
            opts = {k: str(v) for k, v in props.items()
                    if k in ("classes", "width", "size", "seed")}
            bundle = zoo.build(model, opts)
        except Exception:  # noqa: BLE001 - unpriceable, caller diagnoses
            return None
        live_params = bundle.params
        params = jax.tree_util.tree_map(
            lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype)
            if hasattr(a, "shape") and hasattr(a, "dtype") else a,
            bundle.params)
        pspecs = getattr(bundle, "param_pspecs", None)
        in_bytes = (int(bundle.in_spec.nbytes)
                    if bundle.in_spec is not None else 0)
    tx = _make_optimizer(opt, lr)
    try:
        opt_sds = jax.eval_shape(tx.init, params)
    except Exception:  # noqa: BLE001 - exotic trees: price params only
        opt_sds = None
    param_bytes = _tree_nbytes(params)
    label_bytes = 4  # one int32 class id (softmax_ce); mse streams vary
    if str(props.get("loss", "softmax_ce")) == "mse":
        label_bytes = in_bytes  # worst case: regression target ~ input
    return {
        "param_bytes": param_bytes,
        "opt_bytes": _tree_nbytes(opt_sds) if opt_sds is not None else 0,
        "grad_bytes": param_bytes,
        "window_bytes": bs * (in_bytes + label_bytes),
        "programs": TRAINER_PROGRAMS,
        "batch_size": bs,
        "pspecs": pspecs,
        "params": live_params,
    }


@register_trainer("jax")
class JaxTrainer(TrainerSubplugin):
    """Optax-based streaming trainer (see module docstring).

    Props (via tensor_trainer's ``framework-props`` / element props):

    * ``model`` — ``mlp:IN:HIDDEN:...:OUT`` or a zoo name (``mobilenet_v1``)
      whose builder accepts ``classes``/``width`` options;
    * ``optimizer`` — ``sgd`` | ``momentum`` | ``adam`` (default);
    * ``learning-rate`` — float, default 1e-3;
    * ``loss`` — ``softmax_ce`` (labels are int class ids or one-hot) |
      ``mse``;
    * ``batch-size`` — the streaming window width (default 16);
    * ``seed`` — param init seed;
    * ``mesh`` — ``data:N`` (batch sharded over N chips, grads
      all-reduced) or ``data:N,model:M`` (params additionally sharded
      per the bundle's ``param_pspecs``);
    * ``model-load-path`` — checkpoint to resume from (params, optimizer
      moments AND step counter restore — continuation is bit-identical);
    * ``host-accumulate`` — ``true`` keeps the legacy
      stack-the-whole-epoch host path (the bench A/B baseline).
    """

    name = "jax"

    def __init__(self):
        super().__init__()
        self._valid: List[Tuple[List[np.ndarray], List[np.ndarray]]] = []
        self._host_train: List[Tuple[List[np.ndarray], List[np.ndarray]]] = []
        self._lock = threading.Lock()
        self.params = None
        self.apply_fn: Optional[Callable] = None
        self.opt_state = None
        self._tx = None
        self._append_fn = None
        self._step_fn = None
        self._eval_fn = None
        self.step = 0
        self._mesh = None
        self._batch_sharding = None
        # streaming-window state (device arrays once the first sample's
        # shape is known)
        self._wx = None
        self._wy = None
        self._fill = 0  # samples in the window not yet stepped
        self._pending = 0  # samples pushed since the last train_epoch
        self._losses: List[float] = []
        self._accs: List[float] = []
        # nns-xray handoff (attach_xray): the three programs register
        # their compiles under "<stage>.learn"
        self._xray = None
        self._xray_stage = None
        self._xray_rec = None

    # -- lifecycle ---------------------------------------------------------
    def open(self, props: Dict[str, object]) -> None:
        super().open(props)

        model = str(props.get("model", "mlp:4:16:3"))
        seed = int(props.get("seed", 0))
        self._pspecs = None
        if model.startswith("mlp:"):
            sizes = [int(s) for s in model.split(":")[1:]]
            self.params, self.apply_fn = _build_mlp(sizes, seed)
        else:
            from ..models import zoo

            opts = {
                k: str(v)
                for k, v in props.items()
                if k in ("classes", "width", "size", "seed")
            }
            bundle = zoo.build(model, opts)
            self.params, self.apply_fn = bundle.params, bundle.apply_fn
            self._pspecs = getattr(bundle, "param_pspecs", None)

        lr = float(props.get("learning_rate", props.get("learning-rate", 1e-3)))
        opt = str(props.get("optimizer", "adam"))
        self._tx = _make_optimizer(opt, lr)

        self.loss_kind = str(props.get("loss", "softmax_ce"))
        self.batch_size = int(props.get("batch_size", props.get("batch-size", 16)))
        self.host_accumulate = str(
            props.get("host_accumulate", props.get("host-accumulate", "false"))
        ).lower() in ("true", "1", "yes")

        mesh_prop = str(props.get("mesh", "") or "")
        if mesh_prop:
            self._setup_mesh(mesh_prop)

        # A checkpoint's opt_state (Adam moments etc.) wins over a fresh
        # init; under a mesh the fresh init happens AFTER placement
        # (inside _place_on_mesh) so moments inherit each placed leaf's
        # sharding and a full-size pre-placement tree is never built
        # just to be discarded.
        load = props.get("model_load_path") or props.get("model-load-path")
        if load:
            self.load(str(load))
        if self._mesh is not None:
            self._place_on_mesh()
        else:
            if self.opt_state is None:
                self.opt_state = self._tx.init(self.params)
            self._commit_to_device()

    def _commit_to_device(self) -> None:
        """Commit params + opt state to device arrays UP FRONT (the llm
        serve loop's carried-state discipline): jit's fast path keys on
        argument TYPE, so a first step fed host numpy leaves would mint
        a second cache entry and break the 3-program census pin."""
        import jax
        import jax.numpy as jnp

        as_dev = lambda t: jax.tree_util.tree_map(  # noqa: E731
            lambda a: jnp.asarray(a) if hasattr(a, "shape") else a, t)
        self.params = as_dev(self.params)
        if self.opt_state is not None:
            self.opt_state = as_dev(self.opt_state)

    def _setup_mesh(self, spec: str) -> None:
        """``data:N`` / ``data:N,model:M`` — the same (data, model) axes
        the serving pipeline places on (pipeline/plan.mesh_plan)."""
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        from ..parallel import make_mesh

        axes = {"data": 0, "model": 1}
        sizes = {"data": 1, "model": 1}
        for part in spec.split(","):
            part = part.strip()
            if not part:
                continue
            name, _, n = part.partition(":")
            name = name.strip() or "data"
            if name not in axes:
                raise TrainerError(
                    f"bad mesh spec {spec!r}: axis {name!r} (valid: "
                    "data, model)")
            sizes[name] = int(n) if n else len(jax.devices())
        need = sizes["data"] * sizes["model"]
        if len(jax.devices()) < need:
            raise TrainerError(
                f"mesh {spec!r} needs {need} devices, have "
                f"{len(jax.devices())}")
        kw = {"data": sizes["data"]}
        if sizes["model"] > 1:
            kw["model"] = sizes["model"]
        self._mesh = make_mesh(devices=jax.devices()[:need], **kw)
        self._batch_sharding = NamedSharding(self._mesh, P("data"))

    def _place_on_mesh(self) -> None:
        """Params + opt state onto the mesh: leaves whose ``param_pspecs``
        name the ``model`` axis shard over it, everything else replicates
        (``parallel/sharding.shard_params`` — the exact placement
        ``Element.place_params`` runs for serving stages).  The opt state
        is re-initialized FROM the placed params so Adam moments inherit
        each leaf's sharding; a checkpoint-resumed opt state is placed
        leaf-by-leaf alongside instead."""
        from ..parallel.mesh import mesh_axis_size
        from ..parallel.sharding import replicate, shard_params

        old_opt = self.opt_state  # non-None only when a checkpoint loaded
        if mesh_axis_size(self._mesh, "model") > 1 and self._pspecs is not None:
            from ..parallel.sharding import placement_split

            n_shard, n_rep = placement_split(self.params, self._pspecs)
            self.params = shard_params(self._mesh, self.params, self._pspecs)
            # shard-vs-replica split: proof of model-axis placement, the
            # serving stages' counter convention (elements/filter.py)
            metrics.count("trainer.param_shards", n_shard)
            metrics.count("trainer.param_replicas", n_rep)
        else:
            self.params = replicate(self._mesh, self.params)
            metrics.count("trainer.param_replications")
        if old_opt is not None:
            # a checkpoint-resumed opt state replicates onto the mesh:
            # its tree shape does not pair with param pspecs (optax
            # nests params-shaped trees inside namedtuples), and
            # replicated moments are always CORRECT — GSPMD re-shards
            # them through the step's output shardings if beneficial
            self.opt_state = replicate(self._mesh, old_opt)
        else:
            # commit EVERY opt leaf to the mesh up front (the llm serve
            # loop's carried-state discipline): zeros_like inherits the
            # param leaf's placement, but optax's step counter is a
            # fresh uncommitted scalar — after the first step it comes
            # back mesh-committed, and that sharding flip would mint a
            # second step signature (census drift)
            import jax
            from jax.sharding import NamedSharding, PartitionSpec as P

            rep = NamedSharding(self._mesh, P())
            self.opt_state = jax.tree_util.tree_map(
                lambda a: (a if getattr(a, "committed", False)
                           else jax.device_put(a, rep))
                if hasattr(a, "shape") else a,
                self._tx.init(self.params))

    # -- nns-xray ----------------------------------------------------------
    def attach_xray(self, registry, stage: str, rec=None) -> None:
        """Install the predicted census (append/step/eval = one compile
        each — :data:`TRAINER_PROGRAMS`) and track the jitted programs
        under ``<stage>.learn``; idempotent, the ``Framework.attach_xray``
        contract."""
        self._xray = registry
        self._xray_stage = f"{stage}.learn"
        self._xray_rec = rec
        registry.expect(self._xray_stage, "append", budget=1,
                        note="train_plan streaming-window append")
        registry.expect(self._xray_stage, "step", budget=1,
                        note="train_plan fixed update-step signature")
        registry.expect(self._xray_stage, "eval", budget=1,
                        note="train_plan validation eval")
        self._wrap_xray()

    def _wrap_xray(self) -> None:
        xr = self._xray
        if xr is None:
            return
        if self._append_fn is not None:
            self._append_fn = xr.track(self._append_fn, self._xray_stage,
                                       "append", rec=self._xray_rec)
        if self._step_fn is not None:
            self._step_fn = xr.track(self._step_fn, self._xray_stage,
                                     "step", rec=self._xray_rec)
        if self._eval_fn is not None:
            self._eval_fn = xr.track(self._eval_fn, self._xray_stage,
                                     "eval", rec=self._xray_rec)

    # -- data --------------------------------------------------------------
    def push_data(self, inputs, labels, is_validation: bool) -> None:
        if len(inputs) != 1 or len(labels) != 1:
            # Silently training on inputs[0] would corrupt multi-input runs.
            raise TrainerError(
                f"{self.name} trains single-input/single-label models; got "
                f"{len(inputs)} inputs, {len(labels)} labels"
            )
        sample = ([np.asarray(t) for t in inputs], [np.asarray(t) for t in labels])
        if is_validation:
            with self._lock:
                self._valid.append(sample)
            return
        if self.host_accumulate:
            with self._lock:
                self._host_train.append(sample)
                self._pending += 1
            return
        with self._lock:
            self._append_sample(sample[0][0], sample[1][0])
            self._pending += 1
            if self._fill >= self.batch_size:
                self._dispatch_step(self._fill)
                self._fill = 0

    def queued(self) -> Tuple[int, int]:
        """Samples not yet consumed by a ``train_epoch`` (streamed samples
        already stepped still count: their epoch stats await collection)."""
        with self._lock:
            return self._pending, len(self._valid)

    # -- device window -----------------------------------------------------
    def _ensure_window(self, x: np.ndarray, y: np.ndarray) -> None:
        if self._wx is not None:
            return
        import jax.numpy as jnp

        bs = max(1, self.batch_size)
        # label window keeps the per-sample shape; the trailing-singleton
        # collapse happens inside the step's loss math
        self._wx = jnp.zeros((bs,) + tuple(x.shape), jnp.asarray(x).dtype)
        self._wy = jnp.zeros((bs,) + tuple(y.shape), jnp.asarray(y).dtype)
        if self._mesh is not None:
            # mesh-committed like params/opt: the step's donated outputs
            # come back committed, and an uncommitted first-call window
            # would flip the arg sharding and mint a second signature
            import jax
            from jax.sharding import NamedSharding, PartitionSpec as P

            rep = NamedSharding(self._mesh, P())
            self._wx = jax.device_put(self._wx, rep)
            self._wy = jax.device_put(self._wy, rep)
        self._build_programs()

    def _append_sample(self, x: np.ndarray, y: np.ndarray) -> None:
        self._ensure_window(x, y)
        # np.int32 index CONSISTENTLY: mixing python ints in would mint a
        # weak-typed second signature (the census-drift trap nns-xray
        # catches — utils/xray.abstract_signature)
        self._wx, self._wy = self._append_fn(
            self._wx, self._wy, np.int32(self._fill), np.asarray(x),
            np.asarray(y))
        self._fill += 1

    def _dispatch_step(self, count: int) -> None:
        """One fixed-shape update step over the window's first ``count``
        rows (masked loss — a partial tail window reuses the SAME
        compiled program; ``count`` is a VALUE, never a shape)."""
        self.params, self.opt_state, loss, acc = self._step_fn(
            self.params, self.opt_state, self._wx, self._wy,
            np.int32(count))
        self._losses.append(float(loss))
        self._accs.append(float(acc))
        self.step += 1

    # -- math --------------------------------------------------------------
    def _per_example_loss(self, params, x, y):
        """Per-row (loss, correct) — shared by the masked step and the
        validation eval so both paths compute the same math."""
        import jax
        import jax.numpy as jnp

        logits = self.apply_fn(params, x)
        if isinstance(logits, (tuple, list)):
            logits = logits[0]
        if self.loss_kind == "mse":
            per = jnp.mean(
                (logits - y.reshape(logits.shape)) ** 2,
                axis=tuple(range(1, logits.ndim)))
            correct = jnp.full(per.shape, jnp.nan, per.dtype)
        else:
            if y.ndim >= 2 and y.shape[-1] == logits.shape[-1]:
                labels = jnp.argmax(y.reshape((y.shape[0], -1)), axis=-1)
            else:
                labels = y.reshape((y.shape[0],)).astype(jnp.int32)
            logp = jax.nn.log_softmax(logits)
            per = -jnp.take_along_axis(logp, labels[:, None], axis=1)[:, 0]
            correct = (jnp.argmax(logits, axis=-1) == labels).astype(
                jnp.float32)
        return per, correct

    def _masked_stats(self, params, x, y, count):
        import jax.numpy as jnp

        per, correct = self._per_example_loss(params, x, y)
        mask = (jnp.arange(per.shape[0]) < count).astype(per.dtype)
        cf = count.astype(per.dtype) if hasattr(count, "astype") \
            else jnp.asarray(count, per.dtype)
        loss = jnp.sum(per * mask) / cf
        acc = jnp.sum(correct * mask) / cf
        return loss, acc

    def _build_programs(self) -> None:
        import jax
        from jax import lax

        # donation reuses the window/params/opt HBM in place — steady-
        # state training allocates nothing.  CPU backends can't donate
        # and would warn per compile (the FusedElement gate).
        donate = jax.default_backend() not in ("cpu",)

        win_sh = None
        if self._mesh is not None:
            # the step's output-pinning rule applies to append too: the
            # donated window must come back with its INPUT sharding, or
            # the second call's flipped arg sharding mints a phantom
            # append signature (census drift)
            win_sh = getattr(self._wx, "sharding", None)

        def append(wx, wy, i, x, y):
            wx = lax.dynamic_update_slice(
                wx, x[None].astype(wx.dtype), (i,) + (0,) * (wx.ndim - 1))
            wy = lax.dynamic_update_slice(
                wy, y[None].astype(wy.dtype), (i,) + (0,) * (wy.ndim - 1))
            if win_sh is not None:
                wx = lax.with_sharding_constraint(wx, win_sh)
                wy = lax.with_sharding_constraint(wy, win_sh)
            return wx, wy

        self._append_fn = jax.jit(
            append, donate_argnums=(0, 1) if donate else ())

        constrain = self._batch_sharding
        pin_p = pin_o = None
        if self._mesh is not None:
            # pin the step's donated outputs to the INPUT placement: a
            # model-sharded leaf whose output sharding GSPMD re-decided
            # would flip the next call's arg shardings and mint a second
            # step signature (census drift)
            from jax.sharding import NamedSharding, PartitionSpec as P

            rep = NamedSharding(self._mesh, P())
            shs = lambda t: jax.tree_util.tree_map(  # noqa: E731
                lambda a: getattr(a, "sharding", None) or rep, t)
            pin_p, pin_o = shs(self.params), shs(self.opt_state)

        def _pin(tree, shardings):
            if shardings is None:
                return tree
            return jax.tree_util.tree_map(
                lambda t, s: lax.with_sharding_constraint(t, s),
                tree, shardings)

        def step(params, opt_state, wx, wy, count):
            if constrain is not None:
                wx = lax.with_sharding_constraint(wx, constrain)

            def loss_fn(p):
                return self._masked_stats(p, wx, wy, count)

            (loss, acc), grads = jax.value_and_grad(
                loss_fn, has_aux=True)(params)
            updates, opt_state = self._tx.update(grads, opt_state, params)
            params = jax.tree_util.tree_map(
                lambda p, u: p + u, params, updates)
            return _pin(params, pin_p), _pin(opt_state, pin_o), loss, acc

        self._step_fn = jax.jit(
            step, donate_argnums=(0, 1) if donate else ())

        def evaluate(params, x, y, count):
            # the step's masked math over the step's [batch-size] window
            # shape: validation runs in window-sized chunks, so the eval
            # signature is FIXED regardless of the validation-set size
            # (a varying set — e.g. the partial epoch flushed at EOS —
            # must not mint a second program and fire false drift)
            return self._masked_stats(params, x, y, count)

        self._eval_fn = jax.jit(evaluate)
        self._wrap_xray()

    def compile_counts(self) -> Dict[str, int]:
        """Live ``jit._cache_size`` per program — the census pin tests
        read (append/step/eval must each stay at 1 across epoch churn)."""
        out = {}
        for kind, fn in (("append", self._append_fn),
                         ("step", self._step_fn),
                         ("eval", self._eval_fn)):
            if fn is None:
                continue
            try:
                out[kind] = int(fn._cache_size())
            except Exception:  # noqa: BLE001 - non-jit wrapper
                out[kind] = -1
        return out

    # -- epochs ------------------------------------------------------------
    def train_epoch(self) -> Dict[str, float]:
        with self._lock:
            if self.host_accumulate:
                train, self._host_train = self._host_train, []
                if not train:
                    raise TrainerError(
                        "train_epoch called with no queued samples")
                self._train_host(train)
            else:
                if self._pending == 0:
                    raise TrainerError(
                        "train_epoch called with no queued samples")
                if self._fill:
                    # partial tail window: masked step through the SAME
                    # program — count is a value, the census stays closed
                    self._dispatch_step(self._fill)
                    self._fill = 0
            losses, self._losses = self._losses, []
            accs, self._accs = self._accs, []
            valid, self._valid = self._valid, []
            self._pending = 0

        stats = {
            "training_loss": float(np.mean(losses)) if losses else float("nan"),
            "training_accuracy": float(np.mean(accs)) if accs else float("nan"),
            "validation_loss": float("nan"),
            "validation_accuracy": float("nan"),
        }
        if valid:
            if self._eval_fn is None:
                self._ensure_window(valid[0][0][0], valid[0][1][0])
            import jax.numpy as jnp

            bs = max(1, self.batch_size)
            tot_l = tot_a = 0.0
            for off in range(0, len(valid), bs):
                chunk = valid[off:off + bs]
                x = np.stack([s[0][0] for s in chunk])
                y = np.stack([s[1][0] for s in chunk])
                n = x.shape[0]
                if n < bs:  # pad to the window shape; the mask hides it
                    x = np.concatenate(
                        [x, np.zeros((bs - n,) + x.shape[1:], x.dtype)])
                    y = np.concatenate(
                        [y, np.zeros((bs - n,) + y.shape[1:], y.dtype)])
                vl, va = self._eval_fn(self.params, jnp.asarray(x),
                                       jnp.asarray(y), np.int32(n))
                tot_l += float(vl) * n
                tot_a += float(va) * n
            stats["validation_loss"] = tot_l / len(valid)
            stats["validation_accuracy"] = tot_a / len(valid)
        log.debug("epoch stats %s", stats)
        return stats

    def _train_host(self, train) -> None:
        """Legacy host-accumulated epoch (``host-accumulate=true``): the
        whole epoch stacks on host, minibatches slice from the stack.
        Kept as the ``bench.py --config train_stream`` A/B baseline; the
        step program is SHARED with the streaming path (same masked
        signature), so the census stays closed either way."""
        bs = max(1, self.batch_size)
        self._ensure_window(train[0][0][0], train[0][1][0])
        import jax.numpy as jnp

        for off in range(0, len(train), bs):
            chunk = train[off:off + bs]
            x = np.stack([s[0][0] for s in chunk])
            y = np.stack([s[1][0] for s in chunk])
            n = x.shape[0]
            if n < bs:  # pad to the window shape; the mask hides the pad
                x = np.concatenate(
                    [x, np.zeros((bs - n,) + x.shape[1:], x.dtype)])
                y = np.concatenate(
                    [y, np.zeros((bs - n,) + y.shape[1:], y.dtype)])
            self.params, self.opt_state, loss, acc = self._step_fn(
                self.params, self.opt_state, jnp.asarray(x),
                jnp.asarray(y), np.int32(n))
            self._losses.append(float(loss))
            self._accs.append(float(acc))
            self.step += 1

    # -- live accounting (nns-xray HBM ledger) ------------------------------
    def param_nbytes(self) -> int:
        return _tree_nbytes(self.params) if self.params is not None else 0

    def train_state_bytes(self) -> int:
        """Device-resident training state: optimizer moments + the
        streaming sample window — the bytes the ledger's ``train_state``
        category reconciles against :func:`train_plan` (gradients are
        transient per step and priced as activations)."""
        total = _tree_nbytes(self.opt_state) if self.opt_state is not None \
            else 0
        for w in (self._wx, self._wy):
            if w is not None:
                total += int(getattr(w, "nbytes", 0) or 0)
        return total

    def export_params(self):
        """The CURRENT param tree (device arrays) — what
        ``Pipeline.swap_params`` moves into a serving stage.  The serve
        side device_puts per its own placement, so handing live arrays
        is safe (the swap never mutates them)."""
        return self.params

    # -- persistence -------------------------------------------------------
    def save(self, path: str) -> str:
        from .checkpoint import save_checkpoint

        got = save_checkpoint(path, self.params, self.opt_state, self.step,
                              fsync=True)
        metrics.count("trainer.ckpt_writes")
        return got

    def load(self, path: str) -> None:
        from .checkpoint import load_checkpoint

        self.params, opt_state, self.step = load_checkpoint(path)
        if opt_state is not None:
            self.opt_state = opt_state
