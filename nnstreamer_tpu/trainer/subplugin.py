"""Trainer sub-plugin API + the JAX/optax trainer.

Reference analog: the trainer sub-plugin vtable
(``nnstreamer_plugin_api_trainer.h``: create/destroy/start/stop/push_data/
getStatus) and its one implementation
``ext/nnstreamer/tensor_trainer/tensor_trainer_nntrainer.cc`` (SURVEY §2.8,
upstream-reconstructed).  The reference bridges to the external nntrainer C++
library; the TPU-native build trains with a **jitted optax step** instead —
the whole epoch's minibatch loop is a ``lax.scan`` inside one XLA program, so
training rides the MXU exactly like inference does.

Multi-chip: pass ``mesh=data:N`` in props to shard the batch dim over an ICI
mesh (data-parallel; gradients all-reduced by XLA via the sharded jit).
"""

from __future__ import annotations

import threading
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..core.log import logger
from ..core.registry import register_trainer

log = logger("trainer")


class TrainerError(RuntimeError):
    pass


class TrainerSubplugin:
    """Base class for tensor_trainer sub-plugins.

    Lifecycle (driven by the tensor_trainer element):
    ``open(props)`` → N× ``push_data(inputs, labels, is_validation)`` →
    ``train_epoch()`` per completed epoch → ``save(path)`` → ``close()``.
    """

    name: str = "base"

    def __init__(self):
        self.props: Dict[str, object] = {}

    def open(self, props: Dict[str, object]) -> None:
        # Keep the element's own (tracked) dict — see filters/base.py.
        self.props = props if isinstance(props, dict) else dict(props)

    def push_data(
        self, inputs: Sequence[np.ndarray], labels: Sequence[np.ndarray], is_validation: bool
    ) -> None:
        raise NotImplementedError

    def train_epoch(self) -> Dict[str, float]:
        """Consume the queued epoch of samples; returns stats:
        training_loss / training_accuracy / validation_loss /
        validation_accuracy (NaN where not applicable)."""
        raise NotImplementedError

    def save(self, path: str) -> str:
        raise NotImplementedError

    def load(self, path: str) -> None:
        raise NotImplementedError

    def queued(self) -> Tuple[int, int]:
        """(n_train, n_valid) samples awaiting train_epoch; the element uses
        this at EOS to decide whether a partial epoch remains."""
        return (0, 0)

    def close(self) -> None:
        pass


def _stack_labels(labels) -> "np.ndarray":
    """Stack per-sample labels into a batch, collapsing only the trailing
    singleton a scalar-class label carries ([1] per sample -> [B]); one-hot
    rows keep their class dimension even when the batch has one sample."""
    y = np.stack(labels)
    if y.ndim == 2 and y.shape[1] == 1:
        y = y[:, 0]
    return y


def _build_mlp(layer_sizes: List[int], seed: int):
    """Tiny trainable MLP used when no zoo model is named.

    Returns (params, apply).  Kept deliberately simple — real models come
    from the zoo (models/mobilenet.py has init_params/param_pspecs).
    """
    rng = np.random.default_rng(seed)
    params = []
    for fan_in, fan_out in zip(layer_sizes[:-1], layer_sizes[1:]):
        scale = np.sqrt(2.0 / fan_in)
        params.append(
            {
                "w": (rng.standard_normal((fan_in, fan_out)) * scale).astype(np.float32),
                "b": np.zeros((fan_out,), np.float32),
            }
        )

    def apply(params, x):
        import jax.numpy as jnp

        h = x.reshape((x.shape[0], -1)).astype(jnp.float32)
        for i, layer in enumerate(params):
            h = h @ layer["w"] + layer["b"]
            if i < len(params) - 1:
                h = jnp.maximum(h, 0.0)
        return h

    return params, apply


@register_trainer("jax")
class JaxTrainer(TrainerSubplugin):
    """Optax-based trainer.

    Props (via tensor_trainer's ``framework-props`` / element props):

    * ``model`` — ``mlp:IN:HIDDEN:...:OUT`` or a zoo name (``mobilenet_v1``)
      whose builder accepts ``classes``/``width`` options;
    * ``optimizer`` — ``sgd`` | ``momentum`` | ``adam`` (default);
    * ``learning-rate`` — float, default 1e-3;
    * ``loss`` — ``softmax_ce`` (labels are int class ids or one-hot) |
      ``mse``;
    * ``batch-size`` — minibatch size for the epoch scan (default 16);
    * ``seed`` — param init seed;
    * ``mesh`` — ``data:N`` to shard batches over N devices;
    * ``model-load-path`` — checkpoint to resume from.
    """

    name = "jax"

    def __init__(self):
        super().__init__()
        self._train: List[Tuple[List[np.ndarray], List[np.ndarray]]] = []
        self._valid: List[Tuple[List[np.ndarray], List[np.ndarray]]] = []
        self._lock = threading.Lock()
        self.params = None
        self.apply_fn: Optional[Callable] = None
        self.opt_state = None
        self._tx = None
        self._step_fn = None
        self._eval_fn = None
        self.step = 0
        self._sharding = None

    # -- lifecycle ---------------------------------------------------------
    def open(self, props: Dict[str, object]) -> None:
        super().open(props)
        import optax

        model = str(props.get("model", "mlp:4:16:3"))
        seed = int(props.get("seed", 0))
        if model.startswith("mlp:"):
            sizes = [int(s) for s in model.split(":")[1:]]
            self.params, self.apply_fn = _build_mlp(sizes, seed)
        else:
            from ..models import zoo

            opts = {
                k: str(v)
                for k, v in props.items()
                if k in ("classes", "width", "size", "seed")
            }
            bundle = zoo.build(model, opts)
            self.params, self.apply_fn = bundle.params, bundle.apply_fn

        lr = float(props.get("learning_rate", props.get("learning-rate", 1e-3)))
        opt = str(props.get("optimizer", "adam"))
        if opt == "sgd":
            self._tx = optax.sgd(lr)
        elif opt == "momentum":
            self._tx = optax.sgd(lr, momentum=0.9)
        else:
            self._tx = optax.adam(lr)

        self.loss_kind = str(props.get("loss", "softmax_ce"))
        self.batch_size = int(props.get("batch_size", props.get("batch-size", 16)))
        self.opt_state = self._tx.init(self.params)
        # Resume AFTER opt init so a checkpointed opt_state (Adam moments
        # etc.) overrides the fresh one instead of being clobbered.
        load = props.get("model_load_path") or props.get("model-load-path")
        if load:
            self.load(str(load))

        mesh_prop = str(props.get("mesh", "") or "")
        if mesh_prop:
            self._setup_mesh(mesh_prop)

    def _setup_mesh(self, spec: str) -> None:
        import jax
        from jax.sharding import NamedSharding, PartitionSpec as P

        from ..parallel import make_mesh

        n = int(spec.split(":", 1)[1]) if ":" in spec else len(jax.devices())
        mesh = make_mesh(data=n, devices=jax.devices()[:n])
        self._sharding = NamedSharding(mesh, P("data"))

    # -- data --------------------------------------------------------------
    def push_data(self, inputs, labels, is_validation: bool) -> None:
        if len(inputs) != 1 or len(labels) != 1:
            # Silently training on inputs[0] would corrupt multi-input runs.
            raise TrainerError(
                f"{self.name} trains single-input/single-label models; got "
                f"{len(inputs)} inputs, {len(labels)} labels"
            )
        sample = ([np.asarray(t) for t in inputs], [np.asarray(t) for t in labels])
        with self._lock:
            (self._valid if is_validation else self._train).append(sample)

    def queued(self) -> Tuple[int, int]:
        with self._lock:
            return len(self._train), len(self._valid)

    # -- math --------------------------------------------------------------
    def _loss(self, params, x, y):
        import jax
        import jax.numpy as jnp

        logits = self.apply_fn(params, x)
        if isinstance(logits, (tuple, list)):
            logits = logits[0]
        if self.loss_kind == "mse":
            loss = jnp.mean((logits - y.reshape(logits.shape)) ** 2)
            acc = jnp.float32(jnp.nan)
        else:
            if y.ndim >= 2 and y.shape[-1] == logits.shape[-1]:
                labels = jnp.argmax(y.reshape((y.shape[0], -1)), axis=-1)
            else:
                labels = y.reshape((y.shape[0],)).astype(jnp.int32)
            logp = jax.nn.log_softmax(logits)
            loss = -jnp.mean(jnp.take_along_axis(logp, labels[:, None], axis=1))
            acc = jnp.mean((jnp.argmax(logits, axis=-1) == labels).astype(jnp.float32))
        return loss, acc

    def _build_step(self):
        import jax

        def step(params, opt_state, x, y):
            (loss, acc), grads = jax.value_and_grad(self._loss, has_aux=True)(
                params, x, y
            )
            updates, opt_state = self._tx.update(grads, opt_state, params)
            params = jax.tree_util.tree_map(lambda p, u: p + u, params, updates)
            return params, opt_state, loss, acc

        self._step_fn = jax.jit(step, donate_argnums=(0, 1))
        self._eval_fn = jax.jit(self._loss)

    # -- epochs ------------------------------------------------------------
    def train_epoch(self) -> Dict[str, float]:
        import jax

        with self._lock:
            train, self._train = self._train, []
            valid, self._valid = self._valid, []
        if not train:
            raise TrainerError("train_epoch called with no queued samples")
        if self._step_fn is None:
            self._build_step()

        losses, accs = [], []
        bs = max(1, self.batch_size)
        for off in range(0, len(train), bs):
            chunk = train[off : off + bs]
            x = np.stack([s[0][0] for s in chunk])
            y = _stack_labels([s[1][0] for s in chunk])
            if self._sharding is not None and x.shape[0] % self._sharding.mesh.size == 0:
                x = jax.device_put(x, self._sharding)
            self.params, self.opt_state, loss, acc = self._step_fn(
                self.params, self.opt_state, x, y
            )
            losses.append(float(loss))
            accs.append(float(acc))
            self.step += 1

        stats = {
            "training_loss": float(np.mean(losses)),
            "training_accuracy": float(np.mean(accs)),
            "validation_loss": float("nan"),
            "validation_accuracy": float("nan"),
        }
        if valid:
            x = np.stack([s[0][0] for s in valid])
            y = _stack_labels([s[1][0] for s in valid])
            vl, va = self._eval_fn(self.params, x, y)
            stats["validation_loss"] = float(vl)
            stats["validation_accuracy"] = float(va)
        log.debug("epoch stats %s", stats)
        return stats

    # -- persistence -------------------------------------------------------
    def save(self, path: str) -> str:
        from .checkpoint import save_checkpoint

        return save_checkpoint(path, self.params, self.opt_state, self.step)

    def load(self, path: str) -> None:
        from .checkpoint import load_checkpoint

        self.params, opt_state, self.step = load_checkpoint(path)
        if opt_state is not None:
            self.opt_state = opt_state
