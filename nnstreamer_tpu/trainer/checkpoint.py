"""Checkpoint save/restore for the training path.

Reference analog (SURVEY §5.4): the reference's entire checkpoint story is
``tensor_trainer``'s ``model-save-path`` (nntrainer serializes weights) plus
``datareposrc`` ``start-sample-index``/``epochs`` for dataset-position
resume.  TPU-native equivalent: an orbax-style checkpoint of
``(params, opt_state, step)`` — orbax when importable, a portable ``.npz``
fallback otherwise — and the same dataset-position resume on datareposrc.
"""

from __future__ import annotations

import os
import pickle
from typing import Any, Dict, Optional, Tuple

import numpy as np


def save_checkpoint(path: str, params: Any, opt_state: Any = None, step: int = 0,
                    fsync: bool = False) -> str:
    """Write a checkpoint; returns the path written.

    ``params`` must be a pytree of arrays.  Uses orbax when available
    (directory checkpoint), else a single pickle file.  A *failed* orbax
    save propagates — falling back there would leave a partial orbax
    directory shadowing the fallback file.

    ``fsync=True`` makes the pickle path DURABLE the way the request
    journal is (utils/journal.py's flusher discipline): the blob is
    written to a temp sibling, flushed, ``os.fsync``'d, and atomically
    renamed over ``path`` — a crash mid-write leaves the previous
    checkpoint intact, never a torn file, so ``model-load-path`` resume
    always finds a complete ``(params, opt_state, step)`` tree.
    """
    try:
        import orbax.checkpoint as ocp
    except ImportError:
        ocp = None
    if ocp is not None:
        path = os.path.abspath(path)
        ckptr = ocp.PyTreeCheckpointer()
        ckptr.save(
            path,
            {"params": params, "step": np.int64(step)},
            force=True,
        )
        # Optimizer state rides in a sidecar pickle: orbax's untyped restore
        # can't rebuild optax namedtuple structure, pickle can.
        if opt_state is not None:
            with open(path + ".opt", "wb") as f:
                pickle.dump(_to_host(opt_state), f)
        return path
    # Portable fallback: numpy pickle of host arrays.
    host = _to_host(params)
    blob = {"params": host, "opt_state": _to_host(opt_state), "step": int(step)}
    path = os.path.abspath(path)
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    if not fsync:
        with open(path, "wb") as f:
            pickle.dump(blob, f)
        return path
    tmp = f"{path}.tmp.{os.getpid()}"
    try:
        with open(tmp, "wb") as f:
            pickle.dump(blob, f)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, path)
    finally:
        if os.path.exists(tmp):  # failed write: don't leave the temp
            try:
                os.remove(tmp)
            except OSError:
                pass
    return path


def load_checkpoint(path: str) -> Tuple[Any, Optional[Any], int]:
    """Read a checkpoint; returns (params, opt_state, step)."""
    if os.path.isdir(path):
        import orbax.checkpoint as ocp

        ckptr = ocp.PyTreeCheckpointer()
        blob = ckptr.restore(path)
        opt_state = None
        if os.path.exists(path + ".opt"):
            with open(path + ".opt", "rb") as f:
                opt_state = pickle.load(f)
        return blob["params"], opt_state, int(blob.get("step", 0))
    with open(path, "rb") as f:
        blob = pickle.load(f)
    return blob["params"], blob.get("opt_state"), int(blob.get("step", 0))


#: magic header of a serialized serve-stream snapshot (drain/adopt —
#: docs/SERVING.md "Elastic serving"); bumped on layout changes so an
#: adopt can reject a stale snapshot with a named error instead of a
#: shape crash mid-restore.
STREAM_SNAPSHOT_VERSION = 1


def save_stream_snapshot(path: str, snapshot: Dict[str, Any]) -> str:
    """Persist one drained serve-stream snapshot
    (:meth:`~nnstreamer_tpu.pipeline.runtime.Pipeline.drain_stream`)
    through the same serialization substrate checkpoints use: every
    array leaf is moved to host (:func:`to_host_tree`) and the blob is
    a single portable pickle.  Returns the path written."""
    blob = dict(to_host_tree(snapshot))
    blob["snapshot_version"] = STREAM_SNAPSHOT_VERSION
    os.makedirs(os.path.dirname(os.path.abspath(path)) or ".",
                exist_ok=True)
    with open(path, "wb") as f:
        pickle.dump(blob, f)
    return path


def load_stream_snapshot(path: str) -> Dict[str, Any]:
    """Read a snapshot written by :func:`save_stream_snapshot`; raises
    ``ValueError`` on a version the adopt path does not understand."""
    with open(path, "rb") as f:
        blob = pickle.load(f)
    ver = blob.pop("snapshot_version", None)
    if ver != STREAM_SNAPSHOT_VERSION:
        raise ValueError(
            f"stream snapshot version {ver!r} unsupported "
            f"(expected {STREAM_SNAPSHOT_VERSION})")
    return blob


def to_host_tree(tree: Any) -> Any:
    """Public name of the checkpoint serialization substrate: every
    array leaf (jax or numpy) becomes a host numpy array; containers and
    namedtuples keep their structure.  Drain/adopt snapshots go through
    this exact walk so a drained stream is plain host data."""
    return _to_host(tree)


def _to_host(tree: Any) -> Any:
    if tree is None:
        return None
    if isinstance(tree, dict):
        return {k: _to_host(v) for k, v in tree.items()}
    if isinstance(tree, tuple) and hasattr(tree, "_fields"):  # namedtuple
        return type(tree)(*[_to_host(v) for v in tree])
    if isinstance(tree, (list, tuple)):
        t = [_to_host(v) for v in tree]
        return type(tree)(t)
    if hasattr(tree, "shape"):
        return np.asarray(tree)
    return tree
