"""Checkpoint save/restore for the training path.

Reference analog (SURVEY §5.4): the reference's entire checkpoint story is
``tensor_trainer``'s ``model-save-path`` (nntrainer serializes weights) plus
``datareposrc`` ``start-sample-index``/``epochs`` for dataset-position
resume.  TPU-native equivalent: an orbax-style checkpoint of
``(params, opt_state, step)`` — orbax when importable, a portable ``.npz``
fallback otherwise — and the same dataset-position resume on datareposrc.
"""

from __future__ import annotations

import os
import pickle
from typing import Any, Dict, Optional, Tuple

import numpy as np


def save_checkpoint(path: str, params: Any, opt_state: Any = None, step: int = 0) -> str:
    """Write a checkpoint; returns the path written.

    ``params`` must be a pytree of arrays.  Uses orbax when available
    (directory checkpoint), else a single pickle file.  A *failed* orbax
    save propagates — falling back there would leave a partial orbax
    directory shadowing the fallback file.
    """
    try:
        import orbax.checkpoint as ocp
    except ImportError:
        ocp = None
    if ocp is not None:
        path = os.path.abspath(path)
        ckptr = ocp.PyTreeCheckpointer()
        ckptr.save(
            path,
            {"params": params, "step": np.int64(step)},
            force=True,
        )
        # Optimizer state rides in a sidecar pickle: orbax's untyped restore
        # can't rebuild optax namedtuple structure, pickle can.
        if opt_state is not None:
            with open(path + ".opt", "wb") as f:
                pickle.dump(_to_host(opt_state), f)
        return path
    # Portable fallback: numpy pickle of host arrays.
    host = _to_host(params)
    blob = {"params": host, "opt_state": _to_host(opt_state), "step": int(step)}
    os.makedirs(os.path.dirname(os.path.abspath(path)) or ".", exist_ok=True)
    with open(path, "wb") as f:
        pickle.dump(blob, f)
    return path


def load_checkpoint(path: str) -> Tuple[Any, Optional[Any], int]:
    """Read a checkpoint; returns (params, opt_state, step)."""
    if os.path.isdir(path):
        import orbax.checkpoint as ocp

        ckptr = ocp.PyTreeCheckpointer()
        blob = ckptr.restore(path)
        opt_state = None
        if os.path.exists(path + ".opt"):
            with open(path + ".opt", "rb") as f:
                opt_state = pickle.load(f)
        return blob["params"], opt_state, int(blob.get("step", 0))
    with open(path, "rb") as f:
        blob = pickle.load(f)
    return blob["params"], blob.get("opt_state"), int(blob.get("step", 0))


def _to_host(tree: Any) -> Any:
    if tree is None:
        return None
    if isinstance(tree, dict):
        return {k: _to_host(v) for k, v in tree.items()}
    if isinstance(tree, tuple) and hasattr(tree, "_fields"):  # namedtuple
        return type(tree)(*[_to_host(v) for v in tree])
    if isinstance(tree, (list, tuple)):
        t = [_to_host(v) for v in tree]
        return type(tree)(t)
    if hasattr(tree, "shape"):
        return np.asarray(tree)
    return tree
