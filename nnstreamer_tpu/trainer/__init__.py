"""nnstreamer_tpu.trainer"""
