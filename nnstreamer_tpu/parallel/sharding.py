"""Sharding helpers: put batches and params where the mesh wants them.

Reference analog: the reference's "distribution" is serializing tensors over
TCP to another host's pipeline (SURVEY §2.7).  Here distribution is a
``NamedSharding`` annotation — XLA inserts the all-gathers/reduce-scatters
and they ride ICI.  These helpers are the whole host-side API:

* :func:`batch_sharding` / :func:`shard_batch` — split the leading (batch)
  axis over the ``data`` mesh axis (the tensor_query DP path).
* :func:`shard_params` — place a param pytree per its ``param_pspecs``
  (TP over ``model``), replicating anything without a spec.
* :func:`replicate` — broadcast small pytrees to every device.
"""

from __future__ import annotations

from typing import Any, Optional


def _ns(mesh, spec):
    from jax.sharding import NamedSharding

    return NamedSharding(mesh, spec)


def batch_sharding(mesh, ndim: int, axis: str = "data"):
    """NamedSharding splitting dim 0 over ``axis``, replicated elsewhere."""
    from jax.sharding import PartitionSpec as P

    return _ns(mesh, P(axis, *([None] * (ndim - 1))))


def data_sharding(mesh, axis: str = "data"):
    """Rank-agnostic batch-dim sharding: ``P(axis)`` splits dim 0 and
    leaves every trailing dim unspecified (= replicated), so ONE sharding
    serves any mix of tensor ranks — the form jit's
    ``in_shardings``/``out_shardings`` broadcast over a whole arg/out
    pytree (the sharded BatchRunner's contract, pipeline/batching.py)."""
    from jax.sharding import PartitionSpec as P

    return _ns(mesh, P(axis))


def shard_batch(mesh, x, axis: str = "data"):
    """Device_put a host batch split over the data axis (zero-copy per shard)."""
    import jax

    return jax.device_put(x, batch_sharding(mesh, getattr(x, "ndim", 1), axis))


def replicate(mesh, tree):
    import jax
    from jax.sharding import PartitionSpec as P

    sh = _ns(mesh, P())
    return jax.tree_util.tree_map(lambda x: jax.device_put(x, sh), tree)


def shard_params(mesh, params, pspecs: Optional[Any]):
    """Place params per a matching pytree of PartitionSpecs (None→replicate)."""
    import jax
    from jax.sharding import PartitionSpec as P

    if pspecs is None:
        return replicate(mesh, params)

    def put(x, spec):
        spec = spec if spec is not None else P()
        return jax.device_put(x, _ns(mesh, spec))

    # pspecs may be a partial tree (dict subset); normalize with a walk.
    def walk(p, s):
        if isinstance(p, dict):
            return {k: walk(v, (s or {}).get(k) if isinstance(s, dict) else None) for k, v in p.items()}
        return put(p, s)

    if isinstance(params, dict):
        return walk(params, pspecs)
    return jax.tree_util.tree_map(put, params, pspecs)


def out_shardings_like(mesh, tree_pspecs):
    import jax

    return jax.tree_util.tree_map(lambda s: _ns(mesh, s), tree_pspecs)


def iter_param_specs(params, pspecs):
    """Yield ``(path, leaf, spec)`` for every param leaf, pairing a
    (possibly partial) pspec tree with the SAME walk
    :func:`shard_params` places by — the one traversal the placement
    metrics (:func:`placement_split`) and the deep lint's static pspec
    audit (analysis/tracecheck.py) both ride, so the pairing rules can
    never diverge between runtime placement and static pricing."""
    def walk(p, s, path):
        if isinstance(p, dict):
            for k, v in p.items():
                yield from walk(
                    v, (s or {}).get(k) if isinstance(s, dict) else None,
                    f"{path}.{k}" if path else str(k))
        else:
            yield path, p, s

    yield from walk(params, pspecs, "")


def spec_entry_axes(entry) -> tuple:
    """Mesh-axis names one PartitionSpec entry maps a dim over (an entry
    is None, an axis name, or a tuple of axis names)."""
    if entry is None:
        return ()
    if isinstance(entry, (tuple, list)):
        return tuple(entry)
    return (entry,)


def spec_axes(spec) -> set:
    """Every mesh-axis name a leaf's PartitionSpec mentions."""
    out = set()
    for entry in (spec or ()):
        out.update(spec_entry_axes(entry))
    return out


def placement_split(params, pspecs, axis: str = "model"):
    """Count how :func:`shard_params` would place a pytree: returns
    ``(n_sharded, n_replicated)`` leaves, where "sharded" means the
    leaf's PartitionSpec names ``axis``.  The shard-vs-replica split the
    2-D placement metrics report (``<stage>.param_shards`` /
    ``.param_replicas``) and tests assert against — rides
    :func:`iter_param_specs`, zero device work."""
    sharded = replicated = 0
    for _, _, spec in iter_param_specs(params, pspecs):
        if axis in spec_axes(spec):
            sharded += 1
        else:
            replicated += 1
    return sharded, replicated
