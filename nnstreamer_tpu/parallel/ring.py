"""Ring attention: sequence/context parallelism over the ``seq`` mesh axis.

The reference has **no** sequence parallelism (SURVEY §2.9 — its nearest
analog is ``tensor_aggregator`` windowing); long context is first-class
here.  Design: blockwise attention with an online (flash-style) softmax,
where K/V blocks rotate around the ring of ``seq``-axis devices via
``lax.ppermute`` while every device keeps its resident Q shard.  Each hop
overlaps the collective with the local block matmul, so the ICI transfer
hides behind MXU work — the standard TPU ring-attention recipe (Liu et al.,
"Ring Attention with Blockwise Transformers"; see PAPERS.md).

Shapes (per device, inside ``shard_map``): q/k/v ``[B, T_local, H, D]``.
Global sequence length = ``T_local * mesh.shape['seq']``.  Causal masking
uses global token positions derived from ``lax.axis_index('seq')``.

Public entry points:

* :func:`ring_attention` — host-level: shard_map'd over a mesh.
* :func:`ring_attention_local` — the per-device body (usable inside a
  larger shard_map'd transformer like models/llama.py).
"""

from __future__ import annotations

import functools
from typing import Optional


def _block_attn(q, k, v, mask, scale):
    """One (q-shard × kv-block) attention piece with stable running stats.

    Returns (o_unnorm, m, l): unnormalized weighted values, running rowmax,
    running denominator — the flash-attention accumulator triple.
    """
    import jax.numpy as jnp

    # [B, H, Tq, Tk] scores in f32 for numerical stability.
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k, preferred_element_type=jnp.float32)
    s = s * scale
    if mask is not None:
        s = jnp.where(mask, s, -jnp.inf)
    m = jnp.max(s, axis=-1)  # [B,H,Tq]
    # Guard fully-masked rows (m = -inf) -> exp(0)=1 rows scaled to 0 by l.
    m_safe = jnp.where(jnp.isfinite(m), m, 0.0)
    p = jnp.exp(s - m_safe[..., None])
    if mask is not None:
        p = jnp.where(mask, p, 0.0)
    l = jnp.sum(p, axis=-1)  # [B,H,Tq]
    o = jnp.einsum("bhqk,bkhd->bqhd", p.astype(v.dtype), v,
                   preferred_element_type=jnp.float32)
    return o, m, l


def ring_attention_local(q, k, v, *, axis_name: str = "seq",
                         causal: bool = True, scale: Optional[float] = None):
    """Per-device ring attention body. Call inside shard_map/pmap.

    q,k,v: ``[B, T_local, H, D]`` shards along the sequence axis.
    """
    import jax
    import jax.numpy as jnp
    from jax import lax

    B, Tl, H, D = q.shape
    scale = scale if scale is not None else (1.0 / (D ** 0.5))
    n = lax.psum(1, axis_name)
    my = lax.axis_index(axis_name)

    q_pos = my * Tl + jnp.arange(Tl)  # global positions of resident Q rows

    def make_mask(kv_chunk):
        if not causal:
            return None
        k_pos = kv_chunk * Tl + jnp.arange(Tl)
        # [Tq, Tk] -> broadcast to [B,H,Tq,Tk]
        return (q_pos[:, None] >= k_pos[None, :])[None, None]

    def step(carry, _):
        k_blk, v_blk, kv_chunk, o_acc, m_acc, l_acc = carry
        o, m, l = _block_attn(q, k_blk, v_blk, make_mask(kv_chunk), scale)
        # Merge running stats (flash-attention combine).  Guards: a fully
        # masked accumulator/block has m = -inf; exp(-inf - -inf) would be
        # NaN, so rescale factors collapse to 0 for -inf sources.
        m_new = jnp.maximum(m_acc, m)
        m_safe = jnp.where(jnp.isfinite(m_new), m_new, 0.0)
        a = jnp.where(jnp.isfinite(m_acc), jnp.exp(m_acc - m_safe), 0.0)
        b = jnp.where(jnp.isfinite(m), jnp.exp(m - m_safe), 0.0)
        l_new = l_acc * a + l * b
        o_new = (o_acc * a[..., None].transpose(0, 2, 1, 3)
                 + o * b[..., None].transpose(0, 2, 1, 3))
        # Rotate K/V to the next device on the ring (ICI neighbor hop).
        perm = [(i, (i + 1) % n) for i in range(n)]
        k_nxt = lax.ppermute(k_blk, axis_name, perm)
        v_nxt = lax.ppermute(v_blk, axis_name, perm)
        kv_nxt = (kv_chunk - 1) % n
        return (k_nxt, v_nxt, kv_nxt, o_new, m_new, l_new), None

    o0 = jnp.zeros((B, Tl, H, D), jnp.float32)
    m0 = jnp.full((B, H, Tl), -jnp.inf, jnp.float32)
    l0 = jnp.zeros((B, H, Tl), jnp.float32)
    carry = (k, v, my, o0, m0, l0)
    carry, _ = lax.scan(step, carry, None, length=n)
    _, _, _, o, m, l = carry
    l = jnp.maximum(l, 1e-20)
    out = o / l[..., None].transpose(0, 2, 1, 3)
    return out.astype(q.dtype)


def ring_attention(mesh, q, k, v, *, causal: bool = True,
                   scale: Optional[float] = None):
    """Host-level ring attention over ``mesh``'s ``seq`` axis.

    Inputs are global ``[B, T, H, D]`` arrays (host or device); output is the
    exact full attention result, computed without any device ever holding
    more than ``T / seq_size`` keys.
    """
    import jax
    from jax.sharding import PartitionSpec as P

    from .mesh import shard_map

    spec = P(None, "seq", None, None)
    fn = shard_map(
        functools.partial(ring_attention_local, causal=causal, scale=scale),
        mesh=mesh,
        in_specs=(spec, spec, spec),
        out_specs=spec,
        check_vma=False,
    )
    return jax.jit(fn)(q, k, v)
