"""Device-mesh construction: the TPU replacement for nnstreamer-edge topology.

Reference analog: the reference distributes work by *naming hosts* —
``tensor_query_client host=H port=P`` over TCP (SURVEY §2.7/§5.8).  On TPU
the unit of distribution is the **ICI-connected device mesh**: we name
logical axes and let XLA place collectives on ICI links.

Axis conventions used across the framework:

* ``data``   — batch (DP): streams/frames sharded across chips.
* ``model``  — tensor parallel (TP): weight matrices split over channels/heads.
* ``seq``    — sequence/context parallel (SP): ring attention over tokens.
* ``expert`` — expert parallel (EP) for MoE models.
* ``pipe``   — pipeline stages (inter-stage, software-pipelined).

Any axis of size 1 is legal and free, so a single ``make_mesh`` call serves
1-chip dev runs and v5e-8 pods alike.
"""

from __future__ import annotations

from typing import Dict, Optional, Sequence, Tuple

AXES = ("data", "model", "seq", "expert", "pipe")


def make_mesh(
    axis_sizes: Optional[Dict[str, int]] = None,
    *,
    devices=None,
    data: int = 0,
    model: int = 1,
    seq: int = 1,
    expert: int = 1,
    pipe: int = 1,
):
    """Build a ``jax.sharding.Mesh`` with the framework's canonical axes.

    ``data=0`` (default) means "absorb all remaining devices".  Example::

        mesh = make_mesh(model=2)          # on 8 devices -> data=4, model=2
        mesh = make_mesh({"data": 2, "seq": 4})
    """
    import jax
    import numpy as np

    sizes = {"data": data, "model": model, "seq": seq, "expert": expert, "pipe": pipe}
    if axis_sizes:
        unknown = set(axis_sizes) - set(AXES)
        if unknown:
            raise ValueError(f"unknown mesh axes {sorted(unknown)}; valid: {AXES}")
        sizes.update(axis_sizes)

    devs = list(devices if devices is not None else jax.devices())
    n = len(devs)
    fixed = 1
    for name in AXES:
        if name != "data" and sizes[name] > 1:
            fixed *= sizes[name]
    if sizes["data"] in (0, None):
        if n % fixed:
            raise ValueError(f"{n} devices not divisible by fixed axes product {fixed}")
        sizes["data"] = n // fixed
    total = sizes["data"] * fixed
    if total != n:
        raise ValueError(
            f"mesh {sizes} needs {total} devices, have {n}"
        )

    shape = tuple(sizes[a] for a in AXES)
    arr = np.asarray(devs).reshape(shape)
    return jax.sharding.Mesh(arr, AXES)


def single_device_mesh(device=None):
    """A 1-device mesh (every axis size 1) — lets mesh-aware code run anywhere."""
    import jax

    dev = device if device is not None else jax.devices()[0]
    return make_mesh(data=1, devices=[dev])


def mesh_axis_size(mesh, name: str) -> int:
    return int(mesh.shape.get(name, 1))


def local_batch(mesh, global_batch: int) -> int:
    d = mesh_axis_size(mesh, "data")
    if global_batch % d:
        raise ValueError(f"global batch {global_batch} not divisible by data={d}")
    return global_batch // d


def shard_map(f, *, mesh, in_specs, out_specs, check_vma: bool = False):
    """``jax.shard_map`` across jax versions.

    ``jax.shard_map`` (with its ``check_vma`` kwarg) only exists in newer
    releases; older ones ship ``jax.experimental.shard_map.shard_map`` whose
    equivalent kwarg is ``check_rep``.  Every shard_map call site in the
    framework goes through here so version skew stays one function wide.
    """
    import jax

    if hasattr(jax, "shard_map"):
        return jax.shard_map(
            f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
            check_vma=check_vma,
        )
    from jax.experimental.shard_map import shard_map as _shard_map

    return _shard_map(
        f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
        check_rep=check_vma,
    )
