"""Device-mesh construction: the TPU replacement for nnstreamer-edge topology.

Reference analog: the reference distributes work by *naming hosts* —
``tensor_query_client host=H port=P`` over TCP (SURVEY §2.7/§5.8).  On TPU
the unit of distribution is the **ICI-connected device mesh**: we name
logical axes and let XLA place collectives on ICI links.

Axis conventions used across the framework:

* ``data``   — batch (DP): streams/frames sharded across chips.
* ``model``  — tensor parallel (TP): weight matrices split over channels/heads.
* ``seq``    — sequence/context parallel (SP): ring attention over tokens.
* ``expert`` — expert parallel (EP) for MoE models.
* ``pipe``   — pipeline stages (inter-stage, software-pipelined).

Any axis of size 1 is legal and free, so a single ``make_mesh`` call serves
1-chip dev runs and v5e-8 pods alike.
"""

from __future__ import annotations

from typing import Dict, Optional, Sequence, Tuple

AXES = ("data", "model", "seq", "expert", "pipe")


def make_mesh(
    axis_sizes: Optional[Dict[str, int]] = None,
    *,
    devices=None,
    data: int = 0,
    model: int = 1,
    seq: int = 1,
    expert: int = 1,
    pipe: int = 1,
):
    """Build a ``jax.sharding.Mesh`` with the framework's canonical axes.

    ``data=0`` (default) means "absorb all remaining devices".  Example::

        mesh = make_mesh(model=2)          # on 8 devices -> data=4, model=2
        mesh = make_mesh({"data": 2, "seq": 4})
    """
    import jax
    import numpy as np

    sizes = {"data": data, "model": model, "seq": seq, "expert": expert, "pipe": pipe}
    if axis_sizes:
        unknown = set(axis_sizes) - set(AXES)
        if unknown:
            raise ValueError(f"unknown mesh axes {sorted(unknown)}; valid: {AXES}")
        sizes.update(axis_sizes)

    # Validate the plan BEFORE touching numpy: a zero/negative axis used
    # to surface as an opaque numpy reshape error ("cannot reshape array
    # of size 8 into shape (8,0,...)").  Only ``data`` may be 0 (= auto:
    # absorb every device the fixed axes don't claim).
    for name in AXES:
        v = sizes[name]
        if name == "data" and (v is None or v == 0):
            continue  # auto-absorb; resolved below
        if not isinstance(v, int) or isinstance(v, bool):
            raise ValueError(
                f"mesh axis {name!r} size must be an int, got {v!r}")
        if v < 1:
            raise ValueError(
                f"mesh axis {name!r} must be >= 1, got {v} "
                "(only 'data' supports 0/None = auto-absorb)")

    devs = list(devices if devices is not None else jax.devices())
    n = len(devs)
    fixed = 1
    for name in AXES:
        if name != "data" and sizes[name] > 1:
            fixed *= sizes[name]
    requested = {a: sizes[a] for a in AXES if sizes[a] not in (0, 1, None)}
    if sizes["data"] in (0, None):
        if n % fixed:
            # name the axis whose size breaks divisibility, not just the
            # product — the caller needs to know WHICH knob to fix
            bad = next((a for a in AXES
                        if a != "data" and sizes[a] > 1 and n % sizes[a]),
                       None)
            detail = (f"axis {bad!r} = {sizes[bad]} does not divide the "
                      f"device count" if bad else
                      f"the fixed axes {requested} multiply to {fixed}, "
                      "which does not divide the device count")
            raise ValueError(
                f"cannot auto-size the 'data' axis over {n} device(s): "
                f"{detail} (requested {requested or '{}'}, "
                f"{n} device(s) available)")
        sizes["data"] = n // fixed
    total = sizes["data"] * fixed
    if total != n:
        bad = next((a for a in AXES if sizes[a] > 1 and n % sizes[a]), None)
        hint = (f"; axis {bad!r} = {sizes[bad]} does not divide "
                f"{n}" if bad else "")
        raise ValueError(
            f"mesh plan {requested or dict(sizes)} needs {total} device(s), "
            f"have {n}{hint}: axis sizes must multiply to the device count")

    shape = tuple(sizes[a] for a in AXES)
    arr = np.asarray(devs).reshape(shape)
    return jax.sharding.Mesh(arr, AXES)


def single_device_mesh(device=None):
    """A 1-device mesh (every axis size 1) — lets mesh-aware code run anywhere."""
    import jax

    dev = device if device is not None else jax.devices()[0]
    return make_mesh(data=1, devices=[dev])


def mesh_axis_size(mesh, name: str) -> int:
    return int(mesh.shape.get(name, 1))


def device_coords(mesh) -> Dict[int, Tuple[int, int]]:
    """Map ``device.id`` -> its ``(data, model)`` coordinate in the mesh —
    how per-replica counters and trace spans name a chip's position in a
    2-D placement (docs/BATCHING.md "2-D sharded dispatch")."""
    import numpy as np

    coords: Dict[int, Tuple[int, int]] = {}
    arr = np.asarray(mesh.devices)
    di_axis = AXES.index("data")
    mi_axis = AXES.index("model")
    for idx in np.ndindex(arr.shape):
        coords[arr[idx].id] = (int(idx[di_axis]), int(idx[mi_axis]))
    return coords


def local_batch(mesh, global_batch: int) -> int:
    d = mesh_axis_size(mesh, "data")
    if global_batch % d:
        raise ValueError(f"global batch {global_batch} not divisible by data={d}")
    return global_batch // d


def shard_map(f, *, mesh, in_specs, out_specs, check_vma: bool = False):
    """``jax.shard_map`` across jax versions.

    ``jax.shard_map`` (with its ``check_vma`` kwarg) only exists in newer
    releases; older ones ship ``jax.experimental.shard_map.shard_map`` whose
    equivalent kwarg is ``check_rep``.  Every shard_map call site in the
    framework goes through here so version skew stays one function wide.
    """
    import jax

    if hasattr(jax, "shard_map"):
        return jax.shard_map(
            f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
            check_vma=check_vma,
        )
    from jax.experimental.shard_map import shard_map as _shard_map

    return _shard_map(
        f, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
        check_rep=check_vma,
    )
