"""Multi-host runtime initialization (the DCN side of the comm backend).

Reference analog (SURVEY §2.7/§5.8): the reference's multi-machine story
is nnstreamer-edge TCP/MQTT point-to-point — every cross-host hop moves
tensors through sockets.  The TPU-native equivalent splits the job:

* **ICI**: collectives INSIDE a pod slice (data/tensor/sequence sharding
  over a ``Mesh``) — XLA-inserted, never touching host code;
* **DCN**: cross-pod / host-level coordination — ``jax.distributed``
  (one controller process per host, all devices become globally
  addressable), while the stream-feed layer stays on the framework wire
  protocol (query/edge elements).

This module wraps ``jax.distributed`` so pipelines can opt in with env
vars alone (the standard cluster launch shape), and provides
``global_mesh`` for building meshes over every process's devices.

Single-process (the common case, and all CI here): everything degrades to
local devices with no coordinator.
"""

from __future__ import annotations

import os
from typing import Optional

from ..core.log import logger

log = logger(__name__)

_initialized = False


def is_initialized() -> bool:
    return _initialized


def initialize(
    coordinator_address: Optional[str] = None,
    num_processes: Optional[int] = None,
    process_id: Optional[int] = None,
) -> bool:
    """Join the multi-host runtime.  Args default from the standard env
    vars (``JAX_COORDINATOR_ADDRESS``, ``JAX_NUM_PROCESSES``,
    ``JAX_PROCESS_ID`` — also set by TPU pod launchers).  Returns True if
    a multi-process runtime was initialized, False for the single-process
    fallback.  Idempotent."""
    global _initialized
    if _initialized:
        return True
    coordinator_address = coordinator_address or os.environ.get(
        "JAX_COORDINATOR_ADDRESS")
    if num_processes is None:
        np_env = os.environ.get("JAX_NUM_PROCESSES")
        num_processes = int(np_env) if np_env is not None else None
    if process_id is None:
        pid = os.environ.get("JAX_PROCESS_ID")
        process_id = int(pid) if pid is not None else None

    if not coordinator_address:
        # Single process is the quiet default ONLY with no coordinator
        # configured at all; a coordinator with missing counts falls
        # through to jax.distributed.initialize, which auto-detects (TPU
        # pods) or fails loudly — never a silent local-only mesh.
        log.debug("single-process runtime (no coordinator configured)")
        return False
    if num_processes == 1:
        log.debug("single-process runtime (num_processes=1)")
        return False

    import jax

    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
    )
    _initialized = True
    log.info("joined distributed runtime: process %s of %s via %s",
             process_id, num_processes, coordinator_address)
    return True


def global_device_count() -> int:
    import jax

    return len(jax.devices())


def local_device_count() -> int:
    import jax

    return len(jax.local_devices())


def global_mesh(**axes: int):
    """Mesh over ALL processes' devices (== :func:`make_mesh` over
    ``jax.devices()``, which is global after :func:`initialize`).  Axis
    sizes multiply to the global device count; ``data=0`` (the default)
    absorbs the rest (make_mesh semantics)."""
    from .mesh import make_mesh

    return make_mesh(**axes)
