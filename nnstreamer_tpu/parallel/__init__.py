"""nnstreamer_tpu.parallel"""
