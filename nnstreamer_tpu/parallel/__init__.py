"""Parallelism: device meshes, shardings, collectives, sequence parallel.

TPU-native replacement for the reference's distribution stack (SURVEY
§2.7/§2.9/§5.8): instead of TCP/MQTT/gRPC point-to-point between hosts,
scale-out is a ``jax.sharding.Mesh`` with XLA collectives over ICI.
"""

from .mesh import (  # noqa: F401
    AXES,
    local_batch,
    make_mesh,
    mesh_axis_size,
    single_device_mesh,
)
from .sharding import (  # noqa: F401
    batch_sharding,
    replicate,
    shard_batch,
    shard_params,
)
from .ring import ring_attention, ring_attention_local  # noqa: F401
from .distributed import (  # noqa: F401
    global_device_count,
    global_mesh,
    initialize as distributed_initialize,
    is_initialized as distributed_is_initialized,
    local_device_count,
)
